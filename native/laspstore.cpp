// laspstore: log-structured host key-value store for dense CRDT state.
//
// The TPU framework keeps live lattice state in device HBM; this library is
// the durable host-side half — the role the reference fills with its native
// storage engines (eleveldb, a C++ LevelDB NIF, as the default backend at
// include/lasp.hrl:14, and bitcask's C NIFs as the alternative; see
// SURVEY.md §2.4 native-code census). The format is bitcask-style: an
// append-only record log with an in-memory index built by a single
// sequential scan on open; the last record for a key wins; deletes are
// tombstone records. Values are raw byte buffers (the Python layer stores
// array payloads and msgpack-ish manifests).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
//
// Record layout (little-endian):
//   u32 magic 0x4C535052 ("LSPR")  | u32 key_len | u64 val_len (UINT64_MAX
//   = tombstone) | key bytes | val bytes | u32 crc32 of key+val
//
// File header: u32 magic 0x4C535354 ("LSST") | u32 version

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kFileMagic = 0x4C535354;  // "LSST"
constexpr uint32_t kRecMagic = 0x4C535052;   // "LSPR"
constexpr uint32_t kVersion = 1;
constexpr uint64_t kTombstone = UINT64_MAX;

const uint32_t* crc_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  return table;
}

// raw running state; start with 0xFFFFFFFF, finalize with ~state
uint32_t crc32_update(uint32_t state, const uint8_t* data, size_t n) {
  const uint32_t* table = crc_table();
  for (size_t i = 0; i < n; i++)
    state = table[(state ^ data[i]) & 0xFF] ^ (state >> 8);
  return state;
}

uint32_t crc32(const uint8_t* data, size_t n) {
  return ~crc32_update(0xFFFFFFFFu, data, n);
}

struct Entry {
  uint64_t offset;  // offset of value bytes in file
  uint64_t len;
};

struct Store {
  FILE* f = nullptr;
  std::string path;
  std::map<std::string, Entry> index;
  std::string error;
  uint64_t wasted = 0;  // bytes superseded by later writes (compaction cue)
};

bool read_exact(FILE* f, void* buf, size_t n) {
  return fread(buf, 1, n, f) == n;
}

// scan the log, building the index; truncate at the first torn/corrupt record
bool scan(Store* s) {
  uint32_t magic = 0, version = 0;
  if (!read_exact(s->f, &magic, 4) || !read_exact(s->f, &version, 4)) {
    s->error = "missing file header";
    return false;
  }
  if (magic != kFileMagic || version != kVersion) {
    s->error = "bad magic/version";
    return false;
  }
  long pos = ftell(s->f);
  std::vector<uint8_t> buf;
  for (;;) {
    uint32_t rmagic, key_len;
    uint64_t val_len;
    if (!read_exact(s->f, &rmagic, 4)) break;  // clean EOF
    if (rmagic != kRecMagic) break;            // torn write: stop here
    if (!read_exact(s->f, &key_len, 4) || !read_exact(s->f, &val_len, 8)) break;
    bool tomb = (val_len == kTombstone);
    uint64_t vlen = tomb ? 0 : val_len;
    // torn-write/garbage guard: implausible lengths mean the record header
    // is trash, not a record — truncate here instead of trying to allocate
    if (key_len > (1u << 24) || vlen > (1ull << 38)) break;
    try {
      buf.resize(key_len + vlen);
    } catch (...) {
      break;
    }
    if (!read_exact(s->f, buf.data(), key_len + vlen)) break;
    uint32_t stored_crc;
    if (!read_exact(s->f, &stored_crc, 4)) break;
    if (crc32(buf.data(), buf.size()) != stored_crc) break;
    std::string key(reinterpret_cast<char*>(buf.data()), key_len);
    uint64_t val_off = static_cast<uint64_t>(pos) + 4 + 4 + 8 + key_len;
    auto it = s->index.find(key);
    if (it != s->index.end()) s->wasted += it->second.len;
    if (tomb) {
      s->index.erase(key);
    } else {
      s->index[key] = Entry{val_off, vlen};
    }
    pos = ftell(s->f);
  }
  // position for appends at the last valid record boundary
  fseek(s->f, pos, SEEK_SET);
  return true;
}

}  // namespace

extern "C" {

void* lasp_store_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  s->f = fopen(path, "r+b");
  if (!s->f) {
    s->f = fopen(path, "w+b");
    if (!s->f) {
      delete s;
      return nullptr;
    }
    fwrite(&kFileMagic, 4, 1, s->f);
    fwrite(&kVersion, 4, 1, s->f);
    fflush(s->f);
    return s;
  }
  if (!scan(s)) {
    fclose(s->f);
    delete s;
    return nullptr;
  }
  return s;
}

int lasp_store_put(void* handle, const char* key, uint32_t key_len,
                   const uint8_t* val, uint64_t val_len) {
  Store* s = static_cast<Store*>(handle);
  long pos = ftell(s->f);
  uint32_t state = crc32_update(
      0xFFFFFFFFu, reinterpret_cast<const uint8_t*>(key), key_len);
  state = crc32_update(state, val, val_len);
  uint32_t crc = ~state;
  if (fwrite(&kRecMagic, 4, 1, s->f) != 1) return -1;
  fwrite(&key_len, 4, 1, s->f);
  fwrite(&val_len, 8, 1, s->f);
  fwrite(key, 1, key_len, s->f);
  if (val_len) fwrite(val, 1, val_len, s->f);
  fwrite(&crc, 4, 1, s->f);
  fflush(s->f);
  uint64_t val_off = static_cast<uint64_t>(pos) + 4 + 4 + 8 + key_len;
  std::string k(key, key_len);
  auto it = s->index.find(k);
  if (it != s->index.end()) s->wasted += it->second.len;
  s->index[k] = Entry{val_off, val_len};
  return 0;
}

// returns value length, or -1 if absent; copies into out (caller sizes it
// via lasp_store_len first)
int64_t lasp_store_len(void* handle, const char* key, uint32_t key_len) {
  Store* s = static_cast<Store*>(handle);
  auto it = s->index.find(std::string(key, key_len));
  if (it == s->index.end()) return -1;
  return static_cast<int64_t>(it->second.len);
}

int64_t lasp_store_get(void* handle, const char* key, uint32_t key_len,
                       uint8_t* out, uint64_t out_cap) {
  Store* s = static_cast<Store*>(handle);
  auto it = s->index.find(std::string(key, key_len));
  if (it == s->index.end()) return -1;
  if (it->second.len > out_cap) return -2;
  long saved = ftell(s->f);
  fseek(s->f, static_cast<long>(it->second.offset), SEEK_SET);
  size_t got = fread(out, 1, it->second.len, s->f);
  fseek(s->f, saved, SEEK_SET);
  return got == it->second.len ? static_cast<int64_t>(got) : -3;
}

int lasp_store_delete(void* handle, const char* key, uint32_t key_len) {
  Store* s = static_cast<Store*>(handle);
  std::string k(key, key_len);
  if (s->index.find(k) == s->index.end()) return -1;
  uint32_t crc = crc32(reinterpret_cast<const uint8_t*>(key), key_len);
  fwrite(&kRecMagic, 4, 1, s->f);
  fwrite(&key_len, 4, 1, s->f);
  fwrite(&kTombstone, 8, 1, s->f);
  fwrite(key, 1, key_len, s->f);
  fwrite(&crc, 4, 1, s->f);
  fflush(s->f);
  s->wasted += s->index[k].len;
  s->index.erase(k);
  return 0;
}

uint64_t lasp_store_count(void* handle) {
  return static_cast<Store*>(handle)->index.size();
}

uint64_t lasp_store_wasted(void* handle) {
  return static_cast<Store*>(handle)->wasted;
}

// iterate keys, length-prefixed (u32 len | key bytes, repeated) so keys
// may contain any byte — a '\n'-joined listing would corrupt on such keys
uint64_t lasp_store_keys_len(void* handle) {
  Store* s = static_cast<Store*>(handle);
  uint64_t n = 0;
  for (auto& kv : s->index) n += 4 + kv.first.size();
  return n;
}

void lasp_store_keys(void* handle, char* out) {
  Store* s = static_cast<Store*>(handle);
  for (auto& kv : s->index) {
    uint32_t len = static_cast<uint32_t>(kv.first.size());
    memcpy(out, &len, 4);
    out += 4;
    memcpy(out, kv.first.data(), kv.first.size());
    out += kv.first.size();
  }
}

// rewrite live records into a fresh log and swap it in: reclaims the
// `wasted` bytes of superseded/tombstoned records (the compaction the
// reference's waste_pct stat cues, src/lasp_orset.erl:178-191).
// Returns 0 on success; on failure the original log is left untouched.
int lasp_store_compact(void* handle) {
  Store* s = static_cast<Store*>(handle);
  std::string tmp = s->path + ".compact";
  FILE* out = fopen(tmp.c_str(), "w+b");
  if (!out) return -1;
  fwrite(&kFileMagic, 4, 1, out);
  fwrite(&kVersion, 4, 1, out);
  std::map<std::string, Entry> new_index;
  std::vector<uint8_t> buf;
  for (auto& kv : s->index) {
    buf.resize(kv.second.len);
    fseek(s->f, static_cast<long>(kv.second.offset), SEEK_SET);
    if (fread(buf.data(), 1, kv.second.len, s->f) != kv.second.len) {
      fclose(out);
      remove(tmp.c_str());
      fseek(s->f, 0, SEEK_END);  // restore the append-position invariant
      return -2;
    }
    long pos = ftell(out);
    uint32_t key_len = static_cast<uint32_t>(kv.first.size());
    uint64_t val_len = kv.second.len;
    uint32_t state = crc32_update(
        0xFFFFFFFFu, reinterpret_cast<const uint8_t*>(kv.first.data()), key_len);
    state = crc32_update(state, buf.data(), val_len);
    uint32_t crc = ~state;
    fwrite(&kRecMagic, 4, 1, out);
    fwrite(&key_len, 4, 1, out);
    fwrite(&val_len, 8, 1, out);
    fwrite(kv.first.data(), 1, key_len, out);
    if (val_len) fwrite(buf.data(), 1, val_len, out);
    fwrite(&crc, 4, 1, out);
    new_index[kv.first] = Entry{static_cast<uint64_t>(pos) + 16 + key_len, val_len};
  }
  if (fflush(out) != 0) {
    fclose(out);
    remove(tmp.c_str());
    fseek(s->f, 0, SEEK_END);
    return -3;
  }
  fclose(out);
  // every error path below leaves the handle fully usable on the OLD
  // file/index (positioned at end for appends); the old FILE* stays open
  // across the rename — on POSIX it keeps the original (possibly now
  // unlinked) inode alive, and the compacted file holds the same live
  // records, so either outcome is consistent
  fseek(s->f, 0, SEEK_END);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    remove(tmp.c_str());
    return -4;
  }
  FILE* nf = fopen(s->path.c_str(), "r+b");
  if (!nf) return -5;  // keep operating on the old (unlinked) inode
  fseek(nf, 0, SEEK_END);
  fclose(s->f);
  s->f = nf;
  s->index = std::move(new_index);
  s->wasted = 0;
  return 0;
}

void lasp_store_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  if (s->f) fclose(s->f);
  delete s;
}

}  // extern "C"
