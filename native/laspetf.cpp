// Native Erlang External Term Format codec for the bridge hot path.
//
// The reference's wire codec is BEAM's own term_to_binary/binary_to_term
// (C, inside the VM); the Python fallback in lasp_tpu/bridge/etf.py is
// the semantic source of truth. This CPython extension implements the
// SAME subset byte-for-byte (etf.py gates it behind a corpus self-check
// at import and falls back to Python on any mismatch):
//   ints (incl. bignums), floats, atoms (SMALL/UTF8/old-latin1),
//   binaries, strings(STRING_EXT -> list[int]), lists, tuples, maps.
//
// Untrusted input: decode enforces a nesting-depth bound (the Python
// path is bounded by the interpreter's recursion limit; C recursion
// must bound itself) and length-checks every read.
//
// Build: make -C native  (lasp_etf.so, CPython extension module).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

constexpr uint8_t VERSION = 131;
constexpr uint8_t NEW_FLOAT = 70;
constexpr uint8_t SMALL_INT = 97;
constexpr uint8_t INT = 98;
constexpr uint8_t SMALL_BIG = 110;
constexpr uint8_t LARGE_BIG = 111;
constexpr uint8_t ATOM_UTF8 = 118;
constexpr uint8_t SMALL_ATOM_UTF8 = 119;
constexpr uint8_t ATOM_OLD = 100;  // ATOM_EXT, latin-1
constexpr uint8_t BINARY = 109;
constexpr uint8_t STRING = 107;
constexpr uint8_t LIST = 108;
constexpr uint8_t NIL = 106;
constexpr uint8_t SMALL_TUPLE = 104;
constexpr uint8_t LARGE_TUPLE = 105;
constexpr uint8_t MAP = 116;

constexpr int MAX_DEPTH = 512;

// set_classes() installs these from the Python module
PyObject *g_atom_cls = nullptr;
PyObject *g_err_cls = nullptr;

void set_decode_error(const char *msg) {
  PyErr_SetString(g_err_cls ? g_err_cls : PyExc_ValueError, msg);
}

// ---------------------------------------------------------------- encode

struct Buf {
  char *data = nullptr;
  Py_ssize_t len = 0, cap = 0;
  ~Buf() { PyMem_Free(data); }
  bool reserve(Py_ssize_t extra) {
    if (len + extra <= cap) return true;
    Py_ssize_t ncap = cap ? cap : 256;
    while (ncap < len + extra) ncap *= 2;
    char *nd = static_cast<char *>(PyMem_Realloc(data, ncap));
    if (!nd) {
      PyErr_NoMemory();
      return false;
    }
    data = nd;
    cap = ncap;
    return true;
  }
  bool put(const void *src, Py_ssize_t n) {
    if (!reserve(n)) return false;
    std::memcpy(data + len, src, n);
    len += n;
    return true;
  }
  bool u8(uint8_t v) { return put(&v, 1); }
  bool u16be(uint16_t v) {
    uint8_t b[2] = {uint8_t(v >> 8), uint8_t(v)};
    return put(b, 2);
  }
  bool u32be(uint32_t v) {
    uint8_t b[4] = {uint8_t(v >> 24), uint8_t(v >> 16), uint8_t(v >> 8),
                    uint8_t(v)};
    return put(b, 4);
  }
  bool u64be(uint64_t v) {
    uint8_t b[8];
    for (int i = 0; i < 8; i++) b[i] = uint8_t(v >> (56 - 8 * i));
    return put(b, 8);
  }
};

bool enc(PyObject *t, Buf &out, int depth);

bool check_len(Py_ssize_t n) {
  // 4-byte wire length fields; refuse (like the Python codec) instead
  // of truncating into a corrupt frame
  if (n > Py_ssize_t(0xFFFFFFFFLL)) {
    PyErr_SetString(PyExc_ValueError,
                    "term too large for ETF (4-byte length field)");
    return false;
  }
  return true;
}

bool enc_atom_bytes(const char *raw, Py_ssize_t n, Buf &out) {
  if (n < 256) {
    if (!out.u8(SMALL_ATOM_UTF8) || !out.u8(uint8_t(n))) return false;
  } else {
    if (n > 0xFFFF) {
      PyErr_SetString(PyExc_TypeError, "atom too long for ETF");
      return false;
    }
    if (!out.u8(ATOM_UTF8) || !out.u16be(uint16_t(n))) return false;
  }
  return out.put(raw, n);
}

bool enc_bignum(PyObject *t, Buf &out) {
  // arbitrary-precision path: mirror the Python encoder exactly via the
  // int's own bit_length/to_bytes (rare on the hot path)
  PyObject *zero = PyLong_FromLong(0);
  if (!zero) return false;
  int sign = PyObject_RichCompareBool(t, zero, Py_LT);
  Py_DECREF(zero);
  if (sign < 0) return false;
  PyObject *mag = sign ? PyNumber_Negative(t) : Py_NewRef(t);
  if (!mag) return false;
  PyObject *bl = PyObject_CallMethod(mag, "bit_length", nullptr);
  if (!bl) {
    Py_DECREF(mag);
    return false;
  }
  long nbits = PyLong_AsLong(bl);
  Py_DECREF(bl);
  Py_ssize_t nbytes = (nbits + 7) / 8;
  PyObject *raw =
      PyObject_CallMethod(mag, "to_bytes", "ns", nbytes, "little");
  Py_DECREF(mag);
  if (!raw) return false;
  bool ok;
  if (nbytes < 256) {
    ok = out.u8(SMALL_BIG) && out.u8(uint8_t(nbytes));
  } else {
    ok = out.u8(LARGE_BIG) && out.u32be(uint32_t(nbytes));
  }
  ok = ok && out.u8(uint8_t(sign)) &&
       out.put(PyBytes_AS_STRING(raw), PyBytes_GET_SIZE(raw));
  Py_DECREF(raw);
  return ok;
}

bool enc(PyObject *t, Buf &out, int depth) {
  if (depth > MAX_DEPTH) {
    PyErr_SetString(PyExc_TypeError, "ETF term nesting too deep");
    return false;
  }
  // Atom BEFORE str (Atom subclasses str); bool BEFORE int
  if (g_atom_cls && PyObject_TypeCheck(
                        t, reinterpret_cast<PyTypeObject *>(g_atom_cls))) {
    Py_ssize_t n;
    const char *raw = PyUnicode_AsUTF8AndSize(t, &n);
    if (!raw) return false;
    return enc_atom_bytes(raw, n, out);
  }
  if (PyBool_Check(t)) {
    const char *name = (t == Py_True) ? "true" : "false";
    return enc_atom_bytes(name, std::strlen(name), out);
  }
  if (t == Py_None) {
    return enc_atom_bytes("undefined", 9, out);
  }
  if (PyLong_Check(t)) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(t, &overflow);
    if (!overflow) {
      if (0 <= v && v <= 255) {
        return out.u8(SMALL_INT) && out.u8(uint8_t(v));
      }
      if (-(1LL << 31) <= v && v < (1LL << 31)) {
        return out.u8(INT) && out.u32be(uint32_t(int32_t(v)));
      }
      // fits int64 but not INT_EXT: still the bignum wire format
      int sign = v < 0;
      uint64_t mag = sign ? uint64_t(-(v + 1)) + 1 : uint64_t(v);
      int nbytes = 0;
      for (uint64_t m = mag; m; m >>= 8) nbytes++;
      if (!out.u8(SMALL_BIG) || !out.u8(uint8_t(nbytes)) ||
          !out.u8(uint8_t(sign)))
        return false;
      for (int i = 0; i < nbytes; i++) {
        if (!out.u8(uint8_t(mag >> (8 * i)))) return false;
      }
      return true;
    }
    return enc_bignum(t, out);
  }
  if (PyFloat_Check(t)) {
    double d = PyFloat_AS_DOUBLE(t);
    uint64_t bits;
    std::memcpy(&bits, &d, 8);
    return out.u8(NEW_FLOAT) && out.u64be(bits);
  }
  if (PyBytes_Check(t)) {
    Py_ssize_t n = PyBytes_GET_SIZE(t);
    return check_len(n) && out.u8(BINARY) && out.u32be(uint32_t(n)) &&
           out.put(PyBytes_AS_STRING(t), n);
  }
  if (PyByteArray_Check(t)) {
    Py_ssize_t n = PyByteArray_GET_SIZE(t);
    return check_len(n) && out.u8(BINARY) && out.u32be(uint32_t(n)) &&
           out.put(PyByteArray_AS_STRING(t), n);
  }
  if (PyUnicode_Check(t)) {  // plain str crosses as a binary
    Py_ssize_t n;
    const char *raw = PyUnicode_AsUTF8AndSize(t, &n);
    if (!raw) return false;
    return check_len(n) && out.u8(BINARY) && out.u32be(uint32_t(n)) &&
           out.put(raw, n);
  }
  if (PyTuple_Check(t)) {
    Py_ssize_t n = PyTuple_GET_SIZE(t);
    if (n < 256) {
      if (!out.u8(SMALL_TUPLE) || !out.u8(uint8_t(n))) return false;
    } else {
      if (!check_len(n) || !out.u8(LARGE_TUPLE) || !out.u32be(uint32_t(n)))
        return false;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!enc(PyTuple_GET_ITEM(t, i), out, depth + 1)) return false;
    }
    return true;
  }
  if (PyList_Check(t)) {
    Py_ssize_t n = PyList_GET_SIZE(t);
    if (n == 0) return out.u8(NIL);
    if (!check_len(n) || !out.u8(LIST) || !out.u32be(uint32_t(n)))
      return false;
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!enc(PyList_GET_ITEM(t, i), out, depth + 1)) return false;
    }
    return out.u8(NIL);
  }
  if (PyDict_Check(t)) {
    Py_ssize_t n = PyDict_Size(t);
    if (!check_len(n) || !out.u8(MAP) || !out.u32be(uint32_t(n)))
      return false;
    PyObject *k, *v;
    Py_ssize_t pos = 0;
    while (PyDict_Next(t, &pos, &k, &v)) {
      if (!enc(k, out, depth + 1) || !enc(v, out, depth + 1)) return false;
    }
    return true;
  }
  PyErr_Format(PyExc_TypeError, "cannot encode %s as ETF",
               Py_TYPE(t)->tp_name);
  return false;
}

PyObject *py_encode(PyObject *, PyObject *arg) {
  Buf out;
  if (!out.u8(VERSION)) return nullptr;
  if (!enc(arg, out, 0)) return nullptr;
  return PyBytes_FromStringAndSize(out.data, out.len);
}

// ---------------------------------------------------------------- decode

struct Reader {
  const uint8_t *b;
  Py_ssize_t len, off = 0;
  bool need(Py_ssize_t n) {
    if (off + n > len) {
      set_decode_error("truncated term");
      return false;
    }
    return true;
  }
  bool u8(uint8_t *v) {
    if (!need(1)) return false;
    *v = b[off++];
    return true;
  }
  bool u16be(uint32_t *v) {
    if (!need(2)) return false;
    *v = (uint32_t(b[off]) << 8) | b[off + 1];
    off += 2;
    return true;
  }
  bool u32be(uint32_t *v) {
    if (!need(4)) return false;
    *v = (uint32_t(b[off]) << 24) | (uint32_t(b[off + 1]) << 16) |
         (uint32_t(b[off + 2]) << 8) | b[off + 3];
    off += 4;
    return true;
  }
};

PyObject *dec(Reader &r, int depth);

PyObject *make_atom(const char *raw, Py_ssize_t n, bool latin1) {
  // the protocol's special atoms decode to Python singletons
  if (n == 9 && std::memcmp(raw, "undefined", 9) == 0) Py_RETURN_NONE;
  if (n == 4 && std::memcmp(raw, "true", 4) == 0) Py_RETURN_TRUE;
  if (n == 5 && std::memcmp(raw, "false", 5) == 0) Py_RETURN_FALSE;
  PyObject *s = latin1 ? PyUnicode_DecodeLatin1(raw, n, nullptr)
                       : PyUnicode_DecodeUTF8(raw, n, nullptr);
  if (!s) {
    // surface as the codec's error type (etf.py decode() contract)
    PyErr_Clear();
    set_decode_error("malformed atom bytes");
    return nullptr;
  }
  PyObject *atom = PyObject_CallFunctionObjArgs(g_atom_cls, s, nullptr);
  Py_DECREF(s);
  return atom;
}

PyObject *dec(Reader &r, int depth) {
  if (depth > MAX_DEPTH) {
    set_decode_error("term nesting too deep");
    return nullptr;
  }
  uint8_t tag;
  if (!r.u8(&tag)) return nullptr;
  switch (tag) {
    case SMALL_INT: {
      uint8_t v;
      if (!r.u8(&v)) return nullptr;
      return PyLong_FromLong(v);
    }
    case INT: {
      uint32_t v;
      if (!r.u32be(&v)) return nullptr;
      return PyLong_FromLong(int32_t(v));
    }
    case SMALL_BIG:
    case LARGE_BIG: {
      uint32_t n;
      if (tag == SMALL_BIG) {
        uint8_t n8;
        if (!r.u8(&n8)) return nullptr;
        n = n8;
      } else if (!r.u32be(&n)) {
        return nullptr;
      }
      uint8_t sign;
      if (!r.u8(&sign) || !r.need(n)) return nullptr;
      const uint8_t *p = r.b + r.off;
      r.off += n;
      if (n <= 8) {
        uint64_t mag = 0;
        for (uint32_t i = 0; i < n; i++) mag |= uint64_t(p[i]) << (8 * i);
        if (!sign) return PyLong_FromUnsignedLongLong(mag);
        if (mag <= uint64_t(INT64_MAX))
          return PyLong_FromLongLong(-int64_t(mag));
      }
      // large magnitude: int.from_bytes(p, "little"), negated if signed
      PyObject *raw = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(p), n);
      if (!raw) return nullptr;
      PyObject *mag = PyObject_CallMethod(
          reinterpret_cast<PyObject *>(&PyLong_Type), "from_bytes", "Os",
          raw, "little");
      Py_DECREF(raw);
      if (!mag) return nullptr;
      if (!sign) return mag;
      PyObject *negv = PyNumber_Negative(mag);
      Py_DECREF(mag);
      return negv;
    }
    case NEW_FLOAT: {
      if (!r.need(8)) return nullptr;
      uint64_t bits = 0;
      for (int i = 0; i < 8; i++)
        bits = (bits << 8) | r.b[r.off + i];
      r.off += 8;
      double d;
      std::memcpy(&d, &bits, 8);
      return PyFloat_FromDouble(d);
    }
    case SMALL_ATOM_UTF8:
    case ATOM_UTF8:
    case ATOM_OLD: {
      uint32_t n;
      if (tag == SMALL_ATOM_UTF8) {
        uint8_t n8;
        if (!r.u8(&n8)) return nullptr;
        n = n8;
      } else if (!r.u16be(&n)) {
        return nullptr;
      }
      if (!r.need(n)) return nullptr;
      const char *p = reinterpret_cast<const char *>(r.b + r.off);
      r.off += n;
      return make_atom(p, n, tag == ATOM_OLD);
    }
    case BINARY: {
      uint32_t n;
      if (!r.u32be(&n) || !r.need(n)) return nullptr;
      PyObject *out = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(r.b + r.off), n);
      r.off += n;
      return out;
    }
    case STRING: {  // list of bytes, surfaces as list[int]
      uint32_t n;
      if (!r.u16be(&n) || !r.need(n)) return nullptr;
      PyObject *out = PyList_New(n);
      if (!out) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject *v = PyLong_FromLong(r.b[r.off + i]);
        if (!v) {
          Py_DECREF(out);
          return nullptr;
        }
        PyList_SET_ITEM(out, i, v);
      }
      r.off += n;
      return out;
    }
    case NIL:
      return PyList_New(0);
    case LIST: {
      uint32_t n;
      if (!r.u32be(&n)) return nullptr;
      // length-check before allocating: a hostile frame must not make
      // PyList_New reserve gigabytes from a 4-byte claim
      if (Py_ssize_t(n) > r.len - r.off) {
        set_decode_error("truncated term");
        return nullptr;
      }
      PyObject *out = PyList_New(n);
      if (!out) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject *x = dec(r, depth + 1);
        if (!x) {
          Py_DECREF(out);
          return nullptr;
        }
        PyList_SET_ITEM(out, i, x);
      }
      uint8_t tail;
      if (!r.u8(&tail)) {
        Py_DECREF(out);
        return nullptr;
      }
      if (tail != NIL) {
        Py_DECREF(out);
        set_decode_error("improper list");
        return nullptr;
      }
      return out;
    }
    case SMALL_TUPLE:
    case LARGE_TUPLE: {
      uint32_t n;
      if (tag == SMALL_TUPLE) {
        uint8_t n8;
        if (!r.u8(&n8)) return nullptr;
        n = n8;
      } else if (!r.u32be(&n)) {
        return nullptr;
      }
      if (Py_ssize_t(n) > r.len - r.off) {
        set_decode_error("truncated term");
        return nullptr;
      }
      PyObject *out = PyTuple_New(n);
      if (!out) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject *x = dec(r, depth + 1);
        if (!x) {
          Py_DECREF(out);
          return nullptr;
        }
        PyTuple_SET_ITEM(out, i, x);
      }
      return out;
    }
    case MAP: {
      uint32_t n;
      if (!r.u32be(&n)) return nullptr;
      if (Py_ssize_t(n) > (r.len - r.off) / 2 + 1) {
        set_decode_error("truncated term");
        return nullptr;
      }
      PyObject *out = PyDict_New();
      if (!out) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject *k = dec(r, depth + 1);
        if (!k) {
          Py_DECREF(out);
          return nullptr;
        }
        PyObject *v = dec(r, depth + 1);
        if (!v) {
          Py_DECREF(k);
          Py_DECREF(out);
          return nullptr;
        }
        int rc = PyDict_SetItem(out, k, v);
        Py_DECREF(k);
        Py_DECREF(v);
        if (rc < 0) {
          Py_DECREF(out);
          return nullptr;
        }
      }
      return out;
    }
    default: {
      char msg[64];
      std::snprintf(msg, sizeof msg, "unsupported ETF tag %u", tag);
      set_decode_error(msg);
      return nullptr;
    }
  }
}

PyObject *py_decode(PyObject *, PyObject *arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{static_cast<const uint8_t *>(view.buf), view.len};
  if (r.len == 0 || r.b[0] != VERSION) {
    PyBuffer_Release(&view);
    set_decode_error("missing ETF version byte");
    return nullptr;
  }
  r.off = 1;
  PyObject *out = dec(r, 0);
  if (out && r.off != r.len) {
    Py_DECREF(out);
    char msg[64];
    std::snprintf(msg, sizeof msg, "trailing bytes after term (%zd)",
                  r.len - r.off);
    set_decode_error(msg);
    out = nullptr;
  }
  PyBuffer_Release(&view);
  return out;
}

PyObject *py_set_classes(PyObject *, PyObject *args) {
  PyObject *atom_cls, *err_cls;
  if (!PyArg_ParseTuple(args, "OO", &atom_cls, &err_cls)) return nullptr;
  if (!PyType_Check(atom_cls) || !PyType_Check(err_cls)) {
    PyErr_SetString(PyExc_TypeError, "set_classes expects two classes");
    return nullptr;
  }
  Py_INCREF(atom_cls);
  Py_INCREF(err_cls);
  Py_XDECREF(g_atom_cls);
  Py_XDECREF(g_err_cls);
  g_atom_cls = atom_cls;
  g_err_cls = err_cls;
  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"encode", py_encode, METH_O, "Python term -> ETF bytes"},
    {"decode", py_decode, METH_O, "ETF bytes -> Python term"},
    {"set_classes", py_set_classes, METH_VARARGS,
     "install the Atom and ETFDecodeError classes"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "lasp_etf",
    "Native ETF codec (see lasp_tpu/bridge/etf.py for the contract)",
    -1, methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_lasp_etf(void) { return PyModule_Create(&moduledef); }
