"""Test env: force CPU with 8 virtual devices so mesh/sharding tests run
without TPU hardware (the driver separately dry-runs the multi-chip path).

The machine's axon sitecustomize imports jax at interpreter startup and
calls ``jax.config.update("jax_platforms", "axon,cpu")``, which overrides
the JAX_PLATFORMS env var — so setting the env var here is NOT enough; the
config itself must be re-updated. Unit tests must never touch the axon
device: it is a single-client tunnel and concurrent runs deadlock on it.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (must configure before any backend use)

jax.config.update("jax_platforms", "cpu")

assert jax.devices()[0].platform == "cpu", "tests must run on CPU devices"
