"""Test env: force CPU with 8 virtual devices so mesh/sharding tests run
without TPU hardware (the driver separately dry-runs the multi-chip path).

The machine's axon sitecustomize imports jax at interpreter startup and
calls ``jax.config.update("jax_platforms", "axon,cpu")``, which overrides
the JAX_PLATFORMS env var — so setting the env var here is NOT enough; the
config itself must be re-updated. Unit tests must never touch the axon
device: it is a single-client tunnel and concurrent runs deadlock on it.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (must configure before any backend use)

jax.config.update("jax_platforms", "cpu")

assert jax.devices()[0].platform == "cpu", "tests must run on CPU devices"

# (re)build the native host-store engine when a toolchain is present, so
# the native-backend tests run instead of skipping. make is incremental
# (no-op when the .so is newer than the .cpp), which also refreshes a
# STALE .so that host_store.py would otherwise degrade around. Failures
# must never break collection — the tests skip gracefully without the
# library — but a failed attempt is reported, not swallowed.
import shutil  # noqa: E402
import subprocess  # noqa: E402

_native_dir = os.path.join(os.path.dirname(__file__), "..", "native")
_cxx = os.environ.get("CXX", "g++")
if shutil.which("make") and shutil.which(_cxx):
    try:
        _build = subprocess.run(
            ["make", "-C", _native_dir], capture_output=True, text=True,
            timeout=120,
        )
        if _build.returncode != 0:
            import warnings

            warnings.warn(
                "native host-store build failed (tests will use the "
                f"Python fallback):\n{_build.stderr[-500:]}",
                RuntimeWarning,
            )
    except (subprocess.TimeoutExpired, OSError):
        pass  # toolchain wedged: fall through to the graceful skips


def pytest_configure(config):
    # register the tiering marker (ROADMAP tier-1 runs -m 'not slow');
    # without registration a typo'd mark would silently join the fast
    # tier instead of warning
    config.addinivalue_line(
        "markers",
        "slow: heavy measurement/soak tests excluded from the tier-1 run",
    )
