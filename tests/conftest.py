"""Test env: force CPU with 8 virtual devices so mesh/sharding tests run
without TPU hardware (the driver separately dry-runs the multi-chip path).
Must run before jax is imported anywhere."""

import os

# Force, don't setdefault: the machine environment pins JAX_PLATFORMS=axon
# (the real TPU tunnel), which must never be used by unit tests — it is a
# single-client device and concurrent test runs deadlock on it.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
