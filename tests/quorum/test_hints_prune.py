"""Hint-log reclamation (the prune satellite) and the crash ->
checkpoint-restore -> hint-replay -> frontier-degrade ordering."""

import numpy as np

from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Partition, Restore
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.quorum import HintLog, QuorumRuntime
from lasp_tpu.store import Store

R = 9


def _build(n=R):
    store = Store(n_actors=16)
    v = store.declare(id="kv", type="lasp_gset", n_elems=32)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    return rt, v


# -- prune_replayed semantics ------------------------------------------------

def test_prune_requires_full_preflist_reack():
    """A record reclaims only once EVERY preflist replica is live and
    dominating — anything weaker stays load-bearing."""
    rt, v = _build()
    log = HintLog()
    rt.update_at(0, v, ("add", "x"), "w")
    row = __import__("jax").tree_util.tree_map(
        lambda x: x[0], rt._population(v)
    )
    log.append(v, np.asarray([0, 1, 2]), row, rid=0)
    # rows 1 and 2 have not absorbed the write yet: no prune
    assert log.prune_replayed(rt, 0) == 0 and len(log) == 1
    # a crashed preflist member blocks reclaim even when dominating
    rt.join_rows(v, np.asarray([1, 2]), row)
    live = np.ones(R, dtype=bool)
    live[2] = False
    assert log.prune_replayed(rt, 0, live=live) == 0
    # full-strength re-ack: reclaimed
    assert log.prune_replayed(rt, 0) == 1 and len(log) == 0


def test_prune_rewrites_durable_file(tmp_path):
    path = str(tmp_path / "hints.log")
    rt, v = _build()
    log = HintLog(path)
    import jax

    rt.update_at(0, v, ("add", "x"), "w")
    rt.update_at(4, v, ("add", "y"), "u")
    row_x = jax.tree_util.tree_map(lambda x: x[0], rt._population(v))
    row_y = jax.tree_util.tree_map(lambda x: x[4], rt._population(v))
    log.append(v, np.asarray([0, 1, 2]), row_x, rid=0)
    log.append(v, np.asarray([4, 5, 6]), row_y, rid=1)
    rt.join_rows(v, np.asarray([1, 2]), row_x)  # only x re-acked
    assert log.prune_replayed(rt, 0) == 1
    # survivors reload from the rewritten file, index intact
    log2 = HintLog(path)
    assert len(log2) == 1
    assert log2.pending_for(4) and not log2.pending_for(0)


def test_repeat_crash_accumulation_is_reclaimed():
    """The wiring satellite end-to-end: the same replica crashes twice;
    after each restore's replay re-acks the preflist, the record
    reclaims instead of accumulating — and the acked write survives
    both bottom-restores."""
    rt, v = _build()
    events = [Crash(2, 1), Restore(4, 1), Crash(6, 1), Restore(8, 1)]
    ch = ChaosRuntime(rt, ChaosSchedule(R, rt._host_neighbors, events,
                                        seed=3))
    qr = QuorumRuntime(ch, timeout=3, retries=2)
    qr.submit_put(v, ("add", "precious"), "w0", coordinator=0)
    while qr.inflight or ch.round <= ch.schedule.horizon:
        qr.step()
    rt.run_to_convergence()
    assert rt.coverage_value(v) == {"precious"}
    # both restores replayed; the fully re-acked record was reclaimed
    # (gossip had spread the write to the whole ring by the first
    # restore, so the re-ack condition held there already)
    assert qr.hints.replays == 2
    assert len(qr.hints) == 0


def test_prune_then_restore_stays_correct():
    """After a reclaim, ANOTHER crash + bottom-restore of a preflist
    member must still converge to the full value: the live holders
    gossip the write back (the hint was redundant by the time it was
    reclaimed — that is exactly the reclaim condition)."""
    rt, v = _build()
    events = [Crash(2, 1), Restore(4, 1),   # replay + prune here
              Crash(6, 2), Restore(8, 2)]   # no hint left: gossip heals
    ch = ChaosRuntime(rt, ChaosSchedule(R, rt._host_neighbors, events,
                                        seed=5))
    qr = QuorumRuntime(ch, timeout=3, retries=2)
    qr.submit_put(v, ("add", "kept"), "w0", coordinator=0)
    while qr.inflight or ch.round <= ch.schedule.horizon:
        qr.step()
    assert len(qr.hints) == 0  # reclaimed at the first restore
    rt.run_to_convergence()
    assert rt.coverage_value(v) == {"kept"}
    from lasp_tpu.chaos import check_no_write_lost

    check_no_write_lost(rt, qr.acked_terms)


def test_adversarial_total_preflist_crash_still_keeps_hints():
    """The PR-9 control arm is unchanged by pruning: while preflist
    members are DOWN the record never reclaims, so the simultaneous
    3-crash scenario still replays from the log."""
    rt, v = _build()
    events = [Partition(0, 8, 3),
              Crash(2, 0), Crash(2, 1), Crash(2, 2),
              Restore(4, 0), Restore(4, 1), Restore(4, 2)]
    ch = ChaosRuntime(rt, ChaosSchedule(R, rt._host_neighbors, events,
                                        seed=1))
    qr = QuorumRuntime(ch, timeout=3, retries=2)
    qr.submit_put(v, ("add", "precious"), "w0", coordinator=0)
    while qr.inflight or ch.round <= ch.schedule.horizon:
        qr.step()
    rt.run_to_convergence()
    assert rt.coverage_value(v) == {"precious"}
    assert qr.hints.replays == 3


def test_cli_prune_hints_flag(tmp_path, capsys):
    import json

    from lasp_tpu.cli import main

    path = str(tmp_path / "hints.log")
    rc = main([
        "quorum", "--preset", "rolling-crash", "--replicas", "12",
        "--writes", "3", "--reads", "1", "--rounds", "8",
        "--hints", path, "--prune-hints", "--no-replay",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["no_write_lost"]
    assert out["hints_pruned"] >= 0
    assert len(HintLog(path)) == 0  # the durable log was reclaimed


# -- the restore ORDERING satellite ------------------------------------------

def test_restore_from_quiescent_checkpoint_degrades_frontier(tmp_path):
    """Even a checkpoint saved at quiescence restores with an all-dirty
    frontier: the reseeded row must be caught up from peers that are
    themselves quiescent."""
    from lasp_tpu.store.checkpoint import load_runtime_rows, save_runtime

    rt, v = _build()
    rt.update_at(0, v, ("add", "x"), "w")
    rt.run_to_convergence()
    assert rt.frontier_size(v) == 0  # quiescent
    path = str(tmp_path / "ckpt")
    save_runtime(rt, path)
    rt.update_at(2, v, ("add", "later"), "u")
    rt.run_to_convergence()
    rows = load_runtime_rows(path, 3)
    rt.reseed_row(3, rows)
    assert rt._frontier[v].all()  # all-dirty despite quiescent source
    rt.run_to_convergence()
    assert rt.replica_value(v, 3) == {"x", "later"}


def test_hints_replay_before_replica_serves_another_quorum():
    """A restored-from-checkpoint replica, still PARTITIONED off alone,
    answers a degraded R=1 get with the acked write — possible only if
    the hint replayed BEFORE the quorum was served (gossip is cut); the
    protocol trace pins the ordering."""
    rt, v = _build()
    # put acks during the clean prefix; then every row is isolated,
    # replica 1 crashes and bottom-restores while still alone
    events = [Partition(2, 12, R), Crash(3, 1), Restore(5, 1)]
    ch = ChaosRuntime(rt, ChaosSchedule(R, rt._host_neighbors, events,
                                        seed=2))
    qr = QuorumRuntime(ch, timeout=2, retries=1)
    put = qr.submit_put(v, ("add", "precious"), "w0", coordinator=0)
    qr.step()  # round 0: put issues + acks over the healthy ring
    qr.step()  # round 1
    assert qr.result(put)["status"] in ("done", "acked")
    while ch.round < 5:
        qr.step()
    # round 5: restore fires, hints replay, THEN the FSM round runs —
    # submit the get for the NEXT round at the isolated replica
    get = qr.submit_get(v, coordinator=1, degraded=True, r=1, n=1)
    qr.step()
    res = qr.result(get)
    assert res["status"] == "done"
    assert res["value"] == {"precious"}  # only the hint can explain it
    assert res["acks"] == [1]            # served by the lone replica
    # trace ordering: the round-5 handoff precedes the get's quorum
    handoff_i = next(
        i for i, t in enumerate(qr.trace)
        if t[2] == "handoff" and t[3][0] == 1 and t[3][1] > 0
    )
    quorum_i = next(
        i for i, t in enumerate(qr.trace)
        if t[1] == get and t[2] == "quorum"
    )
    assert handoff_i < quorum_i
