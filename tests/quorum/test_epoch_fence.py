"""Membership-epoch fencing of in-flight quorum requests (the
runtime.py quorum_value caveat made typed: a stale preflist after a
resize must never silently read/push the wrong rows)."""

import numpy as np
import pytest

from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Partition
from lasp_tpu.dataflow import Graph
from lasp_tpu.membership import StaleEpochError
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.quorum import QuorumRuntime
from lasp_tpu.store import Store


def _build(n=8):
    store = Store(n_actors=16)
    store.declare(id="kv", type="lasp_gset", n_elems=32)
    return ReplicatedRuntime(store, Graph(store), n, ring(n, 2))


def _partitioned(n=8, rounds=16):
    """A quorum runtime whose requests CANNOT complete (coordinator's
    component too small for n=3 picks) — keeps them in WAITING_R so a
    mid-flight resize actually catches them in flight."""
    rt = _build(n)
    sched = ChaosSchedule(
        n, rt._host_neighbors, [Partition(0, rounds, 4)]
    )
    return rt, ChaosRuntime(rt, sched)


def test_waiting_request_without_retries_fails_typed():
    rt, ch = _partitioned()
    qr = QuorumRuntime(ch, timeout=32, retries=0)
    rid = qr.submit_get("kv", coordinator=6, r=3)
    qr.step()  # issues; the 2-row component starves the R=3 quorum
    assert qr.result(rid, raise_on_error=False)["status"] == "pending"
    rt.resize(4, ring(4, 2), graceful=False)
    qr.step()
    res = qr.result(rid, raise_on_error=False)
    assert res["status"] == "stale_epoch"
    with pytest.raises(StaleEpochError) as ei:
        qr.result(rid)
    assert ei.value.current_epoch == rt.membership_epoch


def test_waiting_request_with_retries_reprepares_on_new_ring():
    rt, ch = _partitioned(rounds=4)
    qr = QuorumRuntime(ch, timeout=32, retries=2)
    rid = qr.submit_get("kv", coordinator=6, r=3)
    qr.step()
    rt.resize(4, ring(4, 2), graceful=False)
    # heal rounds + fence: the request re-prepares (coordinator 6
    # remaps to its claim successor 6 % 4 == 2) and completes on the
    # new ring
    for _ in range(12):
        qr.step()
        if qr.result(rid, raise_on_error=False)["status"] == "done":
            break
    res = qr.result(rid)
    assert res["status"] == "done"
    assert res["coordinator"] == 2
    assert all(r < 4 for r in res["acks"])
    assert res["retries"] >= 1  # the fence consumed a retry
    assert any(
        ev[2] == "epoch_fence" and ev[3][0] == "refenced"
        for ev in qr.trace
    )


def test_prepare_request_with_departed_coordinator_remaps():
    rt = _build(8)
    qr = QuorumRuntime(rt, timeout=6, retries=0)
    rid = qr.submit_put("kv", ("add", "k"), "w0", coordinator=6)
    rt.resize(4, ring(4, 2), graceful=True)
    while qr.inflight:
        qr.step()
    res = qr.result(rid)
    assert res["status"] == "done"
    assert res["coordinator"] == 2  # 6 % 4, the claim successor
    assert all(r < 4 for r in res["acks"])
    assert "k" in rt.coverage_value("kv")


def test_grow_leaves_inflight_requests_untouched():
    """A pure grow advances the epoch but invalidates nothing:
    surviving rows keep their indices, so in-flight requests keep
    their preflists — no retry burned, no spurious stale_epoch, no
    early finalize — and complete normally once reachable."""
    rt, ch = _partitioned(rounds=3)
    qr = QuorumRuntime(ch, timeout=32, retries=2)
    rid = qr.submit_get("kv", coordinator=5, r=3)
    qr.step()
    rt.resize(12, ring(12, 2))
    for _ in range(12):
        qr.step()
        if not qr.inflight:
            break
    res = qr.result(rid)
    assert res["status"] == "done"
    assert res["retries"] == 0  # the fence consumed nothing
    assert not any(ev[2] == "epoch_fence" for ev in qr.trace)


def test_shrink_sparing_the_preflist_leaves_request_untouched():
    """A shrink whose surviving extent still covers a request's whole
    preflist does not disturb it (indices keep their meaning)."""
    rt, ch = _partitioned(rounds=3)
    qr = QuorumRuntime(ch, timeout=32, retries=2)
    rid = qr.submit_get("kv", coordinator=0, r=3)  # picks [0, 1, 2]
    qr.step()
    rt.resize(6, ring(6, 2), graceful=False)  # picks all survive
    for _ in range(12):
        qr.step()
        if not qr.inflight:
            break
    res = qr.result(rid)
    assert res["status"] == "done" and res["retries"] == 0
    assert not any(ev[2] == "epoch_fence" for ev in qr.trace)


def test_fence_counts_metric():
    from lasp_tpu.telemetry import registry

    rt, ch = _partitioned()
    qr = QuorumRuntime(ch, timeout=32, retries=0)
    qr.submit_get("kv", coordinator=6, r=3)
    qr.step()
    rt.resize(4, ring(4, 2), graceful=False)
    qr.step()
    fam = registry.get_registry().snapshot().get(
        "quorum_epoch_fences_total"
    )
    assert fam is not None
    failed = [
        s["value"] for s in fam["series"]
        if s["labels"].get("outcome") == "failed"
    ]
    assert failed and failed[0] >= 1


def test_new_submissions_after_resize_use_new_ring_unfenced():
    rt = _build(8)
    qr = QuorumRuntime(rt, timeout=6, retries=1)
    rt.resize(4, ring(4, 2), graceful=True)
    rid = qr.submit_put("kv", ("add", "fresh"), "w1", coordinator=1)
    while qr.inflight:
        qr.step()
    res = qr.result(rid)
    assert res["status"] == "done" and res["retries"] == 0


def test_prepare_request_too_wide_for_shrunken_ring_fails_typed():
    """A PREPARE-state request whose preflist width no longer fits the
    shrunken population must resolve typed stale_epoch — never abort
    the whole step with an untyped preflist ValueError."""
    rt = _build(8)
    qr = QuorumRuntime(rt, n=6, timeout=6, retries=2)
    rid = qr.submit_put("kv", ("add", "wide"), "w0", coordinator=0,
                        n=6, w=2)
    # hold it in PREPARE: shrink lands before its first step
    rt.resize(4, ring(4, 2), graceful=True)
    qr.step()  # must not raise
    with pytest.raises(StaleEpochError, match="preflist width"):
        qr.result(rid)
    # the engine is not stranded: fresh submissions still complete
    rid2 = qr.submit_put("kv", ("add", "fits"), "w1", coordinator=1,
                         n=3, w=2)
    while qr.inflight:
        qr.step()
    assert qr.result(rid2)["status"] == "done"
