"""Unit semantics of the quorum FSM layer: preflists, component
labeling over chaos masks, and the batched-vs-sequential transition
bit-identity on randomized control-plane states."""

import numpy as np
import pytest

from lasp_tpu.chaos import ChaosSchedule, Crash, DelayLinks, Partition
from lasp_tpu.mesh.topology import random_regular, ring
from lasp_tpu.quorum import fsm


def test_preflist_is_coordinator_first_ring_walk():
    assert fsm.preflist(0, 3, 8).tolist() == [0, 1, 2]
    assert fsm.preflist(6, 3, 8).tolist() == [6, 7, 0]  # wraps
    with pytest.raises(ValueError):
        fsm.preflist(0, 9, 8)


def test_next_live_coordinator_walks_past_crashes():
    crashed = np.zeros(6, dtype=bool)
    crashed[[1, 2]] = True
    assert fsm.next_live_coordinator(0, crashed) == 3
    assert fsm.next_live_coordinator(5, crashed) == 0
    assert fsm.next_live_coordinator(1, np.ones(6, dtype=bool)) is None


def test_components_unmasked_is_one_component():
    nbrs = ring(12, 2)
    comp = fsm.components(nbrs, None, np.ones(12, dtype=bool))
    assert (comp == comp[0]).all()


def test_components_split_by_partition_mask():
    R = 16
    nbrs = ring(R, 2)
    sched = ChaosSchedule(R, nbrs, [Partition(0, 4, 2)], seed=0)
    comp = sched_comp = fsm.components(
        nbrs, sched.mask_at(1), np.ones(R, dtype=bool)
    )
    left, right = comp[:8], comp[8:]
    assert (left == left[0]).all() and (right == right[0]).all()
    assert left[0] != right[0]


def test_components_exclude_crashed_rows():
    R = 8
    nbrs = ring(R, 2)
    sched = ChaosSchedule(R, nbrs, [Crash(0, 3)], seed=0)
    live = ~sched.crashed_at(0)
    comp = fsm.components(nbrs, sched.mask_at(0), live)
    # the crashed row keeps its own label; everyone else connects
    others = comp[live]
    assert (others == others[0]).all()
    assert comp[3] != others[0]


def test_components_under_full_delay_links_isolate_everyone():
    R = 8
    nbrs = ring(R, 2)
    sched = ChaosSchedule(
        R, nbrs, [DelayLinks(0, 8, frac=1.0, delay=3)], seed=0
    )
    comp = fsm.components(nbrs, sched.mask_at(0), np.ones(R, dtype=bool))
    assert len(set(comp.tolist())) == R  # every row its own component


def _random_control_plane(rng, b, n, R):
    state = rng.choice(
        [fsm.WAITING_R, fsm.WAITING_N, fsm.DONE, fsm.FAILED],
        size=b, p=[0.5, 0.3, 0.1, 0.1],
    ).astype(np.int32)
    coord = rng.randint(0, R, size=b).astype(np.int32)
    picks = np.stack(
        [fsm.preflist(c, n, R) for c in coord]
    ).astype(np.int32)
    pick_valid = np.ones((b, n), dtype=bool)
    for i in rng.choice(b, size=b // 4, replace=False):
        pick_valid[i, rng.randint(1, n):] = False
    acks = rng.rand(b, n) < 0.3
    acks &= pick_valid
    deadline = rng.randint(0, 8, size=b).astype(np.int32)
    need = rng.randint(1, n + 1, size=b).astype(np.int32)
    degraded = rng.rand(b) < 0.3
    return state, coord, picks, pick_valid, acks, deadline, need, degraded


@pytest.mark.parametrize("topo", ["ring", "random"])
def test_transition_batched_matches_sequential_randomized(topo):
    """The kernel contract: for random control planes × masked
    reachability, the one-dispatch batched transition equals the
    per-request scalar loop bit-for-bit on every output."""
    R, n = 16, 3
    nbrs = ring(R, 2) if topo == "ring" else random_regular(R, 3, seed=7)
    sched = ChaosSchedule(
        R, nbrs,
        [Partition(0, 3, 2), DelayLinks(3, 6, frac=0.5, delay=1),
         Crash(1, 5), Crash(2, 11)],
        seed=9,
    )
    rng = np.random.RandomState(42)
    for rnd in range(6):
        live = ~sched.crashed_at(rnd)
        comp = fsm.components(nbrs, sched.mask_at(rnd), live)
        for b in (1, 5, 17, 64):
            plane = _random_control_plane(rng, b, n, R)
            out_b = fsm.transition_batched(*plane, comp, live, rnd)
            out_s = fsm.transition_sequential(*plane, comp, live, rnd)
            for ob, os_ in zip(out_b, out_s):
                assert np.array_equal(ob, os_), (rnd, b)


def test_bucket_padding_reuses_kernels():
    R, n = 8, 3
    comp = np.zeros(R, dtype=np.int32)
    live = np.ones(R, dtype=bool)
    rng = np.random.RandomState(0)
    plane = _random_control_plane(rng, 3, n, R)
    fsm.transition_batched(*plane, comp, live, 0)  # ensures (8, 3)
    assert (8, 3) in fsm._kernel_cache
    snapshot = set(fsm._kernel_cache)
    for b in (5, 7, 8):  # all pad to the same bucket (8)
        plane = _random_control_plane(rng, b, n, R)
        fsm.transition_batched(*plane, comp, live, 0)
    # one executable served every size: no new compiles
    assert set(fsm._kernel_cache) == snapshot
