"""QuorumRuntime protocol semantics + the tentpole's acceptance
contract: the batched tensor engine is BIT-IDENTICAL to the
per-request sequential reference — results, repair writes, ack
sequences, final population states — across codecs × topologies ×
chaos presets."""

import numpy as np
import pytest

from lasp_tpu.chaos import (
    ChaosRuntime,
    ChaosSchedule,
    Crash,
    Partition,
    Restore,
    nemesis,
)
from lasp_tpu.chaos.invariants import (
    InvariantViolation,
    check_no_write_lost,
    fingerprint,
    run_quorum_harness,
    snapshot_states,
)
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.quorum import HintLog, PartialQuorumError, QuorumRuntime
from lasp_tpu.store import Store


def _build(R, nbrs, type="lasp_gset", packed=False, **caps):
    store = Store(n_actors=16)
    caps.setdefault("n_elems", 32)
    if type == "riak_dt_orswot":
        caps.setdefault("n_actors", 16)
    v = store.declare(id="kv", type=type, **caps)
    rt = ReplicatedRuntime(store, Graph(store), R, nbrs, packed=packed)
    return rt, v


# -- protocol semantics -----------------------------------------------------

def test_put_reaches_w_then_finalizes_all_n():
    R = 8
    rt, v = _build(R, ring(R, 2))
    qr = QuorumRuntime(rt)
    rid = qr.submit_put(v, ("add", "x"), "w0", coordinator=2)
    qr.step()
    res = qr.result(rid)
    assert res["status"] == "done"
    assert res["acks"] == [2, 3, 4]  # the ring preflist, all N acked
    assert res["rounds"] == 1
    assert rt.replica_value(v, 3) == {"x"}  # replicated, not just local
    assert qr.acked_terms == {v: {"x"}}


def test_get_value_is_quorum_join_and_repairs():
    R = 8
    rt, v = _build(R, ring(R, 2))
    rt.update_at(5, v, ("add", "y"), "w5")
    qr = QuorumRuntime(rt)
    rid = qr.submit_get(v, coordinator=5)
    qr.step()
    res = qr.result(rid)
    assert res["value"] == {"y"} and res["status"] == "done"
    # read-repair pushed the join into the acked quorum rows
    assert rt.replica_value(v, 6) == {"y"}
    assert qr.repaired_rows > 0


def test_timeout_repick_moves_coordinator_past_crash():
    """A crashed coordinator mid-wait: the request times out, re-picks
    the next live replica, and completes there — the preflist routing
    of the reference, as an FSM transition."""
    R = 8
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    rt.update_at(4, v, ("add", "z"), "w4")
    sched = ChaosSchedule(R, nbrs, [Crash(0, 0), Restore(6, 0)], seed=1)
    ch = ChaosRuntime(rt, sched)
    qr = QuorumRuntime(ch, timeout=2, retries=2)
    rid = qr.submit_get(v, coordinator=0)  # crashed at round 0
    while qr.inflight:
        qr.step()
    res = qr.result(rid)
    assert res["status"] == "done"
    assert res["coordinator"] != 0  # re-picked past the crash
    assert res["retries"] == 0  # routed at PREPARE, no retry consumed
    assert qr.report()["completed"] == 1


def test_strict_get_fails_with_partial_quorum_error():
    R = 16
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    # 8-way partition: 2-replica islands; preflist {15, 0, 1} spans cuts
    sched = ChaosSchedule(R, nbrs, [Partition(0, 10, 8)], seed=2)
    qr = QuorumRuntime(ChaosRuntime(rt, sched), timeout=2, retries=1)
    rid = qr.submit_get(v, coordinator=15, r=3)
    while qr.inflight:
        qr.step()
    with pytest.raises(PartialQuorumError, match="partial quorum"):
        qr.result(rid)
    assert qr.result(rid, raise_on_error=False)["status"] == "failed"
    assert qr.report()["failed"] == 1


def test_degraded_get_answers_r_of_live():
    """R-of-live degradation: the same cut that fails a strict get
    answers a degraded one from the coordinator's island."""
    R = 16
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    rt.update_at(15, v, ("add", "edge"), "w15")
    sched = ChaosSchedule(R, nbrs, [Partition(0, 10, 8)], seed=2)
    qr = QuorumRuntime(ChaosRuntime(rt, sched), timeout=2, retries=1)
    rid = qr.submit_get(v, coordinator=15, r=3, degraded=True)
    qr.step()
    res = qr.result(rid)
    # the client has its answer (R-of-live) while the FSM finalizes
    # toward the unreachable preflist stragglers
    assert res["status"] == "acked" and res["value"] == {"edge"}
    assert res["rounds"] == 1
    while qr.inflight:
        qr.step()
    res = qr.result(rid)
    assert res["status"] == "done" and res["value"] == {"edge"}
    # island of coordinator 15 under the 8-way cut is {14, 15}
    assert set(res["acks"]) <= {14, 15, 0, 1}


def test_inflight_batch_advances_together():
    """Thousands-in-flight is the point: a wave of requests advances as
    ONE batch per round (the kernel sees every active request)."""
    R = 32
    rt, v = _build(R, ring(R, 2), n_elems=256)
    qr = QuorumRuntime(rt)
    rids = [
        qr.submit_put(v, ("add", f"e{i}"), f"w{i}", coordinator=i % R)
        for i in range(64)
    ]
    rids += [qr.submit_get(v, coordinator=(i * 7) % R) for i in range(64)]
    out = qr.step()
    assert out["fired"] == 128  # every request reached quorum in round 0
    assert qr.inflight == 0
    assert all(qr.result(r)["status"] == "done" for r in rids)


# -- the acceptance contract: batched == sequential -------------------------

@pytest.mark.parametrize("type_name,packed,topo", [
    ("lasp_gset", False, "ring"),
    ("riak_dt_orswot", False, "random"),
    ("lasp_orset", True, "ring"),  # packed wire format, same FSMs
])
@pytest.mark.parametrize("preset", ["flaky-links", "rolling-crash"])
def test_batched_engine_bit_identical_to_sequential(type_name, packed,
                                                    topo, preset):
    # topology is PAIRED with the codec (the full topology x codec x
    # packed cross runs in tools/quorum_smoke.py, `make verify`)
    R = 16
    nbrs = ring(R, 2) if topo == "ring" else random_regular(R, 3, seed=3)
    outs = []
    for engine in ("batched", "sequential"):
        rt, v = _build(R, nbrs, type=type_name, packed=packed)
        sched = nemesis(preset, R, nbrs, seed=5, rounds=6)
        ch = ChaosRuntime(rt, sched)
        qr = QuorumRuntime(ch, engine=engine, timeout=3, retries=3)
        results = []
        for i in range(14):
            if i < 6:
                coord = (i * 5) % R
                if not ch.crashed[coord]:
                    qr.submit_put(v, ("add", f"e{i}"), f"w{i}",
                                  coordinator=coord)
                qr.submit_get(v, coordinator=int(
                    np.flatnonzero(~ch.crashed)[0]
                ), degraded=True)
            qr.step()
        while qr.inflight:
            qr.step()
        for rid in range(qr._next_rid):
            results.append(qr.result(rid, raise_on_error=False))
        outs.append({
            "trace": qr.trace,
            "fp": fingerprint(snapshot_states(rt)),
            "results": results,
            "accounting": (qr.repaired_rows, qr.pushed_rows,
                           qr.wire_bytes, qr.completed, qr.failed,
                           qr.retries),
        })
    assert outs[0]["trace"] == outs[1]["trace"]
    assert outs[0]["fp"] == outs[1]["fp"]
    assert outs[0]["results"] == outs[1]["results"]
    assert outs[0]["accounting"] == outs[1]["accounting"]


# -- hinted handoff + no-acknowledged-write-lost ----------------------------

def _adversarial_loss_schedule(R, nbrs):
    """Isolate exactly the preflist {0,1,2}, crash ALL THREE at once
    mid-window, restore from bottom still partitioned: without hinted
    handoff the acked write exists nowhere afterwards."""
    events = [Partition(0, 8, 3),
              Crash(2, 0), Crash(2, 1), Crash(2, 2),
              Restore(4, 0), Restore(4, 1), Restore(4, 2)]
    return ChaosSchedule(R, nbrs, events, seed=1)


def test_acked_write_survives_total_preflist_crash_via_hints():
    R = 9
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    ch = ChaosRuntime(rt, _adversarial_loss_schedule(R, nbrs))
    qr = QuorumRuntime(ch, timeout=3, retries=2)
    qr.submit_put(v, ("add", "precious"), "w0", coordinator=0)
    while qr.inflight or ch.round <= ch.schedule.horizon:
        qr.step()
    rt.run_to_convergence()
    check_no_write_lost(rt, qr.acked_terms)
    assert rt.coverage_value(v) == {"precious"}
    assert qr.hints.replays == 3  # one handoff per restored replica


def test_without_hints_the_acked_write_is_lost():
    """The control arm: sabotaging the hint log loses the write — the
    invariant is non-trivially upheld, not vacuous."""
    R = 9
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    ch = ChaosRuntime(rt, _adversarial_loss_schedule(R, nbrs))
    qr = QuorumRuntime(ch, timeout=3, retries=2)
    qr.submit_put(v, ("add", "precious"), "w0", coordinator=0)
    while qr.inflight or ch.round <= ch.schedule.horizon:
        qr.hints.prune()  # drop every hint before it can replay
        qr.step()
    rt.run_to_convergence()
    with pytest.raises(InvariantViolation, match="acknowledged write"):
        check_no_write_lost(rt, qr.acked_terms)


def test_hint_log_durable_roundtrip(tmp_path):
    path = str(tmp_path / "hints.log")
    R = 8
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    qr = QuorumRuntime(rt, hints=path)
    qr.submit_put(v, ("add", "x"), "w0", coordinator=0)
    qr.step()
    assert len(qr.hints) == 1
    # a fresh HintLog over the same path re-reads the records (the
    # process-restart story) and hands off against the SAME store's
    # universe — hint rows are wire-format and interner-relative, so a
    # foreign store could not decode them
    log2 = HintLog(path)
    assert len(log2) == 1
    rt.reseed_row(1, None)  # wipe the row back to bottom
    assert rt.replica_value(v, 1) == set()
    changed = log2.replay(rt, 1)
    assert changed == 1 and rt.replica_value(v, 1) == {"x"}
    assert log2.replay(rt, 1) == 0  # idempotent re-handoff
    assert log2.prune() == 1 and len(HintLog(path)) == 0


def test_run_quorum_harness_rolling_crash():
    """The acceptance criterion end-to-end: puts acked at W=2 survive
    the rolling-crash nemesis via hinted handoff, checked by the
    chaos/invariants.py harness (replay determinism included)."""
    R = 16
    nbrs = ring(R, 2)

    def build():
        store = Store(n_actors=16)
        store.declare(id="kv", type="lasp_gset", n_elems=32)
        return ReplicatedRuntime(store, Graph(store), R, nbrs)

    sched = nemesis("rolling-crash", R, nbrs, seed=11, rounds=9)
    report = run_quorum_harness(
        build, sched,
        writes=[(rnd, "kv", ("add", f"t{rnd}"), f"w{rnd}", (rnd * 3) % R)
                for rnd in range(4)],
        reads=[(3, "kv", 1)],
        timeout=3, retries=3,
    )
    assert report["no_write_lost"] and report["replay_identical"]
    assert report["failed"] == 0
    assert report["acked_terms"] == {"kv": 4}


def test_repicked_coordinator_receives_the_write():
    """Review-hardening regression: after a coordinator re-pick, the
    push exclusion keys on the row the op APPLIED at — the NEW
    coordinator is an ordinary pick and must receive the delta, or it
    would count toward W while holding nothing (an R-of-live read
    coordinated there would then miss an acked write)."""
    R = 6
    nbrs = ring(R, 2)
    rt, v = _build(R, nbrs)
    # partition {0,1,2} | {3,4,5}; put at 2 -> picks {2,3,4} span the
    # cut -> timeout -> re-pick to 3 (the other side)
    sched = ChaosSchedule(R, nbrs, [Partition(0, 20, 2)], seed=0)
    qr = QuorumRuntime(ChaosRuntime(rt, sched), timeout=2, retries=3)
    rid = qr.submit_put(v, ("add", "x"), "w", coordinator=2)
    while qr.inflight:
        qr.step()
    res = qr.result(rid)
    assert res["status"] == "done" and res["coordinator"] == 3
    for r in res["acks"]:
        assert rt.quorum_value(v, [r]) == {"x"}, (
            f"acked row {r} does not hold the write"
        )


def test_quorum_harness_durable_hints_path(tmp_path):
    """Review-hardening regression: a durable ``hints_path`` must not
    break replay determinism — each harness run starts from a truncated
    log (the second run would otherwise inherit the first's fsync'd
    records and diverge on handoff counts)."""
    R = 12
    nbrs = ring(R, 2)

    def build():
        store = Store(n_actors=16)
        store.declare(id="kv", type="lasp_gset", n_elems=32)
        return ReplicatedRuntime(store, Graph(store), R, nbrs)

    sched = nemesis("rolling-crash", R, nbrs, seed=4, rounds=8)
    path = str(tmp_path / "hints.log")
    for _ in range(2):  # second call re-enters over the populated file
        report = run_quorum_harness(
            build, sched,
            writes=[(i, "kv", ("add", f"t{i}"), f"w{i}", (i * 5) % R)
                    for i in range(2)],
            hints_path=path, timeout=3, retries=3,
        )
        assert report["no_write_lost"] and report["replay_identical"]


# -- health / telemetry surfaces --------------------------------------------

def test_report_lands_in_health_surface():
    from lasp_tpu.telemetry import get_monitor

    R = 8
    rt, v = _build(R, ring(R, 2))
    qr = QuorumRuntime(rt)
    qr.submit_put(v, ("add", "x"), "w0", coordinator=0)
    qr.step()
    rep = qr.report()
    health = get_monitor().health()
    assert health["quorum"]["completed"] == rep["completed"]
    assert health["quorum"]["put_p50_rounds"] == rep["put_p50_rounds"]


def test_quorum_step_lands_in_roofline_ledger():
    from lasp_tpu.telemetry import get_ledger

    R = 8
    rt, v = _build(R, ring(R, 2))
    qr = QuorumRuntime(rt)
    for i in range(4):  # warm past the compile bucket
        qr.submit_put(v, ("add", f"x{i}"), f"w{i}", coordinator=i)
        qr.step()
    rows = [e for e in get_ledger().snapshot()
            if e["family"] == "quorum_step"]
    assert rows and rows[0]["dispatches"] >= 1


def test_submit_validation():
    R = 8
    rt, v = _build(R, ring(R, 2))
    qr = QuorumRuntime(rt)
    with pytest.raises(KeyError):
        qr.submit_get("nope")
    with pytest.raises(IndexError):
        qr.submit_get(v, coordinator=99)
    with pytest.raises(ValueError, match="quorum"):
        qr.submit_get(v, r=4)  # r > n
    with pytest.raises(ValueError, match="engine"):
        QuorumRuntime(rt, engine="warp")
