"""Ring-coverage queries: grouped partition-sweep map-merge equals the
per-variable coverage value bit-for-bit, groups by plan signature, and
feeds the 2i index programs in one dispatch per group."""

import numpy as np

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.programs.riak_index import (
    BASE_NAME,
    RiakIndexProgram,
    RiakObject,
    view_name,
)
from lasp_tpu.quorum import coverage_sweep, ring_coverage_execute
from lasp_tpu.quorum.coverage import _sweep_cache
from lasp_tpu.store import Store


def _mixed_rt(R=12, topo=ring, k=2):
    store = Store(n_actors=8)
    ids = []
    for i in range(4):
        ids.append(store.declare(id=f"g{i}", type="lasp_gset", n_elems=16))
    ids.append(store.declare(id="c0", type="riak_dt_gcounter"))
    ids.append(store.declare(id="o0", type="riak_dt_orswot",
                             n_elems=16, n_actors=8))
    rt = ReplicatedRuntime(store, Graph(store), R, topo(R, k))
    for i in range(4):
        rt.update_at((i * 3) % R, f"g{i}", ("add", f"e{i}"), f"w{i}")
    rt.update_at(5, "c0", ("increment",), "wc")
    rt.update_at(7, "o0", ("add", "tag"), "wo")
    return rt, ids


def test_sweep_matches_per_var_coverage_value():
    rt, ids = _mixed_rt()
    for n_shards in (1, 4):
        sw = coverage_sweep(rt, n_shards=n_shards)
        for v in ids:
            assert sw[v] == rt.coverage_value(v), (v, n_shards)


def test_sweep_groups_by_signature():
    """4 same-spec gsets share ONE compiled sweep (G=4); the counter
    and orswot are their own groups — the plan-compiler discipline on
    the query path. (R=14 is unique to this test, so the signature keys
    are fresh in the module-level sweep cache.)"""
    rt, _ids = _mixed_rt(R=14)
    before = set(_sweep_cache)
    coverage_sweep(rt, n_shards=4)
    new = [k for k in _sweep_cache if k not in before]
    gs = [k for k in new if k[2] == 4]  # the G=4 gset group
    assert len(gs) == 1
    assert len(new) == 3  # gset x4, gcounter, orswot


def test_sweep_after_more_writes_stays_exact():
    rt, ids = _mixed_rt(R=10, topo=random_regular, k=3)
    rt.run_to_convergence(max_rounds=64)
    rt.update_at(0, "g0", ("add", "late"), "w9")
    sw = coverage_sweep(rt)
    assert sw["g0"] == rt.coverage_value("g0") >= {"e0", "late"}


def test_ring_coverage_execute_feeds_index_views():
    R = 10
    store = Store(n_actors=8)
    rt = ReplicatedRuntime(store, Graph(store), R, ring(R, 2))
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=32, token_space=32)
    for i in range(6):
        rt.process(
            RiakObject(
                key=f"k{i}", vclock=("vc", i),
                index_specs=(("add", "color",
                              "red" if i % 2 else "blue"),),
            ),
            "put", f"a{i}", replica=i % R,
        )
    rt.run_to_convergence(max_rounds=64)
    out = ring_coverage_execute(rt)
    assert set(out) == set(rt.programs)
    for name in out:
        assert out[name] == rt.execute(name), name
    # the auto-created same-spec views all rode one grouped sweep
    assert view_name("color", "red") in out
    assert out[BASE_NAME] == {f"k{i}" for i in range(6)}


def test_ring_coverage_execute_unknown_program_is_loud():
    rt, _ids = _mixed_rt()
    import pytest

    with pytest.raises(KeyError, match="nope"):
        ring_coverage_execute(rt, names=["nope"])
