"""AAEScrubber: detection/repair lifecycle, pending repairs under
partitions, join-fixed-point escalation, late-attach divergence repair,
the serving background hook, and the health surface."""

import numpy as np
import pytest

from lasp_tpu.aae import AAEScrubber
from lasp_tpu.chaos import (
    ChaosRuntime,
    ChaosSchedule,
    CorruptRows,
    Partition,
)
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.store import Store

R = 12


def _runtime():
    store = Store(n_actors=8)
    store.declare(id="g", type="lasp_gset", n_elems=24)
    rt = ReplicatedRuntime(store, Graph(store), R, ring(R, 2))
    for w in range(4):
        rt.update_at((w * 3) % R, "g", ("add", f"e{w}"), f"w{w}")
    return rt


def test_detects_localizes_and_overwrites_silent_corruption():
    rt = _runtime()
    sched = ChaosSchedule(R, rt._host_neighbors,
                          [CorruptRows(2, kind="bitflip")], seed=3)
    ch = ChaosRuntime(rt, sched)
    sc = AAEScrubber(ch)
    while ch.round < 64:
        if ch.step() == 0 and ch.round > sched.horizon:
            break
    assert len(ch.injected_corruptions) == 1
    inj = ch.injected_corruptions[0]
    assert [(d["var"], d["row"]) for d in sc.detected] == [
        (inj["var"], inj["row"])
    ]
    assert sc.detected[0]["round"] == inj["round"]  # same-round detect
    assert sc.incidents and not sc.pending
    assert sc.repaired_overwrites == 1
    # repaired before any gossip could spread it: the fixed point is
    # the corruption-free one
    assert rt.coverage_value("g") == {"e0", "e1", "e2", "e3"}


def test_pending_repair_waits_out_full_isolation():
    """A corrupt row with NO reachable healthy peer parks as pending
    and repairs the moment its partition heals."""
    rt = _runtime()
    # every row its own partition group for rounds [1, 5): zero peers
    events = [Partition(1, 5, R), CorruptRows(2, kind="bitflip")]
    sched = ChaosSchedule(R, rt._host_neighbors, events, seed=7)
    ch = ChaosRuntime(rt, sched)
    sc = AAEScrubber(ch)
    for _ in range(3):  # rounds 0..2: injection + detection, isolated
        ch.step()
    assert len(sc.detected) == 1 and len(sc.pending) == 1
    assert not sc.incidents
    while ch.round < 64:
        if ch.step() == 0 and ch.round > sched.horizon:
            break
    assert not sc.pending and sc.incidents  # healed -> repaired


def test_join_fixed_point_divergence_escalates_to_overwrite(monkeypatch):
    """A pair still hashing unequal after its own repair join is a
    broken lattice: both rows escalate through the corruption path."""
    rt = _runtime()
    rt.run_to_convergence()
    sc = AAEScrubber(rt)
    sc.forest.refresh()
    # silent divergence the committed baseline cannot see: attach-time
    # state is trusted (fresh scrubber), so rig the forest to report a
    # post-join mismatch once — the escalation trigger in isolation
    import lasp_tpu.aae.repair as repair_mod

    sw = {"pairs": [(2, 3, ["g"])], "divergent": {"g": [2, 3]},
          "rounds": 1, "comparisons": 5, "components": 1}
    calls = {"n": 0}
    real = sc.forest.rehash_rows

    def rigged(var_id, rows):
        out = real(var_id, rows)
        calls["n"] += 1
        if calls["n"] == 1 and len(rows) == 2:
            return np.asarray([1, 2], dtype=np.uint32)  # still unequal
        return out

    monkeypatch.setattr(sc.forest, "rehash_rows", rigged)
    live = np.ones(R, dtype=bool)
    joined, escalated = sc._repair_divergence(0, sw, None, live)
    assert joined == 1 and escalated == 2
    assert {d["source"] for d in sc.detected} == {"join_fixed_point"}
    assert {(i["var"], i["row"]) for i in sc.incidents} == {
        ("g", 2), ("g", 3)
    }
    assert not sc.pending


def test_late_attach_deflationary_corruption_repairs_via_join():
    """Corruption predating the forest is indistinguishable from legit
    state (the riak caveat) — but a DEFLATED row still surfaces as
    exchange divergence on a quiet frontier and join-repairs."""
    rt = _runtime()
    rt.run_to_convergence()
    st = rt.states["g"]
    # silent deflation: drop every set bit at row 5 (no marks)
    rt.states["g"] = st._replace(mask=st.mask.at[5].set(False))
    sc = AAEScrubber(rt)
    out = sc.scrub()
    assert out["joins"] >= 1 and out["escalated"] == 0
    assert bool(np.asarray(rt.states["g"].mask[5]).any())
    # a second scrub finds nothing left
    out = sc.scrub()
    assert out["divergent_rows"] == 0 and out["corrupt_detected"] == 0


def test_serve_background_scrub_runs_and_defers_under_pressure():
    from lasp_tpu.serve import AdmissionController, ServeFrontend

    rt = _runtime()
    sc = AAEScrubber(rt)
    fe = ServeFrontend(rt, admission=AdmissionController(),
                       gossip_block=0, aae=sc, scrub_every=1)
    fe.cycle()
    assert fe.scrubs_run == 1 and fe.scrubs_skipped == 0
    fe.admission.level = 2  # pressure: the ladder outranks hygiene
    fe.cycle()
    assert fe.scrubs_run == 1 and fe.scrubs_skipped == 1
    fe.admission.level = 0
    fe.cycle()
    assert fe.scrubs_run == 2
    rep = fe.report()
    assert rep["aae_scrubs"] == 2 and rep["aae_scrubs_deferred"] == 1


def test_report_lands_in_health_surface():
    from lasp_tpu.telemetry import get_monitor

    rt = _runtime()
    sc = AAEScrubber(rt)
    sc.scrub()
    rep = sc.report()
    health = get_monitor().health()
    assert health["aae"]["scrubs"] == rep["scrubs"]
    assert "full_resync_bytes" in health["aae"]
    assert rep["repair_bytes"] <= rep["full_resync_bytes"]


def test_aae_hash_ledger_family_records():
    from lasp_tpu.telemetry import get_ledger

    rt = _runtime()
    sc = AAEScrubber(rt)
    sc.scrub()
    sc.scrub()  # past the compile-bucket slot
    fams = {row["family"] for row in get_ledger().snapshot()}
    assert "aae_hash" in fams


def test_session_on_ramp():
    from lasp_tpu.api import Session

    session = Session()
    v = session.declare(type="lasp_gset", id="g", n_elems=8)
    session.update(v, ("add", "x"), "w")
    rt = session.replicate(8)
    sc = session.aae(rt)
    out = sc.scrub()
    assert out["corrupt_detected"] == 0
    assert session.health()["aae"]["scrubs"] >= 0 or True
