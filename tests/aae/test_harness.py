"""The end-to-end corruption drill (the acceptance matrix): for three
codecs (gset, OR-SWOT, packed OR-Set) under both corruption-class
presets — including CorruptRows combined with a partition — every
injected corruption is detected within the scrub cadence, localized to
exactly the injected (var, row) set, repaired, and the healed
population is bit-identical to a fault-free twin's fixed point."""

import json

import pytest

from lasp_tpu.chaos import (
    CORRUPTION_PRESETS,
    BitRot,
    ChaosSchedule,
    CorruptRows,
    InvariantViolation,
    nemesis,
)
from lasp_tpu.chaos.invariants import run_aae_harness
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.store import Store

R = 12
NBRS = ring(R, 2)

_CODECS = {
    "gset": dict(type="lasp_gset", n_elems=32),
    "orswot": dict(type="riak_dt_orswot", n_elems=16, n_actors=8),
    "packed_orset": dict(type="lasp_orset", n_elems=16,
                         tokens_per_actor=4),
}


def _builder(codec_key):
    caps = dict(_CODECS[codec_key])
    packed = codec_key == "packed_orset"

    def build():
        store = Store(n_actors=16)
        store.declare(id="v", **caps)
        rt = ReplicatedRuntime(store, Graph(store), R, NBRS,
                               packed=packed)
        for w in range(4):
            rt.update_at((w * 3 + 1) % R, "v", ("add", f"e{w}"), f"w{w}")
        return rt

    return build


@pytest.mark.parametrize("preset", sorted(CORRUPTION_PRESETS))
@pytest.mark.parametrize("codec", sorted(_CODECS))
def test_corruption_drill_matrix(codec, preset):
    sched = nemesis(preset, R, NBRS, seed=9, rounds=6)
    report = run_aae_harness(_builder(codec), sched, scrub_every=1,
                             replay=False)
    assert report["injected"] >= 1
    assert report["detected_and_repaired"]
    assert report["bit_identical_to_fault_free"]
    assert max(report["detection_latency_rounds"]) <= 1
    assert report["pending"] == 0
    assert report["repair_bytes"] < report["full_resync_bytes"]


def test_drill_replay_determinism():
    sched = nemesis("corrupt-partition", R, NBRS, seed=4, rounds=6)
    report = run_aae_harness(_builder("gset"), sched, scrub_every=1,
                             replay=True)
    assert report["replay_identical"]


def test_wider_cadence_bounds_detection_latency():
    """scrub_every=2 with EXACT dirty tracking (frontier mode) on a
    quiesced population: a silent corruption injected between scrubs is
    detected at the next one — latency bounded by the cadence, never
    laundered into the baseline. (Dense mode's conservative all-dirty
    marks legitimize everything each active round, which is why the
    acceptance drill pins scrub_every=1 there — the documented
    strictness/latency trade, docs/RESILIENCE.md.)"""
    sched = ChaosSchedule(R, NBRS, [CorruptRows(9, kind="bitflip")],
                          seed=6)
    report = run_aae_harness(_builder("gset"), sched, scrub_every=2,
                             mode="frontier", replay=False)
    assert report["injected"] == 1
    assert report["detection_latency_rounds"] == [1]


def test_dense_wide_cadence_is_refused_loudly():
    """Dense all-dirty marks launder corruption between scrubs, so the
    harness cannot uphold its own detection guarantee there — it must
    refuse the configuration with the explanation, not fail later with
    a confusing UNDETECTED violation (review-hardening regression)."""
    sched = nemesis("bit-rot", R, NBRS, seed=9, rounds=6)
    with pytest.raises(ValueError, match="launder"):
        run_aae_harness(_builder("gset"), sched, scrub_every=3,
                        replay=False)
    from lasp_tpu.cli import main

    rc = main(["aae", "--preset", "bit-rot", "--replicas", "10",
               "--scrub-every", "3", "--no-replay"])
    assert rc == 2


def test_cli_prune_hints_requires_durable_path():
    """--prune-hints without --hints would prune a fresh empty log and
    report 0 while inspecting nothing (review-hardening regression)."""
    from lasp_tpu.cli import main

    rc = main(["quorum", "--preset", "rolling-crash", "--replicas",
               "12", "--writes", "2", "--rounds", "8", "--prune-hints",
               "--no-replay"])
    assert rc == 2


def test_harness_has_teeth_without_a_scrubber():
    """The control arm: the same corruption with NO detection must fail
    bit-equality — the drill is non-vacuous."""
    from lasp_tpu.chaos import ChaosRuntime
    from lasp_tpu.chaos.invariants import snapshot_states, states_equal

    build = _builder("gset")
    sched = nemesis("bit-rot", R, NBRS, seed=9, rounds=6,
                    kind="bitflip", every=2)
    free = build()
    free.run_to_convergence()
    free_states = snapshot_states(free)
    rt = build()
    ch = ChaosRuntime(rt, sched)  # no AAE attached
    while ch.round < 128:
        if ch.step() == 0 and ch.round > sched.horizon:
            break
    assert ch.injected_corruptions, "nemesis injected nothing"
    assert not states_equal(snapshot_states(rt), free_states), (
        "undetected corruption should have changed the destination"
    )


# -- schedule vocabulary -----------------------------------------------------

def test_corruption_events_validate():
    with pytest.raises(ValueError, match="kind"):
        ChaosSchedule(R, NBRS, [CorruptRows(2, kind="nope")])
    with pytest.raises(ValueError, match="n_rows"):
        ChaosSchedule(R, NBRS, [CorruptRows(2, n_rows=0)])
    with pytest.raises(ValueError, match="empty fault window"):
        ChaosSchedule(R, NBRS, [BitRot(5, 5)])


def test_corruptions_at_and_window_splitting():
    sched = ChaosSchedule(
        R, NBRS,
        [CorruptRows(3), BitRot(6, 12, every=3)],
        seed=1,
    )
    assert [i for i, _e, _s in sched.corruptions_at(3)] == [0]
    assert sched.corruptions_at(4) == []
    assert [s for _i, _e, s in sched.corruptions_at(9)] == [1]
    # fused windows must break at injection rounds
    assert sched.next_action_round(0) == 3
    assert sched.next_action_round(3) == 6
    assert sched.next_action_round(6) == 9
    assert sched.next_action_round(9) is None
    assert sched.horizon == 12


def test_corruption_injection_is_pure_in_seed_and_round():
    from lasp_tpu.chaos import ChaosRuntime

    build = _builder("gset")
    ledgers = []
    for _ in range(2):
        rt = build()
        sched = ChaosSchedule(R, NBRS, [CorruptRows(1, n_rows=2)],
                              seed=13)
        ch = ChaosRuntime(rt, sched)
        ch.step()
        ch.step()
        ledgers.append(ch.injected_corruptions)
    assert ledgers[0] == ledgers[1] and ledgers[0]


def test_cli_aae_preset_choices_in_sync():
    """cli.py keeps a literal corruption-preset list (the no-jax-at-
    parse rule); it must match chaos.CORRUPTION_PRESETS."""
    import os
    import re

    import lasp_tpu.cli

    src = open(os.path.abspath(lasp_tpu.cli.__file__)).read()
    block = re.search(
        r'aae\.add_argument\("--preset", default="bit-rot",\s*'
        r"choices=\[(.*?)\]", src, re.S,
    ).group(1)
    choices = set(re.findall(r'"([a-z-]+)"', block))
    assert choices == set(CORRUPTION_PRESETS)


def test_cli_aae_verb_end_to_end(capsys):
    from lasp_tpu.cli import main

    rc = main([
        "aae", "--preset", "bit-rot", "--replicas", "10",
        "--rounds", "6", "--writers", "4", "--no-replay",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["detected_and_repaired"]
    assert out["bit_identical_to_fault_free"]
    assert out["preset"] == "bit-rot"
    assert out["aae_health"]["scrubs"] > 0
