"""Tree exchange: root fast path, exact localization, component
confinement, and the O(log)-comparison walk."""

import numpy as np

from lasp_tpu.aae import HashForest, exchange_pair, sweep
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.store import Store

R = 12


def _runtime(n_vars=6):
    store = Store(n_actors=8)
    for i in range(n_vars):
        store.declare(id=f"v{i}", type="lasp_gset", n_elems=16)
    rt = ReplicatedRuntime(store, Graph(store), R, ring(R, 2))
    return rt


def _diverge(rt, var, row, elem=7):
    """Make ONE row of one var differ (a tracked write that has not
    gossiped yet)."""
    rt.update_at(row, var, ("add", f"d{elem}"), f"w{elem}")


def test_converged_population_exchanges_in_one_root_comparison():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    out = exchange_pair(forest, 2, 9)
    assert out["divergent"] == [] and out["comparisons"] == 1
    sw = sweep(forest)
    assert sw["divergent"] == {}
    # stride-1 early exit: one pairing round, R root comparisons
    assert sw["rounds"] == 1 and sw["comparisons"] == R


def test_exchange_localizes_exactly_the_divergent_var():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    _diverge(rt, "v3", 5)
    forest.refresh()
    out = exchange_pair(forest, 5, 6)
    assert out["divergent"] == ["v3"]
    # the walk descended: root + all segments + one segment's leaves
    assert out["comparisons"] > 1
    sw = sweep(forest)
    assert set(sw["divergent"]) == {"v3"}
    assert 5 in sw["divergent"]["v3"]


def test_sweep_respects_components():
    """Divergence across a partition cut is NOT paired — exchange
    through the cut would be the side channel the chaos discipline
    forbids."""
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    _diverge(rt, "v1", 2)  # rows 0..5 = component 0, 6..11 = comp 1
    forest.refresh()
    comp = np.asarray([0] * 6 + [6] * 6, dtype=np.int32)
    sw = sweep(forest, components=comp)
    # row 2 diverges only against ITS component's members
    assert all(r < 6 for r in sw["divergent"]["v1"])
    assert sw["components"] == 2


def test_sweep_skips_crashed_rows():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    _diverge(rt, "v0", 4)
    forest.refresh()
    live = np.ones(R, dtype=bool)
    live[4] = False  # the divergent row is down: frozen, not exchanged
    sw = sweep(forest, live=live)
    assert sw["divergent"] == {}
