"""HashForest: row-hash sensitivity, incremental-vs-full agreement,
plan-group dispatch, segment reuse, and epoch lifecycle."""

import numpy as np
import pytest

from lasp_tpu.aae import HashForest, group_row_hashes, row_hashes
from lasp_tpu.aae.hashtree import subset_row_hashes
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.store import Store

R = 10


def _runtime(packed=False, n_gsets=3):
    store = Store(n_actors=8)
    for i in range(n_gsets):
        store.declare(id=f"g{i}", type="lasp_gset", n_elems=24)
    store.declare(id="o", type="riak_dt_orswot", n_elems=12, n_actors=4)
    store.declare(id="p", type="lasp_orset", n_elems=12,
                  tokens_per_actor=4)
    rt = ReplicatedRuntime(store, Graph(store), R, ring(R, 2),
                           packed=packed)
    for i in range(n_gsets):
        rt.update_at(i % R, f"g{i}", ("add", f"e{i}"), f"w{i}")
    rt.update_at(1, "o", ("add", "x"), "a0")
    rt.update_at(2, "p", ("add", "y"), "b0")
    return rt


@pytest.mark.parametrize("packed", [False, True])
@pytest.mark.parametrize("var", ["g0", "o", "p"])
def test_row_hash_detects_any_single_bit_flip(packed, var):
    """The mixer is a bijection: flipping ONE bit of ONE wire word
    changes that row's hash with certainty — never just whp."""
    import jax

    rt = _runtime(packed=packed)
    pop = rt._population(var)
    before = row_hashes(pop)
    leaves = jax.tree_util.tree_leaves(pop)
    host = np.array(np.asarray(leaves[0]))
    flat = host.reshape(R, -1)
    if flat.dtype == np.bool_:
        flat[4, 0] = ~flat[4, 0]
    else:
        flat[4, 0] = flat[4, 0] ^ flat.dtype.type(1)
    mutated = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(pop),
        [host] + [np.asarray(x) for x in leaves[1:]],
    )
    after = row_hashes(mutated)
    assert after[4] != before[4]
    mask = np.ones(R, dtype=bool)
    mask[4] = False
    assert np.array_equal(after[mask], before[mask])


def test_subset_hashes_match_full():
    rt = _runtime()
    pop = rt._population("o")
    full = row_hashes(pop)
    rows = np.asarray([0, 3, 7], dtype=np.int64)
    assert np.array_equal(subset_row_hashes(pop, rows), full[rows])


def test_grouped_hashes_match_pervar():
    from lasp_tpu.mesh.plan import stack_group

    rt = _runtime(n_gsets=4)
    ids = [f"g{i}" for i in range(4)]
    stacked = stack_group([rt._population(v) for v in ids])
    mat = group_row_hashes(stacked)
    for i, v in enumerate(ids):
        assert np.array_equal(mat[i], row_hashes(rt._population(v)))


def test_quiescent_refresh_costs_nothing():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()  # commit the baseline
    out = forest.refresh()
    assert out["rows_hashed"] == 0 and out["vars_touched"] == 0


def test_incremental_refresh_matches_full_rebuild():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    rt.update_at(5, "g1", ("add", "fresh"), "w9")  # marks row 5 dirty
    out = forest.refresh()
    assert 0 < out["rows_hashed"] < R  # the incremental arm ran
    inc = {v: forest.committed[v].copy() for v in forest.var_order}
    # from-scratch twin forest over the same population
    twin = HashForest(rt)
    twin.refresh()
    for v in forest.var_order:
        assert np.array_equal(inc[v], twin.committed[v]), v
    assert np.array_equal(forest.roots, twin.roots)


def test_clean_segments_are_not_rehashed():
    rt = _runtime(n_gsets=12)  # > 2 segments at seg_size=4
    forest = HashForest(rt, seg_size=4)
    forest.refresh()
    base = forest.segments_rehashed
    rt.update_at(0, "g0", ("add", "zz"), "wz")  # leaf 0 -> segment 0
    forest.refresh()
    assert forest.segments_rehashed == base + 1  # only segment 0


def test_verify_flags_untracked_mutation_exactly():
    import jax
    import jax.numpy as jnp

    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    # silent mutation: direct state write, no mark_dirty / _aae_mark
    pop = rt.states["g0"]
    rt.states["g0"] = pop._replace(mask=pop.mask.at[6, 3].set(True))
    out = forest.refresh(verify=True)
    assert out["corrupt"] == [("g0", 6)]
    # tracked mutations are never flagged
    rt.update_at(2, "g0", ("add", "ok"), "wk")
    out = forest.refresh(verify=True)
    assert out["corrupt"] == []


def test_structural_epoch_resyncs_and_mask_epoch_keeps_baseline():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    committed_before = {
        v: forest.committed[v].copy() for v in forest.var_order
    }
    rt._invalidate_plan("mask_change")
    forest.refresh()
    for v in forest.var_order:  # baseline survives a mask flip
        assert np.array_equal(forest.committed[v], committed_before[v])
    rt._invalidate_plan("resize")
    forest.refresh()
    # resync happened: everything went dirty and recommitted (values
    # equal — state unchanged — but the pass was a full rehash)
    assert forest.rows_hashed["full"] > 0


def test_late_declared_variable_joins_the_forest():
    rt = _runtime()
    forest = HashForest(rt)
    forest.refresh()
    rt.store.declare(id="late", type="lasp_gset", n_elems=8)
    rt._population("late")  # sync the late declare
    forest.refresh()
    assert "late" in forest.var_order
    assert forest.committed["late"].shape == (R,)
