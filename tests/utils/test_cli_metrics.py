"""CLI (L6 console role) and step-trace metrics tests."""

import json

from lasp_tpu import cli
from lasp_tpu.utils.metrics import StepTrace


def test_cli_status(capsys):
    assert cli.main(["status"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["platform"] == "cpu"
    assert len(out["devices"]) == 8


def test_cli_simulate(capsys):
    rc = cli.main(
        ["simulate", "--replicas", "64", "--topology", "ring", "--writers", "4"]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["rounds_to_convergence"] >= 1
    assert out["residual_path"][-1] == 0
    assert out["value_size"] == 4


def test_cli_inspect_checkpoint(tmp_path, capsys):
    from lasp_tpu.store import Store, save_store

    store = Store(n_actors=4)
    v = store.declare(type="lasp_gset", n_elems=4)
    store.update(v, ("add", "x"), "w")
    path = str(tmp_path / "c.log")
    save_store(store, path)
    assert cli.main(["inspect", path]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["kind"] == "store"
    assert out["vars"][v] == "lasp_gset"


def test_runtime_records_trace():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    store = Store(n_actors=4)
    v = store.declare(type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    rt.update_at(0, v, ("add", "x"), "w")
    rounds = rt.run_to_convergence(max_rounds=16)
    s = rt.trace.summary()
    assert s["rounds"] == rounds
    assert s["residual_path"][-1] == 0
    assert s["seconds"] > 0


def test_step_trace_counters():
    t = StepTrace()
    t.bump("merges", 5)
    t.bump("merges")
    t.record_round(3, 0.25)
    assert t.summary() == {
        "rounds": 1,
        "seconds": 0.25,
        "residual_path": [3],
        "merges": 6,
    }


def test_profile_context_emits_trace(tmp_path):
    """profile() wraps a block in a jax.profiler trace and leaves the
    artifacts on disk (the §5 tracing/profiling subsystem)."""
    import os

    import jax.numpy as jnp

    from lasp_tpu.utils.metrics import profile

    d = str(tmp_path / "trace")
    with profile(d):
        jnp.ones((8, 8)).sum().block_until_ready()
    found = [
        os.path.join(root, f)
        for root, _dirs, files in os.walk(d)
        for f in files
    ]
    assert found, "profiler trace produced no files"


def test_cli_simulate_gcounter_value_key(capsys):
    # the counter total rides under "value" (a number), never under
    # "value_size" (a cardinality) — consumers must not misread totals
    rc = cli.main(
        ["simulate", "--replicas", "32", "--topology", "ring",
         "--writers", "4", "--type", "riak_dt_gcounter"]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["value"] == 4  # one increment per writer lane
    assert "value_size" not in out


def test_profile_start_failure_is_not_masked(monkeypatch):
    """If start_trace itself raises, the ORIGINAL error must propagate
    and stop_trace must not run (stopping a never-started trace raises
    its own error, masking the real one)."""
    import jax.profiler as jp

    import pytest

    from lasp_tpu.utils.metrics import profile

    stopped = []
    monkeypatch.setattr(
        jp, "start_trace",
        lambda d: (_ for _ in ()).throw(RuntimeError("start failed")),
    )
    monkeypatch.setattr(jp, "stop_trace", lambda: stopped.append(1))
    with pytest.raises(RuntimeError, match="start failed"):
        with profile("/tmp/never"):
            raise AssertionError("body must not run")
    assert stopped == []


def test_profile_body_error_survives_stop_failure(monkeypatch):
    """A stop_trace failure while the body is already raising must not
    mask the body's exception."""
    import jax.profiler as jp

    import pytest

    from lasp_tpu.utils.metrics import profile

    monkeypatch.setattr(jp, "start_trace", lambda d: None)
    monkeypatch.setattr(
        jp, "stop_trace",
        lambda: (_ for _ in ()).throw(RuntimeError("stop failed")),
    )
    with pytest.raises(ValueError, match="the real error"):
        with profile("/tmp/never"):
            raise ValueError("the real error")


def test_profile_reexported_from_telemetry():
    from lasp_tpu.telemetry import profile as tele_profile
    from lasp_tpu.utils.metrics import profile as util_profile

    assert tele_profile is util_profile
