"""CLI tests for the convergence observatory verbs: ``lasp_tpu top``
(live per-variable residual/staleness table + shard lag + alerts
against a running mesh) and ``lasp_tpu trace --var --export``
(Perfetto/Chrome-trace causal history through a combinator edge)."""

import json

import pytest

from lasp_tpu import cli
from lasp_tpu import telemetry
from lasp_tpu.telemetry import events as E


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    E.clear()
    yield
    telemetry.reset()
    E.clear()


def test_cli_top_renders_live_mesh(capsys):
    rc = cli.main([
        "top", "--replicas", "16", "--iterations", "3",
        "--refresh", "0", "--shards", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    frames = [f for f in out.split("---") if f.strip()]
    assert len(frames) == 3
    # the table names every workload variable with residual/stale/lag
    for var in ("ads", "seen_ads", "hits"):
        assert var in frames[0]
    assert "RESIDUAL" in frames[0] and "STALE" in frames[0]
    assert "shard lag: s0=" in frames[0] and "s3=" in frames[0]
    assert "worst replica:" in frames[0]
    # the observed mesh steps between frames: the round counter advances
    rounds = [
        int(line.split("round=")[1].split()[0])
        for line in out.splitlines()
        if line.startswith("convergence: round=")
    ]
    assert rounds == sorted(rounds) and rounds[0] < rounds[-1]


def test_cli_top_rejects_degenerate_population(capsys):
    assert cli.main(["top", "--replicas", "1", "--iterations", "1"]) == 2


def test_cli_top_bridge_scrape(capsys):
    from lasp_tpu.bridge import BridgeServer

    with BridgeServer(port=0) as server:
        rc = cli.main([
            "top", "--bridge", f"127.0.0.1:{server.port}",
            "--iterations", "1", "--refresh", "0",
        ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "convergence: round=" in out
    assert "alerts: none" in out or "ALERT" in out


def test_cli_trace_exports_chrome_json(tmp_path, capsys):
    path = str(tmp_path / "trace.json")
    rc = cli.main([
        "trace", "--var", "seen_ads", "--export", path,
        "--replicas", "16",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["var"] == "seen_ads"
    # the lineage walks the map edge back to the source variable
    assert summary["lineage"] == {"seen_ads": ["ads"]}
    assert summary["events"] > 0
    doc = json.loads(open(path).read())
    evs = doc["traceEvents"]
    assert evs and all(
        {"name", "ph", "ts", "pid", "tid"} <= set(t) for t in evs
    )
    assert {t["ph"] for t in evs} <= {"X", "i"}
    # the causal history reaches the SOURCE updates through the edge
    updates = [
        t for t in evs
        if t["cat"] == "event" and t["name"] == "update"
    ]
    assert any(t["args"].get("var") == "ads" for t in updates)
    # population context (deliveries) rides along, ordered by ts
    assert any(t["name"] == "delivery" for t in evs)
    ts = [t["ts"] for t in evs]
    assert ts == sorted(ts)


def test_cli_trace_deep_carries_edge_provenance(tmp_path, capsys):
    path = str(tmp_path / "deep.json")
    rc = cli.main([
        "trace", "--var", "seen_ads", "--export", path,
        "--replicas", "8", "--deep",
    ])
    E.set_deep(False)
    assert rc == 0
    doc = json.loads(open(path).read())
    recomputes = [
        t for t in doc["traceEvents"] if t["name"] == "edge_recompute"
    ]
    assert recomputes, "deep trace must carry edge provenance"
    assert recomputes[0]["args"]["var"] == "seen_ads"
    assert recomputes[0]["args"]["srcs"] == ["ads"]


def _delivery_rounds(path):
    """Per-round (round, residual) pairs from a trace export's delivery
    markers, in round order."""
    doc = json.loads(open(path).read())
    out = [
        (t["args"]["round"], t["args"]["residual"])
        for t in doc["traceEvents"]
        if t.get("cat") == "event" and t["name"] == "delivery"
    ]
    out.sort()
    return out


def test_cli_trace_fused_window_has_real_round_records(tmp_path, capsys):
    """A fused-window convergence (--block > 1) must contribute REAL
    per-round delivery records to the trace — the flight recorder's
    whole point: the on-device ring carries what each in-block round
    did, where the pre-flight path logged one opaque marker."""
    path = str(tmp_path / "fused.json")
    rc = cli.main([
        "trace", "--var", "seen_ads", "--export", path,
        "--replicas", "16", "--block", "4",
    ])
    assert rc == 0
    rounds = _delivery_rounds(path)
    # one record per executed in-block round, with round provenance
    assert len(rounds) >= 2
    rs = [r for r, _res in rounds]
    assert rs == list(range(rs[0], rs[0] + len(rs)))
    # the drained records are attributed to the fused family
    doc = json.loads(open(path).read())
    assert any(
        t["args"].get("fused") == "fused_block"
        for t in doc["traceEvents"] if t["name"] == "delivery"
    )
    # the window reaches the fixed point: the residual curve ends at 0
    assert rounds[-1][1] == 0


def test_cli_trace_fused_and_unfused_round_curves_agree(tmp_path, capsys):
    """Same seeded workload, fused vs per-round stepping: the per-round
    residuals the flight ring drained must agree bit-for-bit with the
    unfused deliveries on every productive round (the fused block may
    append trailing no-op zeros — full blocks run to the block edge)."""
    p1 = str(tmp_path / "unfused.json")
    assert cli.main([
        "trace", "--var", "seen_ads", "--export", p1, "--replicas", "16",
    ]) == 0
    unfused = _delivery_rounds(p1)
    telemetry.reset()
    E.clear()
    p2 = str(tmp_path / "fused.json")
    assert cli.main([
        "trace", "--var", "seen_ads", "--export", p2,
        "--replicas", "16", "--block", "4",
    ]) == 0
    fused = _delivery_rounds(p2)
    res_unfused = [res for _r, res in unfused]
    res_fused = [res for _r, res in fused]
    # identical productive-round count and identical residual values;
    # any fused tail beyond the unfused run is all-zero no-ops
    assert len(res_fused) >= len(res_unfused)
    assert res_fused[: len(res_unfused)] == res_unfused
    assert all(r == 0 for r in res_fused[len(res_unfused):])


def test_cli_trace_unknown_var(tmp_path, capsys):
    rc = cli.main([
        "trace", "--var", "nope", "--export", str(tmp_path / "x.json"),
        "--replicas", "8",
    ])
    assert rc == 2
