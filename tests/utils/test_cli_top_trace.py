"""CLI tests for the convergence observatory verbs: ``lasp_tpu top``
(live per-variable residual/staleness table + shard lag + alerts
against a running mesh) and ``lasp_tpu trace --var --export``
(Perfetto/Chrome-trace causal history through a combinator edge)."""

import json

import pytest

from lasp_tpu import cli
from lasp_tpu import telemetry
from lasp_tpu.telemetry import events as E


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    E.clear()
    yield
    telemetry.reset()
    E.clear()


def test_cli_top_renders_live_mesh(capsys):
    rc = cli.main([
        "top", "--replicas", "16", "--iterations", "3",
        "--refresh", "0", "--shards", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    frames = [f for f in out.split("---") if f.strip()]
    assert len(frames) == 3
    # the table names every workload variable with residual/stale/lag
    for var in ("ads", "seen_ads", "hits"):
        assert var in frames[0]
    assert "RESIDUAL" in frames[0] and "STALE" in frames[0]
    assert "shard lag: s0=" in frames[0] and "s3=" in frames[0]
    assert "worst replica:" in frames[0]
    # the observed mesh steps between frames: the round counter advances
    rounds = [
        int(line.split("round=")[1].split()[0])
        for line in out.splitlines()
        if line.startswith("convergence: round=")
    ]
    assert rounds == sorted(rounds) and rounds[0] < rounds[-1]


def test_cli_top_rejects_degenerate_population(capsys):
    assert cli.main(["top", "--replicas", "1", "--iterations", "1"]) == 2


def test_cli_top_bridge_scrape(capsys):
    from lasp_tpu.bridge import BridgeServer

    with BridgeServer(port=0) as server:
        rc = cli.main([
            "top", "--bridge", f"127.0.0.1:{server.port}",
            "--iterations", "1", "--refresh", "0",
        ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "convergence: round=" in out
    assert "alerts: none" in out or "ALERT" in out


def test_cli_trace_exports_chrome_json(tmp_path, capsys):
    path = str(tmp_path / "trace.json")
    rc = cli.main([
        "trace", "--var", "seen_ads", "--export", path,
        "--replicas", "16",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["var"] == "seen_ads"
    # the lineage walks the map edge back to the source variable
    assert summary["lineage"] == {"seen_ads": ["ads"]}
    assert summary["events"] > 0
    doc = json.loads(open(path).read())
    evs = doc["traceEvents"]
    assert evs and all(
        {"name", "ph", "ts", "pid", "tid"} <= set(t) for t in evs
    )
    assert {t["ph"] for t in evs} <= {"X", "i"}
    # the causal history reaches the SOURCE updates through the edge
    updates = [
        t for t in evs
        if t["cat"] == "event" and t["name"] == "update"
    ]
    assert any(t["args"].get("var") == "ads" for t in updates)
    # population context (deliveries) rides along, ordered by ts
    assert any(t["name"] == "delivery" for t in evs)
    ts = [t["ts"] for t in evs]
    assert ts == sorted(ts)


def test_cli_trace_deep_carries_edge_provenance(tmp_path, capsys):
    path = str(tmp_path / "deep.json")
    rc = cli.main([
        "trace", "--var", "seen_ads", "--export", path,
        "--replicas", "8", "--deep",
    ])
    E.set_deep(False)
    assert rc == 0
    doc = json.loads(open(path).read())
    recomputes = [
        t for t in doc["traceEvents"] if t["name"] == "edge_recompute"
    ]
    assert recomputes, "deep trace must carry edge provenance"
    assert recomputes[0]["args"]["var"] == "seen_ads"
    assert recomputes[0]["args"]["srcs"] == ["ads"]


def test_cli_trace_unknown_var(tmp_path, capsys):
    rc = cli.main([
        "trace", "--var", "nope", "--export", str(tmp_path / "x.json"),
        "--replicas", "8",
    ])
    assert rc == 2
