"""Execute every ``python`` code block in docs/GUIDE.md.

The guide is the migration path for reference users (SURVEY.md §2.7 API
parity); running its examples verbatim keeps the documentation honest —
the rebuild of the reference's pattern of documenting behavior through
executable riak_tests (``riak_test/lasp_bind_test.erl`` et al.)."""

import os
import re

import pytest

GUIDE = os.path.join(os.path.dirname(__file__), "..", "..", "docs", "GUIDE.md")


def _blocks():
    text = open(GUIDE).read()
    out = []
    for i, m in enumerate(re.finditer(r"```python\n(.*?)```", text, re.S)):
        # name blocks by the nearest preceding heading for readable ids
        head = re.findall(r"^##+ (.+)$", text[: m.start()], re.M)
        label = (head[-1] if head else "intro").split("(")[0].strip()
        label = re.sub(r"[^A-Za-z0-9]+", "_", label).strip("_").lower()
        out.append(pytest.param(m.group(1), id=f"{i:02d}_{label}"))
    return out


BLOCKS = _blocks()


def test_guide_has_examples():
    assert len(BLOCKS) >= 10


@pytest.mark.parametrize("src", BLOCKS)
def test_guide_block_runs(src):
    exec(compile(src, GUIDE, "exec"), {"__name__": "guide"})
