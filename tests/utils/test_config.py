"""Unified config (SURVEY §5 config/flag system; VERDICT item 31):
typed defaults, LASP_* env overrides, loud rejection of typos."""

import pytest

from lasp_tpu.config import LaspConfig


def test_defaults_validate():
    cfg = LaspConfig().validate()
    assert cfg.n_actors == 16 and cfg.gossip_impl == "auto"


def test_env_overrides_and_types():
    cfg = LaspConfig.from_env(
        {
            "LASP_N_ACTORS": "32",
            "LASP_GOSSIP_IMPL": "xla",
            "LASP_BENCH_REPLICAS": "4096",
            "UNRELATED": "x",
        }
    ).validate()
    assert cfg.n_actors == 32
    assert cfg.gossip_impl == "xla"
    assert cfg.bench_replicas == 4096


def test_unknown_lasp_var_rejected():
    with pytest.raises(ValueError, match="unknown config variable"):
        LaspConfig.from_env({"LASP_N_ACTRS": "8"})  # typo must be loud


def test_driver_owned_knobs_pass_through():
    cfg = LaspConfig.from_env(
        {"LASP_BENCH_PROBE_WINDOW": "10", "LASP_DRYRUN_TIMEOUT": "60"}
    )
    assert cfg == LaspConfig()


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError, match="gossip_impl"):
        LaspConfig(gossip_impl="mosaic").validate()
    with pytest.raises(ValueError, match="fanout"):
        LaspConfig(fanout=0).validate()


def test_store_uses_config_default(monkeypatch):
    import lasp_tpu.config as config_mod
    from lasp_tpu.store import Store

    monkeypatch.setattr(config_mod, "_CONFIG", None)
    monkeypatch.setenv("LASP_N_ACTORS", "5")
    try:
        assert Store().n_actors == 5
        assert Store(n_actors=9).n_actors == 9
    finally:
        monkeypatch.setattr(config_mod, "_CONFIG", None)
