"""Bench-artifact content tests: the CPU-fallback `at_scale` fold-in
(the driver artifact must never understate the engine) and the headline
scenario's convergence narration."""

import importlib.util
import os


def _load_bench():
    path = os.path.join(
        os.path.dirname(__file__), "..", "..", "bench.py"
    )
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_at_scale_evidence_folds_target_scales():
    bench = _load_bench()
    ev = bench._load_at_scale_evidence()
    assert ev is not None, "docs/artifacts/cpu_evidence_*.jsonl must load"
    assert "note" in ev and ev["runs"]
    scenarios = {r["scenario"] for r in ev["runs"]}
    # the target-scale ladder: 100K gossip, 1M pipeline, 10M ad counter
    assert {"orset_100000", "pipeline_1048576",
            "adcounter_10485760"} <= scenarios
    # every folded run is labeled evidence, never an error record
    assert all("error" not in r for r in ev["runs"])


def test_headline_scenario_narrates_convergence():
    from lasp_tpu.bench_scenarios import orset_anti_entropy

    out = orset_anti_entropy(256, block=4)
    conv = out["convergence"]
    assert conv["rounds_to_quiescence"] == out["rounds"]
    # the per-block productive curve sums to the exact round count
    assert sum(conv["productive_rounds_per_block"]) == out["rounds"]
    assert conv["block"] == 4
    # every replica but at most one starts behind the global join
    assert conv["diverged_replicas_at_seed"] > 0
    assert conv["worst_replica_lag_at_seed"] == 1
