"""Pallas gossip kernel: correctness against the XLA gossip_round path,
in interpret mode on the CPU mesh (compiled execution is exercised on the
real chip by bench_pallas.py / the driver)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.lattice.base import replicate
from lasp_tpu.mesh import gossip_round, random_regular
from lasp_tpu.ops import PackedORSet, PackedORSetSpec
from lasp_tpu.ops.pallas_gossip import (
    flatten_plane,
    pallas_gossip_round,
    unflatten_plane,
)


def seeded_states(spec, n):
    states = replicate(PackedORSet.new(spec), n)
    r = jnp.arange(n)
    states = jax.vmap(
        lambda i, s: PackedORSet.add(spec, s, i % spec.n_elems, i % spec.n_actors)
    )(r, states)
    # a few removals so the removed plane is non-trivial
    states = jax.vmap(
        lambda i, s: jax.lax.cond(
            i % 5 == 0,
            lambda x: PackedORSet.remove(spec, x, i % spec.n_elems),
            lambda x: x,
            s,
        )
    )(r, states)
    return states


@pytest.mark.parametrize("n,k", [(32, 2), (64, 3)])
def test_pallas_round_matches_xla(n, k):
    spec = PackedORSetSpec(n_elems=16, n_actors=8, tokens_per_actor=8)  # W=2
    states = seeded_states(spec, n)
    nbrs = jnp.asarray(random_regular(n, k, seed=3))

    ref = gossip_round(PackedORSet, spec, states, nbrs)

    fe, d = flatten_plane(states.exists)
    fr, _ = flatten_plane(states.removed)
    oe, orr = pallas_gossip_round(fe, fr, nbrs, block=8, interpret=True)
    got_e = unflatten_plane(oe, states.exists.shape)
    got_r = unflatten_plane(orr, states.removed.shape)

    np.testing.assert_array_equal(np.asarray(got_e), np.asarray(ref.exists))
    np.testing.assert_array_equal(np.asarray(got_r), np.asarray(ref.removed))


def test_pallas_matches_xla_at_the_headline_shape():
    """The EXACT wide-row bench shape (128 elems x 64 actors x 4 tokens =
    1024 words/plane, 8 KiB/replica over both planes) at a tiny
    population — the shape the TPU autotune gate will hand the kernel
    first. A shape assumption that only breaks at bench widths must die
    here in interpret mode, not in Mosaic on the capture run."""
    spec = PackedORSetSpec(n_elems=128, n_actors=64, tokens_per_actor=4)
    n, k = 32, 3
    states = seeded_states(spec, n)
    nbrs = jnp.asarray(random_regular(n, k, seed=7))
    ref = gossip_round(PackedORSet, spec, states, nbrs)
    fe, _d = flatten_plane(states.exists)
    fr, _ = flatten_plane(states.removed)
    # the bench gate's block parameter (cfg.bench_block default 4)
    oe, orr = pallas_gossip_round(fe, fr, nbrs, block=4, interpret=True)
    got_e = unflatten_plane(oe, states.exists.shape)
    got_r = unflatten_plane(orr, states.removed.shape)
    np.testing.assert_array_equal(np.asarray(got_e), np.asarray(ref.exists))
    np.testing.assert_array_equal(np.asarray(got_r), np.asarray(ref.removed))


def test_pallas_rounds_converge():
    n, k = 64, 3
    spec = PackedORSetSpec(n_elems=16, n_actors=8, tokens_per_actor=8)
    states = seeded_states(spec, n)
    nbrs = jnp.asarray(random_regular(n, k, seed=5))
    fe, d = flatten_plane(states.exists)
    fr, _ = flatten_plane(states.removed)
    for _ in range(16):
        ne, nr = pallas_gossip_round(fe, fr, nbrs, block=8, interpret=True)
        if bool(jnp.all(ne == fe)) and bool(jnp.all(nr == fr)):
            break
        fe, fr = ne, nr
    # fixed point = every row equals the global join
    top_e = jnp.broadcast_to(
        jax.lax.reduce(fe, jnp.uint32(0), jax.lax.bitwise_or, (0,)), fe.shape
    )
    np.testing.assert_array_equal(np.asarray(fe), np.asarray(top_e))
