"""Packed OR-Set correctness: every operation must agree with the dense
codec through pack/unpack (the dense codec is itself property-tested
against the reference oracle), and fused gossip must equal per-round
gossip."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.lattice import ORSet, replicate
from lasp_tpu.mesh import converged, gossip_round, ring
from lasp_tpu.ops import (
    PackedORSet,
    PackedORSetSpec,
    fused_gossip_rounds,
    pack_orset,
    unpack_orset,
)

SPEC = PackedORSetSpec(n_elems=5, n_actors=3, tokens_per_actor=13)  # T=39 > 32
DENSE = SPEC.dense()


def random_dense(rng, n_ops=25):
    state = ORSet.new(DENSE)
    for _ in range(n_ops):
        roll = rng.random()
        e = rng.randrange(SPEC.n_elems)
        if roll < 0.6:
            state = ORSet.add(DENSE, state, e, rng.randrange(SPEC.n_actors))
        else:
            state = ORSet.remove(DENSE, state, e)
    return state


@pytest.mark.parametrize("seed", range(5))
def test_pack_unpack_roundtrip(seed):
    d = random_dense(random.Random(seed))
    p = pack_orset(SPEC, d)
    back = unpack_orset(SPEC, p)
    np.testing.assert_array_equal(np.asarray(back.exists), np.asarray(d.exists))
    # removed flags only meaningful where exists
    np.testing.assert_array_equal(
        np.asarray(back.removed & back.exists),
        np.asarray(d.removed & d.exists),
    )


@pytest.mark.parametrize("seed", range(5))
def test_ops_agree_with_dense(seed):
    rng = random.Random(100 + seed)
    d1, d2 = random_dense(rng), random_dense(rng)
    p1, p2 = pack_orset(SPEC, d1), pack_orset(SPEC, d2)

    # merge
    dm = ORSet.merge(DENSE, d1, d2)
    pm = PackedORSet.merge(SPEC, p1, p2)
    assert bool(PackedORSet.equal(SPEC, pm, pack_orset(SPEC, dm)))
    # value / member
    np.testing.assert_array_equal(
        np.asarray(PackedORSet.value(SPEC, p1)), np.asarray(ORSet.value(DENSE, d1))
    )
    np.testing.assert_array_equal(
        np.asarray(PackedORSet.member_mask(SPEC, p1)),
        np.asarray(ORSet.member_mask(DENSE, d1)),
    )
    # order predicates
    assert bool(PackedORSet.is_inflation(SPEC, p1, pm)) == bool(
        ORSet.is_inflation(DENSE, d1, dm)
    )
    assert bool(PackedORSet.is_strict_inflation(SPEC, p1, pm)) == bool(
        ORSet.is_strict_inflation(DENSE, d1, dm)
    )
    assert bool(PackedORSet.is_inflation(SPEC, pm, p1)) == bool(
        ORSet.is_inflation(DENSE, dm, d1)
    )


@pytest.mark.parametrize("seed", range(3))
def test_update_ops_agree(seed):
    rng = random.Random(200 + seed)
    d = random_dense(rng)
    p = pack_orset(SPEC, d)
    e, a = rng.randrange(SPEC.n_elems), rng.randrange(SPEC.n_actors)
    d2 = ORSet.add(DENSE, d, e, a)
    p2 = PackedORSet.add(SPEC, p, e, a)
    assert bool(PackedORSet.equal(SPEC, p2, pack_orset(SPEC, d2)))
    d3 = ORSet.remove(DENSE, d2, e)
    p3 = PackedORSet.remove(SPEC, p2, e)
    assert bool(PackedORSet.equal(SPEC, p3, pack_orset(SPEC, d3)))
    tok = rng.randrange(SPEC.n_tokens)
    d4 = ORSet.add_by_token(DENSE, d3, e, tok)
    p4 = PackedORSet.add_by_token(SPEC, p3, e, tok)
    assert bool(PackedORSet.equal(SPEC, p4, pack_orset(SPEC, d4)))


def test_fused_gossip_matches_per_round():
    n = 16
    states = replicate(PackedORSet.new(SPEC), n)
    # replica r adds element r%E with actor r%A
    states = jax.vmap(
        lambda i, s: PackedORSet.add(SPEC, s, i % SPEC.n_elems, i % SPEC.n_actors)
    )(jnp.arange(n), states)
    nbrs = jnp.asarray(ring(n, 2))

    loop = states
    for _ in range(4):
        loop = gossip_round(PackedORSet, SPEC, loop, nbrs)
    fused, changed = fused_gossip_rounds(PackedORSet, SPEC, states, nbrs, 4)
    assert bool(changed)
    eq = jax.vmap(lambda a, b: PackedORSet.equal(SPEC, a, b))(loop, fused)
    assert bool(jnp.all(eq))
    # drive to convergence with blocks; final block reports unchanged
    while True:
        fused, changed = fused_gossip_rounds(PackedORSet, SPEC, fused, nbrs, 4)
        if not bool(changed):
            break
    assert bool(converged(PackedORSet, SPEC, fused))


def test_fused_gossip_count_exact_rounds():
    """The counting block's productive-round sum equals the exact
    rounds-to-convergence found by stepping one round at a time."""
    from lasp_tpu.ops.fused import fused_gossip_rounds_count

    n = 24
    states = replicate(PackedORSet.new(SPEC), n)
    states = jax.vmap(
        lambda i, s: PackedORSet.add(SPEC, s, i % SPEC.n_elems, i % SPEC.n_actors)
    )(jnp.arange(n), states)
    nbrs = jnp.asarray(ring(n, 2))

    # oracle: exact per-round convergence count
    t, oracle_rounds = states, 0
    while True:
        t2 = gossip_round(PackedORSet, SPEC, t, nbrs)
        if bool(jnp.all(jax.vmap(lambda a, b: PackedORSet.equal(SPEC, a, b))(t, t2))):
            break
        t, oracle_rounds = t2, oracle_rounds + 1

    for block in (1, 3, 4, 7):  # block sizes that do and don't divide it
        s, rounds = states, 0
        while True:
            s, prod = fused_gossip_rounds_count(PackedORSet, SPEC, s, nbrs, block)
            prod = int(prod)
            rounds += prod
            if prod < block:
                break
        assert rounds == oracle_rounds, (block, rounds, oracle_rounds)
        assert bool(converged(PackedORSet, SPEC, s))
