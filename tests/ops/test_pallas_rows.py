"""Row-sparse Pallas gossip kernel: bit-equality against the XLA
``gossip_round_rows`` / ``gossip_round_rows_grouped`` kernels in
interpret mode on the CPU mesh, across codec families (leafwise or/max,
packed two-plane, vclock), bucket sizes, valid-mask patterns, and edge
masks — plus the signature cache, the dense kernel's pad fix, and the
runtime's winner-ships dispatch race (exercised end-to-end via the
interpret arm). Compiled Mosaic execution is exercised on the real chip
by bench_pallas.py / tools/pallas_smoke.py / the driver."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.lattice.base import replicate
from lasp_tpu.lattice.gcounter import GCounter, GCounterSpec
from lasp_tpu.lattice.gset import GSet, GSetSpec
from lasp_tpu.lattice.orswot import ORSWOT, ORSWOTSpec
from lasp_tpu.mesh import gossip_round, random_regular
from lasp_tpu.mesh.gossip import (
    gossip_round_rows,
    gossip_round_rows_grouped,
)
from lasp_tpu.ops import PackedORSet, PackedORSetSpec
from lasp_tpu.ops.pallas_gossip import (
    flatten_plane,
    pallas_gossip_round,
    pallas_gossip_round_rows,
    pallas_gossip_round_rows_grouped,
    rows_kernel_cache_stats,
    rows_plan_of,
    tuned_rows_block,
    unflatten_plane,
)

N, K = 48, 3


def tree_eq(a, b) -> bool:
    same = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b,
    )
    return all(jax.tree_util.tree_leaves(same))


def seeded(kind: str, n: int = N):
    """A population with non-trivial per-row divergence for one codec."""
    r = jnp.arange(n)
    if kind == "gset":
        spec = GSetSpec(n_elems=16)
        st = replicate(GSet.new(spec), n)
        st = jax.vmap(lambda i, s: GSet.add(spec, s, i % 16))(r, st)
        return GSet, spec, st
    if kind == "gcounter":
        spec = GCounterSpec(n_actors=4)
        st = replicate(GCounter.new(spec), n)
        st = jax.vmap(
            lambda i, s: GCounter.increment(spec, s, i % 4)
        )(r, st)
        return GCounter, spec, st
    if kind == "orswot":
        spec = ORSWOTSpec(n_elems=8, n_actors=4)
        st = replicate(ORSWOT.new(spec), n)
        st = jax.vmap(lambda i, s: ORSWOT.add(spec, s, i % 8, i % 4))(r, st)
        # removals too, so dead dots exercise the survival rule
        st = jax.vmap(
            lambda i, s: jax.lax.cond(
                i % 7 == 0,
                lambda x: ORSWOT.remove(spec, x, i % 8),
                lambda x: x,
                s,
            )
        )(r, st)
        return ORSWOT, spec, st
    assert kind == "packed"
    spec = PackedORSetSpec(n_elems=16, n_actors=8, tokens_per_actor=8)
    st = replicate(PackedORSet.new(spec), n)
    st = jax.vmap(
        lambda i, s: PackedORSet.add(spec, s, i % 16, i % 8)
    )(r, st)
    st = jax.vmap(
        lambda i, s: jax.lax.cond(
            i % 5 == 0,
            lambda x: PackedORSet.remove(spec, x, i % 16),
            lambda x: x,
            s,
        )
    )(r, st)
    return PackedORSet, spec, st


CODECS = ("gset", "gcounter", "orswot", "packed")


@pytest.mark.parametrize("kind", CODECS)
@pytest.mark.parametrize("bucket", [5, 16, 33])
def test_rows_matches_xla_across_codecs_and_buckets(kind, bucket):
    """Single-population parity: states AND changed flags bit-identical
    to ``gossip_round_rows`` for every codec family the kernel plans
    (leafwise or/max, two-plane packed, vclock), at bucket sizes below/
    at/above the tuned grid block (non-pow2 buckets exercise the
    wrapper's slot-0 pad)."""
    codec, spec, st = seeded(kind)
    nbrs = jnp.asarray(random_regular(N, K, seed=3))
    rng = np.random.RandomState(bucket)
    rows = jnp.asarray(rng.randint(0, N, size=bucket))
    ref = gossip_round_rows(codec, spec, st, nbrs, rows)
    got = pallas_gossip_round_rows(
        codec, spec, st, nbrs, rows, interpret=True
    )
    assert tree_eq(ref, got)


@pytest.mark.parametrize("kind", ("gset", "orswot", "packed"))
def test_rows_matches_xla_under_edge_mask(kind):
    """Dead edges: the kernel SKIPS the dead neighbor's merge where the
    XLA round substitutes the row's own state — bit-identical because
    or/max are absorbing on the accumulated own state and the vclock
    merge is idempotent against any already-absorbed ancestor."""
    codec, spec, st = seeded(kind)
    nbrs = jnp.asarray(random_regular(N, K, seed=5))
    rng = np.random.RandomState(7)
    mask = jnp.asarray(rng.rand(N, K) > 0.4)
    rows = jnp.asarray(rng.randint(0, N, size=12))
    ref = gossip_round_rows(codec, spec, st, nbrs, rows, mask)
    got = pallas_gossip_round_rows(
        codec, spec, st, nbrs, rows, mask, interpret=True
    )
    assert tree_eq(ref, got)


@pytest.mark.parametrize("kind", CODECS)
def test_grouped_matches_xla_with_valid_masks(kind):
    """Grouped parity at G=3 with per-member valid patterns: dense, a
    pad tail, and a fully-invalid (quiescent) member that must ride
    through bit-unchanged with all-False changed flags — the PR5
    pad-slot contract the runtime's plan dispatch relies on."""
    codec, spec, st = seeded(kind)
    g, f = 3, 10
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([x, x[::-1], x]), st
    )
    nbrs = jnp.asarray(random_regular(N, K, seed=9))
    rng = np.random.RandomState(11)
    rows = jnp.asarray(rng.randint(0, N, size=(g, f)))
    valid = jnp.asarray(
        np.stack([
            np.ones(f, bool),                      # dense member
            np.arange(f) < 4,                      # pad tail
            np.zeros(f, bool),                     # quiescent member
        ])
    )
    ref = gossip_round_rows_grouped(
        codec, spec, stacked, nbrs, rows, valid
    )
    got = pallas_gossip_round_rows_grouped(
        codec, spec, stacked, nbrs, rows, valid, interpret=True
    )
    assert tree_eq(ref, got)
    assert not np.asarray(got[1])[2].any()  # quiescent member: no change


def test_grouped_matches_xla_with_edge_mask_and_duplicates():
    """Edge mask + duplicate row slots together (bucket padding names
    the same row twice): idempotent joins make duplicate scatter writes
    identical, masked or not."""
    codec, spec, st = seeded("gcounter")
    stacked = jax.tree_util.tree_map(lambda x: jnp.stack([x, x]), st)
    nbrs = jnp.asarray(random_regular(N, K, seed=13))
    rng = np.random.RandomState(17)
    mask = jnp.asarray(rng.rand(N, K) > 0.3)
    rows = jnp.asarray([[1, 1, 4, 9, 9, 9, 20, 33]] * 2)
    valid = jnp.asarray([[True] * 8, [True, True, True, False] + [False] * 4])
    ref = gossip_round_rows_grouped(
        codec, spec, stacked, nbrs, rows, valid, mask
    )
    got = pallas_gossip_round_rows_grouped(
        codec, spec, stacked, nbrs, rows, valid, mask, interpret=True
    )
    assert tree_eq(ref, got)


def test_changed_flag_matches_codec_equal_on_packed():
    """The kernel's CHANGED flag is a raw leaf-inequality reduction;
    the packed codecs' ``equal`` masks the removed plane with exists.
    They coincide because ``removed ⊆ exists`` is an invariant of every
    constructor / op / merge — asserted here across gossip rounds, so
    the kernel's shortcut can never silently diverge."""
    codec, spec, st = seeded("packed")
    nbrs = jnp.asarray(random_regular(N, K, seed=19))
    for _ in range(3):
        assert bool(jnp.all((st.removed & ~st.exists) == 0))
        st = gossip_round(codec, spec, st, nbrs)
    assert bool(jnp.all((st.removed & ~st.exists) == 0))


def test_signature_cache_shares_variants():
    """Same-signature dispatches reuse ONE compiled variant; a new
    bucket or codec builds a new one (the JITSPMM specialization
    granularity, keyed like ``plan.signature_of``)."""
    codec, spec, st = seeded("gset")
    nbrs = jnp.asarray(random_regular(N, K, seed=23))
    rows = jnp.arange(8)
    before = rows_kernel_cache_stats()
    pallas_gossip_round_rows(codec, spec, st, nbrs, rows, interpret=True)
    mid = rows_kernel_cache_stats()
    pallas_gossip_round_rows(
        codec, spec, st, nbrs, rows + 1, interpret=True
    )
    after = rows_kernel_cache_stats()
    assert mid["built"] >= before["built"]
    assert after["built"] == mid["built"]  # same signature: no rebuild
    assert after["hits"] == mid["hits"] + 1


def test_unplannable_codec_raises():
    """A codec with neither a leafwise join nor a (clock, dots) pair
    must refuse loudly — the dispatch race then keeps XLA."""
    from lasp_tpu.lattice import CrdtMap, MapSpec

    spec = MapSpec(
        fields=(("a", GSet, GSetSpec(n_elems=4)),), n_actors=2
    )
    st = replicate(CrdtMap.new(spec), 8)
    assert rows_plan_of(CrdtMap, spec, st) is None
    with pytest.raises(ValueError, match="no Pallas row-sparse plan"):
        pallas_gossip_round_rows(
            CrdtMap, spec, st,
            jnp.zeros((8, 2), jnp.int32), jnp.arange(4), interpret=True
        )


def test_tuned_block_is_pure_and_bounded():
    """The (block, bucket) tuning is a pure function of the signature
    (reproducible cache keys) and stays inside the VMEM budget."""
    assert tuned_rows_block(64, 256, 3) == tuned_rows_block(64, 256, 3)
    for rb in (4, 64, 4096, 1 << 20):
        for bucket in (1, 5, 16, 1024):
            for k in (1, 3, 16):
                fb = tuned_rows_block(rb, bucket, k)
                assert 1 <= fb <= 32
                assert fb & (fb - 1) == 0  # power of two


def test_dense_pad_fix_arbitrary_population():
    """Satellite 1: ``pallas_gossip_round`` pads the replica axis to the
    block boundary internally — populations not divisible by the block
    ship the dense Pallas arm instead of tripping an assert."""
    spec = PackedORSetSpec(n_elems=16, n_actors=8, tokens_per_actor=8)
    for n in (27, 33):
        codec, _, st = (PackedORSet, spec, None)
        r = jnp.arange(n)
        st = replicate(PackedORSet.new(spec), n)
        st = jax.vmap(
            lambda i, s: PackedORSet.add(spec, s, i % 16, i % 8)
        )(r, st)
        nbrs = jnp.asarray(random_regular(n, K, seed=29))
        ref = gossip_round(PackedORSet, spec, st, nbrs)
        fe, _ = flatten_plane(st.exists)
        fr, _ = flatten_plane(st.removed)
        oe, orr = pallas_gossip_round(fe, fr, nbrs, block=8, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(unflatten_plane(oe, st.exists.shape)),
            np.asarray(ref.exists),
        )
        np.testing.assert_array_equal(
            np.asarray(unflatten_plane(orr, st.removed.shape)),
            np.asarray(ref.removed),
        )


# -- the runtime's winner-ships dispatch race --------------------------------


def _race_runtime(plan: str, mode: str, n: int = 48):
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    store = Store(n_actors=4)
    ids = [
        store.declare(id="g0", type="lasp_gset", n_elems=16),
        store.declare(id="g1", type="lasp_gset", n_elems=16),
        store.declare(id="c0", type="riak_dt_gcounter", n_actors=4),
    ]
    rt = ReplicatedRuntime(
        store, Graph(store), n, random_regular(n, K, seed=31), plan=plan
    )
    rt.pallas_rows_mode = mode
    rng = np.random.RandomState(37)
    for v in ids:
        rows = rng.choice(n, 3, replace=False)
        if v == "c0":
            rt.update_batch(
                v, [(int(r), ("increment",), ("lane", int(r) % 4))
                    for r in rows]
            )
        else:
            rt.update_batch(
                v, [(int(r), ("add", f"e{int(r) % 8}"), f"a{int(r)}")
                    for r in rows]
            )
    return rt, ids


@pytest.mark.parametrize("plan", ["auto", "off"])
def test_runtime_race_interpret_parity_and_records(plan):
    """End-to-end dispatch race on CPU via the interpret arm: the raced
    runtime's fixed point is bit-identical to the XLA-only runtime,
    both arms' timings land in ``impl_block_seconds`` with a winner,
    and the emulator arm never ships (parity-check-only — the CPU
    degradation contract)."""
    rt_ref, ids = _race_runtime(plan, "off")
    while rt_ref.frontier_step():
        pass
    ref = {v: jax.tree_util.tree_map(np.asarray, rt_ref.states[v])
           for v in ids}
    assert rt_ref.impl_block_seconds == {}  # no race under "off"

    rt, ids = _race_runtime(plan, "interpret")
    while rt.frontier_step():
        pass
    got = {v: jax.tree_util.tree_map(np.asarray, rt.states[v])
           for v in ids}
    assert tree_eq(ref, got)
    assert rt.impl_block_seconds, "race recorded nothing"
    for label, rec in rt.impl_block_seconds.items():
        assert "xla" in rec and "winner" in rec, (label, rec)
        assert "pallas_rows" in rec or "pallas_rows_error" in rec
        # the interpret emulator must never ship a dispatch
        assert rec["winner"] == "xla"


def test_runtime_race_mode_validation():
    rt, _ids = _race_runtime("auto", "banana")
    with pytest.raises(ValueError, match="pallas_rows_mode"):
        rt.frontier_step()
