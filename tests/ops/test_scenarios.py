"""BASELINE eval-config scenarios at CI scale (the full populations run on
the TPU via ``cli scenario`` / the driver bench). Every scenario embeds its
own correctness cross-check against reference semantics; these tests assert
the checks hold at small populations on the CPU mesh."""

import pytest

from lasp_tpu.bench_scenarios import (
    SCENARIOS,
    adcounter_6,
    adcounter_10m,
    gset_1k,
    orset_100k,
    pipeline_1m,
)


def test_scenario_registry_complete():
    assert set(SCENARIOS) == {
        "adcounter_6",
        "gset_1k",
        "orset_100k",
        "pipeline_1m",
        "adcounter_10m",
        "packed_vs_dense",
        "bridge_throughput",
        "partitioned_gossip",
        "mesh_scale",
        "frontier_sparse",
        "many_vars",
        "ingest_storm",
        "dataflow_chain",
        "quorum_kv",
        "chaos_heal",
        "serve_load",
        "aae_scrub",
        "elastic_rebalance",
    }


def test_cli_scenario_choices_in_sync():
    """cli.py keeps a literal choices list (importing the registry there
    would pull jax into every CLI start); it must match SCENARIOS."""
    import re

    src = open("lasp_tpu/cli.py").read()
    block = re.search(
        r'scen\.add_argument\(\s*"name",\s*choices=\[(.*?)\]', src, re.S
    ).group(1)
    choices = set(re.findall(r'"([a-z0-9_]+)"', block))
    assert choices == set(SCENARIOS)


def test_cli_import_stays_light():
    """Importing the CLI (or the bare package) must not load the heavy
    submodules — lasp_tpu/__init__ is lazy (PEP 562) so lightweight
    consumers (--help, the bridge parent, bench.py's parent) pay no
    framework import cost. jax itself cannot be asserted absent here:
    this machine's sitecustomize imports it in every interpreter."""
    import subprocess
    import sys

    probe = subprocess.run(
        [sys.executable, "-c",
         "import sys; import lasp_tpu.cli; "
         "heavy = [m for m in sys.modules if m.startswith('lasp_tpu.') "
         "and m not in ('lasp_tpu.cli',)]; "
         "sys.exit(1 if heavy else 0)"],
        capture_output=True,
    )
    assert probe.returncode == 0, probe.stderr.decode()[-500:]


def test_packed_vs_dense_small():
    """CI-scale packed-vs-dense comparison: both modes produce the same
    dataflow value and the record carries per-mode round timings."""
    from lasp_tpu.bench_scenarios import packed_vs_dense

    out = packed_vs_dense(n_replicas=256, blocks=2, block=4)
    assert out["check"] == "dense==packed value"
    assert set(out["per_round_s"]) == {"dense", "packed"}
    assert out["per_round_s"]["dense"] > 0 and out["per_round_s"]["packed"] > 0
    assert out["rounds_timed"] == 8


def test_adcounter_6():
    out = adcounter_6()
    assert sum(out["totals"]) == 100
    assert out["rounds"] >= 1


def test_gset_1k():
    out = gset_1k()
    assert out["union_size"] >= out["intersection_size"]
    assert out["check"] == "matches-global-reference"


def test_orset_small():
    out = orset_100k(n_replicas=2048)
    assert out["check"] == "converged+all-live"
    assert out["merges_per_sec"] > 0


def test_pipeline_small():
    out = pipeline_1m(n_replicas=4096)
    assert out["check"] == "fold==reference"
    assert out["folded_count"] > 0


def test_adcounter_small():
    out = adcounter_10m(n_replicas=8192, threshold=5)
    assert out["check"] == "live==(<threshold), active==matching-pairs"
    assert out["engine"] == "Graph+ReplicatedRuntime(packed)+trigger"
    # ads 0..9 have L[a] = (a % 8) + 1 active view lanes; with threshold 5
    # the ads whose totals stay under 5 (L in {1,2,3,4}) survive: ads
    # 0,1,2,3 and 8,9 -> 6 live ads, each with its matching contract pair
    assert out["live_ads"] == 6
    assert out["active_pairs"] == 6
    assert out["ad_totals"] == [1, 2, 3, 4, 5, 6, 7, 8, 1, 2]


def test_many_vars_small():
    from lasp_tpu.bench_scenarios import many_vars

    out = many_vars(n_replicas=48, n_vars=12, reps=1)
    # the megabatch contract is asserted INSIDE the scenario
    # (bit-identical states + residual sequences across arms); here we
    # pin the artifact shape the driver embeds
    assert out["check"] == (
        "bit-identical states + residual sequences across arms"
    )
    assert set(out["impl_block_seconds"]) == {
        "per_var", "planned", "pallas_rows"
    }
    assert out["plan"]["groups"] == 3 and out["plan"]["vars"] == 12
    assert out["rounds"] >= 1 and out["plan_speedup"] > 0
    _assert_pallas_arm(out)


def test_ingest_storm_small():
    """The plan-grouped ingest A/B at CI shape: bit-identical final
    states and the one-dispatch-per-active-group-per-cycle contract are
    asserted INSIDE the scenario; here we pin the artifact shape —
    per-arm timings, non-null rooflines against the shared ingest_apply
    numerator, the dispatch-count record, and the _normalize_ops
    allocation check (the copy-on-write micro-fix)."""
    from lasp_tpu.bench_scenarios import ingest_storm

    out = ingest_storm(n_replicas=32, n_vars=15, cycles=3,
                       ops_per_cycle=150, reps=1, gate=None)
    assert set(out["impl_block_seconds"]) == {"per_var", "grouped"}
    assert out["dispatches"]["got"] == out["dispatches"]["expected"] > 0
    assert out["impl_roofline"]["grouped"]["roofline_frac"] is not None
    assert out["impl_roofline"]["per_var"]["roofline_frac"] is not None
    assert out["normalize_alloc_bytes"] < 65536
    assert out["ingest_speedup"] > 0
    assert out["check"].startswith("bit-identical final states")


def _assert_pallas_arm(out):
    """The ISSUE-7 acceptance shape: the Pallas row-sparse arm records a
    timing AND a non-null per-arm roofline on EVERY backend; on CPU the
    parity probe is interpret-mode-only (its own key, never competing
    with the measured arms) and says so."""
    arm = out["pallas_rows"]
    assert arm["seconds"] > 0
    assert arm["achieved_GBps"] is not None
    assert arm["roofline_frac"] is not None
    assert out["impl_roofline"]["pallas_rows"]["roofline_frac"] is not None
    assert arm["check"] == "bit-identical to gossip_round_rows"
    import jax

    if jax.devices()[0].platform == "cpu":
        assert arm["mode"] == "interpret-parity"


def test_frontier_sparse_small_pallas_arm():
    """frontier_sparse at CI shape embeds the Pallas row-sparse arm
    (timing + non-null roofline) next to the dense/frontier arms."""
    from lasp_tpu.bench_scenarios import frontier_sparse

    out = frontier_sparse(n_replicas=256, n_vars=4, n_elems=32)
    assert set(out["impl_block_seconds"]) >= {
        "dense", "frontier", "pallas_rows"
    }
    _assert_pallas_arm(out)


def test_dataflow_chain_small():
    """CI-scale dataflow-fusion A/B: the fusion contract is asserted
    INSIDE the scenario (bit-identical states + round counts across
    schedulers); here we pin the artifact shape the driver embeds —
    both arms timed, per-arm roofline non-null on every backend."""
    from lasp_tpu.bench_scenarios import dataflow_chain

    out = dataflow_chain(n_chains=6, depth=2, reps=1)
    assert out["check"] == (
        "bit-identical states + round counts across schedulers"
    )
    assert out["n_edges"] >= 12 and out["rounds"] >= 2
    assert set(out["impl_block_seconds"]) == {"per_edge", "fused"}
    assert out["impl_block_seconds"]["per_edge"] > 0
    assert out["impl_block_seconds"]["fused"] > 0
    assert out["fused_speedup"] > 0
    # the megakernel actually stacked same-signature edges
    assert out["plan"]["groups"] < out["n_edges"]
    assert out["plan"]["edges_stacked"] >= 2
    for arm in ("per_edge", "fused"):
        roof = out["impl_roofline"][arm]
        assert roof["achieved_GBps"] is not None
        assert roof["roofline_frac"] is not None


def test_chaos_heal_small():
    from lasp_tpu.bench_scenarios import chaos_heal

    out = chaos_heal(n_replicas=96, fault_rounds=6)
    assert out["check"] == (
        "post-heal state bit-identical to fault-free fixed point"
    )
    assert out["healed"] and out["restores"] == out["crashes"] == 2
    assert out["rounds_to_heal"] >= 0 and out["degraded_reads"] > 0


def test_quorum_kv_small():
    """The quorum_kv artifact shape: per-preset latency percentiles,
    staleness-vs-converged distance, repair traffic, and the asserted
    no-acked-write-lost invariant — on every backend."""
    from lasp_tpu.bench_scenarios import quorum_kv
    from lasp_tpu.chaos import PRESETS

    out = quorum_kv(n_replicas=16, client_rounds=3,
                    puts_per_round=2, gets_per_round=2)
    assert set(out["presets"]) == set(PRESETS)
    assert out["n_r_w"] == [3, 2, 2]
    for preset, rep in out["presets"].items():
        assert rep["no_write_lost"], preset
        assert rep["completed"] + rep["failed"] == rep["requests"], preset
        for key in ("get_p50_rounds", "get_p99_rounds",
                    "put_p50_rounds", "put_p99_rounds"):
            assert rep[key] is None or rep[key] >= 1, (preset, key)
        assert rep["staleness_mean"] is None or rep["staleness_mean"] >= 0
        assert rep["repair_wire_bytes"] >= 0
    # rolling-crash restores replicas: the hinted-handoff path ran
    assert out["presets"]["rolling-crash"]["hint_replays"] > 0


def test_serve_load_small():
    """The serve_load artifact shape: offered/admitted/completed rates,
    the typed shed breakdown, queue high-water marks, ladder
    transitions, per-class latency percentiles, and the two in-scenario
    assertions (no-acked-write-lost + threshold fan-out parity) — on
    every backend."""
    from lasp_tpu.bench_scenarios import serve_load

    out = serve_load(n_replicas=16, n_clients=300, ticks=10,
                     arrivals_per_tick=60, seed_watches=80,
                     parity_thresholds=1024)
    assert out["scenario"] == "serve_load_16"
    assert out["no_write_lost"] is True
    assert out["threshold_parity"]["parity"] is True
    assert out["chaos"]["healed"]
    for key in ("offered_per_tick", "admitted_per_tick",
                "completed_per_tick", "admit_frac", "complete_frac"):
        assert out["rates"][key] >= 0
    assert set(out["queue_high_water"]) == {"write", "read", "watch"}
    assert out["latency_ticks"]["write"]["p99"] is not None
    assert out["max_inflight"] >= 80  # the standing-watch floor
    # the grouped-ingest rate line (writes landed through mesh.ingest:
    # one dispatch per codec group per cycle)
    assert out["ingest"]["dispatches"] > 0
    assert out["ingest"]["grouped_ops"] > 0
    assert out["ingest"]["ops_per_dispatch"] > 0
    # the shed breakdown is typed kind:reason pairs (may be empty at
    # this scale); accounting never loses a request
    offered = sum(out["offered"].values())
    terminal = (
        sum(out["completed"].values()) + sum(out["errors"].values())
        + sum(out["expired"].values()) + sum(out["shed"].values())
    )
    assert offered == terminal + out["watch_parked_final"]


def test_aae_scrub_small():
    """The aae_scrub artifact shape: per-preset detection latency,
    repair-vs-resync traffic, incremental-vs-full rehash cost — with
    the corruption drill invariant asserted in-scenario for EVERY
    nemesis preset (CorruptRows overlays on the crash/partition class,
    the corruption presets natively)."""
    from lasp_tpu.bench_scenarios import aae_scrub
    from lasp_tpu.chaos import CORRUPTION_PRESETS, PRESETS

    out = aae_scrub(n_replicas=16, rounds=6)
    assert set(out["presets"]) == set(PRESETS) | set(CORRUPTION_PRESETS)
    for preset, rep in out["presets"].items():
        assert rep["detected_and_repaired"], preset
        assert rep["injected"] >= 1, preset
        assert rep["detection_latency_rounds_max"] <= 1, preset
        assert rep["repair_frac_of_resync"] < 1.0, preset
    rh = out["rehash"]
    assert rh["incremental_seconds"] > 0 and rh["full_seconds"] > 0


def test_elastic_rebalance_small():
    """The elastic_rebalance artifact shape: staged-vs-legacy wire
    figures, settle rounds, per-cycle cap evidence, during/after serve
    latency — with the bit-equality, cap, and wire gates asserted
    in-scenario."""
    from lasp_tpu.bench_scenarios import elastic_rebalance

    out = elastic_rebalance(n_replicas=16, grow_to=24, waves_during=4,
                            waves_after=3, per_cycle=4)
    assert out["scenario"] == "elastic_rebalance_16_24"
    assert out["epoch"] == 2  # one grow + one leave, each fenced once
    g = out["grow"]
    assert g["max_cycle_transfers"] <= out["per_cycle_cap"]
    assert g["pending_high_water"] <= 24 - 16
    assert g["transfer_bytes"] > 0
    assert g["transfer_bytes"] <= g["full_resync_bytes"]
    assert g["full_resync_rounds"] >= 1
    assert g["settle_rounds"] >= 1
    assert out["leave"]["transfer_bytes"] > 0
    lat = out["serve_tick_ms"]
    assert lat["during_p99"] is not None and lat["after_p99"] is not None
