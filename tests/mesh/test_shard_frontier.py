"""Sharded frontier gossip on the partitioned mesh (round 13).

Four claims, each pinned on the 8-device emulated mesh:

1. The SPARSE boundary exchange (dirty cut rows only, halo-backed,
   interior joins overlapping the collective) is bit-identical to the
   dense partitioned round AND the unsharded dense reference — states,
   residual sequences, round counts — across wire modes, codecs, and
   grouped/singleton dispatch.
2. The hierarchical ``converge_on_device`` (per-shard residual
   partials + a psum tree every ``sync_every`` rounds) returns EXACT
   round counts matching the host-driven loop, in one dispatch.
3. The halo lifecycle is sound: every path that changes rows without
   shipping them (opaque converge, dense-crossover arm, dense steps)
   forces a full-cut resync before the next sparse join.
4. ``run_to_convergence(mode="auto")`` never degrades silently: the
   partitioned mesh takes the frontier path, and shapes that DO need
   the dense sweep increment
   ``gossip_frontier_dense_fallbacks_total{reason=}``.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import locality_order, scale_free
from lasp_tpu.store import Store


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("replicas",))


def _topo(n, seed=3):
    return locality_order(scale_free(n, 3, seed=seed))[1]


def _build(n=96, seed=3, codec="gset", n_vars=1, packed=False):
    nn = _topo(n, seed)
    store = Store(n_actors=8)
    ids = []
    for i in range(n_vars):
        if codec == "gset":
            ids.append(store.declare(id=f"v{i}", type="lasp_gset",
                                     n_elems=16))
        elif codec == "orswot":
            ids.append(store.declare(id=f"v{i}", type="riak_dt_orswot",
                                     n_elems=8, n_actors=4))
        else:
            ids.append(store.declare(id=f"v{i}", type="lasp_orset",
                                     n_elems=8))
    rt = ReplicatedRuntime(store, Graph(store), n, nn, packed=packed)
    for i, v in enumerate(ids):
        rt.update_at((7 * i + 1) % n, v, ("add", "a"), f"w{i}")
        rt.update_at((n // 2 + i) % n, v, ("add", "b"), f"x{i}")
    return rt, ids


def _states_equal(a, b) -> bool:
    same = jax.tree_util.tree_map(
        lambda x, y: bool(jnp.array_equal(x, y)), a, b
    )
    return all(jax.tree_util.tree_leaves(same))


@pytest.mark.parametrize("mode,codec,packed", [
    ("gather", "gset", False),
    ("alltoall", "orswot", False),
    ("alltoall", "orset", True),
])
def test_sparse_frontier_bit_identical_per_round(mode, codec, packed):
    rt_f, ids = _build(codec=codec, packed=packed)
    rt_d, _ = _build(codec=codec, packed=packed)
    ref, _ = _build(codec=codec, packed=packed)
    rt_f.shard(_mesh(), axis="replicas", partition=True,
               partition_mode=mode)
    rt_d.shard(_mesh(), axis="replicas", partition=True,
               partition_mode=mode)
    for rnd in range(64):
        rf, rd, rr = rt_f.frontier_step(), rt_d.step(), ref.step()
        assert rf == rd == rr, (rnd, rf, rd, rr)
        for v in ids:
            assert _states_equal(rt_f.states[v], rt_d.states[v]), (rnd, v)
            assert _states_equal(rt_f.states[v], ref.states[v]), (rnd, v)
        if rd == 0:
            break
    assert rd == 0
    assert rt_f.divergence(ids[0]) == 0


def test_grouped_and_singleton_members_match_plan_off():
    # 2 same-spec gsets (one plan group) + 1 orswot (singleton): the
    # grouped partitioned dispatch is bit-identical to plan="off"
    # (every member a G=1 singleton) and to the dense partitioned round
    def mixed(plan):
        nn = _topo(96)
        store = Store(n_actors=8)
        a = store.declare(id="a", type="lasp_gset", n_elems=16)
        b = store.declare(id="b", type="lasp_gset", n_elems=16)
        c = store.declare(id="c", type="riak_dt_orswot", n_elems=8,
                          n_actors=4)
        rt = ReplicatedRuntime(store, Graph(store), 96, nn, plan=plan)
        rt.update_at(1, a, ("add", "p"), "w0")
        rt.update_at(50, b, ("add", "q"), "w1")
        rt.update_at(9, c, ("add", "r"), "w2")
        return rt, (a, b, c)

    rt_g, ids = mixed("auto")
    rt_s, _ = mixed("off")
    rt_d, _ = mixed("auto")
    for rt in (rt_g, rt_s, rt_d):
        rt.shard(_mesh(), axis="replicas", partition=True)
    plan = rt_g._ensure_plan()
    assert any(len(g.var_ids) > 1 for g in plan.groups)
    for rnd in range(64):
        rg, rs, rd = (rt_g.frontier_step(), rt_s.frontier_step(),
                      rt_d.step())
        assert rg == rs == rd, (rnd, rg, rs, rd)
        for v in ids:
            assert _states_equal(rt_g.states[v], rt_s.states[v]), (rnd, v)
            assert _states_equal(rt_g.states[v], rt_d.states[v]), (rnd, v)
        if rd == 0:
            break
    assert rd == 0


def test_run_to_convergence_auto_takes_frontier_path():
    from lasp_tpu.telemetry import registry as _reg

    rt, ids = _build()
    twin, _ = _build()
    rt.shard(_mesh(), axis="replicas", partition=True)
    reg = _reg.get_registry()
    frontier_rounds = reg.counter(
        "gossip_frontier_rounds_total",
        help="frontier-scheduled gossip rounds executed",
    )
    fall = reg.counter(
        "gossip_frontier_dense_fallbacks_total",
        help="dense rounds/runs taken where frontier scheduling was "
             "requested, by reason",
        reason="partitioned",
    )
    before_rounds, before_fall = frontier_rounds.value, fall.value
    r_auto = rt.run_to_convergence(mode="auto")
    r_dense = 0
    while True:
        r_dense += 1
        if twin.step() == 0:
            break
    assert r_auto == r_dense
    # the partitioned mesh runs the frontier path natively now — no
    # silent (or loud) dense degrade
    assert frontier_rounds.value > before_rounds
    assert fall.value == before_fall
    for v in ids:
        assert _states_equal(rt.states[v], twin.states[v])


def test_auto_fallback_is_observable():
    """The r13 bugfix: auto mode degrading to dense must increment the
    labeled fallback counter — here via the one remaining reason
    (dataflow edges), on both partitioned and unpartitioned runtimes."""
    from lasp_tpu.telemetry import registry as _reg

    def with_edges(shard):
        nn = _topo(96)
        store = Store(n_actors=8)
        s = store.declare(id="s", type="lasp_orset", n_elems=16)
        graph = Graph(store)
        graph.map(s, lambda x: f"m:{x}", dst="out", dst_elems=32)
        rt = ReplicatedRuntime(store, graph, 96, nn)
        rt.update_at(0, s, ("add", "a"), "w0")
        if shard:
            rt.shard(_mesh(), axis="replicas", partition=True)
        return rt

    fall = _reg.get_registry().counter(
        "gossip_frontier_dense_fallbacks_total",
        help="dense rounds/runs taken where frontier scheduling was "
             "requested, by reason",
        reason="dataflow",
    )
    for shard in (False, True):
        rt = with_edges(shard)
        before = fall.value
        rt.run_to_convergence(mode="auto", max_rounds=64)
        assert fall.value == before + 1, f"shard={shard}"
        with pytest.raises(RuntimeError, match="frontier gossip"):
            rt.run_to_convergence(mode="frontier", max_rounds=4)


@pytest.mark.parametrize("mode,window", [
    ("gather", 1), ("gather", 8), ("alltoall", 4),
])
def test_hier_converge_exact_rounds_one_dispatch(mode, window):
    rt, ids = _build(codec="orswot")
    host, _ = _build(codec="orswot")
    rt.shard(_mesh(), axis="replicas", partition=True,
             partition_mode=mode)
    host_rounds = 0
    while True:
        host_rounds += 1
        if host.step() == 0:
            break
    traces_before = len(rt.trace.rounds)
    r = rt.converge_on_device(sync_every=window)
    assert r == host_rounds
    # ONE dispatch = one trace row: zero per-round host syncs
    assert len(rt.trace.rounds) == traces_before + 1
    for v in ids:
        assert _states_equal(rt.states[v], host.states[v])
    # already-converged population bills exactly the one probe round
    assert rt.converge_on_device(sync_every=window) == 1


def test_hier_converge_budget_and_resume():
    rt, ids = _build()
    rt.shard(_mesh(), axis="replicas", partition=True)
    host, _ = _build()
    host_rounds = 0
    while True:
        host_rounds += 1
        if host.step() == 0:
            break
    signed = rt.converge_on_device(max_rounds=2, strict=False,
                                   sync_every=4)
    assert signed == -2
    with pytest.raises(RuntimeError, match="no convergence within"):
        rt2, _ = _build()
        rt2.shard(_mesh(), axis="replicas", partition=True)
        rt2.converge_on_device(max_rounds=2, sync_every=4)
    # resuming completes with the EXACT remaining count (the executed
    # budget rounds were real rounds)
    assert rt.converge_on_device(sync_every=4) == host_rounds - 2
    for v in ids:
        assert _states_equal(rt.states[v], host.states[v])


def test_halo_survives_converge_then_writes():
    """Halo-staleness regression: an opaque converge changes cut rows
    the sparse exchange never shipped — the next frontier rounds must
    resync (halo drop) and stay bit-identical to a dense twin."""
    rt, ids = _build(n_vars=2)
    twin, _ = _build(n_vars=2)
    rt.shard(_mesh(), axis="replicas", partition=True)
    twin.shard(_mesh(), axis="replicas", partition=True)
    # converge both (rt hierarchically, twin by dense steps)
    rt.converge_on_device()
    while twin.step():
        pass
    assert not rt._part_halo  # opaque block dropped every halo
    for i, v in enumerate(ids):
        rt.update_at(11 + i, v, ("add", "late"), f"l{i}")
        twin.update_at(11 + i, v, ("add", "late"), f"l{i}")
    for rnd in range(64):
        rf, rd = rt.frontier_step(), twin.step()
        assert rf == rd, rnd
        for v in ids:
            assert _states_equal(rt.states[v], twin.states[v]), (rnd, v)
        if rd == 0:
            break
    assert rd == 0


def test_halo_survives_dense_crossover_interleaving():
    """A member that takes the dense-crossover arm retires dirty rows
    WITHOUT shipping them — its halo must resync before its next
    sparse round (the pop-on-dense-arm rule). Forcing a tiny crossover
    makes rounds alternate arms as the epidemic grows and collapses."""
    rt, ids = _build(n_vars=1)
    twin, _ = _build(n_vars=1)
    rt.shard(_mesh(), axis="replicas", partition=True)
    twin.shard(_mesh(), axis="replicas", partition=True)
    rt.frontier_crossover = 0.05  # almost everything goes dense-arm
    for rnd in range(64):
        rf, rd = rt.frontier_step(), twin.step()
        assert rf == rd, rnd
        assert _states_equal(rt.states[ids[0]], twin.states[ids[0]]), rnd
        if rd == 0:
            break
    assert rd == 0
    # a fresh write wave rides sparse again (crossover back up), with
    # the resync keeping it exact
    rt.frontier_crossover = 0.25
    rt.update_at(2, ids[0], ("add", "z"), "zz")
    twin.update_at(2, ids[0], ("add", "z"), "zz")
    for rnd in range(64):
        rf, rd = rt.frontier_step(), twin.step()
        assert rf == rd, rnd
        assert _states_equal(rt.states[ids[0]], twin.states[ids[0]]), rnd
        if rd == 0:
            break
    assert rd == 0


def test_compaction_drops_halo():
    """Review repro (confirmed): compact_orset reindexes every row
    WITHOUT frontier knowledge — a live boundary halo still holds
    old-element-order rows, and the next sparse rounds would scatter
    them into the reindexed population (silently resurrecting the
    reclaimed slots, bit-divergent from the unsharded reference while
    internal divergence stays 0). The fix drops the var's halo at
    compaction; this pins bit-identity through the full sequence."""
    def build():
        nn = _topo(96)
        store = Store(n_actors=8)
        s = store.declare(id="s", type="lasp_orset", n_elems=8)
        rt = ReplicatedRuntime(store, Graph(store), 96, nn)
        rt.update_at(1, s, ("add", "keep"), "w0")
        rt.update_at(50, s, ("add", "drop"), "w1")
        return rt, s

    rt, s = build()
    ref, _ = build()
    rt.shard(_mesh(), axis="replicas", partition=True)
    rt.frontier_crossover = 1.0  # sparse-only: halos stay live
    for r in (rt, ref):
        seq = r.frontier_step if r is rt else r.step
        while seq():
            pass
        r.update_at(7, s, ("remove", "drop"), "w1")
        while seq():
            pass
    assert rt._part_halo  # a live (about to be stale) halo
    assert rt.compact_orset(s) == ref.compact_orset(s) > 0
    assert s not in rt._part_halo  # the fix: compaction dropped it
    # post-compaction writes ride the sparse exchange bit-identically
    hot = int(rt._partition["plan"]["cut_rows"][0])
    for r in (rt, ref):
        r.update_at(hot, s, ("add", "after"), "w2")
    for rnd in range(64):
        rf, rd = rt.frontier_step(), ref.step()
        assert rf == rd, rnd
        assert _states_equal(rt.states[s], ref.states[s]), rnd
        if rd == 0:
            break
    assert rd == 0
    assert rt.coverage_value(s) == frozenset({"keep", "after"})


def test_exchange_accounting_and_probe():
    """The sparse exchange's wire accounting: steady-state rounds at
    tiny dirty fractions move strictly less than the dense cut plane,
    and the monitor probe surfaces the cumulative ledger."""
    from lasp_tpu.telemetry.convergence import get_monitor

    rt, ids = _build(n=256, n_vars=1)
    rt.shard(_mesh(), axis="replicas", partition=True)
    # keep every round sparse (no dense-arm halo pops) so the halo
    # persists past the warm cycle and the measured round is the
    # steady-state shape, not the one-off full-cut resync
    rt.frontier_crossover = 1.0
    # warm cycle (halo resync + compiles)
    while rt.frontier_step():
        pass
    assert rt._part_halo  # the halo survived the sparse-only cycle
    # write at a CUT row (referenced by definition, so the round is
    # never an empty-reach skip)
    hot = int(rt._partition["plan"]["cut_rows"][0])
    rt.update_at(hot, ids[0], ("add", "s2"), "s2")
    xb0 = rt.part_exchange_bytes_total
    db0 = rt.part_dense_plane_bytes_total
    rt.frontier_step()  # one-row dirty set: payload << plane
    payload = rt.part_exchange_bytes_total - xb0
    plane = rt.part_dense_plane_bytes_total - db0
    assert 0 < payload < plane
    assert rt.part_exchange_rows_last > 0
    try:
        probe = get_monitor().probe(rt)
        xch = probe["shard_exchange"]
        assert xch["payload_bytes_total"] == rt.part_exchange_bytes_total
        assert xch["interior_overlap_frac"] is not None
        while rt.frontier_step():
            pass
        assert rt.divergence(ids[0]) == 0
    finally:
        # the probe registered 8-shard lag gauges in the GLOBAL
        # registry; detach them so series-census tests downstream
        # (tests/telemetry/test_convergence.py) see a clean slate
        import lasp_tpu.telemetry as telemetry

        telemetry.reset()


def test_sparse_exchange_hlo_is_payload_sized():
    """The compiled sparse round's collectives move the bucket-padded
    PAYLOAD, never the population and never the full cut plane."""
    from lasp_tpu.mesh.shard_gossip import (
        make_halo,
        partitioned_frontier_round_fn,
        sparse_exchange_tables,
    )

    n = 256
    rt, ids = _build(n=n, n_vars=1)
    rt.shard(_mesh(), axis="replicas", partition=True,
             partition_mode="gather")
    part = rt._partition
    pplan = part["plan"]
    v = ids[0]
    halo = make_halo(rt.states[v], pplan, "gather", part["mesh"],
                     axis="replicas")
    dirty = np.zeros(n, dtype=bool)
    dirty[pplan["cut_rows"][:3]] = True  # 3 dirty cut rows
    tabs = sparse_exchange_tables(pplan, "gather", dirty)
    assert tabs["bucket"] < pplan["m"] or pplan["m"] <= 8
    f_i = f_b = 8
    rows_i = np.zeros((8, 1, f_i), np.int32)
    valid_i = np.zeros((8, 1, f_i), bool)
    rows_b = np.zeros((8, 1, f_b), np.int32)
    valid_b = np.zeros((8, 1, f_b), bool)
    valid_i[0, 0, 0] = valid_b[1, 0, 0] = True
    fn = partitioned_frontier_round_fn(
        *rt._mesh_meta(v), part["mesh"], pplan, axis="replicas",
        mode="gather", n_g=1, donate=False,
    )
    args = (
        (rt.states[v],), (halo,),
        jnp.asarray(tabs["pay_slot"]), jnp.asarray(tabs["pay_pos"]),
        jnp.asarray(rows_i), jnp.asarray(valid_i),
        jnp.asarray(rows_b), jnp.asarray(valid_b), part["idx"],
    )
    hlo = fn.lower(*args).compile().as_text()
    ags = re.findall(r"= (\w+)\[([\d,]*)\][^=]*all-gather\(", hlo)
    assert ags, "sparse exchange must lower to an all-gather"
    bucket = tabs["bucket"]
    for _dt, dims in ags:
        lead = [int(d) for d in dims.split(",") if d]
        # payload all-gathers are [S, G, D, ...]: never the population,
        # never the full cut plane
        assert n not in lead, dims
        assert 8 * bucket >= lead[0] * (lead[1] if len(lead) > 1 else 1), dims
    # and it runs: the dirty rows' exchange is live
    outs, halos, ch_i, ch_b = fn(*args)
    assert np.asarray(ch_i).shape == (8, 1, f_i)


def test_resize_and_reshard_drop_halos_and_keep_serving():
    from lasp_tpu.mesh.topology import random_regular

    rt, ids = _build(n=96, n_vars=1)
    rt.shard(_mesh(), axis="replicas", partition=True)
    rt.frontier_crossover = 1.0  # sparse-only: halos persist
    while rt.frontier_step():
        pass
    assert rt._part_halo  # live halos
    rt.resize(104, random_regular(104, 3, seed=9))
    assert not rt._part_halo  # invalidation dropped them with the plan
    assert rt._partition is None
    rt.run_to_convergence(mode="auto", max_rounds=128)
    assert rt.divergence(ids[0]) == 0


def test_mesh_scale_scenario_small():
    """The measured-artifact producer at CI shape: wire gate holds,
    hierarchical converge matches the host loop, roofline_frac
    non-null, per-shard accounting present."""
    from lasp_tpu.bench_scenarios import mesh_scale

    out = mesh_scale(n_replicas=1 << 11, cycles=1)
    assert out["cut_rows_sparse_bytes"] > 0
    assert out["cut_rows_dense_bytes"] > 0
    assert out["wire_cut_at_5pct_dirty"] >= out["wire_gate"]
    assert len(out["per_shard"]["per_shard_cut_bytes"]) == out["n_shards"]
    assert out["hier_converge"]["rounds"] == out["hier_converge"][
        "host_loop_rounds"
    ]
    assert out["impl_roofline"]["shard_exchange"]["roofline_frac"] is not None
    assert 0.0 <= out["interior_overlap_frac"] <= 1.0


@pytest.mark.slow
def test_mesh_scale_1m_slow():
    """ROADMAP open item 1's acceptance shape: >= 1M replicas across
    the 8-device mesh, sparse exchange >= 5x under the dense cut plane
    at <= 5% dirty, non-null roofline accounting."""
    from lasp_tpu.bench_scenarios import mesh_scale

    out = mesh_scale(n_replicas=1 << 20, cycles=1, write_frac=0.001)
    assert out["wire_cut_at_5pct_dirty"] >= 5.0
    assert out["impl_roofline"]["shard_exchange"]["roofline_frac"] is not None
    assert out["cut_rows_sparse_bytes"] < out["cut_rows_dense_bytes"]
