"""Batched client ops + recompile behavior of ReplicatedRuntime.

VERDICT/ADVICE round-1 items: client writes must not re-jit the step
(edge tables are traced args now), and realistic workloads need a
vectorized update path instead of per-op host round-trips.
"""

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store
from lasp_tpu.utils.interning import CapacityError


def _runtime(n=4, **declare):
    store = Store(n_actors=8)
    graph = Graph(store)
    store.declare(id="s", **declare)
    return store, graph, ReplicatedRuntime(store, graph, n, ring(n, 1))


def test_update_at_does_not_recompile_step():
    store = Store(n_actors=8)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=8)
    b = store.declare(id="b", type="lasp_orset", n_elems=8)
    graph.union(a, b, dst="u")
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 1))
    rt.update_at(0, a, ("add", "x"), "w0")
    rt.step()
    assert rt._step is not None
    compiled = rt._step
    sizes = compiled._cache_size()
    # interner growth via more client writes must NOT invalidate or retrace
    for i in range(5):
        rt.update_at(i % 4, a, ("add", f"y{i}"), "w0")
        rt.update_at(i % 4, b, ("add", f"z{i}"), "w1")
        rt.step()
    assert rt._step is compiled
    assert compiled._cache_size() == sizes == 1
    rt.run_to_convergence()
    assert rt.coverage_value("u") == {"x", "z0", "z1", "z2", "z3", "z4"} | {
        f"y{i}" for i in range(5)
    }


@pytest.mark.parametrize("verb", ["add", "add_all"])
def test_update_batch_orset_matches_sequential(verb):
    _, _, rt1 = _runtime(type="lasp_orset", n_elems=8)
    _, _, rt2 = _runtime(type="lasp_orset", n_elems=8)
    ops = []
    for i in range(6):
        if verb == "add":
            ops.append((i % 4, ("add", f"e{i % 3}"), f"w{i % 2}"))
        else:
            ops.append((i % 4, ("add_all", [f"e{i % 3}", f"e{(i + 1) % 3}"]), f"w{i % 2}"))
    for r, op, actor in ops:
        rt1.update_at(r, "s", op, actor)
    rt2.update_batch("s", ops)
    rt1.run_to_convergence()
    rt2.run_to_convergence()
    assert rt1.coverage_value("s") == rt2.coverage_value("s")
    assert rt1.divergence("s") == rt2.divergence("s") == 0


def test_update_batch_orset_remove_and_precondition():
    _, _, rt = _runtime(type="lasp_orset", n_elems=8)
    rt.update_batch("s", [(0, ("add_all", ["a", "b"]), "w")])
    rt.run_to_convergence()
    rt.update_batch("s", [(2, ("remove", "a"), "w")])
    rt.run_to_convergence()
    assert rt.coverage_value("s") == {"b"}
    from lasp_tpu.store.store import PreconditionError

    with pytest.raises(PreconditionError):
        rt.update_batch("s", [(1, ("remove", "nope"), "w")])
    with pytest.raises(PreconditionError):
        # "a" is tombstoned everywhere after convergence
        rt.update_batch("s", [(0, ("remove", "a"), "w")])


def test_update_batch_gcounter_and_gset():
    _, _, rt = _runtime(type="riak_dt_gcounter")
    # an actor's writes land at one replica (per-actor lanes merge by max:
    # same-lane writes at two replicas would be concurrent and collapse)
    rt.update_batch(
        "s",
        [(0, ("increment",), "c1"), (1, ("increment", 4), "c2"), (0, ("increment",), "c1")],
    )
    rt.run_to_convergence()
    assert rt.coverage_value("s") == 6

    _, _, rt = _runtime(type="lasp_gset", n_elems=8)
    rt.update_batch(
        "s", [(0, ("add", "x"), None), (3, ("add_all", ["y", "z"]), None)]
    )
    rt.run_to_convergence()
    assert rt.coverage_value("s") == {"x", "y", "z"}


def test_update_batch_remove_then_add_keeps_element():
    # sequential semantics: a remove BEFORE an add in the same batch must
    # not tombstone the add's freshly minted token
    _, _, rt = _runtime(type="lasp_orset", n_elems=8)
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    rt.update_batch("s", [(0, ("remove", "e"), "w"), (0, ("add", "e"), "w")])
    rt.run_to_convergence()
    assert rt.coverage_value("s") == {"e"}
    # and a duplicate remove inside one batch is a precondition error,
    # exactly as two sequential update_at calls would be
    from lasp_tpu.store.store import PreconditionError

    with pytest.raises(PreconditionError):
        rt.update_batch(
            "s", [(0, ("remove", "e"), "w"), (0, ("remove", "e"), "w")]
        )


def test_update_batch_respects_pool_holes():
    # a hole left by add_by_token must be skipped per-add, not assumed
    # contiguous: slot 1 pre-taken, two batch adds must land on 0 and 2
    import numpy as np

    _, _, rt = _runtime(type="lasp_orset", n_elems=4, tokens_per_actor=3)
    var = rt.store.variable("s")
    e = var.elems.intern("e")
    a = var.actors.intern("w")  # base = a * 3
    states = rt.states["s"]
    rt.states["s"] = states._replace(
        exists=states.exists.at[0, e, a * 3 + 1].set(True)
    )
    rt.update_batch("s", [(0, ("add", "e"), "w"), (0, ("add", "e"), "w")])
    pool = np.asarray(rt.states["s"].exists[0, e, a * 3 : a * 3 + 3])
    assert pool.tolist() == [True, True, True]
    removed = np.asarray(rt.states["s"].removed[0, e, a * 3 : a * 3 + 3])
    assert not removed.any()


@pytest.mark.parametrize("packed", [False, True])
def test_update_batch_failure_persists_earlier_ops(packed):
    # sequential semantics on failure: ops BEFORE the failing one stick,
    # exactly as a per-op update_at loop would leave the state
    from lasp_tpu.store.store import PreconditionError

    store = Store(n_actors=8)
    graph = Graph(store)
    store.declare(id="s", type="lasp_orset", n_elems=8, tokens_per_actor=1)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 1), packed=packed)
    with pytest.raises(PreconditionError):
        rt.update_batch(
            "s", [(0, ("add", "kept"), "w"), (0, ("remove", "kept"), "w"),
                  (0, ("remove", "kept"), "w")]
        )
    # the add AND the first remove landed; only the dup remove failed
    import jax
    import numpy as np

    assert rt.replica_value("s", 0) == set()
    st = rt._to_dense_row("s", jax.tree_util.tree_map(lambda x: x[0], rt.states["s"]))
    assert np.asarray(st.exists).any() and np.asarray(st.removed & st.exists).any()


def test_update_batch_empty_is_noop():
    _, _, rt = _runtime(type="riak_dt_gcounter")
    rt.update_batch("s", [])
    _, _, rt = _runtime(type="lasp_gset", n_elems=4)
    rt.update_batch("s", [(0, ("add_all", []), None)])
    assert rt.coverage_value("s") == set()


def test_token_pool_exhaustion_is_loud():
    # store path: k+1 sequential adds of the same elem by one actor raise
    store = Store(n_actors=4)
    v = store.declare(id="v", type="lasp_orset", n_elems=4, tokens_per_actor=2)
    store.update(v, ("add", "e"), "w")
    store.update(v, ("add", "e"), "w")  # idempotent pool fill is fine
    with pytest.raises(CapacityError):
        store.update(v, ("add", "e"), "w")
    # batch path raises too
    _, _, rt = _runtime(type="lasp_orset", n_elems=4, tokens_per_actor=1)
    with pytest.raises(CapacityError):
        rt.update_batch("s", [(0, ("add", "e"), "w"), (0, ("add", "e"), "w")])
    # device-side saturation is observable via stats
    from lasp_tpu.lattice import ORSet

    var = store.variable(v)
    stats = ORSet.stats(var.spec, var.state)
    assert stats["full_pools"] == 1


# -- ADVICE round-2 fixes ----------------------------------------------------

def test_gcounter_batch_rejects_nonpositive_increment():
    """Reference riak_dt_gcounter rejects non-positive increments; the
    batched scatter-add must raise instead of silently deflating a lane."""
    _, _, rt = _runtime(type="riak_dt_gcounter")
    with pytest.raises(ValueError, match=">= 1"):
        rt.update_batch("s", [(0, ("increment", 0), "a")])
    with pytest.raises(ValueError, match=">= 1"):
        rt.update_batch("s", [(0, ("increment", -3), "a")])
    rt.update_batch("s", [(0, ("increment", 2), "a")])
    rt.run_to_convergence()
    assert rt.coverage_value("s") == 2


def test_seed_tokens_duplicate_triples_idempotent_packed_vs_dense():
    """Duplicate (row, elem, token) triples must be idempotent in BOTH
    modes: the packed scatter-add emulation of scatter-OR would otherwise
    carry a duplicate bit into an unrelated token/element."""
    import numpy as np

    for packed in (False, True):
        store = Store(n_actors=4)
        graph = Graph(store)
        store.declare(id="s", type="lasp_orset", n_elems=4, n_actors=4,
                      tokens_per_actor=2)
        rt = ReplicatedRuntime(store, graph, 4, ring(4, 1), packed=packed)
        rt.intern_terms("s", ["a", "b", "c", "d"])
        rows = np.array([0, 0, 0, 1, 1])
        elems = np.array([1, 1, 1, 2, 2])
        tokens = np.array([3, 3, 3, 5, 5])  # duplicates on purpose
        rt.seed_tokens("s", rows, elems, tokens)
        if packed:
            got = rt.states["s"]
            from lasp_tpu.ops import FlatORSet
            dense = FlatORSet.unpack(rt._packed_specs["s"], got)
        else:
            dense = rt.states["s"]
        ex = np.asarray(dense.exists)
        assert ex[0, 1, 3] and ex[1, 2, 5]
        assert ex.sum() == 2, f"packed={packed}: duplicate bits leaked"


def test_mid_batch_failure_still_refreshes_edge_tables():
    """A caught mid-batch PreconditionError persists earlier ops; their
    interned terms must reach the edge tables (graph.refresh in finally),
    so a subsequent sweep projects them into dataflow outputs."""
    from lasp_tpu.store.store import PreconditionError

    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=8)
    b = store.declare(id="b", type="lasp_orset", n_elems=8)
    graph.union(a, b, dst="u")
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 1))
    with pytest.raises(PreconditionError):
        rt.update_batch(
            "a", [(0, ("add", "x"), "w"), (0, ("remove", "ghost"), "w")]
        )
    rt.run_to_convergence()
    assert rt.coverage_value("u") == {"x"}


def test_elem_word_masks_vectorized_matches_bit_loop():
    import numpy as np

    store = Store(n_actors=4)
    graph = Graph(store)
    store.declare(id="s", type="lasp_orset", n_elems=5, n_actors=3,
                  tokens_per_actor=3)
    rt = ReplicatedRuntime(store, graph, 2, ring(2, 1), packed=True)
    pspec = rt._packed_specs["s"]
    d = pspec.dense
    got = rt._elem_word_masks("s")
    ref = np.zeros((d.n_elems, pspec.n_words), dtype=np.uint32)
    for bit in range(pspec.n_bits):
        ref[bit // d.n_tokens, bit // 32] |= np.uint32(1) << (bit % 32)
    assert (got == ref).all()


def test_remove_of_unknown_term_fails_at_its_position_packed():
    """Packed twin: earlier adds persist before the unknown-term remove
    raises, matching per-op sequential semantics."""
    from lasp_tpu.store.store import PreconditionError

    store = Store(n_actors=4)
    graph = Graph(store)
    store.declare(id="s", type="lasp_orset", n_elems=8)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 1), packed=True)
    with pytest.raises(PreconditionError, match="ghost"):
        rt.update_batch(
            "s", [(1, ("add", "kept"), "w"), (1, ("remove", "ghost"), "w")]
        )
    assert rt.replica_value("s", 1) == {"kept"}


# -- batched OR-SWOT ----------------------------------------------------------

def test_update_batch_orswot_matches_sequential():
    def build():
        store = Store(n_actors=8)
        graph = Graph(store)
        store.declare(id="s", type="riak_dt_orswot", n_elems=8, n_actors=8)
        return ReplicatedRuntime(store, graph, 4, ring(4, 1))

    ops = [
        (0, ("add", "x"), "w0"),
        (0, ("add_all", ["y", "z"]), "w0"),
        (1, ("add", "x"), "w1"),
        (0, ("remove", "y"), "w0"),
        (0, ("add", "y"), "w2"),       # re-add after remove, fresh dot
        (2, ("add", "q"), "w3"),
        (2, ("remove", "q"), "w3"),    # add earlier in batch enables remove
    ]
    rt1, rt2 = build(), build()
    for r, op, actor in ops:
        rt1.update_at(r, "s", op, actor)
    rt2.update_batch("s", ops)
    import jax

    for r in range(4):
        s1 = jax.tree_util.tree_map(lambda x: x[r], rt1.states["s"])
        s2 = jax.tree_util.tree_map(lambda x: x[r], rt2.states["s"])
        assert (np.asarray(s1.clock) == np.asarray(s2.clock)).all(), r
        assert (np.asarray(s1.dots) == np.asarray(s2.dots)).all(), r
    rt2.run_to_convergence()
    assert rt2.coverage_value("s") == {"x", "y", "z"}


def test_update_batch_orswot_midbatch_precondition():
    from lasp_tpu.store.store import PreconditionError

    store = Store(n_actors=4)
    graph = Graph(store)
    store.declare(id="s", type="riak_dt_orswot", n_elems=4, n_actors=4)
    rt = ReplicatedRuntime(store, graph, 2, ring(2, 1))
    with pytest.raises(PreconditionError, match="ghost"):
        rt.update_batch(
            "s",
            [(0, ("add", "kept"), "w"),
             (0, ("remove", "ghost"), "w"),
             (0, ("add", "never-applied"), "w")],
        )
    assert rt.replica_value("s", 0) == {"kept"}
    # removing an element another replica added (not yet gossiped) also
    # fails the local precondition
    with pytest.raises(PreconditionError):
        rt.update_batch("s", [(1, ("remove", "kept"), "w")])
    rt.run_to_convergence()
    assert rt.coverage_value("s") == {"kept"}


# -- per-op atomicity + capacity-prefix parity with update_at ----------------

@pytest.mark.parametrize("packed", [False, True])
def test_failing_multiterm_op_is_atomic_like_update_at(packed):
    """A failing remove_all applies NOTHING of itself (update_at raises
    before merging the candidate), while prior ops persist."""
    def build():
        store = Store(n_actors=4)
        graph = Graph(store)
        store.declare(id="s", type="lasp_orset", n_elems=8)
        return ReplicatedRuntime(store, graph, 2, ring(2, 1), packed=packed)

    from lasp_tpu.store.store import PreconditionError

    ops = [
        (0, ("add_all", ["a", "b"]), "w0"),
        (0, ("remove_all", ["a", "ghost"]), "w0"),
    ]
    rt1, rt2 = build(), build()
    with pytest.raises(PreconditionError):
        for r, op, actor in ops:
            rt1.update_at(r, "s", op, actor)
    with pytest.raises(PreconditionError):
        rt2.update_batch("s", ops)
    assert rt1.replica_value("s", 0) == rt2.replica_value("s", 0) == {"a", "b"}


def test_failing_multiterm_orswot_op_is_atomic():
    from lasp_tpu.store.store import PreconditionError

    def build():
        store = Store(n_actors=4)
        graph = Graph(store)
        store.declare(id="s", type="riak_dt_orswot", n_elems=8, n_actors=4)
        return ReplicatedRuntime(store, graph, 2, ring(2, 1))

    ops = [
        (0, ("add_all", ["a", "b"]), "w0"),
        (0, ("remove_all", ["a", "ghost"]), "w0"),
    ]
    rt1, rt2 = build(), build()
    with pytest.raises(PreconditionError):
        for r, op, actor in ops:
            rt1.update_at(r, "s", op, actor)
    with pytest.raises(PreconditionError):
        rt2.update_batch("s", ops)
    import jax

    s1 = jax.tree_util.tree_map(lambda x: x[0], rt1.states["s"])
    s2 = jax.tree_util.tree_map(lambda x: x[0], rt2.states["s"])
    assert (np.asarray(s1.dots) == np.asarray(s2.dots)).all()
    assert (np.asarray(s1.clock) == np.asarray(s2.clock)).all()
    assert rt2.replica_value("s", 0) == {"a", "b"}


def test_interner_overflow_mid_batch_applies_op_prefix():
    """CapacityError from term interning follows the same per-op prefix
    rule: earlier ops persist, the overflowing op applies nothing."""
    from lasp_tpu.utils.interning import CapacityError

    def build():
        store = Store(n_actors=4)
        graph = Graph(store)
        store.declare(id="s", type="lasp_orset", n_elems=3)
        return ReplicatedRuntime(store, graph, 2, ring(2, 1))

    ops = [
        (0, ("add", "e1"), "w"),
        (1, ("add_all", ["e2", "e3"]), "w"),
        (0, ("add_all", ["e2", "e4"]), "w"),  # e4 overflows n_elems=3
        (0, ("add", "never"), "w"),
    ]
    rt1, rt2 = build(), build()
    with pytest.raises(CapacityError):
        for r, op, actor in ops:
            rt1.update_at(r, "s", op, actor)
    with pytest.raises(CapacityError):
        rt2.update_batch("s", ops)
    for r in range(2):
        assert rt1.replica_value("s", r) == rt2.replica_value("s", r), r
    assert rt2.replica_value("s", 0) == {"e1"}
    assert rt2.replica_value("s", 1) == {"e2", "e3"}


@pytest.mark.parametrize("packed", [False, True])
def test_add_all_exhausting_pool_is_atomic(packed):
    """An add_all whose LATER term exhausts the token pool must discard
    its own earlier allocations too (update_at applies ops atomically)."""
    def build():
        store = Store(n_actors=2)
        graph = Graph(store)
        store.declare(id="s", type="lasp_orset", n_elems=8, n_actors=2,
                      tokens_per_actor=1)
        return ReplicatedRuntime(store, graph, 2, ring(2, 1), packed=packed)

    ops = [
        (0, ("add", "x"), "w"),
        (0, ("add_all", ["y", "x"]), "w"),  # second add of x: pool of 1 full
    ]
    rt1, rt2 = build(), build()
    with pytest.raises(CapacityError):
        for r, op, actor in ops:
            rt1.update_at(r, "s", op, actor)
    with pytest.raises(CapacityError):
        rt2.update_batch("s", ops)
    assert rt1.replica_value("s", 0) == rt2.replica_value("s", 0) == {"x"}


def test_failing_batch_does_not_intern_later_ops_terms():
    """Ops after the failing op must not consume interner slots: a caller
    that catches the error and continues must see exactly the per-op
    loop's capacity."""
    from lasp_tpu.store.store import PreconditionError

    def build():
        store = Store(n_actors=4)
        graph = Graph(store)
        store.declare(id="s", type="riak_dt_orswot", n_elems=2, n_actors=4)
        return ReplicatedRuntime(store, graph, 2, ring(2, 1))

    ops = [
        (0, ("remove", "ghost"), "w"),
        (0, ("add", "a"), "w"),
        (0, ("add", "b"), "w"),
    ]
    rt1, rt2 = build(), build()
    with pytest.raises(PreconditionError):
        for r, op, actor in ops:
            rt1.update_at(r, "s", op, actor)
    with pytest.raises(PreconditionError):
        rt2.update_batch("s", ops)
    # both paths left the 2-slot universe empty; 'c' then 'd' both fit
    rt1.update_at(0, "s", ("add", "c"), "w")
    rt2.update_batch("s", [(0, ("add", "c"), "w"), (0, ("add", "d"), "w")])
    assert rt1.replica_value("s", 0) == {"c"}
    assert rt2.replica_value("s", 0) == {"c", "d"}


def test_update_batch_accepts_iterator_payloads():
    """One-shot iterables as add_all payloads must not be silently drained
    by the validation walks before dispatch."""
    _, _, rt = _runtime(type="lasp_orset", n_elems=8)
    rt.update_batch("s", [(0, ("add_all", iter(["a", "b"])), "w")])
    assert rt.replica_value("s", 0) == {"a", "b"}
    store = Store(n_actors=4)
    graph = Graph(store)
    store.declare(id="s", type="riak_dt_orswot", n_elems=8, n_actors=4)
    rt2 = ReplicatedRuntime(store, graph, 2, ring(2, 1))
    rt2.update_batch("s", [(0, ("add_all", iter(["x", "y"])), "w")])
    rt2.update_batch("s", [(0, ("remove_all", iter(["x"])), "w")])
    assert rt2.replica_value("s", 0) == {"y"}


def test_ivar_batch_first_set_wins_and_respects_existing():
    """Vectorized I-Var batch: per row the FIRST set defines (later
    different payloads are bind-rule non-inflations), and an
    already-defined row keeps its value (src/lasp_ivar.erl:50-56)."""
    store = Store(n_actors=2)
    graph = Graph(store)
    v = store.declare(id="v", type="lasp_ivar")
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch(v, [(3, ("set", "pre"), "w")])
    rt.update_batch(v, [
        (0, ("set", "a"), "w"),
        (0, ("set", "clobber"), "w"),   # same row, later: ignored
        (3, ("set", "clobber"), "w"),   # already defined: ignored
        (5, ("set", "b"), "w"),
    ])
    assert rt.replica_value(v, 0) == "a"
    assert rt.replica_value(v, 3) == "pre"
    assert rt.replica_value(v, 5) == "b"
    # converges to ONE winner under the ivar conflict rule, deterministically
    rt.run_to_convergence(block=4)
    assert rt.divergence(v) == 0


def test_map_batch_vectorized_without_warning():
    """Maps whose fields all have pure batch kernels take the vectorized
    path — no per-op fallback warning — and converge correctly."""
    import warnings

    store = Store(n_actors=4)
    graph = Graph(store)
    m = store.declare(
        id="m", type="riak_dt_map",
        fields=[("tags", "lasp_gset", {"n_elems": 4}),
                ("hits", "riak_dt_gcounter", {})],
        n_actors=4,
    )
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.update_batch(m, [
            (0, ("update", "tags", ("add", "t1")), "w0"),
            (2, ("update", "hits", ("increment", 3)), "w1"),
        ])
    assert not any("no vectorized kernel" in str(w.message) for w in caught)
    rt.run_to_convergence(block=4)
    assert rt.coverage_value(m) == {"tags": frozenset({"t1"}), "hits": 3}
    assert rt.divergence(m) == 0


def _map_rt(n=8, n_actors=4, gset_elems=4):
    store = Store(n_actors=n_actors)
    graph = Graph(store)
    m = store.declare(
        id="m", type="riak_dt_map",
        fields=[("tags", "lasp_gset", {"n_elems": gset_elems}),
                ("hits", "riak_dt_gcounter", {}),
                ("owner", "lasp_ivar", {})],
        n_actors=n_actors,
    )
    rt = ReplicatedRuntime(store, graph, n, ring(n, 2))
    return rt, m


def test_map_batch_matches_per_op_random():
    """The vectorized map batch is indistinguishable from the per-op
    update_at loop: same presence dots, same clock, same field states,
    over random op sequences (the EQC-style oracle at batch altitude)."""
    import random

    import numpy as np

    for seed in range(6):
        rng = random.Random(seed)
        ops = []
        for _ in range(40):
            r = rng.randrange(8)
            actor = f"w{rng.randrange(3)}"
            kind = rng.random()
            if kind < 0.4:
                ops.append((r, ("update", "tags",
                                ("add", f"t{rng.randrange(4)}")), actor))
            elif kind < 0.6:
                ops.append((r, ("update", "hits",
                                ("increment", rng.randrange(1, 4))), actor))
            elif kind < 0.7:
                ops.append((r, ("update", "owner",
                                ("set", f"o{rng.randrange(2)}")), actor))
            elif kind < 0.85:
                # batched sub-op shape: atomic pair
                ops.append((r, ("update", [
                    ("update", "tags", ("add", f"t{rng.randrange(4)}")),
                    ("update", "hits", ("increment",)),
                ]), actor))
            else:
                ops.append((r, ("remove", "tags"), actor))

        rt1, m1 = _map_rt()
        rt2, m2 = _map_rt()
        ok1 = ok2 = 0
        try:
            rt1.update_batch(m1, list(ops))
            ok1 = len(ops)
        except Exception as e1:
            err1 = type(e1).__name__
        for r, op, actor in ops:
            try:
                rt2.update_at(r, m2, op, actor)
                ok2 += 1
            except Exception as e2:
                err2 = type(e2).__name__
                break
        if ok1 != len(ops):
            # both must fail at the same op with the same error class
            assert ok2 != len(ops) and err1 == err2, (seed, err1)
        s1, s2 = rt1.states[m1], rt2.states[m2]
        assert np.array_equal(s1.clock, s2.clock), seed
        assert np.array_equal(s1.dots, s2.dots), seed
        for f1, f2 in zip(s1.fields, s2.fields):
            for l1, l2 in zip(f1, f2):
                assert np.array_equal(l1, l2), seed
        rt1.run_to_convergence(block=4)
        rt2.run_to_convergence(block=4)
        assert rt1.coverage_value(m1) == rt2.coverage_value(m2), seed


def test_map_batch_per_op_atomicity_on_failure():
    """A failing op mid-batch applies NOTHING of itself (not even earlier
    sub-ops of its own atomic group); earlier ops persist; the error
    surfaces."""
    import numpy as np
    import pytest as _pytest

    from lasp_tpu.store.store import PreconditionError

    rt, m = _map_rt()
    with _pytest.raises(PreconditionError, match="not_present"):
        rt.update_batch(m, [
            (0, ("update", "tags", ("add", "t1")), "w0"),
            # atomic group: the add lands in sim, then the remove of an
            # absent field fails -> the whole group must rewind
            (1, ("update", [
                ("update", "hits", ("increment", 2)),
                ("remove", "owner"),
            ]), "w1"),
            (2, ("update", "tags", ("add", "t2")), "w2"),  # never reached
        ])
    assert rt.replica_value(m, 0)["tags"] == frozenset({"t1"})
    assert "hits" not in rt.replica_value(m, 1)  # group rewound: absent
    assert "tags" not in rt.replica_value(m, 2)  # op after the failure
    # clock untouched by the rewound group: w1 minted nothing
    assert int(np.asarray(rt.states[m].clock).sum()) == 1


def test_map_batch_capacity_prefix():
    from lasp_tpu.utils.interning import CapacityError

    import pytest as _pytest

    rt, m = _map_rt(gset_elems=2)
    with _pytest.raises(CapacityError):
        rt.update_batch(m, [
            (0, ("update", "tags", ("add", "a")), "w"),
            (0, ("update", "tags", ("add", "b")), "w"),
            (0, ("update", "tags", ("add", "c")), "w"),  # overflows
            (0, ("update", "tags", ("add", "d")), "w"),
        ])
    assert rt.replica_value(m, 0)["tags"] == frozenset({"a", "b"})


def test_map_batch_fallback_warning_only_for_unbatchable_fields():
    import warnings

    store = Store(n_actors=4)
    graph = Graph(store)
    m = store.declare(
        id="m", type="riak_dt_map",
        fields=[("s", "lasp_orset", {"n_elems": 4, "n_actors": 4,
                                     "tokens_per_actor": 2})],
        n_actors=4,
    )
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.update_batch(m, [(0, ("update", "s", ("add", "x")), "w")])
    assert any("no vectorized kernel" in str(w.message) for w in caught)
    assert rt.replica_value(m, 0)["s"] == frozenset({"x"})
