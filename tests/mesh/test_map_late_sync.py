"""Regression tier for the ISSUE-3 map/population lock-step satellites:

1. a LATE-DECLARED schemaless map's first update naming a fresh
   ``{Name, Type}`` key must not KeyError in ``_grow_map_population``
   (the spec used to grow while the population row was never created) —
   both the ``update_at`` and ``update_batch`` paths;
2. map fields admitted on the STORE variable behind the runtime's back
   (the bridge's merge_batch/import path) must be resolved by
   ``_population``'s spec/state field-axis re-layout, and an impossible
   shrink must raise clearly."""

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _rt(n: int = 4):
    store = Store(n_actors=4)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    return store, rt


KEY = ("S", "lasp_gset")
KEY2 = ("C", "riak_dt_gcounter")


def test_late_declared_map_first_update_at_admits_fresh_key():
    store, rt = _rt()
    # declared AFTER the runtime was built: no population row yet
    m = store.declare(id="m", type="riak_dt_map", n_actors=4)
    rt.update_at(1, m, ("update", [("update", KEY, ("add", "a"))]), "w")
    # spec and population are in lock-step: the row holds the write and
    # the population has exactly the admitted field axis
    assert rt.replica_value(m, 1) == {KEY: {"a"}}
    assert rt.states[m].dots.shape[-2] == store.variable(m).spec.n_fields
    rt.run_to_convergence()
    assert rt.coverage_value(m) == {KEY: {"a"}}


def test_late_declared_map_first_update_batch_admits_fresh_key():
    store, rt = _rt()
    m = store.declare(id="m2", type="riak_dt_map", n_actors=4)
    rt.update_batch(
        m,
        [
            (0, ("update", [("update", KEY, ("add", "x"))]), "w0"),
            (2, ("update", [("update", KEY2, ("increment", 2))]), "w2"),
        ],
    )
    assert rt.states[m].dots.shape[-2] == store.variable(m).spec.n_fields
    rt.run_to_convergence()
    assert rt.coverage_value(m) == {KEY: {"x"}, KEY2: 2}


def test_population_relayouts_fields_admitted_behind_runtimes_back():
    store, rt = _rt()
    m = store.declare(id="m3", type="riak_dt_map", n_actors=4)
    rt.update_at(0, m, ("update", [("update", KEY, ("add", "a"))]), "w")
    var = store.variable(m)
    before = var.spec.n_fields
    # the bridge's import path grows the STORE variable directly
    # (server.py _validate_portable -> Store.grow_map_fields), with the
    # runtime none the wiser
    triple = Store.resolve_dynamic_field(var.spec, KEY2)
    Store.grow_map_fields(var, [triple])
    var.state = var.codec.grow(var.spec, var.state)
    assert var.spec.n_fields == before + 1
    assert rt.states[m].dots.shape[-2] == before  # skewed, not yet seen
    # the next verb through _population re-lays-out the population
    assert rt.replica_value(m, 0) == {KEY: {"a"}}
    assert rt.states[m].dots.shape[-2] == before + 1
    # and the admitted field is writable at mesh level right away
    rt.update_at(1, m, ("update", [("update", KEY2, ("increment",))]), "w2")
    rt.run_to_convergence()
    assert rt.coverage_value(m) == {KEY: {"a"}, KEY2: 1}


def test_population_with_more_fields_than_spec_raises():
    store, rt = _rt()
    m = store.declare(id="m4", type="riak_dt_map", n_actors=4)
    rt.update_at(0, m, ("update", [("update", KEY, ("add", "a"))]), "w")
    var = store.variable(m)
    # simulate an impossible shrink (spec rolled back behind the
    # runtime): must be a loud error, not a silent misaligned gather
    import dataclasses

    var.spec = dataclasses.replace(var.spec, fields=())
    with pytest.raises(RuntimeError, match="field planes"):
        rt.replica_value(m, 0)
