"""Mesh-level program deployment (VERDICT r3 ask #5): register-on-every-
partition + targeted process + coverage/quorum execute, mirrored from
``src/lasp_vnode.erl:276-366`` + ``src/lasp_execute_coverage_fsm.erl:50-97``
and the riak_test program suites (``riak_test/lasp_global_programs_test.erl``,
``lasp_global_program_keylist_test.erl``)."""

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import random_regular, ring
from lasp_tpu.programs import ExampleKeylistProgram, ExampleProgram
from lasp_tpu.programs.riak_index import (
    BASE_NAME,
    RiakIndexProgram,
    RiakObject,
    view_name,
)
from lasp_tpu.store import Store


def _rt(n=16, k=2, topo=ring):
    store = Store(n_actors=8)
    return ReplicatedRuntime(store, Graph(store), n, topo(n, k))


def test_keylist_program_over_population():
    rt = _rt()
    rt.register("keylist", ExampleKeylistProgram, n_elems=16)
    # events land on different replica rows (different clients/partitions)
    for i, key in enumerate(["k1", "k2", "k3", "k4"]):
        rt.process((key, f"v{i}"), "put", f"actor{i}", replica=(i * 5) % 16)
    # coverage execute sees every partition's accumulator BEFORE gossip —
    # exactly the coverage-FSM merge
    assert rt.execute("keylist") == {"k1", "k2", "k3", "k4"}
    # a single row has only its own events until anti-entropy runs
    assert rt.replica_value(rt._programs["keylist"].id, 0) == {"k1"}
    rt.run_to_convergence(max_rounds=64)
    # convergence predicate: every replica's local view reaches coverage
    pid = rt._programs["keylist"].id
    assert rt.divergence(pid) == 0
    for r in range(rt.n_replicas):
        assert rt.replica_value(pid, r) == {"k1", "k2", "k3", "k4"}


def test_example_program_accumulates_objects():
    rt = _rt(n=8, k=2)
    rt.register("acc", ExampleProgram, n_elems=16)
    rt.process("obj1", "put", "a0", replica=0)
    rt.process("obj2", "delete", "a1", replica=3)  # every event adds (:43-45)
    assert rt.execute("acc") == {"obj1", "obj2"}


def test_register_is_idempotent():
    rt = _rt(n=8)
    rt.register("keylist", ExampleKeylistProgram, n_elems=8)
    pid = rt._programs["keylist"].id
    rt.register("keylist", ExampleKeylistProgram, n_elems=8)
    assert rt._programs["keylist"].id == pid
    assert list(rt.programs) == ["keylist"]


def test_programs_cannot_write_during_execute():
    class Misbehaved(ExampleKeylistProgram):
        def execute(self, session):
            session.store.update(self.id, ("add", "sneaky"), "x")

    rt = _rt(n=8)
    rt.register("bad", Misbehaved, n_elems=8)
    with pytest.raises(RuntimeError, match="coverage execute"):
        rt.execute("bad")


def test_riak_index_program_mesh_views_and_delete():
    rt = _rt(n=16, k=3)
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=32, token_space=16)

    def route(key):  # the preflist-hash discipline: same key, same row
        return hash(key) % rt.n_replicas

    def put(key, vclock, specs=()):
        rt.process(
            RiakObject(key=key, vclock=vclock, metadata=f"m-{key}",
                       index_specs=specs),
            "put", f"client-{route(key)}", replica=route(key),
        )

    put("alpha", ("vc", 1), specs=(("add", "color", "red"),))
    put("beta", ("vc", 2), specs=(("add", "color", "blue"),))
    put("gamma", ("vc", 3), specs=(("add", "color", "red"),))
    # auto-created parameterized views exist at the mesh registry
    assert view_name("color", "red") in rt.programs
    assert view_name("color", "blue") in rt.programs
    # the view registered by an event sees the NEXT event: replay reds so
    # the red view (created by alpha's put) indexes them
    put("alpha", ("vc", 1.1), specs=(("add", "color", "red"),))
    put("gamma", ("vc", 3.1), specs=(("add", "color", "red"),))

    assert rt.execute(BASE_NAME) == {"alpha", "beta", "gamma"}
    assert rt.execute(view_name("color", "red")) == {"alpha", "gamma"}

    # delete removes the key's entries at its routed row; coverage join
    # sees the tombstones immediately
    rt.process(
        RiakObject(key="beta", vclock=("vc", 4)), "delete",
        f"client-{route('beta')}", replica=route("beta"),
    )
    assert rt.execute(BASE_NAME) == {"alpha", "gamma"}

    # remove-then-add on a re-put: stale entry replaced, not duplicated
    put("alpha", ("vc", 5), specs=(("add", "color", "red"),))
    prog = rt._programs[BASE_NAME]
    session = rt._session()
    session.replica = None
    entries = prog.execute(session)
    assert {k for k, _m in entries if k == "alpha"} == {"alpha"}
    assert len([k for k, _m in entries if k == "alpha"]) == 1

    rt.run_to_convergence(max_rounds=64)
    assert rt.divergence(prog.id) == 0
    assert rt.execute(BASE_NAME) == {"alpha", "gamma"}


def test_riak_index_handoff_idempotent_and_unknown_reason_loud():
    rt = _rt(n=8, k=2)
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=8, token_space=8,
                auto_views=False)
    obj = RiakObject(key="k", vclock=("vc", 1), metadata="m")
    rt.process(obj, "put", "a0", replica=0)
    assert rt.execute(BASE_NAME) == {"k"}
    # handoff re-describes the object at a row that never saw the put:
    # the vclock-derived token makes the re-index IDEMPOTENT — after
    # convergence there is exactly one entry, never a duplicate
    rt.process(obj, "handoff", "a1", replica=3)
    assert rt.execute(BASE_NAME) == {"k"}
    rt.run_to_convergence(max_rounds=64)
    prog = rt._programs[BASE_NAME]
    assert rt.divergence(prog.id) == 0
    assert rt.execute(BASE_NAME) == {"k"}
    # an unknown reason must be LOUD, not a silently dropped notification
    with pytest.raises(NotImplementedError, match="unsupported object-event"):
        rt.process(obj, "putt", "a0", replica=0)
    assert rt.execute(BASE_NAME) == {"k"}


def test_riak_index_put_handoff_delete_sequence():
    """The satellite contract: put → handoff → delete. Handoff of an
    already-indexed object is a no-op (same entry, no token churn);
    handoff of an UNSEEN object indexes it; a handoff replayed after
    the delete stays deleted (the re-add lands on its own tombstoned
    token — delete wins, replay-safe)."""
    rt = _rt(n=8, k=2)
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=8, token_space=8,
                auto_views=False)
    prog = rt._programs[BASE_NAME]
    obj = RiakObject(key="k", vclock=("vc", 1), metadata="m")

    rt.process(obj, "put", "a0", replica=0)
    before = rt.store.variable(prog.id)
    n_elems_before = len(before.elems)
    # handoff at the SAME row: the exact entry is live -> no-op (no new
    # element interned, no remove-then-add churn)
    rt.process(obj, "handoff", "a0", replica=0)
    assert rt.execute(BASE_NAME) == {"k"}
    assert len(rt.store.variable(prog.id).elems) == n_elems_before

    # handoff of an object this index NEVER saw put: ownership moved
    # mid-stream — the re-description must index it
    other = RiakObject(key="k2", vclock=("vc", 7), metadata="m2")
    rt.process(other, "handoff", "a1", replica=5)
    assert rt.execute(BASE_NAME) == {"k", "k2"}

    rt.process(obj, "delete", "a0", replica=0)
    assert rt.execute(BASE_NAME) == {"k2"}
    # a handoff frame replayed after the delete must NOT resurrect the
    # entry: the re-add's vclock-derived token is tombstoned
    rt.process(obj, "handoff", "a0", replica=0)
    assert rt.execute(BASE_NAME) == {"k2"}
    rt.run_to_convergence(max_rounds=64)
    assert rt.execute(BASE_NAME) == {"k2"}


def test_riak_index_stale_handoff_cannot_erase_newer_entry():
    """The review-hardening regression: a handoff carrying an OLDER
    version of an already-indexed key must NOT take the put path —
    remove-then-re-add would tombstone the newer entry's token while
    the stale re-add lands on its own tombstoned token, leaving the
    key unrecoverably unindexed."""
    rt = _rt(n=8, k=2)
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=8, token_space=8,
                auto_views=False)
    rt.process(RiakObject(key="k", vclock=("vc", 1), metadata="old"),
               "put", "a0", replica=0)
    rt.process(RiakObject(key="k", vclock=("vc", 2), metadata="new"),
               "put", "a0", replica=0)
    assert rt.execute(BASE_NAME) == {"k"}
    # a fallback vnode hands off the version IT held — the older one
    rt.process(RiakObject(key="k", vclock=("vc", 1), metadata="old"),
               "handoff", "a1", replica=0)
    out = rt._programs[BASE_NAME].execute(rt._session())
    assert out == {("k", "new")}  # the newer entry survived, unreplaced
    # replaying the stale handoff again is still a no-op
    rt.process(RiakObject(key="k", vclock=("vc", 1), metadata="old"),
               "handoff", "a1", replica=0)
    assert rt._programs[BASE_NAME].execute(rt._session()) == {("k", "new")}


def test_riak_index_handoff_respects_subset_views():
    """A handoff re-description flows through view selection like a
    put: matching subset views index it, non-matching views skip it."""
    rt = _rt(n=8, k=2)
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=8, token_space=8)
    seed = RiakObject(key="seed", vclock=("vc", 0),
                      index_specs=(("add", "color", "red"),))
    rt.process(seed, "put", "a0", replica=0)  # auto-creates the red view
    handed = RiakObject(key="h", vclock=("vc", 1),
                        index_specs=(("add", "color", "red"),))
    rt.process(handed, "handoff", "a1", replica=2)
    assert rt.execute(BASE_NAME) == {"seed", "h"}
    assert rt.execute(view_name("color", "red")) == {"h"}


def test_index_capacity_recovery_converges_then_compacts():
    # delete/re-put churn fills the view's element universe with dead
    # entries; the program's CapacityError recovery must work under mesh
    # delivery: converge the population, compact every row, retry the add
    rt = _rt(n=8, k=2)
    rt.register(BASE_NAME, RiakIndexProgram, n_elems=6, token_space=8,
                auto_views=False)
    row = 3  # same-key discipline: all churn for these keys at one row
    for i in range(10):  # 10 distinct (key, vclock) entries >> 6 slots
        key = f"churn{i % 2}"
        rt.process(RiakObject(key=key, vclock=("vc", i)), "put",
                   "c0", replica=row)
        rt.process(RiakObject(key=key, vclock=("vc", i)), "delete",
                   "c0", replica=row)
    rt.process(RiakObject(key="live", vclock=("vc", 99)), "put",
               "c0", replica=row)
    assert rt.execute(BASE_NAME) == {"live"}
    prog = rt._programs[BASE_NAME]
    # compaction really ran: 11 distinct entries were interned into a
    # 6-slot universe, so dead entries were reclaimed along the way
    assert len(rt.store.variable(prog.id).elems) <= 6
    rt.run_to_convergence(max_rounds=32)
    assert rt.divergence(prog.id) == 0
    assert rt.execute(BASE_NAME) == {"live"}


def test_execute_during_process_preserves_row_binding():
    # a program consulting another program's result mid-delivery must not
    # unbind the row for the programs that run after it
    seen = []

    class Nosy(ExampleKeylistProgram):
        def process(self, session, object, reason, actor):
            session.runtime.execute("keylist")  # nested coverage execute
            super().process(session, object, reason, actor)

    rt = _rt(n=8, k=2)
    rt.register("keylist", ExampleKeylistProgram, n_elems=8)
    rt.register("nosy", Nosy, n_elems=8)

    class After(ExampleKeylistProgram):
        def process(self, session, object, reason, actor):
            seen.append(session.replica)
            super().process(session, object, reason, actor)

    rt.register("after", After, n_elems=8)
    rt.process(("k1", 1), "put", "a0", replica=5)
    assert seen == [5]  # binding survived the nested execute
    assert rt.execute("after") == {"k1"}


def test_programs_on_packed_runtime():
    # program accumulators are OR-Set-family -> packable: delivery and
    # coverage execute must work through the packed wire format too
    store = Store(n_actors=8)
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2), packed=True)
    rt.register("keylist", ExampleKeylistProgram, n_elems=16)
    rt.register("acc", ExampleProgram, n_elems=16)
    for i, key in enumerate(["k1", "k2", "k3"]):
        rt.process((key, i), "put", f"actor{i}", replica=(i * 3) % 8)
    assert rt.execute("keylist") == {"k1", "k2", "k3"}
    rt.run_to_convergence(max_rounds=16)
    pid = rt._programs["keylist"].id
    assert rt.divergence(pid) == 0
    for r in range(8):
        assert rt.replica_value(pid, r) == {"k1", "k2", "k3"}


def test_programs_survive_membership_changes():
    # register-on-every-partition must hold across joins/leaves: the
    # accumulator rides the population through resize (new rows at
    # bottom, caught up by gossip; graceful leave hands state to a
    # survivor), and delivery/execute keep working
    rt = _rt(n=8, k=2)
    rt.register("keylist", ExampleKeylistProgram, n_elems=16)
    rt.process(("before", 0), "put", "a0", replica=2)
    rt.resize(12, ring(12, 2))  # join: 4 fresh rows
    rt.process(("after-grow", 1), "put", "a1", replica=10)  # a new row
    assert rt.execute("keylist") == {"before", "after-grow"}
    rt.run_to_convergence(max_rounds=32)
    rt.resize(6, ring(6, 2), graceful=True)  # leave: survivors keep state
    assert rt.execute("keylist") == {"before", "after-grow"}
    rt.process(("after-shrink", 2), "put", "a2", replica=5)
    rt.run_to_convergence(max_rounds=32)
    pid = rt._programs["keylist"].id
    assert rt.divergence(pid) == 0
    for r in range(6):
        assert rt.replica_value(pid, r) == {
            "before", "after-grow", "after-shrink",
        }


def test_quorum_execute_is_monotone_lower_bound():
    rt = _rt(n=12, k=3, topo=random_regular)
    rt.register("keylist", ExampleKeylistProgram, n_elems=8)
    rt.process(("k1", 1), "put", "a0", replica=2)
    rt.process(("k2", 2), "put", "a1", replica=9)
    # a quorum missing row 9 sees only k1; the full coverage sees both
    assert rt.execute("keylist", replicas=[2, 3]) == {"k1"}
    assert rt.execute("keylist", replicas=[2, 9]) == {"k1", "k2"}
    rt.run_to_convergence(max_rounds=64)
    assert rt.execute("keylist", replicas=[0]) == {"k1", "k2"}
