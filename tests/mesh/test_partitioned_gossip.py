"""Locality-aware sharding for irregular gossip (VERDICT r4 weak #3).

Three claims, each pinned:
1. ``topology.locality_order`` is a graph isomorphism — renumbering
   changes nothing observable about gossip dynamics.
2. The boundary-exchange rounds (``shard_gossip.partitioned_gossip_*``)
   are semantically identical to the dense ``gossip_round`` on the same
   topology, for multiple state-plane shapes including the packed wire
   format.
3. The compiled HLO's only collective is an all-gather of ``[S, M, ...]``
   — cross-shard bytes scale with the CUT (M = max per-shard boundary
   rows), never the population R.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lasp_tpu.lattice import GSet, GSetSpec
from lasp_tpu.lattice.base import replicate
from lasp_tpu.mesh.gossip import gossip_round
from lasp_tpu.mesh.shard_gossip import (
    partitioned_gossip_plan,
    partitioned_gossip_round_fn,
    partitioned_gossip_rounds,
)
from lasp_tpu.mesh.topology import (
    locality_order,
    random_regular,
    scale_free,
    shard_cut_stats,
)
from lasp_tpu.ops import PackedORSet, PackedORSetSpec


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("replicas",))


def _put(states, mesh, spec=P("replicas")):
    sh = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)


def _tables(plan, mesh):
    tsh = NamedSharding(mesh, P("replicas", None))
    return (
        jax.device_put(jnp.asarray(plan["send_idx"]), tsh),
        jax.device_put(jnp.asarray(plan["idx"]), tsh),
    )


@pytest.mark.parametrize("builder,seed", [
    (scale_free, 3), (scale_free, 7), (random_regular, 2),
])
def test_locality_order_is_isomorphism(builder, seed):
    R = 192
    nbrs = builder(R, 3, seed=seed)
    perm, nn = locality_order(nbrs)
    assert sorted(perm.tolist()) == list(range(R))  # a real permutation
    spec = GSetSpec(n_elems=16)
    rng = np.random.RandomState(seed)
    states = replicate(GSet.new(spec), R)._replace(
        mask=jnp.asarray(rng.rand(R, 16) < 0.05)
    )
    ref = states
    got = jax.tree_util.tree_map(lambda x: x[perm], states)
    for _ in range(3):
        ref = gossip_round(GSet, spec, ref, jnp.asarray(nbrs))
        got = gossip_round(GSet, spec, got, jnp.asarray(nn))
    assert jnp.array_equal(got.mask, ref.mask[perm])


def test_locality_order_localizes_backbone():
    # column 0 is a permutation backbone; after cycle-following its edges
    # are +1 shifts — cross-shard only at block boundaries and cycle
    # closures, never O(R)
    R, S = 1024, 8
    _, nn = locality_order(scale_free(R, 3, seed=5))
    B = R // S
    src = np.arange(R) // B
    cross0 = ((nn[:, 0] // B) != src).sum()
    # bound: one boundary edge per block edge (S) plus one per cycle; a
    # random permutation of 1024 has ~ln(1024)=7 cycles
    assert cross0 <= S + 32, int(cross0)


def test_locality_order_cuts_scale_free_wire():
    R, S = 4096, 8
    nbrs = scale_free(R, 3, seed=1)
    before = shard_cut_stats(nbrs, S)
    _, nn = locality_order(nbrs)
    after = shard_cut_stats(nn, S)
    # the renumbered exchange must beat BOTH the unordered exchange and
    # the population all-gather by a real margin
    assert after["exchange_rows_per_round"] < before["exchange_rows_per_round"]
    assert after["exchange_rows_per_round"] < 0.6 * R, after


def test_partitioned_rounds_equal_dense_gset():
    R, S = 256, 8
    mesh = _mesh()
    _, nn = locality_order(scale_free(R, 3, seed=3))
    plan = partitioned_gossip_plan(nn, S)
    spec = GSetSpec(n_elems=16)
    rng = np.random.RandomState(0)
    states = replicate(GSet.new(spec), R)._replace(
        mask=jnp.asarray(rng.rand(R, 16) < 0.05)
    )
    sharded = _put(states, mesh)
    got, changed = partitioned_gossip_rounds(GSet, spec, sharded, mesh, plan, 3)
    ref = states
    for _ in range(3):
        ref = gossip_round(GSet, spec, ref, jnp.asarray(nn))
    assert bool(changed)
    assert jnp.array_equal(got.mask, ref.mask)


def test_partitioned_rounds_equal_dense_packed_orset():
    # the wire format the population-scale configs actually ride
    R, S = 128, 8
    mesh = _mesh()
    _, nn = locality_order(scale_free(R, 3, seed=9))
    plan = partitioned_gossip_plan(nn, S)
    spec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    rng = np.random.RandomState(4)
    states = replicate(PackedORSet.new(spec), R)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(R, spec.n_elems, spec.n_words)),
            dtype=jnp.uint32,
        )
    )
    sharded = _put(states, mesh)
    got, _ = partitioned_gossip_rounds(PackedORSet, spec, sharded, mesh, plan, 2)
    ref = states
    for _ in range(2):
        ref = gossip_round(PackedORSet, spec, ref, jnp.asarray(nn))
    assert jnp.array_equal(got.exists, ref.exists)
    assert jnp.array_equal(got.removed, ref.removed)


def test_hlo_collectives_are_boundary_sized():
    # THE claim of this feature: cross-shard bytes scale with the cut
    # (S*M rows), not the population (R rows)
    R, S = 256, 8
    mesh = _mesh()
    _, nn = locality_order(scale_free(R, 3, seed=3))
    plan = partitioned_gossip_plan(nn, S)
    spec = GSetSpec(n_elems=16)
    states = _put(replicate(GSet.new(spec), R), mesh)
    send_idx, idx = _tables(plan, mesh)
    fn = jax.jit(partitioned_gossip_round_fn(GSet, spec, mesh, plan))
    hlo = fn.lower(states, send_idx, idx).compile().as_text()
    ags = re.findall(r"= (\w+)\[([\d,]*)\][^=]*all-gather\(", hlo)
    assert ags, "boundary exchange must lower to an all-gather"
    for _dt, dims in ags:
        lead = int(dims.split(",")[0]) if dims else 1
        assert lead <= S * plan["m"], (
            f"population-sized collective {dims} (M={plan['m']})"
        )
    assert S * plan["m"] < R  # the cut genuinely beats the population here
    # and no other collective sneaks the population across shards
    assert "all-reduce" not in hlo or f"[{R}," not in hlo


def test_plan_rejects_indivisible_population():
    with pytest.raises(ValueError):
        partitioned_gossip_plan(scale_free(100, 3, seed=0), 8)


def test_scenario_smoke():
    # the measured-artifact producer runs end to end at CI scale
    from lasp_tpu.bench_scenarios import partitioned_gossip

    out = partitioned_gossip(n_replicas=512, rounds=2)
    assert out["wire_reduction"] is not None
    assert (
        out["exchange_allgather_bytes_per_round"]
        < out["dense_allgather_bytes_per_round"]
    )


# -- the ENGINE step under shard(partition=True) ------------------------------

def _partitioned_runtime(n=256, seed=3):
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    _, nn = locality_order(scale_free(n, 3, seed=seed))
    store = Store(n_actors=8)
    s = store.declare(id="s", type="lasp_orset", n_elems=16)
    graph = Graph(store)
    graph.map(s, lambda x: f"m:{x}", dst="out", dst_elems=32)
    rt = ReplicatedRuntime(store, graph, n, nn)
    rt.update_at(0, s, ("add_all", ["a", "b"]), "w0")
    rt.update_at(n // 2, s, ("add", "c"), "w1")
    return rt, nn, s


def test_engine_step_partitioned_matches_unsharded():
    rt, nn, s = _partitioned_runtime()
    ref, _nn, _s = _partitioned_runtime()
    mesh = _mesh()
    rt.shard(mesh, axis="replicas", partition=True)
    rt.run_to_convergence(max_rounds=64)
    ref.run_to_convergence(max_rounds=64)
    assert rt.divergence(s) == 0
    assert rt.coverage_value(s) == ref.coverage_value(s) == frozenset(
        {"a", "b", "c"}
    )
    assert rt.coverage_value("out") == ref.coverage_value("out")


def test_engine_step_partitioned_hlo_is_boundary_sized():
    # THE upgrade over r4: the flagship step itself — not a side entry
    # point — stops all-gathering the population on irregular topologies
    rt, nn, _s = _partitioned_runtime()
    mesh = _mesh()
    rt.shard(mesh, axis="replicas", partition=True, partition_mode="gather")
    tables = rt._ensure_step()
    hlo = (
        jax.jit(rt._step_pure)
        .lower(rt.states, rt.neighbors, None, tables)
        .compile()
        .as_text()
    )
    m = rt._partition["plan"]["m"]
    S = 8
    ags = re.findall(r"= (\w+)\[([\d,]*)\][^=]*all-gather\(", hlo)
    assert ags, "boundary exchange must lower to an all-gather"
    for _dt, dims in ags:
        lead = int(dims.split(",")[0]) if dims else 1
        assert lead <= S * m, (dims, m)
    assert S * m < 256  # the cut beat the population on this topology


def test_engine_step_partitioned_rejects_edge_mask_and_shift():
    import jax.numpy as jnp
    import pytest

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    rt, nn, _s = _partitioned_runtime(n=64)
    rt.shard(_mesh(), axis="replicas", partition=True)
    with pytest.raises(ValueError, match="edge_mask"):
        rt.step(edge_mask=jnp.ones((64, 3), dtype=bool))
    # shift-structured topologies refuse the plan outright
    store = Store(n_actors=4)
    store.declare(id="x", type="lasp_gset", n_elems=4)
    rt2 = ReplicatedRuntime(store, Graph(store), 64, ring(64, 2))
    with pytest.raises(ValueError, match="shift-structured"):
        rt2.shard(_mesh(), axis="replicas", partition=True)


def test_engine_step_partition_cleared_by_resize():
    from lasp_tpu.mesh.topology import random_regular

    rt, nn, s = _partitioned_runtime(n=64)
    rt.shard(_mesh(), axis="replicas", partition=True)
    rt.run_to_convergence(max_rounds=32)
    assert rt._partition is not None
    rt.resize(72, random_regular(72, 3, seed=9))
    assert rt._partition is None  # plan was topology-specific
    rt.run_to_convergence(max_rounds=64)  # gather path serves again
    assert rt.divergence(s) == 0


def test_failed_partition_reshard_leaves_runtime_intact():
    # r5 review: a REJECTED partition re-shard must not leave re-sharded
    # states bound to a previous mesh's stale plan — validation runs
    # before any state moves
    import pytest

    rt, nn, s = _partitioned_runtime(n=64)
    mesh = _mesh()
    rt.shard(mesh, axis="replicas", partition=True)
    rt.run_to_convergence(max_rounds=32)
    plan_before = rt._partition["plan"]
    with pytest.raises(ValueError, match="not (found )?in mesh"):
        # jax's NamedSharding validation or our plan validation — either
        # way the runtime must be left exactly as it was
        rt.shard(mesh, axis="no_such_axis", partition=True)
    assert rt._partition is not None
    assert rt._partition["plan"] is plan_before  # untouched
    rt.run_to_convergence(max_rounds=32)  # still serves
    assert rt.divergence(s) == 0


# -- per-destination (all-to-all) exchange ------------------------------------

@pytest.mark.parametrize("seed", [3, 9])
def test_alltoall_rounds_equal_dense(seed):
    R, S = 256, 8
    mesh = _mesh()
    _, nn = locality_order(scale_free(R, 3, seed=seed))
    plan = partitioned_gossip_plan(nn, S)
    assert plan["m2"] <= plan["m"]  # per-destination never exceeds union
    spec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    rng = np.random.RandomState(seed)
    states = replicate(PackedORSet.new(spec), R)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(R, spec.n_elems, spec.n_words)),
            dtype=jnp.uint32,
        )
    )
    sharded = _put(states, mesh)
    got, _ = partitioned_gossip_rounds(
        PackedORSet, spec, sharded, mesh, plan, 3, mode="alltoall"
    )
    ref = states
    for _ in range(3):
        ref = gossip_round(PackedORSet, spec, ref, jnp.asarray(nn))
    assert jnp.array_equal(got.exists, ref.exists)
    assert jnp.array_equal(got.removed, ref.removed)


def test_alltoall_hlo_ships_per_destination_slices():
    from lasp_tpu.mesh.shard_gossip import partition_tables

    R, S = 256, 8
    mesh = _mesh()
    _, nn = locality_order(scale_free(R, 3, seed=3))
    plan = partitioned_gossip_plan(nn, S)
    spec = GSetSpec(n_elems=16)
    states = _put(replicate(GSet.new(spec), R), mesh)
    send_idx, idx = partition_tables(plan, mesh, mode="alltoall")
    fn = jax.jit(partitioned_gossip_round_fn(GSet, spec, mesh, plan,
                                             mode="alltoall"))
    hlo = fn.lower(states, send_idx, idx).compile().as_text()
    tups = re.findall(r"= \(([^)]*)\)[^=]*all-to-all\(", hlo)
    assert tups, "alltoall mode must lower to an all-to-all"
    for tup in tups:
        for _dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", tup):
            lead = [int(d) for d in dims.split(",") if d]
            # every piece is ONE destination's slice: m2 rows, never the
            # union buffer and never the population
            rows = lead[1] if len(lead) > 1 else lead[0]
            assert rows <= plan["m2"], dims
    assert "all-gather" not in hlo


def test_engine_step_alltoall_mode():
    rt, nn, s = _partitioned_runtime()
    ref, _nn, _s = _partitioned_runtime()
    rt.shard(_mesh(), axis="replicas", partition=True,
             partition_mode="alltoall")
    assert rt._partition["mode"] == "alltoall"
    # the DEFAULT mode's wire bound holds on the FULL compiled step,
    # not just the side round fn (docs/PERF.md claims exactly this)
    tables = rt._ensure_step()
    hlo = (
        jax.jit(rt._step_pure)
        .lower(rt.states, rt.neighbors, None, tables)
        .compile()
        .as_text()
    )
    assert "all-gather" not in hlo
    m2 = rt._partition["plan"]["m2"]
    for tup in re.findall(r"= \(([^)]*)\)[^=]*all-to-all\(", hlo):
        for _dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", tup):
            lead = [int(d) for d in dims.split(",") if d]
            rows = lead[1] if len(lead) > 1 else lead[0]
            assert rows <= m2, dims
    rt.run_to_convergence(max_rounds=64)
    ref.run_to_convergence(max_rounds=64)
    assert rt.divergence(s) == 0
    assert rt.coverage_value(s) == ref.coverage_value(s)
    assert rt.coverage_value("out") == ref.coverage_value("out")


def test_unknown_partition_mode_is_loud():
    rt, _nn, _s = _partitioned_runtime(n=64)
    with pytest.raises(ValueError, match="partition_mode"):
        rt.shard(_mesh(), axis="replicas", partition=True,
                 partition_mode="broadcast")


def test_engine_step_partitioned_joint_slices_layout():
    # the canonical build_mesh (slices, replicas) layout — the pod
    # deployment shape — takes the boundary exchange too: axis=None
    # resolves to the joint axes, and convergence matches unsharded
    from lasp_tpu.mesh.comm import build_mesh

    rt, nn, s = _partitioned_runtime(n=256)
    ref, _nn, _s = _partitioned_runtime(n=256)
    mesh = build_mesh(slice_of=lambda d: d.id % 2)  # fake 2 DCN slices
    assert mesh.shape["slices"] == 2
    rt.shard(mesh, partition=True)
    assert rt._partition["axis"] == ("slices", "replicas")
    assert rt._partition["plan"]["n_shards"] == 8
    rt.run_to_convergence(max_rounds=64)
    ref.run_to_convergence(max_rounds=64)
    assert rt.divergence(s) == 0
    assert rt.coverage_value(s) == ref.coverage_value(s)
    assert rt.coverage_value("out") == ref.coverage_value("out")


def test_read_until_and_checkpoint_under_partition(tmp_path):
    # the device-parked blocking read and the checkpoint round-trip both
    # ride the compiled step — they must keep working when the step runs
    # the boundary exchange
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import load_runtime, save_runtime

    rt, nn, s = _partitioned_runtime(n=64)
    rt.shard(_mesh(), axis="replicas", partition=True)
    # a write lands at row 0; a reader at a far row blocks until gossip
    # delivers it through the exchange
    rt.update_at(0, s, ("add", "blocking"), "w9")
    row = rt.read_until(
        40, s, Threshold(rt.states[s].__class__(
            exists=rt.states[s].exists[40] * 0,
            removed=rt.states[s].removed[40] * 0,
        ), strict=True),
        max_rounds=64,
    )
    assert row is not None
    rt.run_to_convergence(max_rounds=64)
    want = rt.coverage_value(s)
    assert "blocking" in want
    # checkpoint the partition-sharded runtime and restore it fresh
    path = str(tmp_path / "part_rt.log")
    save_runtime(rt, path)
    restored = load_runtime(path)
    restored.run_to_convergence(max_rounds=64)
    assert restored.coverage_value(s) == want
    # the restored runtime re-shards and keeps converging
    restored.shard(_mesh(), axis="replicas", partition=True)
    restored.update_at(3, s, ("add", "post-restore"), "w10")
    restored.run_to_convergence(max_rounds=64)
    assert restored.coverage_value(s) == want | {"post-restore"}
