"""Frontier (delta) gossip property tier: dirty-set scheduling must be
BIT-IDENTICAL to dense gossip — same fixed point AND same per-round
states — across codecs, edge_mask failure injection, and shard
boundaries (ISSUE-3 acceptance). The frontier's whole soundness
argument is one invariant: the scheduled row set is always a superset
of the rows that round could change; these tests check the consequence
directly instead of trusting the argument."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import GCounter, GCounterSpec, GSet, GSetSpec, ORSWOT, ORSWOTSpec
from lasp_tpu.lattice.base import replicate
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.mesh.gossip import (
    frontier_reach,
    gossip_round,
    gossip_round_rows,
)
from lasp_tpu.mesh.topology import edge_failure_mask
from lasp_tpu.ops import PackedORSet, PackedORSetSpec
from lasp_tpu.ops.fused import fused_frontier_rounds, fused_gossip_rounds_count
from lasp_tpu.store import Store


def _tree_eq(a, b) -> bool:
    flags = jax.tree_util.tree_map(
        lambda x, y: bool(jnp.array_equal(x, y)), a, b
    )
    return all(jax.tree_util.tree_leaves(flags))


def _seed_cases(n):
    """(codec, spec, states, dirty_rows) per codec family: a handful of
    rows carry non-bottom state (the client-write shape)."""
    rng = np.random.RandomState(3)
    rows = np.unique(rng.randint(0, n, size=max(2, n // 20)))
    cases = []

    gspec = GSetSpec(n_elems=16)
    g = replicate(GSet.new(gspec), n)
    g = g._replace(
        mask=g.mask.at[jnp.asarray(rows), jnp.asarray(rows % 16)].set(True)
    )
    cases.append(("lasp_gset", GSet, gspec, g, rows))

    cspec = GCounterSpec(n_actors=8)
    c = replicate(GCounter.new(cspec), n)
    c = c._replace(
        counts=c.counts.at[jnp.asarray(rows), jnp.asarray(rows % 8)].set(
            jnp.asarray((rows % 5 + 1).astype(np.int32))
        )
    )
    cases.append(("riak_dt_gcounter", GCounter, cspec, c, rows))

    ospec = ORSWOTSpec(n_elems=8, n_actors=8)
    o = replicate(ORSWOT.new(ospec), n)
    for i, r in enumerate(rows):
        row = jax.tree_util.tree_map(lambda x: x[int(r)], o)
        row = ORSWOT.add(ospec, row, int(r) % 8, int(r) % 8)
        if i % 2:  # some removes too: dots churn under equal clocks
            row = ORSWOT.add(ospec, row, (int(r) + 1) % 8, int(r) % 8)
            row = ORSWOT.remove(ospec, row, int(r) % 8)
        o = jax.tree_util.tree_map(
            lambda x, v: x.at[int(r)].set(v), o, row
        )
    cases.append(("riak_dt_orswot", ORSWOT, ospec, o, rows))

    pspec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    p = replicate(PackedORSet.new(pspec), n)
    p = jax.vmap(
        lambda i, s: PackedORSet.add(
            pspec, s, i % pspec.n_elems, i % pspec.n_actors
        )
    )(jnp.asarray(rows), jax.tree_util.tree_map(lambda x: x[rows], p))
    base = replicate(PackedORSet.new(pspec), n)
    p = jax.tree_util.tree_map(
        lambda full, sub: full.at[jnp.asarray(rows)].set(sub), base, p
    )
    cases.append(("lasp_orset(packed)", PackedORSet, pspec, p, rows))
    return cases


@pytest.mark.parametrize("masked", [False, True])
def test_rows_kernel_bit_identical_per_round(masked):
    """gossip_round_rows over the frontier-reach set reproduces every
    dense round exactly, for every codec family, to the fixed point."""
    n, k = 96, 3
    nbrs_np = random_regular(n, k, seed=1)
    nbrs = jnp.asarray(nbrs_np)
    mask_np = edge_failure_mask(n, k, 0.3, seed=2) if masked else None
    mask = jnp.asarray(mask_np) if masked else None
    for name, codec, spec, states, rows in _seed_cases(n):
        dense = states
        sparse = states
        frontier = np.zeros(n, dtype=bool)
        frontier[rows] = True
        for rnd in range(64):
            new_dense = gossip_round(codec, spec, dense, nbrs, mask)
            reach = frontier_reach(frontier, nbrs_np)
            if masked:
                reach = (
                    frontier[nbrs_np] & np.asarray(mask_np)
                ).any(axis=1)
            idx = np.flatnonzero(reach)
            if idx.size:
                sparse, changed = gossip_round_rows(
                    codec, spec, sparse, nbrs, jnp.asarray(idx), mask
                )
                frontier = np.zeros(n, dtype=bool)
                frontier[idx[np.asarray(changed)]] = True
            else:
                frontier = np.zeros(n, dtype=bool)
            assert _tree_eq(new_dense, sparse), (name, rnd)
            quiescent = _tree_eq(dense, new_dense)
            dense = new_dense
            if quiescent:
                assert not frontier.any(), name  # frontier agrees: done
                break
        else:
            pytest.fail(f"{name}: no convergence in 64 rounds")


def test_rows_kernel_accepts_duplicate_padding():
    n = 32
    nbrs = jnp.asarray(random_regular(n, 3, seed=5))
    _nm, codec, spec, states, rows = _seed_cases(n)[0]
    ref = gossip_round(codec, spec, states, nbrs)
    all_rows = np.arange(n)
    padded = np.concatenate([all_rows, all_rows[:7]])  # duplicates
    out, _ = gossip_round_rows(codec, spec, states, nbrs, jnp.asarray(padded))
    assert _tree_eq(ref, out)


def test_fused_frontier_rounds_matches_dense_and_early_exits():
    n = 64
    nbrs = jnp.asarray(random_regular(n, 3, seed=7))
    _nm, codec, spec, states, rows = _seed_cases(n)[0]
    f0 = jnp.zeros(n, dtype=bool).at[jnp.asarray(rows)].set(True)
    budget = 50
    out_f, f_end, prod = fused_frontier_rounds(
        codec, spec, states, nbrs, f0, budget
    )
    out_d, prod_d = fused_gossip_rounds_count(
        codec, spec, states, nbrs, budget
    )
    assert _tree_eq(out_f, out_d)
    assert not bool(jnp.any(f_end))
    # early exit: productive rounds + the frontier-emptying round, far
    # under the budget (dense fori always burns all 50)
    assert int(prod) <= int(prod_d) + 1 < budget

    # empty frontier: zero rounds, states untouched
    out0, f0_end, prod0 = fused_frontier_rounds(
        codec, spec, states, nbrs, jnp.zeros(n, bool), budget
    )
    assert int(prod0) == 0 and _tree_eq(out0, states)


@pytest.mark.parametrize("topology", ["random", "ring"])
@pytest.mark.parametrize("crossover", [0.25, 0.0])
def test_runtime_frontier_vs_dense_bit_identical(topology, crossover):
    """Engine-level property: frontier_step and step produce identical
    per-round states, residuals, and round counts — including when the
    crossover forces every frontier round onto the dense per-var arm
    (crossover=0)."""
    # ring diameter is n/2: keep it small enough for the round cap
    n = 128 if topology == "random" else 48
    nbrs = (
        random_regular(n, 3, seed=11) if topology == "random" else ring(n, 2)
    )

    def build():
        store = Store(n_actors=4)
        v1 = store.declare(id="a", type="lasp_gset", n_elems=16)
        v2 = store.declare(id="b", type="riak_dt_gcounter", n_actors=4)
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
        rng = np.random.RandomState(2)
        rows = rng.choice(n, 6, replace=False)
        rt.update_batch(
            v1, [(int(r), ("add", f"e{r % 4}"), f"c{r}") for r in rows]
        )
        rt.update_batch(v2, [(int(rows[0]), ("increment", 3), "w0")])
        return rt, (v1, v2)

    rt_f, ids = build()
    rt_f.frontier_crossover = crossover
    rt_d, _ = build()
    for rnd in range(64):
        rf, rd = rt_f.frontier_step(), rt_d.step()
        assert rf == rd, rnd
        for v in ids:
            assert _tree_eq(rt_f.states[v], rt_d.states[v]), (v, rnd)
        if rd == 0:
            break
    else:
        pytest.fail("no convergence")
    assert all(rt_f.divergence(v) == 0 for v in ids)
    # the skipped-var accounting: variable "b" quiesces rounds before
    # "a"; its empty frontier must have produced skip events
    from lasp_tpu.telemetry import events as tel_events

    assert any(
        r["etype"] == "frontier_skip" for r in tel_events.events()
    )


def test_run_to_convergence_modes_agree():
    n = 96

    def build():
        store = Store(n_actors=4)
        v = store.declare(id="a", type="lasp_gset", n_elems=8)
        rt = ReplicatedRuntime(
            store, Graph(store), n, random_regular(n, 3, seed=4)
        )
        rt.update_batch(v, [(5, ("add", "x"), "c5"), (40, ("add", "y"), "c40")])
        return rt, v

    rt_f, v = build()
    rt_d, _ = build()
    rounds_f = rt_f.run_to_convergence(mode="frontier")
    rounds_d = rt_d.run_to_convergence(block=4)
    assert rounds_f == rounds_d
    assert _tree_eq(rt_f.states[v], rt_d.states[v])


def test_frontier_with_edge_mask_matches_dense():
    n, k = 96, 3
    nbrs = random_regular(n, k, seed=9)
    mask = jnp.asarray(edge_failure_mask(n, k, 0.4, seed=1))

    def build():
        store = Store(n_actors=4)
        v = store.declare(id="a", type="lasp_gset", n_elems=8)
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
        rt.update_batch(v, [(0, ("add", "x"), "c0"), (70, ("add", "y"), "c70")])
        return rt, v

    rt_f, v = build()
    rt_d, _ = build()
    for _ in range(64):
        rf, rd = rt_f.frontier_step(mask), rt_d.step(mask)
        assert rf == rd
        assert _tree_eq(rt_f.states[v], rt_d.states[v])
        if rd == 0:
            return
    pytest.fail("no fixed point under the static mask")


def test_frontier_mode_refuses_edges_and_triggers():
    store = Store(n_actors=4)
    g = Graph(store)
    v = store.declare(id="a", type="lasp_gset", n_elems=8)
    g.map(v, lambda x: x, dst="out", dst_elems=8)
    rt = ReplicatedRuntime(store, g, 16, ring(16, 2))
    with pytest.raises(RuntimeError, match="edges / triggers"):
        rt.frontier_step()
    with pytest.raises(RuntimeError, match="frontier gossip unavailable"):
        rt.run_to_convergence(mode="frontier")
    # auto falls back to dense and still converges
    assert rt.run_to_convergence(mode="auto") >= 1


def test_packed_mode_frontier():
    """Packed wire-format populations ride the same sparse kernels (the
    flat codec is leafwise-or)."""
    n = 64

    def build():
        store = Store(n_actors=4)
        v = store.declare(
            id="s", type="lasp_orset", n_elems=8, n_actors=4,
            tokens_per_actor=2,
        )
        rt = ReplicatedRuntime(
            store, Graph(store), n, random_regular(n, 3, seed=6),
            packed=True,
        )
        rt.update_batch(
            v, [(3, ("add", "p"), "w3"), (50, ("add", "q"), "w50")]
        )
        return rt, v

    rt_f, v = build()
    rt_d, _ = build()
    rounds_f = rt_f.run_to_convergence(mode="frontier")
    rounds_d = rt_d.run_to_convergence()
    assert rounds_f == rounds_d
    assert _tree_eq(rt_f.states[v], rt_d.states[v])
    assert rt_f.coverage_value(v) == {"p", "q"}


def test_resize_degrades_frontier_conservatively():
    """Fresh bottom rows must catch up from QUIESCENT peers — only the
    all-dirty degrade on resize makes that reachable for the frontier
    scheduler."""
    n = 48
    store = Store(n_actors=4)
    v = store.declare(id="a", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), n, random_regular(n, 3, seed=8))
    rt.update_batch(v, [(0, ("add", "x"), "c0")])
    rt.run_to_convergence(mode="frontier")
    assert rt.frontier_size(v) == 0
    rt.resize(n + 16, random_regular(n + 16, 3, seed=9))
    assert rt.frontier_size(v) == n + 16  # all-dirty
    rt.run_to_convergence(mode="frontier")
    assert rt.divergence(v) == 0
    assert rt.replica_value(v, n + 15) == {"x"}  # the new row caught up


def test_mark_dirty_after_direct_state_surgery():
    n = 32
    store = Store(n_actors=4)
    v = store.declare(id="a", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), n, random_regular(n, 3, seed=2))
    rt.run_to_convergence(mode="frontier")  # quiescent, empty frontiers
    st = rt.states[v]
    rt.states[v] = st._replace(mask=st.mask.at[7, 3].set(True))
    rt.mark_dirty(v, [7])
    rt.run_to_convergence(mode="frontier")
    assert rt.divergence(v) == 0
    assert rt.coverage_value(v) == rt.replica_value(v, 0)


def test_fused_frontier_rounds_across_shard_boundaries():
    """Shard-boundary arm of the equivalence property: the device-side
    frontier block on a population sharded over the 8-device CPU mesh
    lands the same states as the dense rounds on unsharded state."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = 128
    n_dev = len(jax.devices())
    nbrs = jnp.asarray(random_regular(n, 3, seed=3))
    _nm, codec, spec, states, rows = _seed_cases(n)[0]
    ref, _prod = fused_gossip_rounds_count(codec, spec, states, nbrs, 32)

    mesh = Mesh(np.array(jax.devices()), ("replicas",))
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sh), states
    )
    f0 = jnp.zeros(n, bool).at[jnp.asarray(rows)].set(True)
    out, f_end, _prod2 = jax.jit(
        lambda s, f: fused_frontier_rounds(codec, spec, s, nbrs, f, 32)
    )(sharded, jax.device_put(f0, NamedSharding(mesh, P("replicas"))))
    assert _tree_eq(out, ref)
    assert not bool(jnp.any(f_end))


def test_shard_frontier_counts():
    from lasp_tpu.mesh.shard_gossip import shard_frontier_counts

    f = np.zeros(64, bool)
    f[[0, 1, 17, 63]] = True
    counts = shard_frontier_counts(f, 4)
    assert counts.tolist() == [2, 1, 0, 1]
    assert shard_frontier_counts(f, 3).sum() == 4  # ragged tail folds in


def test_mask_change_degrades_frontier():
    """Quiescence under failure injection is only a fixed point of the
    MASKED graph: lifting (or changing) the mask must degrade every
    frontier to all-dirty, or a later frontier run falsely reports
    convergence while mask-separated replicas still diverge (the
    review-confirmed repro: dead-mask converge -> unmasked frontier run
    returned 1 with divergence intact)."""
    n, k = 8, 1
    store = Store(n_actors=4)
    v = store.declare(id="a", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, k))
    rt.update_at(0, v, ("add", "x"), "w0")
    dead = jnp.zeros((n, k), dtype=bool)  # total partition
    assert rt.run_to_convergence(edge_mask=dead) == 1  # masked fixed point
    assert rt.divergence(v) == n - 1  # nothing delivered
    # partition heals: the unmasked frontier run must deliver everywhere
    rounds = rt.run_to_convergence(mode="frontier")
    assert rt.divergence(v) == 0
    assert rounds >= 2
    assert rt.coverage_value(v) == {"x"} == rt.replica_value(v, n - 1)


def test_crash_checkpoint_restore_frontier_chaos_path(tmp_path):
    """The chaos extension of the mask-tagging regression: a replica
    crashed mid-soak and restored from a ``store/checkpoint.py``
    runtime snapshot must degrade every frontier to all-dirty and still
    drive the population to the DENSE fixed point — stale checkpoint
    rows (including a token the survivors have since tombstoned) are
    caught up / overruled by gossip, with no resurrection."""
    from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Restore
    from lasp_tpu.store import save_runtime

    n = 48
    nbrs = random_regular(n, 3, seed=13)
    store = Store(n_actors=8)
    v = store.declare(id="s", type="lasp_orset", n_elems=8, n_actors=8,
                      tokens_per_actor=2)
    rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
    rt.update_at(5, v, ("add", "keep"), "w5")
    rt.update_at(5, v, ("add", "gone"), "w5")
    rt.run_to_convergence(mode="frontier")
    path = str(tmp_path / "soak.ck")
    save_runtime(rt, path)
    # post-snapshot divergence: a remove the checkpoint row never saw,
    # plus a fresh element the crashed replica must learn on return
    rt.update_at(5, v, ("remove", "gone"), "w5")
    rt.update_at(7, v, ("add", "new"), "w7")
    sched = ChaosSchedule(
        n, nbrs, [Crash(1, 5), Restore(4, 5, source="checkpoint")],
        seed=2,
    )
    ch = ChaosRuntime(rt, sched, checkpoint=path)
    rep = ch.soak(mode="frontier", max_rounds=200)
    assert rep["healed"] and rep["restores"] == 1
    # the restore degraded row knowledge: frontier runs reached the
    # dense fixed point anyway
    assert rt.divergence(v) == 0
    assert rt.coverage_value(v) == {"keep", "new"}
    assert rt.replica_value(v, 5) == {"keep", "new"}
    # a dense twin driven through the same schedule lands the same state
    store2 = Store(n_actors=8)
    v2 = store2.declare(id="s", type="lasp_orset", n_elems=8, n_actors=8,
                        tokens_per_actor=2)
    rt2 = ReplicatedRuntime(store2, Graph(store2), n, nbrs)
    rt2.update_at(5, v2, ("add", "keep"), "w5")
    rt2.update_at(5, v2, ("add", "gone"), "w5")
    rt2.run_to_convergence()
    rt2.update_at(5, v2, ("remove", "gone"), "w5")
    rt2.update_at(7, v2, ("add", "new"), "w7")
    ch2 = ChaosRuntime(rt2, ChaosSchedule(
        n, nbrs, [Crash(1, 5), Restore(4, 5, source="checkpoint")],
        seed=2,
    ), checkpoint=path)
    ch2.soak(mode="dense")
    assert _tree_eq(rt.states[v], rt2.states[v2])


def test_probe_reports_frontier_cut_rows():
    """A dense-scheduled partitioned runtime still maintains frontier
    masks; the monitor probe reports dirty ∩ cut (the exchange-waste
    signal)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    from jax.sharding import Mesh

    from lasp_tpu.telemetry import get_monitor, reset

    n = 128
    n_dev = len(jax.devices())
    store = Store(n_actors=4)
    v = store.declare(id="a", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(
        store, Graph(store), n, random_regular(n, 3, seed=4)
    )
    rt.update_at(0, v, ("add", "x"), "w0")
    rt.shard(
        Mesh(np.array(jax.devices()), ("replicas",)),
        axis="replicas", partition=True,
    )
    try:
        probe = get_monitor().probe(rt)
        assert "frontier_cut_rows" in probe and "cut_rows" in probe
        assert 0 <= probe["frontier_cut_rows"] <= probe["cut_rows"] + n_dev
    finally:
        # the probe stamped per-shard gauges for THIS test's 8-shard
        # layout into the process-global registry; exact-series
        # assertions elsewhere (test_convergence's probe test) must not
        # see them
        reset()


def test_frontier_cut_rows():
    from lasp_tpu.mesh.shard_gossip import (
        frontier_cut_rows,
        partitioned_gossip_plan,
    )
    from lasp_tpu.mesh.topology import locality_order, scale_free

    n, s = 128, 4
    _perm, nbrs = locality_order(scale_free(n, 3, seed=2))
    plan = partitioned_gossip_plan(nbrs, s)
    full = np.ones(n, bool)
    # every cut row dirty (pad aliasing can only add shard-row-0 dups,
    # bounded by the shard count)
    hi = frontier_cut_rows(full, plan)
    assert plan["stats"]["send_rows"] <= hi <= plan["stats"]["send_rows"] + s
    assert frontier_cut_rows(np.zeros(n, bool), plan) == 0
