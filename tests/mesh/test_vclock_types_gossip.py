"""Mesh-level gossip for the vclock-bearing types (riak_dt_orswot,
riak_dt_map): convergence to the join of all writes, remove-wins-over-
concurrent-stale semantics, permutation invariance of the gossip
schedule, and the ReplicatedRuntime path end-to-end. Extends the
determinism suite (SURVEY §5) beyond the single-replica lattice tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import ORSWOT, ORSWOTSpec, replicate
from lasp_tpu.mesh import (
    ReplicatedRuntime,
    converged,
    gossip_round,
    join_all,
    random_regular,
    ring,
)
from lasp_tpu.store import Store


def _seeded_orswot_population(n=16, e=8):
    """Each replica adds one element under ITS OWN actor. Actor identity
    must be writer-unique: riak_dt actors are replica identities, and two
    replicas minting dots under one actor produce colliding counters that
    the vclock-domination rule reads as observed-and-removed (the same
    constraint the reference inherits from riak_dt_orswot)."""
    spec = ORSWOTSpec(n_elems=e, n_actors=n)
    states = replicate(ORSWOT.new(spec), n)

    def seed(i, st):
        return ORSWOT.add(spec, st, i % e, i)

    states = jax.vmap(seed)(jnp.arange(n), states)
    return spec, states


def test_orswot_gossip_converges_to_join():
    spec, states = _seeded_orswot_population()
    nbrs = jnp.asarray(random_regular(16, 3, seed=13))
    s = states
    for _ in range(12):
        s = gossip_round(ORSWOT, spec, s, nbrs)
    assert bool(converged(ORSWOT, spec, s))
    top = join_all(ORSWOT, spec, states)
    live = np.asarray(ORSWOT.value(spec, top))
    assert live[: min(16, 8)].all()  # every added element survives the join


def test_orswot_observed_remove_wins_over_stale_add():
    """A remove that OBSERVED the add must beat the stale add when the
    two replicas merge (the no-tombstone ORSWOT rule, lattice/dots.py)."""
    spec = ORSWOTSpec(n_elems=4, n_actors=2)
    a = ORSWOT.add(spec, ORSWOT.new(spec), 0, 0)
    b = a  # replica b observed the add...
    b = ORSWOT.remove(spec, b, 0)  # ...then removed it
    merged = ORSWOT.merge(spec, a, b)
    assert not bool(ORSWOT.value(spec, merged)[0])
    # but a CONCURRENT re-add under a fresh dot survives the remove
    a2 = ORSWOT.add(spec, a, 0, 1)
    merged2 = ORSWOT.merge(spec, a2, b)
    assert bool(ORSWOT.value(spec, merged2)[0])


def test_orswot_gossip_schedule_permutation_invariant():
    spec, states = _seeded_orswot_population()
    results = []
    for seed in (1, 2, 3):
        nbrs = jnp.asarray(random_regular(16, 3, seed=seed))
        s = states
        for _ in range(14):
            s = gossip_round(ORSWOT, spec, s, nbrs)
        assert bool(converged(ORSWOT, spec, s))
        top = join_all(ORSWOT, spec, s)
        results.append(np.asarray(ORSWOT.value(spec, top)))
    assert (results[0] == results[1]).all()
    assert (results[1] == results[2]).all()


def test_runtime_orswot_and_map_end_to_end():
    """ORSWOT + CRDT-Map variables through the full ReplicatedRuntime:
    client ops at different replicas, gossip to the fixed point, decoded
    values match the reference semantics."""
    store = Store(n_actors=4)
    graph = Graph(store)
    sw = store.declare(id="sw", type="riak_dt_orswot", n_elems=8, n_actors=4)
    mp = store.declare(
        id="mp",
        type="riak_dt_map",
        fields=[("tags", "lasp_gset", {"n_elems": 4}),
                ("hits", "riak_dt_gcounter", {})],
        n_actors=4,
    )
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_at(0, sw, ("add", "x"), "w0")
    rt.update_at(3, sw, ("add", "y"), "w1")
    rt.update_at(5, mp, ("update", "tags", ("add", "t1")), "w0")
    rt.update_at(6, mp, ("update", "hits", ("increment", 3)), "w1")
    rt.run_to_convergence(block=4)
    assert rt.coverage_value(sw) == {"x", "y"}
    assert rt.coverage_value(mp) == {"tags": frozenset({"t1"}), "hits": 3}
    assert rt.divergence(sw) == 0 and rt.divergence(mp) == 0
    # causal remove after convergence propagates everywhere
    rt.update_at(2, sw, ("remove", "x"), "w0")
    rt.run_to_convergence(block=4)
    assert rt.coverage_value(sw) == {"y"}
    assert rt.divergence(sw) == 0
