"""Mesh-level statem: random client ops + gossip rounds + edge failures +
membership changes against an OP-BASED model — the randomized integration
tier above the per-CRDT and store statems (the role of the reference's
riak_test multi-node suites, with the sleeps replaced by exact
round-by-round state prediction).

Model: each replica row is the SET OF OPERATIONS it has observed; a pull
round unions each row's set with its (unmasked) neighbors' pre-round
sets — valid because every CRDT here is a join of its op history:

- OR-Set: an add op carries a unique id; a remove kills exactly the add
  ops of that element VISIBLE at the removing row (the reference
  tombstones the tokens present at the replica, live or already dead);
  value = adds seen and not killed by any seen remove.
- G-Counter: value = number of increments seen, summed over actors
  (per-actor lanes merge by max, and a row's own increments are
  cumulative, so seen-count == max-merged lane value under the one-home
  actor discipline — which debug_actors enforces as a bonus here).
- OR-SWOT (the vclock family): the SAME op model as the OR-Set —
  add-wins observe-remove is add-wins observe-remove whether the
  implementation carries tombstoned tokens or vclock-dominated dots;
  a remove kills the adds visible at the removing row, a concurrent
  (unseen) add survives the merge.
- riak_dt_map (round 5, BOTH re-add modes, schemaless dynamic fields):
  field updates mint presence-touch ops; a field remove kills the
  touches visible at the removing row (presence = any unkilled touch —
  the ORSWOT dot rule). Contents: in default mode content ops are
  join-monotone (a remove kills presence only); in reset_on_readd mode
  the remove ALSO kills the content ops visible at the remover — which
  is exactly riak_dt reset-remove (observed OR-Set tokens tombstone,
  observed counter increments floor away; a concurrent unseen update
  survives). One kill rule models tokens, dots, AND floors, because
  each actor's increments spread as nested prefixes under the one-home
  discipline.

Membership mirrors resize: joins start empty; graceful leaves hand the
departing rows' op sets to surviving row 0; crash leaves drop them.
Actor discipline follows the riak_dt incarnation rule the debug guard
enforces: writer names are per-(row, membership-generation), never
reused across resizes — an earlier version of this statem reused
``a{r}`` across incarnations and caught real silent token-reuse loss
(now a guarded ActorCollisionError; see test_actor_guard.py)."""

import os
import random

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import random_regular, ring
from lasp_tpu.store import Store
from lasp_tpu.utils.interning import CapacityError

N_SEEDS = int(os.environ.get("LASP_STATEM_SEEDS", "6"))
N_OPS = int(os.environ.get("LASP_STATEM_OPS", "50"))
ELEMS = ["a", "b", "c", "d", "e", "f"]
MAX_R = 16
#: per-(row, generation) actor names scale with the op budget (a
#: membership change per ~7 ops mints a fresh generation of writers)
N_ACTORS = max(256, N_OPS)


class MeshModel:
    def __init__(self, n, neighbors):
        self.n = n
        self.neighbors = np.asarray(neighbors)
        self.seen = [set() for _ in range(n)]
        self.next_id = 0

    def add(self, row, elem, var="s"):
        op = ("add", self.next_id, elem, var)
        self.next_id += 1
        self.seen[row].add(op)

    def member(self, row, elem, var="s") -> bool:
        return any(
            o[0] == "add" and o[2] == elem and o[3] == var
            for o in self.seen[row]
        )

    def remove(self, row, elem, var="s"):
        killed = frozenset(
            o[1] for o in self.seen[row]
            if o[0] == "add" and o[2] == elem and o[3] == var
        )
        op = ("rm", self.next_id, killed)
        self.next_id += 1
        self.seen[row].add(op)

    def increment(self, row, by):
        op = ("inc", self.next_id, by)
        self.next_id += 1
        self.seen[row].add(op)

    def step(self, edge_mask=None):
        prev = [set(s) for s in self.seen]
        for r in range(self.n):
            for k in range(self.neighbors.shape[1]):
                if edge_mask is not None and not edge_mask[r, k]:
                    continue
                self.seen[r] |= prev[int(self.neighbors[r, k])]

    def converge(self):
        for _ in range(self.n + 2):
            before = [len(s) for s in self.seen]
            self.step()
            if [len(s) for s in self.seen] == before:
                return
        raise AssertionError("model failed to converge")

    @staticmethod
    def orset_of(seen: set, var="s") -> frozenset:
        killed = set()
        for o in seen:
            if o[0] == "rm":
                killed |= o[2]
        return frozenset(
            o[2] for o in seen
            if o[0] == "add" and o[3] == var and o[1] not in killed
        )

    @staticmethod
    def counter_of(seen: set) -> int:
        return sum(o[2] for o in seen if o[0] == "inc")

    # -- riak_dt_map (composed fields under presence dots; keys are key
    # PATHS — tuples — so nested submaps model with the same ops) ------------
    def map_update(self, row, var, path, content):
        """One (possibly nested) field update: a presence touch for EVERY
        prefix of ``path`` plus a content op at ``path``. ``content``:
        ("madd", elem) or ("minc", by)."""
        for i in range(1, len(path) + 1):
            self.seen[row].add(("mtouch", self.next_id, var, path[:i]))
            self.next_id += 1
        self.seen[row].add((content[0], self.next_id, var, path, content[1]))
        self.next_id += 1

    @staticmethod
    def _killed(seen) -> set:
        out: set = set()
        for o in seen:
            if o[0] == "mkill":
                out |= o[2]
        return out

    def map_present(self, row, var, path) -> bool:
        seen = self.seen[row]
        killed = self._killed(seen)
        return any(
            o[0] == "mtouch" and o[2] == var and o[3] == path
            and o[1] not in killed
            for o in seen
        )

    def map_remove(self, row, var, path, reset: bool):
        """Remove the field at ``path``: kill the touches observed AT the
        path; in reset mode also kill everything observed BELOW it
        (touches + content ops — riak_dt's recursive reset-remove). In
        default mode only the path's own presence dies: nested
        sub-presences survive hidden and resurface on re-add, exactly
        like the dense encoding's outer-dots-only removal."""
        seen = self.seen[row]
        n = len(path)
        # an INNER remove rides {update, OuterKey, {remove, InnerKey}}:
        # the engine MINTS a fresh presence dot on every ancestor on the
        # way down (the update path touches), so the model must too —
        # an inner remove resurrects a previously-removed ancestor
        for i in range(1, n):
            self.seen[row].add(("mtouch", self.next_id, var, path[:i]))
            self.next_id += 1
        if reset:
            killed = frozenset(
                o[1] for o in seen
                if o[0] in ("mtouch", "madd", "minc") and o[2] == var
                and o[3][:n] == path
            )
        else:
            killed = frozenset(
                o[1] for o in seen
                if o[0] == "mtouch" and o[2] == var and o[3] == path
            )
        self.seen[row].add(("mkill", self.next_id, killed))
        self.next_id += 1

    def map_value(self, row, var) -> dict:
        seen = self.seen[row]
        killed = self._killed(seen)
        visible = {
            o[3]
            for o in seen
            if o[0] == "mtouch" and o[2] == var and o[1] not in killed
        }
        # ancestor visibility is enforced structurally: build() recurses
        # only through prefixes that are themselves visible

        def build(prefix) -> dict:
            out: dict = {}
            depth = len(prefix) + 1
            for path in visible:
                if len(path) != depth or path[: len(prefix)] != prefix:
                    continue
                key = path[-1]
                if key[1] == "riak_dt_map":
                    out[key] = build(path)
                elif key[1] == "riak_dt_gcounter":
                    out[key] = sum(
                        o[4] for o in seen
                        if o[0] == "minc" and o[2] == var and o[3] == path
                        and o[1] not in killed
                    )
                else:
                    out[key] = frozenset(
                        o[4] for o in seen
                        if o[0] == "madd" and o[2] == var and o[3] == path
                        and o[1] not in killed
                    )
            return out

        return build(())

    def orset_value(self, row, var="s") -> frozenset:
        return self.orset_of(self.seen[row], var)

    def counter_value(self, row) -> int:
        return self.counter_of(self.seen[row])

    def resize(self, new_n, new_neighbors, graceful):
        if new_n < self.n:
            if graceful:
                # the claim rule: each departing row folds onto its
                # ring-fold successor row % new_n (not row 0)
                for i, s in enumerate(self.seen[new_n:]):
                    self.seen[(new_n + i) % new_n] |= s
            self.seen = self.seen[:new_n]
        else:
            self.seen += [set() for _ in range(new_n - self.n)]
        self.n = new_n
        self.neighbors = np.asarray(new_neighbors)


# test tiering (README "Test tiers"): the full soak is multi-minute
# (~25s/seed × N_SEEDS); the first two seeds run in the quick tier
# (`pytest -m "not slow"`, the tier-1 shape) for coverage, the rest ride
# the slow tier so tier-1 stays well under its timeout
@pytest.mark.parametrize(
    "seed",
    [
        seed if seed < 2 else pytest.param(seed, marks=pytest.mark.slow)
        for seed in range(N_SEEDS)
    ],
)
def test_mesh_statem(seed):
    rng = random.Random(seed)
    n = 12
    nbrs = random_regular(n, 2, seed=seed)
    store = Store(n_actors=N_ACTORS)
    s = store.declare(id="s", type="lasp_orset", n_elems=len(ELEMS),
                      n_actors=N_ACTORS, tokens_per_actor=32)
    c = store.declare(id="c", type="riak_dt_gcounter", n_actors=N_ACTORS)
    w = store.declare(id="w", type="riak_dt_orswot", n_elems=len(ELEMS),
                      n_actors=N_ACTORS)
    # SCHEMALESS maps (round 5): fields admit dynamically mid-run, one
    # map per re-add mode — contents join-monotone vs riak_dt
    # reset-remove — against the one op-kill model
    m_def = store.declare(id="m_def", type="riak_dt_map",
                          n_actors=N_ACTORS)
    m_rst = store.declare(id="m_rst", type="riak_dt_map",
                          n_actors=N_ACTORS, reset_on_readd=True)
    MKEYS = [("S1", "lasp_orset"), ("C1", "riak_dt_gcounter")]
    MSUB = ("M1", "riak_dt_map")  # nested submap key
    MPATHS = (
        [(k,) for k in MKEYS]
        + [(MSUB, ("s", "lasp_orset")), (MSUB, ("c", "riak_dt_gcounter"))]
    )
    rt = ReplicatedRuntime(store, Graph(store), n, nbrs,
                           debug_actors=True, donate_steps=False)
    model = MeshModel(n, nbrs)
    gen = 0  # membership generation: actor names are never reused

    def actor(r):
        return f"a{r}g{gen}"

    def check(rows=None):
        rows = rows if rows is not None else rng.sample(
            range(model.n), min(3, model.n)
        )
        for r in rows:
            assert rt.replica_value(s, r) == model.orset_value(r), r
            assert rt.replica_value(w, r) == model.orset_value(r, "w"), r
            assert rt.replica_value(c, r) == model.counter_value(r), r
            assert rt.replica_value(m_def, r) == model.map_value(r, "md"), r
            assert rt.replica_value(m_rst, r) == model.map_value(r, "mr"), r

    for _step in range(N_OPS):
        roll = rng.random()
        if roll < 0.35:  # client write at a row
            r = rng.randrange(model.n)
            # half the set traffic targets the OR-Set, half the OR-SWOT:
            # same observe-remove op model, two very different encodings
            vid, tag = (s, "s") if rng.random() < 0.5 else (w, "w")
            if rng.random() < 0.5:
                e = rng.choice(ELEMS)
                rt.update_at(r, vid, ("add", e), actor(r))
                model.add(r, e, tag)
            elif rng.random() < 0.6:
                e = rng.choice(ELEMS)
                if tag == "w" and not model.orset_value(r, "w"):
                    pass  # orswot remove needs liveness (see below)
                elif tag == "w":
                    # ORSWOT remove precondition is LIVENESS (dominated
                    # dots are dropped, not tombstoned) — unlike the
                    # OR-Set's orddict-membership rule
                    live = sorted(model.orset_value(r, "w"))
                    e = rng.choice(live)
                    rt.update_at(r, vid, ("remove", e), actor(r))
                    model.remove(r, e, tag)
                elif model.member(r, e, tag):
                    rt.update_at(r, vid, ("remove", e), actor(r))
                    model.remove(r, e, tag)
            else:
                by = rng.randint(1, 3)
                rt.update_at(r, c, ("increment", by), actor(r))
                model.increment(r, by)
        elif roll < 0.42:  # batched writes
            ops, k = [], rng.randint(1, 4)
            for _ in range(k):
                r = rng.randrange(model.n)
                e = rng.choice(ELEMS)
                ops.append((r, ("add", e), actor(r)))
                model.add(r, e)
            rt.update_batch(s, ops)
        elif roll < 0.60:  # map field ops (dynamic admission, NESTED paths)
            r = rng.randrange(model.n)
            vid, tag = (m_def, "md") if rng.random() < 0.5 else (m_rst, "mr")
            path = rng.choice(MPATHS)

            def wire_update(path, inner):
                op = ("update", path[-1], inner)
                for key in reversed(path[:-1]):
                    op = ("update", key, op)
                return ("update", [op])

            def wire_remove(path):
                op = ("remove", path[-1])
                for key in reversed(path[:-1]):
                    op = ("update", key, op)
                return ("update", [op])

            # removes get near-parity odds AND pick their row among rows
            # where the field IS present: the round-5 reset-remove
            # semantics (token tombstones, counter floors, recursive
            # submap resets) live on this branch
            present_rows = (
                [q for q in range(model.n) if model.map_present(q, tag, path)]
                if rng.random() < 0.45
                else []
            )
            if present_rows:
                r = rng.choice(present_rows)
                rt.update_at(r, vid, wire_remove(path), actor(r))
                model.map_remove(r, tag, path, reset=(tag == "mr"))
            elif rng.random() < 0.15 and (subrows := [
                q for q in range(model.n)
                if model.map_present(q, tag, (MSUB,))
            ]):
                # occasionally remove the WHOLE submap (recursive reset)
                r = rng.choice(subrows)
                rt.update_at(r, vid, wire_remove((MSUB,)), actor(r))
                model.map_remove(r, tag, (MSUB,), reset=(tag == "mr"))
            else:
                key = path[-1]
                inner = (
                    ("increment", rng.randint(1, 3))
                    if key[1] == "riak_dt_gcounter"
                    else ("add", rng.choice(ELEMS))
                )
                try:
                    rt.update_at(r, vid, wire_update(path, inner), actor(r))
                except CapacityError:
                    # reset-mode OR-Set fields pin tombstoned token slots
                    # (documented cost): the default espec's pool can
                    # exhaust under churn — loud, and the op is skipped
                    # in both worlds
                    pass
                else:
                    model.map_update(
                        r, tag, path,
                        ("minc", inner[1]) if inner[0] == "increment"
                        else ("madd", inner[1]),
                    )
        elif roll < 0.82:  # gossip round, possibly with dead edges
            mask = None
            if rng.random() < 0.4:
                mask = np.asarray(
                    np.random.RandomState(rng.randrange(1 << 16)).rand(
                        model.n, model.neighbors.shape[1]
                    ) < 0.7
                )
            rt.step(edge_mask=None if mask is None else mask)
            model.step(mask)
        elif roll < 0.9 and model.n < MAX_R:  # join
            new_n = model.n + rng.randint(1, 2)
            new_nbrs = (random_regular(new_n, 2, seed=rng.randrange(99))
                        if rng.random() < 0.5 else ring(new_n, 2))
            rt.resize(new_n, new_nbrs)
            model.resize(new_n, new_nbrs, graceful=True)
            gen += 1
        elif model.n > 6:  # leave (graceful or crash)
            new_n = model.n - rng.randint(1, 2)
            graceful = rng.random() < 0.7
            new_nbrs = ring(new_n, 2)
            rt.resize(new_n, new_nbrs, graceful=graceful)
            model.resize(new_n, new_nbrs, graceful=graceful)
            gen += 1
        check()

    # final: converge both worlds and compare EVERY row + coverage.
    # k=2 random-permutation digraphs on ~12 nodes are strongly connected
    # only w.h.p. — on a disconnected draw both worlds converge to the
    # same PER-COMPONENT fixed points, so global assertions come from the
    # model, not from an assumed connectivity
    rt.run_to_convergence(max_rounds=4 * model.n + 16)
    model.converge()
    check(rows=range(model.n))
    if all(seen == model.seen[0] for seen in model.seen):
        assert rt.divergence(s) == 0 and rt.divergence(c) == 0
        assert rt.divergence(w) == 0
        assert rt.divergence(m_def) == 0 and rt.divergence(m_rst) == 0
    union = set().union(*model.seen)
    assert rt.coverage_value(s) == MeshModel.orset_of(union)
    assert rt.coverage_value(w) == MeshModel.orset_of(union, "w")
    assert rt.coverage_value(c) == MeshModel.counter_of(union)
    umodel = MeshModel(1, [[0]])
    umodel.seen = [union]
    assert rt.coverage_value(m_def) == umodel.map_value(0, "md")
    assert rt.coverage_value(m_rst) == umodel.map_value(0, "mr")
