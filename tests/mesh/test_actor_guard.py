"""Actor-collision debug guard (VERDICT r3 ask #8): the riak_dt actor
requirement — one actor, one writing site — enforced loudly under the
opt-in ``debug_actors`` flag. Without the guard the misuse corrupts state
SILENTLY (the first test demonstrates the loss), which is exactly why it
exists."""

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ActorCollisionError, ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _rt(type_name="riak_dt_orswot", debug=True, **caps):
    store = Store(n_actors=4)
    caps.setdefault("n_elems", 8) if type_name != "riak_dt_gcounter" else None
    s = store.declare(id="s", type=type_name, **caps)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2),
                           debug_actors=debug)
    return rt, s


def test_silent_loss_without_guard_raises_with_guard():
    # the footgun, demonstrated: two rows minting orswot dots under ONE
    # actor produce colliding counters the vclock rule reads as
    # observed-and-removed — elements silently disappear after gossip
    rt_off, s = _rt(debug=False)
    rt_off.update_at(0, s, ("add", "x"), "shared-actor")
    rt_off.update_at(2, s, ("add", "y"), "shared-actor")  # colliding dot
    rt_off.run_to_convergence(max_rounds=16)
    merged = rt_off.coverage_value(s)
    assert merged != {"x", "y"}  # the silent loss (x or y vanished)

    # same sequence under the guard: loud at the second write site
    rt_on, s2 = _rt(debug=True)
    rt_on.update_at(0, s2, ("add", "x"), "shared-actor")
    with pytest.raises(ActorCollisionError, match="shared-actor"):
        rt_on.update_at(2, s2, ("add", "y"), "shared-actor")


def test_same_site_rewrites_pass():
    rt, s = _rt()
    rt.update_at(1, s, ("add", "x"), "a1")
    rt.update_at(1, s, ("add", "y"), "a1")  # same home replica: fine
    rt.update_at(2, s, ("add", "z"), "a2")  # different actor: fine
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(s) == {"x", "y", "z"}


def test_removes_at_other_sites_are_safe():
    # removes mint nothing; a remove from another row under the same
    # actor is legitimate (read-side) and must not trip the guard
    rt, s = _rt()
    rt.update_at(0, s, ("add", "x"), "a0")
    rt.run_to_convergence(max_rounds=16)
    rt.update_at(3, s, ("remove", "x"), "a0")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(s) == frozenset()


def test_gcounter_lane_guard():
    rt, c = _rt("riak_dt_gcounter")
    rt.update_at(0, c, ("increment", 2), "w")
    with pytest.raises(ActorCollisionError):
        rt.update_at(1, c, ("increment",), "w")


def test_map_update_guard():
    store = Store(n_actors=4)
    m = store.declare(
        id="m", type="riak_dt_map",
        fields=[(("X", "lasp_gset"), "lasp_gset", {"n_elems": 4})],
    )
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2),
                           debug_actors=True)
    key = ("X", "lasp_gset")
    rt.update_at(0, m, ("update", [("update", key, ("add", "a"))]), "w")
    with pytest.raises(ActorCollisionError):
        rt.update_at(2, m, ("update", [("update", key, ("add", "b"))]), "w")
    # a remove from elsewhere under the same actor mints nothing: allowed
    rt.run_to_convergence(max_rounds=16)
    rt.update_at(3, m, ("update", [("remove", key)]), "w")


def test_update_batch_guard_is_all_or_nothing():
    rt, s = _rt()
    bad = [
        (0, ("add", "x"), "w"),
        (1, ("add", "y"), "w"),  # collision within the batch
    ]
    with pytest.raises(ActorCollisionError):
        rt.update_batch(s, bad)
    # nothing applied, registry not extended: the actor can still pick
    # its one home site
    assert rt.coverage_value(s) == frozenset()
    rt.update_batch(s, [(2, ("add", "z"), "w")])
    assert rt.replica_value(s, 2) == {"z"}
    with pytest.raises(ActorCollisionError):
        rt.update_batch(s, [(0, ("add", "q"), "w")])  # vs registry


def test_seed_increments_guard():
    rt, c = _rt("riak_dt_gcounter")
    rt.seed_increments(c, [0, 1, 2], [0, 1, 2])
    with pytest.raises(ActorCollisionError):
        rt.seed_increments(c, [3], [1])  # lane 1 lives at row 1
    rt.seed_increments(c, [1], [1])  # same site: fine


def test_cross_surface_lane_alias_collision():
    # update_at registers by term; seed_increments writes the SAME lane
    # by index from another row — the alias must catch it (reviewer
    # scenario: the two spellings name one physical counter lane)
    rt, c = _rt("riak_dt_gcounter")
    rt.update_at(0, c, ("increment",), "w")  # interns "w" -> lane 0
    with pytest.raises(ActorCollisionError):
        rt.seed_increments(c, [3], [0])
    rt.seed_increments(c, [0], [0])  # same site through the alias: fine
    # and the reverse direction: seed first, term write later
    rt2, c2 = _rt("riak_dt_gcounter")
    rt2.seed_increments(c2, [2], [0])  # lane 0 homes at row 2, no term yet
    with pytest.raises(ActorCollisionError):
        rt2.update_at(1, c2, ("increment",), "w0")  # "w0" interns to lane 0


def test_seed_increments_intra_call_collision():
    rt, c = _rt("riak_dt_gcounter")
    with pytest.raises(ActorCollisionError):
        rt.seed_increments(c, [0, 3], [1, 1])  # lane 1 from two rows
    rt.seed_increments(c, [0, 0], [1, 1])  # same row twice: fine


def test_partial_batch_failure_registers_no_phantom_sites():
    # a capacity-truncated batch registers sites for NOTHING, so a caller
    # that catches the error and retries the unapplied suffix elsewhere
    # is judged afresh (the suffix minted nothing)
    from lasp_tpu.utils.interning import CapacityError

    store = Store(n_actors=2)
    s = store.declare(id="s", type="riak_dt_orswot", n_elems=2)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2),
                           debug_actors=True)
    with pytest.raises(CapacityError):
        rt.update_batch(s, [
            (0, ("add", "e0"), "w0"),
            (0, ("add", "e1"), "w0"),
            (1, ("add", "e2"), "w1"),  # 3rd element overflows n_elems=2
        ])
    # w1's op never applied: no phantom site for it — the caller may
    # legitimately home w1 elsewhere on retry
    assert ("s", "w1") not in rt._actor_sites
    # w0's prefix DID apply, so its site IS registered
    assert rt._actor_sites.get(("s", "w0")) == 0


def test_precondition_failure_still_registers_persisted_prefix():
    # _orswot_batch persists ops before a PreconditionError; their minted
    # lane events MUST register, or a later cross-replica write under the
    # same actor would pass the guard and corrupt silently (the guard
    # errs toward false collisions, never silent misses)
    from lasp_tpu.store import PreconditionError

    rt, s = _rt()
    with pytest.raises(PreconditionError):
        rt.update_batch(s, [
            (0, ("add", "x"), "w"),
            (1, ("remove", "missing"), "a"),  # fails; the add persisted
        ])
    assert rt.replica_value(s, 0) == {"x"}  # the prefix really applied
    with pytest.raises(ActorCollisionError):
        rt.update_at(2, s, ("add", "y"), "w")


def test_seed_increments_shape_error_leaves_no_phantom_sites():
    rt, c = _rt("riak_dt_gcounter")
    with pytest.raises(Exception):
        rt.seed_increments(c, [0, 1], [0, 1], by=[[1, 2, 3]])  # bad shape
    assert not rt._actor_sites  # nothing was written, nothing registered
    rt.seed_increments(c, [3], [0])  # lane 0 free to home anywhere


def test_resize_keeps_registry_for_surviving_rows():
    # surviving rows keep their indices, so actor bindings survive resize
    rt, s = _rt()
    rt.update_at(0, s, ("add", "x"), "w")
    rt.resize(6, ring(6, 2))
    with pytest.raises(ActorCollisionError):
        rt.update_at(5, s, ("add", "y"), "w")  # w still homes at row 0
    rt.update_at(0, s, ("add", "y"), "w")  # its home still works


def test_orset_token_reuse_after_churn_is_caught():
    # the silent loss the mesh statem caught (150-op soak): shrink drops
    # a row whose tokens still circulate via gossip; a later grow reuses
    # the row index, and a fresh mint under the SAME actor allocates the
    # same row-local slot — a circulating tombstone then eats the new
    # add. The guard must refuse the reused-actor write.
    store = Store(n_actors=8)
    s = store.declare(id="s", type="lasp_orset", n_elems=8,
                      tokens_per_actor=4)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2),
                           debug_actors=True)
    rt.update_at(3, s, ("add", "x"), "a3")
    rt.run_to_convergence(max_rounds=8)   # x's token circulates
    rt.update_at(0, s, ("remove", "x"), "a0")  # tombstone circulates too
    rt.run_to_convergence(max_rounds=8)
    rt.resize(3, ring(3, 2), graceful=False)  # row 3 crashes away
    rt.resize(4, ring(4, 2))                  # a new row 3 joins
    with pytest.raises(ActorCollisionError):
        # without the guard this add would mint (x, a3, slot 0) again and
        # the circulating tombstone would silently absorb it
        rt.update_at(3, s, ("add", "x"), "a3")
    rt.update_at(3, s, ("add", "x"), "a3-incarnation2")  # fresh actor: fine
    rt.run_to_convergence(max_rounds=8)
    assert rt.coverage_value(s) == {"x"}


def test_graceful_departure_remaps_actor_to_row0():
    store = Store(n_actors=8)
    s = store.declare(id="s", type="lasp_orset", n_elems=8,
                      tokens_per_actor=4)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2),
                           debug_actors=True)
    rt.update_at(3, s, ("add", "x"), "a3")
    rt.resize(3, ring(3, 2), graceful=True)  # row 3's state joins row 0
    # row 0 sees ALL of a3's tokens post-handoff: continuing there is safe
    rt.update_at(0, s, ("add", "y"), "a3")
    with pytest.raises(ActorCollisionError):
        rt.update_at(2, s, ("add", "z"), "a3")  # anywhere else is not
    rt.run_to_convergence(max_rounds=8)
    assert rt.coverage_value(s) == {"x", "y"}


def test_guard_off_by_default():
    rt, s = _rt(debug=False)
    rt.update_at(0, s, ("add", "x"), "w")
    rt.update_at(1, s, ("add", "y"), "w")  # no raise (documented caveat)


def test_batch_failure_commits_only_applied_write_sites():
    # r4 advisor finding: after a mid-batch dispatch failure the guard
    # used to register write sites for every CHECKED op, including ops
    # past the failure that never applied — a later legitimate write then
    # hit a false ActorCollisionError. The batch kernels now stamp the
    # failing op's index on the error and the guard commits only ops
    # before it.
    from lasp_tpu.store import PreconditionError

    store = Store(n_actors=8)
    s = store.declare(id="s", type="lasp_orset", n_elems=8,
                      tokens_per_actor=4)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2),
                           debug_actors=True)
    with pytest.raises(PreconditionError):
        rt.update_batch(s, [
            (0, ("add", "a"), "w0"),
            (1, ("remove", "never-added"), "w1"),  # fails at index 1
            (2, ("add", "b"), "w2"),               # never applies
        ])
    # w0 applied and is pinned to replica 0
    with pytest.raises(ActorCollisionError):
        rt.update_at(3, s, ("add", "c"), "w0")
    # w2 minted nothing: its home replica is still free to choose
    rt.update_at(3, s, ("add", "c"), "w2")
    rt.run_to_convergence(max_rounds=8)
    assert rt.coverage_value(s) == {"a", "c"}


def test_shift_step_guards_foreign_neighbor_table():
    # r4 advisor finding: on shift-structured topologies the compiled
    # step gossips via offsets baked at build time; a concrete call with
    # a DIFFERENT table must raise, not silently run the old topology
    import numpy as np

    from lasp_tpu.mesh import random_regular

    rt, s = _rt(debug=False)
    rt._build_step()
    step = rt._step_pure
    tables = tuple(e.device_tables() for e in rt.graph.edges)
    # the runtime's own table passes
    step(rt.states, rt.neighbors, None, tables)
    # an equal-valued copy passes (equality fallback)
    import jax.numpy as jnp

    step(rt.states, jnp.asarray(np.asarray(rt.neighbors).copy()), None, tables)
    # a different topology of the same shape raises
    other = random_regular(rt.n_replicas, rt.neighbors.shape[1], seed=9)
    with pytest.raises(ValueError):
        step(rt.states, jnp.asarray(other), None, tables)
