"""Two-process jax.distributed smoke test — the multi-host communication
backend (SURVEY.md §2.5 "disterl role" / VERDICT r3 component #32's "as
far as verifiable without a pod" caveat) exercised across a REAL process
boundary: two OS processes × 4 virtual CPU devices join through
``comm.init_distributed`` into one 8-device global mesh, the canonical
``build_mesh`` lays slices outermost, and the ENGINE's sharded step runs
cross-process collectives (Gloo here; ICI/DCN on a pod) to convergence."""

import os
import signal
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_engine():
    port = _free_port()
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
    worker = os.path.join(os.path.dirname(__file__), "multiprocess_worker.py")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, worker],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        raise
    for i, (rc, out, err) in enumerate(outs):
        if rc != 0 and (
            "Multiprocess computations aren't implemented on the CPU"
            in (out + err)
        ):
            # capability gate, not a code bug: this jaxlib's CPU backend
            # has no cross-process collective support (newer jaxlib ships
            # the Gloo backend this test exercises)
            import pytest

            pytest.skip(
                "jaxlib CPU backend lacks multiprocess collectives"
            )
        assert rc == 0, f"worker {i} rc={rc}\nstdout:{out}\nstderr:{err}"
        assert f"WORKER-OK process={i}" in out, (out, err)
