"""ReplicatedRuntime(packed=True): the flat bit-packed wire format must be
semantically invisible — same fixed points, same decoded values, same
client-op semantics as dense mode. Plus the reactive trigger mechanism
(the TPU dissolution of the reference's server threshold-read -> remove
loop, riak_test/lasp_advertisement_counter_test.erl:197-235).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import GCounter, ORSet
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.ops import FlatORSet, FlatORSetSpec
from lasp_tpu.store import Store


def _pipeline_runtime(packed: bool, n=8):
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=4, tokens_per_actor=2)
    b = store.declare(id="b", type="lasp_orset", n_elems=4, tokens_per_actor=2)
    c = store.declare(id="c", type="lasp_orset", n_elems=4, tokens_per_actor=2)
    u = graph.union(a, b, dst="u")
    p = graph.product(u, c, dst="p")
    graph.filter(p, lambda xy: xy[1] != "skip", dst="f")
    rt = ReplicatedRuntime(store, graph, n, ring(n, 2), packed=packed)
    return rt


def _drive(rt):
    rt.update_batch("a", [(0, ("add_all", ["x", "y"]), "w0")])
    rt.update_batch("b", [(1, ("add", "z"), "w1"), (2, ("add", "y"), "w1")])
    rt.update_batch("c", [(3, ("add_all", ["k", "skip"]), "w2")])
    rt.run_to_convergence()
    rt.update_batch("a", [(5, ("remove", "y"), "w0")])
    rt.run_to_convergence()
    return {
        v: rt.coverage_value(v) for v in ("a", "b", "c", "u", "p", "f")
    }


def test_packed_mode_matches_dense_fixed_point():
    dense = _drive(_pipeline_runtime(packed=False))
    packed = _drive(_pipeline_runtime(packed=True))
    assert dense == packed
    # sanity on the actual semantics, not just agreement
    assert packed["u"] == {"x", "z", "y"} or packed["u"] == {"x", "z"}
    # left-biased union: removing y from a tombstones a's tokens; b's y
    # token was suppressed while a held y, so y disappears from the union
    assert "y" not in packed["f"] or ("y", "skip") not in packed["f"]
    assert all(pair[1] != "skip" for pair in packed["f"])


def test_packed_update_at_and_reads():
    rt = _pipeline_runtime(packed=True)
    rt.update_at(0, "a", ("add", "solo"), "w0")
    assert rt.replica_value("a", 0) == {"solo"}
    assert rt.replica_value("a", 1) == set()
    rt.run_to_convergence()
    assert rt.divergence("a") == 0
    assert rt.coverage_value("a") == {"solo"}
    row = rt.read_at(3, "a")
    assert row is not None  # bottom threshold met; row is a DENSE state
    assert hasattr(row, "exists") and row.exists.dtype == jnp.bool_


def test_packed_pool_holes_and_exhaustion():
    from lasp_tpu.utils.interning import CapacityError

    rt = _pipeline_runtime(packed=True)
    # fill one slot by hand via seed_tokens (add_by_token analogue), then
    # batch adds must skip the hole
    e = rt.intern_terms("a", ["e"])[0]
    a_idx = rt.intern_actors("a", ["w0"])[0]
    base = int(a_idx) * 2
    rt.seed_tokens("a", [0], [e], [base + 1])
    rt.update_batch("a", [(0, ("add", "e"), "w0")])
    dense0 = rt.replica_value("a", 0)
    assert dense0 == {"e"}
    st = rt._to_dense_row("a", _row(rt, "a", 0))
    pool = np.asarray(st.exists[e, base : base + 2])
    assert pool.tolist() == [True, True]
    with pytest.raises(CapacityError):
        rt.update_batch("a", [(0, ("add", "e"), "w0")])


def _row(rt, var_id, r):
    import jax

    return jax.tree_util.tree_map(lambda x: x[r], rt.states[var_id])


def test_trigger_threshold_remove():
    """Counter passes threshold at a replica -> trigger removes the ad from
    the OR-Set -> tombstone gossips everywhere (the ad-counter server)."""
    store = Store(n_actors=4)
    graph = Graph(store)
    ads = store.declare(id="ads", type="lasp_orset", n_elems=4, tokens_per_actor=1)
    views = store.declare(id="views", type="riak_dt_gcounter", n_actors=4)
    n = 8
    rt = ReplicatedRuntime(store, graph, n, ring(n, 2), packed=True)
    ad_idx = rt.intern_terms(ads, ["ad0", "ad1"])
    rt.seed_tokens(ads, [0, 0], ad_idx, [0, 1])
    var = store.variable(ads)
    aspec = var.spec
    threshold = 3

    def server(dense):
        total = jnp.sum(dense[views].counts)
        over = total >= threshold
        # remove ad0 when views pass the threshold
        mask = jnp.zeros((aspec.n_elems,), bool).at[ad_idx[0]].set(over)
        st = dense[ads]
        return {ads: st._replace(removed=st.removed | (st.exists & mask[:, None]))}

    rt.register_trigger(server)
    rt.run_to_convergence()
    assert rt.coverage_value(ads) == {"ad0", "ad1"}
    # seed views: lanes 0..2 at their own replicas -> total 3 >= threshold
    rt.seed_increments(views, [0, 1, 2], [0, 1, 2])
    rt.run_to_convergence()
    assert rt.coverage_value(ads) == {"ad1"}
    assert rt.coverage_value(views) == 3
    assert rt.divergence(ads) == 0


def test_flatpack_roundtrip_and_kernels():
    from lasp_tpu.lattice.orset import ORSetSpec

    rng = np.random.RandomState(0)
    spec = ORSetSpec(n_elems=5, n_actors=3, tokens_per_actor=3)
    pspec = FlatORSetSpec(dense=spec)
    for _ in range(20):
        exists = jnp.asarray(rng.rand(5, 9) < 0.4)
        removed = jnp.asarray(rng.rand(5, 9) < 0.3) & exists
        dense = ORSet.new(spec)._replace(exists=exists, removed=removed)
        packed = FlatORSet.pack(pspec, dense)
        rt_dense = FlatORSet.unpack(pspec, packed)
        assert bool(ORSet.equal(spec, dense, rt_dense))
        # merge commutes with pack
        exists2 = jnp.asarray(rng.rand(5, 9) < 0.4)
        removed2 = jnp.asarray(rng.rand(5, 9) < 0.3) & exists2
        dense2 = ORSet.new(spec)._replace(exists=exists2, removed=removed2)
        m_dense = ORSet.merge(spec, dense, dense2)
        m_packed = FlatORSet.merge(
            pspec, packed, FlatORSet.pack(pspec, dense2)
        )
        assert bool(
            ORSet.equal(spec, m_dense, FlatORSet.unpack(pspec, m_packed))
        )
        assert bool(FlatORSet.equal(pspec, FlatORSet.pack(pspec, m_dense), m_packed))
        assert (
            np.asarray(FlatORSet.value(pspec, m_packed))
            == np.asarray(ORSet.value(spec, m_dense))
        ).all()
