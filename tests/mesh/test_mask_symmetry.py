"""Bidirectional link removal (the chaos-mesh satellite): symmetrize /
assert helpers, the edge_failure_mask symmetric mode, and the property
that partition masks are symmetric by construction."""

import numpy as np
import pytest

from lasp_tpu.mesh import (
    assert_symmetric_mask,
    edge_failure_mask,
    partition_mask,
    random_regular,
    ring,
    scale_free,
    symmetrize_edge_mask,
)
from lasp_tpu.mesh.topology import _pair_keys


@pytest.mark.parametrize("seed", range(4))
def test_symmetrize_property(seed):
    """For random topologies and random masks: the symmetrized mask
    passes the loud assert, only ever KILLS edges, and kills exactly
    the pairs that had any dead direction."""
    rng = np.random.RandomState(seed)
    n, k = 64, 3
    nbrs = random_regular(n, k, seed=seed)
    raw = rng.random_sample((n, k)) >= 0.3
    sym = symmetrize_edge_mask(nbrs, raw)
    assert_symmetric_mask(nbrs, sym)
    assert not (sym & ~raw).any()  # never resurrects an edge
    # pair-accurate: an edge survives iff NO direction of its pair died
    keys = _pair_keys(nbrs)
    dead = set(np.unique(keys[~raw]).tolist())
    expect = raw & ~np.isin(keys, list(dead))
    assert np.array_equal(sym, expect)
    # idempotent
    assert np.array_equal(symmetrize_edge_mask(nbrs, sym), sym)


def test_assert_raises_on_one_way_link():
    n = 16
    nbrs = ring(n, 2)  # columns +1, -1: every link appears both ways
    mask = np.ones((n, 2), dtype=bool)
    mask[3, 0] = False  # 3 -/-> 4, but 4 -> 3 still alive
    with pytest.raises(ValueError, match="asymmetric edge mask"):
        assert_symmetric_mask(nbrs, mask)
    fixed = symmetrize_edge_mask(nbrs, mask)
    assert_symmetric_mask(nbrs, fixed)
    assert not fixed[4, 1]  # the reverse direction died too


def test_self_edges_exempt():
    nbrs = np.zeros((4, 1), dtype=np.int32)
    nbrs[:, 0] = np.arange(4)  # every edge is a self-loop
    mask = np.array([[True], [False], [True], [True]])
    assert_symmetric_mask(nbrs, mask)  # dead self-edges are no-ops


def test_partition_mask_symmetric_by_construction():
    for n, k, groups in ((48, 3, 2), (60, 4, 3)):
        nbrs = random_regular(n, k, seed=1)
        assert_symmetric_mask(nbrs, partition_mask(n, nbrs, groups))
        nbrs = scale_free(n, k, seed=2)
        assert_symmetric_mask(nbrs, partition_mask(n, nbrs, groups))


def test_edge_failure_mask_symmetric_mode():
    n, k = 64, 3
    nbrs = random_regular(n, k, seed=5)
    sym = edge_failure_mask(n, k, 0.3, seed=7, neighbors=nbrs)
    assert_symmetric_mask(nbrs, sym)
    raw = edge_failure_mask(n, k, 0.3, seed=7)
    # the symmetric mode is the raw draw, normalized (kills only)
    assert np.array_equal(sym, symmetrize_edge_mask(nbrs, raw))
    assert not (sym & ~raw).any()


def test_shape_mismatch_is_loud():
    nbrs = ring(8, 2)
    with pytest.raises(ValueError, match="does not match"):
        symmetrize_edge_mask(nbrs, np.ones((8, 3), dtype=bool))
    with pytest.raises(ValueError, match="does not match"):
        assert_symmetric_mask(nbrs, np.ones((4, 2), dtype=bool))


def test_frontier_matches_dense_under_symmetrized_mask():
    """The reachability story the satellite protects: under a
    symmetrized mask, frontier and dense scheduling stay bit-identical
    to the fixed point (the frontier-reach superset invariant holds on
    bidirectional-failure graphs)."""
    import jax

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    n, k = 64, 3
    nbrs = random_regular(n, k, seed=9)
    mask = edge_failure_mask(n, k, 0.35, seed=3, neighbors=nbrs)

    def build():
        store = Store(n_actors=4)
        v = store.declare(id="a", type="lasp_gset", n_elems=8)
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
        rt.update_batch(
            v, [(0, ("add", "x"), "c0"), (40, ("add", "y"), "c40")]
        )
        return rt, v

    import jax.numpy as jnp

    jmask = jnp.asarray(mask)
    rt_f, v = build()
    rt_d, _ = build()
    for _ in range(64):
        rf, rd = rt_f.frontier_step(jmask), rt_d.step(jmask)
        assert rf == rd
        same = jax.tree_util.tree_map(
            lambda x, y: bool(jnp.array_equal(x, y)),
            rt_f.states[v], rt_d.states[v],
        )
        assert all(jax.tree_util.tree_leaves(same))
        if rd == 0:
            return
    pytest.fail("no fixed point under the symmetrized mask")
