"""fused_steps: the FULL engine step (sweep + triggers + gossip +
residual) in one lax.fori_loop dispatch per block — must reach the same
fixed point in the same number of rounds as the per-round path (VERDICT r2
ask #4: the engine path the 10M north-star runs through must not pay one
dispatch + host sync per round)."""

import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.store import Store


def _adcounter_runtime(n=32, packed=False, threshold=2):
    """Miniature of the north-star: union pipeline + counter + server
    trigger that removes an over-threshold ad."""
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=4, n_actors=1,
                      tokens_per_actor=1)
    b = store.declare(id="b", type="lasp_orset", n_elems=4, n_actors=1,
                      tokens_per_actor=1)
    graph.union(a, b, dst="u")
    views = store.declare(id="views", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(
        store, graph, n, random_regular(n, 3, seed=9), packed=packed
    )
    rt.update_batch("a", [(0, ("add_all", ["x", "y"]), "p")])
    rt.update_batch("b", [(1, ("add", "z"), "q")])
    rt.update_batch(
        "views", [(2, ("increment",), "c0"), (3, ("increment",), "c1")]
    )
    x_idx = rt.intern_terms("a", ["x"])

    def server(dense):
        over = jnp.sum(dense["views"].counts, dtype=jnp.int32) >= threshold
        st = dense["a"]
        mask = jnp.zeros((4,), bool).at[jnp.asarray(x_idx)].set(over)
        return {"a": st._replace(removed=st.removed | (st.exists & mask[:, None]))}

    rt.register_trigger(server)
    return rt


@pytest.mark.parametrize("packed", [False, True])
def test_fused_matches_per_round_fixed_point_and_count(packed):
    rt1 = _adcounter_runtime(packed=packed)
    rt2 = _adcounter_runtime(packed=packed)
    r1 = rt1.run_to_convergence()
    r2 = rt2.run_to_convergence(block=4)
    assert r1 == r2
    for v in rt1.var_ids:
        assert rt1.coverage_value(v) == rt2.coverage_value(v)
        assert rt2.divergence(v) == 0
    # the trigger fired everywhere: x removed once views reached threshold
    assert rt2.coverage_value("u") == {"y", "z"}


def test_fused_steps_reports_in_block_quiescent_round():
    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    # ring k=2 over 8 replicas: diameter 2, converges round 3 is quiescent
    first_zero = rt.fused_steps(8)
    assert 0 <= first_zero < 8
    # a second fused block is immediately quiescent at index 0
    assert rt.fused_steps(8) == 0
    assert rt.coverage_value("s") == {"e"}
    assert rt.divergence("s") == 0


def test_fused_block_larger_than_convergence_is_harmless():
    rt = _adcounter_runtime(n=16)
    rounds = rt.run_to_convergence(block=64)
    assert rounds <= 64
    assert rt.coverage_value("u") == {"y", "z"}


def test_fused_cache_invalidated_by_new_trigger():
    rt = _adcounter_runtime(n=16)
    rt.run_to_convergence(block=4)
    fired = {}

    def late_trigger(dense):
        fired["yes"] = True
        return {}

    rt.register_trigger(late_trigger)
    rt.fused_steps(2)
    assert fired.get("yes")


def test_edge_failure_mask_respected_in_fused_path():
    from lasp_tpu.mesh import edge_failure_mask

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    dead = jnp.zeros((8, 2), dtype=bool)  # all edges down: nothing moves
    assert rt.fused_steps(4, edge_mask=dead) >= 0
    assert rt.replica_value("s", 4) == frozenset()
    alive = jnp.asarray(edge_failure_mask(8, 2, 0.0))
    rt.run_to_convergence(block=4, edge_mask=alive)
    assert rt.coverage_value("s") == {"e"}
    assert rt.divergence("s") == 0


def test_trigger_touch_sets_keep_untouched_vars_packed():
    """A trigger with a declared touch set must behave identically to an
    undeclared one, and writing outside the declared set fails loudly."""
    import jax.numpy as jnp

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.store import Store

    def build(touches):
        store = Store(n_actors=2)
        graph = Graph(store)
        store.declare(id="watched", type="riak_dt_gcounter")
        store.declare(id="target", type="lasp_orset", n_elems=4, n_actors=1,
                      tokens_per_actor=1)
        store.declare(id="bystander", type="lasp_orset", n_elems=4)
        rt = ReplicatedRuntime(store, graph, 8, ring(8, 2), packed=True)
        rt.update_batch("target", [(0, ("add", "ad"), "p")])
        rt.update_batch("bystander", [(3, ("add", "b"), "p")])
        rt.update_batch("watched", [(1, ("increment", 2), "c")])
        idx = rt.intern_terms("target", ["ad"])

        def trig(dense):
            over = jnp.sum(dense["watched"].counts, dtype=jnp.int32) >= 2
            st = dense["target"]
            mask = jnp.zeros((4,), bool).at[jnp.asarray(idx)].set(over)
            return {"target": st._replace(
                removed=st.removed | (st.exists & mask[:, None]))}

        rt.register_trigger(trig, touches=touches)
        rt.run_to_convergence(block=4)
        return rt

    declared = build(["watched", "target"])
    universal = build(None)
    for v in ("watched", "target", "bystander"):
        assert declared.coverage_value(v) == universal.coverage_value(v)
        assert declared.divergence(v) == 0
    assert declared.coverage_value("target") == frozenset()
    assert declared.coverage_value("bystander") == {"b"}

    # writes outside the declared set are a loud trace-time error
    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="a", type="lasp_gset", n_elems=2)
    store.declare(id="b", type="lasp_gset", n_elems=2)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 1))

    def rogue(dense):
        return {"b": dense["a"]}  # "b" never declared

    rt.register_trigger(rogue, touches=["a"])
    with pytest.raises(KeyError, match="outside its declared touches"):
        rt.step()


def test_runtime_compact_orset_reclaims_after_convergence():
    from lasp_tpu.store import Store
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.utils.interning import CapacityError

    for packed in (False, True):
        store = Store(n_actors=2)
        graph = Graph(store)
        store.declare(id="s", type="lasp_orset", n_elems=4, n_actors=2,
                      tokens_per_actor=2)
        rt = ReplicatedRuntime(store, graph, 8, ring(8, 2), packed=packed)
        rt.update_batch("s", [(0, ("add", f"e{i}"), "w") for i in range(4)])
        rt.run_to_convergence()
        rt.update_batch("s", [(0, ("remove_all", ["e0", "e1", "e2"]), "w")])
        # not converged yet: compaction must refuse
        with pytest.raises(RuntimeError, match="not converged"):
            rt.compact_orset("s")
        rt.run_to_convergence()
        assert rt.compact_orset("s") == 3
        assert rt.coverage_value("s") == {"e3"}
        assert rt.divergence("s") == 0
        # reclaimed slots are usable again (would CapacityError before)
        rt.update_batch("s", [(2, ("add_all", ["f1", "f2", "f3"]), "w")])
        rt.run_to_convergence()
        assert rt.coverage_value("s") == {"e3", "f1", "f2", "f3"}, f"packed={packed}"


def test_store_compact_orset_single_replica():
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    store.declare(id="s", type="lasp_orset", n_elems=3)
    for e in ("a", "b", "c"):
        store.update("s", ("add", e), "w")
    store.update("s", ("remove_all", ["a", "b"]), "w")
    assert store.compact_orset("s") == 2
    assert store.value("s") == {"c"}
    store.update("s", ("add", "d"), "w")  # reclaimed slot
    store.update("s", ("add", "e"), "w")
    assert store.value("s") == {"c", "d", "e"}


def test_compact_refuses_trigger_touched_variable():
    """Trigger closures hold element indices baked in the old order
    (intern_terms results) — compaction must refuse, loudly."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_orset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 1))
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    rt.register_trigger(lambda dense: {}, touches=["s"])
    rt.run_to_convergence(block=4)
    with pytest.raises(RuntimeError, match="trigger"):
        rt.compact_orset("s")


def test_read_until_auto_defaults_to_device_parked(monkeypatch):
    # VERDICT r3 ask #9: the default wait does ZERO per-probe row pulls —
    # read_at (the host probe that unpacks + pulls a row) runs exactly
    # once, for the final met-row return, not once per round. Wide packed
    # rows make the per-probe pull the dominant cost of the host path.
    from lasp_tpu.lattice import Threshold

    def build():
        store = Store(n_actors=4)
        s = store.declare(id="w", type="lasp_orset", n_elems=64,
                          tokens_per_actor=4)
        rt = ReplicatedRuntime(store, Graph(store), 32, ring(32, 2),
                               packed=True)
        rt.update_at(0, s, ("add", "seed"), "a0")
        # threshold = replica 0's seeded row (dense): unmet anywhere else
        # until gossip carries it over
        return rt, s, rt.read_at(0, s)

    calls = {"n": 0}
    orig = ReplicatedRuntime.read_at

    def counting_read_at(self, *a, **kw):
        calls["n"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(ReplicatedRuntime, "read_at", counting_read_at)
    # replica 16 is ~8 ring hops away: many rounds pass before the wait
    # completes, but the host probe still runs exactly once
    rt, s, want = build()
    calls["n"] = 0  # build() itself probed row 0 for the threshold
    row = rt.read_until(16, s, Threshold(want), max_rounds=64)
    assert row is not None
    assert calls["n"] == 1

    # explicit opt-out still host-probes (one probe per round)
    rt2, s2, want2 = build()
    calls["n"] = 0
    row = rt2.read_until(16, s2, Threshold(want2), max_rounds=64,
                         on_device=False)
    assert row is not None
    assert calls["n"] > 2


def test_read_until_auto_falls_back_for_host_only_threshold():
    # an object-dtype threshold leaf cannot ride as a traced operand;
    # auto must pick the host loop (which the codec also cannot compare —
    # asserting the ROUTING, with a threshold the device check rejects)
    import numpy as np

    from lasp_tpu.mesh.runtime import _device_expressible

    assert _device_expressible(5)
    assert _device_expressible((np.zeros(3), np.ones((2, 2), bool)))
    assert not _device_expressible(np.array([object()], dtype=object))
    assert not _device_expressible({"not", "arrayable"})


def test_read_until_fused_blocks():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="c", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, graph, 16, ring(16, 1))
    rt.update_batch("c", [(0, ("increment", 5), "w")])
    assert rt.read_at(8, "c", Threshold(5)) is None
    row = rt.read_until(8, "c", Threshold(5), block=4)
    assert row is not None
    with pytest.raises(TimeoutError, match="unreachable"):
        # fails fast: the mesh quiesces long before 1000 rounds
        rt.read_until(8, "c", Threshold(99), max_rounds=1000, block=4)


def test_poisoned_runtime_raises_loudly():
    """After a failed donated dispatch the pre-step state is gone; the
    runtime must refuse further stepping with a clear error instead of
    surfacing 'Array has been deleted' from deep inside jax."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="v", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt._poisoned = "ResourceExhausted: simulated"
    with pytest.raises(RuntimeError, match="donate_steps=False"):
        rt.step()
    with pytest.raises(RuntimeError, match="failed donated step"):
        rt.fused_steps(4)
    # every state consumer gets the clear error, not jax's deleted-array one
    with pytest.raises(RuntimeError, match="failed donated step"):
        rt.coverage_value("v")
    with pytest.raises(RuntimeError, match="failed donated step"):
        rt.states


def test_read_until_quiescent_on_final_block_still_labeled():
    """Quiescence detected during the LAST permitted fused block must be
    reported as unreachable, not as a plain round-budget timeout (the exit
    reason is tracked, not inferred from the round count)."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="c", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("c", [(0, ("increment", 1), "w")])
    # diameter of ring(8,2) is 2: the mesh quiesces inside one 8-round
    # block, which is also the whole budget
    with pytest.raises(TimeoutError, match="unreachable"):
        rt.read_until(0, "c", Threshold(99), max_rounds=8, block=8)


def test_engine_fixed_point_schedule_independent():
    """Whole-engine determinism (SURVEY §5 permutation suite, at the top
    altitude): the same client ops issued in different orders, at
    different replicas, over different gossip topologies, through
    different block sizes, all converge to the IDENTICAL dataflow fixed
    point — the merge-schedule-independence argument that lets the BSP
    engine stand in for the reference's asynchronous FSMs."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import random_regular, scale_free
    from lasp_tpu.store import Store

    def run(order, topo_fn, block, n=24):
        store = Store(n_actors=4)
        graph = Graph(store)
        a = store.declare(id="a", type="lasp_orset", n_elems=8)
        b = store.declare(id="b", type="lasp_orset", n_elems=8)
        u = graph.union(a, b, dst="u")
        graph.filter(u, lambda x: not x.endswith("!"), dst="keep")
        ops = [
            (a, (3, ("add", "x"), "w1")),
            (a, (7, ("add", "gone!"), "w1")),
            (b, (11, ("add_all", ["y", "z"]), "w2")),
            # remove at the SAME replica as the add: observe-remove needs
            # the tokens visible locally (no gossip runs between ops here)
            (a, (7, ("remove", "gone!"), "w1")),
        ]
        rt = ReplicatedRuntime(store, graph, n, topo_fn(n))
        for i in order:
            var, (r, op, actor) = ops[i]
            rt.update_batch(var, [(r % n, op, actor)])
        rt.run_to_convergence(block=block)
        assert rt.divergence("keep") == 0
        # check the UNION too: a schedule-dependent (or silently no-op'd)
        # remove would leave "gone!" in u, which the filter on keep hides
        assert rt.coverage_value("u") == frozenset({"x", "y", "z"})
        return rt.coverage_value("keep")

    # remove-after-add must stay AFTER its add in any tested order
    # (observe-remove semantics: an unobserved remove is a precondition
    # error, exactly like the reference)
    orders = [(0, 1, 2, 3), (1, 0, 2, 3), (2, 1, 0, 3), (1, 2, 0, 3)]
    topos = [
        lambda n: ring(n, 2),
        lambda n: random_regular(n, 3, seed=2),
        lambda n: scale_free(n, 3, seed=2),
    ]
    results = {
        run(o, t, blk)
        for o in orders
        for t, blk in zip(topos, (1, 4, 8))
    }
    assert results == {frozenset({"x", "y", "z"})}


@pytest.mark.parametrize("packed", [False, True])
def test_converge_on_device_matches_host_loop(packed):
    """The single-dispatch while_loop driver reaches the same fixed point
    in the same number of rounds as the host-looped paths."""
    rt1 = _adcounter_runtime(packed=packed)
    rt2 = _adcounter_runtime(packed=packed)
    r_host = rt1.run_to_convergence(block=4)
    r_dev = rt2.converge_on_device()
    assert r_host == r_dev
    for v in rt1.var_ids:
        assert rt1.coverage_value(v) == rt2.coverage_value(v)
        assert rt2.divergence(v) == 0
    # an already-converged population bills exactly the one probe round
    assert rt2.converge_on_device() == 1


def test_converge_on_device_budget_and_mask():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 64, ring(64, 1))
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    # diameter of ring(64,1) is 32; a 4-round budget must fail loudly
    with pytest.raises(RuntimeError, match="no convergence within 4"):
        rt.converge_on_device(max_rounds=4)
    # all edges dead: quiesces immediately under the mask
    dead = jnp.zeros((64, 1), dtype=bool)
    assert rt.converge_on_device(edge_mask=dead) == 1
    assert rt.converge_on_device() >= 1
    assert rt.coverage_value("s") == {"e"}
    assert rt.divergence("s") == 0


def test_converge_on_device_under_chaos_edge_mask():
    """converge_on_device with a chaos-compiled edge mask (a masked
    FIXED point, not the fault-free one): exact round counts and
    bit-identical states vs the host-stepped loop under the SAME
    mask — the mask rides as a traced operand through the while body."""
    import jax

    from lasp_tpu.chaos import ChaosSchedule, Partition

    def build():
        store = Store(n_actors=4)
        s = store.declare(id="s", type="lasp_gset", n_elems=8)
        rt = ReplicatedRuntime(
            store, Graph(store), 48, random_regular(48, 3, seed=4)
        )
        rt.update_batch(s, [(0, ("add", "a"), "w0"),
                            (24, ("add", "b"), "w1")])
        return rt, s

    rt_d, s = build()
    rt_h, _ = build()
    sched = ChaosSchedule(
        48, random_regular(48, 3, seed=4), seed=9,
        events=[Partition(0, 1 << 30, 2)],
    )
    mask = jnp.asarray(sched.mask_at(0))
    host_rounds = 0
    while True:
        host_rounds += 1
        if rt_h.step(edge_mask=mask) == 0:
            break
    dev_rounds = rt_d.converge_on_device(edge_mask=mask)
    assert dev_rounds == host_rounds
    same = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.array_equal(a, b)),
        rt_d.states[s], rt_h.states[s],
    )
    assert all(jax.tree_util.tree_leaves(same))
    # the masked fixed point is NOT the fault-free one: healing the
    # mask converges further (non-vacuousness of the mask operand)
    assert rt_d.run_to_convergence() > 1
    assert rt_d.coverage_value(s) == {"a", "b"}


def test_converge_interleaved_with_fused_steps_no_donation():
    """donate_steps=False: the `_fused_steps_cache["while"]` entry and
    the integer-block entries share one cache — interleaving
    converge_on_device between fused_steps blocks (and a plain step)
    must keep state intact and reach the same fixed point as a twin
    running the same schedule, with no donation poisoning."""
    import jax

    def build():
        store = Store(n_actors=4)
        s = store.declare(id="s", type="lasp_gset", n_elems=8)
        rt = ReplicatedRuntime(
            store, Graph(store), 32, random_regular(32, 3, seed=7),
            donate_steps=False,
        )
        rt.update_batch(s, [(0, ("add", "a"), "w0")])
        return rt, s

    rt, s = build()
    twin, _ = build()
    # the same interleaved schedule on both: fused block -> step ->
    # on-device while -> fused block again (the "while" cache entry is
    # exercised before AND after integer-block entries)
    for r in (rt, twin):
        r.fused_steps(2)
        r.step()
        r.converge_on_device()
        r.update_batch(s, [(5, ("add", "b"), "w1")])
        r.fused_steps(3)
        r.converge_on_device()
    assert rt._poisoned is None
    assert "while" in rt._fused_steps_cache
    assert any(isinstance(k, int) for k in rt._fused_steps_cache)
    same = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.array_equal(a, b)),
        rt.states[s], twin.states[s],
    )
    assert all(jax.tree_util.tree_leaves(same))
    assert rt.coverage_value(s) == {"a", "b"}
    assert rt.divergence(s) == 0
    # undonated entry states stay readable after every dispatch (the
    # keep-state-across-failures mode's core guarantee)
    _ = rt.states[s]


def test_read_until_on_device_matches_host_loop():
    """The device-parked read (lax.while_loop threshold wait) delivers
    the same row, fails the same ways, and stops exactly when met."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    def build():
        store = Store(n_actors=2)
        graph = Graph(store)
        store.declare(id="c", type="riak_dt_gcounter")
        rt = ReplicatedRuntime(store, graph, 16, ring(16, 1))
        rt.update_batch("c", [(0, ("increment", 5), "w")])
        return rt

    rt_host, rt_dev = build(), build()
    row_h = rt_host.read_until(8, "c", Threshold(5), block=4)
    row_d = rt_dev.read_until(8, "c", Threshold(5), on_device=True)
    assert row_d is not None and row_h is not None
    assert int(row_d.counts.sum()) == int(row_h.counts.sum()) == 5
    # already-met: returns without stepping
    assert rt_dev.read_until(8, "c", Threshold(5), on_device=True) is not None
    # unreachable threshold: quiescent fast-fail with the labeled error
    with pytest.raises(TimeoutError, match="unreachable"):
        rt_dev.read_until(8, "c", Threshold(99), max_rounds=1000,
                          on_device=True)
    # budget exhaustion without quiescence (budget < diameter)
    rt2 = build()
    with pytest.raises(TimeoutError) as ei:
        rt2.read_until(8, "c", Threshold(5), max_rounds=2, on_device=True)
    assert "unreachable" not in str(ei.value)


def test_read_until_on_device_packed_orset_threshold():
    """Set-typed (state) thresholds ride as traced operands through the
    packed wire mode too."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    s = store.declare(id="s", type="lasp_orset", n_elems=4, n_actors=2,
                      tokens_per_actor=2)
    rt = ReplicatedRuntime(store, graph, 16, ring(16, 2), packed=True)
    rt.update_batch(s, [(0, ("add", "x"), "w")])
    # threshold: the state where x exists (build via a scratch store op)
    probe = Store(n_actors=2)
    p = probe.declare(id="p", type="lasp_orset", n_elems=4, n_actors=2,
                      tokens_per_actor=2)
    probe.update(p, ("add", "x"), "w")
    thr = Threshold(probe.state(p))
    row = rt.read_until(9, s, thr, on_device=True)
    assert row is not None
    assert rt.divergence(s) >= 0  # runtime still healthy post-wait


def test_read_any_until_first_match_wins():
    """lasp:read_any at the mesh surface: the first threshold met by
    gossip delivers; quiescence with none met fails fast."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="a", type="riak_dt_gcounter")
    store.declare(id="b", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, graph, 16, ring(16, 1))
    # pull ring(16,1): replica r pulls from r+1, so a write at 9 reaches
    # the reading replica 8 in one round; the write at 0 needs eight
    rt.update_batch("a", [(0, ("increment", 5), "w")])
    rt.update_batch("b", [(9, ("increment", 3), "w")])
    var, row = rt.read_any_until(
        8, [("a", Threshold(5)), ("b", Threshold(3))], block=4
    )
    assert var == "b" and int(row.counts.sum()) == 3
    # both unreachable: labeled quiescent fast-fail
    with pytest.raises(TimeoutError, match="none is reachable"):
        rt.read_any_until(
            8, [("a", Threshold(99)), ("b", Threshold(99))],
            max_rounds=500, block=4,
        )


def test_read_any_until_device_parked_default(monkeypatch):
    """The multi-threshold wait parks on the chip by default: exactly one
    host probe (the final met-row return), list-order tie-breaking, and
    host/device path agreement."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    def build():
        store = Store(n_actors=2)
        store.declare(id="a", type="riak_dt_gcounter")
        store.declare(id="b", type="riak_dt_gcounter")
        rt = ReplicatedRuntime(store, Graph(store), 16, ring(16, 1))
        rt.update_batch("a", [(0, ("increment", 5), "w")])
        rt.update_batch("b", [(9, ("increment", 3), "w")])
        return rt

    calls = {"n": 0}
    orig = ReplicatedRuntime.read_at

    def counting(self, *a, **kw):
        calls["n"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(ReplicatedRuntime, "read_at", counting)
    rt = build()
    calls["n"] = 0
    var, row = rt.read_any_until(8, [("a", Threshold(5)), ("b", Threshold(3))])
    assert var == "b" and int(row.counts.sum()) == 3
    assert calls["n"] == 1  # zero per-probe pulls; one final re-check

    # host opt-out agrees
    rt2 = build()
    var2, row2 = rt2.read_any_until(
        8, [("a", Threshold(5)), ("b", Threshold(3))], on_device=False,
        block=4,
    )
    assert (var2, int(row2.counts.sum())) == (var, 3)

    # same-round tie: both already met at the reader -> list order wins
    rt3 = build()
    var3, _row3 = rt3.read_any_until(
        0, [("b", Threshold(0)), ("a", Threshold(0))]
    )
    assert var3 == "b"

    # quiescent fast-fail on the device path too
    with pytest.raises(TimeoutError, match="none is reachable"):
        rt3.read_any_until(
            8, [("a", Threshold(99)), ("b", Threshold(99))], max_rounds=500
        )


def test_read_until_max_rounds_zero_probes_once():
    # the 'check once, never step' idiom must survive the device-parked
    # default (the old host default returned the already-met row)
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    store.declare(id="c", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    rt.update_at(3, "c", ("increment", 5), "w")
    row = rt.read_until(3, "c", Threshold(5), max_rounds=0)
    assert int(row.counts.sum()) == 5
    with pytest.raises(TimeoutError, match="within 0 rounds"):
        rt.read_until(0, "c", Threshold(5), max_rounds=0)  # not arrived
    var, _row = rt.read_any_until(
        3, [("c", Threshold(5))], max_rounds=0
    )
    assert var == "c"


def test_late_declared_variable_readable_on_all_paths():
    """A variable declared AFTER the runtime was built is readable via
    every surface — host reads, device-parked reads, coverage, quorum,
    divergence — in both dense and packed modes."""
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    for packed in (False, True):
        store = Store(n_actors=2)
        graph = Graph(store)
        rt = ReplicatedRuntime(store, graph, 8, ring(8, 2), packed=packed)
        store.declare(id="late", type="lasp_orset", n_elems=4, n_actors=2,
                      tokens_per_actor=2)
        # READ FIRST, before any write registers the packed spec: the
        # sync must run before codec resolution (a reverted ordering
        # would pair the dense codec with packed wire words)
        assert rt.coverage_value("late") == frozenset()
        assert rt.divergence("late") == 0
        rt.update_batch("late", [(0, ("add", "x"), "w")])
        assert rt.divergence("late") >= 0
        assert rt.coverage_value("late") == frozenset({"x"})
        rt.run_to_convergence(block=4)
        assert rt.quorum_value("late", [3, 4]) == frozenset({"x"})
        assert rt.replica_value("late", 5) == frozenset({"x"})
        store.declare(id="late_c", type="riak_dt_gcounter")
        rt.update_batch("late_c", [(0, ("increment", 2), "w")])
        row = rt.read_until(5, "late_c", Threshold(2), on_device=True)
        assert row is not None and int(row.counts.sum()) == 2


def test_unknown_variable_raises_without_cache_invalidation():
    """Probing a nonexistent id must raise KeyError WITHOUT rebuilding the
    graph or invalidating the compiled step (a monitoring loop probing an
    optional var would otherwise force re-jits every round)."""
    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.fused_steps(4)  # populate the executable cache
    cached = dict(rt._fused_steps_cache)
    step = rt._step
    for probe in (rt.coverage_value, rt.divergence):
        with pytest.raises(KeyError):
            probe("nope")
    with pytest.raises(KeyError):
        rt.replica_value("nope", 0)
    assert rt._step is step and dict(rt._fused_steps_cache) == cached
