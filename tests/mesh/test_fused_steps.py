"""fused_steps: the FULL engine step (sweep + triggers + gossip +
residual) in one lax.fori_loop dispatch per block — must reach the same
fixed point in the same number of rounds as the per-round path (VERDICT r2
ask #4: the engine path the 10M north-star runs through must not pay one
dispatch + host sync per round)."""

import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.store import Store


def _adcounter_runtime(n=32, packed=False, threshold=2):
    """Miniature of the north-star: union pipeline + counter + server
    trigger that removes an over-threshold ad."""
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=4, n_actors=1,
                      tokens_per_actor=1)
    b = store.declare(id="b", type="lasp_orset", n_elems=4, n_actors=1,
                      tokens_per_actor=1)
    graph.union(a, b, dst="u")
    views = store.declare(id="views", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(
        store, graph, n, random_regular(n, 3, seed=9), packed=packed
    )
    rt.update_batch("a", [(0, ("add_all", ["x", "y"]), "p")])
    rt.update_batch("b", [(1, ("add", "z"), "q")])
    rt.update_batch(
        "views", [(2, ("increment",), "c0"), (3, ("increment",), "c1")]
    )
    x_idx = rt.intern_terms("a", ["x"])

    def server(dense):
        over = jnp.sum(dense["views"].counts, dtype=jnp.int32) >= threshold
        st = dense["a"]
        mask = jnp.zeros((4,), bool).at[jnp.asarray(x_idx)].set(over)
        return {"a": st._replace(removed=st.removed | (st.exists & mask[:, None]))}

    rt.register_trigger(server)
    return rt


@pytest.mark.parametrize("packed", [False, True])
def test_fused_matches_per_round_fixed_point_and_count(packed):
    rt1 = _adcounter_runtime(packed=packed)
    rt2 = _adcounter_runtime(packed=packed)
    r1 = rt1.run_to_convergence()
    r2 = rt2.run_to_convergence(block=4)
    assert r1 == r2
    for v in rt1.var_ids:
        assert rt1.coverage_value(v) == rt2.coverage_value(v)
        assert rt2.divergence(v) == 0
    # the trigger fired everywhere: x removed once views reached threshold
    assert rt2.coverage_value("u") == {"y", "z"}


def test_fused_steps_reports_in_block_quiescent_round():
    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    # ring k=2 over 8 replicas: diameter 2, converges round 3 is quiescent
    first_zero = rt.fused_steps(8)
    assert 0 <= first_zero < 8
    # a second fused block is immediately quiescent at index 0
    assert rt.fused_steps(8) == 0
    assert rt.coverage_value("s") == {"e"}
    assert rt.divergence("s") == 0


def test_fused_block_larger_than_convergence_is_harmless():
    rt = _adcounter_runtime(n=16)
    rounds = rt.run_to_convergence(block=64)
    assert rounds <= 64
    assert rt.coverage_value("u") == {"y", "z"}


def test_fused_cache_invalidated_by_new_trigger():
    rt = _adcounter_runtime(n=16)
    rt.run_to_convergence(block=4)
    fired = {}

    def late_trigger(dense):
        fired["yes"] = True
        return {}

    rt.register_trigger(late_trigger)
    rt.fused_steps(2)
    assert fired.get("yes")


def test_edge_failure_mask_respected_in_fused_path():
    from lasp_tpu.mesh import edge_failure_mask

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("s", [(0, ("add", "e"), "w")])
    dead = jnp.zeros((8, 2), dtype=bool)  # all edges down: nothing moves
    assert rt.fused_steps(4, edge_mask=dead) >= 0
    assert rt.replica_value("s", 4) == frozenset()
    alive = jnp.asarray(edge_failure_mask(8, 2, 0.0))
    rt.run_to_convergence(block=4, edge_mask=alive)
    assert rt.coverage_value("s") == {"e"}
    assert rt.divergence("s") == 0
