"""Plan-grouped device-resident ingest (mesh.ingest): the grouped
op-table arm must be indistinguishable from sequential per-op
``update_at`` application — final states, error surfaces, frontier and
AAE dirty marks — across codecs × plan modes × failure edges, and the
cycle-level dispatch contract (one vmapped kernel per active plan group
per cycle) must hold."""

import numpy as np
import pytest

import jax

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store, PreconditionError
from lasp_tpu.utils.interning import CapacityError

N = 6


def _declare_all(store, packed_shapes=False):
    ids = {
        "g": store.declare(id="g", type="lasp_gset", n_elems=16),
        "c": store.declare(id="c", type="riak_dt_gcounter", n_actors=4),
        "o": store.declare(id="o", type="lasp_orset", n_elems=8,
                           n_actors=4, tokens_per_actor=4),
        "w": store.declare(id="w", type="riak_dt_orswot", n_elems=8,
                           n_actors=4),
        "i": store.declare(id="i", type="lasp_ivar"),
        "m": store.declare(
            id="m", type="riak_dt_map",
            fields=[("tags", "lasp_gset", {"n_elems": 8}),
                    ("hits", "riak_dt_gcounter", {})],
            n_actors=4,
        ),
    }
    return ids


def _build(plan="auto", packed=False, debug_actors=False):
    store = Store(n_actors=4)
    _declare_all(store)
    rt = ReplicatedRuntime(store, Graph(store), N, ring(N, 2),
                           plan=plan, packed=packed,
                           debug_actors=debug_actors)
    rt._aae_dirty = {}  # activate the AAE dirty accumulator (forest feed)
    return rt


_OPS = {
    "g": [(0, ("add", "a"), "x"), (1, ("add_all", ["b", "c"]), "x"),
          (0, ("add", "a"), "x"), (2, ("add", "b"), "x")],
    "c": [(0, ("increment",), "a0"), (1, ("increment", 3), "a1"),
          (0, ("increment", 2), "a0")],
    "o": [(0, ("add", "e1"), "a0"), (0, ("add_all", ["e2", "e3"]), "a0"),
          (0, ("remove", "e1"), "a0"), (0, ("add", "e1"), "a1"),
          (3, ("add", "e2"), "a3"), (0, ("remove_all", ["e2", "e3"]), "a0")],
    "w": [(2, ("add", "s1"), "a2"), (2, ("add_all", ["s2", "s3"]), "a2"),
          (2, ("remove", "s1"), "a2"), (4, ("add", "s1"), "a0"),
          (2, ("add", "s1"), "a2")],
    "i": [(0, ("set", "v1"), "x"), (0, ("set", "v2"), "x"),
          (3, ("set", "v3"), "x")],
    "m": [(0, ("update", "tags", ("add", "t1")), "w0"),
          (1, ("update", "hits", ("increment", 2)), "w1"),
          (0, ("remove", "tags"), "w0"),
          (0, ("update", "tags", ("add", "t2")), "w0")],
}


def _states_np(rt, v):
    return jax.tree_util.tree_map(np.asarray, rt.states[v])


def _assert_same_var(rt_a, rt_b, v):
    a, b = _states_np(rt_a, v), _states_np(rt_b, v)
    same = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(x, y)), a, b
    )
    assert all(jax.tree_util.tree_leaves(same)), f"{v}: states diverged"
    fa = rt_a._frontier.get(v)
    fb = rt_b._frontier.get(v)
    assert np.array_equal(
        fa if fa is not None else np.zeros(N, bool),
        fb if fb is not None else np.zeros(N, bool),
    ), f"{v}: frontier marks diverged"
    da = rt_a._aae_dirty.get(v)
    db = rt_b._aae_dirty.get(v)
    assert np.array_equal(
        da if da is not None else np.zeros(N, bool),
        db if db is not None else np.zeros(N, bool),
    ), f"{v}: AAE dirty marks diverged"


@pytest.mark.parametrize("var", ["g", "c", "o", "w", "i", "m"])
@pytest.mark.parametrize("packed", [False, True])
def test_grouped_matches_per_op(var, packed):
    """THE bit-identity matrix: grouped op-table application ==
    sequential per-op update_at — states, frontier, AAE marks — for
    every codec (map via the per-var fallback) in dense and packed
    mode."""
    grouped = _build("auto", packed=packed)
    ref = _build("auto", packed=packed)
    grouped.update_batch(var, list(_OPS[var]))
    for r, op, actor in _OPS[var]:
        try:
            ref.update_at(r, var, op, actor)
        except Exception:
            pass  # non-inflations etc. never raise here by construction
    _assert_same_var(grouped, ref, var)


@pytest.mark.parametrize("packed", [False, True])
def test_grouped_matches_plan_off(packed):
    """Whole-store sweep: plan=auto vs plan=off land bit-identical
    states (the legacy arm is the per_var bench arm)."""
    a = _build("auto", packed=packed)
    b = _build("off", packed=packed)
    for var, ops in _OPS.items():
        a.update_batch(var, list(ops))
        b.update_batch(var, list(ops))
    for var in _OPS:
        sa, sb = _states_np(a, var), _states_np(b, var)
        same = jax.tree_util.tree_map(
            lambda x, y: bool(np.array_equal(x, y)), sa, sb
        )
        assert all(jax.tree_util.tree_leaves(same)), var


def _dispatch_total():
    from lasp_tpu.telemetry.registry import get_registry

    ent = get_registry().snapshot().get("ingest_apply_dispatches_total")
    return sum(s["value"] for s in ent["series"]) if ent else 0


def test_ingest_cycle_one_dispatch_per_group():
    """A multi-var cycle lands in ONE kernel dispatch per plan group:
    here 2 gset vars share a signature (one dispatch), the counter is
    its own group, and the map rides the per-var fallback (zero grouped
    dispatches)."""
    store = Store(n_actors=4)
    g1 = store.declare(id="g1", type="lasp_gset", n_elems=16)
    g2 = store.declare(id="g2", type="lasp_gset", n_elems=16)
    c1 = store.declare(id="c1", type="riak_dt_gcounter", n_actors=4)
    m1 = store.declare(
        id="m1", type="riak_dt_map",
        fields=[("hits", "riak_dt_gcounter", {})], n_actors=4,
    )
    rt = ReplicatedRuntime(store, Graph(store), N, ring(N, 2))
    before = _dispatch_total()
    report = rt.ingest_cycle({
        g1: [(0, ("add", "a"), "x")],
        g2: [(1, ("add", "b"), "x"), (2, ("add", "c"), "x")],
        c1: [(0, ("increment",), "a0")],
        m1: [(0, ("update", "hits", ("increment",)), "w0")],
    })
    assert report["dispatches"] == 2  # {g1, g2} stacked + {c1}
    assert _dispatch_total() - before == 2
    assert report["errors"] == {}
    assert report["fallback_vars"] == [m1]
    assert rt.coverage_value(g1) == {"a"}
    assert rt.coverage_value(g2) == {"b", "c"}
    assert rt.coverage_value(c1) == 1
    assert rt.coverage_value(m1) == {"hits": 1}
    # grouped marks are EXACT: only the written rows are dirty
    assert np.flatnonzero(rt._frontier[g2]).tolist() == [1, 2]


def test_orset_remove_not_present_identical():
    """The failure-edge contract: OR-Set remove of an absent element
    fails at its position with the prefix persisted — error type,
    final state, and marks identical between the grouped arm and the
    per-op update_at loop."""
    grouped = _build("auto")
    ref = _build("auto")
    ops = [(0, ("add", "e1"), "a0"), (1, ("remove", "missing"), "a1"),
           (2, ("add", "e2"), "a2")]
    with pytest.raises(PreconditionError) as gexc:
        grouped.update_batch("o", list(ops))
    assert gexc.value.batch_index == 1
    ref_exc = None
    for r, op, actor in ops:
        try:
            ref.update_at(r, "o", op, actor)
        except PreconditionError as exc:
            ref_exc = exc
            break  # sequential semantics: stop at the failure
    assert type(ref_exc).__name__ == type(gexc.value).__name__
    assert str(ref_exc) == str(gexc.value)
    _assert_same_var(grouped, ref, "o")


def test_map_late_declared_fields_identical():
    """riak_dt_map fields admitted mid-batch (dynamic {Name, Type}
    keys): identical result between the grouped arm's fallback and
    per-op update_at, including the late-declare spec/population
    sync."""
    KEY = ("S", "lasp_gset")
    KEY2 = ("C", "riak_dt_gcounter")

    def fresh():
        store = Store(n_actors=4)
        rt = ReplicatedRuntime(store, Graph(store), N, ring(N, 2))
        # declared AFTER the runtime was built: no population row yet —
        # the late-declare sync must run before field admission
        m = store.declare(id="m", type="riak_dt_map", n_actors=4)
        rt._aae_dirty = {}
        return rt, m

    ops = [
        (0, ("update", [("update", KEY, ("add", "x"))]), "w0"),
        (1, ("update", [("update", KEY2, ("increment", 2))]), "w1"),
        (0, ("update", [("update", KEY2, ("increment",))]), "w0"),
    ]
    grouped, m = fresh()
    grouped.update_batch(m, list(ops))
    ref, m2 = fresh()
    for r, op, actor in ops:
        ref.update_at(r, m2, op, actor)
    ga, rb = grouped.coverage_value(m), ref.coverage_value(m2)
    assert ga == rb
    a, b = _states_np(grouped, m), _states_np(ref, m2)
    same = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(x, y)), a, b
    )
    assert all(jax.tree_util.tree_leaves(same))


def test_chaos_crashed_replica_refusal_identical():
    """ChaosRuntime.write_batch == a per-op write_at loop: ops before
    the first one targeting a crashed replica apply, the refused op
    raises ReplicaDownError, nothing after applies."""
    from lasp_tpu.chaos.engine import ChaosRuntime, ReplicaDownError
    from lasp_tpu.chaos.schedule import ChaosSchedule, Crash

    def fresh():
        store = Store(n_actors=4)
        store.declare(id="g", type="lasp_gset", n_elems=16)
        rt = ReplicatedRuntime(store, Graph(store), N, ring(N, 2))
        ch = ChaosRuntime(rt, ChaosSchedule(
            N, ring(N, 2), [Crash(0, 2)], seed=3,
        ))
        ch.step()  # executes the crash
        assert ch.crashed[2]
        return rt, ch

    ops = [(0, ("add", "a"), "x"), (1, ("add", "b"), "x"),
           (2, ("add", "c"), "x"), (3, ("add", "d"), "x")]
    rt_b, ch_b = fresh()
    with pytest.raises(ReplicaDownError) as bexc:
        ch_b.write_batch("g", list(ops))
    assert bexc.value.batch_index == 2
    rt_s, ch_s = fresh()
    seq_exc = None
    for r, op, actor in ops:
        try:
            ch_s.write_at(r, "g", op, actor)
        except ReplicaDownError as exc:
            seq_exc = exc
            break
    assert seq_exc is not None
    assert str(seq_exc) == str(bexc.value)
    sa, sb = _states_np(rt_b, "g"), _states_np(rt_s, "g")
    same = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(x, y)), sa, sb
    )
    assert all(jax.tree_util.tree_leaves(same))
    assert rt_b.coverage_value("g") == {"a", "b"}


def test_actor_collision_refusal_identical():
    """debug_actors: a lane collision inside one batch refuses
    all-or-nothing under both arms — same error, nothing applied."""
    from lasp_tpu.mesh.runtime import ActorCollisionError

    for plan in ("auto", "off"):
        rt = _build(plan, debug_actors=True)
        with pytest.raises(ActorCollisionError):
            rt.update_batch("w", [(0, ("add", "x"), "a0"),
                                  (1, ("add", "y"), "a0")])
        assert rt.coverage_value("w") == frozenset()
        f = rt._frontier.get("w")
        assert f is None or not f.any()


def test_capacity_prefix_identical():
    """Interner overflow mid-batch: the grouped arm persists exactly
    the fitting prefix and raises CapacityError, like per-op."""
    grouped = _build("auto")
    ref = _build("auto")
    ops = [(0, ("add", f"t{i}"), "a0") for i in range(20)]
    with pytest.raises(CapacityError):
        grouped.update_batch("g", list(ops))
    for r, op, actor in ops:
        try:
            ref.update_at(r, "g", op, actor)
        except CapacityError:
            break
    _assert_same_var(grouped, ref, "g")


def test_ivar_first_set_wins_and_exact_marks():
    """IVar single-assignment under the grouped arm: per row the first
    set wins, an already-defined row's set is a NON-inflation and marks
    nothing (the exact-changed-flags contract)."""
    rt = _build("auto")
    rt.update_batch("i", [(0, ("set", "v1"), "x")])
    rt._frontier["i"][:] = False
    rt._aae_dirty["i"][:] = False
    rt.update_batch("i", [(0, ("set", "v2"), "x"),
                          (1, ("set", "v3"), "x")])
    # row 0 was already defined: no state change, no mark; row 1 fresh
    assert np.flatnonzero(rt._frontier["i"]).tolist() == [1]
    assert np.flatnonzero(rt._aae_dirty["i"]).tolist() == [1]
    assert rt.replica_value("i", 0) == "v1"
    assert rt.replica_value("i", 1) == "v3"


def test_isolate_errors_per_var():
    """ingest_cycle(isolate_errors=True): a failing variable's error is
    reported, the other variables' ops land (the serving front-end's
    per-variable isolation contract)."""
    rt = _build("auto")
    report = rt.ingest_cycle({
        "o": [(0, ("remove", "absent"), "a0")],
        "g": [(1, ("add", "ok"), "x")],
    }, isolate_errors=True)
    assert set(report["errors"]) == {"o"}
    assert isinstance(report["errors"]["o"], PreconditionError)
    assert rt.coverage_value("g") == {"ok"}


def test_group_dispatch_failure_does_not_strand_cycle(monkeypatch):
    """Review regression: a grouped kernel failure fails ITS batches
    typed but must not skip the cycle's other bookkeeping — the other
    group still applies, every batch still lands its dirty marks /
    telemetry, and errors surface per variable (the serve layer's
    no-silent-drop contract depends on this)."""
    from lasp_tpu.mesh import ingest as ingest_mod

    store = Store(n_actors=4)
    g1 = store.declare(id="g1", type="lasp_gset", n_elems=16)
    c1 = store.declare(id="c1", type="riak_dt_gcounter", n_actors=4)
    rt = ReplicatedRuntime(store, Graph(store), N, ring(N, 2))

    real_kernel_for = ingest_mod.kernel_for

    def failing_kernel_for(kind, g, buckets, state_sig, donate):
        if kind == "gcounter":
            def boom(states, tables):
                raise RuntimeError("injected kernel failure")
            return boom
        return real_kernel_for(kind, g, buckets, state_sig, donate)

    monkeypatch.setattr(ingest_mod, "kernel_for", failing_kernel_for)
    report = rt.ingest_cycle({
        c1: [(0, ("increment",), "a0")],
        g1: [(1, ("add", "ok"), "x")],
    }, isolate_errors=True)
    assert "injected kernel failure" in str(report["errors"][c1])
    assert g1 not in report["errors"]
    assert rt.coverage_value(g1) == {"ok"}  # the healthy group applied
    # the failed batch's conservative bookkeeping still landed
    # (superset marking: its touched row is dirty even though the
    # kernel never ran — over-marking is sound, stranding is not)
    assert rt._frontier[c1][0]
    assert np.flatnonzero(rt._frontier[g1]).tolist() == [1]


def test_quorum_put_mints_ride_grouped_ingest():
    """The quorum put path mints coordinator deltas through the grouped
    arm: a round's puts across same-signature vars cost one grouped
    dispatch (plus gathers), and results match the historical
    behavior."""
    from lasp_tpu.quorum import QuorumRuntime

    store = Store(n_actors=8)
    a = store.declare(id="qa", type="lasp_gset", n_elems=16)
    b = store.declare(id="qb", type="lasp_gset", n_elems=16)
    rt = ReplicatedRuntime(store, Graph(store), N, ring(N, 2))
    q = QuorumRuntime(rt)
    before = _dispatch_total()
    r1 = q.submit_put(a, ("add", "x"), "w0", coordinator=0)
    r2 = q.submit_put(b, ("add", "y"), "w1", coordinator=1)
    q.step()  # both PREPARE puts mint in one ingest cycle
    assert _dispatch_total() - before == 1  # same signature: one group
    for _ in range(16):
        if q.result(r1)["status"] == "done" and \
                q.result(r2)["status"] == "done":
            break
        q.step()
    assert q.result(r1)["status"] == "done"
    assert q.result(r2)["status"] == "done"
    assert rt.coverage_value(a) == {"x"}
    assert rt.coverage_value(b) == {"y"}
