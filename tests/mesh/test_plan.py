"""Dispatch-plan (megabatch gossip) property tier: grouping same-codec
variables into stacked ``[G, R, ...]`` kernels must be BIT-IDENTICAL to
per-var stepping — same per-round states, residual sequences, and
frontier masks — across codecs (leafwise / vclock / packed), dense and
frontier schedulers, ring/random topologies, and chaos edge masks
(ISSUE-5 acceptance). Plus the plan-cache lifecycle: resize, checkpoint
restore, chaos mask flips, and late-declared map fields must each force
a recompile (plan invalidation) rather than stepping a stale grouping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import GSet, GSetSpec
from lasp_tpu.lattice.base import replicate
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.mesh.gossip import (
    gossip_round,
    gossip_round_grouped,
    gossip_round_rows,
    gossip_round_rows_grouped,
)
from lasp_tpu.mesh.plan import compile_plan
from lasp_tpu.mesh.topology import edge_failure_mask
from lasp_tpu.ops.fused import (
    fused_chaos_rounds,
    fused_chaos_rounds_grouped,
    fused_gossip_rounds,
    fused_gossip_rounds_grouped,
)
from lasp_tpu.store import Store
from lasp_tpu.telemetry import registry as tel_registry


def _tree_eq(a, b) -> bool:
    flags = jax.tree_util.tree_map(
        lambda x, y: bool(jnp.array_equal(x, y)), a, b
    )
    return all(jax.tree_util.tree_leaves(flags))


def _seed_mixed(rt, ids, n, seed=7, writes=4):
    rng = np.random.RandomState(seed)
    for v in ids:
        rows = rng.choice(n, writes, replace=False)
        tn = rt.store.variable(v).type_name
        if tn == "lasp_gset":
            rt.update_batch(
                v, [(int(r), ("add", f"e{r % 4}"), f"a{r}") for r in rows]
            )
        elif tn == "riak_dt_gcounter":
            rt.update_batch(
                v,
                [(int(r), ("increment",), ("lane", int(r) % 4))
                 for r in rows],
            )
        elif tn in ("lasp_orset", "lasp_orset_gbtree"):
            rt.update_batch(
                v, [(int(r), ("add", f"t{r % 6}"), f"w{r % 4}")
                    for r in rows]
            )
        else:  # riak_dt_orswot
            rt.update_batch(
                v, [(int(r), ("add", f"x{r % 8}"), f"w{r % 4}")
                    for r in rows]
            )


def _build_mixed(plan, n, nbrs, packed=False):
    store = Store(n_actors=4)
    ids = [store.declare(id=f"g{i}", type="lasp_gset", n_elems=16)
           for i in range(3)]
    ids += [store.declare(id=f"c{i}", type="riak_dt_gcounter", n_actors=4)
            for i in range(2)]
    ids += [store.declare(id=f"o{i}", type="riak_dt_orswot", n_elems=8,
                          n_actors=4)
            for i in range(2)]
    ids += [store.declare(id=f"s{i}", type="lasp_orset", n_elems=8,
                          n_actors=4, tokens_per_actor=2)
            for i in range(2)]
    rt = ReplicatedRuntime(store, Graph(store), n, nbrs, packed=packed,
                           plan=plan)
    _seed_mixed(rt, ids, n)
    return rt, ids


# -- grouping ---------------------------------------------------------------

def test_plan_groups_by_signature():
    n = 32
    rt, _ids = _build_mixed("auto", n, random_regular(n, 3, seed=5))
    plan = rt._ensure_plan()
    sizes = sorted(len(g) for g in plan.groups)
    # 4 signatures: gset x3, gcounter x2, orswot x2, orset x2
    assert sizes == [2, 2, 2, 3]
    assert plan.n_vars == 9
    for g in plan.groups:
        metas = {rt._mesh_meta(v) for v in g.var_ids}
        assert len(metas) == 1  # every member shares (codec, spec)


def test_plan_groups_split_on_spec_mismatch():
    n = 16
    store = Store(n_actors=4)
    store.declare(id="a", type="lasp_gset", n_elems=16)
    store.declare(id="b", type="lasp_gset", n_elems=16)
    store.declare(id="w", type="lasp_gset", n_elems=32)  # different shape
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    plan = rt._ensure_plan()
    assert sorted(len(g) for g in plan.groups) == [1, 2]


def test_plan_groups_packed_mode_by_wire_spec():
    # packed OR-Sets group by their FlatORSetSpec (the wire format the
    # mesh actually steps), not the dense spec
    n = 16
    store = Store(n_actors=4)
    for i in range(3):
        store.declare(id=f"p{i}", type="lasp_orset", n_elems=8,
                      n_actors=4, tokens_per_actor=2)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2), packed=True)
    plan = rt._ensure_plan()
    assert [len(g) for g in plan.groups] == [3]
    from lasp_tpu.ops.flatpack import FlatORSet

    assert plan.groups[0].codec is FlatORSet


# -- bit-identity: planned vs per-var ---------------------------------------

@pytest.mark.parametrize("topo", ["random", "ring"])
@pytest.mark.parametrize("scheduler", ["frontier", "dense"])
def test_planned_bitidentical_to_pervar(topo, scheduler):
    n = 64
    nbrs = (random_regular(n, 3, seed=11) if topo == "random"
            else ring(n, 2))
    rt_p, ids = _build_mixed("auto", n, nbrs)
    rt_o, _ = _build_mixed("off", n, nbrs)
    verb = "frontier_step" if scheduler == "frontier" else "step"
    for rnd in range(64):
        rp, ro = getattr(rt_p, verb)(), getattr(rt_o, verb)()
        assert rp == ro, (rnd, rp, ro)
        for v in ids:
            assert _tree_eq(rt_p.states[v], rt_o.states[v]), (rnd, v)
            if scheduler == "frontier":
                assert (rt_p._frontier[v] == rt_o._frontier[v]).all(), (
                    rnd, v,
                )
        if ro == 0:
            break
    assert ro == 0, "no convergence within 64 rounds"


def test_planned_bitidentical_under_edge_mask():
    n = 48
    nbrs = random_regular(n, 3, seed=13)
    mask = edge_failure_mask(n, 3, 0.3, seed=3, neighbors=nbrs)
    rt_p, ids = _build_mixed("auto", n, nbrs)
    rt_o, _ = _build_mixed("off", n, nbrs)
    for rnd in range(64):
        rp, ro = rt_p.frontier_step(mask), rt_o.frontier_step(mask)
        assert rp == ro, (rnd, rp, ro)
        for v in ids:
            assert _tree_eq(rt_p.states[v], rt_o.states[v]), (rnd, v)
        if ro == 0:
            break
    assert ro == 0  # the MASKED fixed point


def test_planned_bitidentical_packed():
    n = 48
    nbrs = random_regular(n, 3, seed=17)
    store_kw = dict(type="lasp_orset", n_elems=8, n_actors=4,
                    tokens_per_actor=2)

    def build(plan):
        store = Store(n_actors=4)
        ids = [store.declare(id=f"p{i}", **store_kw) for i in range(4)]
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs, packed=True,
                               plan=plan)
        _seed_mixed(rt, ids, n)
        return rt, ids

    rt_p, ids = build("auto")
    rt_o, _ = build("off")
    for rnd in range(64):
        rp, ro = rt_p.frontier_step(), rt_o.frontier_step()
        assert rp == ro
        for v in ids:
            assert _tree_eq(rt_p.states[v], rt_o.states[v]), (rnd, v)
        if ro == 0:
            break
    assert ro == 0


def test_quiescent_member_rides_group_as_empty_rowmask():
    # one member of a group is quiescent while its peers are dirty: the
    # group dispatch must leave it bit-untouched with an EMPTY frontier
    # (not degrade it dense, not re-dirty it)
    n = 32
    nbrs = random_regular(n, 3, seed=23)
    store = Store(n_actors=4)
    hot = store.declare(id="hot", type="lasp_gset", n_elems=16)
    cold = store.declare(id="cold", type="lasp_gset", n_elems=16)
    rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
    rt.update_batch(hot, [(0, ("add", "h"), "a0")])
    assert rt.frontier_size(cold) == 0
    before = jax.tree_util.tree_map(np.asarray, rt.states[cold])
    assert rt.frontier_step() > 0  # the hot member spread
    assert _tree_eq(rt.states[cold], before)
    assert rt.frontier_size(cold) == 0


# -- plan-cache invalidation -------------------------------------------------

def _invalidations(reason: str) -> int:
    snap = tel_registry.get_registry().snapshot().get(
        "plan_invalidation_total", {"series": []}
    )
    return sum(
        s["value"] for s in snap["series"]
        if s["labels"].get("reason") == reason
    )


def test_plan_invalidated_on_resize():
    n = 24
    rt, ids = _build_mixed("auto", n, random_regular(n, 3, seed=5))
    rt.run_to_convergence(mode="frontier", max_rounds=64)
    plan0 = rt._plan
    assert plan0 is not None
    before = _invalidations("resize")
    rt.resize(n + 8, random_regular(n + 8, 3, seed=6))
    assert rt._plan is None  # stale grouping dropped
    assert _invalidations("resize") == before + 1
    plan1 = rt._ensure_plan()
    assert plan1 is not plan0
    assert plan1.n_replicas == n + 8
    assert rt.run_to_convergence(mode="frontier", max_rounds=64) >= 1


def test_plan_invalidated_on_checkpoint_row_restore(tmp_path):
    from lasp_tpu.store import checkpoint

    n = 16
    rt, ids = _build_mixed("auto", n, ring(n, 2))
    rt.run_to_convergence(mode="frontier", max_rounds=64)
    path = str(tmp_path / "rt.ckpt")
    checkpoint.save_runtime(rt, path)
    rows = checkpoint.load_runtime_rows(path, 3)
    assert rt._plan is not None
    before = _invalidations("restore")
    rt.reseed_row(3, rows)
    assert rt._plan is None
    assert _invalidations("restore") == before + 1
    # recompile-or-degrade: stepping after the restore regroups and the
    # restored row re-converges with its peers
    assert rt.run_to_convergence(mode="frontier", max_rounds=64) >= 1
    assert all(rt.divergence(v) == 0 for v in ids)


def test_plan_invalidated_on_chaos_mask_flip():
    n = 24
    nbrs = random_regular(n, 3, seed=5)
    rt, ids = _build_mixed("auto", n, nbrs)
    rt.frontier_step()  # compiles the unmasked plan kernels
    assert rt._plan is not None
    mask = edge_failure_mask(n, 3, 0.25, seed=1, neighbors=nbrs)
    before = _invalidations("mask_change")
    rt.frontier_step(mask)
    assert _invalidations("mask_change") == before + 1
    # the flip also degraded every frontier (the PR3 mask rule) and the
    # next call recompiled a plan for the masked regime
    assert rt._plan is not None


def test_plan_invalidated_on_late_map_field():
    n = 16
    store = Store(n_actors=4)
    store.declare(id="m1", type="riak_dt_map", n_actors=4)
    store.declare(id="m2", type="riak_dt_map", n_actors=4)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    # seed both maps with the SAME first field so their specs (and plan
    # signatures) agree
    key = ("S", "lasp_gset")
    rt.update_at(0, "m1", ("update", [("update", key, ("add", "a"))]), "w")
    rt.update_at(0, "m2", ("update", [("update", key, ("add", "a"))]), "w")
    plan0 = rt._ensure_plan()
    assert [len(g) for g in plan0.groups] == [2]  # identical map specs
    before = _invalidations("map_growth")
    # admit a NEW field on m1 only: its spec (and state planes) grow, so
    # the old two-member group is stale — the plan must recompile and
    # split them
    key2 = ("C", "riak_dt_gcounter")
    rt.update_at(
        0, "m1", ("update", [("update", key2, ("increment",))]), "w2"
    )
    assert _invalidations("map_growth") >= before + 1
    plan1 = rt._ensure_plan()
    assert plan1 is not plan0
    assert sorted(len(g) for g in plan1.groups) == [1, 1]
    assert rt.run_to_convergence(max_rounds=64) >= 1
    assert rt.divergence("m1") == 0 and rt.divergence("m2") == 0


# -- grouped kernels (codec level) ------------------------------------------

def _stacked_gset(n, g=3, seed=3):
    spec = GSetSpec(n_elems=16)
    rng = np.random.RandomState(seed)
    states = []
    for _ in range(g):
        st = replicate(GSet.new(spec), n)
        rows = rng.choice(n, 4, replace=False)
        st = st._replace(
            mask=st.mask.at[jnp.asarray(rows),
                            jnp.asarray(rows % 16)].set(True)
        )
        states.append(st)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return spec, states, stacked


def test_gossip_round_grouped_matches_pervar():
    n = 32
    nbrs = jnp.asarray(random_regular(n, 3, seed=9))
    spec, states, stacked = _stacked_gset(n)
    out = gossip_round_grouped(GSet, spec, stacked, nbrs)
    for i, st in enumerate(states):
        ref = gossip_round(GSet, spec, st, nbrs)
        assert _tree_eq(jax.tree_util.tree_map(lambda x: x[i], out), ref)


def test_gossip_round_rows_grouped_valid_mask():
    n = 32
    nbrs = jnp.asarray(random_regular(n, 3, seed=9))
    spec, states, stacked = _stacked_gset(n, g=2)
    # member 1 is genuinely QUIESCENT (bottom everywhere — the only
    # shape the empty-row-mask contract covers: pad-slot writes carry
    # the joined value, which is a no-op only at a fixed point; a
    # diverged all-invalid member never reaches the kernel because the
    # runtime stacks only ACTIVE members)
    states[1] = replicate(GSet.new(spec), n)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    rows = np.array([[1, 5, 9, 1], [0, 0, 0, 0]], dtype=np.int64)
    valid = np.array([[True, True, True, False],
                      [False, False, False, False]])
    out, changed = gossip_round_rows_grouped(
        GSet, spec, stacked, nbrs, rows, valid
    )
    # member 1 (all-invalid, quiescent): bit-untouched, changed all False
    assert _tree_eq(
        jax.tree_util.tree_map(lambda x: x[1], out), states[1]
    )
    assert not np.asarray(changed)[1].any()
    # member 0: identical to the per-var rows kernel on the valid rows
    ref, ref_changed = gossip_round_rows(
        GSet, spec, states[0], nbrs, rows[0][:3]
    )
    assert _tree_eq(jax.tree_util.tree_map(lambda x: x[0], out), ref)
    assert (np.asarray(changed)[0][:3] == np.asarray(ref_changed)).all()


def test_fused_grouped_rounds_match_pervar():
    n = 32
    nbrs = jnp.asarray(random_regular(n, 3, seed=29))
    spec, states, stacked = _stacked_gset(n)
    out, changed = fused_gossip_rounds_grouped(GSet, spec, stacked, nbrs, 3)
    for i, st in enumerate(states):
        ref, ref_changed = fused_gossip_rounds(GSet, spec, st, nbrs, 3)
        assert _tree_eq(jax.tree_util.tree_map(lambda x: x[i], out), ref)
        assert bool(changed[i]) == bool(ref_changed)


def test_fused_chaos_grouped_composes_stacked_masks():
    # stacked-mask chaos windows x stacked-variable groups: the [T, R, K]
    # schedule and the [G, R, ...] group compose in one dispatch,
    # bit-identical per member to the per-var chaos kernel
    n = 32
    nbrs_np = random_regular(n, 3, seed=31)
    nbrs = jnp.asarray(nbrs_np)
    spec, states, stacked = _stacked_gset(n)
    rng = np.random.RandomState(4)
    masks = np.stack([
        edge_failure_mask(n, 3, f, seed=int(rng.randint(99)),
                          neighbors=nbrs_np)
        for f in (0.4, 0.2, 0.0)
    ])
    out, res = fused_chaos_rounds_grouped(GSet, spec, stacked, nbrs, masks)
    assert res.shape == (3, 3)  # [T, G]
    for i, st in enumerate(states):
        ref, ref_res = fused_chaos_rounds(GSet, spec, st, nbrs, masks)
        assert _tree_eq(jax.tree_util.tree_map(lambda x: x[i], out), ref)
        assert (np.asarray(res)[:, i] == np.asarray(ref_res)).all()


@pytest.mark.parametrize("mode", ["gather", "alltoall"])
def test_partitioned_grouped_round_matches_pervar(mode):
    # the boundary exchange with a leading group axis: one collective
    # moves all G members' cut rows; per-member results identical to the
    # ungrouped round on the 8-virtual-device mesh
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from lasp_tpu.mesh.shard_gossip import (
        partition_tables,
        partitioned_gossip_plan,
        partitioned_gossip_round_fn,
        partitioned_gossip_round_grouped,
    )

    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provision 8 virtual CPU devices"
    mesh = Mesh(np.array(devs[:8]), ("replicas",))
    n = 64
    nbrs = random_regular(n, 3, seed=37)
    plan = partitioned_gossip_plan(nbrs, 8)
    spec, states, stacked = _stacked_gset(n, g=3, seed=21)
    shard = NamedSharding(mesh, P("replicas"))
    g_shard = NamedSharding(mesh, P(None, "replicas"))
    send, idx = partition_tables(plan, mesh, mode=mode)
    grouped_fn = partitioned_gossip_round_grouped(
        GSet, spec, mesh, plan, mode=mode
    )
    pervar_fn = partitioned_gossip_round_fn(GSet, spec, mesh, plan,
                                            mode=mode)
    stacked_dev = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, g_shard), stacked
    )
    out = jax.jit(grouped_fn)(stacked_dev, send, idx)
    for i, st in enumerate(states):
        st_dev = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shard), st
        )
        ref = jax.jit(pervar_fn)(st_dev, send, idx)
        assert _tree_eq(jax.tree_util.tree_map(lambda x: x[i], out), ref)
        # and both agree with the dense unsharded reference round
        dense = gossip_round(GSet, spec, states[i], jnp.asarray(nbrs))
        assert _tree_eq(ref, dense)


def test_hot_member_promotes_only_itself_to_dense():
    # one all-dirty member must not drag its small-frontier peers
    # through the full-population dense round: the crossover is decided
    # PER MEMBER, so the round's row work is R + |peer reach|, not 2R
    n = 64
    nbrs = random_regular(n, 3, seed=41)
    store = Store(n_actors=4)
    hot = store.declare(id="hot", type="lasp_gset", n_elems=16)
    cold = store.declare(id="cold", type="lasp_gset", n_elems=16)
    rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
    rt.update_batch(cold, [(0, ("add", "c"), "a0")])
    rt.update_batch(hot, [(int(r), ("add", f"h{r % 4}"), f"w{r}")
                          for r in range(n)])
    assert rt.frontier_size(hot) == n  # all-dirty: past any crossover
    rt.frontier_step()
    # hot went dense (n rows); cold stayed sparse (its tiny reach set)
    assert n < rt.frontier_rows_last < 2 * n, rt.frontier_rows_last


def test_residual_gauge_coherent_across_schedulers():
    # the frontier path's skip-if-unchanged gauge cache must observe
    # dense-step writes too: dense writes X, then a frontier round
    # reproducing the PRE-dense value must still set the gauge
    n = 16
    store = Store(n_actors=4)
    v = store.declare(id="v", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    rt.update_batch(v, [(0, ("add", "x"), "a0")])
    rt.frontier_step()  # warms instruments + seeds the caches

    def gauge_value():
        snap = tel_registry.get_registry().snapshot()["gossip_residual"]
        return next(
            s["value"] for s in snap["series"]
            if s["labels"] == {"var": "v"}
        )

    rt._emit_frontier_telemetry([3], 3, 3, 0, 0, 1e-6)
    assert gauge_value() == 3
    rt._emit_step_telemetry(np.array([7], dtype=np.int32), 7, 1e-6)
    assert gauge_value() == 7
    # same residual as the earlier frontier round: a stale cache would
    # skip this set and leave the dense value exported
    rt._emit_frontier_telemetry([3], 3, 3, 0, 0, 1e-6)
    assert gauge_value() == 3


def test_compile_plan_counts_and_gauges():
    n = 16
    rt, _ids = _build_mixed("auto", n, ring(n, 2))
    reg = tel_registry.get_registry()
    before = reg.counter("plan_compile_total").value
    plan = compile_plan(rt)
    assert reg.counter("plan_compile_total").value == before + 1
    snap = reg.snapshot()
    assert snap["gossip_plan_groups"]["series"][0]["value"] == len(
        plan.groups
    )


def test_plan_off_never_groups():
    n = 16
    rt, _ids = _build_mixed("off", n, ring(n, 2))
    assert rt._ensure_plan() is None
    rt.frontier_step()
    assert rt._plan is None
