"""Worker for the two-process jax.distributed smoke test (launched by
test_multiprocess.py with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID in the env; 4 virtual CPU devices per process form one
8-device global mesh — the CPU stand-in for a DCN-spanned pod).

SPMD discipline: every process executes the SAME host program; all math
on globally-sharded arrays happens inside jit (eager indexing of a
non-fully-addressable array is illegal), which is exactly how a real
multi-host deployment drives the engine."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from lasp_tpu.mesh.comm import (  # noqa: E402
    build_mesh,
    init_distributed,
    n_slices,
)

assert init_distributed(), "env wiring should trigger initialization"
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

# one "slice" per DCN island (= OS process here): the canonical mesh
# puts that axis outermost so gossip rides the intra-process axis
mesh = build_mesh(slice_of=lambda d: d.process_index)
assert mesh.shape == {"slices": 2, "replicas": 4, "state": 1}, mesh.shape
assert n_slices(slice_of=lambda d: d.process_index) == 2

import jax.numpy as jnp  # noqa: E402

from lasp_tpu.dataflow import Graph  # noqa: E402
from lasp_tpu.lattice import GCounter  # noqa: E402
from lasp_tpu.mesh import ReplicatedRuntime, divergence, ring  # noqa: E402
from lasp_tpu.store import Store  # noqa: E402

R = 64
store = Store(n_actors=4)
c = store.declare(id="c", type="riak_dt_gcounter")
rt = ReplicatedRuntime(store, Graph(store), R, ring(R, 2))
rt.shard(mesh)  # canonical (slices, replicas) population split

var = store.variable(c)

# seeds land inside jit: rows 0 (slice 0's block) and 37 (slice 1's)
rt.apply_batch(c, jax.jit(
    lambda s: s._replace(
        counts=s.counts.at[0, 0].add(5).at[37, 1].add(2)
    )
))

rounds = rt.run_to_convergence(max_rounds=R + 4, block=8)
assert rounds >= 1

# verification stays jitted (SPMD-safe reductions, replicated scalars)
div = int(jax.jit(
    lambda s: divergence(var.codec, var.spec, s)
)(rt.states[c]))
assert div == 0, div
total = int(jax.jit(lambda s: s.counts[13].sum())(rt.states[c]))
assert total == 7, total

# the explicit-collective ring path works across the process boundary too
from lasp_tpu.mesh.shard_gossip import ring_gossip_rounds  # noqa: E402
from lasp_tpu.ops import PackedORSet, PackedORSetSpec  # noqa: E402
from lasp_tpu.lattice.base import replicate  # noqa: E402

spec = PackedORSetSpec(n_elems=4, n_actors=4, tokens_per_actor=1)
pop = replicate(PackedORSet.new(spec), R)
flat = jax.sharding.Mesh(mesh.devices.reshape(-1), ("replicas",))
pop = jax.tree_util.tree_map(
    lambda x: jax.device_put(
        x, jax.sharding.NamedSharding(
            flat, jax.sharding.PartitionSpec("replicas")
        )
    ), pop,
)
out, _changed = ring_gossip_rounds(PackedORSet, spec, pop, flat, 1, k=2)
jax.block_until_ready(jax.tree_util.tree_leaves(out))

# the round-5 boundary exchange (per-destination all_to_all) crosses the
# process boundary too: an irregular locality-ordered topology converges
# to a uniform population with the cut-sized collective as the only wire
from lasp_tpu.lattice import GSet, GSetSpec  # noqa: E402
from lasp_tpu.mesh.shard_gossip import (  # noqa: E402
    partitioned_gossip_plan,
    partitioned_gossip_rounds,
)
from lasp_tpu.mesh.topology import locality_order, scale_free  # noqa: E402

_, nn = locality_order(scale_free(R, 3, seed=4))
plan = partitioned_gossip_plan(nn, 8)
gspec = GSetSpec(n_elems=8)
gpop = replicate(GSet.new(gspec), R)
# the jitted seed write also establishes the block sharding (out_shardings)
gpop = gpop._replace(mask=jax.jit(
    lambda m: m.at[0, 0].set(True).at[41, 3].set(True),
    out_shardings=jax.sharding.NamedSharding(
        flat, jax.sharding.PartitionSpec("replicas")
    ),
)(gpop.mask))
gout, _ = partitioned_gossip_rounds(
    GSet, gspec, gpop, flat, plan, 24, mode="alltoall"
)
uniform, bits = jax.jit(
    lambda m: (jnp.all(m == m[0:1]), jnp.sum(m[0]))
)(gout.mask)
assert bool(uniform), "partitioned exchange failed to converge cross-process"
assert int(bits) == 2, int(bits)

print(f"WORKER-OK process={jax.process_index()}", flush=True)
sys.exit(0)
