"""Online compaction (VERDICT r3 ask #7): ``rt.compaction_window()`` lets a
long-lived population WITH registered triggers reclaim tombstoned element
slots mid-run — the reclamation the reference's ``waste_pct`` stat cues but
never performs (``src/lasp_orset.erl:156-192``)."""

import jax
import jax.numpy as jnp
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _waste_pct(rt, var_id) -> int:
    var = rt.store.variable(var_id)
    row0 = jax.tree_util.tree_map(
        lambda x: x[0], rt._to_dense_states(var_id)
    )
    return var.codec.stats(var.spec, row0)["waste_pct"]


def _build(n=8):
    store = Store(n_actors=4)
    s = store.declare(id="s", type="lasp_orset", n_elems=32)
    flag = store.declare(id="flag", type="lasp_gset", n_elems=2)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))

    # a builder-backed trigger: when the set holds the sentinel element it
    # raises the flag — the closure bakes element indices via intern_terms
    # exactly like the ad-counter server does
    def make_trigger():
        (sent_idx,) = rt.intern_terms(s, ["sentinel"])
        (f_idx,) = rt.intern_terms(flag, ["raised"])

        def trig(dense):
            st, fl = dense[s], dense[flag]
            live = (st.exists[sent_idx] & ~st.removed[sent_idx]).any()
            return {flag: fl._replace(mask=fl.mask.at[f_idx].set(
                fl.mask[f_idx] | live
            ))}

        return trig

    rt.register_trigger(builder=make_trigger, touches=[s, flag])
    return rt, s, flag


def test_soak_waste_returns_to_zero_mid_run():
    rt, s, flag = _build()
    # churn phase 1: add/remove cycles fill element slots with tombstones
    # (two keepers stay live — waste_pct is defined over a live set)
    for k in range(2):
        rt.update_at(k, s, ("add", f"keep{k}"), f"a{k}")
    for i in range(12):
        rt.update_at(i % 8, s, ("add", f"churn{i}"), f"a{i % 4}")
    rt.run_to_convergence(max_rounds=32)
    for i in range(12):
        rt.update_at(0, s, ("remove", f"churn{i}"), "a0")
    rt.run_to_convergence(max_rounds=32)
    assert _waste_pct(rt, s) > 50  # tombstone-dominated
    before = len(rt.store.variable(s).elems)

    # the online window: quiesce -> converge -> compact -> rebuild
    with rt.compaction_window() as w:
        reclaimed = w.compact_orset(s)
    # 12 churn slots + the builder's pre-interned (never-added, token-free)
    # sentinel slot; the rebuilt builder then re-interns the sentinel, so
    # the post-window universe is exactly {keep0, keep1, sentinel}
    assert reclaimed == 13
    assert before == 15
    assert sorted(rt.store.variable(s).elems.terms()) == [
        "keep0", "keep1", "sentinel",
    ]
    assert _waste_pct(rt, s) == 0  # mid-run, back to zero

    # churn phase 2: the REBUILT trigger still fires with the compacted
    # index order — the sentinel raises the flag
    rt.update_at(3, s, ("add", "sentinel"), "a3")
    rt.run_to_convergence(max_rounds=32)
    assert rt.coverage_value(flag) == {"raised"}
    assert rt.coverage_value(s) == {"keep0", "keep1", "sentinel"}
    assert rt.divergence(s) == 0


def test_window_refuses_plain_fn_triggers():
    store = Store(n_actors=2)
    s = store.declare(id="s", type="lasp_orset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    rt.register_trigger(lambda dense: {}, touches=[s])
    with pytest.raises(RuntimeError, match="builder"):
        with rt.compaction_window():
            pass


def test_window_restores_triggers_on_body_error():
    rt, s, flag = _build()
    with pytest.raises(ValueError, match="boom"):
        with rt.compaction_window():
            raise ValueError("boom")
    assert len(rt._triggers) == 1  # rebuilt despite the error
    rt.update_at(0, s, ("add", "sentinel"), "a0")
    rt.run_to_convergence(max_rounds=32)
    assert rt.coverage_value(flag) == {"raised"}


def test_window_keeps_other_triggers_when_one_builder_fails():
    store = Store(n_actors=2)
    s = store.declare(id="s", type="lasp_orset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    calls = {"good": 0, "bad": 0}

    def good_builder():
        calls["good"] += 1
        return lambda dense: {}

    flaky = {"armed": False}

    def bad_builder():
        calls["bad"] += 1
        if flaky["armed"]:
            raise RuntimeError("re-intern failed")
        return lambda dense: {}

    rt.register_trigger(builder=good_builder, touches=[s])
    rt.register_trigger(builder=bad_builder, touches=[s])
    flaky["armed"] = True
    with pytest.raises(RuntimeError, match="DROPPED"):
        with rt.compaction_window():
            pass
    # the good trigger survived the bad builder; the bad one was dropped
    assert len(rt._triggers) == 1
    assert calls["good"] == 2  # registration + rebuild


def test_window_keeps_triggers_registered_inside_body():
    store = Store(n_actors=2)
    s = store.declare(id="s", type="lasp_orset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    rt.register_trigger(builder=lambda: (lambda dense: {}), touches=[s])
    with rt.compaction_window() as w:
        w.register_trigger(builder=lambda: (lambda dense: {}), touches=[s])
    assert len(rt._triggers) == 2


def test_register_trigger_rejects_fn_and_builder_together():
    store = Store(n_actors=2)
    store.declare(id="s", type="lasp_orset", n_elems=4)
    rt = ReplicatedRuntime(store, Graph(store), 2, ring(2, 1))
    with pytest.raises(ValueError, match="exactly one"):
        rt.register_trigger(lambda d: {}, builder=lambda: (lambda d: {}))
    with pytest.raises(ValueError, match="exactly one"):
        rt.register_trigger()


def test_window_works_in_packed_mode():
    store = Store(n_actors=4)
    s = store.declare(id="s", type="lasp_orset", n_elems=16,
                      tokens_per_actor=2)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2), packed=True)

    def make_trigger():
        def trig(dense):
            return {}

        return trig

    rt.register_trigger(builder=make_trigger, touches=[s])
    for i in range(8):
        rt.update_at(i % 4, s, ("add", f"e{i}"), f"a{i % 4}")
    rt.run_to_convergence(max_rounds=16)
    for i in range(8):
        rt.update_at(1, s, ("remove", f"e{i}"), "a1")
    rt.run_to_convergence(max_rounds=16)
    with rt.compaction_window() as w:
        assert w.compact_orset(s) == 8
    rt.update_at(2, s, ("add", "fresh"), "a2")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(s) == {"fresh"}
