"""Mesh-layer tests: gossip convergence, quorum/coverage reads, failure
injection, determinism under merge-schedule permutation (the reference
proves this by EQC merge-commutativity, ``test/crdt_statem_eqc.erl:158-160``
— here it is the permutation-invariance suite of SURVEY.md §5), and sharded
execution over the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import GCounter, GCounterSpec, ORSet, ORSetSpec, replicate
from lasp_tpu.mesh import (
    ReplicatedRuntime,
    converged,
    divergence,
    edge_failure_mask,
    gossip_round,
    join_all,
    quorum_read,
    random_regular,
    ring,
    scale_free,
)


def seeded_counter_states(n_replicas=16, n_actors=16):
    """Each replica has incremented its own actor slot once."""
    spec = GCounterSpec(n_actors=n_actors)
    states = replicate(GCounter.new(spec), n_replicas)
    eye = jnp.eye(n_actors, dtype=jnp.int32)[:n_replicas]
    return spec, states._replace(counts=eye)


def test_topologies_shapes_and_determinism():
    for builder in (ring, random_regular, scale_free):
        a = builder(100, 3)
        b = builder(100, 3)
        assert a.shape == (100, 3)
        assert a.dtype == np.int32
        np.testing.assert_array_equal(a, b)  # deterministic
        assert a.min() >= 0 and a.max() < 100


def test_gossip_converges_ring():
    spec, states = seeded_counter_states()
    nbrs = jnp.asarray(ring(16, 2))
    rounds = 0
    while not bool(converged(GCounter, spec, states)):
        states = gossip_round(GCounter, spec, states, nbrs)
        rounds += 1
        assert rounds < 32
    # every replica holds the full count
    assert int(GCounter.value(spec, jax.tree_util.tree_map(lambda x: x[0], states))) == 16
    # ring of degree 2 spreads information at distance ~2/round
    assert rounds <= 8


def test_gossip_converges_random_and_scale_free():
    for topo in (random_regular(32, 3, seed=1), scale_free(32, 3, seed=1)):
        spec, states = seeded_counter_states(32, 32)
        nbrs = jnp.asarray(topo)
        for _ in range(64):
            if bool(converged(GCounter, spec, states)):
                break
            states = gossip_round(GCounter, spec, states, nbrs)
        assert bool(converged(GCounter, spec, states))


def test_gossip_schedule_permutation_invariance():
    # the determinism suite: permuting the gossip schedule must reach the
    # identical fixed point (join confluence)
    spec, states0 = seeded_counter_states(8, 8)
    topo_a = random_regular(8, 2, seed=3)
    topo_b = topo_a[:, ::-1].copy()  # same edges, different merge order
    sa = states0
    sb = states0
    for _ in range(10):
        sa = gossip_round(GCounter, spec, sa, jnp.asarray(topo_a))
        sb = gossip_round(GCounter, spec, sb, jnp.asarray(topo_b))
    np.testing.assert_array_equal(np.asarray(sa.counts), np.asarray(sb.counts))


def test_join_all_odd_and_quorum():
    spec, states = seeded_counter_states(7, 8)
    top = join_all(GCounter, spec, states)
    assert int(GCounter.value(spec, top)) == 7
    # R-of-N quorum read sees the members' writes
    q = quorum_read(GCounter, spec, states, [0, 3, 5])
    assert int(GCounter.value(spec, q)) == 3


def test_failure_injection_blocks_then_heals():
    spec, states = seeded_counter_states(8, 8)
    nbrs = jnp.asarray(ring(8, 2))
    dead = jnp.zeros((8, 2), dtype=bool)  # all edges down
    for _ in range(5):
        states = gossip_round(GCounter, spec, states, nbrs, edge_mask=dead)
    assert int(divergence(GCounter, spec, states)) == 8  # nothing moved
    alive = jnp.ones((8, 2), dtype=bool)
    for _ in range(8):
        states = gossip_round(GCounter, spec, states, nbrs, edge_mask=alive)
    assert bool(converged(GCounter, spec, states))  # healed via join


def test_orset_gossip_with_removals():
    spec = ORSetSpec(n_elems=4, n_actors=8, tokens_per_actor=2)
    n = 8
    states = replicate(ORSet.new(spec), n)
    # replica r adds element (r % 4) with its own actor; replica 0 then
    # removes element 0 after observing its own add
    def upd(r, s):
        s1 = ORSet.add(spec, s, r % 4, r)
        return jax.lax.cond(r == 0, lambda x: ORSet.remove(spec, x, 0), lambda x: x, s1)

    states = jax.vmap(upd)(jnp.arange(n), states)
    nbrs = jnp.asarray(ring(n, 2))
    for _ in range(8):
        states = gossip_round(ORSet, spec, states, nbrs)
    assert bool(converged(ORSet, spec, states))
    top = join_all(ORSet, spec, states)
    live = np.asarray(ORSet.value(spec, top))
    # element 0: replica 0's token tombstoned, but replica 4's concurrent add
    # survives (observe-remove semantics: only observed tokens die)
    assert list(live) == [True, True, True, True]


class TestReplicatedRuntime:
    def _runtime(self, n=8):
        from lasp_tpu.store import Store

        store = Store(n_actors=8)
        graph = Graph(store)
        s1 = store.declare(id="src", type="lasp_orset", n_elems=4)
        s2 = graph.map(s1, lambda x: x * 10, dst="out")
        rt = ReplicatedRuntime(store, graph, n, ring(n, 2))
        return store, graph, rt, s1, s2

    def test_update_gossip_dataflow(self):
        store, graph, rt, s1, s2 = self._runtime()
        rt.update_at(0, s1, ("add", 1), "a0")
        rt.update_at(3, s1, ("add", 2), "a3")
        rounds = rt.run_to_convergence(max_rounds=32)
        assert rounds <= 8
        assert rt.coverage_value(s2) == frozenset({10, 20})
        # every replica's local dataflow output converged too
        for r in range(rt.n_replicas):
            assert rt.replica_value(s2, r) == frozenset({10, 20})

    def test_remove_propagates_through_mesh(self):
        store, graph, rt, s1, s2 = self._runtime()
        rt.update_at(0, s1, ("add", 1), "a0")
        rt.run_to_convergence(max_rounds=32)
        # remove at a *different* replica (it has observed the add via gossip)
        rt.update_at(5, s1, ("remove", 1), "a5")
        rt.run_to_convergence(max_rounds=32)
        assert rt.coverage_value(s1) == frozenset()
        assert rt.coverage_value(s2) == frozenset()

    def test_divergence_metric(self):
        store, graph, rt, s1, s2 = self._runtime()
        rt.update_at(0, s1, ("add", 1), "a0")
        assert rt.divergence(s1) == 7  # everyone but replica 0 behind
        rt.run_to_convergence(max_rounds=32)
        assert rt.divergence(s1) == 0


def test_read_until_blocks_for_gossip():
    # the blocking monotonic read: a replica far from the writer must wait
    # for the update to gossip over before its threshold fires
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.store import Store

    store = Store(n_actors=4)
    c = store.declare(id="ctr", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 1))
    rt.update_at(0, c, ("increment", 5), "w")
    assert rt.read_at(4, c, Threshold(5)) is None  # not arrived yet
    row = rt.read_until(4, c, Threshold(5), max_rounds=16)
    assert int(row.counts.sum()) == 5
    with pytest.raises(TimeoutError):
        rt.read_until(4, c, Threshold(99), max_rounds=4)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_gossip_over_device_mesh():
    # the multi-chip path: replica axis split over an 8-device mesh; the
    # neighbor gather rides XLA collectives (SURVEY.md §2.5 equivalence table)
    n = 64
    spec, states = seeded_counter_states(n, n)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("replicas",))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("replicas"))
    states = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    nbrs = jax.device_put(
        jnp.asarray(ring(n, 2)),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("replicas", None)),
    )

    @jax.jit
    def one_round(s, nb):
        return gossip_round(GCounter, spec, s, nb)

    for _ in range(n):
        states = one_round(states, nbrs)
        if bool(converged(GCounter, spec, states)):
            break
    assert bool(converged(GCounter, spec, states))
    assert int(GCounter.value(spec, jax.tree_util.tree_map(lambda x: x[0], states))) == n


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_replicated_runtime():
    from lasp_tpu.store import Store

    store = Store(n_actors=8)
    graph = Graph(store)
    s1 = store.declare(id="src", type="lasp_orset", n_elems=4)
    s2 = graph.map(s1, lambda x: x + 100, dst="out")
    n = 32
    rt = ReplicatedRuntime(store, graph, n, ring(n, 2))
    rt.update_at(0, s1, ("add", 7), "a0")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("replicas",))
    rt.shard(mesh)
    rt.run_to_convergence(max_rounds=64)
    assert rt.coverage_value(s2) == frozenset({107})


def test_runtime_quorum_value_monotone_lower_bound():
    """quorum_value over R rows is a monotone lower bound of the coverage
    value (the first-R merge of lasp_read_fsm), coinciding after gossip."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    s = store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 9, ring(9, 1))
    rt.update_batch(s, [(0, ("add", "a"), "w"), (4, ("add", "b"), "w")])
    # before gossip: a quorum holding only replica 4's write sees {b}
    assert rt.quorum_value(s, [3, 4, 5]) == frozenset({"b"})
    assert rt.quorum_value(s, [0, 4, 8]) == frozenset({"a", "b"})
    assert rt.coverage_value(s) == frozenset({"a", "b"})
    rt.run_to_convergence(block=4)
    # after anti-entropy every quorum agrees with coverage (read-repair)
    assert rt.quorum_value(s, [1, 2]) == frozenset({"a", "b"})


def test_runtime_quorum_value_rejects_out_of_range():
    import pytest as _pytest

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    store = Store(n_actors=2)
    graph = Graph(store)
    s = store.declare(id="s", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 6, ring(6, 1))
    with _pytest.raises(IndexError, match="out of range"):
        rt.quorum_value(s, [5, 6])
    with _pytest.raises(ValueError, match="at least one"):
        rt.quorum_value(s, [])


def test_leafwise_fast_path_equals_generic():
    # codecs declaring leafwise_join take a fused per-leaf gossip path;
    # it must be BIT-identical to the generic per-column vmapped merge
    # for every such codec, on random states and topologies
    import numpy as np

    import jax
    import jax.numpy as jnp

    from lasp_tpu.lattice import (
        GCounter,
        GCounterSpec,
        GSet,
        GSetSpec,
        ORSet,
        ORSetSpec,
    )
    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.mesh.gossip import gossip_round
    from lasp_tpu.mesh.topology import random_regular
    from lasp_tpu.ops import FlatORSet, FlatORSetSpec, PackedORSet, PackedORSetSpec

    rng = np.random.RandomState(3)
    R = 96
    nbrs = jnp.asarray(random_regular(R, 3, seed=5))

    def generic(codec, spec, states):
        # the SHIPPED generic branch, not a frozen copy: an all-alive
        # edge mask routes gossip_round down the per-column vmapped
        # merge path with identical semantics (alive edges are a no-op)
        return gossip_round(
            codec, spec, states, nbrs,
            edge_mask=jnp.ones((R, nbrs.shape[1]), dtype=bool),
        )

    cases = []
    ps = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    st = replicate(PackedORSet.new(ps), R)._replace(
        exists=jnp.asarray(rng.randint(0, 256, size=(R, 8, ps.n_words)),
                           dtype=jnp.uint32),
        removed=jnp.asarray(rng.randint(0, 64, size=(R, 8, ps.n_words)),
                            dtype=jnp.uint32),
    )
    cases.append((PackedORSet, ps, st))
    os_ = ORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    st = replicate(ORSet.new(os_), R)._replace(
        exists=jnp.asarray(rng.rand(R, 8, os_.n_tokens) < 0.2),
        removed=jnp.asarray(rng.rand(R, 8, os_.n_tokens) < 0.1),
    )
    cases.append((ORSet, os_, st))
    gs = GSetSpec(n_elems=16)
    cases.append((GSet, gs, replicate(GSet.new(gs), R)._replace(
        mask=jnp.asarray(rng.rand(R, 16) < 0.2))))
    cs = GCounterSpec(n_actors=8)
    cases.append((GCounter, cs, replicate(GCounter.new(cs), R)._replace(
        counts=jnp.asarray(rng.randint(0, 9, size=(R, 8)), dtype=jnp.int32))))
    fs = FlatORSetSpec(dense=os_)
    st = replicate(FlatORSet.new(fs), R)
    st = jax.tree_util.tree_map(
        lambda x: jnp.asarray(
            rng.randint(0, 2**31, size=x.shape), dtype=x.dtype
        ),
        st,
    )
    cases.append((FlatORSet, fs, st))

    for codec, spec, states in cases:
        assert getattr(codec, "leafwise_join", None) is not None, codec
        fast = gossip_round(codec, spec, states, nbrs)
        slow = generic(codec, spec, states)
        for a, b in zip(jax.tree_util.tree_leaves(fast),
                        jax.tree_util.tree_leaves(slow)):
            assert bool(jnp.array_equal(a, b)), codec.name


def test_leafwise_shift_path_equals_generic():
    # the shift-topology round takes the same fused per-leaf path; it
    # must match the gather form on the equivalent ring neighbor table
    import numpy as np

    import jax
    import jax.numpy as jnp

    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.mesh.gossip import gossip_round, gossip_round_shift
    from lasp_tpu.mesh.topology import ring, shift_offsets
    from lasp_tpu.ops import PackedORSet, PackedORSetSpec

    rng = np.random.RandomState(11)
    R = 64
    spec = PackedORSetSpec(n_elems=4, n_actors=4, tokens_per_actor=2)
    states = replicate(PackedORSet.new(spec), R)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(R, 4, spec.n_words)), dtype=jnp.uint32
        )
    )
    nbrs = ring(R, 3)
    offs = shift_offsets(nbrs, R)
    fast = gossip_round_shift(PackedORSet, spec, states, offs)
    ref = gossip_round(
        PackedORSet, spec, states, jnp.asarray(nbrs),
        edge_mask=jnp.ones((R, 3), dtype=bool),
    )
    assert bool(jnp.array_equal(fast.exists, ref.exists))
    assert bool(jnp.array_equal(fast.removed, ref.removed))


def test_unknown_leafwise_join_is_loud():
    import pytest

    from lasp_tpu.lattice import GSet, GSetSpec
    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.mesh.gossip import gossip_round
    from lasp_tpu.mesh.topology import ring

    class Bad(GSet):
        leafwise_join = "xor"

    spec = GSetSpec(n_elems=4)
    with pytest.raises(ValueError, match="leafwise_join"):
        gossip_round(Bad, spec, replicate(GSet.new(spec), 8),
                     __import__("jax.numpy", fromlist=["x"]).asarray(ring(8, 2)))
