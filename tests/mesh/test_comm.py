"""mesh_comm: mesh construction, slice-aware layout, single-process
degradation, and an end-to-end sharded gossip run on the built mesh
(SURVEY §2.5 communication-backend equivalence; VERDICT r2 item 32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.lattice import GSet, GSetSpec, replicate
from lasp_tpu.mesh import gossip_round, random_regular
from lasp_tpu.mesh.comm import (
    build_mesh,
    init_distributed,
    n_slices,
    neighbor_sharding,
    population_sharding,
)


def test_init_distributed_noop_without_cluster(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_distributed() is False
    assert init_distributed(num_processes=1) is False


def test_build_mesh_flat_single_slice():
    mesh = build_mesh()
    assert mesh.axis_names == ("slices", "replicas", "state")
    assert mesh.shape["slices"] == n_slices() == 1
    assert mesh.shape["replicas"] == 8  # the conftest's 8 virtual devices
    assert mesh.shape["state"] == 1


def test_build_mesh_state_axis_and_validation():
    mesh = build_mesh(state=2)
    assert mesh.shape["replicas"] == 4 and mesh.shape["state"] == 2
    with pytest.raises(ValueError, match="does not divide"):
        build_mesh(state=3)
    with pytest.raises(ValueError, match="exceeds"):
        build_mesh(replicas=8, state=2)


def test_sharded_gossip_converges_on_built_mesh():
    mesh = build_mesh()
    n, e = 64, 16
    spec = GSetSpec(n_elems=e)
    rng = np.random.RandomState(6)
    states = replicate(GSet.new(spec), n)._replace(
        mask=jnp.asarray(rng.rand(n, e) < 0.08)
    )
    nbrs = jnp.asarray(random_regular(n, 3, seed=6))
    sh = population_sharding(mesh)
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    nbrs_sh = jax.device_put(nbrs, neighbor_sharding(mesh))
    step = jax.jit(lambda s, nb: gossip_round(GSet, spec, s, nb))
    out = sharded
    for _ in range(8):
        out = step(out, nbrs_sh)
    expect = np.asarray(states.mask).any(axis=0)
    assert (np.asarray(out.mask) == expect[None, :]).all()
