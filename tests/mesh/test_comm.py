"""mesh_comm: mesh construction, slice-aware layout, single-process
degradation, and an end-to-end sharded gossip run on the built mesh
(SURVEY §2.5 communication-backend equivalence; VERDICT r2 item 32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.lattice import GSet, GSetSpec, replicate
from lasp_tpu.mesh import gossip_round, random_regular
from lasp_tpu.mesh.comm import (
    build_mesh,
    init_distributed,
    n_slices,
    neighbor_sharding,
    population_sharding,
)


def test_init_distributed_noop_without_cluster(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_distributed() is False
    assert init_distributed(num_processes=1) is False


def test_build_mesh_flat_single_slice():
    mesh = build_mesh()
    assert mesh.axis_names == ("slices", "replicas", "state")
    assert mesh.shape["slices"] == n_slices() == 1
    assert mesh.shape["replicas"] == 8  # the conftest's 8 virtual devices
    assert mesh.shape["state"] == 1


def test_build_mesh_state_axis_and_validation():
    mesh = build_mesh(state=2)
    assert mesh.shape["replicas"] == 4 and mesh.shape["state"] == 2
    with pytest.raises(ValueError, match="does not divide"):
        build_mesh(state=3)
    with pytest.raises(ValueError, match="exceeds"):
        build_mesh(replicas=8, state=2)


def test_build_mesh_two_virtual_slices():
    """The multi-slice (DCN) layout, exercised without a pod: partition
    the 8 virtual devices into 2 'slices' of 4 via the slice_of override
    and check the hybrid (slices, replicas, state) grid comes out with the
    DCN axis outermost and each slice's devices contiguous inside it."""
    devs = jax.devices()
    by_half = {d: i // 4 for i, d in enumerate(devs)}
    mesh = build_mesh(slice_of=by_half.get)
    assert mesh.shape["slices"] == 2
    assert mesh.shape["replicas"] == 4 and mesh.shape["state"] == 1
    assert n_slices(slice_of=by_half.get) == 2
    # each row of the slices axis holds exactly one half's devices
    grid = np.asarray(mesh.devices)
    for si in range(2):
        assert {by_half[d] for d in grid[si].ravel()} == {si}
    # state axis still splits within a slice
    mesh2 = build_mesh(state=2, slice_of=by_half.get)
    assert mesh2.shape == {"slices": 2, "replicas": 2, "state": 2}


def test_sharded_gossip_converges_on_two_slice_mesh():
    """Random-neighbor gossip where the population spans both virtual
    slices: gathers cross the slice boundary (the boundary-exchange role,
    SURVEY §2.5 'partition the replica graph between slices') and still
    reach the global join."""
    devs = jax.devices()
    mesh = build_mesh(slice_of={d: i // 4 for i, d in enumerate(devs)}.get)
    n, e = 32, 8
    spec = GSetSpec(n_elems=e)
    rng = np.random.RandomState(3)
    states = replicate(GSet.new(spec), n)._replace(
        mask=jnp.asarray(rng.rand(n, e) < 0.1)
    )
    nbrs = jnp.asarray(random_regular(n, 3, seed=3))
    sharded = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, population_sharding(mesh)), states
    )
    nbrs_sh = jax.device_put(nbrs, neighbor_sharding(mesh))
    step = jax.jit(lambda s, nb: gossip_round(GSet, spec, s, nb))
    out = sharded
    for _ in range(8):
        out = step(out, nbrs_sh)
    expect = np.asarray(states.mask).any(axis=0)
    assert (np.asarray(out.mask) == expect[None, :]).all()


def test_runtime_shard_on_two_slice_mesh():
    """ReplicatedRuntime.shard with no axis adapts to the canonical mesh:
    population split over (slices, replicas), and the engine still steps
    to the right fixed point across the virtual DCN boundary."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    devs = jax.devices()
    mesh = build_mesh(slice_of={d: i // 4 for i, d in enumerate(devs)}.get)
    store = Store(n_actors=2)
    graph = Graph(store)
    v = store.declare(id="v", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 32, ring(32, 2))
    rt.update_batch(v, [(0, ("add", "k"), "w")])
    rt.shard(mesh)
    rt.run_to_convergence(block=4)
    assert rt.coverage_value(v) == frozenset({"k"})
    assert rt.divergence(v) == 0


def test_runtime_shard_falls_back_when_joint_axis_does_not_divide():
    """n_replicas not divisible by slices*replicas: shard(None) must fall
    back to the plain replicas split instead of raising."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    devs = jax.devices()
    mesh = build_mesh(slice_of={d: i // 4 for i, d in enumerate(devs)}.get)
    assert mesh.shape["slices"] * mesh.shape["replicas"] == 8
    store = Store(n_actors=2)
    graph = Graph(store)
    v = store.declare(id="v", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 12, ring(12, 2))  # 12 % 8 != 0
    rt.update_batch(v, [(0, ("add", "k"), "w")])
    rt.shard(mesh)
    rt.run_to_convergence(block=4)
    assert rt.coverage_value(v) == frozenset({"k"})

    # population dividing NEITHER extent: a clear error, not a jax one
    store2 = Store(n_actors=2)
    graph2 = Graph(store2)
    store2.declare(id="v", type="lasp_gset", n_elems=4)
    rt2 = ReplicatedRuntime(store2, graph2, 10, ring(10, 2))  # 10 % 4 != 0
    with pytest.raises(ValueError, match="resize the population"):
        rt2.shard(mesh)


def test_sharded_gossip_converges_on_built_mesh():
    mesh = build_mesh()
    n, e = 64, 16
    spec = GSetSpec(n_elems=e)
    rng = np.random.RandomState(6)
    states = replicate(GSet.new(spec), n)._replace(
        mask=jnp.asarray(rng.rand(n, e) < 0.08)
    )
    nbrs = jnp.asarray(random_regular(n, 3, seed=6))
    sh = population_sharding(mesh)
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    nbrs_sh = jax.device_put(nbrs, neighbor_sharding(mesh))
    step = jax.jit(lambda s, nb: gossip_round(GSet, spec, s, nb))
    out = sharded
    for _ in range(8):
        out = step(out, nbrs_sh)
    expect = np.asarray(states.mask).any(axis=0)
    assert (np.asarray(out.mask) == expect[None, :]).all()
