"""Elastic membership: grow/shrink a running population and restore a
checkpoint onto a different replica count (VERDICT r2 ask #7; reference
staged join/leave/down, src/lasp_console.erl:31-94)."""

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.store import Store


def _runtime(n=8, packed=False, with_edge=True):
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=8)
    b = store.declare(id="b", type="lasp_orset", n_elems=8)
    if with_edge:
        graph.union(a, b, dst="u")
    rt = ReplicatedRuntime(store, graph, n, ring(n, 2), packed=packed)
    rt.update_batch("a", [(0, ("add", "x"), "p")])
    rt.update_batch("b", [(n // 2, ("add", "y"), "q")])
    return rt


@pytest.mark.parametrize("packed", [False, True])
def test_grow_new_rows_catch_up_by_gossip(packed):
    rt = _runtime(8, packed=packed)
    rt.run_to_convergence()
    rt.resize(16, ring(16, 2))
    assert rt.n_replicas == 16
    # fresh rows join at bottom...
    assert rt.replica_value("a", 12) == frozenset()
    rt.update_batch("a", [(15, ("add", "z"), "p")])  # writes land on new rows
    rt.run_to_convergence()
    # ...and catch up to the full join, including post-join writes
    for r in (0, 8, 12, 15):
        assert rt.replica_value("a", r) == {"x", "z"}
        assert rt.replica_value("u", r) == {"x", "y", "z"}
    assert rt.divergence("u") == 0


@pytest.mark.parametrize("packed", [False, True])
def test_graceful_leave_preserves_ungossiped_writes(packed):
    rt = _runtime(8, packed=packed)
    # a write at a departing replica that NEVER gossiped
    rt.update_batch("a", [(7, ("add", "only-at-7"), "p")])
    rt.resize(4, ring(4, 2), graceful=True)
    rt.run_to_convergence()
    assert rt.coverage_value("a") == {"x", "only-at-7"}
    assert rt.coverage_value("u") == {"x", "y", "only-at-7"}
    assert rt.divergence("a") == 0


def test_crash_leave_loses_only_ungossiped_state():
    rt = _runtime(8)
    rt.run_to_convergence()  # x and y reach every replica pre-crash
    rt.update_batch("a", [(7, ("add", "doomed"), "p")])
    rt.resize(4, ring(4, 2), graceful=False)
    rt.run_to_convergence()
    # the never-gossiped write is lost (crash semantics); gossiped ones live
    assert rt.coverage_value("a") == {"x"}
    assert rt.coverage_value("u") == {"x", "y"}


def test_resize_validates_topology():
    rt = _runtime(8)
    with pytest.raises(ValueError, match="new_n"):
        rt.resize(4, ring(8, 2))
    with pytest.raises(ValueError, match="out of range"):
        rt.resize(4, np.array([[0, 5]] * 4))


def test_shrink_then_grow_round_trip_with_trigger():
    import jax.numpy as jnp

    rt = _runtime(8)
    seen = {}

    def trig(dense):
        seen["fired"] = True
        return {}

    rt.register_trigger(trig)
    rt.run_to_convergence(block=4)
    rt.resize(2, ring(2, 1))
    rt.run_to_convergence(block=4)
    rt.resize(12, random_regular(12, 3, seed=1))
    rt.update_batch("b", [(11, ("add", "late"), "q")])
    rt.run_to_convergence(block=4)
    assert seen.get("fired")
    assert rt.coverage_value("u") == {"x", "y", "late"}
    assert rt.divergence("u") == 0


@pytest.mark.parametrize("packed", [False, True])
def test_checkpoint_restore_onto_different_population(tmp_path, packed):
    from lasp_tpu.store.checkpoint import load_runtime, save_runtime

    rt = _runtime(8, packed=packed, with_edge=False)
    rt.run_to_convergence()
    path = str(tmp_path / "m.lasp")
    save_runtime(rt, path)

    bigger = load_runtime(path, n_replicas=16, neighbors=ring(16, 2))
    assert bigger.n_replicas == 16
    bigger.run_to_convergence()
    assert bigger.replica_value("a", 15) == {"x"}

    smaller = load_runtime(path, n_replicas=3, neighbors=ring(3, 2))
    smaller.run_to_convergence()
    assert smaller.coverage_value("a") == {"x"}
    assert smaller.divergence("a") == 0

    with pytest.raises(ValueError, match="neighbors"):
        load_runtime(path, n_replicas=5)


def test_resize_then_device_driver_and_device_read():
    """Shape-changing membership ops must invalidate the cached
    while_loop executables (converge_on_device / on-device read_until)."""
    from lasp_tpu.lattice import Threshold

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="c", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("c", [(0, ("increment", 3), "w")])
    assert rt.converge_on_device() >= 1
    row = rt.read_until(5, "c", Threshold(3), on_device=True)
    assert row is not None
    rt.resize(12, ring(12, 2))  # grow: new rows at bottom
    assert rt.converge_on_device() >= 1  # recompiled for the new shape
    assert rt.read_until(11, "c", Threshold(3), on_device=True) is not None
    rt.resize(6, ring(6, 2))  # graceful shrink
    assert rt.converge_on_device() >= 1
    assert int(rt.coverage_value("c")) == 3
