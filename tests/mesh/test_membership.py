"""Elastic membership: grow/shrink a running population and restore a
checkpoint onto a different replica count (VERDICT r2 ask #7; reference
staged join/leave/down, src/lasp_console.erl:31-94)."""

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.store import Store


def _runtime(n=8, packed=False, with_edge=True):
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=8)
    b = store.declare(id="b", type="lasp_orset", n_elems=8)
    if with_edge:
        graph.union(a, b, dst="u")
    rt = ReplicatedRuntime(store, graph, n, ring(n, 2), packed=packed)
    rt.update_batch("a", [(0, ("add", "x"), "p")])
    rt.update_batch("b", [(n // 2, ("add", "y"), "q")])
    return rt


@pytest.mark.parametrize("packed", [False, True])
def test_grow_new_rows_catch_up_by_gossip(packed):
    rt = _runtime(8, packed=packed)
    rt.run_to_convergence()
    rt.resize(16, ring(16, 2))
    assert rt.n_replicas == 16
    # fresh rows join at bottom...
    assert rt.replica_value("a", 12) == frozenset()
    rt.update_batch("a", [(15, ("add", "z"), "p")])  # writes land on new rows
    rt.run_to_convergence()
    # ...and catch up to the full join, including post-join writes
    for r in (0, 8, 12, 15):
        assert rt.replica_value("a", r) == {"x", "z"}
        assert rt.replica_value("u", r) == {"x", "y", "z"}
    assert rt.divergence("u") == 0


@pytest.mark.parametrize("packed", [False, True])
def test_graceful_leave_preserves_ungossiped_writes(packed):
    rt = _runtime(8, packed=packed)
    # a write at a departing replica that NEVER gossiped
    rt.update_batch("a", [(7, ("add", "only-at-7"), "p")])
    rt.resize(4, ring(4, 2), graceful=True)
    rt.run_to_convergence()
    assert rt.coverage_value("a") == {"x", "only-at-7"}
    assert rt.coverage_value("u") == {"x", "y", "only-at-7"}
    assert rt.divergence("a") == 0


def test_crash_leave_loses_only_ungossiped_state():
    rt = _runtime(8)
    rt.run_to_convergence()  # x and y reach every replica pre-crash
    rt.update_batch("a", [(7, ("add", "doomed"), "p")])
    rt.resize(4, ring(4, 2), graceful=False)
    rt.run_to_convergence()
    # the never-gossiped write is lost (crash semantics); gossiped ones live
    assert rt.coverage_value("a") == {"x"}
    assert rt.coverage_value("u") == {"x", "y"}


def test_resize_validates_topology():
    rt = _runtime(8)
    with pytest.raises(ValueError, match="new_n"):
        rt.resize(4, ring(8, 2))
    with pytest.raises(ValueError, match="out of range"):
        rt.resize(4, np.array([[0, 5]] * 4))


def test_shrink_then_grow_round_trip_with_trigger():
    import jax.numpy as jnp

    rt = _runtime(8)
    seen = {}

    def trig(dense):
        seen["fired"] = True
        return {}

    rt.register_trigger(trig)
    rt.run_to_convergence(block=4)
    rt.resize(2, ring(2, 1))
    rt.run_to_convergence(block=4)
    rt.resize(12, random_regular(12, 3, seed=1))
    rt.update_batch("b", [(11, ("add", "late"), "q")])
    rt.run_to_convergence(block=4)
    assert seen.get("fired")
    assert rt.coverage_value("u") == {"x", "y", "late"}
    assert rt.divergence("u") == 0


@pytest.mark.parametrize("packed", [False, True])
def test_checkpoint_restore_onto_different_population(tmp_path, packed):
    from lasp_tpu.store.checkpoint import load_runtime, save_runtime

    rt = _runtime(8, packed=packed, with_edge=False)
    rt.run_to_convergence()
    path = str(tmp_path / "m.lasp")
    save_runtime(rt, path)

    bigger = load_runtime(path, n_replicas=16, neighbors=ring(16, 2))
    assert bigger.n_replicas == 16
    bigger.run_to_convergence()
    assert bigger.replica_value("a", 15) == {"x"}

    smaller = load_runtime(path, n_replicas=3, neighbors=ring(3, 2))
    smaller.run_to_convergence()
    assert smaller.coverage_value("a") == {"x"}
    assert smaller.divergence("a") == 0

    with pytest.raises(ValueError, match="neighbors"):
        load_runtime(path, n_replicas=5)


def test_resize_then_device_driver_and_device_read():
    """Shape-changing membership ops must invalidate the cached
    while_loop executables (converge_on_device / on-device read_until)."""
    from lasp_tpu.lattice import Threshold

    store = Store(n_actors=2)
    graph = Graph(store)
    store.declare(id="c", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, graph, 8, ring(8, 2))
    rt.update_batch("c", [(0, ("increment", 3), "w")])
    assert rt.converge_on_device() >= 1
    row = rt.read_until(5, "c", Threshold(3), on_device=True)
    assert row is not None
    rt.resize(12, ring(12, 2))  # grow: new rows at bottom
    assert rt.converge_on_device() >= 1  # recompiled for the new shape
    assert rt.read_until(11, "c", Threshold(3), on_device=True) is not None
    rt.resize(6, ring(6, 2))  # graceful shrink
    assert rt.converge_on_device() >= 1
    assert int(rt.coverage_value("c")) == 3


class TestClaimSuccessorLeave:
    """The graceful-leave claim rule: departing rows fold onto their
    ring successors (row % new_n), not row 0."""

    def test_departing_state_lands_at_claim_successor(self):
        rt = _runtime(8, with_edge=False)
        # ungossiped writes at two departing rows
        rt.update_batch("a", [(5, ("add", "only-5"), "p")])
        rt.update_batch("a", [(7, ("add", "only-7"), "q")])
        rt.resize(4, ring(4, 2), graceful=True)
        # BEFORE any gossip: each departer's write sits at row r % 4
        assert "only-5" in rt.replica_value("a", 1)
        assert "only-7" in rt.replica_value("a", 3)
        # ...and row 0 did not absorb them (the legacy rule is gone)
        assert "only-5" not in rt.replica_value("a", 0)
        assert "only-7" not in rt.replica_value("a", 0)
        rt.run_to_convergence()
        assert rt.divergence("a") == 0

    def test_epoch_advances_on_every_membership_change(self):
        rt = _runtime(8)
        assert rt.membership_epoch == 0
        rt.resize(12, ring(12, 2))
        rt.resize(6, ring(6, 2), graceful=True)
        rt.resize(4, ring(4, 2), graceful=False)
        rt.resize(4, ring(4, 2))  # topology swap fences too
        assert rt.membership_epoch == 4


class TestGracefulLeaveChaosGuard:
    """Regression (confirmed repro): the graceful-leave merge is a
    host-side tree_map that historically IGNORED any active chaos edge
    mask — a partition bypass (the same class as the degraded-read
    confinement fix). The guard must refuse typed; crash-leave and
    post-heal leaves stay allowed."""

    def _partitioned(self, rounds=6):
        import numpy as np

        from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Partition

        rt = _runtime(8, with_edge=False)
        # a write at row 7 that never crossed the cut
        rt.update_batch("a", [(7, ("add", "sealed"), "w7")])
        sched = ChaosSchedule(
            8, np.asarray(rt._host_neighbors), [Partition(0, rounds, 2)]
        )
        ch = ChaosRuntime(rt, sched)
        ch.step()  # the cut is live: rows {0..3} | {4..7}
        return rt, ch

    def test_repro_unguarded_merge_tunnels_through_the_cut(self):
        """The bypass, demonstrated: with the guard disabled (the old
        behavior), a graceful shrink moves row 7's sealed write into
        the OTHER side of a live partition — state crossed a cut no
        gossip round could cross."""
        rt, ch = self._partitioned()
        rt._handoff_guard = None  # the pre-fix behavior
        rt.resize(4, ring(4, 2), graceful=True)
        assert "sealed" in rt.replica_value("a", 3)  # 7 % 4: side A!

    def test_guard_refuses_typed_while_partitioned(self):
        from lasp_tpu.membership import HandoffPartitionError

        rt, ch = self._partitioned()
        with pytest.raises(HandoffPartitionError, match="partition"):
            rt.resize(4, ring(4, 2), graceful=True)
        # nothing moved, nothing dropped
        assert rt.n_replicas == 8 and rt.membership_epoch == 0

    def test_crash_leave_still_allowed_under_partition(self):
        rt, ch = self._partitioned()
        rt.resize(4, ring(4, 2), graceful=False)
        assert rt.n_replicas == 4
        rt.run_to_convergence()
        assert "sealed" not in rt.coverage_value("a")  # crash semantics

    def test_graceful_leave_allowed_after_heal(self):
        rt, ch = self._partitioned(rounds=3)
        while ch.round <= ch.schedule.horizon:
            ch.step()
        rt.resize(4, ring(4, 2), graceful=True)
        rt.run_to_convergence()
        assert "sealed" in rt.coverage_value("a")

    def test_guard_refuses_crashed_departer(self):
        import numpy as np

        from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash
        from lasp_tpu.membership import HandoffPartitionError

        rt = _runtime(8, with_edge=False)
        sched = ChaosSchedule(
            8, np.asarray(rt._host_neighbors), [Crash(0, 6)]
        )
        ch = ChaosRuntime(rt, sched)
        ch.step()
        with pytest.raises(HandoffPartitionError, match="crashed"):
            rt.resize(4, ring(4, 2), graceful=True)


class TestGuardHardening:
    """Review-hardening regressions: the guard must judge against
    bookkeeping re-based onto the CURRENT extent, and a fault-free
    convenience wrapper must never neuter a real nemesis's guard."""

    def test_guard_rebases_after_unstepped_grow(self):
        """A grow commits without consulting the guard; a graceful
        shrink straight after (no chaos round in between) must still
        refuse TYPED against the rebased mask — not crash with an
        IndexError off the stale 8-row crashed vector."""
        import numpy as np

        from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Partition
        from lasp_tpu.membership import HandoffPartitionError

        rt = _runtime(8, with_edge=False)
        sched = ChaosSchedule(
            8, np.asarray(rt._host_neighbors), [Partition(0, 6, 2)]
        )
        ChaosRuntime(rt, sched)
        rt.resize(12, ring(12, 2))  # grow: guard not consulted
        with pytest.raises(HandoffPartitionError, match="partition"):
            rt.resize(6, ring(6, 2), graceful=True)

    def test_faultfree_wrapper_keeps_real_guard(self):
        """Wrapping the same runtime in a fault-free ChaosRuntime (the
        QuorumRuntime / MembershipCoordinator convenience wrap) must
        not replace the nemesis wrapper's partition guard."""
        import numpy as np

        from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Partition
        from lasp_tpu.membership import HandoffPartitionError

        rt = _runtime(8, with_edge=False)
        sched = ChaosSchedule(
            8, np.asarray(rt._host_neighbors), [Partition(0, 6, 2)]
        )
        ch = ChaosRuntime(rt, sched)
        ch.step()  # the cut is live
        # the fault-free convenience wrap (no events: vacuous guard)
        ChaosRuntime(
            rt, ChaosSchedule(8, np.asarray(rt._host_neighbors), ())
        )
        with pytest.raises(HandoffPartitionError, match="partition"):
            rt.resize(4, ring(4, 2), graceful=True)
