"""Explicit-collective ring gossip: semantics vs the dense reference path
and an HLO-level proof that the lowering really uses `collective-permute`
(VERDICT r2 ask #8 — "lowers to ppermute" must be verified, not claimed).
Runs on the 8-virtual-CPU-device mesh provisioned by tests/conftest.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lasp_tpu.lattice import GSet, GSetSpec, replicate
from lasp_tpu.mesh import gossip_round, ring
from lasp_tpu.mesh.shard_gossip import (
    ring_gossip_round_fn,
    ring_gossip_rounds,
    ring_gossip_shardmap_dryrun,
    ring_offsets,
)
from lasp_tpu.ops import PackedORSet, PackedORSetSpec


def _mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provision 8 virtual CPU devices"
    return Mesh(np.array(devs[:8]), ("replicas",))


def test_ring_offsets_match_topology():
    n, k = 32, 3
    nbrs = ring(n, k)
    offs = ring_offsets(k)
    r = np.arange(n)
    for j, off in enumerate(offs):
        assert (nbrs[:, j] == (r + off) % n).all()


@pytest.mark.parametrize("k", [1, 2, 3])
def test_ppermute_ring_equals_dense_ring_gset(k):
    mesh = _mesh()
    n, e = 64, 16
    spec = GSetSpec(n_elems=e)
    rng = np.random.RandomState(4)
    states = replicate(GSet.new(spec), n)._replace(
        mask=jnp.asarray(rng.rand(n, e) < 0.1)
    )
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    round_fn = jax.jit(ring_gossip_round_fn(GSet, spec, mesh, k=k))
    got = round_fn(sharded)
    ref = gossip_round(GSet, spec, states, jnp.asarray(ring(n, k)))
    assert jnp.array_equal(got.mask, ref.mask)


def test_ppermute_ring_equals_dense_ring_packed_orset_multiround():
    mesh = _mesh()
    n = 64
    spec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    rng = np.random.RandomState(5)
    from lasp_tpu.lattice.base import replicate as rep

    states = rep(PackedORSet.new(spec), n)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(n, spec.n_elems, spec.n_words)),
            dtype=jnp.uint32,
        )
    )
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    got, changed = ring_gossip_rounds(PackedORSet, spec, sharded, mesh, 3, k=2)
    ref = states
    nbrs = jnp.asarray(ring(n, 2))
    for _ in range(3):
        ref = gossip_round(PackedORSet, spec, ref, nbrs)
    assert bool(changed)
    assert jnp.array_equal(got.exists, ref.exists)
    assert jnp.array_equal(got.removed, ref.removed)


def test_hlo_contains_collective_permute():
    mesh = _mesh()
    n, e = 64, 16
    spec = GSetSpec(n_elems=e)
    states = replicate(GSet.new(spec), n)
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    round_fn = jax.jit(ring_gossip_round_fn(GSet, spec, mesh, k=2))
    hlo = round_fn.lower(sharded).compile().as_text()
    assert "collective-permute" in hlo, "ring gossip must lower to ppermute"


def test_dryrun_helper_runs():
    ring_gossip_shardmap_dryrun(_mesh(), 64)


def test_sharded_join_all_equals_dense_join():
    from lasp_tpu.mesh.gossip import join_all
    from lasp_tpu.mesh.shard_gossip import sharded_join_all

    mesh = _mesh()
    n = 72  # odd per-device blocks (9 rows) exercise join_all's padding
    spec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    rng = np.random.RandomState(8)
    from lasp_tpu.lattice.base import replicate as rep

    states = rep(PackedORSet.new(spec), n)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(n, spec.n_elems, spec.n_words)),
            dtype=jnp.uint32,
        )
    )
    got = sharded_join_all(PackedORSet, spec, states, mesh)
    ref = join_all(PackedORSet, spec, states)
    assert jnp.array_equal(got.exists, ref.exists)
    assert jnp.array_equal(got.removed, ref.removed)


def test_sharded_join_all_hlo_contains_all_gather():
    from lasp_tpu.mesh.shard_gossip import sharded_join_all

    mesh = _mesh()
    spec = GSetSpec(n_elems=16)
    states = replicate(GSet.new(spec), 64)
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    fn = jax.jit(lambda s: sharded_join_all(GSet, spec, s, mesh))
    hlo = fn.lower(sharded).compile().as_text()
    assert "all-gather" in hlo, "coverage join must lower to all-gather"


# -- the REAL engine step under shard() (VERDICT r3 ask #4) -------------------

def _sharded_step(topology, n=64):
    """Build a ReplicatedRuntime on `topology`, shard it over the 8-device
    mesh, and return (rt, compiled-HLO text of the jitted engine step)."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    store = Store(n_actors=8)
    s = store.declare(id="s", type="lasp_orset", n_elems=16)
    rt = ReplicatedRuntime(store, Graph(store), n, topology)
    rt.update_at(0, s, ("add", "seed"), "a0")
    rt.shard(Mesh(np.array(jax.devices()[:8]), ("replicas",)), axis="replicas")
    tables = rt._ensure_step()
    hlo = (
        jax.jit(rt._step_pure)
        .lower(rt.states, rt.neighbors, None, tables)
        .compile()
        .as_text()
    )
    return rt, hlo


def test_engine_step_ring_lowers_to_collective_permute():
    # the flagship sharded step itself — not a side entry point — must ride
    # nearest-neighbor ICI bandwidth on ring topologies
    _rt, hlo = _sharded_step(ring(64, 2))
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo, (
        "ring-topology engine gossip regressed to full-population all-gather"
    )


def test_engine_step_random_topology_lowers_to_all_gather():
    # irregular topologies keep the dynamic gather: the partitioner must
    # materialize the population (documented cost, runtime.py module doc).
    # XLA CSEs the per-column gathers — EXACTLY one real all-gather per
    # state plane (exists + removed = 2), not one per neighbor column;
    # the exact count pins that a formulation change can't silently
    # multiply ICI traffic by k
    import re

    from lasp_tpu.mesh.topology import random_regular

    _rt, hlo = _sharded_step(random_regular(64, 3, seed=2))
    real = re.findall(r"= \S+ all-gather\(", hlo)
    assert len(real) == 2, hlo.count("all-gather")


def test_engine_step_shift_path_matches_gather_path():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.store import Store

    def build(force_gather):
        store = Store(n_actors=8)
        s = store.declare(id="s", type="lasp_orset", n_elems=16)
        rt = ReplicatedRuntime(store, Graph(store), 48, ring(48, 3),
                               donate_steps=False)
        if force_gather:
            rt._shift_offsets = None  # pretend detection failed
        for r in range(0, 48, 5):
            rt.update_at(r, s, ("add", f"e{r}"), f"a{r % 8}")
        return rt, s

    rt_shift, s = build(False)
    rt_gather, _ = build(True)
    assert rt_shift._shift_offsets == (1, -1, 2)
    # identical evolution round by round, including under an edge mask
    rng = np.random.RandomState(9)
    mask = jnp.asarray(rng.rand(48, 3) < 0.7)
    for em in (None, mask):
        rs = rt_shift.step(edge_mask=em)
        rg = rt_gather.step(edge_mask=em)
        assert rs == rg
        for a, b in zip(
            jax.tree_util.tree_leaves(rt_shift.states["s"]),
            jax.tree_util.tree_leaves(rt_gather.states["s"]),
        ):
            assert jnp.array_equal(a, b)


def test_shift_offsets_detection():
    from lasp_tpu.mesh.topology import random_regular, shift_offsets

    assert shift_offsets(ring(64, 2), 64) == (1, -1)
    assert shift_offsets(ring(10, 4), 10) == (1, -1, 2, -2)
    assert shift_offsets(random_regular(64, 3, seed=0), 64) is None
    # a hand-built constant-shift table that isn't literally ring()'s
    r = np.arange(12)
    tbl = np.stack([(r + 5) % 12, (r + 11) % 12], axis=1)
    assert shift_offsets(tbl, 12) == (5, -1)
    assert shift_offsets(tbl, 11) is None  # wrong population size


def test_resize_redetects_shift_structure():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.mesh.topology import random_regular
    from lasp_tpu.store import Store

    store = Store(n_actors=4)
    g = store.declare(id="g", type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, Graph(store), 16, ring(16, 2))
    assert rt._shift_offsets == (1, -1)
    rt.update_at(0, g, ("increment", 3), "w")
    rt.resize(24, random_regular(24, 3, seed=1))
    assert rt._shift_offsets is None
    rt.run_to_convergence(max_rounds=32)
    assert rt.coverage_value("g") == 3
    rt.resize(20, ring(20, 2), graceful=True)
    assert rt._shift_offsets == (1, -1)
    rt.run_to_convergence(max_rounds=32)
    assert rt.coverage_value("g") == 3
