"""Explicit-collective ring gossip: semantics vs the dense reference path
and an HLO-level proof that the lowering really uses `collective-permute`
(VERDICT r2 ask #8 — "lowers to ppermute" must be verified, not claimed).
Runs on the 8-virtual-CPU-device mesh provisioned by tests/conftest.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lasp_tpu.lattice import GSet, GSetSpec, replicate
from lasp_tpu.mesh import gossip_round, ring
from lasp_tpu.mesh.shard_gossip import (
    ring_gossip_round_fn,
    ring_gossip_rounds,
    ring_gossip_shardmap_dryrun,
    ring_offsets,
)
from lasp_tpu.ops import PackedORSet, PackedORSetSpec


def _mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provision 8 virtual CPU devices"
    return Mesh(np.array(devs[:8]), ("replicas",))


def test_ring_offsets_match_topology():
    n, k = 32, 3
    nbrs = ring(n, k)
    offs = ring_offsets(k)
    r = np.arange(n)
    for j, off in enumerate(offs):
        assert (nbrs[:, j] == (r + off) % n).all()


@pytest.mark.parametrize("k", [1, 2, 3])
def test_ppermute_ring_equals_dense_ring_gset(k):
    mesh = _mesh()
    n, e = 64, 16
    spec = GSetSpec(n_elems=e)
    rng = np.random.RandomState(4)
    states = replicate(GSet.new(spec), n)._replace(
        mask=jnp.asarray(rng.rand(n, e) < 0.1)
    )
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    round_fn = jax.jit(ring_gossip_round_fn(GSet, spec, mesh, k=k))
    got = round_fn(sharded)
    ref = gossip_round(GSet, spec, states, jnp.asarray(ring(n, k)))
    assert jnp.array_equal(got.mask, ref.mask)


def test_ppermute_ring_equals_dense_ring_packed_orset_multiround():
    mesh = _mesh()
    n = 64
    spec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    rng = np.random.RandomState(5)
    from lasp_tpu.lattice.base import replicate as rep

    states = rep(PackedORSet.new(spec), n)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(n, spec.n_elems, spec.n_words)),
            dtype=jnp.uint32,
        )
    )
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    got, changed = ring_gossip_rounds(PackedORSet, spec, sharded, mesh, 3, k=2)
    ref = states
    nbrs = jnp.asarray(ring(n, 2))
    for _ in range(3):
        ref = gossip_round(PackedORSet, spec, ref, nbrs)
    assert bool(changed)
    assert jnp.array_equal(got.exists, ref.exists)
    assert jnp.array_equal(got.removed, ref.removed)


def test_hlo_contains_collective_permute():
    mesh = _mesh()
    n, e = 64, 16
    spec = GSetSpec(n_elems=e)
    states = replicate(GSet.new(spec), n)
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    round_fn = jax.jit(ring_gossip_round_fn(GSet, spec, mesh, k=2))
    hlo = round_fn.lower(sharded).compile().as_text()
    assert "collective-permute" in hlo, "ring gossip must lower to ppermute"


def test_dryrun_helper_runs():
    ring_gossip_shardmap_dryrun(_mesh(), 64)


def test_sharded_join_all_equals_dense_join():
    from lasp_tpu.mesh.gossip import join_all
    from lasp_tpu.mesh.shard_gossip import sharded_join_all

    mesh = _mesh()
    n = 72  # odd per-device blocks (9 rows) exercise join_all's padding
    spec = PackedORSetSpec(n_elems=8, n_actors=4, tokens_per_actor=2)
    rng = np.random.RandomState(8)
    from lasp_tpu.lattice.base import replicate as rep

    states = rep(PackedORSet.new(spec), n)._replace(
        exists=jnp.asarray(
            rng.randint(0, 256, size=(n, spec.n_elems, spec.n_words)),
            dtype=jnp.uint32,
        )
    )
    got = sharded_join_all(PackedORSet, spec, states, mesh)
    ref = join_all(PackedORSet, spec, states)
    assert jnp.array_equal(got.exists, ref.exists)
    assert jnp.array_equal(got.removed, ref.removed)


def test_sharded_join_all_hlo_contains_all_gather():
    from lasp_tpu.mesh.shard_gossip import sharded_join_all

    mesh = _mesh()
    spec = GSetSpec(n_elems=16)
    states = replicate(GSet.new(spec), 64)
    sh = NamedSharding(mesh, P("replicas"))
    sharded = jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), states)
    fn = jax.jit(lambda s: sharded_join_all(GSet, spec, s, mesh))
    hlo = fn.lower(sharded).compile().as_text()
    assert "all-gather" in hlo, "coverage join must lower to all-gather"
