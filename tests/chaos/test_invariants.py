"""The acceptance matrix: the invariant harness passes on every nemesis
preset × topology × scheduler mode — post-heal state bit-identical to
the fault-free fixed point, per-replica monotone inflation, the same
(seed, schedule) replaying to identical per-round states, and no
resurrection of removed OR-Set dots across crash/restore."""

import numpy as np
import pytest

from lasp_tpu.chaos import (
    InvariantViolation,
    check_inflation,
    check_no_resurrection,
    nemesis,
    run_harness,
    snapshot_states,
)
from lasp_tpu.chaos.schedule import PRESETS
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.store import Store

N = 32

_TOPOLOGIES = {
    "ring": ring(N, 2),
    "random": random_regular(N, 3, seed=11),
}


def _builder(nbrs):
    def build():
        store = Store(n_actors=8)
        g = store.declare(id="g", type="lasp_gset", n_elems=16)
        s = store.declare(id="s", type="riak_dt_orswot", n_elems=8,
                          n_actors=8)
        rt = ReplicatedRuntime(store, Graph(store), N, nbrs)
        rng = np.random.RandomState(3)
        rows = rng.choice(N, 5, replace=False)
        rt.update_batch(
            g, [(int(r), ("add", f"e{int(r) % 6}"), f"c{r}") for r in rows]
        )
        rt.update_at(int(rows[0]), s, ("add", "kept"), "w0")
        rt.update_at(int(rows[1]), s, ("add", "gone"), "w1")
        rt.update_at(int(rows[1]), s, ("remove", "gone"), "w1")
        return rt

    return build


@pytest.mark.parametrize("mode", ["dense", "frontier"])
@pytest.mark.parametrize("topology", sorted(_TOPOLOGIES))
@pytest.mark.parametrize("preset", sorted(PRESETS))
def test_invariants_preset_matrix(preset, topology, mode):
    """≥4 presets × ≥2 topologies × both schedulers (the ISSUE-4
    acceptance grid), replay determinism included."""
    nbrs = _TOPOLOGIES[topology]
    schedule = nemesis(preset, N, nbrs, seed=9, rounds=8)
    report = run_harness(
        _builder(nbrs), schedule, mode=mode, replay=True,
        removed_terms={"s": {"gone"}},
    )
    assert report["bit_identical_to_fault_free"]
    assert report["replay_identical"]
    assert report["healed"]


def test_check_inflation_flags_deflation():
    nbrs = ring(N, 2)
    rt = _builder(nbrs)()
    prev = snapshot_states(rt)
    # surgically deflate a row that actually carries state (drop every
    # set bit at the first seeded writer row)
    row = int(np.random.RandomState(3).choice(N, 5, replace=False)[0])
    st = rt.states["g"]
    assert bool(np.asarray(st.mask[row]).any())
    rt.states["g"] = st._replace(mask=st.mask.at[row].set(False))
    with pytest.raises(InvariantViolation, match="monotone-inflation"):
        check_inflation(rt, prev)
    # the same deflation at an exempt (just-restored) row passes
    check_inflation(rt, prev, exempt_rows=[row])


def test_check_no_resurrection_flags_comeback():
    nbrs = ring(N, 2)
    rt = _builder(nbrs)()
    rt.run_to_convergence()
    with pytest.raises(InvariantViolation, match="resurrection"):
        check_no_resurrection(rt, "s", {"kept"})  # "kept" IS present
    check_no_resurrection(rt, "s", {"gone"})  # removed stays removed


def test_harness_catches_destination_change():
    """A workload whose chaos run lands a DIFFERENT fixed point (the
    builder is non-deterministic) must fail the bit-equality invariant
    — the harness is only as good as its teeth."""
    nbrs = ring(N, 2)
    calls = {"n": 0}

    def flaky_build():
        store = Store(n_actors=8)
        g = store.declare(id="g", type="lasp_gset", n_elems=16)
        rt = ReplicatedRuntime(store, Graph(store), N, nbrs)
        calls["n"] += 1
        # later builds write MORE state: the chaos run's fixed point
        # genuinely differs from the fault-free twin's (note a single
        # varying term would not — fresh stores intern it to the same
        # slot, landing bit-identical planes)
        rt.update_at(0, g, ("add", "a"), "w0")
        if calls["n"] > 1:
            rt.update_at(0, g, ("add", "b"), "w0")
        return rt

    schedule = nemesis("ring-cut", N, nbrs, seed=1, rounds=4)
    with pytest.raises(InvariantViolation, match="fixed point differs"):
        run_harness(flaky_build, schedule, mode="dense", replay=False)
