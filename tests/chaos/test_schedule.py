"""ChaosSchedule compilation properties: determinism, symmetry, event
semantics, and the fused-kernel equivalence (a schedule window run
through ``ops.fused.fused_chaos_rounds`` is bit-identical to stepping
its masks one round at a time)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.chaos import (
    ChaosSchedule,
    Crash,
    DelayLinks,
    DuplicateLinks,
    FlakyLinks,
    Partition,
    Restore,
    SlowShard,
    nemesis,
)
from lasp_tpu.chaos.schedule import PRESETS
from lasp_tpu.lattice import GSet, GSetSpec
from lasp_tpu.lattice.base import replicate
from lasp_tpu.mesh import random_regular, ring
from lasp_tpu.mesh.gossip import gossip_round
from lasp_tpu.mesh.topology import assert_symmetric_mask
from lasp_tpu.ops.fused import fused_chaos_rounds

N = 48


def _sched(events, seed=7, nbrs=None):
    nbrs = random_regular(N, 3, seed=1) if nbrs is None else nbrs
    return ChaosSchedule(N, nbrs, events, seed=seed)


def test_masks_deterministic_and_symmetric():
    nbrs = random_regular(N, 3, seed=1)
    ev = [FlakyLinks(0, 10, 0.3), Partition(3, 7, 2),
          SlowShard(2, 9, shard=1, n_shards=4, period=2),
          DelayLinks(0, 10, frac=0.4, delay=2)]
    a, b = _sched(ev, nbrs=nbrs), _sched(ev, nbrs=nbrs)
    for rnd in range(12):
        ma, mb = a.mask_at(rnd), b.mask_at(rnd)
        if ma is None:
            assert mb is None
            continue
        assert np.array_equal(ma, mb)  # (seed, schedule) -> same masks
        assert_symmetric_mask(nbrs, ma)  # bidirectional link removal
    # a different seed produces different flaky draws
    c = _sched(ev, seed=8, nbrs=nbrs)
    assert any(
        not np.array_equal(a.mask_at(r), c.mask_at(r)) for r in range(10)
    )


def test_no_active_fault_returns_none_and_stable_identity():
    s = _sched([Partition(2, 6, 2)])
    assert s.mask_at(0) is None and s.mask_at(7) is None
    # identical fault state across a stable window -> the SAME object
    # (the frontier mask-identity contract)
    assert s.mask_at(3) is s.mask_at(4)


def test_crash_kills_all_links_and_restore_heals():
    nbrs = ring(N, 2)
    s = _sched([Crash(1, 5), Restore(4, 5)], nbrs=nbrs)
    assert s.mask_at(0) is None
    m = s.mask_at(2)
    # every edge pulling FROM 5 and every edge OF 5 is dead
    assert not m[5].any()
    assert not m[np.asarray(nbrs) == 5].any()
    assert s.crashed_at(2)[5] and not s.crashed_at(4)[5]
    assert s.mask_at(4) is None
    assert s.horizon == 4


def test_schedule_validation():
    nbrs = ring(N, 2)
    with pytest.raises(ValueError, match="not crashed"):
        _sched([Restore(2, 3)], nbrs=nbrs)
    with pytest.raises(ValueError, match="already crashed"):
        _sched([Crash(1, 3), Crash(2, 3)], nbrs=nbrs)
    with pytest.raises(ValueError, match="empty fault window"):
        _sched([Partition(5, 5, 2)], nbrs=nbrs)
    with pytest.raises(TypeError, match="unknown chaos event"):
        _sched([("boom", 1)], nbrs=nbrs)
    with pytest.raises(ValueError, match="unknown nemesis preset"):
        nemesis("split-brain", N, nbrs)
    with pytest.raises(TypeError, match="unknown options"):
        nemesis("ring-cut", N, nbrs, frobnicate=1)


def test_duplicates_count_but_do_not_mask():
    s = _sched([DuplicateLinks(0, 4, frac=0.5)])
    assert s.mask_at(1) is None  # idempotence absorbs duplication
    assert s.duplicate_links_at(1) > 0
    assert s.duplicate_links_at(9) == 0


def test_presets_heal_by_horizon():
    nbrs = random_regular(N, 3, seed=2)
    for preset in PRESETS:
        s = nemesis(preset, N, nbrs, seed=4, rounds=6)
        assert s.horizon > 0
        assert s.mask_at(s.horizon) is None, preset  # healed at horizon
        assert not s.crashed_at(s.horizon).any(), preset


def test_fused_chaos_rounds_matches_per_round_masks():
    """The whole timeline compiles into the existing masked kernel: one
    fori_loop over stacked masks == per-round host dispatches."""
    nbrs = jnp.asarray(random_regular(N, 3, seed=3))
    s = _sched([FlakyLinks(0, 6, 0.4), Partition(2, 5, 2)],
               nbrs=np.asarray(nbrs))
    spec = GSetSpec(n_elems=16)
    states = replicate(GSet.new(spec), N)
    rows = np.asarray([0, 7, 23])
    states = states._replace(
        mask=states.mask.at[jnp.asarray(rows), jnp.asarray(rows % 16)].set(
            True
        )
    )
    masks = s.masks(0, 8)
    fused, residuals = fused_chaos_rounds(
        GSet, spec, states, nbrs, jnp.asarray(masks)
    )
    ref = states
    ref_res = []
    for t in range(8):
        new = gossip_round(GSet, spec, ref, nbrs, jnp.asarray(masks[t]))
        changed = jax.vmap(lambda a, b: ~GSet.equal(spec, a, b))(ref, new)
        ref_res.append(int(jnp.sum(changed)))
        ref = new
    same = jax.tree_util.tree_map(
        lambda x, y: bool(jnp.array_equal(x, y)), fused, ref
    )
    assert all(jax.tree_util.tree_leaves(same))
    assert np.asarray(residuals).tolist() == ref_res
