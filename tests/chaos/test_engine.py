"""ChaosRuntime semantics: crash freezing + write refusal, restore
reseed + catch-up, degraded quorum reads with bounded read-repair,
fused chaos windows, and the actor-incarnation discipline."""

import jax
import numpy as np
import pytest

from lasp_tpu.chaos import (
    ChaosRuntime,
    ChaosSchedule,
    Crash,
    FlakyLinks,
    Partition,
    ReplicaDownError,
    Restore,
    nemesis,
)
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
from lasp_tpu.mesh.runtime import ActorCollisionError
from lasp_tpu.store import Store

N = 32


def _tree_eq(a, b):
    flags = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b,
    )
    return all(jax.tree_util.tree_leaves(flags))


def _build(nbrs, type="lasp_gset", **caps):
    store = Store(n_actors=8)
    caps.setdefault("n_elems", 16)
    v = store.declare(id="v", type=type, **caps)
    rt = ReplicatedRuntime(store, Graph(store), N, nbrs)
    return rt, v


def test_crashed_row_freezes_and_writes_refused():
    nbrs = ring(N, 2)
    rt, v = _build(nbrs)
    rt.update_at(0, v, ("add", "x"), "w0")
    ch = ChaosRuntime(rt, ChaosSchedule(
        N, nbrs, [Crash(0, 9), Restore(6, 9)], seed=1,
    ))
    row_before = jax.tree_util.tree_map(lambda x: x[9], rt.states[v])
    for _ in range(4):
        ch.step()
    # down: the row moved nowhere even as gossip spread "x" elsewhere
    row_after = jax.tree_util.tree_map(lambda x: x[9], rt.states[v])
    assert _tree_eq(row_before, row_after)
    with pytest.raises(ReplicaDownError):
        ch.write_at(9, v, ("add", "y"), "w9")
    rep = ch.soak()
    assert rep["healed"] and rep["restores"] == 1
    assert rt.replica_value(v, 9) == {"x"}  # caught up post-restore
    assert rt.divergence(v) == 0


def test_degraded_read_answers_live_and_repair_bounded():
    """During a partition the degraded read answers from live replicas;
    read-repair closes the quorum's gap immediately and the partition's
    gap within diameter rounds of healing (the acceptance bound)."""
    nbrs = ring(N, 2)
    rt, v = _build(nbrs)
    sched = ChaosSchedule(
        N, nbrs,
        [Partition(0, 8, 2), Crash(0, N - 1), Restore(8, N - 1)],
        seed=2,
    )
    ch = ChaosRuntime(rt, sched)
    rt.update_at(0, v, ("add", "x"), "w0")
    for _ in range(3):
        ch.step()
    assert (N - 1) not in ch.live_replicas()
    val = ch.degraded_read(v, k=2)
    assert val == {"x"}  # replica 0's write is visible via the quorum
    assert ch.degraded_reads == 1
    # repair merged the join back into the quorum rows read
    assert rt.replica_value(v, int(ch.live_replicas()[1])) == {"x"}
    rep = ch.soak()
    assert rep["healed"]
    # post-heal: read-repair + gossip closed every gap
    assert rt.divergence(v) == 0
    assert rep["rounds_to_heal"] <= N  # bounded by the ring diameter


def test_degraded_read_never_crosses_a_partition():
    """The quorum comes from the coordinator's SIDE of the cut: a
    host-side read spanning the partition would be a side channel that
    heals through the very fault the nemesis installed."""
    nbrs = ring(N, 2)
    rt, v = _build(nbrs)
    sched = ChaosSchedule(N, nbrs, [Partition(0, 12, 2)], seed=4)
    ch = ChaosRuntime(rt, sched)
    # one write on each side of the 2-way contiguous-group cut
    rt.update_at(2, v, ("add", "left"), "wl")
    rt.update_at(N - 2, v, ("add", "right"), "wr")
    for _ in range(6):  # intra-group gossip saturates both sides
        ch.step()
    assert ch.degraded_read(v, k=3, coordinator=2) == {"left"}
    assert ch.degraded_read(v, k=3, coordinator=N - 2) == {"right"}
    # read-repair stayed inside each side: no replica holds both yet
    for r in range(N):
        assert rt.replica_value(v, r) != {"left", "right"}
    rep = ch.soak()
    assert rep["healed"] and rt.coverage_value(v) == {"left", "right"}


def test_degraded_read_quorum_larger_than_reachable_clamps():
    """The partial-quorum surface: a requested k beyond the live
    reachable set clamps to R-of-live (the first-replies rule) instead
    of blocking or crossing the cut — and the answer is the join of
    exactly that smaller quorum."""
    nbrs = ring(N, 2)
    rt, v = _build(nbrs)
    # isolate a 4-replica group (N/8 groups of 8... use 8 groups of 4)
    sched = ChaosSchedule(N, nbrs, [Partition(0, 8, 8)], seed=3)
    ch = ChaosRuntime(rt, sched)
    rt.update_at(1, v, ("add", "near"), "w1")
    rt.update_at(20, v, ("add", "far"), "w2")
    for _ in range(4):
        ch.step()
    # coordinator 0's component is rows {0..3}: k=12 >> 4 reachable
    val = ch.degraded_read(v, k=12, coordinator=0)
    assert val == {"near"}  # clamped quorum, confined to the component
    # the strict quorum layer surfaces the same situation as an ERROR
    from lasp_tpu.quorum import PartialQuorumError, QuorumRuntime

    qr = QuorumRuntime(ch, n=3, r=3, timeout=2, retries=0)
    # a strict R=3 get whose coordinator sits in the 4-row island CAN
    # assemble (3 <= 4); break it harder: preflist {30, 31, 0} spans the
    # cut — rows 0 is unreachable from 30's island {28..31}
    rid = qr.submit_get(v, coordinator=30, r=3)
    while qr.inflight:
        qr.step()
    res = qr.result(rid, raise_on_error=False)
    assert res["status"] == "failed" and "partial quorum" in res["error"]
    with pytest.raises(PartialQuorumError, match="partial quorum"):
        qr.result(rid)


def test_degraded_read_repair_false_accounting():
    """``repair=False`` answers the quorum WITHOUT the read-repair
    partial join: no state changes, no repair rows, no wire bytes —
    the read-only accounting contract."""
    nbrs = ring(N, 2)
    rt, v = _build(nbrs)
    sched = ChaosSchedule(N, nbrs, [Partition(0, 8, 2)], seed=5)
    ch = ChaosRuntime(rt, sched)
    rt.update_at(0, v, ("add", "x"), "w0")
    ch.step()
    before = jax.tree_util.tree_map(np.asarray, rt.states[v])
    val = ch.degraded_read(v, k=3, repair=False)
    assert val == {"x"}
    assert ch.repaired_rows == 0 and ch.repair_bytes == 0
    assert _tree_eq(before, rt.states[v])  # no repair write happened
    # with repair on, the same read DOES move rows and count bytes
    val = ch.degraded_read(v, k=3, repair=True)
    assert val == {"x"}
    assert ch.repaired_rows > 0 and ch.repair_bytes > 0


def test_degraded_read_confined_under_delay_links_mask():
    """Quorum confinement holds for EVERY mask source, not just
    Partition: under a DelayLinks window that buffers every link, a
    non-flush round's mask isolates each replica — the quorum must
    collapse to the coordinator's own row."""
    nbrs = ring(N, 2)
    rt, v = _build(nbrs)
    from lasp_tpu.chaos import DelayLinks

    # frac=1.0: every link buffered; flush only every (delay+1)=4 rounds
    sched = ChaosSchedule(N, nbrs, [DelayLinks(0, 12, frac=1.0, delay=3)],
                          seed=6)
    ch = ChaosRuntime(rt, sched)
    rt.update_at(0, v, ("add", "x"), "w0")
    rt.update_at(5, v, ("add", "y"), "w5")
    ch.step()  # round 0: buffered (non-flush), nothing delivered
    # round 1's mask is still the buffered one: every replica is its own
    # component, so a k=3 read at coordinator 0 sees ONLY row 0
    assert ch.degraded_read(v, k=3, coordinator=0) == {"x"}
    assert ch.degraded_read(v, k=3, coordinator=5) == {"y"}
    assert ch.degraded_read(v, k=3, coordinator=9) == set()
    rep = ch.soak()
    assert rep["healed"] and rt.coverage_value(v) == {"x", "y"}


def test_degraded_read_without_live_replicas_raises():
    nbrs = ring(4, 2)
    store = Store(n_actors=4)
    v = store.declare(id="v", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, Graph(store), 4, nbrs)
    ch = ChaosRuntime(rt, ChaosSchedule(
        4, nbrs,
        [Crash(0, r) for r in range(4)]
        + [Restore(3, r) for r in range(4)],
        seed=0,
    ))
    ch.step()
    with pytest.raises(ReplicaDownError, match="every replica is down"):
        ch.degraded_read(v)


def test_crash_retires_actor_lanes():
    """The riak_dt never-reuse-an-actor incarnation rule under chaos:
    a crashed replica's actors may not mint again, anywhere."""
    nbrs = ring(N, 2)
    store = Store(n_actors=8)
    v = store.declare(id="v", type="riak_dt_orswot", n_elems=8,
                      n_actors=8)
    rt = ReplicatedRuntime(store, Graph(store), N, nbrs,
                           debug_actors=True)
    rt.update_at(3, v, ("add", "e"), "w3")
    ch = ChaosRuntime(rt, ChaosSchedule(
        N, nbrs, [Crash(0, 3), Restore(4, 3)], seed=0,
    ))
    ch.soak()
    with pytest.raises(ActorCollisionError, match="never mint again"):
        rt.update_at(3, v, ("add", "f"), "w3")
    # a FRESH actor name at the restored row is fine
    rt.update_at(3, v, ("add", "f"), "w3b")


def test_fused_windows_match_per_round_and_split_on_actions():
    nbrs = random_regular(N, 3, seed=4)

    def build():
        rt, v = _build(nbrs)
        rt.update_batch(
            v, [(0, ("add", "x"), "c0"), (11, ("add", "y"), "c11")]
        )
        return rt, v

    ev = [FlakyLinks(0, 6, 0.3), Crash(3, 7), Restore(6, 7)]
    ra, va = build()
    rb, vb = build()
    ca = ChaosRuntime(ra, ChaosSchedule(N, nbrs, ev, seed=5))
    cb = ChaosRuntime(rb, ChaosSchedule(N, nbrs, ev, seed=5))
    rep_a = ca.soak(block=1)
    rep_b = cb.soak(block=4)  # fused windows split at the crash/restore
    assert rep_a["healed"] and rep_b["healed"]
    # fused windows may overshoot quiescence by a partial block (the
    # rounds past the fixed point are no-ops); the destination agrees
    assert rep_b["rounds"] >= rep_a["rounds"]
    assert _tree_eq(ra.states[va], rb.states[vb])
    assert ra.divergence(va) == 0 and rb.divergence(vb) == 0

    # a window straddling an action is refused loudly
    rc, _ = build()
    cc = ChaosRuntime(rc, ChaosSchedule(N, nbrs, ev, seed=5))
    with pytest.raises(RuntimeError, match="crosses a crash/restore"):
        cc.fused_steps(8)


def test_engine_refuses_mismatched_schedule_and_partitioned_runtime():
    nbrs = ring(N, 2)
    rt, _v = _build(nbrs)
    with pytest.raises(ValueError, match="different neighbor table"):
        ChaosRuntime(rt, ChaosSchedule(N, ring(N, 4), [], seed=0))
    with pytest.raises(ValueError, match="for .* replicas"):
        ChaosRuntime(rt, ChaosSchedule(N * 2, ring(N * 2, 2), [], seed=0))


def test_session_nemesis_entry_point():
    from lasp_tpu.api import Session

    s = Session()
    v = s.declare(type="lasp_gset", id="g", n_elems=8)
    s.update(v, ("add", "x"), "w")
    rt = s.replicate(16, topology="ring", fanout=2)
    chaos = s.nemesis(rt, "ring_cut", seed=1, rounds=4)
    rep = chaos.soak()
    assert rep["healed"] and rt.divergence(v) == 0
    assert s.health()["chaos"]["healed"] is True


def test_cli_preset_choices_in_sync():
    """cli.py keeps a literal preset list (importing chaos there would
    pull jax into every CLI start); it must match chaos.PRESETS."""
    import os
    import re

    from lasp_tpu.chaos import PRESETS

    import lasp_tpu.cli

    src = open(os.path.abspath(lasp_tpu.cli.__file__)).read()
    block = re.search(
        r'ch\.add_argument\("--preset", required=True,\s*'
        r"choices=\[(.*?)\]", src, re.S,
    ).group(1)
    choices = set(re.findall(r'"([a-z-]+)"', block))
    assert choices == set(PRESETS)


def test_checkpoint_restore_row(tmp_path):
    """Restore(source='checkpoint') reseeds the crashed row from the
    snapshot and tombstones still win: no resurrection of an element
    removed AFTER the snapshot."""
    nbrs = random_regular(N, 3, seed=6)
    store = Store(n_actors=8)
    v = store.declare(id="s", type="lasp_orset", n_elems=8, n_actors=8,
                      tokens_per_actor=2)
    rt = ReplicatedRuntime(store, Graph(store), N, nbrs)
    rt.update_at(3, v, ("add", "keep"), "w3")
    rt.update_at(3, v, ("add", "gone"), "w3")
    rt.run_to_convergence()
    from lasp_tpu.store import save_runtime

    path = str(tmp_path / "chaos_ck.hs")
    save_runtime(rt, path)
    rt.update_at(3, v, ("remove", "gone"), "w3")
    sched = ChaosSchedule(
        N, nbrs, [Crash(1, 3), Restore(5, 3, source="checkpoint")],
        seed=3,
    )
    ch = ChaosRuntime(rt, sched, checkpoint=path)
    rep = ch.soak()
    assert rep["healed"]
    assert rt.coverage_value(v) == {"keep"}  # "gone" stays gone
    assert rt.divergence(v) == 0
