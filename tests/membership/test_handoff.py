"""The transfer engine: grouped dispatch bit-identity vs per-pair
partial joins, per-cycle capping, duplicate-target deferral, and
partition parking/resume."""

import numpy as np
import pytest

import jax

from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Partition
from lasp_tpu.dataflow import Graph
from lasp_tpu.membership import HandoffEngine, grouped_transfer
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _build(n=8, packed=False):
    store = Store(n_actors=8)
    store.declare(id="g", type="lasp_gset", n_elems=16)
    store.declare(id="g2", type="lasp_gset", n_elems=16)
    store.declare(id="o", type="lasp_orset", n_elems=16)
    store.declare(id="w", type="riak_dt_orswot", n_elems=16)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2),
                           packed=packed)
    rt.update_at(5, "g", ("add", "a"), "p")
    rt.update_at(6, "g2", ("add", "b"), "p2")
    rt.update_at(6, "o", ("add", "c"), "q")
    rt.update_at(7, "w", ("add", "d"), "r")
    return rt


@pytest.mark.parametrize("packed", [False, True])
def test_grouped_transfer_bit_identical_to_per_pair_joins(packed):
    pairs = [(5, 0), (6, 1), (7, 2)]
    rt = _build(packed=packed)
    ref = _build(packed=packed)
    # reference: one join_rows per pair per var, source row gathered
    for src, dst in pairs:
        for v in ref.var_ids:
            row = jax.tree_util.tree_map(
                lambda x: x[src], ref._population(v)
            )
            ref.join_rows(v, np.asarray([dst], dtype=np.int64), [row])
    changed = grouped_transfer(rt, pairs)
    assert changed > 0
    for v in rt.var_ids:
        for a, b in zip(
            jax.tree_util.tree_leaves(rt.states[v]),
            jax.tree_util.tree_leaves(ref.states[v]),
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b)), v
        # changed targets carry the exact frontier marks
        assert np.array_equal(rt._frontier[v], ref._frontier[v]), v


def test_grouped_transfer_refuses_duplicate_targets():
    rt = _build()
    with pytest.raises(ValueError, match="duplicate target"):
        grouped_transfer(rt, [(5, 0), (6, 0)])


def test_engine_caps_per_cycle_and_defers_duplicate_targets():
    rt = _build()
    sched = ChaosSchedule(8, ring(8, 2), events=())
    ch = ChaosRuntime(rt, sched)
    # two transfers share target 0: the second defers a cycle even
    # though the cap would admit it (the scatter would race)
    eng = HandoffEngine(ch, [(5, 0), (6, 0), (7, 2)], per_cycle=2)
    out1 = eng.cycle()
    assert out1["transfers"] == 2  # (5,0) and (7,2); (6,0) deferred
    assert eng.outstanding == 1
    out2 = eng.cycle()
    assert out2["transfers"] == 1 and eng.outstanding == 0
    assert eng.max_batch <= 2


def test_transfers_park_across_partition_and_resume_after_heal():
    rt = _build()
    # rows {0..3} | {4..7} split for rounds [0, 4)
    sched = ChaosSchedule(8, ring(8, 2), [Partition(0, 4, 2)])
    ch = ChaosRuntime(rt, sched)
    eng = HandoffEngine(ch, [(5, 0), (6, 5)], per_cycle=4)
    ch.step()
    out = eng.cycle()
    # (5, 0) crosses the cut: parked; (6, 5) is intra-component: done
    assert out["transfers"] == 1 and out["parked"] == 1
    assert eng.outstanding == 1
    # parked while the cut holds; resumes the first cycle whose mask
    # has healed (the window closing), without any re-submission
    while eng.outstanding:
        assert ch.round < 12, "parked transfer never resumed"
        ch.step()
        out = eng.cycle()
        if out["transfers"]:
            assert ch.round >= 4, "dispatched across the live cut"
    assert rt.replica_value("g", 0) == {"a"}


def test_crashed_source_parks():
    rt = _build()
    sched = ChaosSchedule(8, ring(8, 2), [Crash(0, 5)])
    ch = ChaosRuntime(rt, sched)
    ch.step()
    eng = HandoffEngine(ch, [(5, 0)], per_cycle=4)
    out = eng.cycle()
    assert out["transfers"] == 0 and out["parked"] == 1
    assert eng.outstanding == 1


def test_transfer_is_idempotent():
    rt = _build()
    pairs = [(5, 0), (6, 1)]
    assert grouped_transfer(rt, pairs) > 0
    snap = {
        v: jax.tree_util.tree_map(np.asarray, rt.states[v])
        for v in rt.var_ids
    }
    assert grouped_transfer(rt, pairs) == 0  # exact no-op re-run
    for v in rt.var_ids:
        for a, b in zip(
            jax.tree_util.tree_leaves(rt.states[v]),
            jax.tree_util.tree_leaves(snap[v]),
        ):
            assert np.array_equal(np.asarray(a), b)
