"""The staged flow end to end: commit semantics, epoch advance,
row-scoped frontier degrade, twin bit-equality, crashed-departer hint
fallback, partition-deferred finalize, and serve watch re-homing."""

import numpy as np
import pytest

import jax

from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Partition
from lasp_tpu.chaos.invariants import snapshot_states, states_equal
from lasp_tpu.dataflow import Graph
from lasp_tpu.membership import MembershipCoordinator
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _build(n=8, packed=False):
    store = Store(n_actors=8)
    store.declare(id="g", type="lasp_gset", n_elems=16)
    store.declare(id="o", type="lasp_orset", n_elems=16)
    store.declare(id="w", type="riak_dt_orswot", n_elems=16)
    return store, ReplicatedRuntime(store, Graph(store), n, ring(n, 2),
                                    packed=packed)


WRITES1 = [(0, "g", ("add", "a"), "p"), (3, "o", ("add", "b"), "q"),
           (5, "w", ("add", "c"), "r")]
WRITES2 = [(1, "g", ("add", "d"), "p2"), (2, "o", ("add", "e"), "q2")]


@pytest.mark.parametrize("packed", [False, True])
def test_round_trip_bit_identical_to_static_twin(packed):
    """join 8->12, writes, leave 12->8: the settled population is
    BIT-IDENTICAL to a twin built statically at 8 with the same writes
    (the acceptance criterion, across leafwise/vclock/packed)."""
    _store, rt = _build(packed=packed)
    for r, v, op, a in WRITES1:
        rt.update_at(r, v, op, a)
    rt.run_to_convergence()
    mc = MembershipCoordinator(rt, per_cycle=3)
    mc.stage_join(12)
    plan = mc.commit()
    assert rt.membership_epoch == plan.epoch == 1
    mc.run_to_settled()
    for r, v, op, a in WRITES2:
        rt.update_at(r, v, op, a)
    mc.stage_leave(8)
    mc.commit()
    mc.run_to_settled()
    rt.run_to_convergence()
    assert rt.membership_epoch == 2

    _s2, twin = _build(packed=packed)
    for r, v, op, a in WRITES1 + WRITES2:
        twin.update_at(r, v, op, a)
    twin.run_to_convergence()
    assert states_equal(snapshot_states(rt), snapshot_states(twin))


def test_join_seeds_new_rows_from_claim_predecessors():
    _store, rt = _build()
    rt.update_at(2, "g", ("add", "seeded"), "p")
    rt.run_to_convergence()
    mc = MembershipCoordinator(rt, per_cycle=8)
    mc.stage_join(12)
    mc.commit()
    # one transfer cycle seeds every new row directly — before any
    # further gossip delivery could have reached them
    mc.cycle()
    assert rt.replica_value("g", 10) == {"seeded"}  # src = 10 % 8 = 2


def test_row_scoped_frontier_degrade_on_staged_join():
    _store, rt = _build()
    rt.update_at(0, "g", ("add", "x"), "p")
    rt.run_to_convergence()
    for v in rt.var_ids:
        assert rt._frontier[v].sum() == 0  # quiescent
    mc = MembershipCoordinator(rt)
    mc.stage_join(12)
    plan = mc.commit()
    dirty = set(np.flatnonzero(rt._frontier["g"]).tolist())
    # row-scoped: exactly the plan's changed-delivery set, NOT all 12
    assert dirty == set(int(r) for r in plan.dirty_rows)
    assert len(dirty) < 12
    # and the run still converges to the full join everywhere
    mc.run_to_settled()
    rt.run_to_convergence()
    assert rt.replica_value("g", 11) == {"x"}
    assert rt.divergence("g") == 0


def test_leave_keeps_serving_while_transfers_drain():
    """During a staged leave the population stays intact and gossip
    keeps flowing — no stop-the-world window."""
    _store, rt = _build()
    rt.update_at(7, "g", ("add", "late"), "p")
    mc = MembershipCoordinator(rt, per_cycle=1)
    mc.stage_leave(6)
    mc.commit()
    assert rt.n_replicas == 8  # not dropped yet
    out = mc.step()
    assert rt.n_replicas == 8 and mc.rebalancing
    assert out["transfers"] == 1  # capped at per_cycle
    # a write lands on a departing row mid-rebalance; the finalize
    # sweep re-joins it (idempotent), so it survives the drop
    rt.update_at(6, "o", ("add", "mid"), "q")
    mc.run_to_settled()
    assert rt.n_replicas == 6
    assert "late" in rt.coverage_value("g")
    assert "mid" in rt.coverage_value("o")


def test_down_drops_immediately_with_crash_semantics():
    _store, rt = _build()
    rt.update_at(7, "g", ("add", "doomed"), "p")  # never gossips
    mc = MembershipCoordinator(rt)
    mc.stage_down(6)
    mc.commit()
    assert rt.n_replicas == 6 and not mc.rebalancing
    rt.run_to_convergence()
    assert "doomed" not in rt.coverage_value("g")


def test_finalize_defers_while_partitioned_then_completes():
    _store, rt = _build()
    rt.update_at(6, "g", ("add", "held"), "p")
    sched = ChaosSchedule(8, ring(8, 2), [Partition(0, 5, 2)])
    ch = ChaosRuntime(rt, sched)
    ch.step()  # partition live: rows {0..3} | {4..7}
    mc = MembershipCoordinator(ch, per_cycle=8)
    mc.stage_leave(6)  # (6 -> 0) crosses the cut, (7 -> 1) too
    mc.commit()
    out = mc.cycle()
    assert out["parked"] == 2 and mc.rebalancing
    assert rt.n_replicas == 8  # finalize deferred, nothing dropped
    mc.run_to_settled()
    assert rt.n_replicas == 6
    assert "held" in rt.coverage_value("g")
    assert rt.membership_epoch == 1


def test_crashed_departer_falls_back_to_hints():
    """A departing replica that crashes before its transfer: its acked
    (hint-logged) writes replay into the claim successor at finalize —
    no acknowledged write lost; unlogged state takes crash semantics."""
    from lasp_tpu.quorum import HintLog

    _store, rt = _build()
    rt.update_at(6, "g", ("add", "acked"), "p")
    rt.update_at(6, "o", ("add", "unacked"), "q")
    hints = HintLog()
    row = jax.tree_util.tree_map(
        lambda x: np.asarray(x[6]), rt._population("g")
    )
    hints.append("g", np.asarray([6], dtype=np.int64), row, rid=0)
    sched = ChaosSchedule(8, ring(8, 2), [Crash(0, 6)])
    ch = ChaosRuntime(rt, sched)
    mc = MembershipCoordinator(ch, per_cycle=8, hints=hints)
    ch.step()  # crash lands before any gossip moves row 6's state
    mc.stage_leave(6)
    mc.commit()
    mc.run_to_settled()
    assert rt.n_replicas == 6
    assert 6 in mc.lost_sources
    assert "acked" in rt.coverage_value("g")  # hint fallback
    rt.run_to_convergence()
    assert "unacked" not in rt.coverage_value("o")  # honest crash loss


def test_serve_watches_rehome_at_finalize():
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.serve import ServeFrontend

    store, rt = _build()
    fe = ServeFrontend(rt)
    gvar = store.variable("g")
    bottom = gvar.codec.new(gvar.spec)
    sid = fe.subs.register("g", gvar.codec, gvar.spec,
                           Threshold(bottom, True), replica=7,
                           payload="park")
    mc = MembershipCoordinator(rt, serve=fe)
    mc.stage_leave(6)
    mc.commit()
    mc.run_to_settled()
    # the watch re-homed to 7 % 6 == 1 (the claim successor)
    _var, slot = fe.subs._index[sid]
    group = fe.subs._groups["g"]
    assert int(group.replica[slot]) == 1


def test_commit_refused_while_rebalancing():
    _store, rt = _build()
    mc = MembershipCoordinator(rt, per_cycle=1)
    mc.stage_join(12)
    mc.commit()
    mc.staging.stage_join(16)
    with pytest.raises(RuntimeError, match="still rebalancing"):
        mc.commit()
