"""The staging console: claim rule, seed rule, row-scoped delivery
sets, and staging semantics (one direction per plan)."""

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.membership import (
    MembershipStaging,
    changed_delivery_rows,
    claim_targets,
    seed_sources,
)
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _rt(n=8):
    store = Store(n_actors=4)
    store.declare(id="s", type="lasp_gset", n_elems=8)
    return ReplicatedRuntime(store, Graph(store), n, ring(n, 2))


class TestClaimRule:
    def test_ring_fold_spreads_over_survivors(self):
        # 12 -> 8: departing rows 8..11 fold onto 0..3 — never all row 0
        t = claim_targets(12, 8)
        assert t.tolist() == [0, 1, 2, 3]

    def test_shrink_by_more_than_half_wraps(self):
        t = claim_targets(8, 3)
        assert t.tolist() == [0, 1, 2, 0, 1]

    def test_seed_sources_mirror(self):
        s = seed_sources(8, 12)
        assert s.tolist() == [0, 1, 2, 3]

    def test_validation(self):
        with pytest.raises(ValueError):
            claim_targets(8, 8)
        with pytest.raises(ValueError):
            seed_sources(8, 8)
        with pytest.raises(ValueError):
            claim_targets(8, 0)


class TestChangedDeliveryRows:
    def test_grow_marks_new_rows_and_fresh_references_only(self):
        old = ring(8, 2)
        new = ring(12, 2)
        dirty = set(changed_delivery_rows(old, new, 8, 12).tolist())
        # new rows always re-deliver
        assert {8, 9, 10, 11} <= dirty
        # ring(12)'s surviving prefix only rewires rows 0 and 7 (their
        # wrap edges now point at 11 and 8); interior rows 1..6 keep
        # identical pull lists and must NOT be marked
        assert not ({1, 2, 3, 4, 5, 6} & dirty)

    def test_shrink_marks_rewired_references(self):
        old = ring(12, 2)
        new = ring(8, 2)
        dirty = set(changed_delivery_rows(old, new, 12, 8).tolist())
        # rows 0 and 7's wrap edges change (7 and 0 newly reference
        # each other); interior pairs keep their knowledge
        assert dirty <= {0, 7}
        assert not ({2, 3, 4, 5} & dirty)

    def test_identical_topology_is_empty(self):
        old = ring(8, 2)
        assert changed_delivery_rows(old, old, 8, 8).size == 0


class TestStaging:
    def test_plan_join_has_seed_transfers_and_next_epoch(self):
        rt = _rt(8)
        st = MembershipStaging(rt)
        st.stage_join(12)
        plan = st.plan()
        assert plan.kind == "join"
        assert plan.epoch == rt.membership_epoch + 1
        assert plan.transfers == ((0, 8), (1, 9), (2, 10), (3, 11))
        d = plan.describe()
        assert d["old_n"] == 8 and d["new_n"] == 12

    def test_plan_leave_claims_ring_successors(self):
        rt = _rt(8)
        st = MembershipStaging(rt)
        st.stage_leave(6)
        plan = st.plan()
        assert plan.transfers == ((6, 0), (7, 1))

    def test_down_plans_no_transfers(self):
        rt = _rt(8)
        st = MembershipStaging(rt)
        st.stage_down(6)
        assert st.plan().transfers == ()

    def test_chained_same_direction_collapses(self):
        rt = _rt(8)
        st = MembershipStaging(rt)
        st.stage_join(10)
        st.stage_join(12)
        assert st.plan().new_n == 12

    def test_opposite_directions_refused(self):
        rt = _rt(8)
        st = MembershipStaging(rt)
        st.stage_join(12)
        with pytest.raises(ValueError, match="one direction"):
            st.stage_leave(6)
        st.clear()
        st.stage_leave(6)
        with pytest.raises(ValueError, match="one direction"):
            st.stage_join(12)

    def test_empty_staging_refuses_plan(self):
        rt = _rt(8)
        with pytest.raises(ValueError, match="nothing staged"):
            MembershipStaging(rt).plan()

    def test_stage_bounds(self):
        rt = _rt(8)
        st = MembershipStaging(rt)
        with pytest.raises(ValueError):
            st.stage_join(8)
        with pytest.raises(ValueError):
            st.stage_leave(8)
        with pytest.raises(ValueError):
            st.stage_down(0)
