"""run_membership_harness across nemesis presets — the acceptance
gates: no acked write lost, static-twin bit-equality, typed fencing,
replay determinism. (The full preset × codec matrix runs in
tools/membership_smoke.py; this keeps a representative slice in
tier-1.)"""

import pytest

from lasp_tpu.chaos import ChaosSchedule, Crash, Partition
from lasp_tpu.dataflow import Graph
from lasp_tpu.membership import run_membership_harness
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def _build(n=12, packed=False):
    def build():
        store = Store(n_actors=32)
        store.declare(id="kv", type="lasp_orset", n_elems=64,
                      tokens_per_actor=8)
        store.declare(id="g", type="lasp_gset", n_elems=64)
        return ReplicatedRuntime(store, Graph(store), n, ring(n, 2),
                                 packed=packed)

    return build


DIRECT_WRITES = [
    (1, 0, "kv", ("add", "w0"), "a0"),
    (5, 3, "g", ("add", "w1"), "a1"),
    (10, 7, "kv", ("add", "w2"), "a2"),
]


@pytest.mark.parametrize("preset", ["ring-cut", "flaky-links"])
@pytest.mark.parametrize("packed", [False, True])
def test_direct_workload_twin_bit_equality(preset, packed):
    rep = run_membership_harness(
        _build(packed=packed),
        [(2, "join", 18), (8, "leave", 12)],
        build_twin=_build(packed=packed),
        preset=preset, seed=5, nemesis_rounds=8,
        writes=DIRECT_WRITES, per_cycle=3,
    )
    assert rep["bit_identical_to_twin"]
    assert rep["replay_identical"]
    assert rep["final_n"] == 12 and rep["epoch"] == 2


def test_quorum_workload_no_write_lost_under_rolling_crash():
    rep = run_membership_harness(
        _build(),
        [(3, "join", 16), (9, "leave", 12)],
        preset="rolling-crash", seed=7, nemesis_rounds=10,
        quorum_writes=[
            (1, "kv", ("add", "q0"), "c0", 0),
            (4, "kv", ("add", "q1"), "c1", 13),
            (8, "kv", ("add", "q2"), "c2", 5),
            (10, "kv", ("add", "q3"), "c3", 14),
        ],
        per_cycle=2,
    )
    assert rep["no_write_lost"] and rep["replay_identical"]
    assert rep["acked_writes"] >= 1


def test_partition_during_handoff_no_write_lost():
    """The named composite: a partition window OVERLAPPING the leave's
    transfer phase — transfers park, serving continues degraded, and
    every acked write survives the eventual drop."""
    build = _build()
    rt0 = build()
    schedule = ChaosSchedule(
        12, rt0._host_neighbors, [Partition(6, 14, 2)], seed=3
    )
    rep = run_membership_harness(
        build,
        [(2, "join", 16), (7, "leave", 12)],
        schedule=schedule,
        quorum_writes=[
            (1, "kv", ("add", "p0"), "d0", 2),
            (6, "kv", ("add", "p1"), "d1", 9),
            (9, "kv", ("add", "p2"), "d2", 4),
        ],
        per_cycle=2,
    )
    assert rep["no_write_lost"] and rep["replay_identical"]
    assert rep["final_n"] == 12


def test_crash_of_departing_replica_no_write_lost():
    """A departing replica crashes mid-rebalance and NEVER restores:
    its acked writes survive via the hint-log lost_src fallback (the
    coordinator's crash-patience path replays the hints into the claim
    successor before the drop)."""
    build = _build()
    rt0 = build()
    # leave 12 -> 10 departs rows 10 and 11; row 10 crashes at round 5
    # (BEFORE the leave commits, no Restore scheduled) after
    # coordinating a put at round 2 — its transfer can never dispatch,
    # so the coordinator's crash-patience window trips lost_src
    schedule = ChaosSchedule(
        12, rt0._host_neighbors, [Crash(5, 10)], seed=11
    )
    rep = run_membership_harness(
        build,
        [(6, "leave", 10)],
        schedule=schedule,
        quorum_writes=[
            (1, "kv", ("add", "h0"), "e0", 3),
            (2, "kv", ("add", "h1"), "e1", 10),
        ],
        per_cycle=1, max_rounds=256,
    )
    assert rep["no_write_lost"]
    assert rep["final_n"] == 10


def test_twin_check_survives_write_landing_on_crashed_row():
    """A direct write whose round finds its target row crashed is
    dropped deterministically in the live run; the static twin must
    replay the APPLIED subset, not the full schedule — the bit-equality
    check judges the handoff, never a harness-introduced divergence."""
    from lasp_tpu.chaos import Restore

    build = _build()
    rt0 = build()
    # row 5 is down for rounds [2, 8) — exactly when its write arrives
    schedule = ChaosSchedule(
        12, rt0._host_neighbors, [Crash(2, 5), Restore(8, 5)], seed=1
    )
    rep = run_membership_harness(
        build,
        [(4, "join", 16), (10, "leave", 12)],
        build_twin=build,
        schedule=schedule,
        writes=[
            (1, 0, "g", ("add", "w0"), "a0"),
            (3, 5, "g", ("add", "dropped"), "a1"),  # row 5 crashed at 3
            (9, 2, "g", ("add", "w2"), "a2"),
        ],
        per_cycle=3,
    )
    assert rep["bit_identical_to_twin"] and rep["replay_identical"]
