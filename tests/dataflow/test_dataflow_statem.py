"""Dataflow statem: RANDOM combinator pipelines under add/remove churn
against a REFERENCE-FAITHFUL oracle — the property tier above the fixed
riak_test pipelines (test_combinators.py): at every propagated fixed
point, every derived variable's live value equals the oracle's
prediction, no matter what causal machinery (tokens, pair universes,
tombstone flow) produced it.

Round-5 oracle design: instead of encoding the tricky consequences of
Lasp's combinator semantics (union freeze points, intersection's
either-side causality) as closed-form rules over per-propagate
snapshots, the oracle SIMULATES the engine's dynamics at the token-dict
level — a Python model of ``src/lasp_core.erl``'s combinators over
``elem -> {token_id: deleted}`` orddicts, run in the same synchronous
rounds to the same fixed point:

- ``union`` is LEFT-BIASED (``orddict:merge`` keeping left,
  ``src/lasp_core.erl:616-621``): the model computes ``l[e] if e in l
  else r[e]`` per round, and the output variable's join-monotone bind
  does the freezing — exactly the engine's mechanism, so the one-round
  shift that derived LEFT inputs introduce (membership is read from
  pre-round state) emerges instead of being special-cased. The r4
  restriction of union lefts to source variables is LIFTED.
- ``intersection`` gates on membership in BOTH dicts but its causality
  is the UNION of both token dicts (``src/lasp_lattice.erl:311-312``):
  live iff live on either side.
- ``product`` pairs carry token pairs with ``deleted = XDel orelse
  YDel`` (``src/lasp_core.erl`` causal product).
- ``map``/``fold``/``filter`` flow each preimage's token dict to its
  image (images merge preimage causality).

Token identity models the ENGINE, not the reference: union/intersection
outputs CONCAT their input token axes, so the oracle tags token ids per
side — a diamond (the same source token reaching a union via both
inputs) keeps two independent copies, exactly like the dense encoding.
The one observable consequence (a left-path tombstone cannot kill a
frozen right-path copy, where the reference's global token ids would) is
a documented reference delta, pinned separately in
test_combinators.py::test_union_diamond_frozen_copy. This oracle found
it: the r4 snapshot oracle's source-left restriction was masking it.

Because both the engine and the model are deterministic synchronous
round systems with identical per-round dynamics, their trajectories —
and therefore their fixed points — coincide exactly.

map/fold still avoid product inputs in the random DAG: their token
spaces multiply into OOM territory at soak budgets (an engine capacity
bound, not a semantics gap)."""

import itertools
import os
import random

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.store import Store

N_SEEDS = int(os.environ.get("LASP_STATEM_SEEDS", "8"))
N_OPS = int(os.environ.get("LASP_STATEM_OPS", "40"))
DOMAIN = list(range(6))

FNS = {
    "x7": lambda x: (x * 7) % 11,
    "neg": lambda x: -x,
    "dup": lambda x: [x, x + 10],
    "even": lambda x: (x if isinstance(x, int) else hash(x)) % 2 == 0,
    "small": lambda x: (x if isinstance(x, int) else hash(x)) % 3 != 0,
}


def _join_entry(a: dict, b: dict) -> dict:
    """Join two token dicts: union of ids, deleted flags OR-monotone."""
    out = dict(a)
    for tid, dead in b.items():
        out[tid] = out.get(tid, False) or dead
    return out


def _join_dict(a: dict, b: dict) -> dict:
    out = {e: dict(toks) for e, toks in a.items()}
    for e, toks in b.items():
        out[e] = _join_entry(out.get(e, {}), toks)
    return out


class Oracle:
    """Token-dict model of the dataflow engine: sources hold
    ``elem -> {token_id: deleted}`` orddicts mutated by client ops;
    ``propagate`` runs the combinator DAG in synchronous rounds (every
    edge reads the PREVIOUS round's node states; outputs join-bind) to
    the fixed point, exactly like ``Graph.propagate``."""

    def __init__(self, sources, edges):
        #: edges: [(out_id, node_tuple)] in creation order; node tuples
        #: reference input ids, e.g. ("union", "src0", "d2")
        self.edges = edges
        self.state = {s: {} for s in sources}
        for out, _node in edges:
            self.state.setdefault(out, {})
        self._tokens = itertools.count()

    # -- client ops on sources -----------------------------------------------
    def add(self, src, e):
        entry = self.state[src].setdefault(e, {})
        entry[next(self._tokens)] = False

    def remove(self, src, e):
        for tid in self.state[src].get(e, {}):
            self.state[src][e][tid] = True

    # -- one synchronous round -----------------------------------------------
    def _edge_out(self, node, prev) -> dict:
        kind = node[0]
        if kind in ("map", "fold"):
            # image tokens are keyed by (preimage, token) — the engine's
            # S*T token space (edges.py ProjectEdge): colliding images
            # merge their preimages' CAUSALITY without conflating their
            # token columns
            out: dict = {}
            for e, toks in prev[node[2]].items():
                images = (
                    FNS[node[1]](e) if kind == "fold" else [FNS[node[1]](e)]
                )
                tagged = {(e, t): d for t, d in toks.items()}
                for img in images:
                    out[img] = _join_entry(out.get(img, {}), tagged)
            return out
        if kind == "filter":
            return {
                e: dict(toks)
                for e, toks in prev[node[2]].items()
                if FNS[node[1]](e)
            }
        if kind == "union":
            # left-biased orddict:merge — and, faithful to the ENGINE's
            # dense concat token axis (not the reference's global token
            # ids), each side's tokens are tagged by side: a token
            # reaching the union through BOTH inputs (a diamond) keeps
            # two independent columns, so a tombstone arriving via the
            # left path never kills the frozen right-side copy. See
            # edges.py PairwiseEdge for the documented reference delta.
            l, r = prev[node[1]], prev[node[2]]
            out = {
                e: {("L", t): d for t, d in toks.items()}
                for e, toks in l.items()
            }
            for e, toks in r.items():
                if e not in l:
                    out[e] = {("R", t): d for t, d in toks.items()}
            return out
        if kind == "intersection":
            l, r = prev[node[1]], prev[node[2]]
            return {
                e: {
                    **{("L", t): d for t, d in l[e].items()},
                    **{("R", t): d for t, d in r[e].items()},
                }
                for e in l.keys() & r.keys()
            }
        if kind == "product":
            l, r = prev[node[1]], prev[node[2]]
            out = {}
            for a, ta in l.items():
                for b, tb in r.items():
                    out[(a, b)] = {
                        (x, y): dx or dy
                        for (x, dx) in ta.items()
                        for (y, dy) in tb.items()
                    }
            return out
        if kind == "bind_to":
            return {e: dict(toks) for e, toks in prev[node[1]].items()}
        raise AssertionError(kind)

    def propagate(self):
        while True:
            prev = self.state
            new = dict(prev)
            changed = False
            for out, node in self.edges:
                candidate = _join_dict(prev[out], self._edge_out(node, prev))
                if candidate != prev[out]:
                    changed = True
                new[out] = candidate
            self.state = new
            if not changed:
                return

    def live(self, vid) -> frozenset:
        return frozenset(
            e
            for e, toks in self.state[vid].items()
            if any(not dead for dead in toks.values())
        )


# test tiering (README "Test tiers"): half the seeds run in the quick
# tier (`pytest -m "not slow"`), the rest in the slow soak tier
@pytest.mark.parametrize(
    "seed",
    [
        seed if seed < 4 else pytest.param(seed, marks=pytest.mark.slow)
        for seed in range(N_SEEDS)
    ],
)
def test_dataflow_statem(seed):
    rng = random.Random(seed)
    store = Store(n_actors=4)
    graph = Graph(store)

    sources = []
    for i in range(3):
        vid = store.declare(id=f"src{i}", type="lasp_orset", n_elems=16,
                            tokens_per_actor=max(16, N_OPS))
        sources.append(vid)

    def has_product(node_id, nodes):
        node = nodes.get(node_id)
        if node is None:
            return False  # a source
        return node[0] == "product" or any(
            has_product(x, nodes) for x in node[1:]
        )

    nodes: dict = {}  # out_id -> node tuple over INPUT IDS
    edges: list = []
    ids = list(sources)
    for j in range(rng.randint(3, 6)):
        kind = rng.choice(
            ["map", "fold", "filter", "union", "intersection", "product",
             "bind_to"]
        )
        a = rng.choice(ids)
        if kind in ("map", "fold") and has_product(a, nodes):
            # map/fold token spaces are S*T of their input; over a
            # product (whose token space is already Tl*Tr) the widths
            # multiply into OOM territory at soak op budgets — only
            # token-width-preserving edges consume products
            a = rng.choice(sources)
        if kind == "map":
            fn = rng.choice(["x7", "neg"])
            out = graph.map(a, FNS[fn], dst=f"d{j}", dst_elems=64)
            nodes[out] = ("map", fn, a)
        elif kind == "fold":
            out = graph.fold(a, FNS["dup"], dst=f"d{j}", dst_elems=64)
            nodes[out] = ("fold", "dup", a)
        elif kind == "filter":
            fn = rng.choice(["even", "small"])
            out = graph.filter(a, FNS[fn], dst=f"d{j}")
            nodes[out] = ("filter", fn, a)
        elif kind == "bind_to":
            out = graph.bind_to(f"d{j}", a)
            nodes[out] = ("bind_to", a)
        elif kind == "union":
            # round 5: the LEFT may be ANY node, derived included — the
            # r4 source-only restriction is lifted (module docstring)
            left = rng.choice(ids)
            out = graph.union(left, a, dst=f"d{j}")
            nodes[out] = ("union", left, a)
        else:
            b = rng.choice(ids)
            if kind == "product":
                # products multiply token widths: sources only
                a, b = rng.choice(sources), rng.choice(sources)
            out = getattr(graph, kind)(a, b, dst=f"d{j}")
            nodes[out] = (kind, a, b)
        edges.append((out, nodes[out]))
        ids.append(out)

    oracle = Oracle(sources, edges)
    live = {s: set() for s in sources}

    def check():
        graph.propagate()
        oracle.propagate()
        for vid in ids:
            assert store.value(vid) == oracle.live(vid), (seed, vid)

    for _step in range(N_OPS):
        src = rng.choice(sources)
        if live[src] and rng.random() < 0.3:
            e = rng.choice(sorted(live[src]))
            store.update(src, ("remove", e), "w")
            oracle.remove(src, e)
            live[src].discard(e)
        else:
            e = rng.choice(DOMAIN)
            store.update(src, ("add", e), "w")
            oracle.add(src, e)
            live[src].add(e)
        if rng.random() < 0.5:
            check()
    check()
