"""Dataflow statem: RANDOM combinator pipelines under add/remove churn
against a REFERENCE-FAITHFUL oracle — the property tier above the fixed
riak_test pipelines (test_combinators.py): at every propagated fixed
point, every derived variable's live value equals the oracle's
prediction, no matter what causal machinery (tokens, pair universes,
tombstone flow) produced it.

The oracle models Lasp's combinators, not clean set algebra — building
it surfaced exactly the corners that differ:

- ``union`` is LEFT-BIASED (``orddict:merge`` keeping left,
  ``src/lasp_core.erl:616-621``): right-side tokens flow into the
  monotone output only while the element is absent from the left DICT
  (live or tombstoned); once it appears there, later right-side
  removals are invisible — the right-live state freezes as of the last
  propagate where the element was left-absent. The oracle tracks
  per-propagate source snapshots to evaluate that frozen state.
- ``intersection`` gates on membership in BOTH dicts but its causality
  is the UNION of both token dicts (``src/lasp_lattice.erl:311-312``):
  the output element is live iff live on EITHER side — removing it from
  just one input does not remove it from the intersection.
- ``product`` pairs are live iff both coordinates are live
  (``deleted = XDel orelse YDel``) — clean algebra.
- ``map``/``fold``/``filter`` preserve causality per element image —
  clean algebra over live values; dict membership flows through images.

Union LEFT inputs are restricted to source variables in the random DAG:
for a derived left, the freeze point shifts by one propagation round
(membership computed from pre-round state), which the per-propagate
snapshot oracle cannot see. Rights are unrestricted, including chained
unions (the freeze rule recurses through snapshots)."""

import os
import random

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.store import Store

N_SEEDS = int(os.environ.get("LASP_STATEM_SEEDS", "8"))
N_OPS = int(os.environ.get("LASP_STATEM_OPS", "40"))
DOMAIN = list(range(6))

FNS = {
    "x7": lambda x: (x * 7) % 11,
    "neg": lambda x: -x,
    "dup": lambda x: [x, x + 10],
    "even": lambda x: (x if isinstance(x, int) else hash(x)) % 2 == 0,
    "small": lambda x: (x if isinstance(x, int) else hash(x)) % 3 != 0,
}


class Oracle:
    """Evaluates live(node, t) and member(node, t) — the live value and
    the dict key set of any DAG node at propagate-snapshot ``t`` — from
    the recorded per-propagate source snapshots."""

    def __init__(self):
        #: per propagate: {src: (frozenset live, frozenset ever)}
        self.snaps: list = []

    def snapshot(self, live, ever):
        self.snaps.append(
            {s: (frozenset(live[s]), frozenset(ever[s])) for s in live}
        )

    def live(self, node, t) -> frozenset:
        kind = node[0]
        if kind == "src":
            return self.snaps[t][node[1]][0]
        if kind == "map":
            return frozenset(FNS[node[1]](x) for x in self.live(node[2], t))
        if kind == "fold":
            out = set()
            for x in self.live(node[2], t):
                out.update(FNS[node[1]](x))
            return frozenset(out)
        if kind == "filter":
            return frozenset(
                x for x in self.live(node[2], t) if FNS[node[1]](x)
            )
        if kind == "union":
            l, r = node[1], node[2]
            out = set(self.live(l, t))
            for e in self.member(r, t):
                # freeze point: the last propagate at-or-before t where e
                # was absent from the LEFT dict; right-live flows only
                # through those propagates (left-biased merge)
                pk = None
                for tt in range(t, -1, -1):
                    if e not in self.member(l, tt):
                        pk = tt
                        break
                if pk is not None and e in self.live(r, pk):
                    out.add(e)
            return frozenset(out)
        if kind == "intersection":
            both = self.member(node[1], t) & self.member(node[2], t)
            either_live = self.live(node[1], t) | self.live(node[2], t)
            return frozenset(both & either_live)
        if kind == "product":
            return frozenset(
                (a, b)
                for a in self.live(node[1], t)
                for b in self.live(node[2], t)
            )
        if kind == "bind_to":
            return self.live(node[1], t)
        raise AssertionError(kind)

    def member(self, node, t) -> frozenset:
        kind = node[0]
        if kind == "src":
            return self.snaps[t][node[1]][1]
        if kind == "map":
            return frozenset(
                FNS[node[1]](x) for x in self.member(node[2], t)
            )
        if kind == "fold":
            out = set()
            for x in self.member(node[2], t):
                out.update(FNS[node[1]](x))
            return frozenset(out)
        if kind == "filter":
            return frozenset(
                x for x in self.member(node[2], t) if FNS[node[1]](x)
            )
        if kind == "union":
            return self.member(node[1], t) | self.member(node[2], t)
        if kind == "intersection":
            return self.member(node[1], t) & self.member(node[2], t)
        if kind == "product":
            return frozenset(
                (a, b)
                for a in self.member(node[1], t)
                for b in self.member(node[2], t)
            )
        if kind == "bind_to":
            return self.member(node[1], t)
        raise AssertionError(kind)


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_dataflow_statem(seed):
    rng = random.Random(seed)
    store = Store(n_actors=4)
    graph = Graph(store)

    sources, live, ever = [], {}, {}
    for i in range(3):
        vid = store.declare(id=f"src{i}", type="lasp_orset", n_elems=16,
                            tokens_per_actor=max(16, N_OPS))
        sources.append(vid)
        live[vid] = set()
        ever[vid] = set()

    def has_product(node):
        return node[0] == "product" or any(
            has_product(x) for x in node[1:] if isinstance(x, tuple)
        )

    nodes = {vid: ("src", vid) for vid in sources}
    ids = list(sources)
    for j in range(rng.randint(3, 6)):
        kind = rng.choice(
            ["map", "fold", "filter", "union", "intersection", "product",
             "bind_to"]
        )
        a = rng.choice(ids)
        if kind in ("map", "fold") and has_product(nodes[a]):
            # map/fold token spaces are S*T of their input; over a
            # product (whose token space is already Tl*Tr) the widths
            # multiply into OOM territory at soak op budgets — only
            # token-width-preserving edges consume products
            a = rng.choice(sources)
        if kind == "map":
            fn = rng.choice(["x7", "neg"])
            out = graph.map(a, FNS[fn], dst=f"d{j}", dst_elems=64)
            nodes[out] = ("map", fn, nodes[a])
        elif kind == "fold":
            out = graph.fold(a, FNS["dup"], dst=f"d{j}", dst_elems=64)
            nodes[out] = ("fold", "dup", nodes[a])
        elif kind == "filter":
            fn = rng.choice(["even", "small"])
            out = graph.filter(a, FNS[fn], dst=f"d{j}")
            nodes[out] = ("filter", fn, nodes[a])
        elif kind == "bind_to":
            out = graph.bind_to(f"d{j}", a)
            nodes[out] = ("bind_to", nodes[a])
        elif kind == "union":
            left = rng.choice(sources)  # see module docstring
            out = graph.union(left, a, dst=f"d{j}")
            nodes[out] = ("union", nodes[left], nodes[a])
        else:
            b = rng.choice(ids)
            if kind == "product":
                # products multiply token widths: sources only
                a, b = rng.choice(sources), rng.choice(sources)
            out = getattr(graph, kind)(a, b, dst=f"d{j}")
            nodes[out] = (kind, nodes[a], nodes[b])
        ids.append(out)

    oracle = Oracle()

    def check():
        graph.propagate()
        oracle.snapshot(live, ever)
        t = len(oracle.snaps) - 1
        for vid, node in nodes.items():
            assert store.value(vid) == oracle.live(node, t), (
                seed, vid, node,
            )

    for _step in range(N_OPS):
        src = rng.choice(sources)
        if live[src] and rng.random() < 0.3:
            e = rng.choice(sorted(live[src]))
            store.update(src, ("remove", e), "w")
            live[src].discard(e)
        else:
            e = rng.choice(DOMAIN)
            store.update(src, ("add", e), "w")
            live[src].add(e)
            ever[src].add(e)
        if rng.random() < 0.5:
            check()
    check()
