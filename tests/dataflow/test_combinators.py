"""Combinator tests mirroring the reference riak_tests (SURVEY.md §4):
``lasp_map_test`` / ``lasp_filter_test`` / ``lasp_fold_test`` /
``lasp_union_test`` / ``lasp_intersection_test`` / ``lasp_product_test``,
with ``timer:sleep`` waits replaced by ``Graph.propagate`` convergence, plus
causality-propagation cases (removals flowing through edges) that the
reference leaves to its EQC suite."""

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.store import Store

SET_TYPES = ["lasp_gset", "lasp_orset"]
REMOVABLE = ["lasp_orset"]


def make(type_name):
    store = Store(n_actors=4)
    graph = Graph(store)
    return store, graph


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_map_incremental(type_name):
    # riak_test/lasp_map_test.erl:56-87: {ok, [1..6], [2,4,..,12]}
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3]), "a")
    s2 = graph.map(s1, lambda x: x * 2)
    graph.propagate()
    assert store.value(s2) == frozenset({2, 4, 6})
    store.update(s1, ("add_all", [4, 5, 6]), "a")
    graph.propagate()
    assert store.value(s1) == frozenset({1, 2, 3, 4, 5, 6})
    assert store.value(s2) == frozenset({2, 4, 6, 8, 10, 12})


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_fold_flatmap(type_name):
    # riak_test/lasp_fold_test.erl:58-90 (flat-map; dense sets dedupe the
    # reference's list-duplication artifact, membership is what converges)
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3]), "a")
    s2 = graph.fold(s1, lambda x: [x, x + 10])
    graph.propagate()
    assert store.value(s2) == frozenset({1, 2, 3, 11, 12, 13})
    store.update(s1, ("add", 4), "a")
    graph.propagate()
    assert store.value(s2) == frozenset({1, 2, 3, 4, 11, 12, 13, 14})


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_filter(type_name):
    # riak_test/lasp_filter_test.erl
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3, 4, 5, 6]), "a")
    s2 = graph.filter(s1, lambda x: x % 2 == 0)
    graph.propagate()
    assert store.value(s2) == frozenset({2, 4, 6})


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_union(type_name):
    # riak_test/lasp_union_test.erl:59-83: [1,2,3] ∪ [a,b,c]
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    s2 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3]), "a")
    store.update(s2, ("add_all", ["a", "b", "c"]), "a")
    s3 = graph.union(s1, s2)
    graph.propagate()
    assert store.value(s3) == frozenset({1, 2, 3, "a", "b", "c"})


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_intersection(type_name):
    # riak_test/lasp_intersection_test.erl: [1,2,3] ∩ [3,4,5] = [3]
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    s2 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3]), "a")
    store.update(s2, ("add_all", [3, 4, 5]), "a")
    s3 = graph.intersection(s1, s2)
    graph.propagate()
    assert store.value(s3) == frozenset({3})
    # intersection keys off *membership order of arrival* too: element added
    # to the right side after the edge exists still joins
    store.update(s2, ("add", 1), "a")
    graph.propagate()
    assert store.value(s3) == frozenset({1, 3})


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_product(type_name):
    # riak_test/lasp_product_test.erl
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=4)
    s2 = store.declare(type=type_name, n_elems=4)
    store.update(s1, ("add_all", [1, 2]), "a")
    store.update(s2, ("add_all", ["x", "y"]), "a")
    s3 = graph.product(s1, s2)
    graph.propagate()
    assert store.value(s3) == frozenset(
        {(1, "x"), (1, "y"), (2, "x"), (2, "y")}
    )


@pytest.mark.parametrize("type_name", SET_TYPES)
def test_bind_to(type_name):
    # bind_to identity link (src/lasp_core.erl:434-446)
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2]), "a")
    s2 = graph.bind_to(None, s1)
    graph.propagate()
    assert store.value(s2) == frozenset({1, 2})
    store.update(s1, ("add", 3), "a")
    graph.propagate()
    assert store.value(s2) == frozenset({1, 2, 3})


# -- causality propagation (OR-set only) -----------------------------------


@pytest.mark.parametrize("type_name", REMOVABLE)
def test_map_remove_propagates(type_name):
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3]), "a")
    s2 = graph.map(s1, lambda x: x * 2)
    graph.propagate()
    assert store.value(s2) == frozenset({2, 4, 6})
    store.update(s1, ("remove", 2), "a")
    graph.propagate()
    assert store.value(s1) == frozenset({1, 3})
    assert store.value(s2) == frozenset({2, 6})


@pytest.mark.parametrize("type_name", REMOVABLE)
def test_map_collision_keeps_tokens_separate(type_name):
    # two sources mapping onto one image: removing one source must not kill
    # the image while the other survives — requires per-(source, token)
    # identity exactly like the reference's globally unique tokens
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [2, 3]), "a")
    s2 = graph.map(s1, lambda x: x // 2)  # both -> 1
    graph.propagate()
    assert store.value(s2) == frozenset({1})
    store.update(s1, ("remove", 2), "a")
    graph.propagate()
    assert store.value(s2) == frozenset({1})  # 3 still maps to 1
    store.update(s1, ("remove", 3), "a")
    graph.propagate()
    assert store.value(s2) == frozenset()


@pytest.mark.parametrize("type_name", REMOVABLE)
def test_filter_remove_propagates(type_name):
    store, graph = make(type_name)
    s1 = store.declare(type=type_name, n_elems=8)
    store.update(s1, ("add_all", [1, 2, 3, 4]), "a")
    s2 = graph.filter(s1, lambda x: x % 2 == 0)
    graph.propagate()
    assert store.value(s2) == frozenset({2, 4})
    store.update(s1, ("remove", 2), "a")
    graph.propagate()
    assert store.value(s2) == frozenset({4})


def test_union_left_bias():
    # orddict:merge(fun(_K, L, _R) -> L end, ...) — src/lasp_core.erl:616-621:
    # for an element present in both inputs, the contribution carries only
    # the left causality, so "tombstoned left + live right" stays invisible
    store, graph = make("lasp_orset")
    s1 = store.declare(type="lasp_orset", n_elems=8)
    s2 = store.declare(type="lasp_orset", n_elems=8)
    store.update(s1, ("add", "x"), "a")
    store.update(s1, ("remove", "x"), "a")  # x member-but-dead in left
    store.update(s2, ("add", "x"), "b")  # x live in right
    s3 = graph.union(s1, s2)
    graph.propagate()
    assert store.value(s3) == frozenset()


def test_intersection_causal_union():
    # element dead in left but member of both dicts: causal union keeps the
    # right side's live tokens, so the element IS in the intersection value
    # (src/lasp_core.erl:565-575 + lasp_lattice.erl:311-312)
    store, graph = make("lasp_orset")
    s1 = store.declare(type="lasp_orset", n_elems=8)
    s2 = store.declare(type="lasp_orset", n_elems=8)
    store.update(s1, ("add", "x"), "a")
    store.update(s1, ("remove", "x"), "a")
    store.update(s2, ("add", "x"), "b")
    s3 = graph.intersection(s1, s2)
    graph.propagate()
    assert store.value(s3) == frozenset({"x"})


def test_product_remove_propagates():
    # deleted = XDel orelse YDel (src/lasp_lattice.erl:303-309)
    store, graph = make("lasp_orset")
    s1 = store.declare(type="lasp_orset", n_elems=4)
    s2 = store.declare(type="lasp_orset", n_elems=4)
    store.update(s1, ("add_all", [1, 2]), "a")
    store.update(s2, ("add_all", ["x", "y"]), "a")
    s3 = graph.product(s1, s2)
    graph.propagate()
    store.update(s1, ("remove", 1), "a")
    graph.propagate()
    assert store.value(s3) == frozenset({(2, "x"), (2, "y")})


def test_pipeline_union_product_filter():
    # the advertisement-counter shape: union -> product -> filter
    # (riak_test/lasp_advertisement_counter_test.erl:107-143)
    store, graph = make("lasp_orset")
    ads_a = store.declare(type="lasp_orset", n_elems=4)
    ads_b = store.declare(type="lasp_orset", n_elems=4)
    clients = store.declare(type="lasp_orset", n_elems=4)
    store.update(ads_a, ("add_all", ["a1", "a2"]), "pub_a")
    store.update(ads_b, ("add", "b1"), "pub_b")
    store.update(clients, ("add_all", ["c1", "c2"]), "srv")
    ads = graph.union(ads_a, ads_b)
    pairs = graph.product(ads, clients)
    only_c1 = graph.filter(pairs, lambda xy: xy[1] == "c1")
    rounds = graph.propagate()
    assert rounds <= 4
    assert store.value(only_c1) == frozenset(
        {("a1", "c1"), ("a2", "c1"), ("b1", "c1")}
    )
    # disable ad a1 (remove from its publisher set) -> drains through all 3
    store.update(ads_a, ("remove", "a1"), "pub_a")
    graph.propagate()
    assert store.value(only_c1) == frozenset({("a2", "c1"), ("b1", "c1")})


def test_propagate_wakes_threshold_watch():
    store, graph = make("lasp_orset")
    s1 = store.declare(type="lasp_orset", n_elems=8)
    s2 = graph.map(s1, lambda x: x + 1)
    from lasp_tpu.lattice import ORSet, Threshold

    store.update(s1, ("add", 1), "a")
    graph.propagate()
    # watch for any strict growth of the (already non-empty) output
    watch = store.read(s2, Threshold(store.state(s2), strict=True))
    assert not watch.done
    store.update(s1, ("add", 2), "a")
    assert not watch.done  # nothing propagated yet
    graph.propagate()
    assert watch.done


def test_ivar_bind_to():
    store, graph = make("lasp_ivar")
    a = store.declare(type="lasp_ivar")
    b = graph.bind_to(None, a)
    store.update(a, ("set", "hello"), "actor")
    graph.propagate()
    assert store.value(b) == "hello"


def test_union_diamond_frozen_copy():
    """Documented reference delta (edges.py PairwiseEdge): a token
    reaching a union through BOTH inputs (diamond lineage) occupies two
    concat-axis columns. When the element enters the derived LEFT a
    round after the right absorbed it, a later removal kills only the
    left-path copy — the frozen right-path copy stays live, where the
    reference's global token ids would collapse the two and remove the
    element. This test pins the engine's actual behavior so any future
    change to it is a conscious one."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.store import Store

    store = Store(n_actors=4)
    src = store.declare(id="s", type="lasp_orset", n_elems=8,
                        tokens_per_actor=8)
    graph = Graph(store)
    d0 = graph.union(src, src, dst="d0")       # derived mirror of src
    d1 = graph.union(d0, src, dst="d1")        # diamond: src via both
    store.update(src, ("add", "x"), "w")
    graph.propagate()
    # round 1 of that propagate saw d0 left-absent for "x", so d1
    # absorbed src's right-side copy
    assert store.value(d1) == frozenset({"x"})
    store.update(src, ("remove", "x"), "w")
    graph.propagate()
    assert store.value(src) == frozenset()
    assert store.value(d0) == frozenset()      # left path saw the remove
    # the engine's frozen right-path copy survives (the reference would
    # return frozenset() here — global token ids collapse the diamond)
    assert store.value(d1) == frozenset({"x"})
