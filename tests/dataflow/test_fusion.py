"""Whole-graph dataflow fusion (``dataflow.plan``): the propagate
megakernel must be bit-identical to the per-edge path — same values,
same round counts — across codecs, graph shapes, and interleavings, and
every non-stackable corner must fall back LOUDLY (counter + warning),
never silently wrong. The shared FIFO propagate-executable cache and
the fused window's causal-log summary are pinned here too."""

import warnings

import jax
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.dataflow import plan as dplan
from lasp_tpu.store import Store
from lasp_tpu.telemetry import get_registry


def _counter_value(name, **labels):
    fam = get_registry().snapshot().get(name)
    if not fam:
        return 0
    return sum(
        s["value"] for s in fam["series"]
        if all(s["labels"].get(k) == v for k, v in labels.items())
    )


def _states_equal(store_a, store_b) -> bool:
    for v in store_a.ids():
        a = jax.tree_util.tree_leaves(store_a.state(v))
        b = jax.tree_util.tree_leaves(store_b.state(v))
        if not all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(a, b)
        ):
            return False
    return True


def _mixed_graph():
    """Every edge kind x every dataflow codec family: parallel orswot
    bind_to chains (vclock codec), stacked G-Set map chains feeding a
    union, an OR-Set filter feeding a product — the shape the fused
    compiler levels, groups, and stacks."""
    store = Store(n_actors=2)
    g = Graph(store)
    for c in range(2):
        store.declare(
            id=f"o{c}_0", type="riak_dt_orswot", n_elems=4, n_actors=2
        )
        for d in range(3):
            g.bind_to(f"o{c}_{d + 1}", f"o{c}_{d}")
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    b = store.declare(id="b", type="lasp_gset", n_elems=8)
    m1 = g.map(a, lambda x: x * 10, dst="m1", dst_elems=8)
    m2 = g.map(b, lambda x: x * 10, dst="m2", dst_elems=8)
    g.union(m1, m2, dst="u")
    s = store.declare(
        id="s", type="lasp_orset", n_elems=4, n_actors=2, tokens_per_actor=8
    )
    f = g.filter(s, lambda t: True, dst="f")
    g.product(f, s, dst="p")
    return store, g


def _drive(store, g, mode) -> list:
    """A write/propagate interleaving touching every chain, with a
    removal mid-stream (vclock dots moving under an equal-clock-blind
    residual is exactly what ``~codec.equal`` change flags must see)."""
    rounds = []
    for c in range(2):
        store.update(f"o{c}_0", ("add", f"e{c}"), "w")
    store.update("a", ("add", 1), "w")
    store.update("s", ("add", "z"), "w")
    rounds.append(g.propagate(mode=mode))
    store.update("o0_0", ("remove", "e0"), "w")
    store.update("b", ("add", 2), "w")
    rounds.append(g.propagate(mode=mode))
    store.update("a", ("add", 3), "w")
    rounds.append(g.propagate(mode=mode))
    return rounds


def test_fused_bit_identical_to_per_edge_mixed_codecs():
    s1, g1 = _mixed_graph()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any fallback is a test failure
        fused_rounds = _drive(s1, g1, "fused")
    s2, g2 = _mixed_graph()
    per_edge_rounds = _drive(s2, g2, "per_edge")
    assert fused_rounds == per_edge_rounds
    assert _states_equal(s1, s2)
    assert s1.value("u") == {10, 20, 30}
    assert s1.value("o0_3") == set()  # the removal reached the chain tail
    assert s1.value("o1_3") == {"e1"}


def test_auto_mode_is_fused_and_default():
    store, g = _mixed_graph()
    assert g.fusion == "auto"
    store.update("a", ("add", 1), "w")
    g.propagate()  # default mode
    from lasp_tpu.telemetry import events as tel_events

    rec = [e for e in tel_events.events() if e["etype"] == "propagate"][-1]
    assert rec["attrs"]["fused"] is True


def test_unknown_mode_rejected():
    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")
    with pytest.raises(ValueError, match="unknown propagate mode"):
        g.propagate(mode="bogus")


# -- compiler internals ------------------------------------------------------

def test_closure_edges_forward_closure_and_never_ran():
    store = Store(n_actors=2)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=4)
    b = g.map(a, lambda x: x, dst="b", dst_elems=4)
    g.map(b, lambda x: x, dst="c", dst_elems=4)
    x = store.declare(id="x", type="lasp_gset", n_elems=4)
    g.map(x, lambda x: x, dst="y", dst_elems=4)
    # never-ran edges are always in the closure
    assert dplan.closure_edges(g.edges, [False] * 3, set()) == (0, 1, 2)
    # a dirty source pulls its whole downstream chain, not the x->y edge
    assert dplan.closure_edges(g.edges, [True] * 3, {"a"}) == (0, 1)
    assert dplan.closure_edges(g.edges, [True] * 3, {"b"}) == (1,)
    assert dplan.closure_edges(g.edges, [True] * 3, {"x"}) == (2,)
    assert dplan.closure_edges(g.edges, [True] * 3, set()) == ()


def test_level_groups_stack_same_signature_per_level():
    store = Store(n_actors=2)
    g = Graph(store)
    for i in range(3):
        v = store.declare(id=f"v{i}", type="lasp_gset", n_elems=4)
        m = g.map(v, lambda x: x, dst=f"m{i}", dst_elems=4)
        g.map(m, lambda x: x, dst=f"t{i}", dst_elems=4)
    idx = tuple(range(6))
    groups = dplan.level_groups(g.edges, idx)
    # 2 levels x 3 same-signature map edges each -> 2 stacked groups
    assert sorted(sorted(grp) for grp in groups) == [[0, 2, 4], [1, 3, 5]]


def test_pre_poisoned_edge_stays_singleton():
    store = Store(n_actors=2)
    g = Graph(store)
    for i in range(2):
        v = store.declare(id=f"v{i}", type="lasp_gset", n_elems=4)
        g.map(v, lambda x: x, dst=f"m{i}", dst_elems=4)
    g.edges[0].stackable = False  # the operator pre-poison hook
    groups = dplan.level_groups(g.edges, (0, 1))
    assert sorted(sorted(grp) for grp in groups) == [[0], [1]]
    # and the fused propagate still lands the right values
    store.update("v0", ("add", 1), "w")
    store.update("v1", ("add", 2), "w")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        g.propagate(mode="fused")
    assert store.value("m0") == {1} and store.value("m1") == {2}


def test_guard_demotes_unstackable_group_loudly():
    """A group whose stacked trace fails is demoted to per-edge
    singletons with a RuntimeWarning + fallback counter, and its
    members are poisoned non-stackable for later compiles."""
    store = Store(n_actors=2)
    g = Graph(store)
    for i in range(2):
        v = store.declare(id=f"v{i}", type="lasp_gset", n_elems=4)
        g.map(v, lambda x: x, dst=f"m{i}", dst_elems=4)
    g.refresh()
    states = {v: store.state(v) for v in store.ids()}
    tables = tuple(e.device_tables() for e in g.edges)
    groups = dplan.level_groups(g.edges, (0, 1))
    assert any(len(grp) == 2 for grp in groups)

    def broken(tables, src):
        raise ValueError("cannot batch this")

    g.edges[0].contribution = broken
    before = _counter_value("dataflow_plan_fallbacks_total", reason="stack")
    with pytest.warns(RuntimeWarning, match="cannot stack"):
        out = dplan.guard_groups(g.edges, groups, states, tables)
    assert sorted(sorted(grp) for grp in out) == [[0], [1]]
    assert not g.edges[0].stackable and not g.edges[1].stackable
    assert (
        _counter_value("dataflow_plan_fallbacks_total", reason="stack")
        == before + 1
    )


def test_dispatch_failure_falls_back_loudly_then_poisons(monkeypatch):
    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")
    g.propagate(mode="per_edge")  # every edge has run once

    def boom(*_a, **_k):
        raise RuntimeError("trace exploded")

    monkeypatch.setattr(dplan, "compile_fused", boom)
    before = _counter_value(
        "dataflow_plan_fallbacks_total", reason="dispatch"
    )
    store.update("a", ("add", 2), "w")
    with pytest.warns(RuntimeWarning, match="fell back to the per-edge"):
        g.propagate(mode="auto")
    assert store.value("m1") == {10, 20}  # the fallback still converged
    assert (
        _counter_value("dataflow_plan_fallbacks_total", reason="dispatch")
        == before + 1
    )
    # the same dirty pattern is poisoned now: straight per-edge, no
    # second warning even with compile_fused still broken
    store.update("a", ("add", 3), "w")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        g.propagate(mode="auto")
    assert store.value("m1") == {10, 20, 30}


def test_strict_fused_mode_raises_instead_of_falling_back(monkeypatch):
    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")

    def boom(*_a, **_k):
        raise RuntimeError("trace exploded")

    monkeypatch.setattr(dplan, "compile_fused", boom)
    with pytest.raises(RuntimeError, match="trace exploded"):
        g.propagate(mode="fused")
    # the pattern is poisoned: strict mode refuses the fallback outright
    with pytest.raises(RuntimeError, match="refuses the fallback"):
        g.propagate(mode="fused")


# -- the shared executable cache ---------------------------------------------

def test_propagate_cache_fifo_bound_and_kinds():
    cache = dplan.PropagateCache(capacity=2)
    cache.put(("subset", (0,)), "s0")
    cache.put(("fused", (0, 1), 3), "f0")
    assert len(cache) == 2
    cache.put(("subset", (1,)), "s1")  # evicts the oldest (FIFO)
    assert len(cache) == 2
    assert cache.get(("subset", (0,))) is None
    assert cache.get(("fused", (0, 1), 3)) == "f0"
    assert cache.get(("subset", (1,))) == "s1"


def test_fused_and_subset_executables_share_one_cache():
    """The PR 3 eligible-subset round fns and the megakernels live in
    ONE keyed FIFO cache — one bound, one hit/built ledger."""
    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")
    g.propagate(mode="fused")
    store.update("a", ("add", 2), "w")
    g.propagate(mode="per_edge")
    kinds = {k[0] for k in g._cache._entries}
    assert kinds == {"fused", "subset"}
    store.update("a", ("add", 3), "w")
    g.propagate(mode="fused")  # builds the {a}-dirty megakernel
    hits0 = _counter_value("dataflow_plan_cache_hits_total", kind="fused")
    store.update("a", ("add", 4), "w")
    g.propagate(mode="fused")  # same dirty pattern: a warm cache hit
    assert (
        _counter_value("dataflow_plan_cache_hits_total", kind="fused")
        > hits0
    )
    built = _counter_value("dataflow_plan_cache_built_total", kind="fused")
    assert built >= 1


def test_graph_mutation_invalidates_cache():
    store = Store(n_actors=2)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=4)
    g.map(a, lambda x: x, dst="b", dst_elems=4)
    store.update(a, ("add", 1), "w")
    g.propagate(mode="fused")
    assert len(g._cache) >= 1
    # adding an edge re-means edge indices: _build resets the cache
    g.map("b", lambda x: x, dst="c", dst_elems=4)
    store.update(a, ("add", 2), "w")
    g.propagate(mode="fused")
    assert store.value("c") == {1, 2}


# -- telemetry: the fused window's causal-log summary ------------------------

def test_propagate_event_carries_per_dst_changed_counts():
    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")
    g.propagate(mode="fused")
    from lasp_tpu.telemetry import events as tel_events

    rec = [e for e in tel_events.events() if e["etype"] == "propagate"][-1]
    attrs = rec["attrs"]
    assert attrs["fused"] is True and attrs["rounds"] >= 1
    by_dst = attrs["changed_by_dst"]
    # the a->m1->u chain moved; counts are per-dst changed sweeps
    assert by_dst["m1"] >= 1 and by_dst["u"] >= 1
    assert set(by_dst) == {e.dst for e in g.edges}


def test_causal_history_includes_fused_propagate_summary():
    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")
    g.propagate(mode="fused")
    from lasp_tpu.telemetry.events import causal_history

    hist = causal_history("u", lineage=g.lineage("u"))
    assert any(r["etype"] == "propagate" for r in hist), (
        "fused windows must not vanish from `lasp_tpu trace --var` lineage"
    )


def test_fused_ledger_family_records():
    from lasp_tpu.telemetry import get_ledger

    store, g = _mixed_graph()
    store.update("a", ("add", 1), "w")
    before = {
        e["kernel"]: e["dispatches"] + e["compile_dispatches"]
        for e in get_ledger().snapshot()
    }
    g.propagate(mode="fused")
    ent = [
        e for e in get_ledger().snapshot()
        if e["family"] == "dataflow_fused"
        and e["dispatches"] + e["compile_dispatches"]
        > before.get(e["kernel"], 0)
    ]
    assert ent, "fused propagate did not feed the kernel ledger"
    assert ent[0]["bytes"] > 0 and ent[0]["rounds"] >= 1


# -- parity corners ----------------------------------------------------------

def test_non_convergence_raises_in_both_modes():
    for mode in ("fused", "per_edge"):
        store = Store(n_actors=2)
        g = Graph(store)
        a = store.declare(id="a", type="lasp_gset", n_elems=4)
        b = g.map(a, lambda x: x, dst="b", dst_elems=4)
        g.map(b, lambda x: x, dst="c", dst_elems=4)
        store.update(a, ("add", 1), "w")
        with pytest.raises(RuntimeError, match="did not converge"):
            g.propagate(max_rounds=1, mode=mode)
        # the budget raise leaves the graph retryable
        assert g.propagate(mode=mode) >= 1
        assert store.value("c") == {1}
        if mode == "fused":
            # the round budget is a traced operand, NOT part of the
            # cache key: the budgeted and default propagates share one
            # megakernel instead of churning the FIFO bound
            fused_keys = [k for k in g._cache._entries if k[0] == "fused"]
            assert len(fused_keys) == 1, fused_keys


def test_empty_frontier_is_zero_rounds_both_modes():
    for mode in ("fused", "per_edge"):
        store = Store(n_actors=2)
        g = Graph(store)
        a = store.declare(id="a", type="lasp_gset", n_elems=4)
        g.map(a, lambda x: x, dst="b", dst_elems=4)
        store.update(a, ("add", 1), "w")
        g.propagate(mode=mode)
        assert g.propagate(mode=mode) == 0


def test_fused_interner_growth_retraces_cleanly():
    """Interner growth between propagates changes table CONTENTS (shapes
    are spec-pinned): the cached megakernel must absorb the new tables
    as traced operands, not bake stale projections in."""
    store = Store(n_actors=2)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    g.map(a, lambda x: x * 10, dst="b", dst_elems=8)
    store.update(a, ("add", 1), "w")
    g.propagate(mode="fused")
    assert store.value("b") == {10}
    store.update(a, ("add", 2), "w")  # new term -> table refresh
    g.propagate(mode="fused")
    assert store.value("b") == {10, 20}
