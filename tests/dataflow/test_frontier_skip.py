"""Edge-level frontier scheduling in Graph.propagate: edges whose source
set is clean are skipped, and skipping never changes values, rounds, or
the fixed point (the idempotent-join argument, checked empirically)."""

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.store import Store
from lasp_tpu.telemetry import get_registry


def _skip_count():
    fam = get_registry().snapshot().get("dataflow_edges_skipped_total")
    if not fam:
        return 0
    return sum(s["value"] for s in fam["series"])


def _build():
    store = Store(n_actors=4)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    b = g.map(a, lambda x: x * 10, dst="b", dst_elems=8)
    c = g.map(b, lambda x: x + 1, dst="c", dst_elems=8)
    x = store.declare(id="x", type="lasp_gset", n_elems=8)
    y = g.map(x, lambda t: -t, dst="y", dst_elems=8)
    return store, g, (a, b, c, x, y)


def test_untouched_chain_is_skipped():
    store, g, (a, b, c, x, y) = _build()
    store.update(a, ("add", 1), "w")
    store.update(x, ("add", 5), "w")
    g.propagate()  # first run: every edge owes its initial evaluation
    assert store.value(c) == {11}
    assert store.value(y) == {-5}

    # a write into ONLY the a->b->c chain: the x->y edge must be skipped
    before = _skip_count()
    store.update(a, ("add", 2), "w")
    rounds = g.propagate()
    assert rounds >= 1
    assert store.value(c) == {11, 21}
    assert store.value(y) == {-5}  # untouched chain unchanged
    assert _skip_count() > before


def test_skipping_matches_full_recompute_values():
    """The same write/propagate interleaving against a FRESH graph (whose
    first propagate recomputes everything) lands identical values —
    skipping is unobservable except in work counters."""
    store, g, ids = _build()
    a, b, c, x, y = ids
    store.update(a, ("add", 1), "w")
    g.propagate()
    store.update(x, ("add", 3), "w")
    g.propagate()
    store.update(a, ("add", 2), "w")
    g.propagate()

    ref_store, ref_g, ref_ids = _build()
    ra, _rb, rc, rx, ry = ref_ids
    ref_store.update(ra, ("add", 1), "w")
    ref_store.update(rx, ("add", 3), "w")
    ref_store.update(ra, ("add", 2), "w")
    ref_g.propagate()
    for v, rv in ((c, rc), (y, ry), (b, _rb)):
        assert store.value(v) == ref_store.value(rv)


def test_clean_propagate_is_free():
    store, g, (a, *_rest) = _build()
    store.update(a, ("add", 1), "w")
    g.propagate()
    # nothing written since: zero rounds, zero sweeps (the _clean_mark
    # fast path), and the dirty cursor holds
    assert g.propagate() == 0


def test_watch_write_during_ingest_stays_dirty():
    """A threshold watch writing mid-ingest must keep the graph dirty so
    the next propagate folds the callback's write in — the frontier
    cursor must not swallow it."""
    store = Store(n_actors=4)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    b = g.map(a, lambda x: x, dst="b", dst_elems=8)
    other = store.declare(id="o", type="lasp_gset", n_elems=8)
    g.map(other, lambda x: x, dst="o2", dst_elems=8)

    fired = []

    def cb(result):
        fired.append(result)
        store.update(other, ("add", 7), "w")

    from lasp_tpu.lattice import Threshold

    var_b = store.variable(b)
    # parked strict-above-bottom watch: fires on b's FIRST inflation,
    # which happens inside propagate's ingest
    w = store.read(b, Threshold(var_b.codec.new(var_b.spec), strict=True))
    assert not w.done
    w.callback = cb
    store.update(a, ("add", 1), "w")
    g.propagate()
    assert fired  # the watch fired mid-ingest
    g.propagate()  # folds the callback's write into o2
    assert store.value("o2") == store.value(other) == {7}


def test_dirty_cursor_is_per_graph():
    """Two graphs over one store must not steal each other's marks."""
    store = Store(n_actors=4)
    g1 = Graph(store)
    g2 = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    g1.map(a, lambda x: x, dst="d1", dst_elems=8)
    g2.map(a, lambda x: x, dst="d2", dst_elems=8)
    store.update(a, ("add", 1), "w")
    g1.propagate()  # consumes ITS view of the marks
    g2.propagate()  # must still see the write
    assert store.value("d1") == {1}
    assert store.value("d2") == {1}


def _two_graphs():
    """Two graphs sharing one store, each with a private chain off the
    shared source plus a private source — the multi-graph cursor shape
    under fused propagate (ISSUE 8 satellite)."""
    store = Store(n_actors=4)
    g1, g2 = Graph(store), Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    m1 = g1.map(a, lambda x: x * 10, dst="g1_m", dst_elems=8)
    g1.map(m1, lambda x: x + 1, dst="g1_t", dst_elems=8)
    p1 = store.declare(id="p1", type="lasp_gset", n_elems=8)
    g1.map(p1, lambda x: -x, dst="g1_p", dst_elems=8)
    m2 = g2.map(a, lambda x: x * 100, dst="g2_m", dst_elems=8)
    g2.map(m2, lambda x: x + 2, dst="g2_t", dst_elems=8)
    return store, g1, g2, a, p1


def test_multigraph_cursors_interleaved_fused_and_per_edge():
    """Interleaved fused/per-edge sweeps over a shared store: each
    graph's cursor consumes exactly ITS unseen writes — never skipping
    one (a write landing between the two graphs' propagates), never
    double-consuming (a re-propagate after the other graph swept)."""
    store, g1, g2, a, p1 = _two_graphs()
    store.update(a, ("add", 1), "w")
    assert g1.propagate(mode="fused") >= 1
    # a write BETWEEN the graphs' sweeps: g2 still owes both
    store.update(a, ("add", 2), "w")
    assert g2.propagate(mode="per_edge") >= 1
    assert store.value("g2_t") == {102, 202}
    # g1 saw only the first write so far; the fused sweep must fold the
    # second in (its cursor held at the pre-write mark)
    assert store.value("g1_t") == {11}
    assert g1.propagate(mode="fused") >= 1
    assert store.value("g1_t") == {11, 21}
    # no double-consume: both graphs are clean now (0 rounds, no work)
    assert g1.propagate(mode="fused") == 0
    assert g2.propagate(mode="per_edge") == 0
    # a write into g1's PRIVATE chain: g2's propagate stays clean and
    # must not advance g1's view past the unseen write
    store.update(p1, ("add", 5), "w")
    assert g2.propagate(mode="fused") == 0
    assert g1.propagate(mode="fused") >= 1
    assert store.value("g1_p") == {-5}


def test_multigraph_fused_matches_per_edge_after_interleaving():
    """The same interleaved schedule driven all-fused vs all-per-edge
    lands identical values on every variable of both graphs."""
    import jax
    import numpy as np

    def run(mode):
        store, g1, g2, a, p1 = _two_graphs()
        store.update(a, ("add", 1), "w")
        r = [g1.propagate(mode=mode)]
        store.update(a, ("add", 3), "w")
        store.update(p1, ("add", 7), "w")
        r.append(g2.propagate(mode=mode))
        r.append(g1.propagate(mode=mode))
        store.update(a, ("add", 4), "w")
        r.append(g2.propagate(mode=mode))
        r.append(g1.propagate(mode=mode))
        return store, r

    s_f, r_f = run("fused")
    s_p, r_p = run("per_edge")
    assert r_f == r_p
    for v in s_f.ids():
        fa = jax.tree_util.tree_leaves(s_f.state(v))
        pa = jax.tree_util.tree_leaves(s_p.state(v))
        assert all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(fa, pa)
        ), v
