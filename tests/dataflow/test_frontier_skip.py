"""Edge-level frontier scheduling in Graph.propagate: edges whose source
set is clean are skipped, and skipping never changes values, rounds, or
the fixed point (the idempotent-join argument, checked empirically)."""

import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.store import Store
from lasp_tpu.telemetry import get_registry


def _skip_count():
    fam = get_registry().snapshot().get("dataflow_edges_skipped_total")
    if not fam:
        return 0
    return sum(s["value"] for s in fam["series"])


def _build():
    store = Store(n_actors=4)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    b = g.map(a, lambda x: x * 10, dst="b", dst_elems=8)
    c = g.map(b, lambda x: x + 1, dst="c", dst_elems=8)
    x = store.declare(id="x", type="lasp_gset", n_elems=8)
    y = g.map(x, lambda t: -t, dst="y", dst_elems=8)
    return store, g, (a, b, c, x, y)


def test_untouched_chain_is_skipped():
    store, g, (a, b, c, x, y) = _build()
    store.update(a, ("add", 1), "w")
    store.update(x, ("add", 5), "w")
    g.propagate()  # first run: every edge owes its initial evaluation
    assert store.value(c) == {11}
    assert store.value(y) == {-5}

    # a write into ONLY the a->b->c chain: the x->y edge must be skipped
    before = _skip_count()
    store.update(a, ("add", 2), "w")
    rounds = g.propagate()
    assert rounds >= 1
    assert store.value(c) == {11, 21}
    assert store.value(y) == {-5}  # untouched chain unchanged
    assert _skip_count() > before


def test_skipping_matches_full_recompute_values():
    """The same write/propagate interleaving against a FRESH graph (whose
    first propagate recomputes everything) lands identical values —
    skipping is unobservable except in work counters."""
    store, g, ids = _build()
    a, b, c, x, y = ids
    store.update(a, ("add", 1), "w")
    g.propagate()
    store.update(x, ("add", 3), "w")
    g.propagate()
    store.update(a, ("add", 2), "w")
    g.propagate()

    ref_store, ref_g, ref_ids = _build()
    ra, _rb, rc, rx, ry = ref_ids
    ref_store.update(ra, ("add", 1), "w")
    ref_store.update(rx, ("add", 3), "w")
    ref_store.update(ra, ("add", 2), "w")
    ref_g.propagate()
    for v, rv in ((c, rc), (y, ry), (b, _rb)):
        assert store.value(v) == ref_store.value(rv)


def test_clean_propagate_is_free():
    store, g, (a, *_rest) = _build()
    store.update(a, ("add", 1), "w")
    g.propagate()
    # nothing written since: zero rounds, zero sweeps (the _clean_mark
    # fast path), and the dirty cursor holds
    assert g.propagate() == 0


def test_watch_write_during_ingest_stays_dirty():
    """A threshold watch writing mid-ingest must keep the graph dirty so
    the next propagate folds the callback's write in — the frontier
    cursor must not swallow it."""
    store = Store(n_actors=4)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    b = g.map(a, lambda x: x, dst="b", dst_elems=8)
    other = store.declare(id="o", type="lasp_gset", n_elems=8)
    g.map(other, lambda x: x, dst="o2", dst_elems=8)

    fired = []

    def cb(result):
        fired.append(result)
        store.update(other, ("add", 7), "w")

    from lasp_tpu.lattice import Threshold

    var_b = store.variable(b)
    # parked strict-above-bottom watch: fires on b's FIRST inflation,
    # which happens inside propagate's ingest
    w = store.read(b, Threshold(var_b.codec.new(var_b.spec), strict=True))
    assert not w.done
    w.callback = cb
    store.update(a, ("add", 1), "w")
    g.propagate()
    assert fired  # the watch fired mid-ingest
    g.propagate()  # folds the callback's write into o2
    assert store.value("o2") == store.value(other) == {7}


def test_dirty_cursor_is_per_graph():
    """Two graphs over one store must not steal each other's marks."""
    store = Store(n_actors=4)
    g1 = Graph(store)
    g2 = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    g1.map(a, lambda x: x, dst="d1", dst_elems=8)
    g2.map(a, lambda x: x, dst="d2", dst_elems=8)
    store.update(a, ("add", 1), "w")
    g1.propagate()  # consumes ITS view of the marks
    g2.propagate()  # must still see the write
    assert store.value("d1") == {1}
    assert store.value("d2") == {1}
