"""Telemetry under elasticity (satellite of the convergence-observatory
PR): registry snapshots, per-shard lag gauges, and the causal event log
must stay consistent across ``ReplicatedRuntime.resize`` (graceful and
crash leave), checkpoint restore onto a different population, and
test-time registry resets — no stale-generation instruments, no
dropped or duplicated membership events."""

import pytest

from lasp_tpu import telemetry
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store
from lasp_tpu.telemetry import events as E
from lasp_tpu.telemetry import registry as R
from lasp_tpu.telemetry.convergence import get_monitor


def _runtime(n=8):
    store = Store(n_actors=32)
    store.declare(id="a", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    rt.update_at(0, "a", ("add", "x"), "w0")
    return rt


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    E.clear()
    yield
    telemetry.reset()
    E.clear()


def _membership_events():
    return [
        (e["attrs"]["kind"], e["attrs"]["old_n"], e["attrs"]["new_n"])
        for e in E.events(etype="membership")
    ]


def test_resize_emits_exactly_one_membership_event_each():
    rt = _runtime(8)
    rt.resize(12, ring(12, 2))                      # join
    rt.resize(6, ring(6, 2), graceful=True)         # graceful leave
    rt.resize(4, ring(4, 2), graceful=False)        # crash leave
    rt.resize(4, ring(4, 2))                        # topology swap
    assert _membership_events() == [
        ("join", 8, 12),
        ("leave_graceful", 12, 6),
        ("leave_crash", 6, 4),
        ("topology_swap", 4, 4),
    ]
    # the monitor saw the same sequence (one record each, same order)
    kinds = [k for _r, k, _o, _n in get_monitor().snapshot()["memberships"]]
    assert kinds == ["join", "leave_graceful", "leave_crash",
                     "topology_swap"]
    assert get_monitor().snapshot()["n_replicas"] == 4


def test_residual_gauges_consistent_across_resize():
    rt = _runtime(8)
    rt.step()
    snap = R.get_registry().snapshot()
    assert {s["labels"]["var"] for s in snap["gossip_residual"]["series"]} \
        == {"a"}
    rt.resize(16, ring(16, 2))
    rt.update_at(9, "a", ("add", "y"), "w9")
    rounds = rt.run_to_convergence(max_rounds=32)
    assert rounds >= 1
    snap = R.get_registry().snapshot()
    # same gauge family keeps reporting after the membership change,
    # and the final round left residual 0
    series = {
        s["labels"]["var"]: s["value"]
        for s in snap["gossip_residual"]["series"]
    }
    assert series == {"a": 0}
    # the convergence view agrees with the resized population
    assert get_monitor().snapshot()["n_replicas"] == 16
    assert rt.coverage_value("a") == {"x", "y"}


def test_shard_lag_gauges_follow_the_new_population():
    rt = _runtime(8)
    mon = get_monitor()
    probe = mon.probe(rt, n_shards=4)
    assert len(probe["shard_lag"]) == 4
    rt.resize(6, ring(6, 2), graceful=True)
    # a resize invalidates the old probe (row-block meaning changed)
    assert mon.snapshot()["probe"] is None
    probe = mon.probe(rt, n_shards=3)
    assert len(probe["shard_lag"]) == 3
    snap = R.get_registry().snapshot()
    shards = {
        s["labels"]["shard"] for s in snap["convergence_shard_lag"]["series"]
    }
    # gauge families accumulate label sets (Prometheus semantics); the
    # fresh shard ids must all be present and correct
    assert {"0", "1", "2"} <= shards


def test_crash_leave_lag_accounting():
    rt = _runtime(8)
    # seed a second write at a row that will crash away un-gossiped
    rt.update_at(7, "a", ("add", "doomed"), "w7")
    rt.resize(4, ring(4, 2), graceful=False)
    probe = get_monitor().probe(rt, n_shards=2)
    # survivors only know x at row 0: 3 rows behind on one var
    assert probe["lag_by_var"] == {"a": 3}
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value("a") == {"x"}  # doomed was lost with its row
    assert get_monitor().probe(rt, n_shards=2)["worst_replica_lag"] == 0


def test_checkpoint_restore_membership_events(tmp_path):
    from lasp_tpu.store.checkpoint import load_runtime, save_runtime

    rt = _runtime(8)
    rt.run_to_convergence(max_rounds=16)
    path = str(tmp_path / "m.lasp")
    save_runtime(rt, path)
    E.clear()
    bigger = load_runtime(path, n_replicas=12, neighbors=ring(12, 2))
    # the elastic restore resizes 8 -> 12: exactly ONE membership event
    assert _membership_events() == [("join", 8, 12)]
    bigger.run_to_convergence(max_rounds=32)
    assert bigger.replica_value("a", 11) == {"x"}
    # same-size restore performs no resize and emits nothing
    E.clear()
    same = load_runtime(path)
    assert _membership_events() == []
    assert same.n_replicas == 8


def test_no_stale_generation_instruments_after_reset():
    rt = _runtime(4)
    rt.step()
    before = R.get_registry().snapshot()
    assert before["gossip_rounds_total"]["series"][0]["value"] >= 1
    telemetry.reset()  # test-time reset: generation bump
    rt.step()  # cached instruments must re-fetch, not increment a ghost
    after = R.get_registry().snapshot()
    assert after["gossip_rounds_total"]["series"][0]["value"] == 1
    # the monitor restarted its round clock with the new generation
    assert get_monitor().snapshot()["round"] == 1
    # and the event round clock follows the monitor, not the old epoch
    assert E.events(etype="delivery")[-1]["round"] == 1
