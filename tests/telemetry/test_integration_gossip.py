"""Integration: a 2-replica gossip run (the CLI's built-in workload)
emits nonzero gossip_rounds_total, per-CRDT-type merge timings,
dataflow edge recomputes, and bridge verb latencies — the acceptance
surface of the telemetry subsystem."""

import json

import pytest

from lasp_tpu import cli, telemetry


@pytest.fixture()
def fresh_registry():
    # the registry is process-global; isolate this test's assertions
    # from whatever other tests emitted before it
    telemetry.reset()
    telemetry.clear_spans()
    yield telemetry.get_registry()


def _value(snap, name, **labels):
    fam = snap.get(name)
    assert fam is not None, f"metric {name} missing from snapshot"
    for s in fam["series"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s
    raise AssertionError(f"{name} has no series matching {labels}: {fam}")


def test_two_replica_workload_covers_all_layers(fresh_registry, capsys):
    assert cli.main(["metrics", "--jsonl"]) == 0
    out = capsys.readouterr().out
    snap = fresh_registry.snapshot()

    # gossip rounds ran and were counted
    assert _value(snap, "gossip_rounds_total")["value"] > 0
    assert _value(snap, "gossip_bytes_exchanged_total")["value"] > 0
    # the run converged: every per-var residual gauge ended at 0
    for s in snap["gossip_residual"]["series"]:
        assert s["value"] == 0

    # per-CRDT-type merge timings (the workload writes through orset,
    # gcounter and orswot rows)
    for tn in ("lasp_orset", "riak_dt_gcounter", "riak_dt_orswot"):
        series = _value(snap, "merge_seconds", type=tn)
        assert series["count"] > 0
        assert series["sum"] >= 0

    # dataflow: the map edge re-evaluated once per engine round
    rec = _value(snap, "dataflow_edge_recomputes_total", kind="map")
    assert rec["value"] > 0

    # bridge verb latencies from the loopback exchange (the client's
    # update ships idem-wrapped — the write-retry dedup path — so the
    # frame counts under the wrapper verb)
    for verb in ("start", "declare", "idem", "read", "metrics"):
        assert _value(snap, "bridge_requests_total", verb=verb)["value"] == 1
        assert _value(snap, "bridge_request_seconds", verb=verb)["count"] == 1
    assert "bridge_errors_total" not in snap  # a clean run errors nowhere

    # stdout carries the Prometheus snapshot...
    assert "# TYPE gossip_rounds_total counter" in out
    assert "gossip_rounds_total" in out
    # ...followed by parseable JSONL events of both kinds
    jlines = [
        json.loads(line) for line in out.splitlines()
        if line.startswith("{")
    ]
    kinds = {l["kind"] for l in jlines}
    assert kinds == {"span", "metric"}
    span_names = {l["name"] for l in jlines if l["kind"] == "span"}
    assert "gossip.round" in span_names
    assert any(n.startswith("merge.") for n in span_names)
    assert any(n.startswith("bridge.") for n in span_names)
    metric_names = {l["name"] for l in jlines if l["kind"] == "metric"}
    assert "gossip_rounds_total" in metric_names


def test_step_trace_facade_mirrors_into_registry(fresh_registry):
    from lasp_tpu.utils.metrics import StepTrace

    t = StepTrace()
    t.bump("merges", 5)
    t.bump("merges")
    t.record_round(3, 0.25)
    # legacy summary surface unchanged
    assert t.summary() == {
        "rounds": 1,
        "seconds": 0.25,
        "residual_path": [3],
        "merges": 6,
    }
    # and the dispatch mirrored into the registry
    snap = fresh_registry.snapshot()
    assert _value(snap, "step_dispatches_total")["value"] == 1
    assert _value(snap, "step_dispatch_seconds")["count"] == 1


def test_bridge_metrics_verb_scrapes_without_start(fresh_registry):
    from lasp_tpu.bridge import BridgeClient, BridgeServer
    from lasp_tpu.bridge.etf import Atom

    with BridgeServer(port=0) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            ok, text = c.metrics()  # before any {start, Name}
            assert ok == Atom("ok")
            assert isinstance(text, bytes)
            # the scrape itself was counted; a second scrape sees it
            ok2, text2 = c.metrics()
            assert b'bridge_requests_total{verb="metrics"}' in text2


def test_actor_guard_rejections_counted(fresh_registry):
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.mesh.runtime import ActorCollisionError
    from lasp_tpu.store import Store

    store = Store(n_actors=4)
    v = store.declare(type="riak_dt_gcounter")
    rt = ReplicatedRuntime(
        store, Graph(store), 4, ring(4, 2), debug_actors=True
    )
    rt.update_at(0, v, ("increment",), "w")
    with pytest.raises(ActorCollisionError):
        rt.update_at(1, v, ("increment",), "w")
    snap = fresh_registry.snapshot()
    assert _value(snap, "actor_guard_rejections_total")["value"] == 1
