"""Prometheus text-exposition golden test: the rendering is a scrape
interface — byte-stable output for a fixed registry state, pinned
against a checked-in golden file so accidental format drift is loud."""

import os

from lasp_tpu.telemetry.export import dump_jsonl, render_prometheus
from lasp_tpu.telemetry.registry import MetricRegistry

GOLDEN = os.path.join(os.path.dirname(__file__), "golden_prometheus.txt")


def _fixture_registry() -> MetricRegistry:
    reg = MetricRegistry()
    reg.counter("gossip_rounds_total", help="gossip rounds executed").inc(7)
    reg.gauge("gossip_residual", help="per-var residual", var="v0").set(2)
    reg.gauge("gossip_residual", help="per-var residual", var="v1").set(0)
    h = reg.histogram(
        "merge_seconds",
        help="merge wall time",
        buckets=(0.001, 0.01, 0.1),
        type="lasp_orset",
    )
    h.observe(0.0005)
    h.observe(0.05)
    h.observe(5.0)
    reg.counter(
        "bridge_requests_total", help="requests", verb="update"
    ).inc(3)
    # a label value needing escaping: backslash, quote, newline
    reg.counter("escape_total", help="escapes", k='a"b\\c\nd').inc(1)
    return reg


def test_prometheus_golden():
    text = render_prometheus(_fixture_registry().snapshot())
    with open(GOLDEN) as f:
        assert text == f.read()


def test_render_is_deterministic():
    a = render_prometheus(_fixture_registry().snapshot())
    b = render_prometheus(_fixture_registry().snapshot())
    assert a == b


def test_jsonl_dump_covers_every_series(tmp_path):
    import io
    import json

    buf = io.StringIO()
    n = dump_jsonl(buf, _fixture_registry().snapshot())
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(lines) == n
    metric_lines = [x for x in lines if x["kind"] == "metric"]
    names = {x["name"] for x in metric_lines}
    assert {"gossip_rounds_total", "gossip_residual", "merge_seconds",
            "bridge_requests_total"} <= names
    hist = next(x for x in metric_lines if x["name"] == "merge_seconds")
    assert hist["count"] == 3
    assert hist["counts"] == [1, 0, 1, 1]  # +Inf overflow slot last
