"""Causal event log tests: typing, bounding, ordering, sinks, export,
and the concurrent-emitter discipline (one locked serialize-and-write
per record — tests/telemetry/test_events.py::test_threaded_emitters
is the stress test of the shared sink)."""

import json
import threading

import pytest

from lasp_tpu.telemetry import events as E
from lasp_tpu.telemetry import registry as R
from lasp_tpu.telemetry import spans as S


@pytest.fixture(autouse=True)
def _clean():
    E.clear()
    E.configure(jsonl_path="", ring_size=E.DEFAULT_RING_SIZE)
    yield
    E.clear()
    E.configure(jsonl_path="", ring_size=E.DEFAULT_RING_SIZE)
    E.set_deep(False)


def test_unknown_event_type_is_loud():
    with pytest.raises(ValueError, match="unknown event type"):
        E.emit("definitely_not_a_type", var="x")


def test_records_carry_provenance_and_order():
    E.emit("bind", var="a", outcome="inflated")
    E.set_round(7)
    E.emit("update", var="a", replica=3, op="add")
    evs = E.events()
    assert [e["etype"] for e in evs] == ["bind", "update"]
    assert evs[0]["seq"] < evs[1]["seq"]
    assert evs[1]["round"] == 7
    assert evs[1]["replica"] == 3
    assert evs[1]["attrs"]["op"] == "add"
    # filtered views
    assert [e["etype"] for e in E.events(etype="update")] == ["update"]
    assert E.events(var="a", etype="bind")[0]["attrs"]["outcome"] == "inflated"


def test_ring_bounds_and_counts_drops():
    E.configure(ring_size=4)
    for i in range(10):
        E.emit("update", var="v", i=i)
    st = E.stats()
    assert st["ring"] == 4
    assert st["dropped"] == 6
    assert [e["attrs"]["i"] for e in E.events()] == [6, 7, 8, 9]
    # the drop tally is also a scrapeable counter (the catalog row:
    # nonzero rate = incomplete forensics)
    snap = R.get_registry().snapshot()
    assert snap["events_dropped_total"]["series"][0]["value"] == 6


def test_disabled_registry_silences_the_log():
    R.set_enabled(False)
    try:
        E.emit("bind", var="x")
    finally:
        R.set_enabled(True)
    assert E.events() == []


def test_deep_tier_off_by_default():
    E.emit_deep("merge", var="x", type="lasp_orset")
    assert E.events() == []
    E.set_deep(True)
    try:
        E.emit_deep("merge", var="x", type="lasp_orset")
    finally:
        E.set_deep(False)
    assert [e["etype"] for e in E.events()] == ["merge"]


def test_jsonl_sink(tmp_path):
    path = str(tmp_path / "events.jsonl")
    E.configure(jsonl_path=path)
    E.emit("membership", kind="join", old_n=4, new_n=8)
    E.configure(jsonl_path="")  # close
    [line] = open(path).read().splitlines()
    rec = json.loads(line)
    assert rec["etype"] == "membership"
    assert rec["attrs"]["new_n"] == 8


def test_chrome_trace_export_is_valid(tmp_path):
    E.emit("update", var="a", replica=1, op="add")
    with S.span("gossip.round"):
        E.emit("delivery", residual=2)
    path = tmp_path / "trace.json"
    with open(path, "w") as fp:
        n = E.export_chrome_trace(fp)
    doc = json.loads(path.read_text())
    assert isinstance(doc["traceEvents"], list) and len(doc["traceEvents"]) == n
    cats = {t["cat"] for t in doc["traceEvents"]}
    assert cats == {"event", "span"}
    for t in doc["traceEvents"]:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(t)
        assert t["ph"] in ("X", "i")
        if t["ph"] == "X":
            assert t["dur"] >= 0
    # the instant event carries its provenance columns
    inst = [t for t in doc["traceEvents"] if t["name"] == "update"][0]
    assert inst["args"]["var"] == "a"
    assert inst["args"]["replica"] == 1


def test_causal_history_walks_lineage():
    E.emit("update", var="src", op="add")
    E.emit("update", var="unrelated", op="add")
    E.emit("membership", kind="join", old_n=2, new_n=4)
    E.emit("bind", var="derived", outcome="inflated")
    lineage = {"derived": {"kinds": ["map"], "srcs": ["src"]}}
    hist = E.causal_history("derived", lineage)
    assert [e.get("var", e["etype"]) for e in hist] == [
        "src", "membership", "derived",
    ]
    seqs = [e["seq"] for e in hist]
    assert seqs == sorted(seqs)


def test_threaded_emitters_never_interleave_records(tmp_path):
    """Satellite: spans + events from concurrent threads (the mesh
    batch-dispatch / bridge-connection shape) — every JSONL line must
    parse, every record must arrive, and the event ring's seq must be
    gap-free. Before the shared-sink lock, concurrent writers could
    interleave partial lines."""
    epath = str(tmp_path / "ev.jsonl")
    spath = str(tmp_path / "sp.jsonl")
    E.configure(jsonl_path=epath, ring_size=100_000)
    S.configure(jsonl_path=spath)
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(per_thread):
            # long attrs make torn writes likely without the lock
            E.emit("update", var=f"v{tid}", replica=tid,
                   payload="x" * 64, i=i)
            with S.span(f"t{tid}", i=i, pad="y" * 64):
                pass

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    E.configure(jsonl_path="")
    S.configure(jsonl_path="")
    total = n_threads * per_thread
    for path, expect in ((epath, total), (spath, total)):
        lines = open(path).read().splitlines()
        assert len(lines) >= expect  # other tests may not have appended
        parsed = [json.loads(line) for line in lines]  # raises on a torn line
        assert len(parsed) == len(lines)
    evs = E.events(etype="update")
    mine = [e for e in evs if e["attrs"].get("payload", "").startswith("x")]
    assert len(mine) == total
    # per-thread arrival order is preserved under the global seq
    for tid in range(n_threads):
        tids = [e["attrs"]["i"] for e in mine if e["var"] == f"v{tid}"]
        assert tids == sorted(tids)
    seqs = sorted(e["seq"] for e in mine)
    assert len(set(seqs)) == total  # no duplicated seq


def test_event_types_match_catalog_lint():
    """The lint's STATIC parse of EVENT_TYPES must agree with the live
    set (a refactor moving the declaration would silently blind the
    catalog check)."""
    import importlib.util
    import os

    tool = os.path.join(
        os.path.dirname(__file__), "..", "..", "tools",
        "check_metrics_catalog.py",
    )
    spec = importlib.util.spec_from_file_location("catalog_lint", tool)
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    assert lint.declared_event_types() == set(E.EVENT_TYPES)


def test_batch_fallback_emits_one_coarse_update(tmp_path):
    """update_batch's per-op update_at fallback must log ONE coarse
    'update' record for the whole batch, not one per op (the
    one-coarse-record-per-batch rule)."""
    import warnings

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    store = Store(n_actors=8)
    # a map embedding an orset field has no vectorized batch kernel:
    # update_batch falls back to per-op update_at
    m = store.declare(
        id="m", type="riak_dt_map",
        fields=[("s", "lasp_orset", {"n_elems": 4})],
    )
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    E.clear()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rt.update_batch(m, [
            (0, ("update", "s", ("add", f"x{i}")), "w0") for i in range(3)
        ])
    coarse = E.events(etype="update", var=m)
    assert len(coarse) == 1, coarse
    assert coarse[0]["attrs"]["ops"] == 3
    assert not rt._suppress_op_events  # flag never leaks past the batch


def test_sink_survives_unserializable_record(tmp_path, capsys):
    from lasp_tpu.telemetry.sink import JsonlSink

    path = str(tmp_path / "s.jsonl")
    sink = JsonlSink()
    sink.configure(path)
    loop: dict = {}
    loop["self"] = loop  # circular: json.dumps raises even with default=
    sink.append({"kind": "event", "bad": loop})  # must not raise
    sink.append({"kind": "event", "ok": 1})
    lines = open(path).read().splitlines()
    assert len(lines) == 1  # bad record dropped, sink still live
    assert json.loads(lines[0])["ok"] == 1


def test_stats_surface():
    E.emit("bind", var="x")
    st = E.stats()
    assert st["seq"] == 1 and st["ring"] == 1 and st["deep"] is False
