"""Registry semantics: counter monotonicity, histogram bucketing,
snapshot isolation, label series, type safety, the enable switch, and
the typed CounterGroup (the Store.metrics schema)."""

import pytest

from lasp_tpu.telemetry import registry as R
from lasp_tpu.telemetry.registry import CounterGroup, MetricRegistry


def test_counter_monotonic():
    reg = MetricRegistry()
    c = reg.counter("ops_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 5  # the refused decrement changed nothing


def test_counter_same_name_same_instrument():
    reg = MetricRegistry()
    reg.counter("x_total").inc()
    reg.counter("x_total").inc()
    assert reg.counter("x_total").value == 2


def test_type_conflict_is_loud():
    reg = MetricRegistry()
    reg.counter("x_total")
    with pytest.raises(TypeError):
        reg.gauge("x_total")
    with pytest.raises(TypeError):
        reg.histogram("x_total")


def test_label_series_are_independent():
    reg = MetricRegistry()
    reg.counter("m_total", type="a").inc(3)
    reg.counter("m_total", type="b").inc(1)
    snap = reg.snapshot()["m_total"]
    by_label = {s["labels"]["type"]: s["value"] for s in snap["series"]}
    assert by_label == {"a": 3, "b": 1}


def test_histogram_bucketing_and_overflow():
    reg = MetricRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 99.0):
        h.observe(v)
    assert h.counts == [1, 2, 1, 1]  # last slot = +Inf overflow
    assert h.cumulative() == [1, 3, 4, 5]
    assert h.count == 5
    assert h.sum == pytest.approx(0.005 + 0.05 + 0.05 + 0.5 + 99.0)


def test_histogram_boundary_lands_in_its_le_bucket():
    # Prometheus semantics: le is INCLUSIVE — an observation exactly on
    # a boundary counts in that boundary's bucket
    reg = MetricRegistry()
    h = reg.histogram("b_seconds", buckets=(0.1, 1.0))
    h.observe(0.1)
    assert h.counts == [1, 0, 0]


def test_histogram_bad_buckets_raise():
    reg = MetricRegistry()
    with pytest.raises(ValueError):
        reg.histogram("h1", buckets=(1.0, 0.5))  # unsorted
    with pytest.raises(ValueError):
        reg.histogram("h2", buckets=(1.0, 1.0))  # duplicate
    with pytest.raises(ValueError):
        reg.histogram("h3", buckets=())  # empty


def test_snapshot_isolation():
    reg = MetricRegistry()
    c = reg.counter("iso_total")
    h = reg.histogram("iso_seconds")
    c.inc(2)
    h.observe(0.2)
    snap = reg.snapshot()
    c.inc(10)
    h.observe(0.9)
    fam = snap["iso_total"]["series"][0]
    assert fam["value"] == 2  # frozen at snapshot time
    hs = snap["iso_seconds"]["series"][0]
    assert hs["count"] == 1


def test_gauge_set_inc_dec():
    reg = MetricRegistry()
    g = reg.gauge("depth")
    g.set(7)
    g.inc(2)
    g.dec()
    assert g.value == 8


def test_enable_switch_returns_nulls():
    prev = R.enabled()
    try:
        R.set_enabled(False)
        c = R.counter("never_total")
        c.inc(100)  # no-op
        R.set_enabled(True)
        assert "never_total" not in R.get_registry().names()
    finally:
        R.set_enabled(prev)


def test_counter_group_typed():
    g = CounterGroup(("binds", "reads"))
    g["binds"] += 1
    g["binds"] += 1
    assert g["binds"] == 2
    with pytest.raises(KeyError):
        g["typo"] = 1
    with pytest.raises(ValueError):
        g["reads"] = -1
    with pytest.raises(TypeError):
        del g["binds"]
    # mapping surface: dict() conversion, update (checkpoint restore),
    # equality with a plain dict (the persistence round-trip contract)
    assert dict(g) == {"binds": 2, "reads": 0}
    g.update({"reads": 5})
    assert g == {"binds": 2, "reads": 5}
    assert g.snapshot() == {"binds": 2, "reads": 5}
    # snapshot is a copy, not a view
    snap = g.snapshot()
    g["reads"] += 1
    assert snap["reads"] == 5
