"""ConvergenceMonitor tests: the hot residual feed (staleness, top-K,
ETA), the on-demand lag probe (per-replica / per-shard), alerts, and
the health snapshot the `{health}` verb serves."""

import pytest

from lasp_tpu import telemetry
from lasp_tpu.telemetry import registry as R
from lasp_tpu.telemetry.convergence import ConvergenceMonitor, get_monitor


@pytest.fixture()
def mon():
    return ConvergenceMonitor()


def test_observe_round_residual_and_staleness(mon):
    mon.observe_round(("a", "b"), [4, 2], 0.01, n_replicas=8)
    mon.observe_round(("a", "b"), [1, 0], 0.01)
    mon.observe_round(("a", "b"), [1, 0], 0.01)
    snap = mon.snapshot()
    assert snap["round"] == 3
    assert snap["residual_by_var"] == {"a": 1, "b": 0}
    # b last changed at round 1 -> stale for 2 rounds; a changed this round
    assert snap["staleness"] == {"a": 0, "b": 2}
    assert snap["total_changes_by_var"] == {"a": 6, "b": 2}
    assert mon.top_divergent() == [("a", 1), ("b", 0)]


def test_quiescence_eta_contracting_and_not(mon):
    mon.observe_round(("a",), [64])
    mon.observe_round(("a",), [32])  # halving: eta = log2(32) = 5
    assert mon.quiescence_eta() == 5
    mon.observe_round(("a",), [32])  # stalled: no converging trend
    assert mon.quiescence_eta() is None
    mon.observe_round(("a",), [0])
    assert mon.quiescence_eta() == 0


def test_eta_unknown_after_opaque_unconverged_tail(mon):
    # quiesce, then an opaque fused block that did NOT reach the fixed
    # point: the stale zero must not read as "converged" (eta 0)
    mon.observe_round(("a",), [4])
    mon.observe_round(("a",), [0])
    assert mon.quiescence_eta() == 0
    mon.observe_opaque_rounds(8, quiescent=False)
    assert mon.quiescence_eta() is None
    assert mon.snapshot()["residual_total"] is None


def test_probe_shard_split_handles_remainder():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    store = Store(n_actors=8)
    v = store.declare(id="rem", type="lasp_gset", n_elems=4)
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    rt.update_at(0, v, ("add", "x"), "w")
    probe = ConvergenceMonitor().probe(rt, n_shards=3)
    # 8 rows over 3 shards: near-equal blocks, never silently empty
    assert len(probe["shard_lag"]) == 3
    assert max(probe["shard_lag"]) == 1


def test_alerts_accept_a_prebuilt_snapshot(mon):
    mon.observe_round(("a",), [1], n_replicas=2)
    snap = mon.snapshot()
    assert mon.alerts(snap) == mon.alerts()


def test_opaque_rounds_advance_the_clock(mon):
    mon.observe_round(("a",), [5], n_replicas=4)
    mon.observe_opaque_rounds(10, quiescent=True)
    snap = mon.snapshot()
    assert snap["round"] == 11
    # a terminal quiescent marker zeroes the residual view
    assert snap["residual_by_var"] == {"a": 0}
    assert snap["quiescence_eta"] == 0


def test_membership_observation(mon):
    mon.observe_round(("a",), [1], n_replicas=8)
    mon.observe_membership("leave_crash", 8, 5)
    snap = mon.snapshot()
    assert snap["n_replicas"] == 5
    assert snap["memberships"] == [(1, "leave_crash", 8, 5)]
    assert snap["probe"] is None  # a stale probe never survives a resize


def test_stuck_alert_needs_divergence(mon):
    mon.thresholds["max_stale_rounds"] = 3
    mon.observe_round(("a",), [2], n_replicas=4)
    for _ in range(4):
        mon.observe_round(("a",), [0])
    # residual 0 and no probe: quiescent-and-stale is NOT stuck
    assert mon.alerts() == []
    # a probe showing rows behind the join makes the same staleness stuck
    mon.last_probe = {"lag_by_var": {"a": 2}, "worst_replica_lag": 2,
                      "worst_replica": 1, "shard_lag": []}
    alerts = mon.alerts()
    assert any("stuck: a" in a for a in alerts)


def test_replica_lag_alert_and_custom_alert(mon):
    mon.thresholds["max_replica_lag"] = 1
    mon.observe_round(("a",), [1], n_replicas=4)
    mon.last_probe = {"lag_by_var": {"a": 3}, "worst_replica_lag": 3,
                      "worst_replica": 2, "shard_lag": [0, 3]}
    alerts = mon.alerts()
    assert any("lagging: replica 2" in a for a in alerts)
    mon.add_alert("custom-fire", lambda snap: snap["round"] >= 1)
    assert "custom-fire" in mon.alerts()


def test_unknown_threshold_is_loud():
    with pytest.raises(TypeError, match="unknown alert thresholds"):
        ConvergenceMonitor(thresholds={"max_stale": 3})


def test_probe_per_replica_and_shard_lag():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    store = Store(n_actors=8)
    v = store.declare(id="g", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    rt.update_at(0, v, ("add", "x"), "w0")
    mon = ConvergenceMonitor()
    probe = mon.probe(rt, n_shards=4)
    # only replica 0 holds x: the other 7 are one variable behind
    assert probe["lag_by_var"] == {"g": 7}
    assert probe["worst_replica_lag"] == 1
    assert probe["shard_lag"] == [1, 1, 1, 1]
    assert probe["n_replicas"] == 8
    # the probe lands in the snapshot and the gauges
    assert mon.snapshot()["probe"]["lag_by_var"] == {"g": 7}
    snap = R.get_registry().snapshot()
    lag = {
        s["labels"]["var"]: s["value"]
        for s in snap["convergence_lag_replicas"]["series"]
    }
    assert lag["g"] == 7
    shard = {
        s["labels"]["shard"]: s["value"]
        for s in snap["convergence_shard_lag"]["series"]
    }
    assert shard == {"0": 1, "1": 1, "2": 1, "3": 1}
    rt.run_to_convergence(max_rounds=16)
    probe2 = mon.probe(rt, n_shards=4)
    assert probe2["worst_replica_lag"] == 0
    assert probe2["shard_lag"] == [0, 0, 0, 0]


def test_runtime_step_feeds_global_monitor():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    telemetry.reset()  # detach any state earlier tests accumulated
    store = Store(n_actors=8)
    v = store.declare(id="fed", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    rt.update_at(3, v, ("add", "x"), "w")
    rounds = rt.run_to_convergence(max_rounds=16)
    mon = get_monitor()
    snap = mon.snapshot()
    assert snap["round"] == rounds
    assert snap["residual_by_var"]["fed"] == 0  # final round is quiescent
    assert snap["n_replicas"] == 8
    # the residual curve narrates the drain to the fixed point
    assert [t for _r, t in snap["residual_curve"]][-1] == 0
    # staleness gauges landed for the fed variable
    reg = R.get_registry().snapshot()
    assert any(
        s["labels"] == {"var": "fed"}
        for s in reg["convergence_staleness"]["series"]
    )
    # delivery events carry the round clock
    from lasp_tpu.telemetry import events as E

    deliveries = E.events(etype="delivery")
    assert deliveries and deliveries[-1]["round"] == rounds


def test_health_includes_alerts(mon):
    mon.observe_round(("a",), [1], n_replicas=2)
    h = mon.health()
    assert "alerts" in h and h["round"] == 1


def test_generation_reset_detaches_state():
    mon = ConvergenceMonitor()
    mon.observe_round(("a",), [3], n_replicas=4)
    telemetry.reset()  # bumps the registry generation
    mon.observe_round(("b",), [1], n_replicas=2)
    snap = mon.snapshot()
    # pre-reset state was dropped with the old generation's instruments
    assert set(snap["residual_by_var"]) == {"b"}
    assert snap["round"] == 1
