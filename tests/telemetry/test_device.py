"""The in-graph telemetry plane (telemetry/device.py): the modulo-K
flight ring's write/decode round-trip, the drained-window log, and the
tentpole parity claim — a fused ``converge_on_device`` run's drained
per-round residual curve is bit-for-bit the unfused ``step()`` curve
on the same seed (the observability-survives-fusion contract)."""

import numpy as np
import pytest

from lasp_tpu import telemetry
from lasp_tpu.telemetry import device as tdev
from lasp_tpu.telemetry import events as tel_events


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    tel_events.clear()
    tdev.clear()
    yield
    telemetry.reset()
    tel_events.clear()
    tdev.clear()


# -- ring write/decode ------------------------------------------------------

def _filled_ring(k, rounds, width=2):
    """Host-side emulation of the in-loop writes: round j (0-based) at
    slot j % k, record [j+1, 10*(j+1)]."""
    ring = np.zeros((k, width), np.int32)
    for j in range(rounds):
        ring[j % k] = [j + 1, 10 * (j + 1)]
    return ring


def test_decode_ring_no_wraparound():
    records, overwritten = tdev.decode_ring(_filled_ring(8, 5), 5)
    assert overwritten == 0
    assert records == [[j + 1, 10 * (j + 1)] for j in range(5)]


def test_decode_ring_exactly_full():
    records, overwritten = tdev.decode_ring(_filled_ring(4, 4), 4)
    assert overwritten == 0
    assert [r[0] for r in records] == [1, 2, 3, 4]


def test_decode_ring_wraparound_keeps_suffix_oldest_first():
    # 7 rounds through a K=4 ring: rounds 1-3 overwritten, 4-7 retained
    records, overwritten = tdev.decode_ring(_filled_ring(4, 7), 7)
    assert overwritten == 3
    assert [r[0] for r in records] == [4, 5, 6, 7]


def test_decode_ring_zero_rounds():
    records, overwritten = tdev.decode_ring(np.zeros((4, 2), np.int32), 0)
    assert records == [] and overwritten == 0


def test_ring_write_traced_matches_host_emulation():
    import jax
    import jax.numpy as jnp

    k, rounds, width = 4, 7, 3

    @jax.jit
    def run():
        def body(i, ring):
            rec = jnp.stack([i + 1, 10 * (i + 1), 100 * (i + 1)])
            return tdev.ring_write(ring, i, rec)
        return jax.lax.fori_loop(0, rounds, body, tdev.ring_init(k, width))

    records, overwritten = tdev.decode_ring(run(), rounds)
    assert overwritten == 3
    assert records == [
        [j + 1, 10 * (j + 1), 100 * (j + 1)] for j in range(3, 7)
    ]


# -- window log -------------------------------------------------------------

def _window(family="converge", records=((3, 1), (0, 0)), **kw):
    return tdev.FlightWindow(
        family=family, columns=("a", "b"), rounds=len(records),
        overwritten=kw.pop("overwritten", 0),
        records=[list(r) for r in records],
        seconds=0.01, quiescent=kw.pop("quiescent", True), **kw,
    )


def test_record_window_log_and_counters():
    tdev.record_window(_window())
    tdev.record_window(_window(family="fused_block", quiescent=None))
    assert len(tdev.windows()) == 2
    assert [w.family for w in tdev.windows("converge")] == ["converge"]
    assert tdev.last_window().family == "fused_block"
    assert tdev.last_window("converge").quiescent is True
    snap = telemetry.get_registry().snapshot()
    by_family = {
        s["labels"].get("family"): s["value"]
        for s in snap["flight_windows_total"]["series"]
    }
    assert by_family == {"converge": 1, "fused_block": 1}
    assert snap["flight_rounds_recorded_total"]["series"][0]["value"] == 4
    st = tdev.stats()
    assert st["windows"] == 2 and st["rounds_recorded"] == 4


def test_record_window_overwritten_counter_and_curve():
    w = _window(records=((5, 2), (1, 0), (0, 0)), overwritten=4)
    tdev.record_window(w)
    snap = telemetry.get_registry().snapshot()
    assert (
        snap["flight_rounds_overwritten_total"]["series"][0]["value"] == 4
    )
    # curve points are (first_round + i, total); default unclocked base
    assert w.residual_curve() == [(0, 7), (1, 1), (2, 0)]
    d = w.to_dict()
    assert d["family"] == "converge" and d["overwritten"] == 4
    assert d["records"] == [[5, 2], [1, 0], [0, 0]]


def test_record_window_disabled_is_noop():
    telemetry.set_enabled(False)
    try:
        tdev.record_window(_window())
        assert tdev.windows() == []
    finally:
        telemetry.set_enabled(True)


def test_window_log_detaches_on_registry_generation():
    tdev.record_window(_window())
    assert len(tdev.windows()) == 1
    telemetry.reset()  # new generation: the log must not leak across
    assert tdev.windows() == []


def test_snapshot_and_render():
    tdev.record_window(_window())
    snap = tdev.snapshot()
    assert snap["flight_rounds"] == tdev.flight_rounds()
    assert len(snap["windows"]) == 1
    text = tdev.render(tdev.windows())
    assert "family=converge" in text and "round" in text


# -- the tentpole parity claim ----------------------------------------------

def _build_runtime():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    n = 16
    store = Store(n_actors=4)
    a = store.declare(id="a", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), n, ring(n, 2))
    rt.update_batch(a, [(0, ("add", "x"), "w0"), (7, ("add", "y"), "w1")])
    return rt


def test_converge_on_device_curve_matches_unfused_bit_for_bit():
    from lasp_tpu.telemetry import get_monitor

    rt_u = _build_runtime()
    mon = get_monitor()
    curve_u = []
    for _ in range(64):
        total = rt_u.step()
        curve_u.append([int(mon.vars[v]["residual"]) for v in rt_u.var_ids])
        if total == 0:
            break
    telemetry.reset()
    tel_events.clear()

    rt_f = _build_runtime()
    rounds = rt_f.converge_on_device(max_rounds=64)
    w = tdev.last_window("converge")
    assert w is not None and w.overwritten == 0
    assert rounds == len(curve_u)
    assert [list(r) for r in w.records] == curve_u
    # the drain also replayed the monitor feed: same round clock, and
    # one real per-round delivery event per retained round
    assert get_monitor().round == rounds
    deliveries = [
        e for e in tel_events.events() if e["etype"] == "delivery"
    ]
    assert len(deliveries) == rounds
    assert [e["attrs"]["residual"] for e in deliveries] == [
        sum(r) for r in curve_u
    ]
    assert all(e["attrs"]["fused"] == "converge" for e in deliveries)


def test_fused_steps_window_records_and_exact_round_accounting():
    from lasp_tpu.telemetry import get_monitor

    rt = _build_runtime()
    first_zero = rt.fused_steps(24)
    assert first_zero >= 0  # this seed converges inside one block
    w = tdev.last_window("fused_block")
    assert w is not None and w.rounds == 24 and w.overwritten == 0
    # quiescent from first_zero on: the fixed point is a no-op
    totals = [sum(r) for r in w.records]
    assert totals[first_zero] == 0
    assert all(t == 0 for t in totals[first_zero:])
    assert all(t > 0 for t in totals[:first_zero])
    assert get_monitor().round == 24
