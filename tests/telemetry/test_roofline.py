"""Roofline observatory tests: the analytic traffic model validated
against XLA's own cost analysis, ledger attribution against wall time,
the capability registry, the hardened probe-report schema, and the
MULTICHIP evidence contract."""

import importlib.util
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, random_regular
from lasp_tpu.mesh.gossip import (
    gossip_round,
    gossip_round_grouped,
    gossip_round_rows,
    gossip_round_rows_grouped,
)
from lasp_tpu.store import Store
from lasp_tpu.telemetry import capability, registry as reg
from lasp_tpu.telemetry.roofline import (
    KernelLedger,
    cost_analysis_bytes,
    get_ledger,
    kernel_traffic,
    state_row_bytes,
)

R, K = 256, 3


def _runtime(packed=False):
    """A runtime holding one variable per codec class the model must
    cover: leafwise (G-Set), vclock (OR-SWOT), and — packed=True — the
    flat bit-packed wire codec."""
    store = Store(n_actors=4)
    if packed:
        store.declare(id="p", type="lasp_orset", n_elems=16, n_actors=4,
                      tokens_per_actor=4)
    else:
        store.declare(id="g", type="lasp_gset", n_elems=64)
        store.declare(id="o", type="riak_dt_orswot", n_elems=8, n_actors=4)
    rt = ReplicatedRuntime(
        store, Graph(store), R, random_regular(R, K, seed=1),
        packed=packed,
    )
    return rt


def _codecs():
    """(name, codec, spec, states, leafwise) across leafwise / vclock /
    packed — the three codec classes of the satellite task."""
    out = []
    rt = _runtime()
    for v in ("g", "o"):
        codec, spec = rt._mesh_meta(v)
        out.append((v, codec, spec, rt.states[v],
                    getattr(codec, "leafwise_join", None) is not None))
    rtp = _runtime(packed=True)
    codec, spec = rtp._mesh_meta("p")
    out.append(("p", codec, spec, rtp.states["p"],
                getattr(codec, "leafwise_join", None) is not None))
    return out


def test_traffic_model_brackets_cost_analysis():
    """The cross-check of the satellite task: for the dense, frontier
    row-sparse, and grouped kernels, the analytic model's xla bounds
    must bracket ``cost_analysis()['bytes accessed']`` on this backend,
    for every codec class (leafwise / vclock / packed)."""
    nbrs = jnp.asarray(random_regular(R, K, seed=1))
    F, G = 16, 3
    rows = jnp.arange(F)
    valid = jnp.ones((G, F), dtype=bool)
    rows_g = jnp.stack([jnp.arange(F)] * G)
    for name, codec, spec, st, leafwise in _codecs():
        rb = state_row_bytes(st, R)
        # dense
        ca = cost_analysis_bytes(
            jax.jit(lambda s, nb: gossip_round(codec, spec, s, nb))
            .lower(st, nbrs).compile()
        )
        if ca is None:
            pytest.skip("backend provides no cost analysis")
        est = kernel_traffic("dense", row_bytes=rb, n_replicas=R, fanout=K,
                             leafwise=leafwise)
        assert est.xla_lo <= ca <= est.xla_hi, (
            name, "dense", est.xla_lo, ca, est.xla_hi
        )
        assert est.joins == R * K
        # frontier row-sparse
        ca = cost_analysis_bytes(
            jax.jit(
                lambda s, nb, r: gossip_round_rows(codec, spec, s, nb, r)
            ).lower(st, nbrs, rows).compile()
        )
        est = kernel_traffic("rows", row_bytes=rb, n_replicas=R, fanout=K,
                             rows=F, leafwise=leafwise)
        assert est.xla_lo <= ca <= est.xla_hi, (
            name, "rows", est.xla_lo, ca, est.xla_hi
        )
        # grouped dense (G stacked members)
        st_g = jax.tree_util.tree_map(lambda x: jnp.stack([x] * G), st)
        ca = cost_analysis_bytes(
            jax.jit(
                lambda s, nb: gossip_round_grouped(codec, spec, s, nb)
            ).lower(st_g, nbrs).compile()
        )
        est = kernel_traffic("grouped_dense", row_bytes=rb, n_replicas=R,
                             fanout=K, g_active=G, leafwise=leafwise)
        assert est.xla_lo <= ca <= est.xla_hi, (
            name, "grouped_dense", est.xla_lo, ca, est.xla_hi
        )
        # grouped row-sparse
        ca = cost_analysis_bytes(
            jax.jit(
                lambda s, nb, r, v: gossip_round_rows_grouped(
                    codec, spec, s, nb, r, v
                )
            ).lower(st_g, nbrs, rows_g, valid).compile()
        )
        est = kernel_traffic("grouped_rows", row_bytes=rb, n_replicas=R,
                             fanout=K, rows=F, g_active=G,
                             leafwise=leafwise)
        assert est.xla_lo <= ca <= est.xla_hi, (
            name, "grouped_rows", est.xla_lo, ca, est.xla_hi
        )


def test_traffic_model_scales_with_population():
    """The model must TRACK cost_analysis across shapes (the roofline
    drives sizing decisions): doubling R doubles both within 25%."""
    from lasp_tpu.lattice import GSet, GSetSpec
    from lasp_tpu.lattice.base import replicate

    spec = GSetSpec(n_elems=64)
    ratios = []
    for r in (R, 2 * R):
        st = replicate(GSet.new(spec), r)
        nbrs = jnp.asarray(random_regular(r, K, seed=1))
        ca = cost_analysis_bytes(
            jax.jit(lambda s, nb: gossip_round(GSet, spec, s, nb))
            .lower(st, nbrs).compile()
        )
        if ca is None:
            pytest.skip("backend provides no cost analysis")
        est = kernel_traffic("dense", row_bytes=state_row_bytes(st, r),
                             n_replicas=r, fanout=K, leafwise=True)
        ratios.append(ca / est.bytes_moved)
    assert abs(ratios[0] - ratios[1]) / ratios[0] < 0.25, ratios


def test_traffic_model_rejects_unknown_family():
    with pytest.raises(ValueError):
        kernel_traffic("warp_drive", row_bytes=8, n_replicas=8, fanout=2)


def test_ledger_attribution_sums_to_round_wall_time():
    """Ledger-attributed dispatch seconds must sum to (at most, and a
    meaningful fraction of) the measured round-loop wall time — the
    attribution satellite. Warm kernels only: the compile bucket keeps
    trace+compile out of achieved figures."""
    reg.reset()  # fresh generation -> fresh ledger
    store = Store(n_actors=4)
    ids = [store.declare(id=f"v{i}", type="lasp_gset", n_elems=16)
           for i in range(6)]
    rt = ReplicatedRuntime(
        store, Graph(store), 128, random_regular(128, 3, seed=2)
    )
    for i, v in enumerate(ids):
        rt.update_batch(v, [(i, ("add", "x"), f"a{i}")])
    while rt.frontier_step():  # cold pass: compiles everything
        pass
    ledger = get_ledger()
    t0_totals = ledger.totals()
    t0 = time.perf_counter()
    rounds = 0
    for rep in range(2):  # fresh writes: rounds must actually gossip
        for i, v in enumerate(ids):
            rt.update_batch(
                v, [((i + rep) % 128, ("add", f"y{rep}"), f"b{i}")]
            )
        while rt.frontier_step():
            rounds += 1
    wall = time.perf_counter() - t0
    d = ledger.totals()
    attributed = d["seconds"] - t0_totals["seconds"]
    assert rounds > 0
    assert 0 < attributed <= wall * 1.02, (attributed, wall)
    # the dispatches ARE the round loop's device work: attribution must
    # cover a meaningful share of wall (host bookkeeping is the rest)
    assert attributed >= 0.05 * wall, (attributed, wall)
    dispatches = d["dispatches"] - t0_totals["dispatches"]
    assert dispatches > 0


def test_ledger_compile_bucket_and_rates():
    led = KernelLedger()
    led.record("rows", "GSet", n_replicas=64, fanout=3, seconds=1.0,
               row_bytes=16, rows=16)
    snap = led.snapshot()[0]
    assert snap["compile_dispatches"] == 1
    assert snap["dispatches"] == 0 and snap["seconds"] == 0.0
    assert snap["achieved_GBps"] is None  # no warm data yet
    for _ in range(3):
        led.record("rows", "GSet", n_replicas=64, fanout=3, seconds=0.001,
                   row_bytes=16, rows=16)
    snap = led.snapshot()[0]
    assert snap["dispatches"] == 3
    assert snap["compile_seconds"] == pytest.approx(1.0)
    est = kernel_traffic("rows", row_bytes=16, n_replicas=64, fanout=3,
                         rows=16)
    assert snap["bytes"] == 3 * est.bytes_moved
    assert snap["achieved_GBps"] == round(
        snap["bytes"] / snap["seconds"] / 1e9, 3
    )
    assert snap["roofline_frac"] is not None  # CPU: measured-host peak


def test_ledger_detaches_on_generation_change():
    led = get_ledger()
    with reg.scratch_registry():
        scratch = get_ledger()
        assert scratch is not led
        scratch.record("dense", "GSet", n_replicas=8, fanout=2,
                       seconds=0.1, row_bytes=8)
    after = get_ledger()
    assert after is not scratch
    assert after.totals()["dispatches"] == 0


def test_ledger_noop_when_disabled():
    led = KernelLedger()
    reg.set_enabled(False)
    try:
        led.record("dense", "GSet", n_replicas=8, fanout=2, seconds=0.1,
                   row_bytes=8)
    finally:
        reg.set_enabled(True)
    assert led.totals()["dispatches"] == 0
    assert led.totals()["compile_seconds"] == 0.0


def test_health_carries_roofline_view():
    from lasp_tpu.telemetry import get_monitor

    h = get_monitor().health()
    assert "roofline" in h
    view = h["roofline"]
    assert set(view) >= {"kernels", "totals", "achieved_GBps",
                         "roofline_frac"}


# -- capability registry ------------------------------------------------------

def test_capability_pinned_kinds():
    assert capability.peak_gbps_for_kind("TPU v5e") == 819.0
    assert capability.peak_gbps_for_kind("TPU v5 lite") == 819.0
    assert capability.peak_gbps_for_kind("TPU v5p") == 2765.0
    assert capability.peak_gbps_for_kind("TPU v4") == 1228.0
    assert capability.peak_gbps_for_kind("quantum-accelerator-9000") is None


def test_capability_host_probe_cached():
    bw1 = capability.measure_host_bandwidth(size_mb=16)
    bw2 = capability.measure_host_bandwidth(size_mb=16)
    assert bw1 > 0 and bw1 == bw2  # one-shot, cached


def test_device_capability_cpu_is_measured_host():
    cap = capability.device_capability(refresh=True)
    assert cap["platform"] == "cpu"  # the test env pins CPU
    assert cap["source"] == "measured-host"
    assert cap["peak_GBps"] and cap["peak_GBps"] > 0


def test_capability_gauge_survives_registry_generation():
    """telemetry reset()/scratch_registry() wipe the live registry, so
    a cache-HIT read of device_capability() must re-emit the
    capability_peak_GBps gauge into the new generation — otherwise
    exports carry roofline_frac with no visible denominator for the
    rest of the process (same lifetime rule as the ledger)."""
    cap = capability.device_capability(refresh=True)
    reg.reset()
    assert "capability_peak_GBps" not in reg.get_registry().snapshot()
    assert capability.device_capability() is cap  # cache hit re-emits
    snap = reg.get_registry().snapshot()
    series = snap["capability_peak_GBps"]["series"]
    assert series[0]["value"] == cap["peak_GBps"]
    # a scrape inside a scratch registry emits THERE, and must not pin
    # the generation so the next live read re-emits into the live one
    reg.reset()
    with reg.scratch_registry():
        capability.device_capability()
    assert "capability_peak_GBps" not in reg.get_registry().snapshot()
    capability.device_capability()
    assert "capability_peak_GBps" in reg.get_registry().snapshot()


# -- probe-report schema ------------------------------------------------------

def test_probe_classification_separates_warning_noise():
    """The r03–r05 regression: stderr whose only content is the
    experimental-platform WARNING must classify as init_timeout with
    the warning in the warnings tier, NOT surfaced as the fatal line."""
    warn = ("WARNING:2026-07-31 13:37:27,736:jax._src.xla_bridge:905: "
            "Platform 'axon' is experimental and not all JAX "
            "functionality may be correctly supported!")
    rec, platforms = capability.classify_probe_attempt(
        capability.PROBE_TIMEOUT_RC, "", warn + "\n"
    )
    assert rec["classification"] == "init_timeout"
    assert rec["fatal"] is None
    assert rec["warnings"] == [warn]
    assert platforms == []


def test_probe_warning_tier_is_anchored():
    """A fatal line that merely MENTIONS a warning must stay fatal — a
    substring match would demote it to the noise tier and null the
    verdict (the r03–r05 blind spot in a new costume)."""
    err = ("WARNING: Platform 'axon' is experimental\n"
           "/x/y.py:6: UserWarning: something benign\n"
           "RuntimeError: TPU init failed, see WARNING above\n")
    rec, _ = capability.classify_probe_attempt(1, "", err)
    assert rec["fatal"] == "RuntimeError: TPU init failed, see WARNING above"
    assert len(rec["warnings"]) == 2


def test_probe_budget_exceeded_not_signal():
    """The watcher's own budget SIGTERM (rc=-15) must classify as
    budget_exceeded, not as an externally-delivered signal."""
    rec, _ = capability.classify_probe_attempt(
        -15, "", "", budget_exceeded=True
    )
    assert rec["classification"] == "budget_exceeded"
    assert rec["classification"] in capability.PROBE_CLASSIFICATIONS


def test_capability_pre_jax_cache_refreshes(monkeypatch):
    """A capability record cached before jax was importable must
    re-resolve on the first call after import — an early startup call
    may never pin the host-DRAM denominator for an accelerator run."""
    stale = {"platform": "cpu", "device_kind": "cpu",
             "peak_GBps": 1.23, "source": "measured-host"}
    monkeypatch.setattr(capability, "_capability", stale)
    monkeypatch.setattr(capability, "_capability_saw_jax", False)
    cap = capability.device_capability()  # jax IS imported in the suite
    assert cap is not stale
    assert capability._capability_saw_jax is True
    # and once resolved under jax, the cache holds
    assert capability.device_capability() is cap


def test_probe_raised_warning_is_the_verdict():
    """Under PYTHONWARNINGS=error a child dies with a bare
    'XWarning: ...' as the traceback's last line — that line IS the
    fatal verdict, not noise (only the 'file.py:123: XWarning:'
    warnings.warn format and logging's 'WARNING' prefix are noise)."""
    err = ("Traceback (most recent call last):\n"
           "DeprecationWarning: jax.xla_computation is deprecated\n")
    rec, _ = capability.classify_probe_attempt(1, "", err)
    assert rec["fatal"] == (
        "DeprecationWarning: jax.xla_computation is deprecated"
    )


def test_cached_peak_follows_staleness_rule(monkeypatch):
    """cached_peak_gbps must refuse a pre-jax record once jax has
    appeared — the ledger's sampled gauges would otherwise divide an
    entire accelerator run by host-DRAM bandwidth."""
    stale = {"platform": "cpu", "device_kind": "cpu",
             "peak_GBps": 1.23, "source": "measured-host"}
    monkeypatch.setattr(capability, "_capability", stale)
    monkeypatch.setattr(capability, "_capability_saw_jax", False)
    assert capability.cached_peak_gbps() is None  # jax IS imported here
    monkeypatch.setattr(capability, "_capability_saw_jax", True)
    assert capability.cached_peak_gbps() == 1.23


def test_probe_classification_fatal_line_wins():
    err = ("WARNING: Platform 'axon' is experimental\n"
           "Traceback (most recent call last):\n"
           "RuntimeError: Unable to initialize backend 'axon'\n")
    rec, _ = capability.classify_probe_attempt(1, "", err)
    assert rec["classification"] == "no_devices"
    assert "Unable to initialize backend" in rec["fatal"]
    assert len(rec["warnings"]) == 1


def test_probe_classification_vocabulary():
    cases = [
        (0, "PLATFORMS=axon,cpu\n", "", "ok", ["axon", "cpu"]),
        (0, "PLATFORMS=cpu\n", "", "cpu_only", ["cpu"]),
        (0, "PLATFORM=cpu\n", "", "cpu_only", ["cpu"]),  # legacy form
        (capability.PROBE_TIMEOUT_RC, "", "", "init_timeout", []),
        # -1 is a SIGHUP'd child (subprocess reports -signum), NOT a
        # timeout — the sentinel collision the review caught
        (-1, "", "", "signal", []),
        (-15, "", "", "signal", []),
        (1, "", "ModuleNotFoundError: No module named 'jax'\n",
         "import_error", []),
        (1, "", "something exploded\n", "nonzero_exit", []),
        # clean exit, no platform evidence (the capture watcher never
        # sees the child's stdout): must NOT read "nonzero_exit"
        (0, "", "", "no_probe_output", []),
    ]
    for rc, out, err, want, want_platforms in cases:
        rec, platforms = capability.classify_probe_attempt(rc, out, err)
        assert rec["classification"] == want, (rc, out, err, rec)
        assert rec["classification"] in capability.PROBE_CLASSIFICATIONS
        assert platforms == want_platforms


def test_probe_timeout_sentinel_pinned_to_bench():
    """bench.py keeps a literal copy of the timeout sentinel (its parent
    stays stdlib-only at module scope) — the two must never drift."""
    path = os.path.join(os.path.dirname(__file__), "..", "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_sentinel", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod._TIMEOUT_RC == capability.PROBE_TIMEOUT_RC


def test_probe_report_schema_keys():
    rec, platforms = capability.classify_probe_attempt(
        capability.PROBE_TIMEOUT_RC, "", "boom\n"
    )
    rec["attempt"] = 1
    rec["seconds"] = 1.5
    assert set(rec) == set(capability.PROBE_ATTEMPT_KEYS)
    report = capability.build_probe_report(
        [rec], platforms, ok=False, reason="init_timeout", elapsed_s=12.3
    )
    assert set(report) == set(capability.PROBE_REPORT_KEYS)
    assert report["ok"] is False and report["reason"] == "init_timeout"


# -- MULTICHIP evidence contract ----------------------------------------------

def _graft():
    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_multichip_evidence_extraction_and_validation():
    ge = _graft()
    good = {
        "devices": [{"id": 0, "platform": "cpu", "kind": "cpu"}],
        "boundary_exchange": {
            "per_shard_cut_bytes": [128, 128],
            "cut_rows_sparse_bytes": 64,
            "cut_rows_dense_bytes": 256,
        },
    }
    import json

    stdout = "noise\nMULTICHIP_EVIDENCE " + json.dumps(good) + "\nok\n"
    assert ge._extract_evidence(stdout) == good
    assert ge._validate_evidence(good) is None
    # the r01–r05 blind spot, now a loud failure:
    assert ge._validate_evidence(None) is not None
    assert ge._validate_evidence({"devices": []}) is not None
    assert ge._validate_evidence(
        {"devices": [{"id": 0}], "boundary_exchange": {}}
    ) is not None
    # r13: sparse-vs-dense exchange accounting is part of the contract —
    # a record without the measured pair is the old claim-not-measure
    # shape and must fail validation
    assert ge._validate_evidence(
        {
            "devices": [{"id": 0}],
            "boundary_exchange": {"per_shard_cut_bytes": [128]},
        }
    ) is not None
    assert ge._extract_evidence("rc=0 but no evidence line\n") is None


def test_shard_cut_bytes_ring():
    from lasp_tpu.mesh.shard_gossip import shard_cut_bytes
    from lasp_tpu.mesh.topology import ring

    out = shard_cut_bytes(ring(16, 2), 4, row_bytes=8)
    # ring k=2 (offsets +1/-1): each 4-row block's first and last rows
    # are referenced by the adjacent blocks — 2 cut rows per shard
    assert out["per_shard_cut_rows"] == [2, 2, 2, 2]
    assert out["per_shard_cut_bytes"] == [16, 16, 16, 16]
    assert out["cut_rows"] == 8
    assert out["row_bytes"] == 8


def test_dryrun_inline_emits_evidence():
    """The 2-device inline dry-run must return a record that PASSES the
    parent's validation — the contract that turns `{ok: true,
    tail: ""}` into per-device evidence."""
    ge = _graft()
    ev = ge._dryrun_inline(2)
    assert ge._validate_evidence(ev) is None
    assert len(ev["devices"]) == 2
    be = ev["boundary_exchange"]
    assert len(be["per_shard_cut_bytes"]) == 2
    assert all(b >= 0 for b in be["per_shard_cut_bytes"])
    assert be["alltoall_bytes_per_round"] > 0
    assert ev["tiers"]["packed_converge_rounds"] >= 1
    assert ev["tiers"]["partitioned_converge_rounds"] >= 1
    # r13 tiers: the sharded frontier ran and measured its exchange
    assert ev["tiers"]["sharded_frontier_rounds"] >= 1
    assert ev["tiers"]["hier_converge_rounds"] >= 1
    assert be["cut_rows_sparse_bytes"] > 0
    assert be["cut_rows_dense_bytes"] > 0


def test_shard_exchange_traffic_family():
    """The sparse partitioned exchange's analytic family: bytes scale
    with the PAYLOAD (2x on the wire) plus the joined rows, never the
    population — and the family is priced per stacked group width."""
    from lasp_tpu.telemetry.roofline import kernel_traffic

    one = kernel_traffic(
        "shard_exchange", row_bytes=64, n_replicas=1 << 20, fanout=3,
        rows=128, exchange_rows=512, g_active=1,
    )
    # payload crosses twice + (K+2) moves per joined row
    assert one.bytes_moved == (2 * 128 + 5 * 512) * 64
    assert one.joins == 512 * 3
    grp = kernel_traffic(
        "shard_exchange", row_bytes=64, n_replicas=1 << 20, fanout=3,
        rows=128, exchange_rows=512, g_active=4,
    )
    assert grp.bytes_moved == 4 * one.bytes_moved
    assert one.xla_lo <= one.bytes_moved <= one.xla_hi
    # population-independent: the same payload at 8x the population
    # moves the same bytes (the whole point of the sparse exchange)
    big = kernel_traffic(
        "shard_exchange", row_bytes=64, n_replicas=1 << 23, fanout=3,
        rows=128, exchange_rows=512, g_active=1,
    )
    assert big.bytes_moved == one.bytes_moved


# -- bench arm roofline -------------------------------------------------------

def test_headline_arms_carry_roofline():
    from lasp_tpu.bench_scenarios import orset_anti_entropy

    out = orset_anti_entropy(256, block=4, timing_reps=1)
    assert out["roofline_GBps"] and out["roofline_GBps"] > 0
    arms = out["impl_roofline"]
    assert set(arms) == {
        k for k, v in out["impl_block_seconds"].items()
        if isinstance(v, float)
    }
    for arm, fig in arms.items():
        assert fig["achieved_GBps"] > 0, (arm, fig)
        assert fig["roofline_frac"] is not None and fig["roofline_frac"] > 0


def test_profile_capture_writes_trace(tmp_path):
    from lasp_tpu.telemetry import capture_scenario

    out, trace_dir = capture_scenario(
        lambda: int(jnp.sum(jnp.arange(8))), log_dir=str(tmp_path / "t")
    )
    assert out == 28
    assert os.path.isdir(trace_dir)
    files = [
        os.path.join(dp, f)
        for dp, _dn, fn in os.walk(trace_dir) for f in fn
    ]
    assert files, "profiler trace produced no files"


def test_cli_roofline_verb(tmp_path, capsys):
    from lasp_tpu.cli import main as cli_main

    export = str(tmp_path / "roof.json")
    rc = cli_main(["roofline", "--replicas", "16", "--rounds", "1",
                   "--export", export])
    assert rc == 0
    import json

    with open(export) as f:
        payload = json.load(f)
    assert payload["capability"]["peak_GBps"] > 0
    assert payload["kernels"], "export carries no kernel rows"
    text = capsys.readouterr().out
    assert "KERNEL" in text and "ROOF%" in text
