"""Telemetry-overhead guard (slow): the always-on registry/span layer
must cost < 5% of the gossip step path — the 'cheap enough to always be
on' contract, measured with the same helper bench.py embeds in its
artifact (see telemetry/overhead.py for the noise-robust methodology)."""

import pytest

from lasp_tpu.telemetry.overhead import measure_overhead


@pytest.mark.slow
def test_telemetry_overhead_under_5_percent():
    out = measure_overhead()
    assert out["step_seconds"] > 0
    assert out["telemetry_cost_per_step_s"] >= 0
    assert out["overhead_frac"] < 0.05, out
    # grouped-dispatch arm: the planned frontier round over a
    # many-small-vars store (the megabatch regime) must keep its O(vars)
    # emission loop under the same budget — per-var gauge sets are
    # amortized to pre-resolved instruments + skip-if-unchanged
    assert out["frontier"]["round_seconds"] > 0
    assert out["frontier"]["overhead_frac"] < 0.05, out["frontier"]
    # kernel-cost-ledger arm: one ledger.record per dispatch (its
    # timing fences reuse the dispatch's own sync) must stay under the
    # budget on BOTH the dense step (1 record/round) and the planned
    # frontier round (1 record per group dispatch)
    assert out["ledger"]["cost_per_record_s"] >= 0
    assert out["ledger"]["dense_overhead_frac"] < 0.05, out["ledger"]
    assert out["ledger"]["frontier_overhead_frac"] < 0.05, out["ledger"]
    # fused-propagate arm (the ISSUE-8 hot path): one megakernel
    # dispatch per propagate, priced against its whole emission path —
    # span + counters + the summarizing `propagate` event with per-dst
    # changed counts + the `dataflow_fused` ledger record
    assert out["dataflow"]["propagate_seconds"] > 0
    assert out["dataflow"]["emission_cost_per_propagate_s"] >= 0
    assert out["dataflow"]["overhead_frac"] < 0.05, out["dataflow"]
    # incremental-rehash arm (the AAE tentpole's per-round hook): the
    # steady-state HashForest.refresh — quiescent vars and clean
    # segments cost nothing — priced against an active frontier round;
    # the dirty-row and full-rebuild figures ride in the artifact as
    # the incremental-vs-full comparison
    assert out["aae"]["round_seconds"] > 0
    assert out["aae"]["refresh_cost_quiescent_s"] >= 0
    assert out["aae"]["overhead_frac"] < 0.05, out["aae"]
    assert out["aae"]["full_rebuild_seconds"] > 0
    # flight-recorder arm (the in-graph-counters tentpole): the fused
    # window's ride-along stats ring (in-graph write per round) PLUS
    # the per-window host drain (decode + monitor feed + per-round
    # delivery events + window-log append) must together stay under
    # the budget against the fused window itself
    assert out["flight"]["window_seconds"] > 0
    assert out["flight"]["ring_write_cost_per_window_s"] >= 0
    assert out["flight"]["drain_cost_per_window_s"] >= 0
    assert out["flight"]["overhead_frac"] < 0.05, out["flight"]
