"""Span nesting/ordering, ring bounding, error capture, and the JSONL
sink."""

import json

import pytest

from lasp_tpu.telemetry import spans as S
from lasp_tpu.telemetry import span


@pytest.fixture(autouse=True)
def _fresh_ring():
    S.clear()
    yield
    S.clear()


def test_nesting_paths_and_completion_order():
    with span("gossip.round"):
        with span("merge.orswot"):
            pass
        with span("merge.orset"):
            pass
    evs = S.events()
    assert [e["name"] for e in evs] == [
        "merge.orswot", "merge.orset", "gossip.round",
    ]  # children finish (and record) before their parent
    assert evs[0]["path"] == "gossip.round>merge.orswot"
    assert evs[1]["path"] == "gossip.round>merge.orset"
    assert evs[2]["path"] == "gossip.round"
    assert all(e["seconds"] >= 0 for e in evs)


def test_stack_unwinds_after_exception():
    with pytest.raises(RuntimeError):
        with span("outer"):
            with span("inner"):
                raise RuntimeError("boom")
    # both spans recorded, durations kept, error type stamped
    evs = {e["name"]: e for e in S.events()}
    assert evs["inner"]["error"] == "RuntimeError"
    assert evs["outer"]["error"] == "RuntimeError"
    # and the thread-local stack fully unwound: a fresh span is a root
    with span("fresh"):
        pass
    assert S.events()[-1]["path"] == "fresh"


def test_ring_is_bounded():
    S.configure(ring_size=4)
    try:
        for i in range(10):
            with span(f"s{i}"):
                pass
        names = [e["name"] for e in S.events()]
        assert names == ["s6", "s7", "s8", "s9"]  # oldest dropped
    finally:
        S.configure(ring_size=S.DEFAULT_RING_SIZE)


def test_attrs_ride_along():
    with span("mesh.update_batch", type="lasp_orset", ops=3):
        pass
    ev = S.events()[-1]
    assert ev["attrs"] == {"type": "lasp_orset", "ops": 3}


def test_jsonl_sink(tmp_path):
    path = str(tmp_path / "events.jsonl")
    S.configure(jsonl_path=path)
    try:
        with span("a"):
            with span("b"):
                pass
    finally:
        S.configure(jsonl_path="")  # close + disable
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert [x["name"] for x in lines] == ["b", "a"]
    assert lines[0]["kind"] == "span"


def test_disabled_spans_record_nothing():
    from lasp_tpu.telemetry import registry as R

    prev = R.enabled()
    try:
        R.set_enabled(False)
        with span("ghost"):
            pass
        assert not any(e["name"] == "ghost" for e in S.events())
    finally:
        R.set_enabled(prev)
