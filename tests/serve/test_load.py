"""Open-loop load harness: overload sheds typed, deadlines cancel,
chaos + burst stay correct (no acked write lost), and the serve_load
artifact keeps its interpretable shape."""

import pytest

from lasp_tpu.serve.harness import composite_nemesis, run_load


def test_small_run_steady_state():
    rep = run_load(n_replicas=12, n_clients=200, ticks=6, n_vars=3,
                   arrivals_per_tick=50, seed=3, seed_watches=40)
    assert rep["no_write_lost"] is True
    offered = sum(rep["offered"].values())
    terminal = (
        sum(rep["completed"].values()) + sum(rep["errors"].values())
        + sum(rep["expired"].values()) + sum(rep["shed"].values())
    )
    # never a silent drop: every offered request reaches a typed
    # terminal outcome (standing watches may stay parked past the run)
    assert offered == terminal + rep["watch_parked_final"]
    assert rep["rates"]["offered_per_tick"] == pytest.approx(
        offered / 6, abs=0.01
    )
    assert rep["latency_ticks"]["write"]["p50"] is not None
    assert rep["acked_writes"] > 0
    assert rep["max_inflight"] >= 40  # the standing watch floor


def test_burst_sheds_typed_and_ladder_climbs():
    rep = run_load(
        n_replicas=12, n_clients=200, ticks=9, n_vars=3,
        arrivals_per_tick=70,
        capacity={"write": 128, "read": 128, "watch": 128},
        burst_at=3, burst_ticks=3, burst_factor=6, seed=5,
    )
    assert sum(rep["shed"].values()) > 0  # overload shed something
    assert all(":" in k for k in rep["shed"])  # typed (kind:reason)
    assert rep["ladder"]["max_level"] >= 1
    assert rep["client_retries"] > 0  # clients honored retry_after_ms
    # bounded queues: the high-water marks never exceed capacity
    assert all(hw <= 128 for hw in rep["queue_high_water"].values())
    assert rep["no_write_lost"] is True


def test_chaos_run_keeps_acked_writes_and_heals():
    rep = run_load(n_replicas=16, n_clients=150, ticks=13, n_vars=3,
                   arrivals_per_tick=40, chaos=True, seed=9,
                   parity_thresholds=512)
    assert rep["no_write_lost"] is True
    assert rep["chaos"]["healed"] and rep["chaos"]["crashes"] == 2
    assert rep["threshold_parity"]["parity"] is True


def test_composite_nemesis_shape():
    from lasp_tpu.chaos import Crash, Restore
    from lasp_tpu.mesh.topology import random_regular

    nbrs = random_regular(24, 3, seed=2)
    sched = composite_nemesis(24, nbrs, seed=2, rounds=12)
    crashes = [e for e in sched.events if isinstance(e, Crash)]
    restores = [e for e in sched.events if isinstance(e, Restore)]
    assert len(crashes) == 2 and len(restores) == 2
    # victims non-adjacent (the W=2 durability precondition)
    v = sorted(c.replica for c in crashes)
    gap = (v[1] - v[0]) % 24
    assert gap not in (1, 23)
    # staggered: each restore lands before the next crash
    assert crashes[1].at > restores[0].at
    # crashes land in link-clean rounds (after the fault windows close)
    link_stop = max(e.stop for e in sched.events
                    if hasattr(e, "stop"))
    assert all(c.at >= link_stop + 2 for c in crashes)


def test_deadlines_expire_under_pressure():
    rep = run_load(
        n_replicas=12, n_clients=100, ticks=8, n_vars=3,
        arrivals_per_tick=60,
        capacity={"write": 64, "read": 64, "watch": 64},
        burst_at=3, burst_ticks=4, burst_factor=8,
        deadline_ticks=2, seed=11,
    )
    # with 2-tick deadlines under an 8x burst, some queued work expired
    # and was cancelled instead of executed
    assert sum(rep["expired"].values()) > 0
    assert rep["no_write_lost"] is True


@pytest.mark.slow
def test_acceptance_scale_10k_clients_burst_chaos():
    """The acceptance gate at full scale: >= 10k concurrent simulated
    clients (write+read+watch mix, gossip concurrent), composite
    nemesis + 5x overload burst — typed sheds with retry-after
    accounting, bounded queues, p50/p99 reported, zero acked writes
    lost, and 100k-threshold vectorized parity."""
    rep = run_load(
        n_replicas=64, n_clients=10_000, ticks=40,
        arrivals_per_tick=1200, chaos=True,
        burst_at=20, burst_ticks=5, burst_factor=5,
        seed_watches=10_000, parity_thresholds=100_000, seed=7,
    )
    assert rep["max_inflight"] >= 10_000
    assert rep["no_write_lost"] is True
    assert rep["threshold_parity"]["parity"] is True
    assert rep["threshold_parity"]["n_thresholds"] >= 100_000
    assert sum(rep["shed"].values()) > 0
    assert rep["latency_ticks"]["write"]["p99"] is not None
    caps = rep["queue_high_water"]
    assert all(hw <= 8192 for hw in caps.values())


def test_same_seed_runs_are_replay_identical():
    """The determinism satellite: each simulated client's RNG seeds
    from (run seed, client id), and the admission drain-rate EWMA runs
    on the simulated tick clock — two same-seed runs therefore produce
    IDENTICAL per-tick offered/shed/outcome traces (and a different
    seed produces a different one: the witness is not vacuous)."""
    kwargs = dict(
        n_replicas=12, n_clients=200, ticks=6, n_vars=3,
        arrivals_per_tick=60, seed_watches=20,
        capacity={"write": 96, "read": 96, "watch": 96},
        burst_at=2, burst_ticks=2, burst_factor=6,
        record_trace=True,
    )
    r1 = run_load(seed=3, **kwargs)
    r2 = run_load(seed=3, **kwargs)
    assert r1["trace"] and r1["trace"] == r2["trace"]
    for key in ("offered", "completed", "errors", "expired", "shed",
                "latency_ticks", "client_retries", "client_gave_up",
                "queue_high_water", "acked_writes"):
        assert r1[key] == r2[key], key
    r3 = run_load(seed=4, **kwargs)
    assert r3["trace"] != r1["trace"]


def test_client_seed_is_pure_in_run_seed_and_client():
    from lasp_tpu.serve.harness import client_seed

    assert client_seed(7, 3) == client_seed(7, 3)
    assert client_seed(7, 3) != client_seed(7, 4)
    assert client_seed(7, 3) != client_seed(8, 3)
