"""Vectorized threshold fan-out: parity vs the per-watch reference
across lattice types and threshold shapes, fire-exactly-once under
concurrent writers, and watch survival across population surgery
(resize / checkpoint restore)."""

import threading

import numpy as np
import pytest

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import Threshold
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.serve import SubscriptionTable
from lasp_tpu.store import Store

R = 8


def build_rt(**declares):
    store = Store(n_actors=32)
    for vid, (tname, caps) in declares.items():
        store.declare(id=vid, type=tname, **caps)
    rt = ReplicatedRuntime(store, Graph(store), R, ring(R, 2))
    return store, rt


def accessors(store, rt):
    def pop_of(v):
        return rt._to_dense_row(v, rt._population(v))

    def meta_of(v):
        var = store.variable(v)
        return var.codec, var.spec

    return pop_of, meta_of


def register_everywhere(tables, var_id, store, thr, replica=0):
    var = store.variable(var_id)
    for t in tables:
        t.register(var_id, var.codec, var.spec, thr, replica=replica)


def assert_parity(store, rt, tables, var_ids=None):
    """Vectorized claims on table 0 must equal the per-watch reference
    verdicts on the identically-registered table 1."""
    pop_of, meta_of = accessors(store, rt)
    vec = {s for s, _ in tables[0].evaluate(pop_of, meta_of,
                                            var_ids=var_ids)}
    ref = {s for s, _ in tables[1].evaluate_pervar(
        pop_of, meta_of, var_ids=var_ids, claim=False
    )}
    assert vec == ref
    return vec


class TestParityAcrossCodecs:
    def test_gset_strict_and_nonstrict(self):
        store, rt = build_rt(g=("lasp_gset", {"n_elems": 16}))
        rt.update_at(2, "g", ("add", "a"), "w0")
        rt.update_at(2, "g", ("add", "b"), "w0")
        var = store.variable("g")
        bottom = var.codec.new(var.spec)
        has_a = var.codec.add(var.spec, bottom, var.elems.intern("a"))
        has_c = var.codec.add(var.spec, bottom, var.elems.intern("c"))
        tables = (SubscriptionTable(), SubscriptionTable())
        cases = [
            (Threshold(bottom, False), 2, True),   # bottom: met
            (Threshold(bottom, True), 2, True),    # strict past bottom
            (Threshold(has_a, False), 2, True),    # {a} <= {a,b}
            (Threshold(has_a, True), 2, True),     # strictly above {a}
            (Threshold(has_c, False), 2, False),   # c absent
            (Threshold(has_a, False), 0, False),   # replica 0 not written
        ]
        subs = []
        for thr, replica, _expect in cases:
            register_everywhere(tables, "g", store, thr, replica)
            subs.append(len(subs))
        fired = assert_parity(store, rt, tables)
        assert fired == {i for i, (_t, _r, want) in enumerate(cases)
                         if want}

    def test_orset_and_orswot_vclock_thresholds(self):
        store, rt = build_rt(
            o=("lasp_orset", {"n_elems": 8, "tokens_per_actor": 4}),
            w=("riak_dt_orswot", {"n_elems": 8}),
        )
        rt.update_at(1, "o", ("add", "x"), "a0")
        rt.update_at(1, "w", ("add", "x"), "a0")
        rt.update_at(1, "w", ("add", "y"), "a0")
        tables = (SubscriptionTable(), SubscriptionTable())
        for vid in ("o", "w"):
            var = store.variable(vid)
            bottom = var.codec.new(var.spec)
            register_everywhere(tables, vid, store,
                                Threshold(bottom, True), 1)
            register_everywhere(tables, vid, store,
                                Threshold(bottom, True), 0)  # unmet
        # a vclock threshold: the orswot's own written state demands
        # clock domination, met only where that state gossiped
        wstate = rt._to_dense_row(
            "w", __import__("jax").tree_util.tree_map(
                lambda x: x[1], rt._population("w")
            ),
        )
        register_everywhere(tables, "w", store, Threshold(wstate, False), 1)
        register_everywhere(tables, "w", store, Threshold(wstate, False), 3)
        fired = assert_parity(store, rt, tables)
        assert len(fired) == 3  # strict-bottom at r1 (x2), own-state at r1

    def test_gcounter_numeric_and_ivar_equality(self):
        store, rt = build_rt(
            c=("riak_dt_gcounter", {"n_actors": 8}),
            i=("lasp_ivar", {}),
        )
        for k in range(5):
            rt.update_at(3, "c", ("increment",), "a3")
        rt.update_at(2, "i", ("set", "ready"), "a0")
        tables = (SubscriptionTable(), SubscriptionTable())
        cvar, ivar = store.variable("c"), store.variable("i")
        cases = [
            ("c", Threshold(5, False), 3, True),    # 5 <= 5
            ("c", Threshold(5, True), 3, False),    # 5 < 5 fails
            ("c", Threshold(4, True), 3, True),
            ("c", Threshold(0, False), 0, True),    # bottom numeric
            ("c", Threshold(1, False), 0, False),   # replica 0 at 0
            # ivar: {strict, undefined} = became defined
            ("i", Threshold(ivar.codec.new(ivar.spec), True), 2, True),
            ("i", Threshold(ivar.codec.new(ivar.spec), True), 0, False),
        ]
        for vid, thr, replica, _want in cases:
            register_everywhere(tables, vid, store, thr, replica)
        fired = assert_parity(store, rt, tables)
        assert fired == {i for i, c in enumerate(cases) if c[3]}

    def test_map_thresholds_ride_the_default_kernel(self):
        store, rt = build_rt(
            m=("riak_dt_map", {"fields": [
                ("s", "lasp_gset", {"n_elems": 4}),
            ]}),
        )
        rt.update_at(4, "m", ("update", "s", ("add", "k")), "w0")
        var = store.variable("m")
        tables = (SubscriptionTable(), SubscriptionTable())
        bottom = var.codec.new(var.spec)
        register_everywhere(tables, "m", store, Threshold(bottom, True), 4)
        register_everywhere(tables, "m", store, Threshold(bottom, True), 0)
        fired = assert_parity(store, rt, tables)
        assert len(fired) == 1


def test_mixed_threshold_structure_is_loud():
    store, rt = build_rt(g=("lasp_gset", {"n_elems": 8}),
                         c=("riak_dt_gcounter", {"n_actors": 8}))
    table = SubscriptionTable()
    gvar = store.variable("g")
    table.register("g", gvar.codec, gvar.spec,
                   Threshold(gvar.codec.new(gvar.spec), False))
    cvar = store.variable("c")
    with pytest.raises(TypeError, match="structure mismatch"):
        # a numeric threshold cannot join a state-threshold group
        table.register("g", gvar.codec, gvar.spec, Threshold(3, False))
    # distinct variables keep distinct groups: no cross-contamination
    table.register("c", cvar.codec, cvar.spec, Threshold(3, False))


def test_fire_exactly_once_under_concurrent_evaluators_and_writers():
    """Two threads evaluating while writers keep inflating the variable:
    every fired sub_id is claimed exactly once across ALL passes."""
    store, rt = build_rt(c=("riak_dt_gcounter", {"n_actors": 8}))
    pop_of, meta_of = accessors(store, rt)
    table = SubscriptionTable()
    cvar = store.variable("c")
    n = 600
    for i in range(n):
        table.register("c", cvar.codec, cvar.spec,
                       Threshold(1 + (i % 20), False), replica=i % R,
                       payload=i)
    fired: list = []
    fired_lock = threading.Lock()
    stop = threading.Event()

    def evaluator():
        while not stop.is_set():
            hits = table.evaluate(pop_of, meta_of)
            with fired_lock:
                fired.extend(hits)

    threads = [threading.Thread(target=evaluator) for _ in range(2)]
    for t in threads:
        t.start()
    for k in range(25):
        rt.update_batch("c", [(r, ("increment",), f"a{r}")
                              for r in range(R)])
    stop.set()
    for t in threads:
        t.join()
    fired.extend(table.evaluate(pop_of, meta_of))  # final sweep
    ids = [s for s, _p in fired]
    assert len(ids) == len(set(ids)), "a watch fired twice"
    # every threshold <= 20 is met at every replica (25 rounds of +1)
    assert len(ids) == n


def test_watches_survive_resize_by_rehoming():
    """A watch homed on a replica a shrink removed re-homes to the last
    surviving row instead of dying or crashing."""
    store, rt = build_rt(g=("lasp_gset", {"n_elems": 8}))
    pop_of, meta_of = accessors(store, rt)
    table = SubscriptionTable()
    gvar = store.variable("g")
    bottom = gvar.codec.new(gvar.spec)
    sid = table.register("g", gvar.codec, gvar.spec,
                         Threshold(bottom, True), replica=R - 1,
                         payload="park")
    assert table.evaluate(pop_of, meta_of) == []
    rt.resize(4, ring(4, 2))  # the watch's home row is gone
    assert table.evaluate(pop_of, meta_of) == []  # clamped, still parked
    rt.update_at(3, "g", ("add", "k"), "w0")  # the clamp target row
    assert table.evaluate(pop_of, meta_of) == [(sid, "park")]


def test_watches_survive_checkpoint_restore(tmp_path):
    """A checkpoint restore replaces the population; parked watches
    keep evaluating against the restored rows and fire when the
    restored state meets them."""
    from lasp_tpu.store.checkpoint import load_runtime_rows, save_runtime

    store, rt = build_rt(g=("lasp_gset", {"n_elems": 8}))
    pop_of, meta_of = accessors(store, rt)
    rt.update_at(2, "g", ("add", "k"), "w0")
    path = str(tmp_path / "ckpt")
    save_runtime(rt, path)

    table = SubscriptionTable()
    gvar = store.variable("g")
    bottom = gvar.codec.new(gvar.spec)
    sid = table.register("g", gvar.codec, gvar.spec,
                         Threshold(bottom, True), replica=5,
                         payload="park")
    assert table.evaluate(pop_of, meta_of) == []  # row 5 still bottom
    rt.reseed_row(5, load_runtime_rows(path, 2))  # restore row 2 -> 5
    assert table.evaluate(pop_of, meta_of) == [(sid, "park")]


def test_deadline_expiry_cancels_without_executing():
    store, rt = build_rt(c=("riak_dt_gcounter", {"n_actors": 8}))
    pop_of, meta_of = accessors(store, rt)
    table = SubscriptionTable()
    cvar = store.variable("c")
    sid = table.register("c", cvar.codec, cvar.spec, Threshold(1, False),
                         deadline=10.0, payload="due")
    keep = table.register("c", cvar.codec, cvar.spec, Threshold(1, False),
                          payload="keep")
    assert table.expire(now=9.0) == []
    assert table.expire(now=11.0) == [(sid, "due")]
    rt.update_at(0, "c", ("increment",), "a0")
    # the expired watch can never fire; the undated one still does
    assert table.evaluate(pop_of, meta_of) == [(keep, "keep")]


def test_cancel_and_len():
    store, rt = build_rt(c=("riak_dt_gcounter", {"n_actors": 8}))
    table = SubscriptionTable()
    cvar = store.variable("c")
    sid = table.register("c", cvar.codec, cvar.spec, Threshold(1, False),
                         payload="p")
    assert len(table) == 1
    assert table.cancel(sid) == "p"
    assert table.cancel(sid) is None  # idempotent
    assert len(table) == 0


def test_unknown_threshold_override_falls_back_to_pervar():
    """A codec with custom threshold_met semantics the vectorized pass
    does not know must fall back to the reference path (counted), never
    silently evaluate the wrong rule."""
    from lasp_tpu.lattice.gset import GSet

    class WeirdSet(GSet):
        name = "weird_set"

        @classmethod
        def threshold_met(cls, spec, state, threshold):
            import jax.numpy as jnp

            return jnp.asarray(True)  # always met, whatever the rule

    store, rt = build_rt(g=("lasp_gset", {"n_elems": 8}))
    pop_of, _ = accessors(store, rt)
    gvar = store.variable("g")
    table = SubscriptionTable()
    thr = Threshold(gvar.codec.new(gvar.spec), True)  # unmet under gset
    sid = table.register("g", WeirdSet, gvar.spec, thr, payload="w")
    fired = table.evaluate(pop_of, lambda v: (WeirdSet, gvar.spec))
    assert fired == [(sid, "w")]  # the override's verdict, not gset's
    assert table.pervar_fallbacks == 1


def test_retired_slots_compact_away():
    """Sustained register→fire churn must not grow a group without
    bound: once retired slots dominate, the group compacts, index
    entries re-point, and survivors keep firing."""
    store, rt = build_rt(c=("riak_dt_gcounter", {"n_actors": 8}))
    pop_of, meta_of = accessors(store, rt)
    table = SubscriptionTable()
    cvar = store.variable("c")
    rt.update_at(0, "c", ("increment", 5), "a0")
    for i in range(2000):
        table.register("c", cvar.codec, cvar.spec, Threshold(1, False),
                       payload=i)  # all met: fire + retire
    survivor = table.register("c", cvar.codec, cvar.spec,
                              Threshold(50, False), payload="keep")
    fired = table.evaluate(pop_of, meta_of)
    assert len(fired) == 2000 and len(table) == 1
    # churn a little more so the compaction trigger fires (the reclaim
    # happens at the NEXT table touch after retirements dominate)
    for i in range(200):
        table.register("c", cvar.codec, cvar.spec, Threshold(1, False))
    table.evaluate(pop_of, meta_of)  # fires + retires the churn
    table.evaluate(pop_of, meta_of)  # entry pass compacts
    group = table._groups["c"]
    assert group.cap <= 64, "retired slots were never reclaimed"
    # the survivor's index re-pointed correctly and still fires
    rt.update_at(0, "c", ("increment", 50), "a0")
    assert table.evaluate(pop_of, meta_of) == [(survivor, "keep")]


@pytest.mark.slow
def test_parity_at_100k_registered_thresholds():
    """The acceptance-scale claim: the tensorized pass agrees with the
    per-watch reference at >= 100k registered thresholds."""
    from lasp_tpu.serve.harness import threshold_parity

    store, rt = build_rt(c=("riak_dt_gcounter", {"n_actors": 64}))
    for i in range(40):
        rt.update_at(i % R, "c", ("increment",), f"a{i % R}")
    out = threshold_parity(rt, "c", 100_000, seed=11)
    assert out["parity"] and out["n_thresholds"] == 100_000


class TestShrinkRehoming:
    """Satellite: serve subscription re-homing under SHRINK — a watch
    parked on a departing replica re-homes to its CLAIM SUCCESSOR
    (``membership.plan.claim_targets`` rule) or expires typed; it never
    fires stale off a departed row's last state."""

    def _parked_watch(self, replica, payload="park"):
        store, rt = build_rt(g=("lasp_gset", {"n_elems": 8}))
        table = SubscriptionTable()
        gvar = store.variable("g")
        thr = Threshold(gvar.codec.new(gvar.spec), True)
        sid = table.register("g", gvar.codec, gvar.spec, thr,
                             replica=replica, payload=payload)
        return store, rt, table, sid

    def test_rehome_moves_watch_to_claim_successor(self):
        store, rt, table, sid = self._parked_watch(replica=6)
        rt.resize(4, ring(4, 2))
        res = table.rehome(4)
        assert res == {"rehomed": 1, "expired": []}
        pop_of, meta_of = accessors(store, rt)
        # the successor row (6 % 4 == 2) is the ONLY row that fires it
        rt.update_at(3, "g", ("add", "elsewhere"), "w0")
        assert table.evaluate(pop_of, meta_of) == []
        rt.update_at(2, "g", ("add", "home"), "w1")
        assert table.evaluate(pop_of, meta_of) == [(sid, "park")]

    def test_rehome_respects_custom_claim(self):
        _store, rt, table, _sid = self._parked_watch(replica=7)
        rt.resize(4, ring(4, 2))
        table.rehome(4, claim_of=lambda r: 1)
        group = table._groups["g"]
        slot = table._index[_sid][1]
        assert int(group.replica[slot]) == 1

    def test_expire_retires_typed_and_never_fires(self):
        store, rt, table, sid = self._parked_watch(replica=7,
                                                   payload="ticket")
        rt.resize(4, ring(4, 2), graceful=False)  # crash semantics
        res = table.rehome(4, expire=True)
        assert res["rehomed"] == 0
        assert res["expired"] == [(sid, "ticket")]
        assert len(table) == 0
        # even a write that would have met it cannot fire a claimed watch
        pop_of, meta_of = accessors(store, rt)
        rt.update_at(3, "g", ("add", "x"), "w0")
        assert table.evaluate(pop_of, meta_of) == []

    def test_surviving_watches_untouched(self):
        store, rt, table, sid = self._parked_watch(replica=1)
        rt.resize(4, ring(4, 2))
        res = table.rehome(4)
        assert res == {"rehomed": 0, "expired": []}
        pop_of, meta_of = accessors(store, rt)
        rt.update_at(1, "g", ("add", "k"), "w0")
        assert table.evaluate(pop_of, meta_of) == [(sid, "park")]

    def test_departed_watch_never_fires_from_departed_state(self):
        """Regression shape: the departing row's state met the watch,
        the claim successor's does not — after re-homing the watch
        stays parked (no stale fire off the dropped row)."""
        store, rt = build_rt(g=("lasp_gset", {"n_elems": 8}))
        table = SubscriptionTable()
        gvar = store.variable("g")
        rt.update_at(6, "g", ("add", "only-at-6"), "w0")
        # strict watch above bottom: met at row 6, not at its successor
        thr = Threshold(gvar.codec.new(gvar.spec), True)
        sid = table.register("g", gvar.codec, gvar.spec, thr,
                             replica=6, payload="p")
        rt.resize(4, ring(4, 2), graceful=False)  # row 6's state gone
        table.rehome(4)
        pop_of, meta_of = accessors(store, rt)
        assert table.evaluate(pop_of, meta_of) == []  # parked, not stale
