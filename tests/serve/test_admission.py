"""Admission control: bounded queues, typed shedding, and the
degradation ladder's transitions (docs/SERVING.md)."""

import pytest

from lasp_tpu.serve import AdmissionController, BoundedQueue, LADDER
from lasp_tpu.serve import requests as rq


def _ticket(kind, priority=rq.PRIO_NORMAL):
    return rq.Ticket(kind, "v", priority=priority)


def test_bounded_queue_refuses_at_capacity_and_tracks_high_water():
    q = BoundedQueue(3)
    assert all(q.offer(i) for i in range(3))
    assert not q.offer(99)  # full: refuse, never block or drop silently
    assert q.depth == 3 and q.high_water == 3
    assert q.drain(2) == [0, 1]
    assert q.drain(None) == [2]
    assert q.depth == 0 and q.high_water == 3  # high water sticks


def test_window_high_survives_a_full_drain():
    """The ladder's pressure signal is the intra-cycle high-water mark:
    a burst fully absorbed by the drain must still read as pressure."""
    q = BoundedQueue(4)
    for i in range(4):
        q.offer(i)
    q.drain(None)
    assert q.take_window() == 4  # saw a full queue since last window
    assert q.take_window() == 0  # reset to the (empty) current depth


def test_admit_and_queue_full_shed():
    ac = AdmissionController(capacity={"write": 2, "read": 2, "watch": 2})
    assert ac.admit(_ticket(rq.WRITE)) is None
    assert ac.admit(_ticket(rq.WRITE)) is None
    reason, retry_ms = ac.admit(_ticket(rq.WRITE))
    assert reason == "queue_full" and retry_ms >= ac.min_retry_ms
    # other classes are independently bounded
    assert ac.admit(_ticket(rq.READ)) is None


def test_ladder_climbs_immediately_and_descends_with_hysteresis():
    ac = AdmissionController(
        capacity={"write": 10, "read": 10, "watch": 10},
        enter=(0.5, 0.75, 0.9), exit=(0.3, 0.5, 0.7),
        hysteresis_cycles=2,
    )
    for _ in range(10):
        ac.queues["write"].offer(object())
    assert ac.observe_cycle(0.01, 0) == 3  # straight to reject_writes
    assert LADDER[ac.level] == "reject_writes"
    ac.queues["write"].drain(None)
    # descent is one rung at a time, only after sustained calm: the
    # window residue of the full cycle still reads as pressure once,
    # then two calm cycles per rung
    assert ac.observe_cycle(0.01, 10) == 3
    assert ac.observe_cycle(0.01, 0) == 3
    assert ac.observe_cycle(0.01, 0) == 2
    assert ac.observe_cycle(0.01, 0) == 2
    assert ac.observe_cycle(0.01, 0) == 1
    # the transition log records every move
    levels = [(old, new) for _c, old, new, _p in ac.transitions]
    assert levels == [(0, 3), (3, 2), (2, 1)]


def test_rung1_sheds_low_priority_reads_only():
    ac = AdmissionController(capacity={"write": 10, "read": 10, "watch": 10})
    for _ in range(6):
        ac.queues["read"].offer(object())
    assert ac.observe_cycle(0.01, 0) == 1
    refusal = ac.admit(_ticket(rq.READ, priority=rq.PRIO_LOW))
    assert refusal is not None and refusal[0] == "shed_low_priority"
    assert ac.admit(_ticket(rq.READ, priority=rq.PRIO_NORMAL)) is None
    assert ac.admit(_ticket(rq.WRITE)) is None  # writes unaffected


def test_rung3_rejects_writes_but_serves_reads():
    ac = AdmissionController(capacity={"write": 4, "read": 10, "watch": 10})
    for _ in range(4):
        ac.queues["write"].offer(object())
    assert ac.observe_cycle(0.01, 0) == 3
    ac.queues["write"].drain(None)
    refusal = ac.admit(_ticket(rq.WRITE))
    assert refusal is not None and refusal[0] == "writes_rejected"
    assert ac.admit(_ticket(rq.READ)) is None  # readers still served


def test_coalesce_multiplier_widens_at_rung2():
    ac = AdmissionController(capacity={"write": 10, "read": 10, "watch": 10},
                             widen_factor=8)
    assert ac.coalesce_multiplier() == 1
    for _ in range(8):
        ac.queues["write"].offer(object())
    ac.observe_cycle(0.01, 0)
    assert ac.level >= 2
    assert ac.coalesce_multiplier() == 8


def test_retry_after_tracks_backlog_and_drain_rate():
    ac = AdmissionController(capacity={"write": 100, "read": 10, "watch": 10},
                             min_retry_ms=5, max_retry_ms=2000)
    # no drain rate yet: worst-case hint
    assert ac.retry_after_ms("write") == 2000
    # 50 drained in 0.1s => 500/s; 20 queued => ~40ms
    ac.observe_cycle(0.1, 50)
    for _ in range(20):
        ac.queues["write"].offer(object())
    est = ac.retry_after_ms("write")
    assert 5 <= est <= 2000
    assert 20 <= est <= 100  # ballpark of depth/rate


def test_probe_is_the_bridge_door():
    ac = AdmissionController(capacity={"write": 1, "read": 1, "watch": 1})
    assert ac.probe("write") is None
    ac.queues["write"].offer(object())
    assert isinstance(ac.probe("write"), int)
    assert ac.probe("read") is None


def test_config_validation_is_loud():
    with pytest.raises(TypeError):
        AdmissionController(capacity={"writes": 1})  # typo'd class
    with pytest.raises(ValueError):
        AdmissionController(enter=(0.5, 0.7, 0.9), exit=(0.6, 0.5, 0.7))


def test_ticket_terminal_transitions_are_exactly_once():
    t = _ticket(rq.WRITE)
    assert t.complete("r", 1.0)
    assert not t.fail("nope", 2.0)  # first terminal wins
    assert t.status == "done" and t.result == "r"
    assert t.latency() == 1.0
