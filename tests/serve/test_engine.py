"""ServeFrontend: coalesced-vs-sequential bit-identity, deadline
cancellation, chaos routing + ack durability, the async fused-window
handle, and the threaded serve loop."""

import threading

import numpy as np
import pytest

from lasp_tpu.chaos.invariants import fingerprint, snapshot_states
from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import Threshold
from lasp_tpu.mesh import ReplicatedRuntime
from lasp_tpu.mesh.topology import ring
from lasp_tpu.serve import AdmissionController, ServeFrontend, ServeLoop
from lasp_tpu.store import Store

R = 12


def build_rt(n=R, **declares):
    store = Store(n_actors=64)
    if not declares:
        declares = {
            "kv": ("lasp_gset", {"n_elems": 64}),
            "os": ("lasp_orset", {"n_elems": 32, "tokens_per_actor": 4}),
            "ctr": ("riak_dt_gcounter", {"n_actors": 64}),
        }
    for vid, (tname, caps) in declares.items():
        store.declare(id=vid, type=tname, **caps)
    return store, ReplicatedRuntime(store, Graph(store), n, ring(n, 2))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_coalesced_cycle_is_bit_identical_to_sequential_update_at():
    rng = np.random.RandomState(3)
    requests = []
    for i in range(120):
        kind = i % 3
        r = int(rng.randint(R))
        if kind == 0:
            requests.append(("kv", ("add", f"k{int(rng.randint(30))}"),
                             f"c{i}", r))
        elif kind == 1:
            requests.append(("os", ("add", f"e{int(rng.randint(16))}"),
                             f"c{i}", r))
        else:
            requests.append(("ctr", ("increment", 2), f"a{r}", r))
    _s1, rt_seq = build_rt()
    for var, op, actor, r in requests:
        rt_seq.update_at(r, var, op, actor)
    _s2, rt_co = build_rt()
    fe = ServeFrontend(rt_co, gossip_block=0, write_backup=False)
    for var, op, actor, r in requests:
        fe.submit_write(var, op, actor, replica=r)
    fe.cycle()
    assert fingerprint(snapshot_states(rt_seq)) == fingerprint(
        snapshot_states(rt_co)
    )


def test_acks_record_witness_terms_and_survive_single_crash():
    """An acked add is replicated to a backup row before the ack: a
    crash + bottom restore of the written row cannot lose it."""
    from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Restore
    from lasp_tpu.chaos.invariants import check_no_write_lost
    from lasp_tpu.mesh.topology import ring as ring_topo

    store, rt = build_rt()
    nbrs = ring_topo(R, 2)
    sched = ChaosSchedule(R, nbrs, [Crash(2, 4), Restore(6, 4)], seed=1)
    ch = ChaosRuntime(rt, sched)
    fe = ServeFrontend(ch, chaos_mode="dense")
    t = fe.submit_write("kv", ("add", "precious"), "c0", replica=4)
    fe.cycle()  # applies at row 4, replicates to row 5, acks
    assert t.status == "done"
    assert fe.acked_terms["kv"] == {"precious"}
    for _ in range(10):
        fe.cycle()  # rides through crash(4) + bottom restore
    assert not ch.crashed.any()
    rt.run_to_convergence(max_rounds=256)
    check_no_write_lost(rt, fe.acked_terms)


def test_writes_to_crashed_replicas_are_rerouted_not_refused():
    from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Restore
    from lasp_tpu.mesh.topology import ring as ring_topo

    store, rt = build_rt()
    sched = ChaosSchedule(R, ring_topo(R, 2),
                          [Crash(0, 7), Restore(8, 7)], seed=1)
    ch = ChaosRuntime(rt, sched)
    fe = ServeFrontend(ch)
    fe.cycle()  # round 0: replica 7 crashes
    assert ch.crashed[7]
    t = fe.submit_write("kv", ("add", "x"), "c0", replica=7)
    fe.cycle()
    assert t.status == "done"
    assert t.result["replica"] != 7  # the preflist routed around it
    # the crashed row itself holds nothing
    assert "x" in rt.replica_value("kv", t.result["replica"])


def test_lane_minting_writes_to_crashed_replicas_fail_typed():
    """A counter increment (or OR-Set add) targeting a crashed replica
    must NOT reroute: the client's actor lane minted at a second row
    would max-merge away an acked increment. It fails typed instead."""
    from lasp_tpu.chaos import ChaosRuntime, ChaosSchedule, Crash, Restore
    from lasp_tpu.mesh.topology import ring as ring_topo

    store, rt = build_rt()
    sched = ChaosSchedule(R, ring_topo(R, 2),
                          [Crash(0, 7), Restore(8, 7)], seed=1)
    ch = ChaosRuntime(rt, sched)
    fe = ServeFrontend(ch)
    fe.cycle()  # replica 7 crashes
    t_ctr = fe.submit_write("ctr", ("increment",), "a7", replica=7)
    t_os = fe.submit_write("os", ("add", "x"), "a7", replica=7)
    t_set = fe.submit_write("kv", ("add", "y"), "c0", replica=7)
    fe.cycle()
    assert t_ctr.status == "error" and "mints actor lanes" in t_ctr.error
    assert t_os.status == "error"
    # the non-minting gset add in the SAME cycle still rerouted fine
    assert t_set.status == "done" and t_set.result["replica"] != 7


def test_bad_requests_fail_typed_without_killing_the_cycle():
    """Per-request isolation: an unknown variable or malformed
    threshold fails its own ticket; everyone else's work resolves."""
    store, rt = build_rt()
    fe = ServeFrontend(rt, gossip_block=0)
    bad_read = fe.submit_read("no_such_var")
    good = fe.submit_write("os", ("add", "x"), "c0")
    bad_watch = fe.submit_watch("also_missing", Threshold(1))
    bad_op = fe.submit_write("kv", ("frobnicate", "x"), "c0")
    fe.cycle()
    assert bad_read.status == "error" and "KeyError" in bad_read.error
    assert bad_watch.status == "error"
    assert bad_op.status == "error"
    # a failing op fails ITS variable's coalesced group; other groups
    # in the same cycle still resolve, and the CYCLE survives
    assert good.status == "done"
    t2 = fe.submit_write("kv", ("add", "z"), "c1")
    fe.cycle()
    assert t2.status == "done"


def test_deadline_expired_work_is_cancelled_not_executed():
    clock = FakeClock()
    store, rt = build_rt()
    fe = ServeFrontend(rt, gossip_block=0, clock=clock)
    # a write whose deadline passes while queued is never applied
    t_w = fe.submit_write("kv", ("add", "late"), "c0", deadline=5.0)
    t_r = fe.submit_read("kv", deadline=5.0)
    clock.t = 6.0
    fe.cycle()
    assert t_w.status == "expired" and t_r.status == "expired"
    assert "late" not in rt.coverage_value("kv")
    assert fe.expired["write"] == 1 and fe.expired["read"] == 1
    # a parked watch expires at its deadline too
    t_watch = fe.submit_watch("ctr", Threshold(100), deadline=8.0)
    fe.cycle()
    assert t_watch.status == "queued"  # parked
    clock.t = 9.0
    fe.cycle()
    assert t_watch.status == "expired"


def test_threshold_read_parks_then_fires_with_value():
    store, rt = build_rt()
    fe = ServeFrontend(rt, gossip_block=0)
    t = fe.submit_read("kv", Threshold(None, strict=True), replica=2)
    fe.cycle()
    assert t.status == "queued"  # parked: nothing written yet
    fe.submit_write("kv", ("add", "hello"), "c0", replica=2)
    fe.cycle()
    assert t.status == "done"
    assert t.result == frozenset({"hello"})


def test_shed_tickets_carry_retry_after_and_accounting():
    store, rt = build_rt()
    fe = ServeFrontend(
        rt, gossip_block=0,
        admission=AdmissionController(
            capacity={"write": 4, "read": 4, "watch": 4},
        ),
    )
    sheds = []
    for i in range(10):
        t = fe.submit_write("kv", ("add", f"k{i}"), "c0")
        if t.status == "shed":
            sheds.append(t)
    assert len(sheds) == 6
    assert all(t.retry_after_ms > 0 for t in sheds)
    assert all(t.error == "queue_full" for t in sheds)
    rep = fe.report()
    assert rep["shed"] == {"write:queue_full": 6}
    # nothing silently dropped: offered == terminal + queued
    fe.drain()
    rep = fe.report()
    assert rep["offered"]["write"] == (
        rep["completed"]["write"] + 6
    )


def test_ladder_rung2_widens_the_coalesce_window():
    store, rt = build_rt()
    ac = AdmissionController(capacity={"write": 64, "read": 8, "watch": 8},
                             widen_factor=4)
    fe = ServeFrontend(rt, gossip_block=0, coalesce_max=8, admission=ac)
    assert fe._coalesce_window() == 8
    for _ in range(60):
        ac.queues["write"].offer(object())
    ac.observe_cycle(0.01, 0)
    assert ac.level >= 2
    assert fe._coalesce_window() == 32


def test_begin_fused_steps_handle_is_deferred_and_idempotent():
    store, rt = build_rt()
    rt.update_at(0, "kv", ("add", "seed"), "c0")
    handle = rt.begin_fused_steps(4)
    assert handle.pending
    first = handle.finish()
    assert not handle.pending
    assert handle.finish() == first  # idempotent replay
    # the states advanced: the write spread beyond row 0
    held = sum(
        1 for r in range(R) if "seed" in rt.replica_value("kv", r)
    )
    assert held > 1
    # and fused_steps still behaves as before (the sync wrapper)
    assert isinstance(rt.fused_steps(2), int)


def test_host_work_between_begin_and_finish_lands_after_the_window():
    """The overlap contract: ops issued against the in-flight window's
    output futures queue behind it and apply correctly."""
    store, rt = build_rt()
    rt.update_at(3, "kv", ("add", "a"), "c0")
    handle = rt.begin_fused_steps(2)
    rt.update_batch("kv", [(0, ("add", "b"), "c1")])  # during the window
    handle.finish()
    rt.run_to_convergence(max_rounds=64)
    assert rt.coverage_value("kv") == frozenset({"a", "b"})


def test_serve_loop_resolves_concurrent_submissions():
    store, rt = build_rt(kv=("lasp_gset", {"n_elems": 128}))
    fe = ServeFrontend(rt, gossip_block=2)
    tickets = []
    with ServeLoop(fe, idle_sleep=0.001):
        threads = []

        def client(base):
            for i in range(20):
                tickets.append(
                    fe.submit_write("kv", ("add", f"k{base}-{i}"),
                                    f"c{base}")
                )

        for b in range(4):
            th = threading.Thread(target=client, args=(b,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        import time

        deadline = time.monotonic() + 30
        while (
            any(t.status == "queued" for t in tickets)
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
    assert all(t.status == "done" for t in tickets)
    assert len(rt.coverage_value("kv")) == 80


def test_report_feeds_health_serve_section():
    from lasp_tpu.telemetry import get_monitor

    store, rt = build_rt()
    fe = ServeFrontend(rt, gossip_block=0)
    fe.submit_write("kv", ("add", "x"), "c0")
    fe.cycle()
    fe.report()
    health = get_monitor().health()
    assert health["serve"]["offered"] >= 1
    assert "level" in health["serve"]


def test_session_serve_onramp():
    from lasp_tpu.api import Session

    s = Session()
    s.declare(type="lasp_gset", id="kv", n_elems=16)
    rt = s.replicate(8, topology="ring", fanout=2)
    fe = s.serve(rt, gossip_block=0)
    t = fe.submit_write("kv", ("add", "x"), "c0")
    fe.cycle()
    assert t.status == "done"
