"""API-facade tests mirroring ``riak_test/lasp_bind_test.erl`` (ivar bind /
bind_to / wait_needed / read_any) and ``riak_test/lasp_monotonic_read_test``
(threshold reads), plus the program registry
(``riak_test/lasp_programs_test.erl`` shape)."""

import pytest

from lasp_tpu import Session
from lasp_tpu.lattice import GSet, GSetSpec, Threshold
from lasp_tpu.programs import ExampleKeylistProgram, ExampleProgram


def test_ivar_bind_and_read():
    # lasp_bind_test: declare, bind, read; re-bind same value idempotent
    s = Session()
    v = s.declare("lasp_ivar")
    w = s.read(v, Threshold(None, strict=True))  # wait-for-defined
    assert not w.done
    s.update(v, ("set", 42), "actor")
    assert w.done
    assert s.value(v) == 42
    s.update(v, ("set", 42), "actor")  # same value: fine
    assert s.value(v) == 42
    # conflicting bind is silently ignored (src/lasp_core.erl:305-311)
    s.update(v, ("set", 99), "actor")
    assert s.value(v) == 42


def test_ivar_dataflow_chain():
    # lasp_bind_test dataflow: i1 -> i2 -> i3 via bind_to
    s = Session()
    i1 = s.declare("lasp_ivar")
    i2 = s.declare("lasp_ivar")
    i3 = s.declare("lasp_ivar")
    s.bind_to(i2, i1)
    s.bind_to(i3, i2)
    s.update(i1, ("set", "hello"), "a")
    assert s.value(i3) == "hello"


def test_wait_needed_fires_on_reader():
    # laziness: wait_needed fires when a reader shows interest
    # (src/lasp_core.erl:728-758)
    s = Session()
    v = s.declare("lasp_ivar")
    wn = s.wait_needed(v)
    assert not wn.done
    s.read(v, Threshold(None, strict=True))
    assert wn.done


def test_read_any_first_match():
    s = Session()
    a = s.declare("lasp_gset", n_elems=4)
    b = s.declare("lasp_gset", n_elems=4)
    spec = GSetSpec(n_elems=4)
    thr = Threshold(GSet.new(spec), strict=True)  # any growth
    w = s.read_any([(a, thr), (b, thr)])
    assert not w.done
    s.update(b, ("add", "x"), "actor")
    assert w.done
    assert w.result[0] == b


def test_monotonic_threshold_read():
    # lasp_monotonic_read_test: counter passes numeric thresholds in order
    s = Session()
    c = s.declare("riak_dt_gcounter")
    w5 = s.read(c, Threshold(5))
    for i in range(4):
        s.update(c, ("increment",), f"client{i}")
    assert not w5.done
    s.update(c, ("increment", 2), "client4")
    assert w5.done
    assert s.value(c) == 6


def test_combinator_verbs_roundtrip():
    s = Session()
    src = s.declare("lasp_orset", n_elems=8)
    s.update(src, ("add_all", [1, 2, 3, 4]), "a")
    doubled = s.map(src, lambda x: x * 2)
    evens = s.filter(src, lambda x: x % 2 == 0)
    assert s.value(doubled) == frozenset({2, 4, 6, 8})
    assert s.value(evens) == frozenset({2, 4})
    other = s.declare("lasp_orset", n_elems=8)
    s.update(other, ("add_all", [3, 4, 5]), "a")
    assert s.value(s.union(src, other)) == frozenset({1, 2, 3, 4, 5})
    assert s.value(s.intersection(src, other)) == frozenset({3, 4})


def test_program_registration_and_execute():
    # riak_test/lasp_programs_test.erl shape: register, notify, execute
    s = Session()
    s.register("example", ExampleProgram, n_elems=16)
    s.register("keylist", ExampleKeylistProgram, n_elems=16)
    s.register("example", ExampleProgram)  # idempotent re-register
    s.process(("k1", "v1"), "put", "actor1")
    s.process(("k2", "v2"), "put", "actor2")
    assert s.execute("example") == frozenset({("k1", "v1"), ("k2", "v2")})
    assert s.execute("keylist") == frozenset({"k1", "k2"})


def test_thread_runs_function():
    s = Session()
    v = s.declare("lasp_gset", n_elems=4)
    s.thread(lambda: s.update(v, ("add", "t"), "thread"))
    assert s.value(v) == frozenset({"t"})


def test_session_replicate_on_ramp():
    # the one-call path from session verbs to the mesh layer: current
    # state seeds every row, the graph sweeps per replica, mesh verbs work
    from lasp_tpu.lattice import Threshold

    s = Session(n_actors=8)
    v = s.declare("lasp_orset", n_elems=8)
    out = s.map(v, lambda x: x.upper())
    s.update(v, ("add", "a"), actor="w")
    rt = s.replicate(64, topology="random", fanout=3, seed=3)
    # EVERY row is seeded (a pre-gossip check at a far row, not just the
    # coverage join, which a row-0-only seeding bug would still pass)
    assert rt.replica_value(out, 63) == {"A"}
    assert rt.replica_value(v, 63) == {"a"}
    rt.update_at(5, v, ("add", "b"), "w5")
    rt.run_to_convergence(max_rounds=32)
    assert rt.divergence(v) == 0
    assert rt.coverage_value(out) == {"A", "B"}
    row = rt.read_until(60, v, Threshold(rt.read_at(5, v)), max_rounds=32)
    assert row is not None


def test_session_replicate_rejects_unknown_topology():
    import pytest

    s = Session()
    s.declare("lasp_gset", n_elems=4)
    with pytest.raises(ValueError, match="unknown topology"):
        s.replicate(8, topology="hypercube")


def test_replicate_locality_ordering():
    # irregular built-in topologies come back locality-ordered with the
    # permutation exposed; rings and explicit tables are untouched
    import numpy as np

    from lasp_tpu import Session
    from lasp_tpu.mesh.topology import locality_order, scale_free

    s = Session(n_actors=4)
    v = s.declare("lasp_gset", n_elems=4)
    s.update(v, ("add", "x"), actor="w")
    rt = s.replicate(64, topology="scale_free", seed=3)
    perm_ref, nn_ref = locality_order(scale_free(64, 3, seed=3))
    assert rt.locality_perm is not None
    assert np.array_equal(np.asarray(rt.neighbors), nn_ref)
    assert np.array_equal(rt.locality_perm, perm_ref)
    rt.run_to_convergence(max_rounds=64)
    assert rt.coverage_value(v) == frozenset({"x"})

    s2 = Session(n_actors=4)
    s2.declare("lasp_gset", n_elems=4)
    rt2 = s2.replicate(16, topology="ring")
    assert rt2.locality_perm is None
    rt3 = s2.replicate(16, topology="scale_free", locality=False)
    assert rt3.locality_perm is None


def test_locality_reorder_note_emitted_once():
    """Session.replicate(locality=True) renumbers irregular topologies —
    the one-time heads-up (ISSUE-3 satellite) must fire exactly once per
    process, point at rt.locality_perm, and stay silent for ring /
    explicit-neighbors / locality=False replicates."""
    import warnings

    import lasp_tpu.api.session as session_mod
    from lasp_tpu.mesh import ring

    session_mod._locality_note_emitted = False
    s = Session(n_actors=4)
    s.declare("lasp_gset", n_elems=4)
    with pytest.warns(UserWarning, match="locality_perm"):
        rt = s.replicate(16, topology="scale_free", fanout=3, seed=1)
    assert rt.locality_perm is not None
    # second reordering replicate: silent (once per process)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        s.replicate(16, topology="random", fanout=3, seed=2)

    # non-reordering paths never warn, even with the flag reset
    session_mod._locality_note_emitted = False
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        s.replicate(16, topology="ring")
        s.replicate(16, topology="scale_free", locality=False)
        s.replicate(16, neighbors=ring(16, 2))
