"""Native ETF codec conformance: the C extension must be byte-identical
to the Python oracle on encode and term-identical on decode — including
the atom/binary/str distinction — and must reject malformed frames with
the codec's own error type. The import-time self-check in etf.py gates
shipping; these tests are the deeper fuzz layer."""

import os
import random

import pytest

from lasp_tpu.bridge import etf
from lasp_tpu.bridge.etf import (
    Atom,
    ETFDecodeError,
    _type_shape as shape,
    py_decode,
    py_encode,
)

_SO = os.path.join(
    os.path.dirname(os.path.abspath(etf.__file__)), "..", "..", "native",
    "lasp_etf.so",
)

if etf.IMPL != "native":
    if os.path.exists(_SO) and os.environ.get("LASP_ETF") != "python":
        # the .so is present but the import-time selfcheck rejected it —
        # FAIL loudly (a silent skip would leave a broken native codec
        # both shipped-adjacent and untested); reproduce the first
        # mismatch for the report
        detail = "no corpus mismatch reproduced (malformed-frame gate?)"
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader("lasp_etf", _SO)
            spec = importlib.util.spec_from_loader("lasp_etf", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            mod.set_classes(Atom, ETFDecodeError)
            for term in etf._SELFCHECK:
                raw = py_encode(term)
                if mod.encode(term) != raw:
                    detail = f"encode mismatch on {term!r}"
                    break
                if shape(mod.decode(raw)) != shape(py_decode(raw)):
                    detail = f"decode mismatch on {term!r}"
                    break
        except Exception as exc:  # noqa: BLE001 — reported below
            detail = f"module load/probe failed: {exc!r}"
        pytest.fail(
            "native lasp_etf.so exists but the import-time selfcheck "
            f"rejected it ({detail}); rebuild with `make -C native` or "
            "force LASP_ETF=python intentionally",
            pytrace=False,
        )
    pytest.skip("native ETF codec not active", allow_module_level=True)
native = etf.native_module


def random_term(rng: random.Random, depth: int = 0):
    kinds = ["int", "big", "float", "atom", "bytes", "str", "none", "bool"]
    if depth < 4:
        kinds += ["list", "tuple", "map"] * 2
    k = rng.choice(kinds)
    if k == "int":
        return rng.randint(-(1 << 33), 1 << 33)
    if k == "big":
        return rng.randint(-(1 << 90), 1 << 90)
    if k == "float":
        return rng.uniform(-1e12, 1e12)
    if k == "atom":
        n = rng.choice([1, 3, 8, 255, 260])
        return Atom("".join(rng.choice("abcXYZ_é") for _ in range(n)))
    if k == "bytes":
        return bytes(rng.randrange(256) for _ in range(rng.randrange(40)))
    if k == "str":
        return "".join(rng.choice("hello wörld 中") for _ in range(8))
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    n = rng.randrange(6)
    items = [random_term(rng, depth + 1) for _ in range(n)]
    if k == "list":
        return items
    if k == "tuple":
        return tuple(items)
    d = {}
    for i, v in enumerate(items):
        d[rng.choice([Atom(f"k{i}"), f"k{i}".encode(), i])] = v
    return d


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_byte_identical_and_roundtrip(seed):
    rng = random.Random(seed)
    for _ in range(300):
        term = random_term(rng)
        raw_py = py_encode(term)
        raw_c = native.encode(term)
        assert raw_c == raw_py, term
        got_c = native.decode(raw_py)
        got_py = py_decode(raw_py)
        assert shape(got_c) == shape(got_py), term


def test_special_atoms_and_int_edges():
    for term in (None, True, False, 0, 255, 256, -1,
                 (1 << 31) - 1, 1 << 31, -(1 << 31), -(1 << 31) - 1,
                 (1 << 63) - 1, 1 << 63, -(1 << 63), 1 << 64, -(1 << 64),
                 1 << 2048, -(1 << 2048)):
        raw = py_encode(term)
        assert native.encode(term) == raw, term
        assert shape(native.decode(raw)) == shape(py_decode(raw)), term


def test_old_latin1_atom_decodes():
    # ATOM_EXT (tag 100, latin-1) — emitted by old nodes, decode-only
    name = "grüß".encode("latin-1")
    raw = bytes([131, 100, 0, len(name)]) + name
    assert shape(native.decode(raw)) == shape(py_decode(raw))


def test_string_ext_decodes_to_byte_list():
    raw = bytes([131, 107, 0, 3]) + b"abc"
    assert native.decode(raw) == py_decode(raw) == [97, 98, 99]


@pytest.mark.parametrize("bad", [
    b"",
    b"\x00",
    b"\x83",                       # version only
    b"\x83\xff",                   # unknown tag
    b"\x83\x6c\xff\xff\xff\xff\x6a",  # LIST claiming 4G items
    b"\x83\x68\x02\x61\x01",       # tuple arity 2, one element
    b"\x83\x6d\xff\xff\xff\xff",   # binary claiming 4G bytes
    b"\x83\x61\x01\x61\x02",       # trailing bytes
    b"\x83\x6c\x00\x00\x00\x01\x61\x01\x61\x02",  # improper list
    b"\x83\x77\x02\xff\xfe",       # atom with invalid utf-8
])
def test_malformed_frames_raise_codec_error(bad):
    with pytest.raises(ETFDecodeError):
        native.decode(bad)
    with pytest.raises(ETFDecodeError):
        py_decode(bad)


def test_deep_nesting_bounded_not_crash():
    # hand-build a 1000-deep list nest: [ [ [ ... ] ] ]. BOTH codecs
    # bound at the same depth (identical accepted wire language), so a
    # hostile frame can neither smash the C stack nor escape the Python
    # path as a RecursionError past the server's error-term handler
    body = b"\x6a"  # NIL
    for _ in range(1000):
        body = b"\x6c\x00\x00\x00\x01" + body + b"\x6a"
    frame = b"\x83" + body
    with pytest.raises(ETFDecodeError, match="deep"):
        native.decode(frame)
    with pytest.raises(ETFDecodeError, match="deep"):
        py_decode(frame)
    # a frame at the shared bound decodes identically on both
    ok_body = b"\x6a"
    for _ in range(500):
        ok_body = b"\x6c\x00\x00\x00\x01" + ok_body + b"\x6a"
    ok_frame = b"\x83" + ok_body
    assert native.decode(ok_frame) == py_decode(ok_frame)


def test_unencodable_raises_typeerror():
    with pytest.raises(TypeError):
        native.encode(object())
    with pytest.raises(TypeError):
        py_encode(object())


def test_encode_depth_bound_matches_between_codecs():
    # BOTH encoders refuse past _MAX_DEPTH (a frame nested deeper could
    # never be decoded by either codec anyway) — an encode-side
    # divergence here would make program behavior depend on whether the
    # .so built
    deep = []
    for _ in range(600):
        deep = [deep]
    with pytest.raises(TypeError, match="deep"):
        native.encode(deep)
    with pytest.raises(TypeError, match="deep"):
        py_encode(deep)
    ok = []
    for _ in range(400):
        ok = [ok]
    assert native.encode(ok) == py_encode(ok)


def test_config_etf_reselect():
    # LaspConfig.etf is a live selector through set_config, not an
    # env-only latch read once at import (r4 advisor finding)
    from lasp_tpu.bridge import etf
    from lasp_tpu.config import LaspConfig, get_config, set_config

    before = get_config()
    initial = etf.IMPL
    try:
        set_config(LaspConfig(etf="python"))
        assert etf.IMPL == "python"
        assert etf.decode(etf.encode((etf.Atom("ok"), 1))) == (etf.Atom("ok"), 1)
        set_config(LaspConfig(etf="auto"))
        # auto re-runs the native self-check: native when the .so is
        # present and conformant, python otherwise — either way it must
        # equal a fresh selection, not the stale latch
        assert etf.IMPL == etf.reselect()
    finally:
        set_config(before)
        assert etf.IMPL == initial
