"""Bridge `{health}` verb: the ConvergenceMonitor state + alerts as a
JSON binary, served before `{start, Name}` like `{metrics}`."""

import json

from lasp_tpu import telemetry
from lasp_tpu.bridge import BridgeClient, BridgeServer
from lasp_tpu.bridge.etf import Atom


def test_health_verb_before_start():
    telemetry.reset()
    with BridgeServer(port=0) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            resp = c.health()  # deliberately BEFORE start
    assert isinstance(resp, tuple) and len(resp) == 2
    assert str(resp[0]) == "ok"
    health = json.loads(resp[1].decode())
    for key in ("round", "residual_by_var", "staleness", "top_divergent",
                "quiescence_eta", "alerts", "thresholds"):
        assert key in health, key
    assert isinstance(health["alerts"], list)


def test_health_reflects_mesh_activity():
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    telemetry.reset()
    store = Store(n_actors=8)
    v = store.declare(id="seen", type="lasp_gset", n_elems=8)
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    rt.update_at(0, v, ("add", "x"), "w")
    rounds = rt.run_to_convergence(max_rounds=16)
    with BridgeServer(port=0) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            resp = c.health()
    health = json.loads(resp[1].decode())
    assert health["round"] == rounds
    assert health["residual_by_var"]["seen"] == 0
    assert health["n_replicas"] == 8
    # the health verb is metered like every other verb
    with BridgeServer(port=0) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.health()
            resp = c.call((Atom("metrics"),))
    text = resp[1].decode()
    assert 'bridge_requests_total{verb="health"}' in text
