"""Bridge protocol conformance: loopback declare/update/bind/read
round-trips over a real TCP socket (VERDICT r2 ask #6 done-condition),
from a client emitting the exact frames lasp_tpu_backend.erl would send
({packet,4} + term_to_binary)."""

import pytest

from lasp_tpu.bridge import Atom, BridgeClient, BridgeServer


@pytest.fixture()
def client():
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            assert c.start("vnode_0") == (Atom("ok"), Atom("vnode_0"))
            yield c


def test_requires_start_first():
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            resp = c.get(b"x")
            assert resp[0] == Atom("error") and resp[1] == Atom("not_started")


def test_declare_update_read_round_trip(client):
    assert client.declare(b"s", "lasp_orset", n_elems=8) == (Atom("ok"), b"s")
    ok, val = client.update(b"s", (Atom("add"), b"x"), b"actor1")
    assert ok == Atom("ok") and val == [b"x"]
    ok, val = client.update(b"s", (Atom("add_all"), [b"y", b"z"]), b"actor1")
    assert ok == Atom("ok") and set(val) == {b"x", b"y", b"z"}
    ok, val = client.update(b"s", (Atom("remove"), b"y"), b"actor1")
    assert set(val) == {b"x", b"z"}
    assert client.read(b"s") == (Atom("ok"), [b"x", b"z"])


def test_get_put_backend_contract(client):
    """start/put/get — the literal lasp_backend behaviour round trip."""
    client.declare(b"c", "riak_dt_gcounter", n_actors=4)
    client.update(b"c", (Atom("increment"), 3), b"a1")
    client.update(b"c", (Atom("increment"),), b"a2")
    ok, (type_atom, portable) = client.get(b"c")
    assert ok == Atom("ok") and type_atom == Atom("riak_dt_gcounter")
    assert sorted(portable) == [(b"a1", 3), (b"a2", 1)]
    # blind put of an externally-merged state (the ets:insert role)
    assert client.put(
        b"c2", "riak_dt_gcounter", [(b"a1", 7), (b"a3", 2)], n_actors=4
    ) == Atom("ok")
    assert client.read(b"c2") == (Atom("ok"), 9)
    assert client.get(b"missing") == (Atom("error"), Atom("not_found"))


def test_bind_merges_through_inflation_gate(client):
    client.declare(b"s", "lasp_orset", n_elems=8, n_actors=2,
                   tokens_per_actor=4)
    client.update(b"s", (Atom("add"), b"x"), b"w1")
    # a remote replica's state: x tombstoned under token 0, plus new elem y
    remote = [(b"x", [(0, True)]), (b"y", [(4, False)])]
    ok, val = client.bind(b"s", remote)
    assert ok == Atom("ok")
    assert val == [b"y"]  # x's only token tombstoned; y joined in
    # binding an OLD state is a non-inflation no-op (bind rule)
    ok, val = client.bind(b"s", [(b"x", [(0, False)])])
    assert val == [b"y"]


def test_merge_batch_anti_entropy(client):
    client.declare(b"a", "lasp_orset", n_elems=8)
    client.declare(b"b", "lasp_gset", n_elems=8)
    client.update(b"a", (Atom("add"), b"local"), b"w")
    resp = client.merge_batch([
        (b"a", [(b"remote", [(0, False)])]),
        (b"b", [b"g1", b"g2"]),
    ])
    assert resp == (Atom("ok"), 2)
    assert client.read(b"a") == (Atom("ok"), [b"local", b"remote"])
    assert client.read(b"b") == (Atom("ok"), [b"g1", b"g2"])


def test_ivar_bridge(client):
    client.declare(b"v", "lasp_ivar")
    client.update(b"v", (Atom("set"), b"payload"), b"w")
    ok, (type_atom, portable) = client.get(b"v")
    assert portable == (Atom("value"), b"payload")


def test_errors_are_terms_not_disconnects(client):
    client.declare(b"s", "lasp_orset", n_elems=4)
    resp = client.update(b"s", (Atom("remove"), b"ghost"), b"w")
    assert resp[0] == Atom("error") and resp[1] == Atom("PreconditionError")
    # the connection is still serviceable after an error
    assert client.read(b"s") == (Atom("ok"), [])
    resp = client.call((Atom("bogus_verb"), 1))
    assert resp[0] == Atom("error")


def test_connections_are_isolated_stores():
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c1, BridgeClient(
            "127.0.0.1", server.port
        ) as c2:
            c1.start("vnode_1")
            c2.start("vnode_2")
            c1.declare(b"s", "lasp_gset", n_elems=4)
            c1.update(b"s", (Atom("add"), b"only-1"), b"w")
            c2.declare(b"s", "lasp_gset", n_elems=4)
            assert c2.read(b"s") == (Atom("ok"), [])
            assert c1.read(b"s") == (Atom("ok"), [b"only-1"])


def test_malformed_frames_get_error_terms_not_disconnects():
    """Truncated/garbage ETF must come back as an error term on a live
    connection, never kill the server thread."""
    from lasp_tpu.bridge.server import _recv_frame, _send_frame

    with BridgeServer() as server:
        import socket

        with socket.create_connection(("127.0.0.1", server.port), 5) as s:
            for bad in (b"\x83\x62\x00",          # truncated INT_EXT
                        b"\x83\x77\x02\xff\xfe",  # invalid-UTF8 atom
                        b"junk"):                  # no version byte
                _send_frame(s, bad)
                resp = _recv_frame(s)
                assert resp is not None
                from lasp_tpu.bridge import etf
                term = etf.decode(resp)
                assert term[0] == Atom("error") and term[1] == Atom("etf_decode")
            # connection still serviceable
            _send_frame(s, etf.encode((Atom("start"), Atom("v"))))
            assert etf.decode(_recv_frame(s)) == (Atom("ok"), Atom("v"))


def test_list_and_tuple_ids_are_distinct_and_round_trip():
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            assert c.declare([1, 2], "lasp_gset", n_elems=4) == (
                Atom("ok"), [1, 2]
            )
            assert c.declare((1, 2), "riak_dt_gcounter", n_actors=2) == (
                Atom("ok"), (1, 2)
            )
            c.update([1, 2], (Atom("add"), b"e"), b"w")
            c.update((1, 2), (Atom("increment"), 5), b"w")
            assert c.read([1, 2]) == (Atom("ok"), [b"e"])
            assert c.read((1, 2)) == (Atom("ok"), 5)
            ok, keys = c.call((Atom("keys"),))
            assert ok == Atom("ok")
            assert [1, 2] in keys and (1, 2) in keys
            # container-valued ELEMENTS round-trip shape-faithfully too
            c.declare(b"s", "lasp_gset", n_elems=4)
            c.update(b"s", (Atom("add"), [b"x", 1]), b"w")
            c.update(b"s", (Atom("add"), (b"x", 1)), b"w")
            ok, val = c.read(b"s")
            assert [b"x", 1] in val and (b"x", 1) in val and len(val) == 2


def test_stop_disconnects_live_clients():
    server = BridgeServer()
    server.start()
    c = BridgeClient("127.0.0.1", server.port)
    c.start("v")
    server.stop()
    import pytest as _pytest

    with _pytest.raises((ConnectionError, OSError)):
        for _ in range(3):  # first call may see the buffered close late
            c.call((Atom("keys"),))
    c.close()


def test_cli_bridge_verb_serves():
    """`cli bridge` starts a servable endpoint (run in-process via the
    server class path the verb uses; the verb itself just wraps it)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "lasp_tpu.cli", "bridge", "--port", "0"],
        stdout=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        import json as _json

        line = proc.stdout.readline()
        port = int(_json.loads(line)["listening"].rsplit(":", 1)[1])
        with BridgeClient("127.0.0.1", port) as c:
            c.start("v")
            c.declare(b"s", "lasp_gset", n_elems=2)
            c.update(b"s", (Atom("add"), b"e"), b"w")
            assert c.read(b"s") == (Atom("ok"), [b"e"])
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_durable_store_survives_reconnect(tmp_path):
    """data_dir makes {start, Name} a durable per-name store (the
    eleveldb per-partition role, src/lasp_eleveldb_backend.erl:38-53):
    state written through one connection is there for the next one."""
    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            assert c.start("vnode_1") == (Atom("ok"), Atom("vnode_1"))
            c.declare(b"s", "lasp_orset", n_elems=8)
            c.update(b"s", (Atom("add_all"), [b"a", b"b"]), b"w")
            c.update(b"s", (Atom("remove"), b"a"), b"w")
        with BridgeClient("127.0.0.1", server.port) as c2:
            import time

            for _ in range(100):  # lock release lags the socket teardown
                resp = c2.start("vnode_1")
                if resp[0] == Atom("ok"):
                    break
                time.sleep(0.02)
            assert resp == (Atom("ok"), Atom("vnode_1"))
            ok, val = c2.read(b"s")
            assert ok == Atom("ok") and val == [b"b"]
    # durability spans server restarts too (fresh process over same dir)
    with BridgeServer(data_dir=d) as server2:
        with BridgeClient("127.0.0.1", server2.port) as c3:
            c3.start("vnode_1")
            ok, val = c3.read(b"s")
            assert ok == Atom("ok") and val == [b"b"]
            # a different name is a different store
            c3.start("vnode_2")
            resp = c3.read(b"s")
            assert resp[0] == Atom("error")


def test_durable_store_name_locked_while_open(tmp_path):
    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c1:
            assert c1.start("p0") == (Atom("ok"), Atom("p0"))
            with BridgeClient("127.0.0.1", server.port) as c2:
                resp = c2.start("p0")
                assert resp[0] == Atom("error") and resp[1] == Atom("locked")
                # a different partition is fine concurrently
                assert c2.start("p1") == (Atom("ok"), Atom("p1"))
        # c1 disconnected -> lock released; retry succeeds (poll: the
        # server releases on its side of the socket teardown)
        import time

        with BridgeClient("127.0.0.1", server.port) as c3:
            for _ in range(100):
                resp = c3.start("p0")
                if resp[0] == Atom("ok"):
                    break
                time.sleep(0.02)
            assert resp == (Atom("ok"), Atom("p0"))


def test_durable_store_rejects_path_names(tmp_path):
    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            resp = c.start("../escape")
            assert resp[0] == Atom("error") and resp[1] == Atom("badarg")


def test_durable_store_accepts_binary_names(tmp_path):
    """BEAM nodes send names as binaries ({start, <<"vnode_1">>})."""
    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            resp = c.start(b"vnode_bin")
            assert resp == (Atom("ok"), Atom("vnode_bin")), resp


def test_failed_durable_start_orphans_nothing(tmp_path):
    """A name REJECTED by validation leaves the previous durable store
    open (no teardown happened); a start that fails mid-open (corrupt
    log) must leave the connection with NO store rather than silently
    writing to the previous one non-durably."""
    import os

    d = str(tmp_path / "stores")
    os.makedirs(d)
    with open(os.path.join(d, "corrupt"), "wb") as f:
        f.write(b"\x00garbage not a log\xff" * 8)
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            assert c.start("good")[0] == Atom("ok")
            c.declare(b"s", "lasp_gset", n_elems=4)
            # early rejection: old store stays open and durable
            assert c.start("../bad")[0] == Atom("error")
            ok, _ = c.update(b"s", (Atom("add"), b"x"), b"w")
            assert ok == Atom("ok")
            # mid-open failure: the connection must end up storeless
            assert c.start(b"corrupt")[0] == Atom("error")
            resp = c.update(b"s", (Atom("add"), b"y"), b"w")
            assert resp[0] == Atom("error") and resp[1] == Atom("not_started")
        # and the pre-failure write to "good" really persisted
        with BridgeClient("127.0.0.1", server.port) as c2:
            c2.start("good")
            assert c2.read(b"s") == (Atom("ok"), [b"x"])


def test_durable_merge_batch_midfail_persists_applied_prefix(tmp_path):
    """If merge_batch fails mid-batch, the applied prefix is visible on
    this connection AND in the durable log (no silent divergence)."""
    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("p")
            c.declare(b"a", "lasp_gset", n_elems=4)
            c.update(b"a", (Atom("add"), b"x"), b"w")
            live = c.get(b"a")[1]
            resp = c.call((Atom("merge_batch"),
                           [(b"a", live[1]), (b"undeclared", live[1])]))
            assert resp[0] == Atom("error")
            assert c.read(b"a") == (Atom("ok"), [b"x"])
        with BridgeClient("127.0.0.1", server.port) as c2:
            import time

            for _ in range(100):  # lock release lags the socket teardown
                if c2.start("p")[0] == Atom("ok"):
                    break
                time.sleep(0.02)
            assert c2.read(b"a") == (Atom("ok"), [b"x"])


def test_durable_store_survives_atom_and_container_terms(tmp_path):
    """Atom ids/elems/actors and container ids are BEAM-idiomatic; the
    durable log must reload them (the key encoding is plain data — a
    bridge class in an interner would be refused by the restricted
    manifest unpickler and brick the store)."""
    import time

    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("p")
            assert c.declare(Atom("myvar"), "lasp_orset", n_elems=8) == (
                Atom("ok"), Atom("myvar")
            )
            c.update(Atom("myvar"), (Atom("add"), Atom("elem_a")), Atom("w"))
            c.update(Atom("myvar"), (Atom("add"), [b"x", 1]), b"w")
            c.declare([1, 2], "lasp_gset", n_elems=4)
            c.update([1, 2], (Atom("add"), (b"t", 9)), b"w")
        with BridgeClient("127.0.0.1", server.port) as c2:
            for _ in range(100):
                if c2.start("p")[0] == Atom("ok"):
                    break
                time.sleep(0.02)
            ok, val = c2.read(Atom("myvar"))
            assert ok == Atom("ok")
            assert Atom("elem_a") in val and [b"x", 1] in val
            assert c2.read([1, 2]) == (Atom("ok"), [(b"t", 9)])


def test_orswot_bridge_round_trip_and_merge():
    """riak_dt_orswot over the wire: {VClock, Entries} portable form,
    get/put round-trip, and the no-tombstone remove-wins merge (a dot the
    peer's clock has seen but no longer carries stays removed)."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            c.declare(b"s", "riak_dt_orswot", n_elems=8, n_actors=4)
            c.update(b"s", (Atom("add"), b"x"), b"a1")
            c.update(b"s", (Atom("add"), b"y"), b"a2")
            ok, (type_atom, portable) = c.get(b"s")
            assert ok == Atom("ok") and type_atom == Atom("riak_dt_orswot")
            clock, entries = portable
            assert (b"a1", 1) in clock and (b"a2", 1) in clock
            assert dict(entries)[b"x"] == [(b"a1", 1)]
            # blind put into a twin, value preserved
            assert c.put(b"s2", "riak_dt_orswot", portable,
                         n_elems=8, n_actors=4) == Atom("ok")
            ok, val = c.read(b"s2")
            assert set(val) == {b"x", b"y"}
            # peer state whose clock saw a1@1 but carries no dot for x:
            # binding it must NOT resurrect x... and y removed by peer
            peer = ([(b"a1", 1), (b"a2", 1)], [])
            ok, val = c.bind(b"s2", peer)
            assert ok == Atom("ok") and val == []
            # invalid dot (beyond own clock) is refused loudly
            bad = ([(b"a9", 1)], [(b"z", [(b"a9", 2)])])
            resp = c.put(b"s3", "riak_dt_orswot", bad, n_elems=4, n_actors=4)
            assert resp[0] == Atom("error")


def test_orswot_bridge_durable(tmp_path):
    import time

    d = str(tmp_path / "stores")
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("p")
            c.declare(b"s", "riak_dt_orswot", n_elems=8, n_actors=4)
            c.update(b"s", (Atom("add"), b"x"), b"a1")
            c.update(b"s", (Atom("remove"), b"x"), b"a1")
            c.update(b"s", (Atom("add"), b"y"), b"a2")
        with BridgeClient("127.0.0.1", server.port) as c2:
            for _ in range(100):
                if c2.start("p")[0] == Atom("ok"):
                    break
                time.sleep(0.02)
            assert c2.read(b"s") == (Atom("ok"), [b"y"])


def test_rejected_state_consumes_no_interner_capacity():
    """A rejected bind/put must leave the live variable untouched — no
    ghost elems/actors interned (4 bad binds must not exhaust a 4-actor
    universe)."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            c.declare(b"s", "riak_dt_orswot", n_elems=4, n_actors=4)
            for i in range(6):  # > n_actors rejected states
                bad = ([(f"bad{i}".encode(), 1)],
                       [(b"z", [(f"bad{i}".encode(), 2)])])
                resp = c.bind(b"s", bad)
                assert resp[0] == Atom("error")
            # legitimate actors still fit
            for i in range(4):
                ok, _ = c.update(b"s", (Atom("add"), b"x"), f"a{i}".encode())
                assert ok == Atom("ok")
            # orset: bad token index must not intern the element
            c.declare(b"o", "lasp_orset", n_elems=2, n_actors=1,
                      tokens_per_actor=1)
            for i in range(4):
                resp = c.bind(b"o", [(f"g{i}".encode(), [(99, False)])])
                assert resp[0] == Atom("error")
            ok, _ = c.update(b"o", (Atom("add"), b"real"), b"w")
            assert ok == Atom("ok")
            assert c.read(b"o") == (Atom("ok"), [b"real"])


def test_map_bridge_declare_update_read_roundtrip():
    """riak_dt_map over the wire: fields schema in caps, {update, Key,
    InnerOp} ops, proplist value (riak_dt_map:value shape), get/put
    round-trip, remove field."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            fields = [
                (b"tags", Atom("lasp_gset"), {Atom("n_elems"): 4}),
                (b"hits", Atom("riak_dt_gcounter"), {}),
            ]
            resp = c.call((Atom("declare"), b"m", Atom("riak_dt_map"),
                           {Atom("fields"): fields, Atom("n_actors"): 4}))
            assert resp == (Atom("ok"), b"m")
            ok, val = c.update(b"m", (Atom("update"), b"tags",
                                      (Atom("add"), b"t1")), b"w0")
            assert ok == Atom("ok")
            ok, val = c.update(b"m", (Atom("update"), b"hits",
                                      (Atom("increment"), 3)), b"w1")
            assert ok == Atom("ok")
            assert val == [(b"hits", 3), (b"tags", [b"t1"])]
            # get/put round-trip into a twin
            ok, (type_atom, portable) = c.get(b"m")
            assert type_atom == Atom("riak_dt_map")
            resp = c.call((Atom("put"), b"m2",
                           (Atom("riak_dt_map"), portable,
                            {Atom("fields"): fields, Atom("n_actors"): 4})))
            assert resp == Atom("ok")
            assert c.read(b"m2") == (Atom("ok"),
                                     [(b"hits", 3), (b"tags", [b"t1"])])
            # remove a field: presence dropped, counter keeps counting
            ok, val = c.update(b"m", (Atom("remove"), b"tags"), b"w0")
            assert val == [(b"hits", 3)]
            # unknown field in a put is rejected, and consumes nothing
            bad = ([(b"w9", 1)], [(b"nope", [(b"w9", 1)], [])])
            resp = c.call((Atom("put"), b"m3",
                           (Atom("riak_dt_map"), bad,
                            {Atom("fields"): fields, Atom("n_actors"): 4})))
            assert resp[0] == Atom("error")


def test_server_survives_malformed_frame_fuzz():
    """Socket-level robustness: random garbage frames each get an error
    TERM back (never a dropped connection, never a hang), and the
    connection keeps serving real ops afterwards — the bridge faces an
    untrusted network."""
    import random
    import socket
    import struct

    rng = random.Random(42)
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("fuzz")
            c.declare(b"c", "riak_dt_gcounter", n_actors=4)
            from lasp_tpu.bridge import etf
            from lasp_tpu.bridge.server import _recv_frame

            sock = c._sock
            for i in range(200):
                n = rng.randrange(0, 64)
                payload = bytes(rng.randrange(256) for _ in range(n))
                if rng.random() < 0.3:  # valid version byte, garbage body
                    payload = b"\x83" + payload
                sock.sendall(struct.pack(">I", len(payload)) + payload)
                body = _recv_frame(sock)  # the REAL framing reader
                assert body is not None, f"server closed on fuzz frame {i}"
                resp = etf.decode(body)
                assert isinstance(resp, tuple) and resp[0] == Atom("error"), (
                    i, resp,
                )
            # the connection still serves real traffic
            ok, total = c.update(b"c", (Atom("increment"), 7), b"w")
            assert ok == Atom("ok") and total == 7


def test_map_bridge_reset_mode_epochs_roundtrip():
    """reset_on_readd maps over the wire: caps flag parsed, remove-then-
    re-add resets contents, and the portable state carries the epoch
    component — whose presence must match the target's mode."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            fields = [(b"tags", Atom("lasp_gset"), {Atom("n_elems"): 4}),
                      (b"hits", Atom("riak_dt_gcounter"), {})]
            caps = {Atom("fields"): fields, Atom("n_actors"): 4,
                    Atom("reset_on_readd"): Atom("true")}
            resp = c.call((Atom("declare"), b"m", Atom("riak_dt_map"), caps))
            assert resp == (Atom("ok"), b"m")
            c.update(b"m", (Atom("update"), b"tags", (Atom("add"), b"t1")), b"w")
            c.update(b"m", (Atom("update"), b"hits",
                            (Atom("increment"), 5)), b"w")
            c.update(b"m", (Atom("remove"), b"tags"), b"w")
            c.update(b"m", (Atom("remove"), b"hits"), b"w")
            ok, val = c.update(b"m", (Atom("update"), b"tags",
                                      (Atom("add"), b"t2")), b"w")
            ok, val = c.update(b"m", (Atom("update"), b"hits",
                                      (Atom("increment"), 2)), b"w")
            assert ok == Atom("ok")
            # t1 reset away (epoch gate); the counter counts 2 past its
            # observed-floor of 5
            assert val == [(b"hits", 2), (b"tags", [b"t2"])]
            ok, (type_atom, portable) = c.get(b"m")
            assert len(portable) == 4  # (clock, fields, epochs, tombs)
            assert sorted(portable[2]) == [(b"hits", 1), (b"tags", 1)]
            # the counter's reset-remove floor rides the wire (gset
            # resets are epoch-gated and carry no baseline): the
            # receiver must never resurrect the 5 observed increments
            assert portable[3] == [(b"hits", [(b"w", 5)])]
            # round-trip into a twin of the same mode
            resp = c.call((Atom("put"), b"m2",
                           (Atom("riak_dt_map"), portable, caps)))
            assert resp == Atom("ok")
            assert c.read(b"m2") == (Atom("ok"),
                                     [(b"hits", 2), (b"tags", [b"t2"])])
            # a floor-LESS epoch-bearing state (pre-round-5 wire shape)
            # is rejected outright: importing it could resurrect resets
            resp = c.call((Atom("put"), b"m2b",
                           (Atom("riak_dt_map"),
                            (portable[0], portable[1], portable[2]), caps)))
            assert resp[0] == Atom("error")
            # a NON-reset twin must refuse the epoch-bearing state
            caps_plain = {Atom("fields"): fields, Atom("n_actors"): 4}
            resp = c.call((Atom("put"), b"m3",
                           (Atom("riak_dt_map"), portable, caps_plain)))
            assert resp[0] == Atom("error")
            # ... and a reset twin must refuse an epoch-LESS state (it can
            # only come from a plain-mode source)
            resp = c.call((Atom("put"), b"m4",
                           (Atom("riak_dt_map"),
                            (portable[0], portable[1]), caps)))
            assert resp[0] == Atom("error")
            # a malformed flag value is rejected at declare, not coerced
            bad_caps = {Atom("fields"): fields, Atom("n_actors"): 4,
                        Atom("reset_on_readd"): 1}
            resp = c.call((Atom("declare"), b"m5", Atom("riak_dt_map"),
                           bad_caps))
            assert resp[0] == Atom("error")


def test_map_bridge_durable(tmp_path):
    import time

    d = str(tmp_path / "stores")
    fields = [(b"tags", Atom("lasp_gset"), {Atom("n_elems"): 4}),
              (b"hits", Atom("riak_dt_gcounter"), {})]
    with BridgeServer(data_dir=d) as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("p")
            c.call((Atom("declare"), b"m", Atom("riak_dt_map"),
                    {Atom("fields"): fields, Atom("n_actors"): 4}))
            c.update(b"m", (Atom("update"), b"tags", (Atom("add"), b"t")), b"w")
            c.update(b"m", (Atom("update"), b"hits", (Atom("increment"),)), b"w")
        with BridgeClient("127.0.0.1", server.port) as c2:
            for _ in range(100):
                if c2.start("p")[0] == Atom("ok"):
                    break
                time.sleep(0.02)
            assert c2.read(b"m") == (Atom("ok"),
                                     [(b"hits", 1), (b"tags", [b"t"])])


def test_map_bridge_batched_op_and_bare_atom_inner():
    """The reference's batched map op {update, [SubOps]} and bare-atom
    inner ops ({update, Key, increment}) work over the wire."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            fields = [(b"tags", Atom("lasp_gset"), {Atom("n_elems"): 4}),
                      (b"hits", Atom("riak_dt_gcounter"), {})]
            c.call((Atom("declare"), b"m", Atom("riak_dt_map"),
                    {Atom("fields"): fields, Atom("n_actors"): 4}))
            ok, val = c.update(
                b"m",
                (Atom("update"), [
                    (Atom("update"), b"tags", (Atom("add"), b"t1")),
                    (Atom("update"), b"hits", Atom("increment")),
                ]),
                b"w0",
            )
            assert ok == Atom("ok"), val
            assert val == [(b"hits", 1), (b"tags", [b"t1"])]
            ok, val = c.update(b"m", (Atom("update"), b"hits",
                                      Atom("increment")), b"w1")
            assert ok == Atom("ok") and (b"hits", 2) in val


def test_oversized_state_rejected_before_any_interning():
    """A structurally-valid state naming more actors/elems than the
    declared universes is refused up front — nothing interned."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            c.declare(b"s", "riak_dt_orswot", n_elems=4, n_actors=2)
            big = ([(f"a{i}".encode(), 1) for i in range(5)], [])
            resp = c.bind(b"s", big)
            assert resp[0] == Atom("error")
            assert b"rejected before interning" in resp[2]
            # both declared actor slots still usable
            for i in range(2):
                ok, _ = c.update(b"s", (Atom("add"), b"x"), f"w{i}".encode())
                assert ok == Atom("ok")
            # gset elem overflow too
            c.declare(b"g", "lasp_gset", n_elems=2)
            resp = c.bind(b"g", [b"e1", b"e2", b"e3"])
            assert resp[0] == Atom("error")
            ok, _ = c.update(b"g", (Atom("add"), b"fine"), b"w")
            assert ok == Atom("ok")


def test_durable_bridge_concurrent_clients_stress(tmp_path):
    """Several clients hammering DIFFERENT durable stores concurrently:
    the name-lock registry and per-connection host logs must not cross
    wires; a contended name serializes via {error, locked}."""
    import threading

    d = str(tmp_path / "stores")
    errors: list = []

    def worker(port, name, n_ops):
        try:
            with BridgeClient("127.0.0.1", port) as c:
                assert c.start(name)[0] == Atom("ok")
                c.declare(b"s", "lasp_gset", n_elems=64)
                for i in range(n_ops):
                    ok, _ = c.update(b"s", (Atom("add"), f"{name}-{i}".encode()),
                                     b"w")
                    assert ok == Atom("ok")
        except Exception as e:  # surfaced after join
            errors.append((name, repr(e)))

    with BridgeServer(data_dir=d) as server:
        threads = [
            threading.Thread(target=worker, args=(server.port, f"p{k}", 50))
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        # every store durably holds exactly its own writes
        for k in range(4):
            with BridgeClient("127.0.0.1", server.port) as c:
                import time

                for _ in range(100):
                    if c.start(f"p{k}")[0] == Atom("ok"):
                        break
                    time.sleep(0.02)
                ok, val = c.read(b"s")
                assert ok == Atom("ok") and len(val) == 50
                assert all(v.startswith(f"p{k}-".encode()) for v in val)


def test_map_bridge_dynamic_field_admission():
    """The reference's exact wire flow (riak_test/lasp_kvs_replica_test.erl:
    57-135): declare riak_dt_map with NO schema, update a {Name, Type}
    tuple key never declared anywhere. The tagged key encoding
    (("tuple", ("atom", Name), ("atom", Type)) after _to_key) must
    self-describe its embedded type and admit on first update — and on
    state import (put/bind with fields this node has never seen)."""
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            c.start("v")
            resp = c.declare(b"m", "riak_dt_map")  # schemaless
            assert resp == (Atom("ok"), b"m")
            key = (Atom("X"), Atom("lasp_orset"))
            ok, val = c.update(
                b"m", (Atom("update"), key, (Atom("add"), b"Chris")), b"w0"
            )
            assert ok == Atom("ok")
            assert val == [(key, [b"Chris"])]
            # a second dynamic field through the batched op shape
            ckey = (Atom("hits"), Atom("riak_dt_gcounter"))
            ok, val = c.update(
                b"m",
                (Atom("update"), [(Atom("update"), ckey, (Atom("increment"), 2))]),
                b"w1",
            )
            assert ok == Atom("ok")
            assert dict(val) == {key: [b"Chris"], ckey: 2}
            # remove of a never-admitted {Name, Type} key: precondition
            # error (riak_dt_map not_present), NOT silent admission
            resp = c.update(
                b"m", (Atom("remove"), (Atom("Z"), Atom("lasp_orset"))), b"w0"
            )
            assert resp[0] == Atom("error")
            # portable-state import admits unknown self-describing fields:
            # put m's state into a twin declared with NO fields at all
            ok, (type_atom, portable) = c.get(b"m")
            assert type_atom == Atom("riak_dt_map")
            resp = c.put(b"m2", "riak_dt_map", portable)
            assert resp == Atom("ok")
            assert dict(c.read(b"m2")[1]) == {key: [b"Chris"], ckey: 2}
            # a non-self-describing unknown field still rejects, with
            # nothing admitted (the twin keeps serving)
            bad = ([(b"w9", 1)], [(b"nope", [(b"w9", 1)], [])])
            resp = c.put(b"m3", "riak_dt_map", bad)
            assert resp[0] == Atom("error")
            ok, _ = c.update(
                b"m2", (Atom("update"), ckey, (Atom("increment"),)), b"w1"
            )
            assert ok == Atom("ok")
