"""ETF codec conformance: byte vectors are checked against the published
External Term Format (the exact bytes term_to_binary/1 produces on a BEAM
for these terms), so the Python side is wire-compatible with
binary_to_term without an Erlang node in the image."""

import pytest

from lasp_tpu.bridge import etf
from lasp_tpu.bridge.etf import Atom


# (term, term_to_binary bytes) — vectors derived from the ETF spec:
# 131 version; 97 SMALL_INTEGER; 98 INTEGER; 119 SMALL_ATOM_UTF8;
# 109 BINARY; 104 SMALL_TUPLE; 108 LIST; 106 NIL; 110 SMALL_BIG; 70 FLOAT
VECTORS = [
    (0, bytes([131, 97, 0])),
    (255, bytes([131, 97, 255])),
    (256, bytes([131, 98, 0, 0, 1, 0])),
    (-1, bytes([131, 98, 255, 255, 255, 255])),
    (Atom("ok"), bytes([131, 119, 2]) + b"ok"),
    (b"hi", bytes([131, 109, 0, 0, 0, 2]) + b"hi"),
    ((Atom("ok"), 1), bytes([131, 104, 2, 119, 2]) + b"ok" + bytes([97, 1])),
    ([], bytes([131, 106])),
    (
        [1, 2],
        bytes([131, 108, 0, 0, 0, 2, 97, 1, 97, 2, 106]),
    ),
    # 2^40 = little-endian big of 6 bytes: 0,0,0,0,0,1
    (1 << 40, bytes([131, 110, 6, 0, 0, 0, 0, 0, 0, 1])),
    (-(1 << 40), bytes([131, 110, 6, 1, 0, 0, 0, 0, 0, 1])),
    (1.5, bytes([131, 70, 63, 248, 0, 0, 0, 0, 0, 0])),
]


@pytest.mark.parametrize("term,blob", VECTORS)
def test_encode_matches_term_to_binary(term, blob):
    assert etf.encode(term) == blob


@pytest.mark.parametrize("term,blob", VECTORS)
def test_decode_matches_binary_to_term(term, blob):
    assert etf.decode(blob) == term


def test_atom_special_values_decode_to_python():
    assert etf.decode(etf.encode(Atom("undefined"))) is None
    assert etf.decode(etf.encode(True)) is True
    assert etf.decode(etf.encode(False)) is False


def test_str_crosses_as_binary():
    assert etf.decode(etf.encode("hello")) == b"hello"


def test_nested_round_trip():
    term = (
        Atom("update"),
        b"views",
        (Atom("increment"), 3),
        [(b"k", [(1, False), (2, True)]), (b"j", [])],
        {Atom("n_elems"): 64},
    )
    out = etf.decode(etf.encode(term))
    assert out == term


def test_old_atom_ext_decodes():
    # ATOM_EXT (100): u16 length + latin1 name — old nodes still emit it
    blob = bytes([131, 100, 0, 2]) + b"ok"
    assert etf.decode(blob) == Atom("ok")


def test_string_ext_decodes_as_int_list():
    # STRING_EXT (107): how term_to_binary encodes [104, 105]
    blob = bytes([131, 107, 0, 2]) + b"hi"
    assert etf.decode(blob) == [104, 105]


def test_improper_and_truncated_raise():
    with pytest.raises(etf.ETFDecodeError):
        etf.decode(b"")
    with pytest.raises(etf.ETFDecodeError):
        etf.decode(bytes([131, 104, 2, 97, 1]))  # tuple arity 2, one elem
    with pytest.raises(etf.ETFDecodeError):
        # LIST with a non-nil tail (improper list)
        etf.decode(bytes([131, 108, 0, 0, 0, 1, 97, 1, 97, 2]))


def test_fuzz_roundtrip_random_nested_terms():
    """decode(encode(t)) == t over a few hundred random nested terms —
    the property the EQC binary round-trip runs per CRDT
    (test/crdt_statem_eqc.erl prop_bin_roundtrip), here at the codec."""
    import random

    rng = random.Random(99)

    def gen(depth=0):
        kinds = ["int", "bigint", "bytes", "atom", "float"]
        if depth < 3:
            kinds += ["list", "tuple", "list", "tuple"]
        k = rng.choice(kinds)
        if k == "int":
            return rng.randint(-(1 << 30), 1 << 30)
        if k == "bigint":
            return rng.randint(-(1 << 200), 1 << 200)
        if k == "bytes":
            return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 12)))
        if k == "atom":
            return Atom("".join(rng.choice("abc_xyz") for _ in range(rng.randint(1, 10))))
        if k == "float":
            return rng.uniform(-1e12, 1e12)
        n = rng.randint(0, 4)
        items = [gen(depth + 1) for _ in range(n)]
        return items if k == "list" else tuple(items)

    for i in range(300):
        t = gen()
        got = etf.decode(etf.encode(t))
        assert got == t, (i, t, got)
