"""Real-BEAM end-to-end of the Erlang adapter (VERDICT r4 missing #3).

``test_beam_adapter_e2e`` compiles and runs
``bridge/erlang/e2e.escript`` against a live server — it SKIPS where no
BEAM exists (this image ships none; any machine with erlang, or docker
via ``make bridge-e2e``, runs it green).

``test_beam_e2e_python_twin`` replays the escript's EXACT verb/value
sequence from Python on every machine, so the scenario the escript
asserts can never silently drift from what the server actually answers.
"""

import os
import shutil
import subprocess

import pytest

from lasp_tpu.bridge import BridgeClient, BridgeServer
from lasp_tpu.bridge.etf import Atom

_ESCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "lasp_tpu", "bridge", "erlang", "e2e.escript",
)


@pytest.mark.skipif(
    shutil.which("escript") is None,
    reason="no BEAM (escript) on PATH — run `make bridge-e2e` where one exists",
)
def test_beam_adapter_e2e():
    with BridgeServer() as server:
        out = subprocess.run(
            ["escript", _ESCRIPT, str(server.port)],
            capture_output=True, text=True, timeout=180,
        )
        assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
        assert "BEAM-E2E PASS" in out.stdout


def test_beam_e2e_python_twin():
    # the escript's scenario, verb for verb, value for value — keep the
    # two in sync BY HAND when either changes
    with BridgeServer() as server:
        with BridgeClient("127.0.0.1", server.port) as c:
            assert c.start(b"beam-e2e")[0] == Atom("ok")

            # 1. blind KV write + read back (gset)
            resp = c.put(b"g", "lasp_gset", [b"a", b"b"], n_elems=8)
            assert resp == Atom("ok")
            ok, (t, g) = c.get(b"g")
            assert (ok, t) == (Atom("ok"), Atom("lasp_gset"))
            assert sorted(g) == [b"a", b"b"]

            # 2. OR-Set portable with live + tombstoned tokens
            or_port = [(b"x", [(0, False), (1, True)])]
            resp = c.put(b"o", "lasp_orset", or_port,
                         n_elems=4, n_actors=2, tokens_per_actor=2)
            assert resp == Atom("ok")
            ok, (t, o) = c.get(b"o")
            assert t == Atom("lasp_orset")
            assert o == [(b"x", [(0, False), (1, True)])]

            # 3. anti-entropy merge_batch through the bind gate
            resp = c.merge_batch([(b"o", [(b"x", [(2, False)])])])
            assert resp == (Atom("ok"), 1)
            ok, (_t, o2) = c.get(b"o")
            assert len(o2[0][1]) == 3

            # 4. absent id
            assert c.get(b"missing") == (Atom("error"), Atom("not_found"))
