"""BridgeClient resilience: idempotent-verb retry across a server
kill/restart mid-session (the chaos-mesh satellite), non-idempotent
fail-fast, and per-call timeouts."""

import socket
import time

import pytest

from lasp_tpu.bridge import BridgeClient, BridgeServer
from lasp_tpu.bridge.etf import Atom


def _restart_on(port: int, **kwargs) -> BridgeServer:
    """Bind a fresh server to a just-freed port (SO_REUSEADDR races on
    loaded hosts: retry briefly instead of flaking)."""
    for _ in range(50):
        try:
            server = BridgeServer(port=port, **kwargs)
            server.start()
            return server
        except OSError:
            time.sleep(0.05)
    raise RuntimeError(f"could not rebind port {port}")


def test_idempotent_verbs_survive_server_restart(tmp_path):
    """Kill and restart a DURABLE BridgeServer mid-session: the client's
    reads retry through the outage, reconnect, replay {start, Name}, and
    see the persisted state."""
    data = str(tmp_path / "bridge_data")
    server = BridgeServer(port=0, data_dir=data)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=4,
                         backoff=0.05)
        assert c.start("soak")[0] == Atom("ok")
        c.declare(b"v", "lasp_gset", n_elems=8)
        c.update(b"v", (Atom("add"), b"x"), b"w")
        assert c.get(b"v")[0] == Atom("ok")

        server.stop()
        server = _restart_on(port, data_dir=data)

        # idempotent read: retried + reconnected + session replayed; the
        # durable store's state survived the restart
        resp = c.get(b"v")
        assert resp[0] == Atom("ok")
        # metrics/health work across the same reconnect machinery
        ok, payload = c.metrics()
        assert ok == Atom("ok") and b"bridge_requests_total" in payload
        c.close()
    finally:
        server.stop()


def test_unwrapped_non_idempotent_verbs_fail_fast():
    server = BridgeServer(port=0)
    port = server.start()
    c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=3,
                     backoff=0.01, idem_writes=False)
    assert c.start("s")[0] == Atom("ok")
    c.declare(b"v", "riak_dt_gcounter")
    # connect BEFORE the kill: constructing a client against a stopped
    # server fails in the constructor, not in the verb under test
    c2 = BridgeClient("127.0.0.1", port, timeout=5.0, retries=3,
                      backoff=0.01)
    assert c2.start("s2")[0] == Atom("ok")
    server.stop()
    with pytest.raises(ConnectionError, match="never retried"):
        # with idem_writes off there is no request id: a lost
        # increment's outcome is unknown and blind replay could
        # double-count — the client must fail fast, not retry
        c.update(b"v", (Atom("increment"),), b"w")
    # merge_batch carries no id either way and stays fail-fast
    with pytest.raises(ConnectionError):
        c2.merge_batch([(b"v", [])])
    c.close()
    c2.close()


def test_idem_update_retries_through_kill_restart(tmp_path):
    """The satellite contract: a mid-update server kill/restart. The
    client's update carries a request id, retries through the outage on
    the same backoff path as reads, replays {start, Name}, and applies
    EXACTLY ONCE on the restarted durable store."""
    data = str(tmp_path / "bridge_data")
    server = BridgeServer(port=0, data_dir=data)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=4,
                         backoff=0.05)
        assert c.start("soak")[0] == Atom("ok")
        c.declare(b"v", "riak_dt_gcounter")
        server.stop()  # the server dies mid-session...
        server = _restart_on(port, data_dir=data)
        # ...and the non-idempotent write still lands, once
        ok, value = c.update(b"v", (Atom("increment"),), b"w")
        assert ok == Atom("ok")
        assert value == 1
        c.close()
    finally:
        server.stop()


def test_idem_dedup_suppresses_replay_of_applied_write(tmp_path):
    """The ambiguous-outcome case the dedup window exists for: the op
    APPLIED but the reply was lost. Replaying the identical idem frame
    (what the retry path sends) must return the first response without
    re-executing — including across a durable server restart, where the
    persisted window is the only memory of the first execution."""
    import os

    data = str(tmp_path / "bridge_data")
    server = BridgeServer(port=0, data_dir=data)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=4,
                         backoff=0.05)
        assert c.start("soak")[0] == Atom("ok")
        c.declare(b"v", "riak_dt_gcounter")
        reqid = os.urandom(16)
        frame = (Atom("idem"), reqid, (Atom("update"), b"v",
                                       (Atom("increment"),), b"w"))
        first = c.call(frame, idempotent=True)
        assert first == (Atom("ok"), 1)
        # same-process replay: served from the window, not re-applied
        assert c.call(frame, idempotent=True) == first
        server.stop()
        server = _restart_on(port, data_dir=data)
        # post-restart replay: the window was persisted with the store
        assert c.call(frame, idempotent=True) == first
        assert c.read(b"v") == (Atom("ok"), 1)  # applied exactly once
        c.close()
    finally:
        server.stop()


def test_idem_scope_is_per_connection_for_in_memory_stores():
    """In-memory stores die with their connection: a second connection
    re-using a request id must NOT be answered from another store's
    window (the write never happened on ITS store)."""
    import os

    server = BridgeServer(port=0)
    port = server.start()
    reqid = os.urandom(16)
    frame = (Atom("idem"), reqid, (Atom("update"), b"v",
                                   (Atom("increment"),), b"w"))
    c1 = BridgeClient("127.0.0.1", port)
    assert c1.start("s")[0] == Atom("ok")
    c1.declare(b"v", "riak_dt_gcounter")
    assert c1.call(frame) == (Atom("ok"), 1)
    assert c1.call(frame) == (Atom("ok"), 1)  # deduped
    c2 = BridgeClient("127.0.0.1", port)
    assert c2.start("s")[0] == Atom("ok")
    c2.declare(b"v", "riak_dt_gcounter")
    # fresh store, fresh window: the id executes here
    assert c2.call(frame) == (Atom("ok"), 1)
    c1.close()
    c2.close()


def test_idempotent_retry_exhaustion_raises():
    server = BridgeServer(port=0)
    port = server.start()
    c = BridgeClient("127.0.0.1", port, timeout=0.5, retries=2,
                     backoff=0.01)
    assert c.start("s")[0] == Atom("ok")
    server.stop()  # nothing ever comes back
    with pytest.raises(ConnectionError, match="after 3 attempts"):
        c.metrics()
    c.close()


def test_explicit_idempotent_override_retries_update(tmp_path):
    """A caller that KNOWS its op is an idempotent CRDT write (a set
    add) can opt into replay across a restart."""
    data = str(tmp_path / "bridge_data")
    server = BridgeServer(port=0, data_dir=data)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=4,
                         backoff=0.05)
        assert c.start("s2")[0] == Atom("ok")
        c.declare(b"v", "lasp_gset", n_elems=8)
        server.stop()
        server = _restart_on(port, data_dir=data)
        resp = c.call(
            (Atom("update"), b"v", (Atom("add"), b"x"), b"w"),
            idempotent=True,
        )
        assert resp[0] == Atom("ok")
        c.close()
    finally:
        server.stop()


class _ShedFirst:
    """Admission probe shedding the first ``n`` requests with a
    retry-after hint, then admitting everything."""

    def __init__(self, n, retry_ms=25):
        self.n = n
        self.retry_ms = retry_ms
        self.seen = 0

    def __call__(self, kind):
        self.seen += 1
        return self.retry_ms if self.seen <= self.n else None


def test_busy_reply_backs_off_and_retries_idempotent_reads():
    """The server's {busy, RetryAfterMs} on an overloaded read: the
    client honors the hint (capped jittered backoff), retries on the
    SAME healthy connection, and succeeds once admission clears."""
    shed = _ShedFirst(0)
    server = BridgeServer(port=0, admission=shed)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=4,
                         backoff=0.01)
        assert c.start("s")[0] == Atom("ok")
        c.declare(b"v", "lasp_gset", n_elems=8)
        shed.n, shed.seen = 2, 0  # now shed the next two requests
        t0 = time.time()
        resp = c.get(b"v")
        assert resp[0] == Atom("ok")
        assert shed.seen == 3  # 2 sheds + the admitted retry
        assert time.time() - t0 >= 0.02  # it actually backed off
        c.close()
    finally:
        server.stop()


def test_idem_wrapped_write_retries_through_busy_exactly_once():
    """update/bind ride the idempotent path via {idem, ReqId, _}: a
    busy reply backs off and retries, and the dedup window keeps the
    eventually-admitted write at-most-once."""
    shed = _ShedFirst(0)
    server = BridgeServer(port=0, admission=shed)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=4,
                         backoff=0.01)
        assert c.start("s")[0] == Atom("ok")
        c.declare(b"v", "riak_dt_gcounter")
        shed.n, shed.seen = 1, 0  # shed the next write once
        ok, value = c.update(b"v", (Atom("increment"),), b"w")
        assert ok == Atom("ok") and value == 1
        assert c.read(b"v") == (Atom("ok"), 1)  # applied exactly once
        c.close()
    finally:
        server.stop()


def test_non_idempotent_busy_surfaces_typed_overload_error():
    """With idem_writes off there is no safe replay: a shed write must
    surface a typed OverloadError carrying the retry-after hint, never
    blind-retry and never silently drop."""
    from lasp_tpu.serve import OverloadError

    server = BridgeServer(port=0, admission=lambda kind: 150)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=3,
                         backoff=0.01, idem_writes=False)
        assert c.start("s")[0] == Atom("ok")  # control verbs always pass
        with pytest.raises(OverloadError) as exc:
            c.update(b"v", (Atom("increment"),), b"w")
        assert exc.value.retry_after_ms == 150
        # merge_batch is fail-fast too (its replay is the caller's call)
        with pytest.raises(OverloadError):
            c.merge_batch([(b"v", [])])
        # an idempotent read that stays shed through every attempt also
        # ends in the typed error, not a silent give-up
        with pytest.raises(OverloadError):
            c.call((Atom("keys"),))
        c.close()
    finally:
        server.stop()


def test_metrics_and_health_bypass_admission():
    server = BridgeServer(port=0, admission=lambda kind: 500)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=0)
        ok, payload = c.metrics()
        assert ok == Atom("ok")
        ok, _health = c.health()
        assert ok == Atom("ok")
        c.close()
    finally:
        server.stop()


def test_concurrent_callers_share_one_socket_without_corruption():
    """The satellite bugfix: two threads sharing one BridgeClient used
    to interleave their frames mid-verb and corrupt the wire stream.
    The per-connection lock serializes exchanges; every caller gets
    its own well-formed answer."""
    import threading

    server = BridgeServer(port=0)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=10.0)
        assert c.start("s")[0] == Atom("ok")
        c.declare(b"v", "riak_dt_gcounter", n_actors=32)
        errors: list = []

        def worker(w):
            try:
                for i in range(40):
                    ok, _val = c.update(
                        b"v", (Atom("increment"),), f"w{w}".encode()
                    )
                    assert ok == Atom("ok")
                    ok, total = c.read(b"v")
                    assert ok == Atom("ok")
                    assert isinstance(total, int) and total >= i + 1
            except Exception as exc:  # surfaced after join
                errors.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=worker, args=(w,)) for w in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert c.read(b"v") == (Atom("ok"), 160)
        c.close()
    finally:
        server.stop()


def test_per_call_timeout_applies():
    """The per-call timeout reaches the socket: a server that accepts
    but never answers trips the deadline instead of hanging."""
    sink = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sink.bind(("127.0.0.1", 0))
    sink.listen(1)
    port = sink.getsockname()[1]
    try:
        c = BridgeClient("127.0.0.1", port, timeout=30.0, retries=0)
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            c.call((Atom("metrics"),), timeout=0.3)
        assert time.monotonic() - t0 < 5.0
        c.close()
    finally:
        sink.close()


def test_idem_log_bounded_across_restart_heavy_sessions(tmp_path):
    """The durable idem-record satellite: sessions that each stay under
    the in-session compaction threshold used to grow the host log
    WITHOUT BOUND across restarts (superseded varmeta/leaf records plus
    evicted idem:<reqid> tombstones pile up while the live key set
    stays constant). The open-time waste-cue compaction folds them: the
    file plateaus, the reloaded dedup window stays <= the 256-entry
    bound, and the on-disk idem record count matches it."""
    import os

    data = str(tmp_path / "bridge_data")
    path = os.path.join(data, "soak")
    sizes = []
    for session in range(6):
        server = BridgeServer(port=0, data_dir=data)
        port = server.start()
        c = BridgeClient("127.0.0.1", port, timeout=5.0, retries=2,
                         backoff=0.02)
        assert c.start("soak")[0] == Atom("ok")
        if session == 0:
            c.declare(b"v", "riak_dt_gcounter")
        for _ in range(100):  # < _COMPACT_EVERY: never compacts in-run
            c.update(b"v", (Atom("increment"),), b"w")
        c.close()
        server.stop()
        sizes.append(os.path.getsize(path))
    # bounded: the tail has PLATEAUED — the file oscillates with the
    # compaction phase, so compare same-phase samples (without the
    # open-time compaction it grew ~60KB per session, strictly
    # monotone: [57k, 113k, 172k, 235k, 297k, 360k])
    assert sizes[-1] <= sizes[-3] + 16384, sizes
    assert sizes[-1] < 4 * sizes[0], sizes
    # the reloaded window and the on-disk record census both hold the
    # <= 256 bound after 600 idem-wrapped writes
    from lasp_tpu.store.host_store import HostStore

    hs = HostStore(path)
    try:
        idem_keys = [k for k in hs.keys() if k.startswith("idem:")]
        assert len(idem_keys) <= 256
    finally:
        hs.close()
    server = BridgeServer(port=0, data_dir=data)
    port = server.start()
    try:
        c = BridgeClient("127.0.0.1", port, timeout=5.0)
        assert c.start("soak")[0] == Atom("ok")
        window = server._idem_windows.get("soak")
        assert window is not None and len(window) <= 256
        ok, value = c.get(b"v")  # portable form: (type, [(actor, n)])
        assert ok == Atom("ok") and value[1] == [(b"w", 600)]  # no loss
        c.close()
    finally:
        server.stop()
