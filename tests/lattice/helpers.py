"""Encode/decode bridges between dense codec states and the Python oracle."""

from __future__ import annotations

import numpy as np

from lasp_tpu.lattice import (
    GCounter,
    GCounterSpec,
    GSet,
    GSetSpec,
    IVar,
    IVarSpec,
    ORSet,
    ORSetSpec,
)


def decode_gset(spec: GSetSpec, state, elems):
    mask = np.asarray(state.mask)
    return frozenset(elems[i] for i in range(spec.n_elems) if mask[i])


def encode_gset(spec: GSetSpec, model, elems):
    state = GSet.new(spec)
    for e in model:
        state = GSet.add(spec, state, elems.index(e))
    return state


def decode_gcounter(spec: GCounterSpec, state):
    counts = np.asarray(state.counts)
    return {a: int(counts[a]) for a in range(spec.n_actors) if counts[a] != 0}


def decode_ivar(state):
    return int(np.asarray(state.value)) if bool(np.asarray(state.defined)) else None


def decode_orswot(spec, state, elems):
    """Dense (clock, dots) -> (clock dict, entries dict elem -> actor -> ctr)."""
    clock = np.asarray(state.clock)
    dots = np.asarray(state.dots)
    cdict = {a: int(clock[a]) for a in range(spec.n_actors) if clock[a] != 0}
    entries = {}
    for e in range(spec.n_elems):
        row = {
            a: int(dots[e, a]) for a in range(spec.n_actors) if dots[e, a] != 0
        }
        if row:
            entries[elems[e]] = row
    return (cdict, entries)


def decode_orset(spec: ORSetSpec, state, elems):
    """Dense (exists, removed) -> dict elem -> dict((actor, k) -> removed)."""
    exists = np.asarray(state.exists)
    removed = np.asarray(state.removed)
    k = spec.tokens_per_actor
    out = {}
    for e in range(spec.n_elems):
        toks = {}
        for t in range(spec.n_tokens):
            if exists[e, t]:
                toks[(t // k, t % k)] = bool(removed[e, t])
        if toks:
            out[elems[e]] = toks
    return out


def encode_orset(spec: ORSetSpec, model, elems):
    state = ORSet.new(spec)
    k = spec.tokens_per_actor
    for elem, tokens in model.items():
        e = elems.index(elem)
        for (actor, kk), rem in sorted(tokens.items()):
            assert kk < k, "model token out of dense pool range"
            state = ORSet.add_by_token(spec, state, e, actor * k + kk)
            if rem:
                state = state._replace(
                    removed=state.removed.at[e, actor * k + kk].set(True)
                )
    return state
