"""Encode/decode bridges between dense codec states and the Python oracle."""

from __future__ import annotations

import numpy as np

from lasp_tpu.lattice import (
    GCounter,
    GCounterSpec,
    GSet,
    GSetSpec,
    IVar,
    IVarSpec,
    ORSet,
    ORSetSpec,
)


def decode_gset(spec: GSetSpec, state, elems):
    mask = np.asarray(state.mask)
    return frozenset(elems[i] for i in range(spec.n_elems) if mask[i])


def encode_gset(spec: GSetSpec, model, elems):
    state = GSet.new(spec)
    for e in model:
        state = GSet.add(spec, state, elems.index(e))
    return state


def decode_gcounter(spec: GCounterSpec, state):
    counts = np.asarray(state.counts)
    return {a: int(counts[a]) for a in range(spec.n_actors) if counts[a] != 0}


def decode_ivar(state):
    return int(np.asarray(state.value)) if bool(np.asarray(state.defined)) else None


def decode_dot_matrix(clock, dots, keys):
    """Shared (clock, dot-matrix) decode: nonzero-filtered clock dict plus
    ``key -> {actor: counter}`` entries (ORSWOT elements and Map field
    presence use the identical convention)."""
    clock = np.asarray(clock)
    dots = np.asarray(dots)
    cdict = {a: int(c) for a, c in enumerate(clock) if c != 0}
    entries = {}
    for i, key in enumerate(keys):
        row = {a: int(c) for a, c in enumerate(dots[i]) if c != 0}
        if row:
            entries[key] = row
    return cdict, entries


def decode_orswot(spec, state, elems):
    """Dense (clock, dots) -> (clock dict, entries dict elem -> actor -> ctr)."""
    return decode_dot_matrix(state.clock, state.dots, elems[: spec.n_elems])


def decode_orset(spec: ORSetSpec, state, elems):
    """Dense (exists, removed) -> dict elem -> dict((actor, k) -> removed)."""
    exists = np.asarray(state.exists)
    removed = np.asarray(state.removed)
    k = spec.tokens_per_actor
    out = {}
    for e in range(spec.n_elems):
        toks = {}
        for t in range(spec.n_tokens):
            if exists[e, t]:
                toks[(t // k, t % k)] = bool(removed[e, t])
        if toks:
            out[elems[e]] = toks
    return out


def encode_orset(spec: ORSetSpec, model, elems):
    state = ORSet.new(spec)
    k = spec.tokens_per_actor
    for elem, tokens in model.items():
        e = elems.index(elem)
        for (actor, kk), rem in sorted(tokens.items()):
            assert kk < k, "model token out of dense pool range"
            state = ORSet.add_by_token(spec, state, e, actor * k + kk)
            if rem:
                state = state._replace(
                    removed=state.removed.at[e, actor * k + kk].set(True)
                )
    return state


def decode_map(spec, state, elems):
    """Dense MapState -> (clock dict, fdots dict fname -> actor -> ctr,
    fields dict fname -> decoded inner state) — the PyMap model shape.
    Assumes the statem schema: field 0 a GSet over ``elems``, field 1 a
    GCounter."""
    cdict, fdots = decode_dot_matrix(
        state.clock, state.dots, [f[0] for f in spec.fields]
    )
    (sname, _sc, sspec), (cname, _cc, cspec) = spec.fields
    fields = {
        sname: decode_gset(sspec, state.fields[0], elems),
        cname: decode_gcounter(cspec, state.fields[1]),
    }
    if state.epochs is None:
        return (cdict, fdots, fields)
    epochs = {
        f[0]: int(e)
        for f, e in zip(spec.fields, np.asarray(state.epochs))
    }
    # tombs carry entries for counter fields only (gset is epoch-gated)
    tombs = {
        cname: decode_gcounter(cspec, GCounter.new(cspec)._replace(
            counts=state.tombs[1])),
    }
    return (cdict, fdots, fields, epochs, tombs)
