"""Inflation / strict-inflation truth tables for every type, mirroring the
reference EUnit suite embedded in ``src/lasp_lattice.erl:314-613``."""

import jax.numpy as jnp

from lasp_tpu.lattice import (
    GCounter,
    GCounterSpec,
    GSet,
    GSetSpec,
    IVar,
    IVarSpec,
    ORSet,
    ORSetSpec,
    Threshold,
)


def b(x):
    return bool(jnp.asarray(x))


class TestIVar:
    spec = IVarSpec()

    def states(self):
        # A1/B1 fresh; A2 = set 1; B2 = set 2 (lasp_ivar_inflation_test)
        a1 = IVar.new(self.spec)
        b1 = IVar.new(self.spec)
        a2 = IVar.set(self.spec, a1, 1)
        b2 = IVar.set(self.spec, b1, 2)
        return a1, b1, a2, b2

    def test_inflation(self):
        a1, b1, a2, b2 = self.states()
        assert b(IVar.is_inflation(self.spec, a1, b1))
        assert b(IVar.is_inflation(self.spec, a1, a2))
        assert not b(IVar.is_inflation(self.spec, a2, b2))

    def test_strict_inflation(self):
        a1, b1, a2, b2 = self.states()
        assert not b(IVar.is_strict_inflation(self.spec, a1, b1))
        assert b(IVar.is_strict_inflation(self.spec, a1, a2))
        assert not b(IVar.is_strict_inflation(self.spec, a2, b2))

    def test_threshold(self):
        # src/lasp_lattice.erl:51-60
        spec = self.spec
        undef = IVar.new(spec)
        bound = IVar.set(spec, undef, 7)
        strict_undef = Threshold(undef, strict=True)
        assert not b(IVar.threshold_met(spec, undef, strict_undef))
        assert b(IVar.threshold_met(spec, undef, Threshold(undef)))
        assert b(IVar.threshold_met(spec, bound, strict_undef))
        assert b(IVar.threshold_met(spec, bound, Threshold(IVar.set(spec, undef, 7))))
        assert not b(
            IVar.threshold_met(spec, bound, Threshold(IVar.set(spec, undef, 8)))
        )


class TestGSet:
    spec = GSetSpec(n_elems=4)

    def states(self):
        a1 = GSet.new(self.spec)
        b1 = GSet.new(self.spec)
        a2 = GSet.add(self.spec, a1, 1)
        b2 = GSet.add(self.spec, b1, 2)
        return a1, b1, a2, b2

    def test_inflation(self):
        a1, b1, a2, b2 = self.states()
        assert b(GSet.is_inflation(self.spec, a1, b1))
        assert b(GSet.is_inflation(self.spec, a1, a2))
        assert not b(GSet.is_inflation(self.spec, a2, b2))

    def test_strict_inflation(self):
        a1, b1, a2, b2 = self.states()
        assert not b(GSet.is_strict_inflation(self.spec, a1, b1))
        assert b(GSet.is_strict_inflation(self.spec, a1, a2))
        assert not b(GSet.is_strict_inflation(self.spec, a2, b2))

    def test_threshold_is_inflation_of_threshold(self):
        # src/lasp_lattice.erl:62-65
        a1, _, a2, _ = self.states()
        assert b(GSet.threshold_met(self.spec, a2, Threshold(a1)))
        assert b(GSet.threshold_met(self.spec, a2, Threshold(a1, strict=True)))
        assert b(GSet.threshold_met(self.spec, a2, Threshold(a2)))
        assert not b(GSet.threshold_met(self.spec, a2, Threshold(a2, strict=True)))


class TestGCounter:
    spec = GCounterSpec(n_actors=2)

    def states(self):
        # actors: a=0, b=1 (riak_dt_gcounter_inflation_test)
        a1 = GCounter.new(self.spec)
        b1 = GCounter.new(self.spec)
        a2 = GCounter.increment(self.spec, a1, 0)
        a3 = GCounter.increment(self.spec, a2, 0)
        b2 = GCounter.increment(self.spec, b1, 1)
        return a1, b1, a2, a3, b2

    def test_inflation(self):
        a1, b1, a2, a3, b2 = self.states()
        assert b(GCounter.is_inflation(self.spec, a1, b1))
        assert not b(GCounter.is_inflation(self.spec, a2, b1))
        assert b(GCounter.is_inflation(self.spec, a1, a2))
        assert b(GCounter.is_inflation(self.spec, b1, a2))
        assert not b(GCounter.is_inflation(self.spec, a2, b2))

    def test_strict_inflation(self):
        a1, b1, a2, a3, b2 = self.states()
        assert not b(GCounter.is_strict_inflation(self.spec, a1, b1))
        assert not b(GCounter.is_strict_inflation(self.spec, a2, b1))
        assert b(GCounter.is_strict_inflation(self.spec, a1, a2))
        assert b(GCounter.is_strict_inflation(self.spec, b1, a2))
        # concurrent: value shortcut says not strict (equal totals)
        assert not b(GCounter.is_strict_inflation(self.spec, a2, b2))
        assert not b(GCounter.is_strict_inflation(self.spec, a2, a2))
        assert b(GCounter.is_strict_inflation(self.spec, a2, a3))

    def test_threshold_numeric(self):
        # src/lasp_lattice.erl:87-90
        _, _, a2, a3, _ = self.states()
        assert b(GCounter.threshold_met(self.spec, a2, Threshold(1)))
        assert not b(GCounter.threshold_met(self.spec, a2, Threshold(1, strict=True)))
        assert b(GCounter.threshold_met(self.spec, a3, Threshold(1, strict=True)))
        assert not b(GCounter.threshold_met(self.spec, a2, Threshold(5)))


class TestORSet:
    spec = ORSetSpec(n_elems=4, n_actors=2, tokens_per_actor=2)

    def states(self):
        # actors a=0, b=1 (lasp_orset_inflation_test)
        a1 = ORSet.new(self.spec)
        b1 = ORSet.new(self.spec)
        a2 = ORSet.add(self.spec, a1, 1, 0)
        b2 = ORSet.add(self.spec, b1, 2, 1)
        a3 = ORSet.remove(self.spec, a2, 1)
        return a1, b1, a2, b2, a3

    def test_inflation(self):
        a1, b1, a2, b2, a3 = self.states()
        assert b(ORSet.is_inflation(self.spec, a1, b1))
        assert b(ORSet.is_inflation(self.spec, a1, a2))
        assert not b(ORSet.is_inflation(self.spec, a2, b2))
        assert b(ORSet.is_inflation(self.spec, a2, a3))

    def test_strict_inflation(self):
        a1, b1, a2, b2, a3 = self.states()
        assert not b(ORSet.is_strict_inflation(self.spec, a1, b1))
        assert b(ORSet.is_strict_inflation(self.spec, a1, a2))
        assert not b(ORSet.is_strict_inflation(self.spec, a2, b2))
        # tombstone flip is a strict inflation (src/lasp_lattice.erl:244-251)
        assert b(ORSet.is_strict_inflation(self.spec, a2, a3))

    def test_value_and_removed(self):
        _, _, a2, _, a3 = self.states()
        assert list(map(bool, ORSet.value(self.spec, a2))) == [False, True, False, False]
        assert list(map(bool, ORSet.value(self.spec, a3))) == [False] * 4
        assert list(map(bool, ORSet.removed_value(self.spec, a3))) == [
            False,
            True,
            False,
            False,
        ]

    def test_stats(self):
        _, _, a2, _, a3 = self.states()
        assert ORSet.stats(self.spec, a2) == {
            "element_count": 1,
            "adds_count": 1,
            "removes_count": 0,
            "waste_pct": 0,
            "full_pools": 0,
        }
        s3 = ORSet.stats(self.spec, a3)
        assert s3["element_count"] == 1
        assert s3["adds_count"] == 0
        assert s3["removes_count"] == 1
