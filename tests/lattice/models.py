"""Pure-Python oracle models of the reference CRDT semantics.

These mirror the Erlang implementations exactly (cited per class) and serve
the role the EQC statem model plays in the reference test suite
(``test/crdt_statem_eqc.erl``): random op sequences run against both the
dense tensor codec and this model, and the decoded codec state must match.

Tokens are ``(actor, k)`` tuples — the deterministic counterpart of the
reference's 20 random bytes (``src/lasp_orset.erl:261-262``).
"""

from __future__ import annotations


class PyIVar:
    """Oracle for ``src/lasp_ivar.erl``: None = undefined; merge is
    defined-wins; conflicting defined merge resolves to max (documented
    lasp_tpu deviation — the reference has no clause for it)."""

    @staticmethod
    def new():
        return None

    @staticmethod
    def set(state, value):
        return value if state is None else state

    @staticmethod
    def merge(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return max(a, b)

    @staticmethod
    def value(state):
        return state

    @staticmethod
    def is_inflation(prev, cur):
        # src/lasp_lattice.erl:126-135
        if prev is None:
            return True
        return prev == cur

    @staticmethod
    def is_strict_inflation(prev, cur):
        # src/lasp_lattice.erl:204-210
        return prev is None and cur is not None


class PyGSet:
    """Oracle for ``src/lasp_gset.erl``: frozenset semantics."""

    @staticmethod
    def new():
        return frozenset()

    @staticmethod
    def add(state, elem):
        return state | {elem}

    @staticmethod
    def merge(a, b):
        return a | b

    @staticmethod
    def value(state):
        return state

    @staticmethod
    def is_inflation(prev, cur):
        return prev <= cur

    @staticmethod
    def is_strict_inflation(prev, cur):
        return prev <= cur and prev != cur


class PyGCounter:
    """Oracle for ``riak_dt_gcounter`` semantics as consumed by the
    reference (``src/lasp_lattice.erl:169-179, 273-275``): dict actor->count."""

    @staticmethod
    def new():
        return {}

    @staticmethod
    def increment(state, actor, by=1):
        out = dict(state)
        out[actor] = out.get(actor, 0) + by
        return out

    @staticmethod
    def merge(a, b):
        out = dict(a)
        for actor, count in b.items():
            out[actor] = max(out.get(actor, 0), count)
        return out

    @staticmethod
    def value(state):
        return sum(state.values())

    @staticmethod
    def is_inflation(prev, cur):
        return all(cur.get(a, -1) >= c for a, c in prev.items())

    @staticmethod
    def is_strict_inflation(prev, cur):
        # total-value shortcut per src/lasp_lattice.erl:273-275
        return PyGCounter.value(prev) < PyGCounter.value(cur)


class PyORSWOT:
    """Oracle for ``riak_dt_orswot`` as consumed by the framework
    (``src/lasp_lattice.erl:163-167, 255-262``): state = (clock dict
    actor -> max event, entries dict elem -> dict actor -> birth counter).
    ``add`` bumps the actor clock and replaces the element's dots with the
    fresh single dot; ``remove`` drops the entry; ``merge`` keeps a dot iff
    both sides hold it or the other side's clock has not seen it."""

    @staticmethod
    def new():
        return ({}, {})

    @staticmethod
    def add(state, elem, actor):
        clock, entries = state
        clock = dict(clock)
        clock[actor] = clock.get(actor, 0) + 1
        entries = {e: dict(d) for e, d in entries.items()}
        entries[elem] = {actor: clock[actor]}
        return (clock, entries)

    @staticmethod
    def remove(state, elem):
        clock, entries = state
        if elem not in entries:
            raise KeyError(f"precondition: not_present {elem!r}")
        entries = {e: dict(d) for e, d in entries.items() if e != elem}
        return (clock, entries)

    @staticmethod
    def merge(a, b):
        ca, ea = a
        cb, eb = b
        return merge_dot_entries(ca, ea, cb, eb)

    @staticmethod
    def value(state):
        return frozenset(state[1])

    @staticmethod
    def is_inflation(prev, cur):
        # vclock descends (src/lasp_lattice.erl:163-164)
        return all(cur[0].get(a, 0) >= c for a, c in prev[0].items())

    @staticmethod
    def is_strict_inflation(prev, cur):
        # src/lasp_lattice.erl:255-262
        if not PyORSWOT.is_inflation(prev, cur):
            return False
        pc = {a: c for a, c in prev[0].items() if c}
        cc = {a: c for a, c in cur[0].items() if c}
        equal_clocks = pc == cc
        dominates = not equal_clocks
        deleted = len(cur[1]) < len(prev[1])
        return (equal_clocks and deleted) or dominates


class PyORSet:
    """Oracle for ``src/lasp_orset.erl``: dict elem -> dict(token -> removed?).

    ``add`` mints the actor's next counter token (deterministic identity);
    ``remove`` tombstones all observed tokens (:232-241); ``merge`` unions
    tokens and ORs flags (:128-134); ``value`` keeps elements with a live
    token (:67-73)."""

    @staticmethod
    def new():
        return {}

    @staticmethod
    def add(state, elem, actor):
        out = {e: dict(t) for e, t in state.items()}
        tokens = out.setdefault(elem, {})
        k = sum(1 for (a, _k) in tokens if a == actor)
        tokens[(actor, k)] = False
        return out

    @staticmethod
    def remove(state, elem):
        if elem not in state:
            raise KeyError(f"precondition: not_present {elem!r}")
        out = {e: dict(t) for e, t in state.items()}
        out[elem] = {tok: True for tok in out[elem]}
        return out

    @staticmethod
    def merge(a, b):
        out = {e: dict(t) for e, t in a.items()}
        for elem, tokens in b.items():
            dst = out.setdefault(elem, {})
            for tok, removed in tokens.items():
                dst[tok] = dst.get(tok, False) or removed
        return out

    @staticmethod
    def value(state):
        return frozenset(
            e for e, toks in state.items() if any(not r for r in toks.values())
        )

    @staticmethod
    def is_inflation(prev, cur):
        # src/lasp_lattice.erl:153-161 + ids_inflated :277-285 (flags ignored)
        return all(
            elem in cur and all(tok in cur[elem] for tok in tokens)
            for elem, tokens in prev.items()
        )

    @staticmethod
    def is_strict_inflation(prev, cur):
        # src/lasp_lattice.erl:235-253
        if not prev and cur:
            return True
        if not PyORSet.is_inflation(prev, cur):
            return False
        deleted = any(
            elem in cur and tokens != cur[elem] for elem, tokens in prev.items()
        )
        new_elems = len(prev) < len(cur)
        return deleted or new_elems


def merge_dot_entries(ca, ea, cb, eb):
    """The shared dot-survival rule (riak_dt vclock merge): keep a dot iff
    both sides hold it, or one side holds it and the other's clock has not
    yet seen it. Entries are ``key -> {actor: counter}``; used for ORSWOT
    elements and Map field presence alike (lattice/dots.py twin)."""
    clock = dict(ca)
    for actor, c in cb.items():
        clock[actor] = max(clock.get(actor, 0), c)
    entries = {}
    for key in set(ea) | set(eb):
        da = ea.get(key, {})
        db = eb.get(key, {})
        keep = {}
        for actor in set(da) | set(db):
            va, vb = da.get(actor, 0), db.get(actor, 0)
            kept = 0
            if va and (va == vb or va > cb.get(actor, 0)):
                kept = max(kept, va)
            if vb and (vb == va or vb > ca.get(actor, 0)):
                kept = max(kept, vb)
            if kept:
                keep[actor] = kept
        if keep:
            entries[key] = keep
    return clock, entries


class PyMap:
    """Oracle for the DENSE riak_dt_map semantics (lattice/map.py): static
    field schema, OR-SWOT presence dots over field names, and — the
    documented divergence from the reference — field CONTENTS that stay
    join-monotone across remove/re-add (presence only controls
    visibility). State = (clock, fdots: fname -> {actor: ctr},
    fields: fname -> inner model state)."""

    SCHEMA = ()  # (fname, inner_model) pairs; set by the harness

    @classmethod
    def new(cls):
        return ({}, {}, {f: m.new() for f, m in cls.SCHEMA})

    @classmethod
    def update(cls, state, fname, actor, inner_fn):
        clock, fdots, fields = state
        clock = dict(clock)
        clock[actor] = clock.get(actor, 0) + 1
        fdots = {f: dict(d) for f, d in fdots.items()}
        fdots[fname] = {actor: clock[actor]}  # mint REPLACES the dot row
        fields = dict(fields)
        fields[fname] = inner_fn(fields[fname])
        return (clock, fdots, fields)

    @classmethod
    def remove(cls, state, fname):
        clock, fdots, fields = state
        if fname not in fdots:
            raise KeyError(f"precondition: not_present {fname!r}")
        fdots = {f: dict(d) for f, d in fdots.items() if f != fname}
        return (clock, fdots, fields)

    @classmethod
    def merge(cls, a, b):
        ca, fa, ia = a
        cb, fb, ib = b
        clock, fdots = merge_dot_entries(ca, fa, cb, fb)
        fields = {f: m.merge(ia[f], ib[f]) for f, m in cls.SCHEMA}
        return (clock, fdots, fields)

    @classmethod
    def value(cls, state):
        return frozenset(state[1])


class PyResetMap(PyMap):
    """Oracle for riak_dt reset-remove semantics (lattice/map.py round 5),
    per embedded type exactly as the dense codec scopes them:

    - counter fields: a remove records the OBSERVED lane counts as a
      tombstone baseline (lane-max joined); contents keep joining
      plainly and the observable subtracts the floor — a concurrent
      increment survives its field's reset.
    - gset fields (epoch-gated — no tokens to tell a re-add from a
      merged copy): a remove resets contents to bottom and bumps the
      field's epoch; merge joins gset contents only between equal eras.

    Epochs bump on every remove (the strict-inflation witness). State =
    (clock, fdots, fields, epochs, tombs); tombs carries entries for
    counter fields only."""

    @classmethod
    def _floored(cls, fname):
        return dict(cls.SCHEMA)[fname] is PyGCounter

    @classmethod
    def new(cls):
        return (
            {},
            {},
            {f: m.new() for f, m in cls.SCHEMA},
            {f: 0 for f, _m in cls.SCHEMA},
            {f: m.new() for f, m in cls.SCHEMA if cls._floored(f)},
        )

    @classmethod
    def update(cls, state, fname, actor, inner_fn):
        clock, fdots, fields, epochs, tombs = state
        c, fd, fl = PyMap.update((clock, fdots, fields), fname, actor, inner_fn)
        return (c, fd, fl, dict(epochs), dict(tombs))

    @classmethod
    def remove(cls, state, fname):
        clock, fdots, fields, epochs, tombs = state
        c, fd, fl = PyMap.remove((clock, fdots, fields), fname)
        m = dict(cls.SCHEMA)[fname]
        fl = dict(fl)
        tombs = dict(tombs)
        if cls._floored(fname):
            tombs[fname] = m.merge(tombs[fname], fields[fname])  # observed
        else:
            fl[fname] = m.new()  # epoch-gated: bottom-reset
        epochs = dict(epochs)
        epochs[fname] += 1
        return (c, fd, fl, epochs, tombs)

    @classmethod
    def merge(cls, a, b):
        ca, fa, ia, ea, ta = a
        cb, fb, ib, eb, tb = b
        clock, fdots = merge_dot_entries(ca, fa, cb, fb)
        epochs = {f: max(ea[f], eb[f]) for f, _m in cls.SCHEMA}
        fields = {}
        for f, m in cls.SCHEMA:
            if cls._floored(f):
                fields[f] = m.merge(ia[f], ib[f])
            else:
                xa = ia[f] if ea[f] == epochs[f] else m.new()
                xb = ib[f] if eb[f] == epochs[f] else m.new()
                fields[f] = m.merge(xa, xb)
        tombs = {f: PyGCounter.merge(ta[f], tb[f]) for f in ta}
        return (clock, fdots, fields, epochs, tombs)
