"""EQC-statem analogue (``test/crdt_statem_eqc.erl``): random op sequences
across virtual replicas; the fold-merge of all replicas must equal the
Python-oracle model (convergence, ``prop_converge`` :91-106), merges must be
commutative/associative/idempotent, and the fixed point must be independent
of merge schedule (the determinism property that replaces race detection —
SURVEY.md §5)."""

import itertools
import random

import numpy as np
import pytest

import jax

from lasp_tpu.lattice import (
    GCounter,
    GCounterSpec,
    GSet,
    GSetSpec,
    ORSWOT,
    ORSWOTSpec,
    ORSet,
    ORSetSpec,
)

from .helpers import decode_gcounter, decode_gset, decode_orset, decode_orswot
from .models import PyGCounter, PyGSet, PyORSWOT, PyORSet

N_REPLICAS = 5
#: ops per sequence; LASP_STATEM_OPS deepens a soak run toward the
#: reference's EQC scale (1000 random sequences per type,
#: test/crdt_statem_eqc.erl:34) without slowing every CI pass
import os as _os  # noqa: E402

N_OPS = int(_os.environ.get("LASP_STATEM_OPS", "40"))
ELEMS = ["apple", "pear", "plum", "fig", "kiwi", "lime"]


def run_gset(seed):
    rng = random.Random(seed)
    spec = GSetSpec(n_elems=len(ELEMS))
    dense = [GSet.new(spec) for _ in range(N_REPLICAS)]
    model = [PyGSet.new() for _ in range(N_REPLICAS)]
    for _ in range(N_OPS):
        r = rng.randrange(N_REPLICAS)
        if rng.random() < 0.7:
            e = rng.randrange(len(ELEMS))
            dense[r] = GSet.add(spec, dense[r], e)
            model[r] = PyGSet.add(model[r], ELEMS[e])
        else:
            r2 = rng.randrange(N_REPLICAS)
            dense[r] = GSet.merge(spec, dense[r], dense[r2])
            model[r] = PyGSet.merge(model[r], model[r2])
    return spec, dense, model


def run_gcounter(seed):
    rng = random.Random(seed)
    spec = GCounterSpec(n_actors=N_REPLICAS)
    dense = [GCounter.new(spec) for _ in range(N_REPLICAS)]
    model = [PyGCounter.new() for _ in range(N_REPLICAS)]
    for _ in range(N_OPS):
        r = rng.randrange(N_REPLICAS)
        if rng.random() < 0.7:
            dense[r] = GCounter.increment(spec, dense[r], r)
            model[r] = PyGCounter.increment(model[r], r)
        else:
            r2 = rng.randrange(N_REPLICAS)
            dense[r] = GCounter.merge(spec, dense[r], dense[r2])
            model[r] = PyGCounter.merge(model[r], model[r2])
    return spec, dense, model


def run_orset(seed):
    rng = random.Random(seed)
    spec = ORSetSpec(n_elems=len(ELEMS), n_actors=N_REPLICAS, tokens_per_actor=16)
    dense = [ORSet.new(spec) for _ in range(N_REPLICAS)]
    model = [PyORSet.new() for _ in range(N_REPLICAS)]
    for _ in range(N_OPS):
        r = rng.randrange(N_REPLICAS)
        roll = rng.random()
        if roll < 0.5:
            e = rng.randrange(len(ELEMS))
            # actor = replica id, like the EQC model's per-replica actor.
            # Skip adds past the dense pool capacity: the codec drops them
            # (documented fixed-shape behaviour) while the oracle is
            # unbounded, so the driver keeps both in the common domain.
            k_used = sum(
                1 for (a, _k) in model[r].get(ELEMS[e], {}) if a == r
            )
            if k_used < spec.tokens_per_actor:
                dense[r] = ORSet.add(spec, dense[r], e, r)
                model[r] = PyORSet.add(model[r], ELEMS[e], r)
        elif roll < 0.7 and model[r]:
            elem = rng.choice(sorted(model[r]))
            e = ELEMS.index(elem)
            dense[r] = ORSet.remove(spec, dense[r], e)
            model[r] = PyORSet.remove(model[r], elem)
        else:
            r2 = rng.randrange(N_REPLICAS)
            dense[r] = ORSet.merge(spec, dense[r], dense[r2])
            model[r] = PyORSet.merge(model[r], model[r2])
    return spec, dense, model


def run_orswot(seed):
    rng = random.Random(seed)
    spec = ORSWOTSpec(n_elems=len(ELEMS), n_actors=N_REPLICAS)
    dense = [ORSWOT.new(spec) for _ in range(N_REPLICAS)]
    model = [PyORSWOT.new() for _ in range(N_REPLICAS)]
    for _ in range(N_OPS):
        r = rng.randrange(N_REPLICAS)
        roll = rng.random()
        if roll < 0.5:
            e = rng.randrange(len(ELEMS))
            dense[r] = ORSWOT.add(spec, dense[r], e, r)
            model[r] = PyORSWOT.add(model[r], ELEMS[e], r)
        elif roll < 0.7 and model[r][1]:
            elem = rng.choice(sorted(model[r][1]))
            dense[r] = ORSWOT.remove(spec, dense[r], ELEMS.index(elem))
            model[r] = PyORSWOT.remove(model[r], elem)
        else:
            r2 = rng.randrange(N_REPLICAS)
            dense[r] = ORSWOT.merge(spec, dense[r], dense[r2])
            model[r] = PyORSWOT.merge(model[r], model[r2])
    return spec, dense, model


CASES = {
    "gset": (run_gset, GSet, decode_gset, PyGSet, True),
    "gcounter": (run_gcounter, GCounter, decode_gcounter, PyGCounter, False),
    "orset": (run_orset, ORSet, decode_orset, PyORSet, True),
    "orswot": (run_orswot, ORSWOT, decode_orswot, PyORSWOT, True),
}


@pytest.mark.parametrize("name", CASES)
@pytest.mark.parametrize("seed", range(8))
def test_converge(name, seed):
    """prop_converge: merged dense state decodes to the merged model state."""
    runner, codec, decode, pymodel, with_elems = CASES[name]
    spec, dense, model = runner(seed)
    merged_d = dense[0]
    merged_m = model[0]
    for d, m in zip(dense[1:], model[1:]):
        merged_d = codec.merge(spec, merged_d, d)
        merged_m = pymodel.merge(merged_m, m)
    decoded = decode(spec, merged_d, ELEMS) if with_elems else decode(spec, merged_d)
    assert decoded == merged_m
    if with_elems:
        value_decoded = {
            ELEMS[i]
            for i, v in enumerate(np.asarray(codec.value(spec, merged_d)))
            if v
        }
        assert value_decoded == set(pymodel.value(merged_m))


@pytest.mark.parametrize("name", CASES)
def test_merge_schedule_independence(name):
    """Determinism: any permutation / tree shape of merges reaches the same
    state — the property that makes BSP rounds equivalent to async gossip."""
    runner, codec, _, _, _ = CASES[name]
    spec, dense, _ = runner(123)

    def fold(order):
        acc = dense[order[0]]
        for i in order[1:]:
            acc = codec.merge(spec, acc, dense[i])
        return acc

    base = fold(list(range(N_REPLICAS)))
    for perm in itertools.islice(itertools.permutations(range(N_REPLICAS)), 12):
        other = fold(list(perm))
        assert bool(codec.equal(spec, base, other))
    # idempotence: merging the fixed point with any input is a no-op
    for i in range(N_REPLICAS):
        assert bool(codec.equal(spec, base, codec.merge(spec, base, dense[i])))


@pytest.mark.parametrize("name", CASES)
def test_vmapped_merge_matches_loop(name):
    """The replica-axis vmap of merge (the TPU kernel form) agrees with the
    per-replica loop."""
    runner, codec, _, _, _ = CASES[name]
    spec, dense, _ = runner(7)
    stack = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *dense)
    rolled = jax.tree_util.tree_map(lambda x: np.roll(x, 1, axis=0), stack)
    vmerged = jax.vmap(lambda a, b: codec.merge(spec, a, b))(stack, rolled)
    for i in range(N_REPLICAS):
        expect = codec.merge(spec, dense[i], dense[(i - 1) % N_REPLICAS])
        got = jax.tree_util.tree_map(lambda x: x[i], vmerged)
        assert bool(codec.equal(spec, expect, got))


def test_orswot_inflation_matches_model():
    spec, dense, model = run_orswot(31)
    for i in range(N_REPLICAS):
        for j in range(N_REPLICAS):
            assert bool(ORSWOT.is_inflation(spec, dense[i], dense[j])) == (
                PyORSWOT.is_inflation(model[i], model[j])
            ), (i, j)
            assert bool(ORSWOT.is_strict_inflation(spec, dense[i], dense[j])) == (
                PyORSWOT.is_strict_inflation(model[i], model[j])
            ), (i, j)


def test_orswot_remove_wins_over_stale_add():
    # the no-tombstone property: a removal propagates to a replica that
    # still holds the element, because its dot is seen by the remover's
    # clock; a concurrent NEWER add survives
    spec = ORSWOTSpec(n_elems=2, n_actors=2)
    a = ORSWOT.add(spec, ORSWOT.new(spec), 0, 0)
    b = ORSWOT.merge(spec, ORSWOT.new(spec), a)  # b observed the add
    b = ORSWOT.remove(spec, b, 0)
    merged = ORSWOT.merge(spec, a, b)
    assert not bool(ORSWOT.value(spec, merged)[0])  # remove wins
    # concurrent re-add at a (unseen by b) must survive the same merge
    a2 = ORSWOT.add(spec, a, 0, 0)
    merged2 = ORSWOT.merge(spec, a2, b)
    assert bool(ORSWOT.value(spec, merged2)[0])


def test_orset_inflation_matches_model():
    spec, dense, model = run_orset(99)
    for i in range(N_REPLICAS):
        for j in range(N_REPLICAS):
            assert bool(ORSet.is_inflation(spec, dense[i], dense[j])) == (
                PyORSet.is_inflation(model[i], model[j])
            ), (i, j)
            assert bool(ORSet.is_strict_inflation(spec, dense[i], dense[j])) == (
                PyORSet.is_strict_inflation(model[i], model[j])
            ), (i, j)


@pytest.mark.parametrize("seed", range(3))
def test_orset_encode_decode_roundtrip(seed):
    """The encode/decode bridges invert each other, pinning the dense token
    layout (actor-major slots) against drift."""
    from .helpers import encode_gset, encode_orset, decode_gset, decode_orset

    spec, dense, model = run_orset(seed)
    gspec, gdense, gmodel = run_gset(seed)
    for d, m in zip(dense, model):
        re_encoded = encode_orset(spec, decode_orset(spec, d, ELEMS), ELEMS)
        assert bool(ORSet.equal(spec, d, re_encoded))
        assert decode_orset(spec, re_encoded, ELEMS) == m
    for d, m in zip(gdense, gmodel):
        re_encoded = encode_gset(gspec, decode_gset(gspec, d, ELEMS), ELEMS)
        assert bool(GSet.equal(gspec, d, re_encoded))


def run_map(seed, reset=False):
    """Statem for the dense riak_dt_map: random field updates (gset add /
    counter increment), observed-field removes, and cross-replica merges,
    against the PyMap oracle (the EQC statem hook riak_dt types provide,
    test/crdt_statem_eqc.erl:50-106, for the composed type). With
    ``reset=True`` the same command sequences run in reset_on_readd mode
    against the PyResetMap oracle."""
    from lasp_tpu.lattice import CrdtMap, MapSpec

    from .models import PyGCounter, PyGSet, PyMap, PyResetMap

    rng = random.Random(seed)
    gspec = GSetSpec(n_elems=len(ELEMS))
    cspec = GCounterSpec(n_actors=N_REPLICAS)
    spec = MapSpec(
        fields=(("s", GSet, gspec), ("c", GCounter, cspec)),
        n_actors=N_REPLICAS,
        reset_on_readd=reset,
    )
    PyMap.SCHEMA = (("s", PyGSet), ("c", PyGCounter))
    cls = PyResetMap if reset else PyMap
    dense = [CrdtMap.new(spec) for _ in range(N_REPLICAS)]
    model = [cls.new() for _ in range(N_REPLICAS)]

    def dense_update(st, f, r, inner_fn):
        st = CrdtMap.touch(spec, st, f, r)
        return CrdtMap.set_field(spec, st, f, inner_fn(st.fields[f]))

    for _ in range(N_OPS):
        r = rng.randrange(N_REPLICAS)
        roll = rng.random()
        if roll < 0.35:
            e = rng.randrange(len(ELEMS))
            dense[r] = dense_update(
                dense[r], 0, r, lambda fs: GSet.add(gspec, fs, e)
            )
            model[r] = cls.update(
                model[r], "s", r, lambda ms: PyGSet.add(ms, ELEMS[e])
            )
        elif roll < 0.55:
            dense[r] = dense_update(
                dense[r], 1, r, lambda fs: GCounter.increment(cspec, fs, r)
            )
            model[r] = cls.update(
                model[r], "c", r, lambda ms: PyGCounter.increment(ms, r)
            )
        elif roll < 0.7 and model[r][1]:
            fname = rng.choice(sorted(model[r][1]))
            f = 0 if fname == "s" else 1
            dense[r] = CrdtMap.remove(spec, dense[r], f)
            model[r] = cls.remove(model[r], fname)
        else:
            r2 = rng.randrange(N_REPLICAS)
            dense[r] = CrdtMap.merge(spec, dense[r], dense[r2])
            model[r] = cls.merge(model[r], model[r2])
    return spec, dense, model


@pytest.mark.parametrize("reset", [False, True])
@pytest.mark.parametrize("seed", range(8))
def test_map_statem_converge(seed, reset):
    """prop_converge for the composed type: fold-merge of all replicas
    decodes to the fold-merged model, and the presence value matches —
    in both re-add modes."""
    from lasp_tpu.lattice import CrdtMap

    from .helpers import decode_map
    from .models import PyMap, PyResetMap

    cls = PyResetMap if reset else PyMap
    spec, dense, model = run_map(seed, reset=reset)
    merged_d, merged_m = dense[0], model[0]
    for d, m in zip(dense[1:], model[1:]):
        merged_d = CrdtMap.merge(spec, merged_d, d)
        merged_m = cls.merge(merged_m, m)
    assert decode_map(spec, merged_d, ELEMS) == merged_m
    present = {
        spec.fields[i][0]
        for i, v in enumerate(np.asarray(CrdtMap.value(spec, merged_d)))
        if v
    }
    assert present == set(cls.value(merged_m))


@pytest.mark.parametrize("reset", [False, True])
@pytest.mark.parametrize("seed", range(4))
def test_map_statem_merge_schedule_independence(seed, reset):
    from lasp_tpu.lattice import CrdtMap

    from .helpers import decode_map

    spec, dense, _model = run_map(seed, reset=reset)
    results = set()
    for perm in itertools.islice(itertools.permutations(range(N_REPLICAS)), 8):
        acc = dense[perm[0]]
        for i in perm[1:]:
            acc = CrdtMap.merge(spec, acc, dense[i])
        decoded = decode_map(spec, acc, ELEMS)
        c, fd, fs = decoded[:3]
        results.add((
            tuple(sorted(c.items())),
            tuple(sorted((f, tuple(sorted(d.items()))) for f, d in fd.items())),
            tuple(sorted(
                (f, v if isinstance(v, frozenset) else tuple(sorted(v.items())))
                for f, v in fs.items()
            )),
            tuple(sorted(decoded[3].items())) if len(decoded) > 3 else (),
        ))
    assert len(results) == 1
