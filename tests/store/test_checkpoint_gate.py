"""The leaf-prefix backfill in checkpoint loading is a SCHEMA-MIGRATION
shim, not a general pardon for truncated snapshots (ISSUE-3 satellite):
only a reset-mode riak_dt_map may load a leaf prefix, and only when the
missing suffix is exactly its tombs planes (the planes round 5 appended
after every pre-existing leaf). Everything else must fail loudly."""

import jax
import numpy as np
import pytest

from lasp_tpu.store import Store
from lasp_tpu.store.checkpoint import (
    _get_state,
    _state_leaf_meta,
    load_store,
    save_store,
)


class _FakeHS:
    """Just enough of HostStore for _get_state: leaf records by key."""

    def __init__(self, records):
        self._r = dict(records)

    def get(self, key):
        return self._r.get(key)


def _leaf_records(var_id, state, keep):
    leaves = jax.tree_util.tree_leaves(state)
    return {
        f"leaf/{var_id}/{i}": np.asarray(leaf).tobytes()
        for i, leaf in enumerate(leaves[:keep])
    }


def _reset_map_var():
    store = Store(n_actors=4)
    m = store.declare(
        id="m", type="riak_dt_map", n_actors=4, reset_on_readd=True,
        fields=[(("C", "riak_dt_gcounter"), "riak_dt_gcounter",
                 {"n_actors": 4})],
    )
    store.update(m, ("update", [("update", ("C", "riak_dt_gcounter"),
                                 ("increment", 3))]), "w")
    return store.variable(m)


def test_reset_map_backfills_exactly_the_tombs_planes():
    var = _reset_map_var()
    n_tombs = len(jax.tree_util.tree_leaves(var.state.tombs))
    assert n_tombs >= 1
    total = len(jax.tree_util.tree_leaves(var.state))
    entry = {
        "type_name": "riak_dt_map",
        "leaves": _state_leaf_meta(var.state)[: total - n_tombs],
    }
    hs = _FakeHS(_leaf_records("m", var.state, total - n_tombs))
    out = _get_state(hs, "m", var.state, entry)
    # restored prefix round-trips; the tombs suffix took the template's
    # planes verbatim
    assert np.array_equal(np.asarray(out.clock), np.asarray(var.state.clock))
    for got, tmpl in zip(
        jax.tree_util.tree_leaves(out.tombs),
        jax.tree_util.tree_leaves(var.state.tombs),
    ):
        assert np.array_equal(np.asarray(got), np.asarray(tmpl))


def test_reset_map_truncated_past_tombs_raises():
    var = _reset_map_var()
    n_tombs = len(jax.tree_util.tree_leaves(var.state.tombs))
    total = len(jax.tree_util.tree_leaves(var.state))
    keep = total - n_tombs - 1  # one non-tombs leaf missing too
    entry = {
        "type_name": "riak_dt_map",
        "leaves": _state_leaf_meta(var.state)[:keep],
    }
    hs = _FakeHS(_leaf_records("m", var.state, keep))
    with pytest.raises(IOError, match="truncated"):
        _get_state(hs, "m", var.state, entry)


def test_non_map_truncation_raises():
    store = Store(n_actors=4)
    s = store.declare(id="s", type="lasp_orset", n_elems=4, n_actors=2)
    store.update(s, ("add", "x"), "w")
    var = store.variable(s)
    total = len(jax.tree_util.tree_leaves(var.state))
    assert total >= 2
    entry = {
        "type_name": "lasp_orset",
        "leaves": _state_leaf_meta(var.state)[: total - 1],
    }
    hs = _FakeHS(_leaf_records("s", var.state, total - 1))
    with pytest.raises(IOError, match="truncated"):
        _get_state(hs, "s", var.state, entry)


def test_default_mode_map_truncation_raises():
    """A NON-reset map has no tombs planes — any short snapshot of it is
    corruption, never migration."""
    store = Store(n_actors=4)
    m = store.declare(
        id="m", type="riak_dt_map", n_actors=4,
        fields=[(("G", "lasp_gset"), "lasp_gset", {"n_elems": 4})],
    )
    store.update(m, ("update", [("update", ("G", "lasp_gset"),
                                 ("add", "a"))]), "w")
    var = store.variable(m)
    total = len(jax.tree_util.tree_leaves(var.state))
    entry = {
        "type_name": "riak_dt_map",
        "leaves": _state_leaf_meta(var.state)[: total - 1],
    }
    hs = _FakeHS(_leaf_records("m", var.state, total - 1))
    with pytest.raises(IOError, match="truncated"):
        _get_state(hs, "m", var.state, entry)


def test_full_round_trip_still_works(tmp_path):
    """The gate must not disturb intact snapshots (reset map included)."""
    store = Store(n_actors=4)
    m = store.declare(
        id="m", type="riak_dt_map", n_actors=4, reset_on_readd=True,
        fields=[(("C", "riak_dt_gcounter"), "riak_dt_gcounter",
                 {"n_actors": 4})],
    )
    store.update(m, ("update", [("update", ("C", "riak_dt_gcounter"),
                                 ("increment", 2))]), "w")
    path = str(tmp_path / "snap.log")
    save_store(store, path)
    loaded = load_store(path)
    assert loaded.value(m) == store.value(m)
