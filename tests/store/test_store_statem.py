"""EQC-statem analogue of ``test/lasp_eqc.erl`` — the STORE-semantics
model (the reference's second EQC suite, distinct from the per-CRDT
``crdt_statem_eqc``): random declare / update / stale-rebind / threshold-
read command sequences against a pure-Python model, with

- the bind inflation-gate rule as a postcondition (non-inflations are
  silently ignored, ``src/lasp_core.erl:305-311`` — exactly
  ``lasp_eqc``'s ``bind_next``/``bind_ok``, :96-137),
- data-dependent failures (absent-element removes) leaving the model
  unchanged,
- random sub-lattice thresholds (the :195-219 generator role — the
  reference samples sublists of the current value): parked watches must
  fire EXACTLY when met, never before, and monotonically stay fired.

Depth scales with LASP_STATEM_OPS like tests/lattice/test_statem.py."""

import os
import random

import jax
import jax.numpy as jnp
import pytest

from lasp_tpu.lattice import Threshold
from lasp_tpu.store import PreconditionError, Store

N_SEEDS = int(os.environ.get("LASP_STATEM_SEEDS", "8"))
N_OPS = int(os.environ.get("LASP_STATEM_OPS", "60"))
ELEMS = ["a", "b", "c", "d", "e", "f", "g", "h"]
ACTORS = ["w0", "w1", "w2"]

TYPES = ("lasp_gset", "lasp_orset", "riak_dt_gcounter", "lasp_ivar")


class Model:
    """One variable's model state. Sets track live AND ever-added
    elements: OR-Set threshold semantics are token-coverage, and a
    tombstoned token still counts as observed — so a set threshold,
    once met, stays met across removes."""

    def __init__(self, tname):
        self.tname = tname
        self.live: set = set()
        self.ever: set = set()
        self.counts: dict = {}
        self.payload = None

    def value(self):
        if self.tname == "riak_dt_gcounter":
            return sum(self.counts.values())
        if self.tname == "lasp_ivar":
            return self.payload
        return frozenset(self.live)


def met(model: Model, thr) -> bool:
    kind, arg, strict = thr
    if kind == "count":
        total = sum(model.counts.values())
        return total > arg if strict else total >= arg
    if kind == "defined":
        return model.payload is not None
    # kind == "subset": token coverage over ever-observed elements
    return set(arg) <= model.ever


def subset_threshold_state(store, vid, subset):
    """Threshold state = the variable's CURRENT state with every element
    row outside ``subset`` zeroed — a random sub-lattice point, like the
    reference's random sublists of Value0 (:205-218)."""
    var = store.variable(vid)
    idx = [var.elems.index_of(e) for e in subset]
    mask = jnp.zeros((var.spec.n_elems,), bool)
    if idx:
        mask = mask.at[jnp.asarray(idx)].set(True)

    def keep(x):
        m = mask.reshape((var.spec.n_elems,) + (1,) * (x.ndim - 1))
        return x & m if x.dtype == jnp.bool_ else x * m

    return jax.tree_util.tree_map(keep, var.state)


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_store_statem(seed):
    rng = random.Random(seed)
    store = Store(n_actors=len(ACTORS))
    models: dict = {}
    watches: list = []  # (watch, vid, thr)
    #: lazy wait_needed watches on G-Counters: (watch, vid, bound|None)
    #: where None = the default {strict, bottom} wait. Laziness fires
    #: ONLY on reader interest (or at creation when already met / a
    #: reader is parked) — _write never wakes the lazy list
    #: (src/lasp_core.erl:728-758 + the reply_to_all wait clause)
    lazies: list = []
    counter = 0

    def parked_reader(vid) -> bool:
        return any(
            v == vid and not met(models[vid], thr) for _w, v, thr in watches
        )

    def offer_to_lazy(vid, r_bound, r_strict):
        # the reply_to_all wait-coverage rule, numeric form
        # (store._wait_covered): default wait fires on any read; a
        # bounded wait fires when the read asks for no more than it
        for entry in lazies:
            if entry["vid"] != vid or entry["expected"]:
                continue
            bound = entry["bound"]
            if bound is None or (
                r_bound < bound if r_strict else r_bound <= bound
            ):
                entry["expected"] = True

    def check_watches():
        for w, vid, thr in watches:
            should = met(models[vid], thr)
            assert w.done == should, (
                f"watch on {vid} thr={thr}: done={w.done}, model says "
                f"{should}"
            )
        for entry in lazies:
            assert entry["watch"].done == entry["expected"], (
                f"lazy wait on {entry['vid']} bound={entry['bound']}: "
                f"done={entry['watch'].done}"
            )

    for step in range(N_OPS):
        roll = rng.random()
        if roll < 0.15 or not models:
            tname = rng.choice(TYPES)
            counter += 1
            caps = {}
            if tname.endswith("set"):
                caps["n_elems"] = len(ELEMS)
            if tname == "lasp_orset":
                # token pools must fit the op budget: churn on one
                # (elem, actor) pair mints a fresh slot per add
                caps["tokens_per_actor"] = max(16, N_OPS)
            vid = store.declare(id=f"v{counter}", type=tname, **caps)
            models[vid] = Model(tname)
            continue
        vid = rng.choice(sorted(models))
        model = models[vid]
        tname = model.tname
        if roll < 0.55:  # update
            actor = rng.choice(ACTORS)
            if tname == "riak_dt_gcounter":
                by = rng.randint(1, 4)
                store.update(vid, ("increment", by), actor)
                model.counts[actor] = model.counts.get(actor, 0) + by
            elif tname == "lasp_ivar":
                if model.payload is None:
                    payload = rng.choice(["x", "y", ("z", 1)])
                    store.update(vid, ("set", payload), actor)
                    model.payload = payload
                else:
                    # double-bind of the same value: idempotent no-op
                    store.update(vid, ("set", model.payload), actor)
            elif tname == "lasp_gset" or rng.random() < 0.75:
                e = rng.choice(ELEMS)
                store.update(vid, ("add", e), actor)
                model.live.add(e)
                model.ever.add(e)
            else:  # lasp_orset remove: observed / tombstoned / unknown
                e = rng.choice(ELEMS)
                if e in model.ever:
                    # the reference's precondition is ORDDICT MEMBERSHIP,
                    # not liveness: removing a fully-tombstoned element
                    # succeeds as a no-op (src/lasp_orset.erl:228-238
                    # remove_elem finds the key and re-tombstones)
                    store.update(vid, ("remove", e), actor)
                    model.live.discard(e)
                else:
                    with pytest.raises(PreconditionError):
                        store.update(vid, ("remove", e), actor)
                    # data-dependent failure: model unchanged
        elif roll < 0.7:  # stale rebind: non-inflation silently ignored
            var = store.variable(vid)
            prev = var.state  # snapshot BEFORE the next write
            if tname in ("lasp_gset", "lasp_orset"):
                e = rng.choice(ELEMS)
                store.update(vid, ("add", e), "w0")
                model.live.add(e)
                model.ever.add(e)
            # prev is now a stale lower bound: merge(current, prev) ==
            # current, not an inflation -> bind must change NOTHING
            # (src/lasp_core.erl:305-311; lasp_eqc bind_ok/bind_next)
            store.bind(vid, prev)
        elif roll < 0.78 and tname == "riak_dt_gcounter":
            # wait_needed (laziness): fires at creation when already met
            # or a reader is parked; later ONLY via reader interest
            total = sum(model.counts.values())
            if rng.random() < 0.4:
                bound = None
                w = store.wait_needed(vid)
                already = total > 0 or parked_reader(vid)
            else:
                bound = rng.randint(1, total + 3)
                w = store.wait_needed(vid, Threshold(bound))
                already = total >= bound or parked_reader(vid)
            lazies.append({"watch": w, "vid": vid, "bound": bound,
                           "expected": already})
        else:  # threshold read
            if tname == "riak_dt_gcounter":
                total = sum(model.counts.values())
                strict = rng.random() < 0.3
                bound = rng.randint(0, total + 3)
                thr = ("count", bound, strict)
                w = store.read(vid, Threshold(bound, strict=strict))
                offer_to_lazy(vid, bound, strict)
            elif tname == "lasp_ivar":
                thr = ("defined", None, True)
                w = store.read(vid, Threshold(None, strict=True))
            else:
                have = sorted(model.live)
                k = rng.randint(0, len(have))
                subset = rng.sample(have, k)
                thr = ("subset", frozenset(subset), False)
                w = store.read(
                    vid, Threshold(subset_threshold_state(store, vid, subset))
                )
            assert w.done == met(model, thr)
            watches.append((w, vid, thr))

        # global invariants after every command
        assert store.value(vid) == model.value(), (
            f"step {step}: {vid} store={store.value(vid)!r} "
            f"model={model.value()!r}"
        )
        check_watches()

    for vid, model in models.items():
        assert store.value(vid) == model.value()
