"""Regression tests for review findings: watch-list mutation during write,
ingest vs concurrent callback writes, derived-type payload universes, and
replicated-runtime graph synchronization."""

from lasp_tpu.dataflow import Graph
from lasp_tpu.lattice import GSet, GSetSpec, Threshold
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import Store


def test_actor_overflow_raises_not_drops():
    # a variable declared with a small writer universe must reject the
    # (n_actors+1)-th distinct actor loudly, not silently drop the update
    # via an out-of-bounds scatter
    import pytest

    from lasp_tpu.utils.interning import CapacityError

    store = Store(n_actors=16)
    c = store.declare(type="riak_dt_gcounter", n_actors=2)
    store.update(c, ("increment",), "a1")
    store.update(c, ("increment",), "a2")
    with pytest.raises(CapacityError):
        store.update(c, ("increment",), "a3")
    assert store.value(c) == 2
    o = store.declare(type="lasp_orset", n_elems=4, n_actors=2)
    store.update(o, ("add", "x"), "w1")
    store.update(o, ("add", "x"), "w2")
    with pytest.raises(CapacityError):
        store.update(o, ("add", "y"), "w3")
    # removes on derived-style pools need no writer slot
    store.update(o, ("remove", "x"), "w3_reader")
    assert store.value(o) == frozenset()


def test_declare_rejects_typoed_capacity():
    import pytest

    store = Store()
    with pytest.raises(TypeError):
        store.declare(type="lasp_orset", n_elem=4096)  # typo for n_elems


def test_write_survives_sibling_retirement():
    # a read_any proxy firing first must not make _write's sweep skip an
    # unrelated parked watch on the same variable
    store = Store(n_actors=4)
    x = store.declare(type="lasp_gset", n_elems=4)
    y = store.declare(type="lasp_gset", n_elems=4)
    spec = GSetSpec(n_elems=4)
    grow = Threshold(GSet.new(spec), strict=True)
    shared = store.read_any([(x, grow), (y, grow)])
    plain = store.read(x, grow)
    assert not shared.done and not plain.done
    store.update(x, ("add", "a"), "actor")
    assert shared.done
    assert plain.done  # previously dropped silently


def test_ingest_preserves_callback_write():
    # a watch callback writing to a source DURING ingest must not be rolled
    # back by ingest's later (stale) state for that source
    store = Store(n_actors=4)
    graph = Graph(store)
    src1 = store.declare(id="src1", type="lasp_gset", n_elems=4)
    src2 = store.declare(id="src2", type="lasp_gset", n_elems=4)
    out1 = graph.map(src1, lambda v: v, dst="out1")
    out2 = graph.map(src2, lambda v: v, dst="out2")

    spec = GSetSpec(n_elems=4)
    w = store.read(out1, Threshold(GSet.new(spec), strict=True))
    w.callback = lambda res: store.update(src2, ("add", "late"), "cb")

    store.update(src1, ("add", "x"), "a")
    graph.propagate()
    assert store.value(src2) == frozenset({"late"})  # previously clobbered
    graph.propagate()
    assert store.value(out2) == frozenset({"late"})


def test_bind_to_after_retype_gets_payload_universe():
    # dst declared as gset (still bottom) then re-laid-out to ivar by
    # bind_to: value() must decode via a payload interner
    store = Store(n_actors=4)
    graph = Graph(store)
    src = store.declare(type="lasp_ivar")
    dst = store.declare(id="d", type="lasp_gset", n_elems=4)
    graph.bind_to(dst, src)
    store.update(src, ("set", "payload"), "a")
    graph.propagate()
    assert store.value(dst) == "payload"


def test_runtime_sees_edges_added_after_construction():
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=4)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 2))
    # edge (and output variable) added AFTER the runtime exists
    graph.map(a, lambda x: x + 1, dst="c")
    rt.update_at(0, a, ("add", 1), "actor")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value("c") == frozenset({2})


def test_update_at_does_not_consume_store_watches():
    # replica-row updates must not fire store-level watches on a transient
    # single-replica view
    store = Store(n_actors=4)
    graph = Graph(store)
    a = store.declare(id="a", type="lasp_gset", n_elems=4)
    spec = GSetSpec(n_elems=4)
    w = store.read(a, Threshold(GSet.new(spec), strict=True))
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 2))
    rt.update_at(0, a, ("add", "x"), "actor")
    assert not w.done  # store-level state never changed
    var = store.variable(a)
    assert w in var.waiting  # still parked, can fire later
