"""Store-semantics tests: the analogue of the reference's store EQC model
(``test/lasp_eqc.erl``) and the bind / monotonic-read riak_tests
(``riak_test/lasp_bind_test.erl``, ``riak_test/lasp_monotonic_read_test.erl``)
— but with convergence predicates instead of sleeps (SURVEY.md §4 caveat)."""

import pytest

from lasp_tpu.lattice import GCounter, Threshold
from lasp_tpu.store import PreconditionError, Store


def test_declare_idempotent():
    s = Store()
    id1 = s.declare("x", type="lasp_ivar")
    id2 = s.declare("x", type="lasp_ivar")
    assert id1 == id2 == "x"
    assert s.declare(type="lasp_gset") != s.declare(type="lasp_gset")


def test_ivar_bind_and_read():
    # lasp_bind_test: declare ivar, bind, read returns; double-bind same value
    # idempotent; conflicting bind ignored (src/lasp_core.erl:291-312)
    s = Store()
    s.declare("i", type="lasp_ivar")
    assert s.value("i") is None
    s.update("i", ("set", "hello"), actor="a")
    assert s.value("i") == "hello"
    s.update("i", ("set", "hello"), actor="b")  # idempotent re-bind
    assert s.value("i") == "hello"
    # conflicting local set is a no-op (reference: update({set,V}) has a
    # clause only for undefined, src/lasp_ivar.erl:46-47)
    s.update("i", ("set", "world"), actor="c")
    assert s.value("i") == "hello"
    # conflicting bind of a *different replica's* state: merge totalizes to
    # max payload id, which does not inflate the loser -> silently ignored
    # (src/lasp_core.erl:305-311)
    from lasp_tpu.lattice import IVar
    var = s.variable("i")
    foreign = IVar.set(var.spec, IVar.new(var.spec), var.ivar_payloads.intern("zzz"))
    s.bind("i", foreign)
    assert s.value("i") == "hello"
    assert s.metrics["ignored_binds"] >= 1


def test_read_blocks_until_bound_then_fires():
    s = Store()
    s.declare("i", type="lasp_ivar")
    w = s.read("i", Threshold(None, strict=True))  # {strict, undefined}
    assert not w.done
    s.update("i", ("set", 42), actor="a")
    assert w.done
    var_id, type_name, state = w.result
    assert var_id == "i" and type_name == "lasp_ivar"
    assert s.value("i") == 42


def test_monotonic_threshold_read_gcounter():
    # lasp_monotonic_read_test: read at threshold 5 fires only at value>=5
    s = Store()
    s.declare("c", type="riak_dt_gcounter")
    w = s.read("c", Threshold(5))
    for i in range(4):
        s.update("c", ("increment",), actor=f"client{i % 2}")
        assert not w.done
    s.update("c", ("increment",), actor="client0")
    assert w.done
    assert s.value("c") == 5


def test_strict_threshold_read():
    s = Store()
    s.declare("g", type="lasp_gset", n_elems=8)
    s.update("g", ("add", "a"), actor="x")
    snapshot = s.state("g")
    w = s.read("g", Threshold(snapshot, strict=True))
    assert not w.done  # same state: not a strict inflation
    s.update("g", ("add", "a"), actor="y")  # no-op add
    assert not w.done
    s.update("g", ("add", "b"), actor="x")
    assert w.done


def test_orset_add_remove_precondition():
    s = Store()
    s.declare("o", type="lasp_orset", n_elems=8)
    s.update("o", ("add_all", ["p", "q"]), actor="a")
    assert s.value("o") == {"p", "q"}
    s.update("o", ("remove", "p"), actor="a")
    assert s.value("o") == {"q"}
    with pytest.raises(PreconditionError):
        s.update("o", ("remove", "zz"), actor="a")
    # removed element may be re-added: new token wins for visibility
    s.update("o", ("add", "p"), actor="a")
    assert s.value("o") == {"p", "q"}


def test_bind_is_inflation_gated_merge():
    # binds merge: two stores' orset states joined via bind converge
    s = Store()
    s.declare("o", type="lasp_orset", n_elems=8)
    s.update("o", ("add", "x"), actor="a")
    other = Store()
    other.declare("o", type="lasp_orset", n_elems=8)
    other.update("o", ("add", "y"), actor="b")
    # carry other's state across (same spec; same interner order matters:
    # each interned its own first element at index 0, so this simulates two
    # replicas with a shared universe only when universes agree)
    s2 = Store()
    s2.declare("o", type="lasp_orset", n_elems=8)
    s2.update("o", ("add", "x"), actor="a")
    s2.update("o", ("add", "y"), actor="b")
    s.variable("o").elems.intern("y")
    s.bind("o", s2.state("o"))
    assert s.value("o") == {"x", "y"}


def test_read_any_first_match():
    s = Store()
    s.declare("a", type="lasp_ivar")
    s.declare("b", type="lasp_ivar")
    w = s.read_any([("a", Threshold(None, strict=True)), ("b", Threshold(None, strict=True))])
    assert not w.done
    s.update("b", ("set", 9), actor="x")
    assert w.done
    assert w.result[0] == "b"
    # later writes don't double-fire
    s.update("a", ("set", 1), actor="x")
    assert w.result[0] == "b"


def test_wait_needed_laziness():
    # src/lasp_core.erl:728-758: wait_needed fires when a reader arrives
    s = Store()
    s.declare("i", type="lasp_ivar")
    lazy = s.wait_needed("i")
    assert not lazy.done
    s.read("i", Threshold(None, strict=True))  # a reader shows interest
    assert lazy.done
    # wait_needed on a variable with waiting readers fires immediately
    lazy2 = s.wait_needed("i")
    assert lazy2.done


def test_wait_needed_met_threshold_fires_immediately():
    s = Store()
    s.declare("c", type="riak_dt_gcounter")
    s.update("c", ("increment", 7), actor="a")
    lazy = s.wait_needed("c", Threshold(3))
    assert lazy.done


def test_metrics_count_inflations():
    s = Store()
    s.declare("c", type="riak_dt_gcounter")
    s.update("c", ("increment",), actor="a")
    s.update("c", ("increment",), actor="a")
    assert s.metrics["inflations"] == 2
    assert s.metrics["binds"] == 2


def test_gcounter_default_threshold_read():
    # numeric bottom (0) must be substituted for None thresholds
    # (src/lasp_lattice.erl:87-90: counter thresholds are numbers)
    s = Store()
    s.declare("c", type="riak_dt_gcounter")
    w = s.read("c")  # default threshold: 0 <= value -> met immediately
    assert w.done
    w2 = s.read("c", Threshold(None, strict=True))  # strict 0: needs value>0
    assert not w2.done
    s.update("c", ("increment",), actor="a")
    assert w2.done


def test_gcounter_wait_needed_numeric():
    s = Store()
    s.declare("c", type="riak_dt_gcounter")
    lazy = s.wait_needed("c")  # default strict-0 parks (value 0, no readers)
    assert not lazy.done
    s.read("c", Threshold(3))  # a reader shows interest
    assert lazy.done
    # a parked reader means later wait_neededs fire immediately
    # (src/lasp_core.erl:739-741)
    assert s.wait_needed("c", Threshold(10)).done


def test_gcounter_wait_needed_numeric_coverage_rule():
    # numeric wait threshold fires only when a read's demand covers it
    s = Store()
    s.declare("c", type="riak_dt_gcounter")
    lazy10 = s.wait_needed("c", Threshold(10))
    assert not lazy10.done
    s.variable("c").waiting.clear()  # isolate the lazy coverage rule
    s.read("c", Threshold(12))  # 12 > 10: does not cover the wait
    assert not lazy10.done
    s.variable("c").waiting.clear()
    s.read("c", Threshold(4))  # 4 <= 10: covers it (reply_to_all wait rule)
    assert lazy10.done


def test_read_any_retires_sibling_proxies():
    s = Store()
    s.declare("a", type="lasp_ivar")
    s.declare("b", type="lasp_ivar")
    w = s.read_any(
        [("a", Threshold(None, strict=True)), ("b", Threshold(None, strict=True))]
    )
    s.update("b", ("set", 1), actor="x")
    assert w.done
    assert s.variable("a").waiting == []  # sibling proxy retired
