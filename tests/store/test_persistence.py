"""Host-store and checkpoint tests: the durable layer (SURVEY.md §2.4/§5 —
the eleveldb/bitcask/dets roles). Covers native-vs-Python on-disk format
interop, torn-write recovery, and full store/runtime checkpoint roundtrips."""

import os

import pytest

import lasp_tpu.store.host_store as hs_mod
from lasp_tpu.dataflow import Graph
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.store import (
    HostStore,
    Store,
    load_runtime,
    load_store,
    save_runtime,
    save_store,
)

BACKENDS = ["native", "python-fallback"]


@pytest.fixture(params=BACKENDS)
def backend(request, monkeypatch):
    if request.param == "python-fallback":
        monkeypatch.setattr(hs_mod, "_NATIVE", None)
    elif hs_mod._NATIVE is None:
        pytest.skip("native library not built")
    return request.param


def test_put_get_delete_roundtrip(tmp_path, backend):
    p = str(tmp_path / "kv.log")
    with HostStore(p) as s:
        assert s.backend == backend
        s.put("a", b"hello")
        s.put("b", b"\x00" * 1000)
        s.put("a", b"hello2")  # supersede
        assert s.get("a") == b"hello2"
        assert s.get("b") == b"\x00" * 1000
        assert s.get("missing") is None
        assert s.delete("b")
        assert not s.delete("b")
        assert s.get("b") is None
        assert s.stats()["keys"] == 1
        assert s.stats()["wasted_bytes"] > 0
    # reopen: index rebuilt from the log
    with HostStore(p) as s:
        assert s.get("a") == b"hello2"
        assert s.get("b") is None
        assert s.keys() == ["a"]


def test_format_interop(tmp_path):
    """The Python fallback reads files the native engine wrote and vice
    versa (identical record format, zlib CRC-32)."""
    if hs_mod._NATIVE is None:
        pytest.skip("native library not built")
    p = str(tmp_path / "x.log")
    with HostStore(p) as s:
        assert s.backend == "native"
        s.put("k1", b"from-native")
    native = hs_mod._NATIVE
    try:
        hs_mod._NATIVE = None
        with HostStore(p) as s:
            assert s.backend == "python-fallback"
            assert s.get("k1") == b"from-native"
            s.put("k2", b"from-python")
    finally:
        hs_mod._NATIVE = native
    with HostStore(p) as s:
        assert s.backend == "native"
        assert s.get("k1") == b"from-native"
        assert s.get("k2") == b"from-python"


def test_torn_write_recovery(tmp_path, backend):
    p = str(tmp_path / "torn.log")
    with HostStore(p) as s:
        s.put("good", b"A" * 100)
    size = os.path.getsize(p)
    with open(p, "ab") as f:  # simulate a crash mid-record
        f.write(b"\x52\x50\x53\x4c" + b"garbage-partial-record")
    with HostStore(p) as s:
        assert s.get("good") == b"A" * 100  # valid prefix survives
        s.put("after", b"B")  # appends over the torn tail
        assert s.get("after") == b"B"
    assert os.path.getsize(p) > size - 1


def test_store_checkpoint_roundtrip(tmp_path):
    store = Store(n_actors=4)
    o = store.declare(type="lasp_orset", n_elems=8)
    c = store.declare(type="riak_dt_gcounter")
    v = store.declare(type="lasp_ivar")
    m = store.declare(
        type="riak_dt_map",
        fields=[(("X", "lasp_orset"), "lasp_orset", {"n_elems": 4})],
    )
    store.update(o, ("add_all", ["a", "b"]), "w1")
    store.update(o, ("remove", "a"), "w1")
    store.update(c, ("increment", 7), "w2")
    store.update(v, ("set", ("compound", "payload")), "w1")
    store.update(m, ("update", [("update", ("X", "lasp_orset"), ("add", "f"))]), "w3")

    path = str(tmp_path / "ckpt.log")
    save_store(store, path)
    loaded = load_store(path)
    assert loaded.value(o) == frozenset({"b"})
    assert loaded.value(c) == 7
    assert loaded.value(v) == ("compound", "payload")
    assert loaded.value(m) == {("X", "lasp_orset"): frozenset({"f"})}
    # resumed stores keep working: writer universes restored in order
    loaded.update(o, ("add", "c"), "w1")
    assert loaded.value(o) == frozenset({"b", "c"})


def test_store_checkpoint_legacy_inline_manifest(tmp_path):
    # pre-round-3 save_store inlined per-variable entries in
    # manifest["vars"] (no varmeta/<id> records); load_store must still
    # read that layout (the leaf records never changed)
    import pickle

    from lasp_tpu.store.host_store import HostStore

    store = Store(n_actors=4)
    o = store.declare(type="lasp_orset", n_elems=8)
    c = store.declare(type="riak_dt_gcounter")
    store.update(o, ("add_all", ["a", "b"]), "w1")
    store.update(c, ("increment", 3), "w2")
    path = str(tmp_path / "legacy.log")
    save_store(store, path)
    with HostStore(path) as hs:
        from lasp_tpu.store.checkpoint import _varmeta_key, loads_manifest

        header = loads_manifest(hs.get("manifest"))
        header["vars"] = {
            vid: loads_manifest(hs.get(_varmeta_key(vid)))
            for vid in header.pop("var_ids")
        }
        # genuine pre-round-3 files inline the counters in the manifest
        # and have NO "counters" record
        header["metrics"] = dict(store.metrics)
        header["mutations"] = store.mutations
        hs.delete("counters")
        hs.put("manifest", pickle.dumps(header))
    loaded = load_store(path)
    assert loaded.value(o) == frozenset({"a", "b"})
    assert loaded.value(c) == 3
    assert loaded.mutations == store.mutations  # inline counters restored
    assert loaded.metrics == store.metrics


def test_reset_mode_map_checkpoint_roundtrip(tmp_path):
    # the epochs plane is a NEW state leaf (round 4): it must ride the
    # generic leaf records, and the unpickled spec must carry the flag so
    # the rebuilt template has a matching tree structure
    store = Store(n_actors=4)
    m = store.declare(
        type="riak_dt_map", reset_on_readd=True,
        fields=[(("X", "lasp_orset"), "lasp_orset", {"n_elems": 4})],
    )
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "v1"))]), "r1")
    store.update(m, ("update", [("remove", key)]), "r1")
    store.update(m, ("update", [("update", key, ("add", "v2"))]), "r1")
    path = str(tmp_path / "reset_map.log")
    save_store(store, path)
    loaded = load_store(path)
    assert loaded.value(m) == {key: frozenset({"v2"})}
    # the restored epoch gate still resets on the NEXT remove/re-add
    loaded.update(m, ("update", [("remove", key)]), "r1")
    loaded.update(m, ("update", [("update", key, ("add", "v3"))]), "r1")
    assert loaded.value(m) == {key: frozenset({"v3"})}


def test_load_store_refuses_runtime_checkpoint(tmp_path):
    from lasp_tpu.store.checkpoint import save_runtime

    store = Store(n_actors=4)
    g = store.declare(type="riak_dt_gcounter")
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    rt.update_at(0, g, ("increment", 2), "w")
    path = str(tmp_path / "rt.log")
    save_runtime(rt, path)
    with pytest.raises(IOError, match="runtime checkpoint"):
        load_store(path)


def test_store_checkpoint_unrecognized_manifest_is_clear_error(tmp_path):
    import pickle

    from lasp_tpu.store.host_store import HostStore

    path = str(tmp_path / "bad.log")
    with HostStore(path) as hs:
        hs.put("manifest", pickle.dumps({"kind": "store", "n_actors": 2}))
    with pytest.raises(IOError, match="neither 'var_ids'"):
        load_store(path)


def test_store_resume_with_dataflow_outputs(tmp_path):
    # the documented workflow: save a store whose combinator outputs hold
    # values, load it, re-register the same edges, keep going — covers every
    # universe flavor (own interner: map; shared: filter; derived: product)
    store = Store(n_actors=4)
    g = Graph(store)
    a = store.declare(id="a", type="lasp_orset", n_elems=4)
    b = store.declare(id="b", type="lasp_orset", n_elems=4)
    g.map(a, lambda x: x * 2, dst="m")
    g.filter(a, lambda x: x > 1, dst="f")
    g.product(a, b, dst="p")
    store.update(a, ("add_all", [1, 2]), "w")
    store.update(b, ("add", "z"), "w")
    g.propagate()
    assert store.value("m") == frozenset({2, 4})

    path = str(tmp_path / "flow.ck")
    save_store(store, path)
    s2 = load_store(path)
    g2 = Graph(s2)
    g2.map("a", lambda x: x * 2, dst="m")
    g2.filter("a", lambda x: x > 1, dst="f")
    g2.product("a", "b", dst="p")
    # restored values intact and decodable
    assert s2.value("m") == frozenset({2, 4})
    assert s2.value("f") == frozenset({2})
    assert s2.value("p") == frozenset({(1, "z"), (2, "z")})
    # and the resumed graph keeps propagating
    s2.update("a", ("add", 3), "w")
    g2.propagate()
    assert s2.value("m") == frozenset({2, 4, 6})
    assert s2.value("f") == frozenset({2, 3})
    assert s2.value("p") == frozenset({(1, "z"), (2, "z"), (3, "z")})


def test_map_field_caps_validated():
    import pytest

    store = Store(n_actors=4)
    with pytest.raises(TypeError, match="n_elem"):
        store.declare(
            type="riak_dt_map",
            fields=[(("k", "lasp_orset"), "lasp_orset", {"n_elem": 2})],
        )
    # nested map fields are supported (round 5): a declared submap schema
    # recurses, and its re-add mode must match the parent's
    m = store.declare(
        type="riak_dt_map",
        fields=[(("k", "riak_dt_map"), "riak_dt_map",
                 {"fields": [(("c", "riak_dt_gcounter"),
                              "riak_dt_gcounter", {})]})],
    )
    store.update(
        m,
        ("update", [("update", ("k", "riak_dt_map"),
                     ("update", ("c", "riak_dt_gcounter"), ("increment",)))]),
        "w",
    )
    assert store.value(m) == {
        ("k", "riak_dt_map"): {("c", "riak_dt_gcounter"): 1}
    }
    with pytest.raises(TypeError, match="reset_on_readd must match"):
        store.declare(
            type="riak_dt_map", reset_on_readd=True,
            fields=[(("k", "riak_dt_map"), "riak_dt_map",
                     {"reset_on_readd": False})],
        )


def test_orswot_duplicate_remove_in_batch_rejected():
    import pytest

    from lasp_tpu.store import PreconditionError

    store = Store(n_actors=4)
    s = store.declare(type="riak_dt_orswot", n_elems=4)
    store.update(s, ("add", "x"), "w")
    with pytest.raises(PreconditionError):
        store.update(s, ("remove_all", ["x", "x"]), "w")


def test_runtime_checkpoint_roundtrip(tmp_path):
    store = Store(n_actors=4)
    graph = Graph(store)
    src = store.declare(id="src", type="lasp_orset", n_elems=4)
    graph.map(src, lambda x: x * 2, dst="out")
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 2))
    rt.update_at(0, src, ("add", 3), "a")
    rt.run_to_convergence(max_rounds=16)

    path = str(tmp_path / "rt.log")
    save_runtime(rt, path)

    def rebuild(new_store):
        g = Graph(new_store)
        g.map("src", lambda x: x * 2, dst="out")
        return g

    rt2 = load_runtime(path, graph=rebuild)
    assert rt2.n_replicas == 4
    assert rt2.coverage_value("out") == frozenset({6})
    # resumed runtime continues: new update converges through the graph
    rt2.update_at(2, "src", ("add", 5), "a")
    rt2.run_to_convergence(max_rounds=16)
    assert rt2.coverage_value("out") == frozenset({6, 10})


# -- round-1 ADVICE tail -----------------------------------------------------

def test_manifest_unpickler_refuses_arbitrary_globals(tmp_path):
    """A checkpoint is untrusted input: a manifest whose pickle references
    os.system (or any non-lasp_tpu global) must be refused, not executed."""
    import pickle

    import pytest

    from lasp_tpu.store import HostStore
    from lasp_tpu.store.checkpoint import load_store, loads_manifest

    class Evil:
        def __reduce__(self):
            import os

            return (os.system, ("true",))

    payload = pickle.dumps({"kind": "store", "vars": {}, "bomb": Evil()})
    with pytest.raises(pickle.UnpicklingError, match="may not reference"):
        loads_manifest(payload)

    path = str(tmp_path / "evil.lasp")
    with HostStore(path) as hs:
        hs.put("manifest", payload)
    with pytest.raises(pickle.UnpicklingError):
        load_store(path)


def test_manifest_unpickler_accepts_real_checkpoints(tmp_path):
    from lasp_tpu.store import Store
    from lasp_tpu.store.checkpoint import load_store, save_store

    store = Store(n_actors=2)
    store.declare(id="s", type="lasp_orset", n_elems=4)
    store.update("s", ("add", "x"), "w")
    path = str(tmp_path / "ok.lasp")
    save_store(store, path)
    assert load_store(path).value("s") == {"x"}


def test_host_store_keys_with_newlines_and_any_bytes(tmp_path):
    from lasp_tpu.store import HostStore

    path = str(tmp_path / "keys.lasp")
    weird = ["plain", "with\nnewline", "tab\tand\x00nul-ish ☃"]
    with HostStore(path) as hs:
        for i, k in enumerate(weird):
            hs.put(k, f"v{i}".encode())
        assert sorted(hs.keys()) == sorted(weird)
        for i, k in enumerate(weird):
            assert hs.get(k) == f"v{i}".encode()


def test_host_store_compact_reclaims_waste(tmp_path):
    import os

    from lasp_tpu.store import HostStore

    path = str(tmp_path / "c.lasp")
    with HostStore(path) as hs:
        for i in range(50):
            hs.put("hot", b"x" * 1000)  # 49 superseded records
        hs.put("cold", b"y" * 100)
        hs.put("gone", b"z" * 500)
        hs.delete("gone")
        before = os.path.getsize(path)
        assert hs.stats()["wasted_bytes"] > 0
        hs.compact()
        assert hs.stats()["wasted_bytes"] == 0
        assert hs.get("hot") == b"x" * 1000
        assert hs.get("cold") == b"y" * 100
        assert hs.get("gone") is None
        # writes after compaction land fine
        hs.put("new", b"n")
    after = os.path.getsize(path)
    assert after < before // 10
    # reopen: the compacted log scans clean
    with HostStore(path) as hs:
        assert sorted(hs.keys()) == ["cold", "hot", "new"]
        assert hs.get("hot") == b"x" * 1000


def test_cli_simulate_rejects_unsupported_type(capsys):
    import pytest

    from lasp_tpu.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["simulate", "--type", "lasp_ivar", "--replicas", "8"])
    assert exc.value.code == 2


def test_cli_simulate_gcounter(capsys):
    import json as _json

    from lasp_tpu.cli import main

    rc = main(["simulate", "--type", "riak_dt_gcounter", "--replicas", "32",
               "--writers", "4", "--topology", "ring"])
    assert rc == 0
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # one increment per writer lane, max-merged across the population;
    # the counter total rides under "value" (a number), not "value_size"
    assert out["value"] == 4
    assert "value_size" not in out


def test_pylog_fallback_compact_and_keys(tmp_path):
    """The pure-Python fallback log must behave identically to the native
    engine (same on-disk format, same compaction/keys semantics)."""
    import os

    from lasp_tpu.store.host_store import _PyLog

    path = str(tmp_path / "py.lasp")
    log = _PyLog(path)
    for i in range(30):
        log.put(b"hot", b"x" * 1000)
    log.put(b"with\nnewline", b"v")
    log.put(b"gone", b"z" * 100)
    log.delete(b"gone")
    assert log.wasted > 0
    before = os.path.getsize(path)
    log.compact()
    assert log.wasted == 0
    assert log.get(b"hot") == b"x" * 1000
    assert log.get(b"with\nnewline") == b"v"
    assert log.get(b"gone") is None
    assert os.path.getsize(path) < before // 5
    log.put(b"new", b"n")
    log.close()
    log2 = _PyLog(path)
    assert sorted(log2.index) == [b"hot", b"new", b"with\nnewline"]
    assert log2.get(b"new") == b"n"
    log2.close()


def test_runtime_checkpoint_round_trips_packed_mode(tmp_path):
    """save_runtime must persist the packed flag: restoring a packed
    runtime into dense templates mis-shapes every OR-Set state."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store
    from lasp_tpu.store.checkpoint import load_runtime, save_runtime

    store = Store(n_actors=2)
    store.declare(id="s", type="lasp_orset", n_elems=4)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 1), packed=True)
    rt.update_batch("s", [(0, ("add", "x"), "w")])
    rt.run_to_convergence()
    path = str(tmp_path / "packed.lasp")
    save_runtime(rt, path)
    rt2 = load_runtime(path)
    assert rt2.packed
    assert rt2.coverage_value("s") == {"x"}
    rt2.update_batch("s", [(1, ("add", "y"), "w")])
    rt2.run_to_convergence()
    assert rt2.coverage_value("s") == {"x", "y"}


def test_checkpoint_migrates_pre_tombs_reset_map(tmp_path):
    # a pre-round-5 snapshot of a reset_on_readd map stores a strict
    # prefix of today's MapState leaves (no tombs planes); loading must
    # fill the missing trailing planes with bottoms, not crash
    from lasp_tpu.store import Store
    from lasp_tpu.store.checkpoint import load_store, save_store

    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[(("Y", "riak_dt_gcounter"), "riak_dt_gcounter", {})],
        reset_on_readd=True,
    )
    ky = ("Y", "riak_dt_gcounter")
    store.update(m, ("update", [("update", ky, ("increment", 3))]), "r1")
    var = store.variable(m)
    var.state = var.state._replace(tombs=None)  # the round-4 leaf layout
    path = str(tmp_path / "old.log")
    save_store(store, path)
    restored = load_store(path)
    assert restored.value(m)[ky] == 3  # zero baselines: nothing subtracted
    # and the restored map keeps working under round-5 semantics
    restored.update(m, ("update", [("remove", ky)]), "r1")
    restored.update(m, ("update", [("update", ky, ("increment", 4))]), "r1")
    assert restored.value(m)[ky] == 4
