"""RiakIndexProgram: materialized 2i views with parameterized instances —
mirrors src/lasp_riak_index_program.erl:59-176 semantics (VERDICT r2 ask
#9): remove-stale-then-add on put, vclock-derived tokens, total index vs
subset views, auto-registered per-spec views, delete removes entries."""

import pytest

from lasp_tpu.api import Session
from lasp_tpu.programs import RiakIndexProgram, RiakObject, view_name


def _put(sess, key, vclock, metadata=None, specs=()):
    sess.process(
        RiakObject(key=key, vclock=vclock, metadata=metadata,
                   index_specs=tuple(specs)),
        "put",
        "idx",
    )


def test_total_index_accumulates_keys_and_replaces_stale():
    sess = Session(n_actors=4)
    sess.register("lasp_riak_index_program", RiakIndexProgram)
    _put(sess, "k1", vclock=("a", 1), metadata="m1")
    _put(sess, "k2", vclock=("b", 1), metadata="m2")
    assert sess.execute("lasp_riak_index_program") == {"k1", "k2"}
    # re-put of k1 with a new vclock REPLACES the stale entry (:67-68)
    _put(sess, "k1", vclock=("a", 2), metadata="m1-v2")
    prog = sess.programs["lasp_riak_index_program"]
    entries = prog.execute(sess)
    k1_entries = [e for e in entries if e[0] == "k1"]
    assert k1_entries == [("k1", "m1-v2")]
    assert prog.value(entries) == {"k1", "k2"}


def test_delete_removes_entries_for_key():
    sess = Session(n_actors=4)
    sess.register("lasp_riak_index_program", RiakIndexProgram)
    _put(sess, "k1", vclock=("a", 1))
    _put(sess, "k2", vclock=("b", 1))
    sess.process(RiakObject(key="k1", vclock=("a", 2)), "delete", "idx")
    assert sess.execute("lasp_riak_index_program") == {"k2"}
    # deleting an unindexed key is a no-op, not an error
    sess.process(RiakObject(key="ghost", vclock=("c", 1)), "delete", "idx")
    assert sess.execute("lasp_riak_index_program") == {"k2"}


def test_index_specs_auto_create_parameterized_views():
    sess = Session(n_actors=4)
    sess.register("lasp_riak_index_program", RiakIndexProgram)
    # first put observes the spec and registers the view (which, like the
    # reference's async create_views, starts seeing events AFTER this one)
    _put(sess, "k1", ("a", 1), "m1", [("add", "color", "red")])
    assert view_name("color", "red") in sess.programs
    _put(sess, "k2", ("b", 1), "m2", [("add", "color", "red")])
    _put(sess, "k3", ("c", 1), "m3", [("add", "color", "blue")])
    _put(sess, "k4", ("d", 1), "m4", [("add", "size", "xl")])
    _put(sess, "k1", ("a", 2), "m1", [("add", "color", "red")])  # now seen
    # the subset view indexes ONLY matching (name, value) objects (:75-89)
    assert sess.execute(view_name("color", "red")) == {"k1", "k2"}
    assert sess.execute(view_name("color", "blue")) == set()  # k3 preceded it
    _put(sess, "k3", ("c", 2), "m3", [("add", "color", "blue")])
    assert sess.execute(view_name("color", "blue")) == {"k3"}
    assert sess.execute(view_name("size", "xl")) == set()
    # the total index saw everything regardless of specs (:71-74)
    assert sess.execute("lasp_riak_index_program") == {"k1", "k2", "k3", "k4"}


def test_view_does_not_index_non_matching_value():
    sess = Session(n_actors=4)
    sess.register(
        view_name("color", "red"),
        RiakIndexProgram,
        index_name="color",
        index_value="red",
        auto_views=False,
    )
    _put(sess, "k1", ("a", 1), None, [("add", "color", "green")])
    _put(sess, "k2", ("b", 1), None, [("add", "color", "red")])
    # remove-type specs never select (:168-173 filters to add)
    _put(sess, "k3", ("c", 1), None, [("remove", "color", "red")])
    assert sess.execute(view_name("color", "red")) == {"k2"}


def test_replayed_vclock_never_duplicates_entries():
    """The vclock-hash token (:146-149): a REPLAYED coordinated write
    mints the same token, so it can never duplicate an entry. After the
    first replay's remove-stale pass the token is tombstoned and the
    re-add by the same token is suppressed by the merge gate (tombstone
    ORs win, ``src/lasp_orset.erl:128-134``) — identical to the reference,
    where only a NEW vclock (a genuinely new write) re-indexes the key."""
    sess = Session(n_actors=4)
    sess.register("lasp_riak_index_program", RiakIndexProgram)
    for _ in range(3):
        _put(sess, "k1", vclock=("a", 1), metadata="m1")
    prog = sess.programs["lasp_riak_index_program"]
    assert len(prog.execute(sess)) <= 1
    # a new vclock (fresh coordinated write) re-indexes the key
    _put(sess, "k1", vclock=("a", 2), metadata="m1")
    assert [e for e in prog.execute(sess)] == [("k1", "m1")]


def test_delete_then_readd_key_resurrects():
    sess = Session(n_actors=4)
    sess.register("lasp_riak_index_program", RiakIndexProgram)
    _put(sess, "k1", vclock=("a", 1))
    sess.process(RiakObject(key="k1", vclock=("a", 2)), "delete", "idx")
    _put(sess, "k1", vclock=("a", 3))
    assert sess.execute("lasp_riak_index_program") == {"k1"}


def test_token_collision_cannot_drop_new_writes():
    """token_space=1 forces EVERY write onto token 0: distinct vclocks
    must still index (element identity carries the full digest), even
    through delete/re-put cycles where the old token is tombstoned."""
    sess = Session(n_actors=4)
    sess.register(
        "lasp_riak_index_program", RiakIndexProgram, token_space=1
    )
    _put(sess, "k1", vclock=("a", 1), metadata="m")
    sess.process(RiakObject(key="k1", vclock=("a", 2)), "delete", "idx")
    _put(sess, "k1", vclock=("a", 3), metadata="m")  # token 0 again
    assert sess.execute("lasp_riak_index_program") == {"k1"}


def test_lifetime_writes_autocompact_past_capacity():
    """A view outlives n_elems distinct writes: dead entries are compacted
    away automatically; the live result stays correct throughout."""
    sess = Session(n_actors=4)
    sess.register(
        "lasp_riak_index_program", RiakIndexProgram, n_elems=4, token_space=4
    )
    for v in range(20):  # 20 distinct vclocks through a 4-element universe
        _put(sess, "k1", vclock=("a", v), metadata=f"m{v}")
    prog = sess.programs["lasp_riak_index_program"]
    assert prog.execute(sess) == {("k1", "m19")}
    # interleaved keys + deletes keep working too
    _put(sess, "k2", vclock=("b", 1), metadata="x")
    sess.process(RiakObject(key="k1", vclock=("a", 99)), "delete", "idx")
    for v in range(6):
        _put(sess, "k3", vclock=("c", v), metadata=f"y{v}")
    assert sess.execute("lasp_riak_index_program") == {"k2", "k3"}


def test_compact_raises_when_live_entries_fill_universe():
    import pytest as _pytest

    from lasp_tpu.utils.interning import CapacityError

    sess = Session(n_actors=4)
    sess.register(
        "lasp_riak_index_program", RiakIndexProgram, n_elems=3, token_space=4
    )
    for i in range(3):
        _put(sess, f"k{i}", vclock=(f"a{i}", 1), metadata="m")
    with _pytest.raises(CapacityError):
        _put(sess, "k-one-too-many", vclock=("z", 1), metadata="m")
