"""KVS-replica workload: the CRDT-Map store of
``riak_test/lasp_kvs_replica_test.erl:55-135`` — put/get/remove against a
``riak_dt_map`` with an OR-Set field, plus multi-replica convergence of map
state under gossip (which the reference test never exercises)."""

import jax

from lasp_tpu.lattice import CrdtMap
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.dataflow import Graph
from lasp_tpu.store import PreconditionError, Store


def make_store():
    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[
            (("X", "lasp_orset"), "lasp_orset", {"n_elems": 4}),
            (("Y", "riak_dt_gcounter"), "riak_dt_gcounter", {}),
        ],
    )
    return store, m


def test_put_get_remove():
    # the reference's exact flow: put {'X', lasp_orset} <- add "Chris",
    # get, remove (riak_test/lasp_kvs_replica_test.erl:62-92)
    store, m = make_store()
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "Chris"))]), "replica1")
    assert store.value(m) == {key: frozenset({"Chris"})}
    store.update(m, ("update", [("remove", key)]), "replica1")
    assert store.value(m) == {}
    # removing an absent key is a precondition error, as in riak_dt_map
    try:
        store.update(m, ("update", [("remove", key)]), "replica1")
        raise AssertionError("expected PreconditionError")
    except PreconditionError:
        pass


def test_mixed_fields_and_batched_ops():
    store, m = make_store()
    kx = ("X", "lasp_orset")
    ky = ("Y", "riak_dt_gcounter")
    store.update(
        m,
        ("update", [("update", kx, ("add", "a")), ("update", ky, ("increment", 5))]),
        "r1",
    )
    store.update(m, ("update", [("update", ky, ("increment",))]), "r2")
    assert store.value(m) == {kx: frozenset({"a"}), ky: 6}


def test_map_remove_readd_presence():
    store, m = make_store()
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "v1"))]), "r1")
    store.update(m, ("update", [("remove", key)]), "r1")
    assert store.value(m) == {}
    store.update(m, ("update", [("update", key, ("add", "v2"))]), "r1")
    # documented dense-shape divergence: contents are join-monotone across
    # remove/re-add, so v1 resurfaces alongside v2 (presence was the only
    # thing removed)
    assert store.value(m)[key] >= frozenset({"v2"})


def test_map_gossip_convergence():
    store, m = make_store()
    graph = Graph(store)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 2))
    key = ("X", "lasp_orset")
    rt.update_at(0, m, ("update", [("update", key, ("add", "from0"))]), "r0")
    rt.update_at(2, m, ("update", [("update", key, ("add", "from2"))]), "r2")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {key: frozenset({"from0", "from2"})}
    for r in range(4):
        assert rt.replica_value(m, r) == {key: frozenset({"from0", "from2"})}
    # a remove at one replica (after observing both adds) wins everywhere
    rt.update_at(1, m, ("update", [("remove", key)]), "r1")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {}


def test_orswot_store_roundtrip():
    store = Store(n_actors=4)
    s = store.declare(type="riak_dt_orswot", n_elems=4)
    store.update(s, ("add_all", ["a", "b"]), "w1")
    assert store.value(s) == frozenset({"a", "b"})
    store.update(s, ("remove", "a"), "w1")
    assert store.value(s) == frozenset({"b"})


def test_kvs_population_scale_batched():
    """The KVS map at population scale through the VECTORIZED batch path:
    thousands of client puts land in O(1) device scatters (gset+counter
    fields — the batchable schema), gossip converges, and the coverage
    value matches the sequential reference semantics."""
    import warnings

    import numpy as np

    from lasp_tpu.mesh import random_regular

    n = 2048
    store = Store(n_actors=8)
    graph = Graph(store)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[
            (("X", "lasp_gset"), "lasp_gset", {"n_elems": 16}),
            (("Y", "riak_dt_gcounter"), "riak_dt_gcounter", {}),
        ],
        n_actors=8,
    )
    rt = ReplicatedRuntime(store, graph, n, random_regular(n, 3, seed=4))
    rng = np.random.RandomState(4)
    ops = []
    for i in range(4000):
        # actor discipline (riak_dt vclock rule, update_at docstring):
        # a WRITER is an identity, minting clock events and presence dots
        # only at its one home replica — one actor at many replicas would
        # collide dot counters (observed-and-removed: silent loss) and
        # max-merge away counter increments
        w = int(rng.randint(8))
        if i % 3 == 0:
            ops.append((w, ("update", ("Y", "riak_dt_gcounter"),
                            ("increment",)), f"w{w}"))
        else:
            ops.append((w, ("update", ("X", "lasp_gset"),
                            ("add", f"k{rng.randint(16)}")), f"w{w}"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.update_batch(m, ops)
    assert not any("no vectorized kernel" in str(w.message) for w in caught)
    rounds = rt.converge_on_device()
    assert rounds >= 1
    v = rt.coverage_value(m)
    n_incr = sum(1 for _r, op, _a in ops if op[1][1] == "riak_dt_gcounter")
    # per-actor-lane max-merge: each lane converges to that actor's total
    assert v[("Y", "riak_dt_gcounter")] == n_incr
    added = {op[2][1] for _r, op, _a in ops if op[1][0] == "X"}
    assert v[("X", "lasp_gset")] == frozenset(added)
    assert rt.divergence(m) == 0
