"""KVS-replica workload: the CRDT-Map store of
``riak_test/lasp_kvs_replica_test.erl:55-135`` — put/get/remove against a
``riak_dt_map`` with an OR-Set field, plus multi-replica convergence of map
state under gossip (which the reference test never exercises)."""

import jax

from lasp_tpu.lattice import CrdtMap
from lasp_tpu.mesh import ReplicatedRuntime, ring
from lasp_tpu.dataflow import Graph
from lasp_tpu.store import PreconditionError, Store


def make_store():
    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[
            (("X", "lasp_orset"), "lasp_orset", {"n_elems": 4}),
            (("Y", "riak_dt_gcounter"), "riak_dt_gcounter", {}),
        ],
    )
    return store, m


def test_put_get_remove():
    # the reference's exact flow: put {'X', lasp_orset} <- add "Chris",
    # get, remove (riak_test/lasp_kvs_replica_test.erl:62-92)
    store, m = make_store()
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "Chris"))]), "replica1")
    assert store.value(m) == {key: frozenset({"Chris"})}
    store.update(m, ("update", [("remove", key)]), "replica1")
    assert store.value(m) == {}
    # removing an absent key is a precondition error, as in riak_dt_map
    try:
        store.update(m, ("update", [("remove", key)]), "replica1")
        raise AssertionError("expected PreconditionError")
    except PreconditionError:
        pass


def test_mixed_fields_and_batched_ops():
    store, m = make_store()
    kx = ("X", "lasp_orset")
    ky = ("Y", "riak_dt_gcounter")
    store.update(
        m,
        ("update", [("update", kx, ("add", "a")), ("update", ky, ("increment", 5))]),
        "r1",
    )
    store.update(m, ("update", [("update", ky, ("increment",))]), "r2")
    assert store.value(m) == {kx: frozenset({"a"}), ky: 6}


def test_map_remove_readd_presence():
    store, m = make_store()
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "v1"))]), "r1")
    store.update(m, ("update", [("remove", key)]), "r1")
    assert store.value(m) == {}
    store.update(m, ("update", [("update", key, ("add", "v2"))]), "r1")
    # documented dense-shape divergence: contents are join-monotone across
    # remove/re-add, so v1 resurfaces alongside v2 (presence was the only
    # thing removed)
    assert store.value(m)[key] >= frozenset({"v2"})


def test_map_gossip_convergence():
    store, m = make_store()
    graph = Graph(store)
    rt = ReplicatedRuntime(store, graph, 4, ring(4, 2))
    key = ("X", "lasp_orset")
    rt.update_at(0, m, ("update", [("update", key, ("add", "from0"))]), "r0")
    rt.update_at(2, m, ("update", [("update", key, ("add", "from2"))]), "r2")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {key: frozenset({"from0", "from2"})}
    for r in range(4):
        assert rt.replica_value(m, r) == {key: frozenset({"from0", "from2"})}
    # a remove at one replica (after observing both adds) wins everywhere
    rt.update_at(1, m, ("update", [("remove", key)]), "r1")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {}


def make_reset_store():
    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[
            (("X", "lasp_orset"), "lasp_orset", {"n_elems": 4}),
            (("Y", "riak_dt_gcounter"), "riak_dt_gcounter", {}),
        ],
        reset_on_readd=True,
    )
    return store, m


def test_reset_mode_remove_readd_resets_contents():
    # the riak_dt_map observable the default dense mode diverges from
    # (VERDICT r3 ask #6): remove-then-re-add yields FRESH contents —
    # the reference sequence of riak_test/lasp_kvs_replica_test.erl:61-129
    # extended with the re-add
    store, m = make_reset_store()
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "Chris"))]), "r1")
    assert store.value(m) == {key: frozenset({"Chris"})}
    store.update(m, ("update", [("remove", key)]), "r1")
    assert store.value(m) == {}
    store.update(m, ("update", [("update", key, ("add", "v2"))]), "r1")
    # reference-identical: v2 only, Chris does NOT resurface
    assert store.value(m) == {key: frozenset({"v2"})}
    # counter fields reset too
    ky = ("Y", "riak_dt_gcounter")
    store.update(m, ("update", [("update", ky, ("increment", 5))]), "r1")
    store.update(m, ("update", [("remove", ky)]), "r1")
    store.update(m, ("update", [("update", ky, ("increment", 2))]), "r1")
    assert store.value(m)[ky] == 2


def test_reset_mode_propagates_over_gossip():
    store, m = make_reset_store()
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    key = ("X", "lasp_orset")
    rt.update_at(0, m, ("update", [("update", key, ("add", "v1"))]), "r0")
    rt.run_to_convergence(max_rounds=16)
    # remove + re-add at one replica (which has observed v1): the reset
    # reaches every replica — none resurrects v1
    rt.update_at(1, m, ("update", [("remove", key)]), "r1")
    rt.update_at(1, m, ("update", [("update", key, ("add", "v2"))]), "r1")
    rt.run_to_convergence(max_rounds=16)
    assert rt.divergence(m) == 0
    for r in range(4):
        assert rt.replica_value(m, r) == {key: frozenset({"v2"})}


def test_reset_mode_concurrent_update():
    # riak_dt's reset-remove (src/lasp_lattice.erl:264-271 ordering over
    # riak_dt_map): a remove erases what the remover OBSERVED; an update
    # CONCURRENT with the remove keeps the field present (fresh dot
    # survives the ORSWOT rule) AND keeps its own contribution (the
    # concurrent add's token was never observed by the remover). Round 5
    # closes the r4 epoch-gate divergence that dropped v2 here.
    from lasp_tpu.lattice import CrdtMap

    store, m = make_reset_store()
    var = store.variable(m)
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "v1"))]), "r1")
    a = var.state  # both sides start converged with {v1}
    b = var.state
    # side A removes; side B concurrently adds v2 under a different actor
    a = store._apply_op(var, a, ("update", [("remove", key)]), "r1")
    b = store._apply_op(var, b, ("update", [("update", key, ("add", "v2"))]), "r2")
    merged = CrdtMap.merge(var.spec, a, b)
    present = CrdtMap.value(var.spec, merged)
    assert bool(present[var.spec.field_index(key)])  # field survives
    decoded = store._decode_value(var, merged)
    assert decoded[key] == frozenset({"v2"})  # v1 reset, v2 survives
    # merge order must not matter
    merged2 = CrdtMap.merge(var.spec, b, a)
    assert store._decode_value(var, merged2)[key] == frozenset({"v2"})


def test_reset_mode_concurrent_counter_increment():
    # counter fields reset via the observed-floor baseline: the remove
    # erases the 5 observed increments; r2's concurrent +3 exceeds the
    # floor on its own lane and survives
    from lasp_tpu.lattice import CrdtMap

    store, m = make_reset_store()
    var = store.variable(m)
    ky = ("Y", "riak_dt_gcounter")
    store.update(m, ("update", [("update", ky, ("increment", 5))]), "r1")
    a = var.state
    b = var.state
    a = store._apply_op(var, a, ("update", [("remove", ky)]), "r1")
    b = store._apply_op(var, b, ("update", [("update", ky, ("increment", 3))]), "r2")
    merged = CrdtMap.merge(var.spec, a, b)
    decoded = store._decode_value(var, merged)
    assert decoded[ky] == 3  # the 5 observed fell to the reset; +3 survives
    # and a re-add increment on TOP of the merge counts from zero + 3
    store.bind_raw(m, merged)
    store.update(m, ("update", [("update", ky, ("increment", 2))]), "r3")
    assert store.value(m)[ky] == 5


def test_reset_mode_gset_field_is_epoch_gated():
    # gset is NOT a riak_dt embedded type: with no tokens to tell a
    # re-add from a merged old copy, a baseline would drop SEQUENTIAL
    # re-adds forever — so gset fields reset behind the epoch gate
    # (documented in lattice/map.py): sequential remove/re-add yields
    # fresh contents; an update CONCURRENT with a remove keeps presence
    # but loses its era's contents.
    from lasp_tpu.lattice import CrdtMap

    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[(("S", "lasp_gset"), "lasp_gset", {"n_elems": 8})],
        reset_on_readd=True,
    )
    var = store.variable(m)
    key = ("S", "lasp_gset")
    # sequential remove/re-add of the SAME element yields fresh contents
    store.update(m, ("update", [("update", key, ("add", "seen"))]), "r1")
    store.update(m, ("update", [("remove", key)]), "r1")
    store.update(m, ("update", [("update", key, ("add", "seen"))]), "r1")
    assert store.value(m) == {key: frozenset({"seen"})}
    # concurrent update vs remove: presence survives, era contents fall
    a = store._apply_op(var, var.state, ("update", [("remove", key)]), "r1")
    b = store._apply_op(
        var, var.state, ("update", [("update", key, ("add", "fresh"))]), "r2"
    )
    merged = CrdtMap.merge(var.spec, a, b)
    assert bool(CrdtMap.value(var.spec, merged)[var.spec.field_index(key)])
    assert store._decode_value(var, merged)[key] == frozenset()


def test_reset_mode_orset_sequential_cycles_and_pool_cost():
    # OR-Set fields give exact riak_dt reset-remove; the documented cost
    # is that tombstones pin token slots — remove/re-add cycling beyond
    # tokens_per_actor raises a LOUD CapacityError, never silent loss
    from lasp_tpu.utils.interning import CapacityError

    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[(("X", "lasp_orset"), "lasp_orset",
                 {"n_elems": 4, "tokens_per_actor": 3})],
        reset_on_readd=True,
    )
    key = ("X", "lasp_orset")
    for _cycle in range(3):
        store.update(m, ("update", [("update", key, ("add", "x"))]), "r1")
        assert store.value(m) == {key: frozenset({"x"})}
        store.update(m, ("update", [("remove", key)]), "r1")
        assert store.value(m) == {}
    import pytest

    with pytest.raises(CapacityError):
        store.update(m, ("update", [("update", key, ("add", "x"))]), "r1")


def test_reset_mode_merge_is_lattice():
    # epoch-gated merge stays idempotent/commutative/associative on
    # divergent histories
    from lasp_tpu.lattice import CrdtMap

    store, m = make_reset_store()
    var = store.variable(m)
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "x"))]), "r1")
    base = var.state
    s1 = store._apply_op(var, base, ("update", [("remove", key)]), "r1")
    s2 = store._apply_op(var, base, ("update", [("update", key, ("add", "y"))]), "r2")
    s3 = store._apply_op(
        var, s1, ("update", [("update", key, ("add", "z"))]), "r3"
    )
    spec = var.spec

    def eq(p, q):
        return bool(CrdtMap.equal(spec, p, q))

    for s in (s1, s2, s3):
        assert eq(CrdtMap.merge(spec, s, s), s)  # idempotent
    for p, q in [(s1, s2), (s1, s3), (s2, s3)]:
        assert eq(CrdtMap.merge(spec, p, q), CrdtMap.merge(spec, q, p))
    lhs = CrdtMap.merge(spec, CrdtMap.merge(spec, s1, s2), s3)
    rhs = CrdtMap.merge(spec, s1, CrdtMap.merge(spec, s2, s3))
    assert eq(lhs, rhs)


def test_reset_mode_batch_routes_through_per_op_path():
    import warnings

    store = Store(n_actors=8)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[(("X", "lasp_gset"), "lasp_gset", {"n_elems": 8})],
        n_actors=8,
        reset_on_readd=True,
    )
    rt = ReplicatedRuntime(store, Graph(store), 8, ring(8, 2))
    key = ("X", "lasp_gset")
    with_remove = [
        (0, ("update", key, ("add", "a")), "w0"),
        (0, ("remove", key), "w0"),
        (0, ("update", key, ("add", "b")), "w0"),
    ]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.update_batch(m, with_remove)
    assert any("no vectorized kernel" in str(w.message) for w in caught)
    assert rt.replica_value(m, 0) == {key: frozenset({"b"})}  # reset applied
    # add-only batches keep the vectorized path even in reset mode
    adds_only = [(1, ("update", key, ("add", "c")), "w1")]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.update_batch(m, adds_only)
    assert not any("no vectorized kernel" in str(w.message) for w in caught)
    assert rt.replica_value(m, 1) == {key: frozenset({"c"})}


def test_orswot_store_roundtrip():
    store = Store(n_actors=4)
    s = store.declare(type="riak_dt_orswot", n_elems=4)
    store.update(s, ("add_all", ["a", "b"]), "w1")
    assert store.value(s) == frozenset({"a", "b"})
    store.update(s, ("remove", "a"), "w1")
    assert store.value(s) == frozenset({"b"})


def test_kvs_population_scale_batched():
    """The KVS map at population scale through the VECTORIZED batch path:
    thousands of client puts land in O(1) device scatters (gset+counter
    fields — the batchable schema), gossip converges, and the coverage
    value matches the sequential reference semantics."""
    import warnings

    import numpy as np

    from lasp_tpu.mesh import random_regular

    n = 2048
    store = Store(n_actors=8)
    graph = Graph(store)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[
            (("X", "lasp_gset"), "lasp_gset", {"n_elems": 16}),
            (("Y", "riak_dt_gcounter"), "riak_dt_gcounter", {}),
        ],
        n_actors=8,
    )
    rt = ReplicatedRuntime(store, graph, n, random_regular(n, 3, seed=4))
    rng = np.random.RandomState(4)
    ops = []
    for i in range(4000):
        # actor discipline (riak_dt vclock rule, update_at docstring):
        # a WRITER is an identity, minting clock events and presence dots
        # only at its one home replica — one actor at many replicas would
        # collide dot counters (observed-and-removed: silent loss) and
        # max-merge away counter increments
        w = int(rng.randint(8))
        if i % 3 == 0:
            ops.append((w, ("update", ("Y", "riak_dt_gcounter"),
                            ("increment",)), f"w{w}"))
        else:
            ops.append((w, ("update", ("X", "lasp_gset"),
                            ("add", f"k{rng.randint(16)}")), f"w{w}"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rt.update_batch(m, ops)
    assert not any("no vectorized kernel" in str(w.message) for w in caught)
    rounds = rt.converge_on_device()
    assert rounds >= 1
    v = rt.coverage_value(m)
    n_incr = sum(1 for _r, op, _a in ops if op[1][1] == "riak_dt_gcounter")
    # per-actor-lane max-merge: each lane converges to that actor's total
    assert v[("Y", "riak_dt_gcounter")] == n_incr
    added = {op[2][1] for _r, op, _a in ops if op[1][0] == "X"}
    assert v[("X", "lasp_gset")] == frozenset(added)
    assert rt.divergence(m) == 0


# -- dynamic field admission (round-5): the reference's schemaless map ------
# riak_dt_map admits {Name, Type} keys on first update — the KVS replica
# declares lasp:declare(riak_dt_map) with NO schema and puts to keys never
# declared anywhere (riak_test/lasp_kvs_replica_test.erl:57-135; ordering
# src/lasp_lattice.erl:264-271).


def test_dynamic_declare_no_schema():
    import pytest

    store = Store(n_actors=4)
    m = store.declare(type="riak_dt_map")  # the reference's exact declare
    kx = ("X", "lasp_orset")
    ky = ("Y", "riak_dt_gcounter")
    store.update(m, ("update", [("update", kx, ("add", "Chris"))]), "r1")
    assert store.value(m) == {kx: frozenset({"Chris"})}
    # a later op admits a second field and updates the first in one batch
    store.update(
        m,
        ("update", [("update", ky, ("increment", 3)), ("update", kx, ("add", "b"))]),
        "r1",
    )
    assert store.value(m) == {kx: frozenset({"Chris", "b"}), ky: 3}
    store.update(m, ("update", [("remove", kx)]), "r1")
    assert store.value(m) == {ky: 3}
    # removing a never-admitted field is the riak_dt precondition error,
    # not a schema error — and does NOT admit the field
    with pytest.raises(PreconditionError):
        store.update(m, ("update", [("remove", ("Z", "lasp_orset"))]), "r1")
    assert len(store.variable(m).spec.fields) == 2


def test_dynamic_reset_mode_no_schema():
    store = Store(n_actors=4)
    m = store.declare(type="riak_dt_map", reset_on_readd=True)
    key = ("X", "lasp_orset")
    store.update(m, ("update", [("update", key, ("add", "Chris"))]), "r1")
    store.update(m, ("update", [("remove", key)]), "r1")
    store.update(m, ("update", [("update", key, ("add", "v2"))]), "r1")
    assert store.value(m) == {key: frozenset({"v2"})}
    # a field admitted AFTER a reset epoch advanced elsewhere starts clean
    ky = ("Y", "riak_dt_gcounter")
    store.update(m, ("update", [("update", ky, ("increment", 2))]), "r1")
    assert store.value(m)[ky] == 2


def test_dynamic_admission_key_validation():
    import pytest

    store = Store(n_actors=4)
    m = store.declare(type="riak_dt_map")
    # keys that are not (name, type_name) pairs cannot self-describe a type
    with pytest.raises(KeyError):
        store.update(m, ("update", [("update", "bare", ("add", "x"))]), "r1")
    # unknown embedded type names are loud — same TypeError the declared-
    # schema path raises for the same misuse (one shared validation path)
    with pytest.raises(TypeError):
        store.update(
            m, ("update", [("update", ("A", "no_such_type"), ("add", "x"))]), "r1"
        )
    # nested maps ADMIT (round 5): an empty batched inner op creates the
    # submap field with an empty dynamic schema
    store.update(
        m,
        ("update", [("update", ("N", "riak_dt_map"), ("update", []))]),
        "r1",
    )
    assert store.value(m) == {("N", "riak_dt_map"): {}}
    # a mismatched nested reset mode is still loud at declare
    with pytest.raises(TypeError, match="reset_on_readd must match"):
        store.declare(
            type="riak_dt_map",
            fields=[(("N", "riak_dt_map"), "riak_dt_map",
                     {"reset_on_readd": True})],
        )


def test_dynamic_watch_thresholds_grow():
    # a strict-threshold read parked BEFORE admission must keep working
    # after the field axis grows (its parked threshold state is re-laid-out)
    from lasp_tpu.lattice import Threshold

    store = Store(n_actors=4)
    m = store.declare(type="riak_dt_map")
    kx = ("X", "lasp_orset")
    store.update(m, ("update", [("update", kx, ("add", "a"))]), "r1")
    var = store.variable(m)
    watch = store.read(m, Threshold(var.state, strict=True))
    assert not watch.done
    ky = ("Y", "riak_dt_gcounter")
    store.update(m, ("update", [("update", ky, ("increment",))]), "r1")
    assert watch.done  # admission + update strictly inflated past the park


def test_dynamic_mesh_growth_update_at():
    # growth after the compiled step exists: the population re-layouts and
    # the step recompiles for the new field axis
    store = Store(n_actors=4)
    m = store.declare(type="riak_dt_map")
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    kx = ("X", "lasp_orset")
    rt.update_at(0, m, ("update", [("update", kx, ("add", "from0"))]), "r0")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {kx: frozenset({"from0"})}
    # now a NEVER-seen key arrives at a different replica
    ky = ("Y", "riak_dt_gcounter")
    rt.update_at(2, m, ("update", [("update", ky, ("increment", 7))]), "r2")
    rt.run_to_convergence(max_rounds=16)
    assert rt.divergence(m) == 0
    for r in range(4):
        assert rt.replica_value(m, r) == {kx: frozenset({"from0"}), ky: 7}


def test_dynamic_mesh_growth_update_batch():
    store = Store(n_actors=8)
    m = store.declare(type="riak_dt_map")
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    # batch over keys never declared: one pre-admission, one re-layout
    ops = []
    for w in range(4):
        ops.append(
            (w, ("update", ("S", "lasp_gset"), ("add", f"e{w}")), f"w{w}")
        )
        ops.append((w, ("update", ("C", "riak_dt_gcounter"), ("increment",)), f"w{w}"))
    rt.update_batch(m, ops)
    rt.run_to_convergence(max_rounds=16)
    v = rt.coverage_value(m)
    assert v[("S", "lasp_gset")] == frozenset({"e0", "e1", "e2", "e3"})
    assert v[("C", "riak_dt_gcounter")] == 4
    assert rt.divergence(m) == 0


def test_dynamic_checkpoint_roundtrip(tmp_path):
    from lasp_tpu.store.checkpoint import load_store, save_store

    store = Store(n_actors=4)
    m = store.declare(id="kvs", type="riak_dt_map")
    kx = ("X", "lasp_orset")
    store.update(m, ("update", [("update", kx, ("add", "a"))]), "r1")
    path = str(tmp_path / "ckpt")
    save_store(store, path)
    restored = load_store(path)
    assert restored.value(m) == {kx: frozenset({"a"})}
    # the restored map keeps admitting: growth works on restored layouts
    ky = ("Y", "riak_dt_gcounter")
    restored.update(m, ("update", [("update", ky, ("increment", 9))]), "r2")
    assert restored.value(m) == {kx: frozenset({"a"}), ky: 9}


def test_dynamic_statem():
    # randomized store-level statem over a DYNAMIC field set: ops draw keys
    # from a pool larger than any declared schema (admission interleaves
    # with updates/removes); oracle is a plain dict model with riak_dt_map
    # observable semantics (join-monotone default mode)
    import random

    import pytest

    for seed in range(6):
        rng = random.Random(seed)
        store = Store(n_actors=8)
        m = store.declare(type="riak_dt_map")
        pool = [(f"K{i}", "lasp_gset") for i in range(5)] + [
            (f"C{i}", "riak_dt_gcounter") for i in range(3)
        ]
        model: dict = {}
        for stepi in range(120):
            key = rng.choice(pool)
            actor = f"w{rng.randrange(8)}"
            roll = rng.random()
            if roll < 0.55:
                if key[1] == "lasp_gset":
                    e = f"e{rng.randrange(6)}"
                    store.update(m, ("update", [("update", key, ("add", e))]), actor)
                    cur = model.get(key)
                    model[key] = (cur[0] if cur else frozenset()) | {e}, True
                else:
                    store.update(
                        m, ("update", [("update", key, ("increment",))]), actor
                    )
                    cur = model.get(key)
                    model[key] = (cur[0] if cur else 0) + 1, True
                model[key] = (model[key][0], True)
            elif roll < 0.75:
                present = model.get(key, (None, False))[1]
                if present:
                    store.update(m, ("update", [("remove", key)]), actor)
                    # default mode: contents survive hidden; presence drops
                    model[key] = (model[key][0], False)
                else:
                    with pytest.raises(PreconditionError):
                        store.update(m, ("update", [("remove", key)]), actor)
            else:
                # batched multi-key op (admits several at once)
                k2 = rng.choice(pool)
                if k2[1] == "lasp_gset" and key[1] == "lasp_gset":
                    e1, e2 = f"e{rng.randrange(6)}", f"e{rng.randrange(6)}"
                    store.update(
                        m,
                        ("update", [("update", key, ("add", e1)),
                                    ("update", k2, ("add", e2))]),
                        actor,
                    )
                    cur = model.get(key)
                    model[key] = ((cur[0] if cur else frozenset()) | {e1}, True)
                    cur = model.get(k2)
                    model[k2] = ((cur[0] if cur else frozenset()) | {e2}, True)
            expect = {
                k: (v if isinstance(v, (frozenset, int)) else v)
                for k, (v, present) in model.items()
                if present
            }
            assert store.value(m) == expect, f"seed={seed} step={stepi}"


def test_dynamic_batch_admission_is_atomic():
    # regression (r5 review): a batch whose LATER op carries an invalid
    # key must raise with NOTHING admitted — a half-grown spec whose
    # population was never re-laid-out wedges the variable permanently
    import pytest

    store = Store(n_actors=8)
    m = store.declare(type="riak_dt_map")
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    with pytest.raises(KeyError):
        rt.update_batch(
            m,
            [
                (0, ("update", ("A", "lasp_gset"), ("add", "x")), "w0"),
                (1, ("update", "bad_key", ("add", "y")), "w1"),
            ],
        )
    assert store.variable(m).spec.fields == ()  # nothing half-admitted
    # the variable still works: the same valid key admits and applies
    rt.update_at(0, m, ("update", ("A", "lasp_gset"), ("add", "z")), "w0")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {("A", "lasp_gset"): frozenset({"z"})}


def test_dynamic_statem_reset_mode():
    # the reset-mode twin of test_dynamic_statem: sequential single-store
    # semantics — a field remove erases its contents (riak_dt observable),
    # so the oracle resets the entry; dynamic admission interleaves
    import random

    import pytest

    from lasp_tpu.utils.interning import CapacityError

    for seed in range(4):
        rng = random.Random(seed + 100)
        store = Store(n_actors=8)
        m = store.declare(type="riak_dt_map", reset_on_readd=True)
        pool = [(f"K{i}", "lasp_orset") for i in range(3)] + [
            (f"C{i}", "riak_dt_gcounter") for i in range(2)
        ]
        model: dict = {}  # key -> (value, present)
        for stepi in range(100):
            key = rng.choice(pool)
            actor = f"w{rng.randrange(8)}"
            roll = rng.random()
            if roll < 0.6:
                if key[1] == "lasp_orset":
                    e = f"e{rng.randrange(5)}"
                    try:
                        store.update(
                            m, ("update", [("update", key, ("add", e))]), actor
                        )
                    except CapacityError:
                        continue  # tombstoned slots pinned (documented)
                    cur = model.get(key, (frozenset(), False))[0]
                    model[key] = (cur | {e}, True)
                else:
                    by = rng.randint(1, 3)
                    store.update(
                        m, ("update", [("update", key, ("increment", by))]),
                        actor,
                    )
                    cur = model.get(key, (0, False))[0]
                    model[key] = (cur + by, True)
            else:
                present = model.get(key, (None, False))[1]
                if present:
                    store.update(m, ("update", [("remove", key)]), actor)
                    # SEQUENTIAL reset-remove: contents erased outright
                    bottom = frozenset() if key[1] == "lasp_orset" else 0
                    model[key] = (bottom, False)
                else:
                    with pytest.raises(PreconditionError):
                        store.update(m, ("update", [("remove", key)]), actor)
            expect = {k: v for k, (v, p) in model.items() if p}
            assert store.value(m) == expect, (seed, stepi)


def test_compact_map_field_sustains_reset_churn():
    # the reclamation that makes reset-mode remove/re-add churn
    # sustainable: each cycle tombstones the observed tokens; compaction
    # at quiescence frees the fully-dead element rows (and their pinned
    # token slots), so churn can continue past tokens_per_actor cycles
    store = Store(n_actors=4)
    m = store.declare(
        id="kvs",
        type="riak_dt_map",
        fields=[(("X", "lasp_orset"), "lasp_orset",
                 {"n_elems": 4, "tokens_per_actor": 3})],
        reset_on_readd=True,
    )
    key = ("X", "lasp_orset")
    for cycle in range(10):  # far beyond the 3-slot pool
        store.update(m, ("update", [("update", key, ("add", "x"))]), "r1")
        assert store.value(m) == {key: frozenset({"x"})}
        store.update(m, ("update", [("remove", key)]), "r1")
        assert store.value(m) == {}
        assert store.compact_map_field(m, key) >= 1
    # refusals: non-orset fields have no tombstones
    import pytest

    store.update(m, ("update", [("update", ("C", "riak_dt_gcounter"),
                                 ("increment",))]), "r1")
    with pytest.raises(TypeError, match="no token tombstones"):
        store.compact_map_field(m, ("C", "riak_dt_gcounter"))


def test_runtime_compact_map_field_population():
    import pytest

    store = Store(n_actors=8)
    m = store.declare(type="riak_dt_map", reset_on_readd=True)
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    key = ("X", "lasp_orset")
    for cycle in range(3):
        rt.update_at(0, m, ("update", [("update", key, ("add", f"v{cycle}"))]),
                     "w0")
        rt.run_to_convergence(max_rounds=16)
        rt.update_at(2, m, ("update", [("remove", key)]), "w2")
        rt.run_to_convergence(max_rounds=16)
        assert rt.coverage_value(m) == {}
    # diverged populations refuse (a dropped tombstone could resurrect)
    rt.update_at(1, m, ("update", [("update", key, ("add", "live"))]), "w1")
    with pytest.raises(RuntimeError, match="not converged"):
        rt.compact_map_field(m, key)
    rt.run_to_convergence(max_rounds=16)
    assert rt.compact_map_field(m, key) >= 1
    # the map keeps serving after the population-wide reindex
    assert rt.coverage_value(m) == {key: frozenset({"live"})}
    rt.update_at(3, m, ("update", [("update", key, ("add", "after"))]), "w3")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m) == {key: frozenset({"live", "after"})}
    assert rt.divergence(m) == 0


# -- nested riak_dt_map fields (round 5) --------------------------------------
# riak_dt_map embeds maps; {Name, riak_dt_map} keys nest to any depth,
# dynamically admitted like every other field.

KN = ("N", "riak_dt_map")
KC = ("c", "riak_dt_gcounter")
KS = ("s", "lasp_orset")


def test_nested_map_schemaless_flow():
    store = Store(n_actors=8)
    m = store.declare(type="riak_dt_map")
    store.update(
        m,
        ("update", [("update", KN,
                     ("update", [("update", KC, ("increment", 3))]))]),
        "r1",
    )
    store.update(m, ("update", [("update", KN, ("update", KS, ("add", "x")))]),
                 "r1")
    assert store.value(m) == {KN: {KC: 3, KS: frozenset({"x"})}}
    # depth 3
    K2 = ("D", "riak_dt_map")
    store.update(
        m,
        ("update", [("update", KN,
                     ("update", K2, ("update", KC, ("increment",))))]),
        "r2",
    )
    assert store.value(m)[KN][K2] == {KC: 1}
    # inner remove: presence only in default mode; absent inner remove is
    # a precondition error
    store.update(m, ("update", [("update", KN, ("remove", KS))]), "r1")
    assert KS not in store.value(m)[KN]
    import pytest

    with pytest.raises(PreconditionError):
        store.update(
            m, ("update", [("update", KN, ("remove", ("zz", "lasp_gset")))]),
            "r1",
        )


def test_nested_map_reset_remove_recurses():
    from lasp_tpu.lattice import CrdtMap

    store = Store(n_actors=8)
    m = store.declare(type="riak_dt_map", reset_on_readd=True)
    store.update(m, ("update", [("update", KN, ("update", KC, ("increment", 5)))]),
                 "r1")
    # removing the SUBMAP resets everything the remover observed inside it
    store.update(m, ("update", [("remove", KN)]), "r1")
    assert store.value(m) == {}
    store.update(m, ("update", [("update", KN, ("update", KC, ("increment", 2)))]),
                 "r1")
    assert store.value(m) == {KN: {KC: 2}}  # the 5 stay reset (floor)
    # inner-field reset works the same one level down
    store.update(m, ("update", [("update", KN, ("remove", KC))]), "r1")
    assert store.value(m)[KN] == {}
    store.update(m, ("update", [("update", KN, ("update", KC, ("increment", 4)))]),
                 "r1")
    assert store.value(m)[KN] == {KC: 4}
    # CONCURRENCY: a submap remove vs a concurrent inner update — the
    # update's own contribution survives (recursive reset-remove)
    var = store.variable(m)
    base = var.state
    a = store._apply_op(var, base, ("update", [("remove", KN)]), "r1")
    b = store._apply_op(
        var, base,
        ("update", [("update", KN, ("update", KC, ("increment", 7)))]), "r2",
    )
    merged = CrdtMap.merge(var.spec, a, b)
    assert store._decode_value(var, merged) == {KN: {KC: 7}}


def test_nested_map_mesh_convergence_and_checkpoint(tmp_path):
    from lasp_tpu.store.checkpoint import load_store, save_store

    store = Store(n_actors=8)
    m = store.declare(type="riak_dt_map")
    rt = ReplicatedRuntime(store, Graph(store), 4, ring(4, 2))
    rt.update_at(0, m, ("update", [("update", KN, ("update", KC, ("increment", 2)))]),
                 "w0")
    rt.run_to_convergence(max_rounds=16)
    # nested DYNAMIC admission mid-run at a different replica
    rt.update_at(2, m, ("update", [("update", KN, ("update", KS, ("add", "deep")))]),
                 "w2")
    rt.run_to_convergence(max_rounds=16)
    assert rt.divergence(m) == 0
    want = {KN: {KC: 2, KS: frozenset({"deep"})}}
    assert rt.coverage_value(m) == want
    # checkpoint round-trips nested interners (round-5 recursion fix)
    store.bind_raw(m, jax.tree_util.tree_map(lambda x: x[0], rt.states[m]))
    path = str(tmp_path / "nested.log")
    save_store(store, path)
    restored = load_store(path)
    assert restored.value(m) == want
    restored.update(
        m, ("update", [("update", KN, ("update", KS, ("add", "post")))]), "w9"
    )
    assert restored.value(m)[KN][KS] == frozenset({"deep", "post"})


def test_compact_nested_map_field_path():
    # nested reset churn pins pools too: compact by PATH into the submap
    from lasp_tpu.utils.interning import CapacityError

    store = Store(n_actors=4)
    m = store.declare(type="riak_dt_map", reset_on_readd=True)
    path = (KN, ("s", "lasp_orset"))
    for _cycle in range(6):  # default pool is 4 tokens/actor
        store.update(
            m, ("update", [("update", KN, ("update", path[1], ("add", "x")))]),
            "r1",
        )
        assert store.value(m)[KN][path[1]] == frozenset({"x"})
        store.update(m, ("update", [("update", KN, ("remove", path[1]))]), "r1")
        assert store.compact_map_field(m, path) >= 1
    # population tier, same path
    store2 = Store(n_actors=8)
    m2 = store2.declare(type="riak_dt_map", reset_on_readd=True)
    rt = ReplicatedRuntime(store2, Graph(store2), 4, ring(4, 2))
    for cycle in range(3):
        rt.update_at(
            0, m2,
            ("update", [("update", KN, ("update", path[1], ("add", f"v{cycle}")))]),
            "w0",
        )
        rt.run_to_convergence(max_rounds=16)
        rt.update_at(2, m2, ("update", [("update", KN, ("remove", path[1]))]), "w2")
        rt.run_to_convergence(max_rounds=16)
    assert rt.compact_map_field(m2, path) >= 1
    rt.update_at(1, m2, ("update", [("update", KN, ("update", path[1], ("add", "after")))]), "w1")
    rt.run_to_convergence(max_rounds=16)
    assert rt.coverage_value(m2)[KN][path[1]] == frozenset({"after"})
    assert rt.divergence(m2) == 0
