"""End-to-end application tests: the advertisement-counter workloads.

Rebuilds of ``riak_test/lasp_adcounter_test.erl`` (G-Counter per ad,
threshold-read servers disabling ads at 5 impressions, every client ending
with zero active ads) and ``riak_test/lasp_advertisement_counter_test.erl``
(the full dataflow pipeline: per-publisher OR-Sets -> union -> product with
contracts -> filter by join, servers removing exhausted ads *through the
pipeline*). The reference drives these with processes and sleeps; here
watches + propagate make them deterministic."""

import random

from lasp_tpu import Session
from lasp_tpu.lattice import Threshold


def test_gcounter_adcounter():
    # riak_test/lasp_adcounter_test.erl:57-120
    s = Session()
    n_ads, n_clients, limit = 5, 5, 5
    ads = [s.declare("riak_dt_gcounter", id=f"ad{i}") for i in range(n_ads)]
    # each client tracks its own active-ad list
    active = {c: set(ads) for c in range(n_clients)}

    # one "server" watch per ad: at `limit` impressions, remove everywhere
    def disable(ad):
        def _cb(_result):
            for client_ads in active.values():
                client_ads.discard(ad)
        return _cb

    watches = {}
    for ad in ads:
        w = s.store.read(ad, Threshold(limit))
        w.callback = disable(ad)
        watches[ad] = w

    rng = random.Random(42)
    views = 0
    while any(active.values()) and views < 500:
        client = rng.randrange(n_clients)
        if not active[client]:
            continue
        ad = rng.choice(sorted(active[client]))
        s.update(ad, ("increment",), f"client{client}")
        views += 1

    # all ads exhausted at exactly the threshold; every client drained
    assert [len(active[c]) for c in range(n_clients)] == [0] * n_clients
    for ad in ads:
        assert s.value(ad) == limit
        assert watches[ad].done


def test_orset_adcounter_reactive_removal():
    """``riak_test/lasp_adcounter_orset_test.erl:57-145``: the ad *set*
    itself is an OR-Set of counter ids; each ad's server is a blocking
    threshold read that REMOVES the ad from the set at 5 impressions
    (:128-137), and clients pick ads by re-reading the live set (:139-151)
    rather than from local bookkeeping. Ends with the ad set empty."""
    s = Session(n_actors=8)
    n_ads, n_clients, limit = 5, 5, 5
    ads = s.declare("lasp_orset", n_elems=8)
    counters = [s.declare("riak_dt_gcounter", id=f"oad{i}") for i in range(n_ads)]
    for c in counters:
        s.update(ads, ("add", c), actor="setup")

    # server per ad: parked threshold watch; firing removes the ad from
    # the OR-Set (the reference's server/2 loop, one process per ad)
    for c in counters:
        w = s.read(c, Threshold(limit))
        assert not w.done
        w.callback = lambda _res, c=c: s.update(ads, ("remove", c), actor=c)

    rng = random.Random(7)
    views = 0
    while views < 500:
        live = sorted(s.value(ads))  # clients read the CURRENT ad list
        if not live:
            break
        ad = live[rng.randrange(len(live))]
        s.update(ad, ("increment",), f"client{rng.randrange(n_clients)}")
        views += 1

    assert s.value(ads) == frozenset()  # every ad disabled by its server
    for c in counters:
        assert s.value(c) == limit  # live-set reads stop views at exactly 5
    assert views == n_ads * limit


def test_advertisement_counter_dataflow():
    # riak_test/lasp_advertisement_counter_test.erl:64-235, shrunk shapes
    s = Session(n_actors=16)
    n_per_pub, n_clients, limit = 3, 3, 3

    rovio_ids = [f"r{i}" for i in range(n_per_pub)]
    trifork_ids = [f"t{i}" for i in range(n_per_pub)]

    counters = {}
    rovio = s.declare("lasp_orset", n_elems=4)
    trifork = s.declare("lasp_orset", n_elems=4)
    for ad_id in rovio_ids:
        counters[ad_id] = s.declare("riak_dt_gcounter", id=f"ctr_{ad_id}")
        s.update(rovio, ("add", ("ad", ad_id)), "rovio")
    for ad_id in trifork_ids:
        counters[ad_id] = s.declare("riak_dt_gcounter", id=f"ctr_{ad_id}")
        s.update(trifork, ("add", ("ad", ad_id)), "trifork")

    contracts = s.declare("lasp_orset", n_elems=8)
    for ad_id in rovio_ids + trifork_ids:
        s.update(contracts, ("add", ("contract", ad_id)), "legal")

    ads = s.union(rovio, trifork)
    ads_contracts = s.product(ads, contracts)
    ads_with_contracts = s.filter(
        ads_contracts, lambda pair: pair[0][1] == pair[1][1]
    )

    # every ad joined with exactly its own contract
    assert s.value(ads_with_contracts) == frozenset(
        {(("ad", a), ("contract", a)) for a in rovio_ids + trifork_ids}
    )

    # servers: when an ad's counter passes `limit`, remove the ad from the
    # *union output* — the removal must drain through product and filter
    # (the reference's server does exactly this, :196-204)
    def disable(ad_id):
        def _cb(_result):
            s.store.update(ads, ("remove", ("ad", ad_id)), f"server_{ad_id}")
        return _cb

    for ad_id, ctr in counters.items():
        w = s.store.read(ctr, Threshold(limit))
        w.callback = disable(ad_id)

    rng = random.Random(7)
    views = 0
    while views < 500:
        visible = s.value(ads_with_contracts)
        if not visible:
            break
        (_, ad_id), _ = sorted(visible)[rng.randrange(len(visible))]
        s.update(counters[ad_id], ("increment",), f"client{rng.randrange(n_clients)}")
        views += 1

    assert s.value(ads_with_contracts) == frozenset()
    assert s.value(ads) == frozenset()
    for ad_id, ctr in counters.items():
        assert s.value(ctr) == limit  # disabled at exactly the threshold
    assert views == limit * 2 * n_per_pub
