# Top-level conveniences; the native engines build via native/Makefile
# (tests/conftest.py invokes it automatically).

.PHONY: test bench native bridge-e2e verify

test:
	python -m pytest tests/ -q

# lint + fast suite: the metrics-catalog check keeps the telemetry key
# set (docs/OBSERVABILITY.md) in lock-step with the code, then the
# non-slow tests run (the tier-1 shape)
verify:
	python tools/check_metrics_catalog.py
	python -m pytest tests/ -q -m 'not slow'

bench:
	python bench.py

native:
	$(MAKE) -C native

# Real-BEAM end-to-end of the lasp_backend delegation: starts the bridge
# server, compiles bridge/erlang/lasp_tpu_backend.erl on a BEAM (local
# escript, or a stock `erlang:26` container when only docker exists) and
# drives start/put/get/merge_batch against the live server. See
# tools/bridge_e2e.sh; a Python twin of the exact scenario runs in the
# normal suite (tests/bridge/test_beam_e2e.py) so protocol drift shows
# up even on BEAM-less machines like this image.
bridge-e2e:
	bash tools/bridge_e2e.sh
