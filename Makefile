# Top-level conveniences; the native engines build via native/Makefile
# (tests/conftest.py invokes it automatically).
#
# Test tiers (see README "Test tiers"):
#   test-fast — `-m 'not slow'`: the tier-1 quick suite (finishes in a
#               few minutes; statem soak seeds and heavy measurement
#               tests are excluded)
#   test-slow — only the slow tier (full statem soaks, the telemetry
#               overhead measurement)
#   test      — everything

.PHONY: test test-fast test-slow bench native bridge-e2e verify

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -m 'not slow'

test-slow:
	python -m pytest tests/ -q -m 'slow'

# lint + fast suite: the telemetry-catalog check keeps the metric /
# event / span key sets (docs/OBSERVABILITY.md) in lock-step with the
# code, a fast frontier-vs-dense equivalence smoke guards the delta
# gossip engine's bit-identical contract, a planned-vs-per-var smoke
# guards the megabatch dispatch plan's bit-identical contract on a
# mixed-codec store (docs/PERF.md "Batched dispatch"), a seeded chaos
# soak guards the convergence-under-failure invariants (post-heal
# bit-equality + replay determinism, docs/RESILIENCE.md), a roofline
# smoke guards the cost ledger's non-null fractions + the probe-report
# schema (docs/OBSERVABILITY.md "Roofline & cost ledger"), a Pallas
# smoke guards the hand-written kernels' interpret-mode parity and the
# winner-ships race contract (docs/PERF.md "Pallas kernels"), a
# dataflow-fusion smoke guards the propagate megakernel's fused-vs-
# per-edge bit-identity over a mixed-codec graph with a non-stackable
# edge plus its live roofline row (docs/PERF.md "Dataflow fusion"), a
# quorum smoke guards the batched-FSM-vs-sequential-reference
# bit-identity and the no-acked-write-lost hinted-handoff invariant
# (docs/RESILIENCE.md "Quorum coordination"), a serve smoke guards the
# serving front-end's coalesced-vs-sequential bit-identity, vectorized
# watch fan-out parity, and typed shed accounting under forced
# overload (docs/SERVING.md), an AAE smoke guards the corruption
# drill end-to-end — inject -> detect -> localize -> repair ->
# bit-equal across three codecs x both corruption presets plus
# aae_* metric liveness (docs/RESILIENCE.md "Active anti-entropy"),
# then the non-slow tests run (the tier-1 shape)
# ... and a sharded-frontier smoke guards the multi-chip hot path on
# the 8-device emulated mesh: sparse boundary exchange bit-identical to
# the dense partitioned round AND the unsharded reference across
# ring/random x leafwise/vclock/packed x both wire modes, plus the
# hierarchical converge's exact-round-count contract (docs/PERF.md
# "Sharded frontier"), and a membership smoke guards the staged
# join/rebalance/leave round-trip's static-twin bit-equality across
# ring/random x leafwise/vclock/packed, the no-acked-write-lost
# contract under rolling-crash mid-rebalance, and membership_* /
# handoff_transfer telemetry liveness (docs/RESILIENCE.md
# "Membership & handoff"), and a flight smoke guards the on-device
# flight recorder: a fused converge_on_device's drained per-round
# per-var residual records bit-identical to unfused stepping on the
# same seed, with a monotone-plausible curve (docs/OBSERVABILITY.md
# "Flight recorder")
verify:
	python tools/check_metrics_catalog.py
	python tools/frontier_smoke.py
	python tools/shard_smoke.py
	python tools/plan_smoke.py
	python tools/chaos_smoke.py
	python tools/roofline_smoke.py
	python tools/pallas_smoke.py
	python tools/dataflow_fusion_smoke.py
	python tools/quorum_smoke.py
	python tools/serve_smoke.py
	python tools/aae_smoke.py
	python tools/ingest_smoke.py
	python tools/membership_smoke.py
	python tools/flight_smoke.py
	python -m pytest tests/ -q -m 'not slow'

bench:
	python bench.py

native:
	$(MAKE) -C native

# Real-BEAM end-to-end of the lasp_backend delegation: starts the bridge
# server, compiles bridge/erlang/lasp_tpu_backend.erl on a BEAM (local
# escript, or a stock `erlang:26` container when only docker exists) and
# drives start/put/get/merge_batch against the live server. See
# tools/bridge_e2e.sh; a Python twin of the exact scenario runs in the
# normal suite (tests/bridge/test_beam_e2e.py) so protocol drift shows
# up even on BEAM-less machines like this image.
bridge-e2e:
	bash tools/bridge_e2e.sh
