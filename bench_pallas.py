"""Standalone sweep: the hand-written Pallas gossip kernels vs XLA.

Run on the TPU:  python bench_pallas.py  — prints one JSON line per
config, two sweeps:

- **dense**: the fused gather+join kernel (``pallas_gossip_round``) over
  row-width configs. The kernel wins when per-replica rows are wide
  (large element universes): the XLA path materializes K gathered copies
  of each plane in HBM per round, the kernel streams rows through VMEM.
- **frontier**: the row-sparse gather–join–scatter kernel
  (``pallas_gossip_round_rows``) over a dirty-fraction × bucket × fanout
  grid — the SpMM-shaped hot kernel of the frontier scheduler. Per
  config both arms' achieved GB/s and HBM roofline fraction come from
  the analytic traffic model + capability registry
  (``telemetry.roofline.kernel_traffic`` / ``capability
  .device_capability``) — the same denominators the cost ledger and the
  bench artifacts use, never ad-hoc byte math — and every dispatch
  feeds the kernel ledger, so a ``lasp_tpu roofline`` after a sweep
  attributes the sweep's traffic per signature.

In-process (CPU) the script refuses: Mosaic only compiles on TPU, and
interpret-mode timings would be the emulator's, not the kernel's.
Parity for both kernels is asserted per config against the XLA round.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _roofline(bytes_moved: int, secs: float, peak: "float | None") -> dict:
    from lasp_tpu.bench_scenarios import roofline_entry

    return roofline_entry(bytes_moved, secs, peak)


def _seed_states(spec, n):
    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.ops import PackedORSet

    states = replicate(PackedORSet.new(spec), n)
    return jax.vmap(
        lambda i, s: PackedORSet.add(
            spec, s, i % spec.n_elems, i % spec.n_actors
        )
    )(jnp.arange(n), states)


def dense_sweep(peak, reps: int = 8):
    from lasp_tpu.mesh import gossip_round, random_regular
    from lasp_tpu.ops import PackedORSet, PackedORSetSpec
    from lasp_tpu.ops.pallas_gossip import flatten_plane, pallas_gossip_round
    from lasp_tpu.telemetry import get_ledger
    from lasp_tpu.telemetry.roofline import kernel_traffic

    configs = [
        # (replicas, n_elems, tokens-per-actor)
        (1 << 15, 128, 32),   # wide rows: 128 elems x 8 words = 4KB/row
        (1 << 17, 16, 8),     # medium
        (1 << 20, 8, 4),      # the headline shape (narrow rows)
    ]
    k = 3
    for n, e, tpa in configs:
        spec = PackedORSetSpec(n_elems=e, n_actors=8, tokens_per_actor=tpa)
        states = _seed_states(spec, n)
        nbrs = jnp.asarray(random_regular(n, k, seed=1))
        row_bytes = 2 * spec.n_elems * spec.n_words * 4

        xla = jax.jit(lambda s, nb: gossip_round(PackedORSet, spec, s, nb))
        jax.block_until_ready(xla(states, nbrs))
        t0 = time.perf_counter()
        out = states
        for _ in range(reps):
            out = xla(out, nbrs)
        jax.block_until_ready(out)
        xla_s = (time.perf_counter() - t0) / reps

        fe, _ = flatten_plane(states.exists)
        fr, _ = flatten_plane(states.removed)
        t0 = time.perf_counter()
        jax.block_until_ready(pallas_gossip_round(fe, fr, nbrs, block=8))
        warmup_s = time.perf_counter() - t0
        # two records per signature: the warm-up dispatch banks into the
        # ledger's compile bucket (record #1 of a label always does), the
        # timed reps land as WARM stats — so `lasp_tpu roofline` after a
        # sweep attributes the traffic instead of showing an empty row
        get_ledger().record(
            "pallas_dense", "PackedORSet", n_replicas=n, fanout=k,
            seconds=warmup_s, row_bytes=row_bytes,
            bytes_moved=(k + 2) * n * row_bytes, joins=n * k, rounds=1,
        )
        t0 = time.perf_counter()
        pe, pr = fe, fr
        for _ in range(reps):
            pe, pr = pallas_gossip_round(pe, pr, nbrs, block=8)
        jax.block_until_ready((pe, pr))
        pallas_s = (time.perf_counter() - t0) / reps
        get_ledger().record(
            "pallas_dense", "PackedORSet", n_replicas=n, fanout=k,
            seconds=pallas_s * reps, row_bytes=row_bytes,
            bytes_moved=(k + 2) * n * row_bytes * reps,
            joins=n * k * reps, rounds=reps,
        )

        # cross-check one round
        ref = xla(states, nbrs)
        ref_fe, _ = flatten_plane(ref.exists)
        one_e, _ = pallas_gossip_round(fe, fr, nbrs, block=8)
        match = bool(jnp.all(one_e == ref_fe))

        est = kernel_traffic(
            "pallas_dense", row_bytes=row_bytes, n_replicas=n, fanout=k
        )
        print(
            json.dumps(
                {
                    "sweep": "dense",
                    "replicas": n,
                    "row_bytes": row_bytes,
                    "xla_round_s": round(xla_s, 4),
                    "pallas_round_s": round(pallas_s, 4),
                    "speedup": round(xla_s / pallas_s, 2),
                    "xla": _roofline(est.bytes_moved, xla_s, peak),
                    "pallas": _roofline(est.bytes_moved, pallas_s, peak),
                    "match": match,
                }
            )
        )


def frontier_sweep(peak, n: int = 1 << 17, reps: int = 8):
    """The row-sparse grid: dirty-fraction × bucket × fanout. One round
    per rep, fresh-seeded rows per config; bucket is the pow2 pad the
    runtime's `_frontier_bucket` would pick for that dirty count."""
    from lasp_tpu.mesh import random_regular
    from lasp_tpu.mesh.gossip import gossip_round_rows
    from lasp_tpu.ops import PackedORSet, PackedORSetSpec
    from lasp_tpu.ops.pallas_gossip import pallas_gossip_round_rows
    from lasp_tpu.telemetry import get_ledger
    from lasp_tpu.telemetry.roofline import kernel_traffic

    spec = PackedORSetSpec(n_elems=32, n_actors=8, tokens_per_actor=8)
    row_bytes = 2 * spec.n_elems * spec.n_words * 4
    states = _seed_states(spec, n)
    rng = np.random.RandomState(5)
    for fanout in (2, 3, 8):
        nbrs = jnp.asarray(random_regular(n, fanout, seed=2))
        for dirty_frac in (0.001, 0.01, 0.05):
            f = max(1, int(dirty_frac * n))
            bucket = 16
            while bucket < f:
                bucket <<= 1
            rows_np = rng.choice(n, size=f, replace=False)
            padded = np.full(bucket, rows_np[0], dtype=np.int64)
            padded[:f] = rows_np
            rows = jnp.asarray(padded)

            xla = jax.jit(
                lambda s, nb, r: gossip_round_rows(
                    PackedORSet, spec, s, nb, r
                )
            )
            out = xla(states, nbrs, rows)
            jax.block_until_ready(out[1])
            t0 = time.perf_counter()
            for _ in range(reps):
                out = xla(states, nbrs, rows)
                jax.block_until_ready(out[1])
            xla_s = (time.perf_counter() - t0) / reps

            pl = jax.jit(
                lambda s, nb, r: pallas_gossip_round_rows(
                    PackedORSet, spec, s, nb, r
                )
            )
            est = kernel_traffic(
                "pallas_rows", row_bytes=row_bytes, n_replicas=n,
                fanout=fanout, rows=bucket,
            )
            t0 = time.perf_counter()
            pout = pl(states, nbrs, rows)
            jax.block_until_ready(pout[1])
            warmup_s = time.perf_counter() - t0
            # warm-up record -> compile bucket; timed reps -> warm stats
            # (explicit bytes/joins for ALL reps, so achieved GB/s never
            # divides one dispatch's analytic bytes by reps' wall time)
            get_ledger().record(
                "pallas_rows", "PackedORSet", n_replicas=n, fanout=fanout,
                seconds=warmup_s, row_bytes=row_bytes, rows=bucket,
                bytes_moved=est.bytes_moved, joins=est.joins, rounds=1,
            )
            t0 = time.perf_counter()
            for _ in range(reps):
                pout = pl(states, nbrs, rows)
                jax.block_until_ready(pout[1])
            pallas_s = (time.perf_counter() - t0) / reps
            get_ledger().record(
                "pallas_rows", "PackedORSet", n_replicas=n, fanout=fanout,
                seconds=pallas_s * reps, row_bytes=row_bytes, rows=bucket,
                bytes_moved=est.bytes_moved * reps, joins=est.joins * reps,
                rounds=reps,
            )

            same = jax.tree_util.tree_map(
                lambda a, b: bool(np.array_equal(
                    np.asarray(a), np.asarray(b))),
                out, pout,
            )
            match = all(jax.tree_util.tree_leaves(same))

            print(
                json.dumps(
                    {
                        "sweep": "frontier",
                        "replicas": n,
                        "row_bytes": row_bytes,
                        "fanout": fanout,
                        "dirty_frac": dirty_frac,
                        "rows": f,
                        "bucket": bucket,
                        "xla_round_s": round(xla_s, 5),
                        "pallas_round_s": round(pallas_s, 5),
                        "speedup": round(xla_s / pallas_s, 2),
                        "xla": _roofline(est.bytes_moved, xla_s, peak),
                        "pallas": _roofline(est.bytes_moved, pallas_s, peak),
                        "match": match,
                    }
                )
            )


def main():
    from lasp_tpu.telemetry.capability import device_capability

    if jax.devices()[0].platform not in ("tpu", "axon"):
        # Mosaic only compiles on TPU; anywhere else we would crash in
        # lowering (GPU) or time the interpret-mode emulator (CPU)
        print(
            json.dumps(
                {"error": "bench_pallas needs a TPU; platform is "
                          f"{jax.devices()[0].platform!r}"}
            )
        )
        return

    cap = device_capability()
    peak = cap["peak_GBps"]
    print(json.dumps({"capability": cap}))
    dense_sweep(peak)
    frontier_sweep(peak)


if __name__ == "__main__":
    main()
