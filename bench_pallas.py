"""Compare the Pallas fused gather+join gossip kernel against the XLA path.

Run on the TPU:  python bench_pallas.py  — prints one JSON line per config.
The Pallas kernel wins when per-replica rows are wide (large element
universes): the XLA path materializes K gathered copies of each plane in
HBM per round, the kernel streams rows through VMEM.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.mesh import gossip_round, random_regular
    from lasp_tpu.ops import PackedORSet, PackedORSetSpec
    from lasp_tpu.ops.pallas_gossip import flatten_plane, pallas_gossip_round

    if jax.devices()[0].platform not in ("tpu", "axon"):
        # Mosaic only compiles on TPU; anywhere else we would crash in
        # lowering (GPU) or time the interpret-mode emulator (CPU)
        print(
            json.dumps(
                {"error": "bench_pallas needs a TPU; platform is "
                          f"{jax.devices()[0].platform!r}"}
            )
        )
        return

    configs = [
        # (replicas, n_elems, words-per-elem tag via tokens)
        (1 << 15, 128, 32),   # wide rows: 128 elems x 8 words = 4KB/row
        (1 << 17, 16, 8),     # medium
        (1 << 20, 8, 4),      # the headline shape (narrow rows)
    ]
    k = 3
    for n, e, tpa in configs:
        spec = PackedORSetSpec(n_elems=e, n_actors=8, tokens_per_actor=tpa)
        states = replicate(PackedORSet.new(spec), n)
        r = jnp.arange(n)
        states = jax.vmap(
            lambda i, s: PackedORSet.add(spec, s, i % spec.n_elems, i % spec.n_actors)
        )(r, states)
        nbrs = jnp.asarray(random_regular(n, k, seed=1))

        xla = jax.jit(lambda s, nb: gossip_round(PackedORSet, spec, s, nb))
        jax.block_until_ready(xla(states, nbrs))
        t0 = time.perf_counter()
        out = states
        for _ in range(8):
            out = xla(out, nbrs)
        jax.block_until_ready(out)
        xla_s = (time.perf_counter() - t0) / 8

        fe, _ = flatten_plane(states.exists)
        fr, _ = flatten_plane(states.removed)
        jax.block_until_ready(pallas_gossip_round(fe, fr, nbrs, block=8))
        t0 = time.perf_counter()
        pe, pr = fe, fr
        for _ in range(8):
            pe, pr = pallas_gossip_round(pe, pr, nbrs, block=8)
        jax.block_until_ready((pe, pr))
        pallas_s = (time.perf_counter() - t0) / 8

        # cross-check one round
        ref = xla(states, nbrs)
        ref_fe, _ = flatten_plane(ref.exists)
        one_e, _ = pallas_gossip_round(fe, fr, nbrs, block=8)
        match = bool(jnp.all(one_e == ref_fe))

        print(
            json.dumps(
                {
                    "replicas": n,
                    "row_bytes": spec.n_elems * spec.n_words * 4,
                    "xla_round_s": round(xla_s, 4),
                    "pallas_round_s": round(pallas_s, 4),
                    "speedup": round(xla_s / pallas_s, 2),
                    "match": match,
                }
            )
        )


if __name__ == "__main__":
    main()
