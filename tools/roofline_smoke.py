#!/usr/bin/env python
"""Roofline-observatory smoke (Makefile ``verify``): a small mixed-codec
scenario must produce a NON-NULL roofline fraction for every warm kernel
signature, the new ``roofline_*`` / ``capability_*`` metrics and the
``gossip.ledger_sample`` span must be live AND cataloged
(docs/OBSERVABILITY.md), and the probe-report schema keys must lint both
ways — the fast guard that the perf instrument of ISSUE 6 cannot
silently go blind again."""

from __future__ import annotations

import importlib.util
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _load_lint():
    path = os.path.join(REPO, "tools", "check_metrics_catalog.py")
    spec = importlib.util.spec_from_file_location("catalog_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    from lasp_tpu.telemetry import device_capability, get_ledger
    from lasp_tpu.telemetry import registry as reg
    from lasp_tpu.telemetry import spans

    # -- capability: a real denominator on every backend --------------------
    cap = device_capability()
    assert cap["peak_GBps"] is not None and cap["peak_GBps"] > 0, cap
    assert cap["source"] in ("pinned", "measured-host"), cap

    # -- drive every ledger-fed family on a mixed-codec store (the ONE
    # shared workload the `roofline` CLI verb also drives) -------------------
    from lasp_tpu.bench_scenarios import roofline_workload

    roofline_workload(n_replicas=128, n_vars=9, rounds=2)

    ledger = get_ledger()
    snap = ledger.snapshot()
    warm = [e for e in snap if e["dispatches"] > 0]
    assert warm, "ledger recorded no warm dispatches"
    families = {e["family"] for e in warm}
    assert "step" in families and "fused_block" in families, families
    assert families & {"rows", "grouped_rows", "grouped_dense"}, families
    for e in warm:
        assert e["achieved_GBps"] is not None and e["achieved_GBps"] >= 0, e
        assert e["roofline_frac"] is not None, (
            f"null roofline_frac for {e['kernel']} — the exact blindness "
            "this PR removes"
        )
    summary = ledger.summary()
    assert summary["roofline_frac"] is not None, summary

    # -- metrics + span actually exported -----------------------------------
    names = reg.get_registry().names()
    for metric in ("roofline_achieved_GBps", "roofline_frac",
                   "capability_peak_GBps"):
        assert metric in names, f"{metric} not in the live registry"
    assert any(
        e["name"] == "gossip.ledger_sample" for e in spans.events()
    ), "no gossip.ledger_sample span emitted"

    # -- catalog lint: the new names + probe schema must be documented ------
    lint = _load_lint()
    docs = lint.cataloged()
    for metric in ("roofline_achieved_GBps", "roofline_frac",
                   "capability_peak_GBps"):
        assert metric in docs["metrics"], f"{metric} not cataloged"
    assert "gossip.ledger_sample" in docs["spans"]
    declared = lint.declared_probe_keys()
    assert declared == docs["probe"], (
        "probe-report schema drift", declared ^ docs["probe"]
    )

    print(
        f"roofline smoke OK: {len(warm)} warm kernel signatures, "
        f"peak {cap['peak_GBps']} GB/s ({cap['source']}), "
        f"achieved {summary['achieved_GBps']} GB/s "
        f"(frac {summary['roofline_frac']}); catalog in sync"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
