#!/usr/bin/env python3
"""Fast grouped-ingest equivalence smoke (Makefile ``verify``).

The ISSUE-14 write-path contract at lint-tier speed: grouped op-table
ingest (``mesh.ingest`` via ``plan="auto"``) must be bit-identical to
the per-var arm (``plan="off"``) AND to sequential per-op ``update_at``
application across gset / gcounter / orswot / packed OR-Set, including
removes and a mid-schedule precondition failure, with a chaos mask
(crash) exercising ``ChaosRuntime.write_batch``'s refusal semantics.
Also asserts ingest metric liveness (``ingest_apply_dispatches_total``,
``ingest_ops_total``, the ``ingest_group_occupancy`` gauge, the
``health()["ingest"]`` view) and a WARM non-null ``ingest_apply``
roofline ledger row. Exits 0 on agreement, 1 with a diff summary."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store, PreconditionError

    n = 24
    nbrs = ring(n, 2)

    def build(plan: str, packed: bool):
        store = Store(n_actors=4)
        ids = []
        for i in range(3):
            ids.append(store.declare(id=f"g{i}", type="lasp_gset",
                                     n_elems=16))
        for i in range(2):
            ids.append(store.declare(id=f"c{i}", type="riak_dt_gcounter",
                                     n_actors=4))
        for i in range(2):
            ids.append(store.declare(id=f"w{i}", type="riak_dt_orswot",
                                     n_elems=8, n_actors=4))
        ids.append(store.declare(id="o0", type="lasp_orset", n_elems=8,
                                 n_actors=4, tokens_per_actor=4))
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs, plan=plan,
                               packed=packed)
        return rt, ids

    def schedule(ids):
        rng = np.random.RandomState(11)
        cycles = []
        for _c in range(3):
            cyc = {}
            for v in ids:
                rows = rng.choice(n, 5, replace=False)
                if v.startswith("g"):
                    ops = [(int(r), ("add", f"e{r % 6}"), "x")
                           for r in rows]
                elif v.startswith("c"):
                    ops = [(int(r), ("increment", 1 + int(r) % 3),
                            ("lane", int(r) % 4)) for r in rows]
                elif v.startswith("w"):
                    ops = [(int(r), ("add", f"s{r % 6}"), f"a{int(r) % 4}")
                           for r in rows]
                else:
                    ops = [(int(r), ("add", f"t{(_c * 3 + r) % 7}"),
                            f"a{int(r) % 4}") for r in rows]
                cyc[v] = ops
            cycles.append(cyc)
        return cycles

    def fail(tag: str, detail: str) -> int:
        print(f"ingest_smoke: {tag}: {detail}", file=sys.stderr)
        return 1

    def states_equal(rt_a, rt_b, ids):
        for v in ids:
            same = jax.tree_util.tree_map(
                lambda x, y: bool(np.array_equal(np.asarray(x),
                                                 np.asarray(y))),
                rt_a.states[v], rt_b.states[v],
            )
            if not all(jax.tree_util.tree_leaves(same)):
                return v
        return None

    for packed in (False, True):
        tag = "packed" if packed else "dense"
        rt_a, ids = build("auto", packed)
        rt_o, _ = build("off", packed)
        rt_s, _ = build("auto", packed)  # per-op update_at reference
        cycles = schedule(ids)
        for cyc in cycles:
            rt_a.ingest_cycle(cyc)
            for v, ops in cyc.items():
                rt_o.update_batch(v, list(ops))
                for r, op, actor in ops:
                    rt_s.update_at(r, v, op, actor)
        bad = states_equal(rt_a, rt_o, ids)
        if bad:
            return fail(tag, f"grouped vs per-var drift on {bad!r}")
        bad = states_equal(rt_a, rt_s, ids)
        if bad:
            return fail(tag, f"grouped vs per-op drift on {bad!r}")
        # frontier marks: grouped == per-op exactly (the no-re-diff claim)
        for v in ids:
            fa, fs = rt_a._frontier.get(v), rt_s._frontier.get(v)
            if not np.array_equal(
                fa if fa is not None else np.zeros(n, bool),
                fs if fs is not None else np.zeros(n, bool),
            ):
                return fail(tag, f"frontier marks drift on {v!r}")
        # mid-batch precondition failure: identical error + final state
        probe = [(0, ("add", "p1"), "a0"), (0, ("remove", "absent"), "a0"),
                 (0, ("add", "p2"), "a0")]
        err_a = err_s = None
        try:
            rt_a.update_batch("o0", list(probe))
        except PreconditionError as exc:
            err_a = exc
        for r, op, actor in probe:
            try:
                rt_s.update_at(r, "o0", op, actor)
            except PreconditionError as exc:
                err_s = exc
                break
        if err_a is None or err_s is None or str(err_a) != str(err_s):
            return fail(tag, f"precondition drift: {err_a!r} vs {err_s!r}")
        if states_equal(rt_a, rt_s, ["o0"]):
            return fail(tag, "post-failure state drift on 'o0'")
        print(f"ingest smoke [{tag}] OK: grouped == per-var == per-op "
              f"over {len(cycles)} cycles x {len(ids)} vars")

    # chaos mask arm: write_batch == a write_at loop under a crash
    from lasp_tpu.chaos.engine import ChaosRuntime, ReplicaDownError
    from lasp_tpu.chaos.schedule import ChaosSchedule, Crash

    def chaos_pair():
        rt, ids = build("auto", False)
        ch = ChaosRuntime(rt, ChaosSchedule(n, nbrs, [Crash(0, 3)],
                                            seed=5))
        ch.step()
        return rt, ch

    probe = [(1, ("add", "ok1"), "x"), (3, ("add", "dead"), "x"),
             (2, ("add", "ok2"), "x")]
    rt_b, ch_b = chaos_pair()
    rt_l, ch_l = chaos_pair()
    eb = el = None
    try:
        ch_b.write_batch("g0", list(probe))
    except ReplicaDownError as exc:
        eb = exc
    for r, op, actor in probe:
        try:
            ch_l.write_at(r, "g0", op, actor)
        except ReplicaDownError as exc:
            el = exc
            break
    if eb is None or el is None or str(eb) != str(el):
        return fail("chaos", f"refusal drift: {eb!r} vs {el!r}")
    if states_equal(rt_b, rt_l, ["g0"]):
        return fail("chaos", "post-refusal state drift")
    print("ingest smoke [chaos] OK: write_batch == write_at loop "
          "(prefix applied, typed refusal)")

    # metric liveness + warm roofline row
    from lasp_tpu.telemetry import get_ledger
    from lasp_tpu.telemetry.convergence import get_monitor
    from lasp_tpu.telemetry.registry import get_registry

    snap = get_registry().snapshot()
    for name in ("ingest_apply_dispatches_total", "ingest_ops_total",
                 "ingest_pad_slots_total", "ingest_group_occupancy",
                 "update_batch_seconds"):
        ent = snap.get(name)
        if not ent or not ent.get("series"):
            return fail("metrics", f"{name} never emitted")
    ing = get_monitor().health().get("ingest") or {}
    if not ing.get("dispatches"):
        return fail("metrics", f"health()['ingest'] empty: {ing!r}")
    rows = [
        k for k in get_ledger().snapshot()
        if k["family"] == "ingest_apply" and k["dispatches"] > 0
        and k.get("achieved_GBps") is not None
    ]
    if not rows:
        return fail("roofline", "no warm ingest_apply ledger row with "
                                "non-null achieved_GBps")
    print(f"ingest smoke [telemetry] OK: metrics live, "
          f"{len(rows)} warm ingest_apply roofline row(s), "
          f"health ingest dispatches={ing['dispatches']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
