#!/usr/bin/env python3
"""Active anti-entropy smoke for the lint tier (Makefile ``verify``):
a sub-minute guard on the corruption drill's whole contract
(docs/RESILIENCE.md "Active anti-entropy"):

1. **inject -> detect -> localize -> repair -> bit-equal** — for THREE
   codecs (gset, OR-SWOT, packed OR-Set) under BOTH corruption-class
   nemesis presets (``bit-rot``, and ``corrupt-partition`` — silent
   corruption inside a split brain), every injected corruption is
   detected within the scrub cadence, localized to exactly the injected
   (var, row) set, quorum-repaired, and the healed population is
   bit-identical to a fault-free twin's fixed point — with replay
   determinism on one cell of the matrix;
2. **repair is targeted** — repair wire bytes stay a fraction of a
   full-state resync (the localization claim, measured);
3. the ``aae_*`` metric family is live in the Prometheus exposition.

Exits 0 on agreement, 1 with the violation."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from lasp_tpu.chaos import CORRUPTION_PRESETS, InvariantViolation, nemesis
    from lasp_tpu.chaos.invariants import run_aae_harness
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    R = 16
    nbrs = ring(R, 2)

    def build(packed: bool):
        # three wire codecs in one store: gset (bool mask), OR-SWOT
        # (vclock-structured ints), OR-Set — flat bit-PACKED in packed
        # mode (the corruption then lands in uint32 wire words)
        store = Store(n_actors=16)
        store.declare(id="g", type="lasp_gset", n_elems=48)
        store.declare(id="o", type="riak_dt_orswot", n_elems=24,
                      n_actors=8)
        store.declare(id="p", type="lasp_orset", n_elems=24,
                      tokens_per_actor=4)
        rt = ReplicatedRuntime(store, Graph(store), R, nbrs,
                               packed=packed)
        for w in range(6):
            rt.update_at((w * 5) % R, "g", ("add", f"e{w}"), f"w{w}")
        rt.update_at(1, "o", ("add", "x"), "a0")
        rt.update_at(5, "o", ("add", "y"), "a1")
        rt.update_at(2, "p", ("add", "t"), "b0")
        return rt

    first = True
    for preset in CORRUPTION_PRESETS:
        for packed in (False, True):
            sched = nemesis(preset, R, nbrs, seed=5, rounds=6)
            try:
                rep = run_aae_harness(
                    lambda p=packed: build(p), sched, scrub_every=1,
                    replay=first,
                )
            except InvariantViolation as exc:
                print(
                    f"aae_smoke: INVARIANT VIOLATED "
                    f"(preset={preset}, packed={packed}): {exc}",
                    file=sys.stderr,
                )
                return 1
            first = False
            if rep["injected"] == 0:
                print(
                    f"aae_smoke: {preset} injected nothing — the drill "
                    "is vacuous",
                    file=sys.stderr,
                )
                return 1
            lat = rep["detection_latency_rounds"]
            if max(lat, default=0) > 1:
                print(
                    f"aae_smoke: detection latency {max(lat)} exceeded "
                    f"the scrub cadence (preset={preset})",
                    file=sys.stderr,
                )
                return 1
            frac = rep["repair_bytes"] / max(rep["full_resync_bytes"], 1)
            if frac >= 1.0:
                print(
                    f"aae_smoke: repair moved {rep['repair_bytes']}B — "
                    f"NOT targeted (full resync is "
                    f"{rep['full_resync_bytes']}B, preset={preset}, "
                    f"packed={packed})",
                    file=sys.stderr,
                )
                return 1
            print(
                f"aae smoke [{preset}, packed={packed}]: "
                f"{rep['injected']} injected, {rep['detected']} "
                f"detected (latency <= {max(lat, default=0)} rounds), "
                f"{rep['repaired_overwrites']} overwrites, repair "
                f"{rep['repair_bytes']}B vs resync "
                f"{rep['full_resync_bytes']}B, twin bit-equal"
            )

    # -- the aae_* metric family is live ------------------------------------
    from lasp_tpu.telemetry import render_prometheus

    text = render_prometheus()
    for needle in ("aae_scrubs_total", "aae_rows_hashed_total",
                   "aae_corruption_detected_total", "aae_repairs_total",
                   "aae_repair_bytes_total"):
        if needle not in text:
            print(f"aae_smoke: metric {needle} not exported",
                  file=sys.stderr)
            return 1
    print("aae smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
