#!/usr/bin/env bash
# One-command BEAM end-to-end: start the bridge server, run the Erlang
# adapter's e2e escript against it (local escript if present, else a
# stock erlang docker image), shut down. Green run == the .erl adapter
# compiles AND speaks the live protocol.
set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${LASP_TPU_BRIDGE_PORT:-9193}"

# pick the BEAM runtime FIRST: without one, fail instantly instead of
# paying the jax-importing server spawn and binding the port for nothing
RUNTIME=""
if command -v escript >/dev/null 2>&1; then
    RUNTIME="escript"
elif command -v docker >/dev/null 2>&1; then
    RUNTIME="docker"
else
    echo "bridge-e2e: neither escript nor docker on PATH" >&2
    echo "(install erlang, or docker for the containerized run)" >&2
    exit 3
fi

# the docker path reaches us via the host-gateway interface, not
# loopback — bind wide for it, loopback-only otherwise
BIND="127.0.0.1"
[ "$RUNTIME" = "docker" ] && BIND="0.0.0.0"

JAX_PLATFORMS=cpu python -m lasp_tpu.cli bridge --host "$BIND" --port "$PORT" &
SRV=$!
trap 'kill "$SRV" 2>/dev/null || true; wait "$SRV" 2>/dev/null || true' EXIT

# wait for OUR listener: the connect probe alone would happily find a
# foreign process already bound to the port while our server died with
# address-in-use — verify the spawned pid is still alive each poll
for _ in $(seq 100); do
    if ! kill -0 "$SRV" 2>/dev/null; then
        echo "bridge-e2e: server process died (port $PORT already in use?)" >&2
        exit 4
    fi
    if python - "$PORT" <<'EOF'
import socket, sys
try:
    socket.create_connection(("127.0.0.1", int(sys.argv[1])), timeout=0.5).close()
except OSError:
    sys.exit(1)
EOF
    then
        break
    fi
    sleep 0.2
done

if [ "$RUNTIME" = "escript" ]; then
    escript lasp_tpu/bridge/erlang/e2e.escript "$PORT"
else
    # host.docker.internal + host-gateway reaches the host's listener on
    # both Linux and Docker Desktop (--network host is a VM-scoped no-op
    # on macOS/Windows); the adapter honors LASP_TPU_BRIDGE_HOST
    docker run --rm \
        --add-host=host.docker.internal:host-gateway \
        -e LASP_TPU_BRIDGE_HOST=host.docker.internal \
        -v "$PWD/lasp_tpu/bridge/erlang":/e2e:ro \
        erlang:26 escript /e2e/e2e.escript "$PORT"
fi
