#!/usr/bin/env python3
"""Fast planned-vs-per-var dispatch equivalence smoke (Makefile ``verify``).

One small mixed-codec store (G-Sets + G-Counters + OR-SWOTs — three plan
groups), stepped to the fixed point twice from identical seeds: once
with the dispatch plan (``plan="auto"``, same-codec variables stacked
into one kernel per group per round) and once per-var (``plan="off"``),
over BOTH schedulers (``frontier_step`` and the dense ``step``) —
asserting identical states EVERY round and identical residual
sequences. A sub-10s subset of tests/mesh/test_plan.py for the
lint-tier loop; exits 0 on agreement, 1 with a diff summary on drift."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from anywhere (the Makefile invokes it from the repo root,
# which may not be on sys.path for a bare `python tools/...` call)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular
    from lasp_tpu.store import Store

    n = 96
    nbrs = random_regular(n, 3, seed=19)

    def build(plan: str):
        store = Store(n_actors=4)
        ids = []
        for i in range(4):
            ids.append(store.declare(id=f"g{i}", type="lasp_gset",
                                     n_elems=16))
        for i in range(3):
            ids.append(store.declare(id=f"c{i}", type="riak_dt_gcounter",
                                     n_actors=4))
        for i in range(2):
            ids.append(store.declare(id=f"o{i}", type="riak_dt_orswot",
                                     n_elems=8, n_actors=4))
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs, plan=plan)
        rng = np.random.RandomState(7)
        for v in ids:
            rows = rng.choice(n, 4, replace=False)
            if v.startswith("g"):
                rt.update_batch(
                    v, [(int(r), ("add", f"e{r % 4}"), f"a{r}") for r in rows]
                )
            elif v.startswith("c"):
                rt.update_batch(
                    v,
                    [(int(r), ("increment",), ("lane", int(r) % 4))
                     for r in rows],
                )
            else:
                rt.update_batch(
                    v, [(int(r), ("add", f"x{r % 8}"), f"w{r % 4}")
                        for r in rows]
                )
        return rt, ids

    def drift(tag: str, rnd: int, detail: str) -> int:
        print(f"plan_smoke: {tag} drift at round {rnd}: {detail}",
              file=sys.stderr)
        return 1

    for tag, verb in (("frontier", "frontier_step"), ("dense", "step")):
        rt_p, ids = build("auto")
        rt_o, _ = build("off")
        plan = rt_p._ensure_plan()
        assert len(plan.groups) == 3, plan.describe()
        for rnd in range(64):
            rp, ro = getattr(rt_p, verb)(), getattr(rt_o, verb)()
            if rp != ro:
                return drift(tag, rnd, f"residual planned={rp} pervar={ro}")
            for v in ids:
                same = jax.tree_util.tree_map(
                    lambda x, y: bool(jnp.array_equal(x, y)),
                    rt_p.states[v], rt_o.states[v],
                )
                if not all(jax.tree_util.tree_leaves(same)):
                    return drift(tag, rnd, f"state of var {v!r}")
            if ro == 0:
                print(f"plan smoke [{tag}] OK: bit-identical over "
                      f"{rnd + 1} rounds, {len(plan.groups)} groups / "
                      f"{plan.n_vars} vars")
                break
        else:
            print(f"plan_smoke: [{tag}] no convergence within 64 rounds",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
