#!/usr/bin/env python
"""Round-5 one-shot TPU capture: probe and measure in ONE process.

Round 4's failure pattern, finally diagnosed at round-5 start: the axon
tunnel is single-client, and a *successful* bounded probe followed by a
second client process (the measurement child) is exactly the reconnect
pattern that wedges it — the probe's lease has not expired when the next
interpreter's sitecustomize connects, and that half-registered client
hangs at backend init forever (observed 03:47 probe OK -> 03:48 bench
child hung -> every later connect hung). So this script connects ONCE:
if ``jax.devices()`` answers with a TPU, the same interpreter runs every
capture job back to back, appending one JSON line per stage to
``tools/capture_out/oneshot_r05.jsonl`` (flushed immediately — a later
hang never loses an earlier stage's number).

Stages, most valuable first (VERDICT r4 "next round" #1):
  1. init           — device kind, roofline lookup
  2. headline       — wide-row packed OR-Set anti-entropy (BASELINE headline)
  3. northstar      — FULL 10,485,760-replica ad counter, engine path
  4. pallas         — fused gather+join kernel vs XLA path sweep
  5. packed_vs_dense— wire-format A/B at 1M replicas
  6. sharded_step   — shard_map gossip + sharded fused step on a real-chip
                      Mesh (1 device: the sharding path itself on silicon)

The parent (``tools/tpu_capture.py`` or the shell) must enforce a
timeout and SIGTERM (never SIGKILL first) — if the tunnel is wedged this
process hangs at import-time backend init, before main() even runs.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT_PATH = os.path.join(
    REPO, "tools", "capture_out",
    os.environ.get("LASP_ONESHOT_NAME", "oneshot_r05.jsonl"),
)

# peak-bandwidth lookups live in the capability registry
# (lasp_tpu/telemetry/capability.py) — one table for bench, oneshot,
# and the kernel cost ledger


def emit(stage: str, record: dict) -> None:
    record = {"stage": stage, "t": round(time.time(), 1), **record}
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(f"[oneshot] {stage}: {json.dumps(record)[:300]}", flush=True)


def main() -> int:
    t_start = time.monotonic()
    budget = float(os.environ.get("LASP_ONESHOT_BUDGET", "3600"))

    try:
        import jax  # the ONE backend connect of this process

        dev = jax.devices()[0]
    except BaseException as exc:
        # a failed connect used to die silently (stdout DEVNULL'd under
        # the watcher); persist a CLASSIFIED record instead — the same
        # schema the bench probe report uses
        import traceback

        from lasp_tpu.telemetry.capability import classify_probe_attempt

        tb = traceback.format_exc()
        rec, _platforms = classify_probe_attempt(1, "", tb)
        rec["attempt"] = 1
        rec["seconds"] = round(time.monotonic() - t_start, 1)
        emit("init", {"error": f"{type(exc).__name__}: {exc}",
                      "probe_attempt": rec})
        return 1

    from lasp_tpu.telemetry.capability import device_capability

    kind = str(getattr(dev, "device_kind", dev.platform))
    if dev.platform == "cpu":
        emit("init", {"error": "platform is cpu; nothing to capture",
                      "platforms_seen": sorted(
                          {str(d.platform) for d in jax.devices()}
                      )})
        return 1
    cap = device_capability()
    roofline = cap["peak_GBps"]
    emit("init", {"platform": dev.platform, "device_kind": kind,
                  "roofline_GBps": roofline,
                  "capability_source": cap["source"]})

    import numpy as np

    from lasp_tpu.bench_scenarios import (
        adcounter_10m,
        orset_anti_entropy,
        packed_vs_dense,
    )

    def left() -> float:
        return budget - (time.monotonic() - t_start)

    def oom_adaptive(fn, n0: int, floor: int):
        n, tries = n0, 0
        while True:
            try:
                return fn(n), n, tries
            except Exception as exc:
                if "RESOURCE_EXHAUSTED" not in str(exc) or n // 2 < floor:
                    raise
                n, tries = n // 2, tries + 1

    # -- 2. headline: wide-row packed OR-Set anti-entropy -------------------
    try:
        wide = dict(n_elems=128, n_actors=64, tokens_per_actor=4)
        out, n_used, downs = oom_adaptive(
            lambda n: orset_anti_entropy(n, block=8, **wide),
            1 << 18, floor=1 << 12,
        )
        emit("headline", {
            "n_replicas": n_used, "oom_downscales": downs,
            "merges_per_sec": out["merges_per_sec"],
            "rounds": out["rounds"], "seconds": out["seconds"],
            "achieved_GBps": out["achieved_GBps"],
            "roofline_frac": (
                round(out["achieved_GBps"] / roofline, 3) if roofline else None
            ),
            "state_bytes_per_replica": out["state_bytes_per_replica"],
            "gossip_impl": out["gossip_impl"],
            "impl_block_seconds": out["impl_block_seconds"],
        })
    except Exception as exc:
        emit("headline", {"error": f"{type(exc).__name__}: {exc}"})

    # -- 3. FULL north-star: 10,485,760 replicas, engine path ---------------
    try:
        if left() < 300:
            raise RuntimeError(f"skipped: only {int(left())}s left")
        ns, ns_n, ns_downs = oom_adaptive(
            lambda n: adcounter_10m(n_replicas=n), 10 * (1 << 20),
            floor=1 << 18,
        )
        emit("northstar", {
            "n_replicas": ns_n, "oom_downscales": ns_downs,
            "rounds": ns["rounds"], "seconds": ns["seconds"],
            "under_60s": ns["under_60s"], "engine": ns["engine"],
            "state_bytes_per_replica": ns["state_bytes_per_replica"],
            "check": ns["check"],
        })
    except Exception as exc:
        emit("northstar", {"error": f"{type(exc).__name__}: {exc}"})

    # -- 4. pallas sweep ----------------------------------------------------
    try:
        if left() < 240:
            raise RuntimeError(f"skipped: only {int(left())}s left")
        import contextlib
        import io

        import bench_pallas

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            bench_pallas.main()
        for line in buf.getvalue().strip().splitlines():
            try:
                emit("pallas", json.loads(line))
            except json.JSONDecodeError:
                pass
    except Exception as exc:
        emit("pallas", {"error": f"{type(exc).__name__}: {exc}"})

    # -- 5. packed vs dense at 1M -------------------------------------------
    try:
        if left() < 180:
            raise RuntimeError(f"skipped: only {int(left())}s left")
        pv = packed_vs_dense(n_replicas=1 << 20)
        emit("packed_vs_dense", pv)
    except Exception as exc:
        emit("packed_vs_dense", {"error": f"{type(exc).__name__}: {exc}"})

    # -- 6. sharded step on a real-chip mesh --------------------------------
    # One real device, but the SAME pjit/shard_map lowering as the 8-way
    # dryrun (collectives degenerate to identity; what's being proven is
    # that the sharded executable compiles and runs on silicon).
    try:
        if left() < 120:
            raise RuntimeError(f"skipped: only {int(left())}s left")
        import __graft_entry__ as ge

        t0 = time.perf_counter()
        # in-process on purpose: dryrun_multichip() would spawn a CPU
        # child; _dryrun_inline over jax.devices()[:1] runs the SAME
        # sharded lowering (pjit step + shard_map gossip + comm-mesh
        # round, value-asserted) on the real chip
        evidence = ge._dryrun_inline(1)
        emit("sharded_step", {
            "n_devices": 1, "ok": True,
            "seconds": round(time.perf_counter() - t0, 2),
            "evidence": evidence,
            "note": "sharded fused step + shard_map gossip + comm-mesh "
                    "round on the real chip (collectives degenerate at "
                    "n=1; lowering and execution are the claim)",
        })
    except Exception as exc:
        emit("sharded_step", {"error": f"{type(exc).__name__}: {exc}"})

    emit("done", {"elapsed_s": round(time.monotonic() - t_start, 1)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
