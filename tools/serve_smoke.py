#!/usr/bin/env python3
"""Serving-front-end smoke for the lint tier (Makefile ``verify``): a
sub-minute guard on the tentpole's contracts (docs/SERVING.md):

1. **coalesced == sequential, bit-for-bit** — a burst of client writes
   applied through the front-end's coalescing cycle produces the
   IDENTICAL final population as applying the same requests one at a
   time via ``update_at`` in submission order;
2. **vectorized watch fan-out fires** — threshold watches registered
   through the front-end fire exactly once, and the tensorized verdict
   pass agrees with the per-watch reference across codecs;
3. **forced overload sheds, typed** — with toy queue capacities an
   open-loop burst produces nonzero shed accounting with retry-after
   hints and a climbed degradation ladder, and NOTHING is silently
   dropped (offered == terminal outcomes);
4. the ``serve_*`` metric family is live in the Prometheus exposition.

Exits 0 on agreement, 1 with the divergence."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from lasp_tpu.chaos.invariants import fingerprint, snapshot_states
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.lattice import Threshold
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.mesh.topology import ring
    from lasp_tpu.serve import AdmissionController, ServeFrontend
    from lasp_tpu.serve.harness import threshold_parity
    from lasp_tpu.store import Store

    R = 16

    def build():
        store = Store(n_actors=64)
        store.declare(id="kv", type="lasp_gset", n_elems=128)
        store.declare(id="os", type="lasp_orset", n_elems=64,
                      tokens_per_actor=4)
        store.declare(id="ctr", type="riak_dt_gcounter", n_actors=64)
        return ReplicatedRuntime(store, Graph(store), R, ring(R, 3))

    rng = np.random.RandomState(5)
    requests = []
    for i in range(160):
        which = i % 3
        replica = int(rng.randint(R))
        if which == 0:
            requests.append(("kv", ("add", f"k{int(rng.randint(40))}"),
                             f"c{i}", replica))
        elif which == 1:
            requests.append(("os", ("add", f"e{int(rng.randint(20))}"),
                             f"c{i}", replica))
        else:
            requests.append(("ctr", ("increment",), f"a{replica}",
                             replica))

    # -- 1. coalesced == sequential bit-identity ----------------------------
    rt_seq = build()
    for var, op, actor, replica in requests:
        rt_seq.update_at(replica, var, op, actor)
    fp_seq = fingerprint(snapshot_states(rt_seq))

    rt_co = build()
    fe = ServeFrontend(rt_co, gossip_block=0, write_backup=False)
    tickets = [
        fe.submit_write(var, op, actor, replica=replica)
        for var, op, actor, replica in requests
    ]
    fe.cycle()
    if not all(t.status == "done" for t in tickets):
        print("serve_smoke: not every coalesced write resolved",
              file=sys.stderr)
        return 1
    fp_co = fingerprint(snapshot_states(rt_co))
    if fp_seq != fp_co:
        print("serve_smoke: coalesced ingest != sequential per-request "
              "application (bit-identity violated)", file=sys.stderr)
        return 1
    print(f"serve smoke [coalesce]: {len(requests)} writes coalesced "
          "bit-identical to sequential update_at")

    # -- 2. watch fan-out fires, vectorized == per-watch --------------------
    w_met = fe.submit_watch("ctr", Threshold(1), replica=0)
    w_unmet = fe.submit_watch("ctr", Threshold(10_000), replica=0)
    w_set = fe.submit_watch("kv", None, replica=3)  # bottom: met
    fe.cycle()
    if not (w_met.status == "done" and w_set.status == "done"
            and w_unmet.status == "queued"):
        print(
            f"serve_smoke: watch fan-out wrong ({w_met.status}/"
            f"{w_set.status}/{w_unmet.status})", file=sys.stderr,
        )
        return 1
    parity = threshold_parity(rt_co, "ctr", 4096, seed=9)
    print(f"serve smoke [watches]: fan-out fired exactly-once; "
          f"vectorized == per-watch at {parity['n_thresholds']} "
          "thresholds")

    # -- 3. forced overload: typed sheds, ladder, nothing silent ------------
    rt_ov = build()
    fe2 = ServeFrontend(
        rt_ov,
        admission=AdmissionController(
            capacity={"write": 64, "read": 64, "watch": 64},
        ),
        gossip_block=2,
    )
    sheds = 0
    for i in range(600):
        t = fe2.submit_write("kv", ("add", f"k{i % 40}"), f"c{i}",
                             replica=i % R)
        if t.status == "shed":
            sheds += 1
            if t.retry_after_ms <= 0:
                print("serve_smoke: shed without retry_after_ms",
                      file=sys.stderr)
                return 1
        if i % 300 == 299:
            fe2.cycle()
    fe2.drain()
    rep = fe2.report()
    offered = sum(rep["offered"].values())
    terminal = (
        sum(rep["completed"].values()) + sum(rep["errors"].values())
        + sum(rep["expired"].values()) + sheds
    )
    if sheds == 0:
        print("serve_smoke: forced overload shed nothing", file=sys.stderr)
        return 1
    if offered != terminal:
        print(
            f"serve_smoke: {offered} offered but {terminal} terminal "
            "outcomes — a request was silently dropped", file=sys.stderr,
        )
        return 1
    if rep["admission"]["level"] == 0 and not rep["admission"]["transitions"]:
        print("serve_smoke: overload never climbed the ladder",
              file=sys.stderr)
        return 1
    print(
        f"serve smoke [overload]: {sheds} typed sheds, ladder peaked at "
        f"level {max(lv for _c, _o, lv, _p in rep['admission']['transitions'])}, "
        "zero silent drops"
    )

    # -- 4. the serve_* metric family is live -------------------------------
    from lasp_tpu.telemetry import render_prometheus

    text = render_prometheus()
    for needle in ("serve_requests_total", "serve_shed_total",
                   "serve_watch_fires_total", "serve_cycle_seconds"):
        if needle not in text:
            print(f"serve_smoke: metric {needle} not exported",
                  file=sys.stderr)
            return 1
    print("serve smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
