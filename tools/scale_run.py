#!/usr/bin/env python3
"""North-star scale run: 1M-10M replicas across the (emulated or real)
multi-chip mesh, with roofline accounting — ROADMAP open item 1's
artifact producer.

Runs ``bench_scenarios.mesh_scale`` (the sharded-frontier steady-state
workload: sparse boundary exchange + hierarchical on-device
quiescence) at population scale and persists a MULTICHIP-shaped JSON
artifact carrying per-shard cut-row bytes, ``cut_rows_sparse_bytes``
vs ``cut_rows_dense_bytes``, the exchange-vs-interior overlap
fraction, rounds-to-quiescence, achieved GB/s and ``roofline_frac``
via the capability registry — real per-device numbers, never
``{ok: true, tail: ""}``.

Usage::

    python tools/scale_run.py --replicas 1048576 --devices 8 \
        --out docs/artifacts/scale_run.json

On a machine without accelerators the mesh is CPU-emulated
(``--xla_force_host_platform_device_count``); on TPU pass
``--no-force-cpu`` so the real chips serve the mesh."""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=1 << 20)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--cycles", type=int, default=2)
    ap.add_argument("--write-frac", type=float, default=0.002)
    ap.add_argument("--vars", type=int, default=2)
    ap.add_argument("--mode", choices=["gather", "alltoall"],
                    default="alltoall")
    ap.add_argument("--sync-every", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="artifact path (default: stdout only)")
    ap.add_argument("--no-force-cpu", action="store_true",
                    help="use the machine's real accelerators instead "
                         "of the emulated CPU mesh")
    args = ap.parse_args()

    if not args.no_force_cpu:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import jax

    if not args.no_force_cpu:
        jax.config.update("jax_platforms", "cpu")
    from lasp_tpu.bench_scenarios import mesh_scale

    t0 = time.time()
    out = mesh_scale(
        n_replicas=args.replicas,
        n_shards=args.devices,
        write_frac=args.write_frac,
        cycles=args.cycles,
        n_vars=args.vars,
        mode=args.mode,
        sync_every=args.sync_every,
    )
    artifact = {
        "ok": True,
        "kind": "scale_run",
        "wall_seconds": round(time.time() - t0, 1),
        "devices": [
            {
                "id": int(d.id),
                "platform": str(d.platform),
                "kind": str(getattr(d, "device_kind", d.platform)),
            }
            for d in jax.devices()[: args.devices]
        ],
        **out,
    }
    text = json.dumps(artifact, indent=1, default=str)
    print(text)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fp:
            fp.write(text + "\n")
        print(f"scale_run: artifact written to {args.out}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
