#!/usr/bin/env python
"""Pallas-kernel smoke (Makefile ``verify``): interpret-mode parity for
the hand-written Mosaic kernels — the dense packed-OR-Set gather+join
(``pallas_gossip_round``, including the satellite-1 non-divisible-
population pad fix) and the row-sparse gather–join–scatter kernel
(``pallas_gossip_round_rows[_grouped]``) across leafwise / vclock /
packed codecs with edge masks and valid masks — plus a winner-ships
race dry run: a runtime under ``pallas_rows_mode="interpret"`` must
converge bit-identically to the XLA-only runtime, record BOTH arms'
timings per dispatch signature, never ship the emulator, and land
``pallas_rows`` / ``pallas_dense`` roofline rows (non-null fractions)
in the kernel ledger. Compiled Mosaic is exercised on the real chip by
bench_pallas.py; this smoke keeps the contract guarded on every
backend. See docs/PERF.md "Pallas kernels"."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _tree_eq(a, b) -> bool:
    same = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b,
    )
    return all(jax.tree_util.tree_leaves(same))


def dense_parity() -> None:
    """``pallas_gossip_round`` == XLA ``gossip_round`` on packed planes,
    at a population NOT divisible by the grid block (the pad fix)."""
    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.mesh import gossip_round, random_regular
    from lasp_tpu.ops import PackedORSet, PackedORSetSpec
    from lasp_tpu.ops.pallas_gossip import (
        flatten_plane,
        pallas_gossip_round,
        unflatten_plane,
    )

    spec = PackedORSetSpec(n_elems=16, n_actors=8, tokens_per_actor=8)
    n = 27  # 27 % 8 != 0: ships via the wrapper's internal pad
    st = replicate(PackedORSet.new(spec), n)
    st = jax.vmap(
        lambda i, s: PackedORSet.add(spec, s, i % 16, i % 8)
    )(jnp.arange(n), st)
    nbrs = jnp.asarray(random_regular(n, 3, seed=41))
    ref = gossip_round(PackedORSet, spec, st, nbrs)
    fe, _ = flatten_plane(st.exists)
    fr, _ = flatten_plane(st.removed)
    oe, orr = pallas_gossip_round(fe, fr, nbrs, block=8, interpret=True)
    assert _tree_eq(
        (unflatten_plane(oe, st.exists.shape),
         unflatten_plane(orr, st.removed.shape)),
        (ref.exists, ref.removed),
    ), "dense Pallas kernel diverged from gossip_round"


def rows_parity() -> None:
    """Row-sparse parity across the kernel's join families (leafwise
    or, vclock, packed two-plane) under edge masks + grouped valid
    masks — bit-identical states AND changed flags."""
    from lasp_tpu.lattice.base import replicate
    from lasp_tpu.lattice.gset import GSet, GSetSpec
    from lasp_tpu.lattice.orswot import ORSWOT, ORSWOTSpec
    from lasp_tpu.mesh import random_regular
    from lasp_tpu.mesh.gossip import (
        gossip_round_rows,
        gossip_round_rows_grouped,
    )
    from lasp_tpu.ops import PackedORSet, PackedORSetSpec
    from lasp_tpu.ops.pallas_gossip import (
        pallas_gossip_round_rows,
        pallas_gossip_round_rows_grouped,
    )

    n, k = 40, 3
    r = jnp.arange(n)
    pops = []
    spec = GSetSpec(n_elems=16)
    st = replicate(GSet.new(spec), n)
    pops.append((GSet, spec, jax.vmap(
        lambda i, s: GSet.add(spec, s, i % 16))(r, st)))
    spec = ORSWOTSpec(n_elems=8, n_actors=4)
    st = replicate(ORSWOT.new(spec), n)
    pops.append((ORSWOT, spec, jax.vmap(
        lambda i, s: ORSWOT.add(spec, s, i % 8, i % 4))(r, st)))
    spec = PackedORSetSpec(n_elems=16, n_actors=8, tokens_per_actor=8)
    st = replicate(PackedORSet.new(spec), n)
    pops.append((PackedORSet, spec, jax.vmap(
        lambda i, s: PackedORSet.add(spec, s, i % 16, i % 8))(r, st)))

    nbrs = jnp.asarray(random_regular(n, k, seed=43))
    rng = np.random.RandomState(47)
    mask = jnp.asarray(rng.rand(n, k) > 0.4)
    rows = jnp.asarray(rng.randint(0, n, size=10))
    for codec, spec, st in pops:
        ref = gossip_round_rows(codec, spec, st, nbrs, rows, mask)
        got = pallas_gossip_round_rows(
            codec, spec, st, nbrs, rows, mask, interpret=True
        )
        assert _tree_eq(ref, got), (
            f"row-sparse Pallas kernel diverged for {codec.__name__}"
        )
        # grouped twin with a pad tail + a quiescent member
        stacked = jax.tree_util.tree_map(
            lambda x: jnp.stack([x, x[::-1]]), st
        )
        rows_g = jnp.asarray(rng.randint(0, n, size=(2, 8)))
        valid = jnp.asarray(
            np.stack([np.arange(8) < 5, np.zeros(8, bool)])
        )
        ref_g = gossip_round_rows_grouped(
            codec, spec, stacked, nbrs, rows_g, valid
        )
        got_g = pallas_gossip_round_rows_grouped(
            codec, spec, stacked, nbrs, rows_g, valid, interpret=True
        )
        assert _tree_eq(ref_g, got_g), (
            f"grouped row-sparse kernel diverged for {codec.__name__}"
        )


def race_dry_run() -> None:
    """Winner-ships dry run off-TPU: the interpret arm contends, both
    arms' timings land per signature, the emulator never ships, the
    raced fixed point is bit-identical to XLA-only, and the ledger
    carries warm ``pallas_rows`` + ``pallas_dense`` roofline rows."""
    from lasp_tpu.bench_scenarios import (
        _pallas_dense_probe,
        _pallas_rows_probe,
    )
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular
    from lasp_tpu.store import Store
    from lasp_tpu.telemetry import get_ledger

    def build(mode):
        store = Store(n_actors=4)
        ids = [
            store.declare(id="g0", type="lasp_gset", n_elems=16),
            store.declare(id="g1", type="lasp_gset", n_elems=16),
        ]
        rt = ReplicatedRuntime(
            store, Graph(store), 48, random_regular(48, 3, seed=53)
        )
        rt.pallas_rows_mode = mode
        for v in ids:
            rt.update_batch(
                v, [(i, ("add", f"e{i % 8}"), f"a{i}") for i in (3, 17, 31)]
            )
        return rt, ids

    rt_ref, ids = build("off")
    while rt_ref.frontier_step():
        pass
    rt, ids = build("interpret")
    while rt.frontier_step():
        pass
    assert _tree_eq(
        {v: rt_ref.states[v] for v in ids},
        {v: rt.states[v] for v in ids},
    ), "raced runtime diverged from XLA-only runtime"
    assert rt.impl_block_seconds, "race recorded no arm timings"
    for label, rec in rt.impl_block_seconds.items():
        assert "xla" in rec and "winner" in rec, (label, rec)
        assert "pallas_rows" in rec or "pallas_rows_error" in rec, (
            label, rec
        )
        assert rec["winner"] == "xla", (
            f"interpret emulator shipped a dispatch: {label}"
        )

    # ledger + roofline entries for both hand-written kernel families
    rows_arm = _pallas_rows_probe(rt, ids)
    dense_arm = _pallas_dense_probe()
    for name, arm in (("pallas_rows", rows_arm),
                      ("pallas_dense", dense_arm)):
        assert arm is not None and arm["seconds"] > 0, (name, arm)
        assert arm["achieved_GBps"] is not None, (name, arm)
        assert arm["roofline_frac"] is not None, (name, arm)
    warm = {
        e["family"]
        for e in get_ledger().snapshot()
        if e["dispatches"] > 0 and e["roofline_frac"] is not None
    }
    assert {"pallas_rows", "pallas_dense"} <= warm, warm


def main() -> int:
    dense_parity()
    rows_parity()
    race_dry_run()
    print(
        "pallas smoke OK: dense + row-sparse interpret parity "
        "(leafwise/vclock/packed, masks), race dry run recorded both "
        "arms + ledger roofline rows"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
