#!/usr/bin/env python3
"""Membership smoke for the lint tier (Makefile ``verify``): a
sub-minute guard on the staged-membership contracts
(docs/RESILIENCE.md "Membership & handoff"):

1. **round-trip bit-equality** — join → rebalance → leave returns a
   population BIT-IDENTICAL to a static twin built at the base
   membership with the same writes, across ring/random topologies ×
   leafwise (gset) / vclock (orswot) / packed (flat OR-Set) codecs,
   with replay determinism;
2. **no acknowledged write lost** — quorum puts submitted while the
   population grows and shrinks under the rolling-crash nemesis all
   survive (epoch fencing resolves every in-flight request typed;
   hints cover crashed departers);
3. **metric liveness** — the ``membership_*`` metric family, the
   ``membership.transfer`` span, and the ``handoff_transfer`` roofline
   ledger family all record live values during the runs above.

Exits 0 on agreement, 1 with the divergence."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.membership import run_membership_harness
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
    from lasp_tpu.store import Store

    N = 12

    def builder(nbrs, packed):
        def build():
            store = Store(n_actors=32)
            store.declare(id="g", type="lasp_gset", n_elems=64)
            store.declare(id="w", type="riak_dt_orswot", n_elems=32)
            store.declare(id="o", type="lasp_orset", n_elems=32,
                          tokens_per_actor=8)
            return ReplicatedRuntime(store, Graph(store), N, nbrs,
                                     packed=packed)

        return build

    writes = [
        (1, 0, "g", ("add", "w0"), "a0"),
        (3, 5, "w", ("add", "w1"), "a1"),
        (6, 2, "o", ("add", "w2"), "a2"),
        (9, 7, "g", ("add", "w3"), "a3"),
    ]

    # -- 1. join -> rebalance -> leave round-trip bit-equality --------------
    for topo_name, nbrs in (
        ("ring", ring(N, 2)),
        ("random", random_regular(N, 3, seed=7)),
    ):
        for packed in (False, True):
            build = builder(nbrs, packed)
            rep = run_membership_harness(
                build,
                [(2, "join", 18), (8, "leave", N)],
                build_twin=build,
                writes=writes,
                per_cycle=3,
            )
            if not rep.get("bit_identical_to_twin"):
                print(
                    f"membership_smoke: round-trip NOT bit-identical "
                    f"({topo_name}, packed={packed})"
                )
                return 1
            if not rep.get("replay_identical"):
                print(
                    f"membership_smoke: replay diverged "
                    f"({topo_name}, packed={packed})"
                )
                return 1
            print(
                f"round-trip ok [{topo_name} packed={packed}] "
                f"rounds={rep['rounds']} epoch={rep['epoch']}"
            )

    # -- 2. no acked write lost under rolling-crash mid-rebalance -----------
    rep = run_membership_harness(
        builder(ring(N, 2), False),
        [(3, "join", 16), (9, "leave", N)],
        preset="rolling-crash", seed=5, nemesis_rounds=10,
        quorum_writes=[
            (1, "g", ("add", "q0"), "c0", 0),
            (4, "g", ("add", "q1"), "c1", 13),
            (8, "g", ("add", "q2"), "c2", 5),
            (10, "g", ("add", "q3"), "c3", 14),
        ],
        per_cycle=2,
    )
    if not rep.get("no_write_lost"):
        print("membership_smoke: acked write lost under rolling-crash")
        return 1
    print(
        f"no-write-lost ok acked={rep['acked_writes']} "
        f"fenced={rep['stale_epoch_failures']} rounds={rep['rounds']}"
    )

    # -- 3. metric / span / ledger liveness ---------------------------------
    from lasp_tpu.telemetry.registry import get_registry
    from lasp_tpu.telemetry.roofline import get_ledger

    snap = get_registry().snapshot()
    for name in ("membership_epoch", "membership_commits_total",
                 "membership_transfers_total",
                 "membership_transfer_bytes_total",
                 "membership_pending_transfers"):
        fam = snap.get(name)
        if fam is None or not fam["series"]:
            print(f"membership_smoke: metric {name} never recorded")
            return 1
    done = [
        s["value"] for s in snap["membership_transfers_total"]["series"]
        if s["labels"].get("outcome") == "done"
    ]
    if not done or done[0] <= 0:
        print("membership_smoke: no transfers recorded as done")
        return 1
    ledger = [
        r for r in get_ledger().snapshot()
        if r["family"] == "handoff_transfer"
    ]
    if not ledger:
        print("membership_smoke: no handoff_transfer ledger rows")
        return 1
    warm = [r for r in ledger if r["dispatches"] > 0]
    if not warm:
        print("membership_smoke: handoff_transfer rows never warmed "
              "past the compile dispatch")
        return 1
    print(
        f"telemetry ok: {int(done[0])} transfers, "
        f"{len(ledger)} handoff_transfer ledger row(s)"
    )
    print("membership smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
