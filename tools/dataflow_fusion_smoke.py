#!/usr/bin/env python
"""Whole-graph dataflow-fusion smoke (Makefile ``verify``): the fused
propagate megakernel must be bit-identical to the per-edge host loop
over a mixed-codec combinator graph — G-Set map chains, OR-Set filter
chains, OR-SWOT bind_to chains (vclock codec), a union cascade, AND a
non-stackable (pre-poisoned) edge riding as a singleton — with
identical round counts, a live ``dataflow_fused`` roofline row in the
kernel ledger, and the ``dataflow_plan_*`` metrics exported + cataloged
(docs/OBSERVABILITY.md). The fast guard that ISSUE 8's fusion contract
cannot silently rot."""

from __future__ import annotations

import importlib.util
import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _load_lint():
    path = os.path.join(REPO, "tools", "check_metrics_catalog.py")
    spec = importlib.util.spec_from_file_location("catalog_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _drive(mode: str):
    """Twin build + identical write/propagate schedule under one
    scheduler; returns (store, rounds list)."""
    from lasp_tpu.bench_scenarios import _build_dataflow_chains

    store, g = _build_dataflow_chains(n_chains=6, depth=3)
    # the non-stackable member: pre-poison one map edge out of stacked
    # groups (the operator hook the poison guard also uses) — it must
    # ride the megakernel as a singleton, bit-identically
    g.edges[0].stackable = False
    rounds = []
    for rep in range(2):
        for c in range(6):
            kind = c % 3
            if kind == 0:
                store.update(f"g{c}_0", ("add", rep), "w")
            elif kind == 1:
                store.update(f"s{c}_0", ("add", f"e{rep}"), "w")
            else:
                store.update(f"o{c}_0", ("add", f"x{rep}"), "w")
        if rep == 1:  # second wave: a removal moves vclock dots too
            store.update("o2_0", ("remove", "x0"), "w")
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any fallback fails the smoke
            rounds.append(g.propagate(mode=mode))
    return store, g, rounds


def main() -> int:
    import numpy as np

    from lasp_tpu.telemetry import get_ledger, get_registry

    s_fused, g_fused, r_fused = _drive("fused")
    s_edge, _g_edge, r_edge = _drive("per_edge")
    assert r_fused == r_edge, (r_fused, r_edge)
    n_vars = 0
    for v in s_fused.ids():
        a = jax.tree_util.tree_leaves(s_fused.state(v))
        b = jax.tree_util.tree_leaves(s_edge.state(v))
        assert all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(a, b)
        ), f"fused/per-edge divergence on {v}"
        n_vars += 1
    # the poisoned edge stayed out of every stacked group
    assert not g_fused.edges[0].stackable
    ents = [e for k, e in g_fused._cache._entries.items()
            if k[0] == "fused" and e is not None]
    assert ents, "no fused megakernel was compiled"
    assert all((0,) in [tuple(g) for g in ent.groups] for ent in ents), (
        "pre-poisoned edge was stacked into a multi-member group"
    )
    assert any(ent.n_stacked >= 2 for ent in ents), (
        "no same-signature edges stacked — the megakernel degenerated "
        "to all-singletons"
    )

    # -- a live roofline row for the megakernel family ----------------------
    warm = [
        e for e in get_ledger().snapshot()
        if e["family"] == "dataflow_fused"
    ]
    assert warm, "fused propagate fed no dataflow_fused ledger row"
    assert any(e["dispatches"] > 0 for e in warm), (
        "dataflow_fused never warmed (every dispatch banked as compile)"
    )
    for e in warm:
        if e["dispatches"] > 0:
            assert e["achieved_GBps"] is not None, e
            assert e["roofline_frac"] is not None, (
                f"null roofline_frac for {e['kernel']}"
            )

    # -- metrics exported + cataloged ---------------------------------------
    names = get_registry().names()
    needed = (
        "dataflow_plan_cache_hits_total",
        "dataflow_plan_cache_built_total",
        "dataflow_plan_groups",
    )
    for metric in needed:
        assert metric in names, f"{metric} not in the live registry"
    lint = _load_lint()
    docs = lint.cataloged()
    for metric in needed + ("dataflow_plan_fallbacks_total",):
        assert metric in docs["metrics"], f"{metric} not cataloged"

    print(
        f"dataflow fusion smoke OK: {n_vars} vars bit-identical across "
        f"schedulers (rounds {r_fused}), poisoned edge rode as a "
        f"singleton, {sum(e['dispatches'] for e in warm)} warm "
        "dataflow_fused dispatches priced; catalog in sync"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
