#!/usr/bin/env python
"""Round-4 TPU capture watcher.

The axon tunnel (single-client; see bench.py's module docstring) was
wedged at round start. This watcher probes it in bounded subprocesses
and, the moment a probe sees a non-cpu platform, runs the three capture
jobs back-to-back — most valuable artifact first — each in its own
SIGTERM-first bounded child:

  1. python bench.py                  -> tools/capture_out/bench.json
  2. python bench_pallas.py           -> tools/capture_out/pallas.jsonl
  3. cli scenario packed_vs_dense 1M  -> tools/capture_out/scenario_1m.json

The parent NEVER imports jax (any backend query can hang for hours on a
wedged tunnel). Probes are spaced minutes apart: the wedge heals on
terminal-side lease expiry, not on retry pressure, and hammering it just
risks stacking half-registered clients.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tools", "capture_out")
LOG = os.path.join(OUT, "watch.log")

PROBE_TIMEOUT_S = 150
PROBE_INTERVAL_S = int(os.environ.get("LASP_WATCH_INTERVAL", "600"))
TOTAL_HOURS = float(os.environ.get("LASP_WATCH_HOURS", "10"))


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def run(cmd, timeout, outfile=None, env=None):
    """SIGTERM-first bounded child (never leave a SIGKILLed process
    holding the tunnel). Returns (rc, stdout_tail)."""
    proc = subprocess.Popen(
        cmd, cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=25)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        rc = -1
    if outfile and out and out.strip():
        # a timed-out/failed child's stdout must not masquerade as a
        # finished artifact
        with open(outfile if rc == 0 else outfile + ".partial", "w") as f:
            f.write(out)
    if err and err.strip():
        with open((outfile or os.path.join(OUT, "misc")) + ".stderr", "w") as f:
            f.write(err)
    return rc, (out or "").strip()[-400:]


def probe() -> bool:
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    rc, out = run([sys.executable, "-c", code], PROBE_TIMEOUT_S)
    if rc == 0 and "PLATFORM=" in out:
        platform = out.rsplit("PLATFORM=", 1)[1].strip()
        log(f"probe: platform={platform}")
        return platform != "cpu"
    log(f"probe: failed rc={rc} tail={out[-120:]!r}")
    return False


def capture() -> bool:
    """One capture pass. Success == bench.py produced a parseable artifact
    that actually ran on the TPU (its internal CPU fallback exits 0 too —
    that must not end the watch)."""
    import json

    log("TPU healthy — starting captures")
    bench_out = os.path.join(OUT, "bench.json")
    rc, tail = run([sys.executable, "bench.py"], 2500, outfile=bench_out)
    log(f"bench.py rc={rc} tail={tail[-200:]!r}")
    bench_on_tpu = False
    if rc == 0:
        try:
            with open(bench_out) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
            bench_on_tpu = rec.get("detail", {}).get("device") not in (
                None, "cpu",
            )
            log(f"bench device={rec.get('detail', {}).get('device')!r}")
        except Exception as e:
            log(f"bench.json unparseable: {e}")
    rc, tail = run(
        [sys.executable, "bench_pallas.py"], 1500,
        outfile=os.path.join(OUT, "pallas.jsonl"),
    )
    log(f"bench_pallas.py rc={rc} tail={tail[-200:]!r}")
    rc, tail = run(
        [sys.executable, "-m", "lasp_tpu.cli", "scenario",
         "packed_vs_dense", "--replicas", "1048576"], 1500,
        outfile=os.path.join(OUT, "scenario_1m.json"),
    )
    log(f"scenario packed_vs_dense rc={rc} tail={tail[-200:]!r}")
    return bench_on_tpu


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    deadline = time.monotonic() + TOTAL_HOURS * 3600
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        log(f"probe attempt {attempt}")
        if probe():
            if capture():
                log("capture pass done (bench ran on TPU)")
                return 0
            # the tunnel re-wedged mid-capture (the known failure mode):
            # keep watching — later attempts may land a full pass
            log("capture pass incomplete; continuing to watch")
        time.sleep(PROBE_INTERVAL_S)
    log("deadline reached with no healthy TPU")
    return 1


if __name__ == "__main__":
    sys.exit(main())
