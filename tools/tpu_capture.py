#!/usr/bin/env python
"""Round-5 TPU capture watcher: single-client-safe, one child per attempt.

Round 4 probed in one bounded subprocess and measured in another — and
that probe->measure reconnect is exactly what wedges the single-client
axon tunnel (the probe's lease outlives its process; the next
interpreter's connect half-registers and hangs forever). Round 5 fixes
the shape: ONE child (``tools/tpu_oneshot.py``) both probes and measures
in the same interpreter, appending one JSON line per stage to
``tools/capture_out/oneshot_r05.jsonl``. The parent NEVER imports jax;
it watches the jsonl:

- no ``init`` line within ``LASP_WATCH_INIT_TIMEOUT`` (240 s): the
  connect is wedged -> SIGTERM the child, sleep out the probe interval
  (the wedge heals on terminal-side lease expiry, not retry pressure);
- ``init`` seen: let the child run its full budget; success = a
  ``headline`` stage without an ``error`` field this attempt.

SIGTERM-first always — a SIGKILLed client holds the tunnel."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tools", "capture_out")
LOG = os.path.join(OUT, "watch.log")
JSONL = os.path.join(OUT, "oneshot_r05.jsonl")

INIT_TIMEOUT_S = int(os.environ.get("LASP_WATCH_INIT_TIMEOUT", "240"))
CAPTURE_BUDGET_S = int(os.environ.get("LASP_WATCH_CAPTURE_BUDGET", "3600"))
PROBE_INTERVAL_S = int(os.environ.get("LASP_WATCH_INTERVAL", "600"))
TOTAL_HOURS = float(os.environ.get("LASP_WATCH_HOURS", "10"))


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    os.makedirs(OUT, exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def _new_lines(offset: int) -> tuple[list, int]:
    """JSON records appended to the jsonl past byte ``offset``. The offset
    only ever advances to the end of the last NEWLINE-TERMINATED line: a
    poll can land mid-append, and consuming the partial line's bytes
    would drop that record forever once its tail arrives."""
    if not os.path.exists(JSONL):
        return [], offset
    with open(JSONL, "rb") as f:
        f.seek(offset)
        chunk = f.read()
    complete = chunk.rfind(b"\n") + 1  # 0 when no full line yet
    records = []
    for line in chunk[:complete].splitlines():
        try:
            records.append(json.loads(line.decode("utf-8", "replace")))
        except json.JSONDecodeError:
            pass
    return records, offset + complete


def _terminate(proc: subprocess.Popen) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=25)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def _classify_attempt(attempt: int, rc: "int | None", stderr_path: str,
                      saw_init: bool, timed_out: bool,
                      budget_killed: bool = False) -> None:
    """Persist a CLASSIFIED probe record for a failed attempt (the
    schema bench.py's probe_report uses — lasp_tpu.telemetry.capability,
    which never imports jax): the child's stderr used to vanish into
    DEVNULL, leaving a wedge indistinguishable from an import error."""
    try:
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from lasp_tpu.telemetry.capability import (
            PROBE_TIMEOUT_RC,
            classify_probe_attempt,
        )

        stderr = ""
        if os.path.exists(stderr_path):
            with open(stderr_path, errors="replace") as f:
                stderr = f.read()[-8000:]
        rec, _platforms = classify_probe_attempt(
            PROBE_TIMEOUT_RC if timed_out else (rc if rc is not None else 1),
            "", stderr, budget_exceeded=budget_killed,
        )
        rec["attempt"] = attempt
        rec["saw_init"] = saw_init
        with open(JSONL, "a") as f:
            f.write(json.dumps({"stage": "probe_report", **rec}) + "\n")
        log(f"attempt {attempt}: classified {rec['classification']} "
            f"fatal={rec['fatal']!r}")
    except Exception as exc:  # classification must never kill the watcher
        log(f"attempt {attempt}: classification failed: {exc}")


def attempt_once(attempt: int) -> bool:
    """One probe+capture child. True iff the headline stage captured."""
    offset = os.path.getsize(JSONL) if os.path.exists(JSONL) else 0
    env = dict(os.environ)
    env["LASP_ONESHOT_BUDGET"] = str(CAPTURE_BUDGET_S)
    os.makedirs(OUT, exist_ok=True)
    stderr_path = os.path.join(OUT, f"attempt_{attempt}.stderr")
    stderr_f = open(stderr_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.join("tools", "tpu_oneshot.py")],
        cwd=REPO, env=env, text=True,
        stdout=subprocess.DEVNULL, stderr=stderr_f,
    )
    t0 = time.monotonic()
    saw_init = False
    headline_ok = False
    budget_killed = False
    while proc.poll() is None:
        time.sleep(5)
        records, offset = _new_lines(offset)
        for rec in records:
            stage = rec.get("stage")
            if stage == "init" and "error" not in rec:
                saw_init = True
                log(f"attempt {attempt}: init ok — {rec.get('device_kind')}")
            elif stage == "init":
                log(f"attempt {attempt}: init says {rec.get('error')!r}")
            elif stage == "headline":
                headline_ok = "error" not in rec
                log(f"attempt {attempt}: headline "
                    f"{'ok' if headline_ok else rec.get('error')!r}")
            elif stage:
                log(f"attempt {attempt}: stage {stage} recorded")
        now = time.monotonic()
        if not saw_init and now - t0 > INIT_TIMEOUT_S:
            log(f"attempt {attempt}: no init after {INIT_TIMEOUT_S}s — "
                "wedged connect, terminating child")
            _terminate(proc)
            stderr_f.close()
            _classify_attempt(attempt, proc.returncode, stderr_path,
                              saw_init=False, timed_out=True)
            return False
        if now - t0 > CAPTURE_BUDGET_S + 120:
            log(f"attempt {attempt}: budget exceeded, terminating child")
            budget_killed = True
            _terminate(proc)
            break
    records, offset = _new_lines(offset)
    for rec in records:
        if rec.get("stage") == "headline":
            headline_ok = "error" not in rec
        if rec.get("stage"):
            log(f"attempt {attempt}: stage {rec.get('stage')} recorded (final)")
    stderr_f.close()
    log(f"attempt {attempt}: child exited rc={proc.returncode} "
        f"headline_ok={headline_ok}")
    if not headline_ok:
        _classify_attempt(attempt, proc.returncode, stderr_path,
                          saw_init=saw_init, timed_out=False,
                          budget_killed=budget_killed)
    return headline_ok


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    deadline = time.monotonic() + TOTAL_HOURS * 3600
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        log(f"attempt {attempt} starting")
        if attempt_once(attempt):
            log("capture complete (headline on TPU) — watcher done")
            return 0
        time.sleep(PROBE_INTERVAL_S)
    log("deadline reached with no healthy TPU")
    return 1


if __name__ == "__main__":
    sys.exit(main())
