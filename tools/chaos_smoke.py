#!/usr/bin/env python3
"""Chaos smoke for the lint tier (Makefile ``verify``): a ~30-second
seeded soak — ring-cut partition THEN rolling crash/restore over one
population — asserting the convergence-under-failure invariants the
chaos mesh exists to uphold (docs/RESILIENCE.md):

- post-heal state BIT-IDENTICAL to the fault-free run's fixed point
  (faults delay convergence, never change its destination);
- per-replica monotone inflation every round (restores exempt);
- the same (seed, schedule) REPLAYS to identical per-round states.

A sub-minute subset of tests/chaos/; exits 0 on agreement, 1 with the
violated invariant on drift."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from lasp_tpu.chaos import (
        ChaosSchedule,
        Crash,
        InvariantViolation,
        Partition,
        Restore,
        run_harness,
    )
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular
    from lasp_tpu.store import Store

    n = 64
    nbrs = random_regular(n, 3, seed=21)

    def build():
        store = Store(n_actors=8)
        v = store.declare(id="s", type="riak_dt_orswot", n_elems=16,
                          n_actors=8)
        g = store.declare(id="g", type="lasp_gset", n_elems=16)
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
        rng = np.random.RandomState(5)
        rows = rng.choice(n, 6, replace=False)
        rt.update_batch(
            g, [(int(r), ("add", f"e{int(r) % 8}"), f"c{r}") for r in rows]
        )
        rt.update_at(int(rows[0]), v, ("add", "kept"), "w0")
        rt.update_at(int(rows[1]), v, ("add", "gone"), "w1")
        rt.update_at(int(rows[1]), v, ("remove", "gone"), "w1")
        return rt

    rng = np.random.RandomState(9)
    victims = [int(r) for r in rng.choice(n, 2, replace=False)]
    schedule = ChaosSchedule(
        n, nbrs,
        [
            Partition(2, 8, 2),                       # ring-cut, heals
            Crash(8, victims[0]), Crash(10, victims[1]),  # then rolling
            Restore(12, victims[0]), Restore(14, victims[1]),
        ],
        seed=13,
    )
    try:
        for mode in ("dense", "frontier"):
            report = run_harness(
                build, schedule, mode=mode, replay=True,
                removed_terms={"s": {"gone"}},
            )
            print(
                f"chaos smoke [{mode}]: healed in "
                f"{report['rounds_to_heal']} rounds post-horizon, "
                f"bit-identical to fault-free, replay deterministic"
            )
    except InvariantViolation as exc:
        print(f"chaos_smoke: INVARIANT VIOLATED: {exc}", file=sys.stderr)
        return 1
    print("chaos smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
