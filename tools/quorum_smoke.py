#!/usr/bin/env python3
"""Quorum-coordination smoke for the lint tier (Makefile ``verify``):
a sub-minute guard on the tentpole's two contracts
(docs/RESILIENCE.md "Quorum coordination"):

1. **batched == sequential, bit-for-bit** — the vectorized FSM batch
   (one jitted transition kernel per round + grouped partial joins)
   produces IDENTICAL results, repair/replication writes, ack-sequence
   traces, and final population states as the per-request sequential
   reference, across ring/random topologies × a nemesis preset ×
   dense/packed codecs;
2. **no acknowledged write lost** — a put acked at W=2 survives the
   rolling-crash nemesis via hinted handoff, with replay determinism
   (the ``run_quorum_harness`` invariant suite);

plus a ring-coverage cross-check (grouped partition-sweep values equal
per-var coverage values) and a metric-liveness probe for the
``quorum_*`` family. Exits 0 on agreement, 1 with the divergence."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from lasp_tpu.chaos import ChaosRuntime, InvariantViolation, nemesis
    from lasp_tpu.chaos.invariants import (
        fingerprint,
        run_quorum_harness,
        snapshot_states,
    )
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring
    from lasp_tpu.quorum import QuorumRuntime, coverage_sweep
    from lasp_tpu.store import Store

    R = 24

    def build(nbrs, packed=False):
        store = Store(n_actors=32)
        store.declare(id="kv", type="lasp_orset", n_elems=64,
                      tokens_per_actor=8)
        store.declare(id="g", type="lasp_gset", n_elems=64)
        return ReplicatedRuntime(store, Graph(store), R, nbrs,
                                 packed=packed)

    # -- 1. batched vs sequential bit-identity ------------------------------
    for topo_name, nbrs in (
        ("ring", ring(R, 2)),
        ("random", random_regular(R, 3, seed=7)),
    ):
        for packed in (False, True):
            outs = []
            for engine in ("batched", "sequential"):
                rt = build(nbrs, packed=packed)
                sched = nemesis("flaky-links", R, nbrs, seed=3, rounds=6)
                ch = ChaosRuntime(rt, sched)
                qr = QuorumRuntime(ch, engine=engine, timeout=3,
                                   retries=3)
                for i in range(12):
                    if i < 5:
                        qr.submit_put("kv", ("add", f"e{i}"), f"w{i}",
                                      coordinator=(i * 5) % R)
                        qr.submit_put("g", ("add", f"t{i}"), f"u{i}",
                                      coordinator=(i * 3 + 1) % R)
                        qr.submit_get("kv", coordinator=(i * 7) % R,
                                      degraded=True)
                    qr.step()
                while qr.inflight:
                    qr.step()
                outs.append({
                    "trace": qr.trace,
                    "fp": fingerprint(snapshot_states(rt)),
                    "results": [
                        qr.result(rid, raise_on_error=False)
                        for rid in range(qr._next_rid)
                    ],
                    "accounting": (qr.repaired_rows, qr.pushed_rows,
                                   qr.wire_bytes, qr.completed,
                                   qr.failed, qr.retries),
                })
            for key in ("trace", "fp", "results", "accounting"):
                if outs[0][key] != outs[1][key]:
                    print(
                        f"quorum_smoke: batched != sequential on {key} "
                        f"(topology={topo_name}, packed={packed})",
                        file=sys.stderr,
                    )
                    return 1
            print(
                f"quorum smoke [{topo_name}, packed={packed}]: batched "
                "== sequential (trace, results, repair writes, states)"
            )

    # -- 2. no-acked-write-lost under rolling-crash (hinted handoff) --------
    nbrs = ring(R, 2)

    def build_one():
        store = Store(n_actors=32)
        store.declare(id="kv", type="lasp_gset", n_elems=64)
        return ReplicatedRuntime(store, Graph(store), R, nbrs)

    sched = nemesis("rolling-crash", R, nbrs, seed=11, rounds=9)
    try:
        report = run_quorum_harness(
            build_one, sched,
            writes=[(rnd, "kv", ("add", f"k{rnd}"), f"c{rnd}",
                     (rnd * 5) % R) for rnd in range(6)],
            reads=[(3, "kv", 1)],
            timeout=3, retries=3,
        )
    except InvariantViolation as exc:
        print(f"quorum_smoke: INVARIANT VIOLATED: {exc}", file=sys.stderr)
        return 1
    print(
        f"quorum smoke [invariants]: {report['acked_terms']['kv']} acked "
        f"writes survived rolling-crash (hint replays: "
        f"{report['hint_replays']}), replay deterministic"
    )

    # -- 3. ring coverage: grouped sweep == per-var coverage ----------------
    rt = build_one()
    rng = np.random.RandomState(2)
    for i in range(6):
        rt.update_at(int(rng.randint(R)), "kv", ("add", f"c{i}"), f"x{i}")
    sweep = coverage_sweep(rt, n_shards=4)
    for v in rt.var_ids:
        if sweep[v] != rt.coverage_value(v):
            print(f"quorum_smoke: coverage sweep drift on {v!r}",
                  file=sys.stderr)
            return 1
    print("quorum smoke [coverage]: grouped partition-sweep == coverage")

    # -- 4. the quorum_* metric family is live ------------------------------
    from lasp_tpu.telemetry import render_prometheus

    text = render_prometheus()
    for needle in ("quorum_requests_total", "quorum_completions_total",
                   "quorum_latency_rounds"):
        if needle not in text:
            print(f"quorum_smoke: metric {needle} not exported",
                  file=sys.stderr)
            return 1
    print("quorum smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
