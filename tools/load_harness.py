#!/usr/bin/env python3
"""Open-loop serving load harness (CLI wrapper over
``lasp_tpu.serve.harness.run_load`` — see docs/SERVING.md "Load
harness").

Drives an open-loop simulated client fleet (sustained write+read+watch
mix, Zipf-hot keys, shed-honoring retry clients) through the serving
front-end while gossip runs concurrently — optionally under a
composite chaos nemesis and a mid-run overload burst — and prints the
JSON report: offered vs admitted vs completed rates, typed
shed/retry-after accounting, deadline-expired cancellations, queue
high-water marks, degradation-ladder transitions, p50/p99 latency per
request class, the no-acked-write-lost verdict, and (with --parity)
vectorized-vs-per-watch threshold parity.

The acceptance-scale run (10k concurrent clients, 5x burst, composite
nemesis, 100k-threshold parity — the serve_load bench scenario's
shape):

    python tools/load_harness.py --clients 10000 --ticks 40 \\
        --arrivals 1200 --burst 5 --chaos --watches 10000 \\
        --parity 100000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("--replicas", type=int, default=64)
    p.add_argument("--fanout", type=int, default=3)
    p.add_argument("--vars", type=int, default=6)
    p.add_argument("--clients", type=int, default=10_000,
                   help="simulated client fleet size")
    p.add_argument("--ticks", type=int, default=40,
                   help="run length in serving cycles")
    p.add_argument("--arrivals", type=int, default=1200,
                   help="open-loop arrivals per tick (before burst)")
    p.add_argument("--zipf", type=float, default=1.1,
                   help="Zipf skew of the key distribution")
    p.add_argument("--burst", type=int, default=1,
                   help="mid-run overload multiplier (1 = none)")
    p.add_argument("--burst-ticks", type=int, default=6)
    p.add_argument("--chaos", action="store_true",
                   help="run the composite nemesis concurrently")
    p.add_argument("--watches", type=int, default=0,
                   help="standing threshold watches registered up front")
    p.add_argument("--parity", type=int, default=0,
                   help="post-run threshold-parity size (0 = skip)")
    p.add_argument("--deadline", type=int, default=30,
                   help="read/watch deadline in ticks")
    p.add_argument("--gossip-block", type=int, default=4)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--write-cap", type=int, default=8192)
    p.add_argument("--read-cap", type=int, default=8192)
    p.add_argument("--watch-cap", type=int, default=8192)
    args = p.parse_args(argv)

    from lasp_tpu.serve.harness import run_load

    report = run_load(
        n_replicas=args.replicas,
        fanout=args.fanout,
        n_vars=args.vars,
        n_clients=args.clients,
        ticks=args.ticks,
        arrivals_per_tick=args.arrivals,
        zipf_s=args.zipf,
        seed=args.seed,
        chaos=args.chaos,
        burst_at=args.ticks // 2 if args.burst > 1 else None,
        burst_ticks=args.burst_ticks,
        burst_factor=args.burst,
        deadline_ticks=args.deadline,
        capacity={"write": args.write_cap, "read": args.read_cap,
                  "watch": args.watch_cap},
        gossip_block=args.gossip_block,
        parity_thresholds=args.parity,
        seed_watches=args.watches,
    )
    print(json.dumps(report, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
