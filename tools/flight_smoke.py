#!/usr/bin/env python3
"""Flight-recorder parity smoke (Makefile ``verify``).

One seeded population converged twice: fully fused
(``converge_on_device`` — the whole fixed point in one dispatch, zero
per-round host syncs) vs per-round ``step()``. The fused run's
on-device flight ring is drained into a ``telemetry.device`` window;
the smoke asserts its per-round per-variable residual records are
BIT-FOR-BIT identical to the unfused stepping's — the tentpole claim
that fusing the loop loses no observability — and that the curve is
monotone-plausible (non-negative, productive prefix, single terminal
zero). Exits 0 on agreement, 1 with a diff summary on drift."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from anywhere (the Makefile invokes it from the repo root,
# which may not be on sys.path for a bare `python tools/...` call)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from lasp_tpu import telemetry
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular
    from lasp_tpu.store import Store
    from lasp_tpu.telemetry import device as tel_flight
    from lasp_tpu.telemetry import get_monitor

    n = 64
    nbrs = random_regular(n, 3, seed=23)

    def build():
        store = Store(n_actors=4)
        a = store.declare(id="a", type="lasp_gset", n_elems=16)
        b = store.declare(id="b", type="riak_dt_gcounter")
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
        rng = np.random.RandomState(3)
        rows = rng.choice(n, 6, replace=False)
        rt.update_batch(
            a, [(int(r), ("add", f"e{r % 5}"), f"c{r}") for r in rows]
        )
        rt.update_batch(
            b, [(int(r), ("increment",), f"w{r}") for r in rows[:3]]
        )
        return rt

    # unfused reference: per-round per-var residuals straight off the
    # monitor feed (the same observe_round stream the drain replays)
    telemetry.reset()
    rt_u = build()
    mon = get_monitor()
    curve_u = []
    for _ in range(128):
        total = rt_u.step()
        curve_u.append(
            [int(mon.vars[v]["residual"]) for v in rt_u.var_ids]
        )
        if total == 0:
            break
    else:
        print("flight_smoke: unfused run did not converge within 128 "
              "rounds", file=sys.stderr)
        return 1

    # fused run: ONE dispatch, the flight ring carries the curve out
    telemetry.reset()
    rt_f = build()
    rounds = rt_f.converge_on_device(max_rounds=128)
    w = tel_flight.last_window("converge")
    if w is None:
        print("flight_smoke: no converge flight window recorded",
              file=sys.stderr)
        return 1
    if w.overwritten:
        print(f"flight_smoke: ring overwrote {w.overwritten} rounds "
              f"(flight_rounds too small for this workload)",
              file=sys.stderr)
        return 1
    if tuple(map(str, w.columns)) != tuple(map(str, rt_f.var_ids)):
        print(f"flight_smoke: column drift {w.columns!r} vs "
              f"{rt_f.var_ids!r}", file=sys.stderr)
        return 1

    # monotone-plausible: non-negative everywhere, a single terminal
    # zero (gossip's monotone join exits at the FIRST quiescent round,
    # so no interior zero), totals matching the window's own curve
    totals = [sum(rec) for rec in w.records]
    if any(t < 0 for t in totals) or totals[-1] != 0:
        print(f"flight_smoke: implausible curve {totals}",
              file=sys.stderr)
        return 1
    if any(t == 0 for t in totals[:-1]):
        print(f"flight_smoke: interior zero in {totals} (fused loop "
              "ran past the fixed point)", file=sys.stderr)
        return 1

    # the tentpole claim: bit-for-bit the unfused curve
    if len(w.records) != len(curve_u) or rounds != len(curve_u):
        print(f"flight_smoke: round-count drift fused={len(w.records)} "
              f"(reported {rounds}) unfused={len(curve_u)}",
              file=sys.stderr)
        return 1
    for i, (rf, ru) in enumerate(zip(w.records, curve_u)):
        if list(rf) != list(ru):
            print(f"flight_smoke: residual drift at round {i + 1}: "
                  f"fused={rf} unfused={ru}", file=sys.stderr)
            return 1

    print(f"flight smoke OK: fused curve bit-identical to unfused over "
          f"{rounds} rounds, totals={totals}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
