#!/usr/bin/env python3
"""Sharded-frontier equivalence smoke on the 8-device emulated mesh
(Makefile ``verify``).

The multi-chip hot path, exercised in tier-1 instead of only on real
TPU: a partitioned 8-device mesh (``XLA_FLAGS=--xla_force_host_
platform_device_count=8``) runs the row-sparse frontier scheduler with
the SPARSE boundary exchange (dirty cut rows only, halo-backed) and is
asserted bit-identical — states, residual sequences, round counts —
against BOTH the dense partitioned round and the unsharded dense
reference, across ring/random topologies × leafwise (G-Set) / vclock
(OR-SWOT) / packed (flat OR-Set) codecs × both wire modes, plus one
hierarchical ``converge_on_device`` exact-round-count check. Exits 0
on agreement, 1 with a diff summary on drift."""

from __future__ import annotations

import os
import re
import sys

_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "", _flags
).strip()
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _permuted_ring(n: int, k: int, seed: int):
    """A ring(n, k) neighbor table under a random renumbering: same
    graph, NOT shift-structured — the shape that exercises the
    partitioned exchange on a ring topology (a raw ring would ride
    collective-permute and refuse the plan)."""
    import numpy as np

    from lasp_tpu.mesh.topology import ring

    rng = np.random.RandomState(seed)
    perm = rng.permutation(n)
    base = ring(n, k)
    nn = np.empty_like(base)
    nn[perm] = perm[base]
    return nn


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime
    from lasp_tpu.mesh.topology import locality_order, scale_free
    from lasp_tpu.store import Store

    if len(jax.devices()) < 8:
        print("shard_smoke: needs 8 emulated devices", file=sys.stderr)
        return 1
    n = 96

    def build(nbrs, codec: str):
        store = Store(n_actors=8)
        packed = codec == "packed"
        if codec == "gset":
            v = store.declare(id="v", type="lasp_gset", n_elems=16)
        elif codec == "orswot":
            v = store.declare(id="v", type="riak_dt_orswot", n_elems=8,
                              n_actors=4)
        else:
            v = store.declare(id="v", type="lasp_orset", n_elems=8)
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs,
                               packed=packed)
        rt.update_at(0, v, ("add", "a"), "w0")
        rt.update_at(n // 2, v, ("add", "b"), "w1")
        rt.update_at(17, v, ("add", "c"), "w2")
        return rt, v

    mesh = Mesh(np.array(jax.devices()[:8]), ("replicas",))
    topos = {
        "ring": _permuted_ring(n, 2, seed=5),
        "random": locality_order(scale_free(n, 3, seed=3))[1],
    }
    configs = []
    for ti, (tname, nbrs) in enumerate(topos.items()):
        for ci, codec in enumerate(("gset", "orswot", "packed")):
            mode = ("gather", "alltoall")[(ti + ci) % 2]
            configs.append((tname, codec, mode, nbrs))

    for tname, codec, mode, nbrs in configs:
        rt_f, v = build(nbrs, codec)
        rt_d, _ = build(nbrs, codec)
        ref, _ = build(nbrs, codec)
        rt_f.shard(mesh, axis="replicas", partition=True,
                   partition_mode=mode)
        rt_d.shard(mesh, axis="replicas", partition=True,
                   partition_mode=mode)
        for rnd in range(64):
            rf, rd, rr = rt_f.frontier_step(), rt_d.step(), ref.step()
            if not (rf == rd == rr):
                print(
                    f"shard_smoke: residual drift [{tname}/{codec}/"
                    f"{mode}] round {rnd}: frontier={rf} "
                    f"dense={rd} unsharded={rr}", file=sys.stderr,
                )
                return 1
            for other, oname in ((rt_d, "dense"), (ref, "unsharded")):
                same = jax.tree_util.tree_map(
                    lambda a, b: bool(jnp.array_equal(a, b)),
                    rt_f.states[v], other.states[v],
                )
                if not all(jax.tree_util.tree_leaves(same)):
                    print(
                        f"shard_smoke: state drift [{tname}/{codec}/"
                        f"{mode}] round {rnd} vs {oname}",
                        file=sys.stderr,
                    )
                    return 1
            if rd == 0:
                break
        else:
            print(f"shard_smoke: no convergence [{tname}/{codec}/{mode}]",
                  file=sys.stderr)
            return 1
        print(f"shard_smoke [{tname}/{codec}/{mode}]: bit-identical "
              f"over {rnd + 1} rounds")

    # hierarchical converge: exact round counts vs the host-driven loop
    nbrs = topos["random"]
    rt_h, v = build(nbrs, "gset")
    host, _ = build(nbrs, "gset")
    rt_h.shard(mesh, axis="replicas", partition=True)
    host_rounds = 0
    while True:
        host_rounds += 1
        if host.step() == 0:
            break
    hier = rt_h.converge_on_device(sync_every=4)
    if hier != host_rounds:
        print(f"shard_smoke: hier converge {hier} != host {host_rounds}",
              file=sys.stderr)
        return 1
    same = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.array_equal(a, b)),
        rt_h.states[v], host.states[v],
    )
    if not all(jax.tree_util.tree_leaves(same)):
        print("shard_smoke: hier converge fixed point drift",
              file=sys.stderr)
        return 1
    print(f"shard smoke OK: sparse exchange bit-identical across "
          f"{len(configs)} configs; hier converge exact at "
          f"{hier} rounds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
