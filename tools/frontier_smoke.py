#!/usr/bin/env python3
"""Fast frontier-vs-dense equivalence smoke (Makefile ``verify``).

One small population, two codecs (leafwise G-Set + vclock OR-SWOT via a
G-Counter lane mix), stepped to the fixed point twice from identical
seeds — dense ``step()`` vs ``frontier_step()`` — asserting identical
states EVERY round and identical round counts. A sub-10s subset of
tests/mesh/test_frontier.py for the lint-tier loop; exits 0 on
agreement, 1 with a diff summary on drift."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from anywhere (the Makefile invokes it from the repo root,
# which may not be on sys.path for a bare `python tools/...` call)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular
    from lasp_tpu.store import Store

    n = 96
    nbrs = random_regular(n, 3, seed=11)

    def build():
        store = Store(n_actors=4)
        a = store.declare(id="a", type="lasp_gset", n_elems=16)
        b = store.declare(id="b", type="riak_dt_orswot", n_elems=8,
                          n_actors=4)
        rt = ReplicatedRuntime(store, Graph(store), n, nbrs)
        rng = np.random.RandomState(7)
        rows = rng.choice(n, 5, replace=False)
        rt.update_batch(
            a, [(int(r), ("add", f"e{r % 4}"), f"c{r}") for r in rows]
        )
        rt.update_batch(b, [(int(rows[0]), ("add", "x"), "w0"),
                            (int(rows[1]), ("add", "y"), "w1")])
        return rt, (a, b)

    rt_f, ids = build()
    rt_d, _ = build()
    for rnd in range(64):
        rf, rd = rt_f.frontier_step(), rt_d.step()
        if rf != rd:
            print(f"frontier_smoke: residual drift at round {rnd}: "
                  f"frontier={rf} dense={rd}", file=sys.stderr)
            return 1
        for v in ids:
            same = jax.tree_util.tree_map(
                lambda x, y: bool(jnp.array_equal(x, y)),
                rt_f.states[v], rt_d.states[v],
            )
            if not all(jax.tree_util.tree_leaves(same)):
                print(f"frontier_smoke: state drift at round {rnd}, "
                      f"var {v!r}", file=sys.stderr)
                return 1
        if rd == 0:
            skipped_ok = rt_f.frontier_size(ids[1]) == 0
            print(f"frontier smoke OK: bit-identical over {rnd + 1} "
                  f"rounds, frontiers empty={skipped_ok}")
            return 0
    print("frontier_smoke: no convergence within 64 rounds",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
