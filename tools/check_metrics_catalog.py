#!/usr/bin/env python3
"""Metric-catalog lint: code and docs/OBSERVABILITY.md must agree.

Every metric emitted anywhere under ``lasp_tpu/`` (a literal first
argument to ``counter(...)`` / ``gauge(...)`` / ``histogram(...)``)
must have a row in the catalog table of ``docs/OBSERVABILITY.md``, and
every cataloged name must still be emitted somewhere — drift in either
direction fails the Makefile ``verify`` target. This is what makes the
metric key set a STABLE interface across PRs (dashboards and the bridge
scrape consumers depend on it).

Zero dependencies, stdlib only; exits 0 on agreement, 1 on drift.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "lasp_tpu")
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

#: a literal metric emission: counter("name"... / gauge('name'... /
#: histogram("name"... — dynamic names are invisible to this lint and
#: therefore forbidden by convention (docs/OBSERVABILITY.md)
_EMIT = re.compile(
    r"""\b(?:counter|gauge|histogram)\(\s*['"]([a-z][a-z0-9_]*)['"]"""
)

#: a catalog row: a markdown table line whose first cell is `name`
_ROW = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|")


def emitted_names() -> set:
    names: set = set()
    for root, _dirs, files in os.walk(SRC):
        for f in files:
            if not f.endswith(".py"):
                continue
            with open(os.path.join(root, f), encoding="utf-8") as fp:
                names.update(_EMIT.findall(fp.read()))
    return names


def cataloged_names() -> set:
    if not os.path.exists(DOC):
        print(f"check_metrics_catalog: {DOC} does not exist", file=sys.stderr)
        sys.exit(1)
    names: set = set()
    with open(DOC, encoding="utf-8") as fp:
        for line in fp:
            m = _ROW.match(line.strip())
            if m:
                names.add(m.group(1))
    return names


def main() -> int:
    code = emitted_names()
    docs = cataloged_names()
    missing_doc = sorted(code - docs)
    missing_code = sorted(docs - code)
    if missing_doc:
        print(
            "metrics emitted in code but MISSING from the "
            "docs/OBSERVABILITY.md catalog:\n  "
            + "\n  ".join(missing_doc)
        )
    if missing_code:
        print(
            "metrics cataloged in docs/OBSERVABILITY.md but emitted "
            "NOWHERE in lasp_tpu/ (stale rows):\n  "
            + "\n  ".join(missing_code)
        )
    if missing_doc or missing_code:
        return 1
    print(f"metrics catalog OK ({len(code)} metrics, code == docs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
