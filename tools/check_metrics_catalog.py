#!/usr/bin/env python3
"""Telemetry-catalog lint: code and docs/OBSERVABILITY.md must agree.

Three interfaces, one doc, linted BOTH ways (drift in either direction
fails the Makefile ``verify`` target):

- **metrics** — every literal first argument to ``counter(...)`` /
  ``gauge(...)`` / ``histogram(...)`` under ``lasp_tpu/`` must have a
  row in the doc's "Metric catalog" table, and every cataloged name
  must still be emitted somewhere;
- **event types** — every literal first argument to ``events.emit(...)``
  / ``events.emit_deep(...)`` must have a row in the "Event catalog"
  table, and vice versa (plus: every cataloged event type must be a
  member of ``telemetry.events.EVENT_TYPES`` — parsed statically, no
  imports);
- **span names** — every literal ``span("...")`` name must match a row
  of the "Span taxonomy" table; dynamic spans (``span(f"merge.{...}")``)
  are checked by their literal prefix against templated rows like
  ``merge.<crdt_type>``. Every cataloged span row must still have an
  emission site.
- **probe-report schema** — the key tuples declared in
  ``lasp_tpu/telemetry/capability.py`` (``PROBE_REPORT_KEYS`` /
  ``PROBE_ATTEMPT_KEYS``, parsed statically) must match the "Probe
  report schema" table rows, both ways — the hardened TPU capture
  path's artifact contract.
- **ledger families** — every kernel family declared in
  ``lasp_tpu/telemetry/roofline.py``'s ``FAMILIES`` tuple (parsed
  statically) must be named in the "Roofline & cost ledger" section,
  and every `` `family` `` token that section names in its family list
  must still be declared — so a new dispatch family (e.g. ``aae_hash``)
  cannot land without its documentation, nor linger documented after
  removal.

Dynamic metric/event names are invisible to this lint and therefore
forbidden by convention (docs/OBSERVABILITY.md).

Zero dependencies, stdlib only; exits 0 on agreement, 1 on drift.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "lasp_tpu")
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

#: a literal metric emission: counter("name"... / gauge('name'... /
#: histogram("name"...  (mixed case allowed after the first char:
#: unit-suffixed names like roofline_achieved_GBps)
_EMIT_METRIC = re.compile(
    r"""\b(?:counter|gauge|histogram)\(\s*['"]([a-z][a-zA-Z0-9_]*)['"]"""
)

#: a literal event emission: events.emit("type"... / events.emit_deep(
#: "type"... (matches the tel_events/_events aliases too)
_EMIT_EVENT = re.compile(
    r"""events\.emit(?:_deep)?\(\s*['"]([a-z][a-z0-9_]*)['"]"""
)

#: span sites: a literal name, or an f-string's literal prefix up to the
#: first interpolation (span(f"merge.{t}") -> "merge.")
_SPAN_LITERAL = re.compile(r"""\bspan\(\s*['"]([a-z][a-z0-9_.]*)['"]""")
_SPAN_FPREFIX = re.compile(r"""\bspan\(\s*f['"]([a-z][a-z0-9_.]*)\{""")

#: a catalog row: a markdown table line whose first cell is `name`
_ROW = re.compile(r"^\|\s*`([a-z][a-zA-Z0-9_.<>]*)`\s*\|")

#: EVENT_TYPES members in telemetry/events.py: "name",  # comment
_EVENT_TYPE_DECL = re.compile(r"""^\s*['"]([a-z][a-z0-9_]*)['"],""")


def _walk_sources():
    for root, _dirs, files in os.walk(SRC):
        for f in files:
            if f.endswith(".py"):
                with open(os.path.join(root, f), encoding="utf-8") as fp:
                    yield fp.read()


def emitted() -> dict:
    """{"metrics": set, "events": set, "span_literals": set,
    "span_prefixes": set} found in code."""
    out = {
        "metrics": set(), "events": set(),
        "span_literals": set(), "span_prefixes": set(),
    }
    for text in _walk_sources():
        out["metrics"].update(_EMIT_METRIC.findall(text))
        out["events"].update(_EMIT_EVENT.findall(text))
        out["span_literals"].update(_SPAN_LITERAL.findall(text))
        out["span_prefixes"].update(_SPAN_FPREFIX.findall(text))
    return out


def declared_event_types() -> set:
    """EVENT_TYPES members, parsed statically from telemetry/events.py."""
    path = os.path.join(SRC, "telemetry", "events.py")
    names: set = set()
    with open(path, encoding="utf-8") as fp:
        in_block = False
        for line in fp:
            if "EVENT_TYPES = frozenset({" in line:
                in_block = True
                continue
            if in_block:
                if line.strip().startswith("})"):
                    break
                m = _EVENT_TYPE_DECL.match(line)
                if m:
                    names.add(m.group(1))
    return names


def declared_probe_keys() -> set:
    """PROBE_REPORT_KEYS + PROBE_ATTEMPT_KEYS members, parsed statically
    from telemetry/capability.py (same no-import rule as the event
    types)."""
    path = os.path.join(SRC, "telemetry", "capability.py")
    names: set = set()
    key_decl = re.compile(r"""^\s*['"]([a-z][a-z0-9_]*)['"],""")
    with open(path, encoding="utf-8") as fp:
        in_block = False
        for line in fp:
            if re.match(r"^PROBE_(REPORT|ATTEMPT)_KEYS = \($", line):
                in_block = True
                continue
            if in_block:
                if line.strip().startswith(")"):
                    in_block = False
                    continue
                m = key_decl.match(line)
                if m:
                    names.add(m.group(1))
    return names


def declared_ledger_families() -> set:
    """``FAMILIES`` members, parsed statically from
    telemetry/roofline.py (the no-import rule)."""
    path = os.path.join(SRC, "telemetry", "roofline.py")
    names: set = set()
    decl = re.compile(r"""^\s*['"]([a-z][a-z0-9_]*)['"],""")
    with open(path, encoding="utf-8") as fp:
        in_block = False
        for line in fp:
            if re.match(r"^FAMILIES = \($", line):
                in_block = True
                continue
            if in_block:
                if line.strip().startswith(")"):
                    break
                m = decl.match(line)
                if m:
                    names.add(m.group(1))
    return names


def roofline_section_families() -> set:
    """Every backticked family-looking token in the doc's "Roofline &
    cost ledger" section that matches a declared-family shape."""
    out: set = set()
    in_section = False
    with open(DOC, encoding="utf-8") as fp:
        for line in fp:
            if line.startswith("##"):
                in_section = (
                    "roofline & cost ledger"
                    in line.lstrip("#").strip().lower()
                )
                continue
            if in_section:
                out.update(re.findall(r"`([a-z][a-z0-9_]*)`", line))
    return out


def cataloged() -> dict:
    """Doc rows per section: {"metrics": set, "events": set,
    "spans": set, "probe": set} — section-aware so `bind` the event
    type can never be confused with a metric row."""
    if not os.path.exists(DOC):
        print(f"check_metrics_catalog: {DOC} does not exist", file=sys.stderr)
        sys.exit(1)
    section = None
    out = {"metrics": set(), "events": set(), "spans": set(),
           "probe": set()}
    with open(DOC, encoding="utf-8") as fp:
        for line in fp:
            if line.startswith("##"):
                title = line.lstrip("#").strip().lower()
                if "metric catalog" in title:
                    section = "metrics"
                elif "event catalog" in title:
                    section = "events"
                elif "span taxonomy" in title:
                    section = "spans"
                elif "probe report schema" in title:
                    section = "probe"
                else:
                    section = None
                continue
            if section is None:
                continue
            m = _ROW.match(line.strip())
            if m:
                out[section].add(m.group(1))
    return out


def _span_doc_matches(name: str, doc_spans: set) -> bool:
    """A code span name (literal or f-prefix ending in '.') matches a doc
    row exactly, or a templated row (`merge.<crdt_type>`) by the part
    before '<'."""
    if name in doc_spans:
        return True
    for row in doc_spans:
        prefix = row.split("<", 1)[0]
        if "<" in row and name.startswith(prefix):
            return True
        if name.endswith(".") and row.startswith(name):
            return True
    return False


def _span_code_matches(row: str, code: dict) -> bool:
    """A doc span row still has some emission site."""
    if row in code["span_literals"]:
        return True
    prefix = row.split("<", 1)[0]
    for p in code["span_prefixes"]:
        if p == prefix or row.startswith(p):
            return True
    for lit in code["span_literals"]:
        if "<" in row and lit.startswith(prefix):
            return True
    return False


def main() -> int:
    code = emitted()
    docs = cataloged()
    problems: list[str] = []

    missing_doc = sorted(code["metrics"] - docs["metrics"])
    if missing_doc:
        problems.append(
            "metrics emitted in code but MISSING from the "
            "docs/OBSERVABILITY.md Metric catalog:\n  "
            + "\n  ".join(missing_doc)
        )
    stale_doc = sorted(docs["metrics"] - code["metrics"])
    if stale_doc:
        problems.append(
            "metrics cataloged in docs/OBSERVABILITY.md but emitted "
            "NOWHERE in lasp_tpu/ (stale rows):\n  "
            + "\n  ".join(stale_doc)
        )

    ev_missing_doc = sorted(code["events"] - docs["events"])
    if ev_missing_doc:
        problems.append(
            "event types emitted in code but MISSING from the Event "
            "catalog:\n  " + "\n  ".join(ev_missing_doc)
        )
    ev_stale = sorted(docs["events"] - code["events"])
    if ev_stale:
        problems.append(
            "event types cataloged but emitted nowhere (stale rows):\n  "
            + "\n  ".join(ev_stale)
        )
    declared = declared_event_types()
    undeclared = sorted(docs["events"] - declared)
    if undeclared:
        problems.append(
            "event types cataloged but absent from "
            "telemetry.events.EVENT_TYPES:\n  " + "\n  ".join(undeclared)
        )
    untabled = sorted(declared - docs["events"])
    if untabled:
        problems.append(
            "EVENT_TYPES members missing from the Event catalog:\n  "
            + "\n  ".join(untabled)
        )

    span_missing_doc = sorted(
        n for n in code["span_literals"] | code["span_prefixes"]
        if not _span_doc_matches(n, docs["spans"])
    )
    if span_missing_doc:
        problems.append(
            "span names emitted in code but MISSING from the Span "
            "taxonomy:\n  " + "\n  ".join(span_missing_doc)
        )
    span_stale = sorted(
        row for row in docs["spans"] if not _span_code_matches(row, code)
    )
    if span_stale:
        problems.append(
            "span rows cataloged but emitted nowhere (stale rows):\n  "
            + "\n  ".join(span_stale)
        )

    probe_declared = declared_probe_keys()
    probe_missing_doc = sorted(probe_declared - docs["probe"])
    if probe_missing_doc:
        problems.append(
            "probe-report keys declared in telemetry/capability.py but "
            "MISSING from the Probe report schema table:\n  "
            + "\n  ".join(probe_missing_doc)
        )
    probe_stale = sorted(docs["probe"] - probe_declared)
    if probe_stale:
        problems.append(
            "probe-report keys cataloged but absent from "
            "PROBE_REPORT_KEYS/PROBE_ATTEMPT_KEYS (stale rows):\n  "
            + "\n  ".join(probe_stale)
        )

    families = declared_ledger_families()
    doc_tokens = roofline_section_families()
    fam_missing_doc = sorted(families - doc_tokens)
    if fam_missing_doc:
        problems.append(
            "kernel ledger families declared in telemetry/roofline.py "
            "FAMILIES but never named in the docs/OBSERVABILITY.md "
            "'Roofline & cost ledger' section:\n  "
            + "\n  ".join(fam_missing_doc)
        )
    # reverse direction: doc tokens that LOOK like families (end in a
    # family-ish suffix or exactly match a historical family) but are
    # no longer declared — restricted to tokens that were clearly
    # family names to avoid flagging ordinary code spans in prose
    fam_stale = sorted(
        t for t in doc_tokens
        if (t.endswith("_dense") or t.endswith("_rows")
            or t.endswith("_window") or t.endswith("_exchange")
            or t.endswith("_fused") or t.endswith("_step")
            or t.endswith("_block") or t.endswith("_hash"))
        and t not in families
        and not t.startswith("roofline")
    )
    if fam_stale:
        problems.append(
            "family-shaped tokens in the 'Roofline & cost ledger' "
            "section with no matching FAMILIES declaration (stale "
            "rows):\n  " + "\n  ".join(fam_stale)
        )

    if problems:
        print("\n".join(problems))
        return 1
    print(
        f"telemetry catalog OK ({len(code['metrics'])} metrics, "
        f"{len(code['events'])} event types, "
        f"{len(docs['spans'])} span rows, "
        f"{len(probe_declared)} probe-report keys, "
        f"{len(families)} ledger families; code == docs)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
