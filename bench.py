"""Benchmark: OR-Set anti-entropy convergence (BASELINE.md headline).

Workload: the 1M-replica OR-Set anti-entropy config ("random gossip"):
every replica performs one local add, then pull-gossip rounds run until no
replica's state changes (the join fixed point). State rides the bit-packed
OR-Set codec (``lasp_tpu.ops.packed`` — 1 bit/token in HBM) and rounds run
in fused blocks (``lasp_tpu.ops.fused``) so dispatch does not dominate.

The headline metric is replica-merges/sec/chip (one merge = one pairwise
OR-Set join); ``vs_baseline`` is the speedup over a host-side NumPy merge
loop on the SAME logical state shape — the stand-in for the reference's
sequential per-replica ETS-backend merge path (the reference publishes no
numbers of its own, SURVEY.md §6).

Prints exactly one JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from lasp_tpu.bench_scenarios import orset_anti_entropy

    n_replicas = int(os.environ.get("LASP_BENCH_REPLICAS", 1 << 20))
    block = int(os.environ.get("LASP_BENCH_BLOCK", 4))

    out = orset_anti_entropy(n_replicas, block=block)
    tpu_rate = out["merges_per_sec"]

    # host NumPy baseline: sequential pairwise joins of the same logical
    # state shape (byte bools, as a host implementation would hold them)
    e, t = 8, 32  # matches orset_anti_entropy's spec (n_elems, n_tokens)
    a_e = np.zeros((e, t), dtype=bool)
    a_r = np.zeros_like(a_e)
    b_e = np.ones_like(a_e)
    b_r = np.zeros_like(a_e)
    n_cpu = 20_000
    t0 = time.perf_counter()
    for _ in range(n_cpu):
        a_e = a_e | b_e
        a_r = a_r | b_r
    cpu_elapsed = time.perf_counter() - t0
    cpu_rate = n_cpu / cpu_elapsed

    print(
        json.dumps(
            {
                "metric": "orset_replica_merges_per_sec_per_chip",
                "value": tpu_rate,
                "unit": "merges/s",
                "vs_baseline": round(tpu_rate / cpu_rate, 2),
                "detail": {
                    "n_replicas": n_replicas,
                    "fanout": out["fanout"],
                    "rounds_executed": out["rounds"],
                    "elapsed_s": out["seconds"],
                    "encoding": "packed-uint32",
                    "cpu_baseline_merges_per_sec": round(cpu_rate, 1),
                    "device": str(jax.devices()[0].platform),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
