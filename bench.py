"""Benchmark: OR-Set anti-entropy convergence (BASELINE.md headline).

Workload: the 100K-replica OR-Set anti-entropy config from the driver's
BASELINE ("random gossip"): every replica performs one local add, then
gossip rounds run until every replica equals the global join. The headline
metric is replica-merges/sec/chip (one merge = one pairwise OR-Set join of
``[E, T]`` token tensors); ``vs_baseline`` is the speedup over a host-side
NumPy merge loop measured in the same run — the stand-in for the reference's
per-replica sequential ETS-backend merge path (the reference itself
publishes no numbers, SURVEY.md §6).

Prints exactly one JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from lasp_tpu.lattice import ORSet, ORSetSpec, replicate
    from lasp_tpu.mesh import divergence, gossip_round, random_regular

    n_replicas = int(os.environ.get("LASP_BENCH_REPLICAS", 1 << 17))
    k = 3
    spec = ORSetSpec(n_elems=8, n_actors=8, tokens_per_actor=4)

    def seed(n):
        states = replicate(ORSet.new(spec), n)
        r = jnp.arange(n)
        return jax.vmap(lambda i, s: ORSet.add(spec, s, i % spec.n_elems, i % spec.n_actors))(
            r, states
        )

    neighbors = jnp.asarray(random_regular(n_replicas, k, seed=7))

    @jax.jit
    def round_fn(s, nb):
        return gossip_round(ORSet, spec, s, nb)

    @jax.jit
    def residual_fn(s):
        return divergence(ORSet, spec, s)

    # compile warmup (not timed)
    states = seed(n_replicas)
    jax.block_until_ready(round_fn(states, neighbors))
    jax.block_until_ready(residual_fn(states))

    # timed convergence run from fresh state
    states = seed(n_replicas)
    jax.block_until_ready(states)
    t0 = time.perf_counter()
    rounds = 0
    for _ in range(64):
        states = round_fn(states, neighbors)
        rounds += 1
        if int(residual_fn(states)) == 0:
            break
    jax.block_until_ready(states)
    elapsed = time.perf_counter() - t0
    merges = n_replicas * k * rounds
    tpu_rate = merges / elapsed

    # host NumPy baseline: sequential pairwise joins of the same state shape
    a_e = np.zeros((spec.n_elems, spec.n_tokens), dtype=bool)
    a_r = np.zeros_like(a_e)
    b_e = np.ones_like(a_e)
    b_r = np.zeros_like(a_e)
    n_cpu = 20_000
    t0 = time.perf_counter()
    for _ in range(n_cpu):
        a_e = a_e | b_e
        a_r = a_r | b_r
    cpu_elapsed = time.perf_counter() - t0
    cpu_rate = n_cpu / cpu_elapsed

    print(
        json.dumps(
            {
                "metric": "orset_replica_merges_per_sec_per_chip",
                "value": round(tpu_rate, 1),
                "unit": "merges/s",
                "vs_baseline": round(tpu_rate / cpu_rate, 2),
                "detail": {
                    "n_replicas": n_replicas,
                    "fanout": k,
                    "rounds_to_convergence": rounds,
                    "elapsed_s": round(elapsed, 3),
                    "cpu_baseline_merges_per_sec": round(cpu_rate, 1),
                    "device": str(jax.devices()[0].platform),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
